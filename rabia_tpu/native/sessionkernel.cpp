// Native gateway plane: the C twin of the client session/dedup table in
// rabia_tpu/gateway/session.py, which stays the semantics owner
// (RABIA_PY_GATEWAY=1 forces it; testing/conformance.py's
// run_gateway_ops_on_both_tables pins byte-identical decisions, cached
// payloads and GC behavior between the two).
//
// Why: the r09 stage-profiler finding (docs/PERFORMANCE.md) attributed
// 55.5% of a loaded replica's wall to the Python control plane — the
// gateway/session/serialization work the profiler lumped as `other` —
// while the native consensus stages totalled ~8%. The session table is
// the gateway's per-request state: every Submit pays a dedup lookup, a
// window check and an ack advance, and the per-second GC sweep walks
// EVERY session on the asyncio loop (a 10^5-session table is a 10^5
// iteration Python loop per second). This kernel holds the whole table
// in C — statekernel-style open addressing keyed by the 16-byte client
// id — and runs the submit hot path (dedup + window + ack + reserve) as
// ONE C call, the GC sweep as one C call, and serves cached dedup
// replies from C-resident payload blobs.
//
// Semantics mirrored element-for-element from session.py:
//   - hello: open-or-resume; granted window = min(default, requested)
//     when requested > 0 (renegotiable on resume, never above default);
//   - submit_check: ensure+touch, ack_upto advance, then classify:
//     DUP_CACHED (raw cached status + payload) / DUP_INFLIGHT /
//     SHED_WINDOW / FRESH (seq reserved in the inflight window);
//   - complete: drop the reservation, cache (status, payload,
//     frontier_mark), bump highest_completed; a no-op returning 0 when
//     the session lease-expired mid-flight;
//   - gc: evict results with seq <= ack_upto AND frontier_mark <
//     state_version; per-session cache cap evicts lowest seqs first;
//     idle sessions (no inflight) expire after session_ttl; the HARD
//     LEASE drops a session regardless of inflight after lease_ttl —
//     frontier-independent, so a stalled frontier cannot pin dead
//     sessions. Evicted counts include a dead session's cached results.
//
// Payload blob ABI (cached result payloads, borrowed pointers valid
// until the next mutating call):
//   [u32 LE nparts][u32 LE len_0]...[u32 LE len_{n-1}][part bytes...]
//
// Layout contract: one GwPlane per gateway, one versioned append-only
// GWC_* counter block (read zero-copy via ctypes like RKC_*/SKC_*).
//
// Threading: every entry point takes the plane mutex internally, so the
// table is safe under concurrent callers (the thread-per-shard-group
// runtime multiplies the gateway's callers — ROADMAP item 1; the
// gws_gc-vs-gws_submit seam is stress-checked under TSan in
// native/stress/stress_session.cpp). Counter cells are relaxed atomics
// read zero-copy by scrape threads (the RKC torn-read contract).
// BORROWED pointers (gws_submit / gws_get_result blob_out) remain valid
// only until the next mutating call ON ANY THREAD — a caller that reads
// them must serialize against mutators itself (the gateway's asyncio
// loop does; the stress harness pins its sessions hot so GC cannot free
// what a submit thread is reading).

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <new>
#include <vector>

#include "annotations.h"

extern "C" {

// ---------------------------------------------------------------------------
// counter block (versioned, append-only — docs/OBSERVABILITY.md)
// ---------------------------------------------------------------------------

enum {
  GWC_HELLOS = 0,        // hello (open/resume) calls
  GWC_SUBMITS,           // submit_check calls
  GWC_DEDUP_CACHED,      // duplicate submits answered from cache
  GWC_DEDUP_INFLIGHT,    // duplicate submits attached to the original
  GWC_SHED_WINDOW,       // submits shed: inflight window full
  GWC_FRESH,             // fresh seqs reserved
  GWC_COMPLETES,         // results cached (complete calls that stored)
  GWC_ABORTS,            // reservations released without a result
  GWC_GC_RUNS,           // gc sweeps
  GWC_SESSIONS_OPENED,   // sessions created
  GWC_SESSIONS_EXPIRED,  // sessions dropped by gc (idle + lease)
  GWC_LEASES_EXPIRED,    // subset of expired: hard-lease drops
  GWC_RESULTS_CACHED,    // cached results stored (== GWC_COMPLETES)
  GWC_RESULTS_EVICTED,   // cached results evicted by gc
  GWC_RESULT_BYTES,      // cumulative payload bytes cached
  GWC_REHASHES,          // session-table growth events
  GWC_COUNT
};

static const int32_t GWS_COUNTERS_VERSION = 1;

// submit_check decisions — must match gateway/session.py SUBMIT_*
enum : int32_t {
  SUBMIT_FRESH = 0,
  SUBMIT_DUP_CACHED = 1,
  SUBMIT_DUP_INFLIGHT = 2,
  SUBMIT_SHED_WINDOW = 3,
};

// ---------------------------------------------------------------------------
// table
// ---------------------------------------------------------------------------

struct CachedRec {
  uint64_t seq;
  uint64_t frontier_mark;
  int32_t status;
  std::vector<uint8_t> blob;  // payload blob (ABI above)
};

struct Session {
  uint8_t cid[16];
  int64_t window;
  uint64_t ack_upto = 0;
  uint64_t highest_completed = 0;
  double last_active = 0.0;
  std::vector<uint64_t> inflight;   // window-bounded; linear scan is fine
  std::vector<CachedRec> results;   // sorted by seq
};

enum : uint8_t { SLOT_EMPTY = 0, SLOT_FULL = 1, SLOT_TOMB = 2 };

struct Slot {
  Session* s = nullptr;
  uint64_t hash = 0;
  uint8_t state = SLOT_EMPTY;
};

static inline uint64_t cid_hash(const uint8_t* p) {
  uint64_t h = 1469598103934665603ull;
  for (int i = 0; i < 16; i++) {
    h ^= p[i];
    h *= 1099511628211ull;
  }
  return h ? h : 1;
}

struct GwPlane {
  rabia::Mutex mu{"sessionkernel.mu"};
  std::vector<Slot> table RABIA_GUARDED_BY(mu);  // power-of-two capacity
  int64_t live RABIA_GUARDED_BY(mu) = 0;   // SLOT_FULL count
  int64_t used RABIA_GUARDED_BY(mu) = 0;   // FULL + TOMB (probe bound)
  int64_t default_window;
  double session_ttl;
  double lease_ttl;
  int64_t result_cache_cap;
  // relaxed atomics, read zero-copy as plain u64s by the scrape path
  std::atomic<uint64_t> counters[GWC_COUNT];
  static_assert(sizeof(std::atomic<uint64_t>) == sizeof(uint64_t),
                "counter block must read as a plain uint64 array");
  void bump(int i, uint64_t n = 1) {
    counters[i].fetch_add(n, std::memory_order_relaxed);
  }
};

static void plane_rehash(GwPlane* p, int64_t want_cap)
    RABIA_REQUIRES(p->mu) {
  int64_t cap = 256;
  while (cap < want_cap) cap <<= 1;
  std::vector<Slot> old;
  old.swap(p->table);
  p->table.assign((size_t)cap, Slot{});
  p->used = 0;
  const uint64_t mask = (uint64_t)cap - 1;
  for (auto& e : old) {
    if (e.state != SLOT_FULL) continue;
    uint64_t i = e.hash & mask;
    while (p->table[i].state == SLOT_FULL) i = (i + 1) & mask;
    p->table[i] = e;
    p->used++;
  }
  p->bump(GWC_REHASHES);
}

// find the slot for cid; returns index or -1. `free_out` (when non-null)
// receives the first insertable slot (tombstone or empty).
static int64_t plane_find(GwPlane* p, uint64_t h, const uint8_t* cid,
                          int64_t* free_out) RABIA_REQUIRES(p->mu) {
  const uint64_t mask = (uint64_t)p->table.size() - 1;
  uint64_t i = h & mask;
  int64_t free_slot = -1;
  for (;;) {
    Slot& e = p->table[i];
    if (e.state == SLOT_EMPTY) {
      if (free_out) *free_out = free_slot >= 0 ? free_slot : (int64_t)i;
      return -1;
    }
    if (e.state == SLOT_TOMB) {
      if (free_slot < 0) free_slot = (int64_t)i;
    } else if (e.hash == h && memcmp(e.s->cid, cid, 16) == 0) {
      if (free_out) *free_out = -1;
      return (int64_t)i;
    }
    i = (i + 1) & mask;
  }
}

static Session* plane_get(GwPlane* p, const uint8_t* cid)
    RABIA_REQUIRES(p->mu) {
  int64_t at = plane_find(p, cid_hash(cid), cid, nullptr);
  return at < 0 ? nullptr : p->table[(size_t)at].s;
}

// open-or-resume (session.py SessionTable.ensure)
static Session* plane_ensure(GwPlane* p, const uint8_t* cid,
                             int64_t requested_window, double now)
    RABIA_REQUIRES(p->mu) {
  uint64_t h = cid_hash(cid);
  int64_t free_slot = -1;
  int64_t at = plane_find(p, h, cid, &free_slot);
  Session* s;
  if (at >= 0) {
    s = p->table[(size_t)at].s;
  } else {
    s = new (std::nothrow) Session();
    if (!s) return nullptr;
    memcpy(s->cid, cid, 16);
    s->window = p->default_window;
    Slot& e = p->table[(size_t)free_slot];
    if (e.state != SLOT_TOMB) p->used++;
    e.state = SLOT_FULL;
    e.s = s;
    e.hash = h;
    p->live++;
    p->bump(GWC_SESSIONS_OPENED);
    if (p->used * 4 >= (int64_t)p->table.size() * 3) {
      // size from LIVE sessions, not the current capacity: the rehash
      // drops every tombstone, and under steady session churn (clients
      // come and go, GC tombstoning as it sweeps) it is usually tombs
      // that tripped the 75% trigger — doubling unconditionally would
      // grow the table with the total sessions EVER seen and never
      // shrink it back to the live set.
      plane_rehash(p, p->live * 4);
    }
  }
  if (requested_window > 0) {
    s->window = std::min(p->default_window, requested_window);
  }
  s->last_active = now;
  return s;
}

static CachedRec* session_result(Session* s, uint64_t seq) {
  auto it = std::lower_bound(
      s->results.begin(), s->results.end(), seq,
      [](const CachedRec& r, uint64_t q) { return r.seq < q; });
  if (it == s->results.end() || it->seq != seq) return nullptr;
  return &*it;
}

static bool session_inflight_has(Session* s, uint64_t seq) {
  for (uint64_t q : s->inflight)
    if (q == seq) return true;
  return false;
}

static void session_inflight_drop(Session* s, uint64_t seq) {
  for (size_t i = 0; i < s->inflight.size(); i++) {
    if (s->inflight[i] == seq) {
      s->inflight.erase(s->inflight.begin() + (ptrdiff_t)i);
      return;
    }
  }
}

// ---------------------------------------------------------------------------
// lifecycle
// ---------------------------------------------------------------------------

void* gws_create(int64_t default_window, double session_ttl,
                 int64_t result_cache_cap, double lease_ttl) {
  GwPlane* p = new (std::nothrow) GwPlane();
  if (!p) return nullptr;
  {
    rabia::MutexLock lk(p->mu);  // no other thread yet; analysis only
    p->table.assign(256, Slot{});
  }
  p->default_window = default_window < 1 ? 1 : default_window;
  p->session_ttl = session_ttl;
  p->lease_ttl = lease_ttl;
  p->result_cache_cap = result_cache_cap < 1 ? 1 : result_cache_cap;
  for (auto& c : p->counters) c.store(0, std::memory_order_relaxed);
  return p;
}

static void plane_drop_all(GwPlane* p) RABIA_REQUIRES(p->mu) {
  for (auto& e : p->table)
    if (e.state == SLOT_FULL) delete e.s;
  p->table.assign(256, Slot{});
  p->live = p->used = 0;
}

void gws_destroy(void* h) {
  GwPlane* p = (GwPlane*)h;
  if (!p) return;
  {
    rabia::MutexLock lk(p->mu);  // last reference; analysis only
    for (auto& e : p->table)
      if (e.state == SLOT_FULL) delete e.s;
  }
  delete p;
}

int32_t gws_counters_version() { return GWS_COUNTERS_VERSION; }
int32_t gws_counters_count() { return GWC_COUNT; }
void* gws_counters(void* h) { return ((GwPlane*)h)->counters; }

int64_t gws_len(void* h) {
  GwPlane* p = (GwPlane*)h;
  rabia::MutexLock lk(p->mu);
  return p->live;
}

// total session-state loss (tests; the restart-wipe chaos shape)
void gws_clear(void* h) {
  GwPlane* p = (GwPlane*)h;
  rabia::MutexLock lk(p->mu);
  plane_drop_all(p);
}

// SessionStats parity: out[0..5] = sessions_opened, duplicate_submits,
// results_cached, results_evicted, sessions_expired, leases_expired
void gws_stats(void* h, uint64_t* out) {
  GwPlane* p = (GwPlane*)h;
  const auto rd = [](const std::atomic<uint64_t>& c) {
    return c.load(std::memory_order_relaxed);
  };
  out[0] = rd(p->counters[GWC_SESSIONS_OPENED]);
  out[1] = rd(p->counters[GWC_DEDUP_CACHED]) +
           rd(p->counters[GWC_DEDUP_INFLIGHT]);
  out[2] = rd(p->counters[GWC_RESULTS_CACHED]);
  out[3] = rd(p->counters[GWC_RESULTS_EVICTED]);
  out[4] = rd(p->counters[GWC_SESSIONS_EXPIRED]);
  out[5] = rd(p->counters[GWC_LEASES_EXPIRED]);
}

// ---------------------------------------------------------------------------
// the hot path
// ---------------------------------------------------------------------------

// hello: open/resume; returns the granted window, fills *last_seq_out.
int64_t gws_hello(void* h, const uint8_t* cid, int64_t req_window,
                  double now, uint64_t* last_seq_out) {
  GwPlane* p = (GwPlane*)h;
  rabia::MutexLock lk(p->mu);
  p->bump(GWC_HELLOS);
  Session* s = plane_ensure(p, cid, req_window, now);
  if (!s) return -1;
  if (last_seq_out) *last_seq_out = s->highest_completed;
  return s->window;
}

// submit_check in one call (see module doc). On SUBMIT_DUP_CACHED,
// *status_out / *blob_out / *blob_len_out describe the cached result
// (borrowed until the next mutating call).
int32_t gws_submit(void* h, const uint8_t* cid, uint64_t seq,
                   uint64_t ack_upto, double now, int32_t* status_out,
                   const uint8_t** blob_out, int64_t* blob_len_out) {
  GwPlane* p = (GwPlane*)h;
  rabia::MutexLock lk(p->mu);
  p->bump(GWC_SUBMITS);
  Session* s = plane_ensure(p, cid, 0, now);
  if (!s) return -1;
  if (ack_upto > s->ack_upto) s->ack_upto = ack_upto;
  CachedRec* r = session_result(s, seq);
  if (r) {
    p->bump(GWC_DEDUP_CACHED);
    if (status_out) *status_out = r->status;
    if (blob_out) *blob_out = r->blob.data();
    if (blob_len_out) *blob_len_out = (int64_t)r->blob.size();
    return SUBMIT_DUP_CACHED;
  }
  if (session_inflight_has(s, seq)) {
    p->bump(GWC_DEDUP_INFLIGHT);
    return SUBMIT_DUP_INFLIGHT;
  }
  if ((int64_t)s->inflight.size() >= s->window) {
    p->bump(GWC_SHED_WINDOW);
    return SUBMIT_SHED_WINDOW;
  }
  s->inflight.push_back(seq);
  p->bump(GWC_FRESH);
  return SUBMIT_FRESH;
}

// complete: returns 1 when stored, 0 when the session is gone
// (lease-expired mid-flight — the Python twin's complete_op contract).
int32_t gws_complete(void* h, const uint8_t* cid, uint64_t seq,
                     int32_t status, uint64_t frontier_mark,
                     const uint8_t* blob, int64_t blob_len, double now) {
  GwPlane* p = (GwPlane*)h;
  rabia::MutexLock lk(p->mu);
  Session* s = plane_get(p, cid);
  if (!s) return 0;
  session_inflight_drop(s, seq);
  auto it = std::lower_bound(
      s->results.begin(), s->results.end(), seq,
      [](const CachedRec& r, uint64_t q) { return r.seq < q; });
  if (it != s->results.end() && it->seq == seq) {
    it->status = status;
    it->frontier_mark = frontier_mark;
    it->blob.assign(blob, blob + blob_len);
  } else {
    CachedRec rec;
    rec.seq = seq;
    rec.status = status;
    rec.frontier_mark = frontier_mark;
    rec.blob.assign(blob, blob + blob_len);
    s->results.insert(it, std::move(rec));
  }
  if (seq > s->highest_completed) s->highest_completed = seq;
  s->last_active = now;
  p->bump(GWC_COMPLETES);
  p->bump(GWC_RESULTS_CACHED);
  p->bump(GWC_RESULT_BYTES, (uint64_t)blob_len);
  return 1;
}

void gws_abort(void* h, const uint8_t* cid, uint64_t seq) {
  GwPlane* p = (GwPlane*)h;
  rabia::MutexLock lk(p->mu);
  Session* s = plane_get(p, cid);
  if (!s) return;
  session_inflight_drop(s, seq);
  p->bump(GWC_ABORTS);
}

// ---------------------------------------------------------------------------
// GC (one C call per sweep — the 10^5-session walk the Python loop paid)
// ---------------------------------------------------------------------------

int64_t gws_gc(void* h, uint64_t state_version, double now) {
  GwPlane* p = (GwPlane*)h;
  rabia::MutexLock lk(p->mu);
  p->bump(GWC_GC_RUNS);
  int64_t evicted = 0;
  for (auto& e : p->table) {
    if (e.state != SLOT_FULL) continue;
    Session* s = e.s;
    if (!s->results.empty()) {
      // frontier-tied eviction: acked AND frontier moved past the mark
      size_t w = 0;
      for (size_t i = 0; i < s->results.size(); i++) {
        CachedRec& r = s->results[i];
        if (r.seq <= s->ack_upto && r.frontier_mark < state_version) {
          evicted++;
          continue;
        }
        if (w != i) s->results[w] = std::move(s->results[i]);
        w++;
      }
      s->results.resize(w);
      // hard cap: evict lowest seqs first (results are seq-sorted)
      if ((int64_t)s->results.size() > p->result_cache_cap) {
        int64_t over = (int64_t)s->results.size() - p->result_cache_cap;
        s->results.erase(s->results.begin(), s->results.begin() + over);
        evicted += over;
      }
    }
    double idle = now - s->last_active;
    if (idle > p->lease_ttl) {
      // hard lease: drop regardless of inflight (frontier-independent)
      evicted += (int64_t)s->results.size();
      delete s;
      e.s = nullptr;
      e.state = SLOT_TOMB;
      p->live--;
      p->bump(GWC_LEASES_EXPIRED);
      p->bump(GWC_SESSIONS_EXPIRED);
    } else if (s->inflight.empty() && idle > p->session_ttl) {
      evicted += (int64_t)s->results.size();
      delete s;
      e.s = nullptr;
      e.state = SLOT_TOMB;
      p->live--;
      p->bump(GWC_SESSIONS_EXPIRED);
    }
  }
  p->bump(GWC_RESULTS_EVICTED, (uint64_t)evicted);
  return evicted;
}

// ---------------------------------------------------------------------------
// introspection (facades, tests, the conformance gate)
// ---------------------------------------------------------------------------

// returns 1 when the session exists and fills the out params
int32_t gws_session_info(void* h, const uint8_t* cid, int64_t* window,
                         uint64_t* ack_upto, uint64_t* highest,
                         int64_t* n_inflight, int64_t* n_results) {
  GwPlane* p = (GwPlane*)h;
  rabia::MutexLock lk(p->mu);
  Session* s = plane_get(p, cid);
  if (!s) return 0;
  if (window) *window = s->window;
  if (ack_upto) *ack_upto = s->ack_upto;
  if (highest) *highest = s->highest_completed;
  if (n_inflight) *n_inflight = (int64_t)s->inflight.size();
  if (n_results) *n_results = (int64_t)s->results.size();
  return 1;
}

// cached-result peek WITHOUT the dedup side effects of gws_submit
// (no counters, no touch). 1 = found.
int32_t gws_get_result(void* h, const uint8_t* cid, uint64_t seq,
                       int32_t* status_out, uint64_t* frontier_out,
                       const uint8_t** blob_out, int64_t* blob_len_out) {
  GwPlane* p = (GwPlane*)h;
  rabia::MutexLock lk(p->mu);
  Session* s = plane_get(p, cid);
  if (!s) return 0;
  CachedRec* r = session_result(s, seq);
  if (!r) return 0;
  if (status_out) *status_out = r->status;
  if (frontier_out) *frontier_out = r->frontier_mark;
  if (blob_out) *blob_out = r->blob.data();
  if (blob_len_out) *blob_len_out = (int64_t)r->blob.size();
  return 1;
}

// write up to cap 16-byte client ids; returns the count (table order —
// callers sort; the conformance gate compares as sets)
int64_t gws_session_ids(void* h, uint8_t* out, int64_t cap) {
  GwPlane* p = (GwPlane*)h;
  rabia::MutexLock lk(p->mu);
  int64_t n = 0;
  for (auto& e : p->table) {
    if (e.state != SLOT_FULL) continue;
    if (n >= cap) break;
    memcpy(out + 16 * n, e.s->cid, 16);
    n++;
  }
  return n;
}

// write up to cap cached seqs (ascending); returns count, or -1 when the
// session does not exist
int64_t gws_result_seqs(void* h, const uint8_t* cid, uint64_t* out,
                        int64_t cap) {
  GwPlane* p = (GwPlane*)h;
  rabia::MutexLock lk(p->mu);
  Session* s = plane_get(p, cid);
  if (!s) return -1;
  int64_t n = 0;
  for (auto& r : s->results) {
    if (n >= cap) break;
    out[n++] = r.seq;
  }
  return n;
}

int64_t gws_inflight_seqs(void* h, const uint8_t* cid, uint64_t* out,
                          int64_t cap) {
  GwPlane* p = (GwPlane*)h;
  rabia::MutexLock lk(p->mu);
  Session* s = plane_get(p, cid);
  if (!s) return -1;
  int64_t n = 0;
  for (uint64_t q : s->inflight) {
    if (n >= cap) break;
    out[n++] = q;
  }
  return n;
}

}  // extern "C"
