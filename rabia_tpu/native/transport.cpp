// TCP transport data plane: epoll loop, framing, handshake, reconnect.
//
// Native equivalent of the reference's TcpNetwork (rabia-engine/src/network/
// tcp.rs, SURVEY.md C17), exposed to Python through a C API consumed via
// ctypes (rabia_tpu/net/tcp.py). Wire compatibility points:
//   - frames: u32 little-endian length prefix + payload, 16 MiB cap
//     (tcp.rs:86,125);
//   - handshake: each side sends its 16-byte node id immediately after
//     connect; a connection is "established" once both ids crossed
//     (tcp.rs:384-413,527-557);
//   - dial with exponential backoff: 5 attempts, 100ms base, x2 growth,
//     30s cap (tcp.rs:54-72), then periodic re-dial while the peer stays
//     configured (keepalive scan, tcp.rs:661-684);
//   - per-peer outbound queues; broadcast = enqueue to every established
//     peer (tcp.rs:771-789).
//
// Threading model: ONE io thread owns all sockets and epoll; callers
// enqueue sends under a mutex and kick an eventfd; inbound frames land in a
// deque the Python side drains (blocking with timeout via condvar). No
// Python/GIL involvement inside the io loop.

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string.h>
#include <sys/epoll.h>
#include <time.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <unistd.h>

#include <array>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <queue>
#include <string>
#include <thread>
#include <vector>

#include "annotations.h"  // RABIA_* thread-safety macros + rabia::Mutex
#include "transport.h"  // the C ABI — definitions below are checked against it

namespace {

constexpr uint32_t kMaxFrame = 16u * 1024u * 1024u;  // 16 MiB (tcp.rs:86)
// Inbox bound: a fast peer with a slow Python drain must not grow memory
// without limit. Beyond the cap the OLDEST frame is dropped (consensus
// retransmits supersede stale votes) and dropped_frames counts it.
constexpr size_t kMaxInbox = 65536;
constexpr int kMaxDialAttempts = 5;                  // tcp.rs:57
// Session multiplexing (the gateway's client-scaling lane): a peer that
// handshakes with this magic id runs MANY sessions over ONE connection.
// Every subsequent frame on a muxed connection carries a 16-byte session
// id prefix inside the payload: inbound, the prefix becomes the sender
// id (the gateway authenticates the embedded client id against it, same
// trust model as the self-declared handshake id); outbound, rt_send to a
// session id bound on a muxed connection wraps the frame with the
// prefix so the client side can demultiplex. 10^4 client sessions then
// cost a handful of sockets (and loadgen reader tasks) instead of 10^4.
constexpr uint8_t kMuxMagic[16] = {0xF5, 'R', 'A', 'B', 'I', 'A', '-',
                                   'M',  'U', 'X', 0xF5, 0xF5, 0xF5,
                                   0xF5, 0xF5, 0xF5};
constexpr double kDialBaseDelayS = 0.1;              // tcp.rs:58
constexpr double kDialMaxDelayS = 30.0;              // tcp.rs:60
constexpr double kRedialPeriodS = 10.0;              // keepalive scan period
constexpr double kStashTtlS = 10.0;  // stranded-frame redelivery window

using Clock = std::chrono::steady_clock;

// Observability counter block (rt_counters). Indices are ABI: append new
// counters before RTC_COUNT and bump kCountersVersion; never renumber.
enum : int32_t {
  RTC_FRAMES_IN = 0,     // inbound frames parsed off sockets
  RTC_BYTES_IN,          // inbound payload bytes
  RTC_FRAMES_OUT,        // frames enqueued to peer connections
  RTC_BYTES_OUT,         // framed bytes enqueued (incl. 4B prefix)
  RTC_INBOX_DROPPED,     // frames dropped by the bounded inbox
  RTC_OUT_POOL_HITS,     // outbound frame arena reuse hits
  RTC_OUT_POOL_MISSES,   // outbound frame arena allocations
  RTC_IN_POOL_HITS,      // inbound buffer arena reuse hits
  RTC_IN_POOL_MISSES,    // inbound buffer arena allocations
  RTC_BORROWS,           // zero-copy frames handed out (rt_recv_borrow)
  RTC_DIALS,             // outbound connection attempts (incl. redials)
  RTC_CONNS_ESTABLISHED, // handshakes completed into `established`
  RTC_CONNS_CLOSED,      // established connections torn down
  // -- chaos shaping layer (rt_set_shaping, v2) ------------------------
  RTC_SHAPE_DROPPED,     // outbound frames dropped by per-peer shaping
  RTC_SHAPE_DELAYED,     // outbound frames held in the delay queue
  RTC_GROUP_FRAMES,      // frames delivered through per-group inboxes
                         // (fan-out counted: one frame to 2 groups = 2)
  RTC_GROUP_COPIES,      // extra buffer copies for multi-group frames
  RTC_COUNT
};
constexpr int32_t kCountersVersion = 3;

// Flight recorder: one compact record per frame in/out, so a transport
// stall is attributable after the fact (the engine's flight merger folds
// these between the consensus-event records). Layout is a versioned ABI
// like the RTC_* block; the Python twin is rabia_tpu/net/tcp.TF_DTYPE.
struct TfEvent {
  uint64_t t_ns;      // CLOCK_MONOTONIC (same domain as the rk flight ring)
  uint64_t peer;      // last 8 bytes of the peer's 16-byte node id
  uint32_t len;       // payload length (sans the 4-byte prefix)
  uint8_t dir;        // 0 = in (parsed off a socket), 1 = out (enqueued)
  uint8_t msg_type;   // wire byte 1 of the payload (the v3 msg_type)
  uint16_t pad;
};
static_assert(sizeof(TfEvent) == 24, "transport flight record is ABI");
constexpr int32_t kFlightVersion = 1;
constexpr uint32_t kFlightCap = 4096;  // power of two

uint64_t tf_now_ns() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (uint64_t)ts.tv_sec * 1000000000ull + (uint64_t)ts.tv_nsec;
}

double now_s() {
  return std::chrono::duration<double>(Clock::now().time_since_epoch()).count();
}

using NodeIdBytes = std::array<uint8_t, 16>;

struct InboundMsg {
  NodeIdBytes sender;
  std::vector<uint8_t> data;
};

struct Conn {
  int fd = -1;
  NodeIdBytes peer{};          // zero until handshake completes
  bool handshaken_in = false;  // peer id received
  bool handshake_sent = false;
  bool outbound = false;       // we dialed (vs accepted)
  // simultaneous-dial duplicate that lost the deterministic tiebreak:
  // no longer in `established` (new sends use the winner) but kept
  // open to DRAIN — queued writes flush, then the write side
  // half-closes; inbound frames keep delivering until the peer's
  // symmetric shutdown EOFs the socket. An immediate ::close() here
  // used to drop any frame in flight on the loser during the
  // handshake race window (both sides briefly hold only the doomed
  // connection), surfacing as a rare receive timeout under CPU load.
  bool draining = false;
  bool shut_wr = false;        // SHUT_WR already issued
  // session-multiplexed connection (handshake id == kMuxMagic): never
  // enters `established`/peer dedup; frames carry 16-byte session ids
  bool mux = false;
  double drain_deadline = 0.0;  // hard close if the peer never EOFs
  // the raw 16-byte handshake id is ALWAYS the first wqueue element
  // and is NOT length-prefixed: it must never be re-routed/stashed as
  // a frame (the receiver would parse its first 4 bytes as a length
  // and poison the winner connection). True until that first element
  // fully flushes.
  bool hs_in_queue = false;
  NodeIdBytes dial_target{};   // peer we dialed (valid when outbound)
  std::vector<uint8_t> rbuf;
  // framed bytes pending write. Shared: one broadcast frame is queued on
  // every peer's connection without copies (recycled to the outbound
  // arena when the last reference completes).
  std::deque<std::shared_ptr<std::vector<uint8_t>>> wqueue;
  size_t woff = 0;  // offset into *wqueue.front()
};

// Per-shard-group inbox (the thread-per-shard-group runtime): the io
// loop classifies each inbound frame by the shard groups it carries
// (rt_set_groups installs the classifier — runtime.cpp's
// rtm_frame_group_mask) and delivers to each flagged group's own queue,
// so N runtime workers pull frames without contending one lock per
// frame. Each GroupInbox has its OWN mutex/condvar: the io thread takes
// it briefly at delivery (lock order: Transport::mu -> gmu), a worker
// takes only its group's — workers never touch the transport-wide `mu`
// on the frame path. Borrowed frames and their recycled buffers are
// group-local; the io thread sweeps `recycle` back into the shared
// arena at its next delivery to that group.
struct GroupInbox {
  rabia::Mutex gmu{"transport.group"};
  rabia::CondVar cv;
  // rt_inbox_kick spurious-wake generation, mirroring the main inbox
  std::atomic<uint64_t> kick_gen{0};
  std::deque<InboundMsg> q RABIA_GUARDED_BY(gmu);
  std::map<int64_t, std::vector<uint8_t>> borrowed RABIA_GUARDED_BY(gmu);
  int64_t next_token RABIA_GUARDED_BY(gmu) = 1;
  std::vector<std::vector<uint8_t>> recycle RABIA_GUARDED_BY(gmu);
};

// classifier: returns a bitmask of groups a frame must reach (bit g =
// deliver to group g); 0 means "group 0" (control/unparseable frames)
typedef uint64_t (*rt_classify_t)(void*, const uint8_t*, uint32_t);

struct Peer {
  std::string host;
  uint16_t port = 0;
  int attempts = 0;
  double next_dial = 0.0;
  bool connected = false;
  // frames stranded on a connection that died before flushing, kept
  // briefly for the next established connection to this peer (the
  // simultaneous-dial duplicate teardown can EOF the loser while our
  // frame is still in its wqueue and before the winner has finished
  // its handshake — dropping there breaks "send after is_connected
  // delivers" even though the peer is up). Expired by kStashTtlS.
  std::deque<std::shared_ptr<std::vector<uint8_t>>> stash;
  double stash_t = 0.0;
};

struct Transport {
  NodeIdBytes self_id{};
  int listen_fd = -1;
  int epoll_fd = -1;
  int wake_fd = -1;
  uint16_t port = 0;

  std::thread io_thread;
  std::atomic<bool> stopping{false};

  rabia::Mutex mu{"transport.mu"};  // guards everything below
  std::map<int, Conn> conns RABIA_GUARDED_BY(mu);       // fd -> conn
  std::map<NodeIdBytes, int> established RABIA_GUARDED_BY(mu);
  std::map<NodeIdBytes, Peer> peers RABIA_GUARDED_BY(mu);  // dial targets
  // session id -> fd of the muxed connection carrying it (auto-bound on
  // the first inbound frame bearing the id; latest binding wins, so a
  // session migrating to a fresh connection reroutes its replies)
  std::map<NodeIdBytes, int> mux_sessions RABIA_GUARDED_BY(mu);
  std::deque<InboundMsg> inbox RABIA_GUARDED_BY(mu);
  rabia::CondVar inbox_cv;
  // per-shard-group inboxes (0 = routing off, the single legacy inbox).
  // `groups` entries are stable once created: rt_set_groups only runs
  // while no worker thread is inside a _group call (the runtime bridge
  // installs routing before rtm_start and clears it after rtm_stop), and
  // the vector never shrinks, so workers index it without `mu`.
  std::atomic<int32_t> ngroups{0};
  std::vector<std::unique_ptr<GroupInbox>> groups;
  rt_classify_t classify RABIA_GUARDED_BY(mu) = nullptr;
  void* classify_arg RABIA_GUARDED_BY(mu) = nullptr;
  // rt_inbox_kick: spurious-wake generation counter. A waiter samples it
  // before waiting and also wakes when it changes (see rt_recv_borrow),
  // so a kick staged between the sample and the wait is never lost.
  std::atomic<uint64_t> kick_gen{0};
  uint64_t dropped_frames RABIA_GUARDED_BY(mu) = 0;
  // Zero-copy recv: frames handed out by rt_recv_borrow are parked here
  // (keyed by token) so their pooled buffers outlive the C call until
  // the borrower releases them. std::map: references stay valid across
  // inserts/erases of other keys.
  std::map<int64_t, std::vector<uint8_t>> borrowed RABIA_GUARDED_BY(mu);
  int64_t next_borrow_token RABIA_GUARDED_BY(mu) = 1;
  // Released tokens are STAGED under this light mutex and reclaimed by
  // the next rt_recv_borrow (which holds `mu` anyway). rt_recv_release
  // is called from the engine's event-loop thread once per consumed
  // frame — taking `mu` there would serialize the consensus tick with
  // whole io-loop epoll batches (the same reason rt_send stages under
  // `mu_out` instead of touching `mu`).
  rabia::Mutex mu_rel{"transport.mu_rel"};
  std::vector<int64_t> released RABIA_GUARDED_BY(mu_rel);

  // Outbound staging: rt_send/rt_broadcast never touch `mu` (the io loop
  // holds it across whole epoll batches, syscalls included — a sending
  // engine thread must not stall behind them). Frames are framed once,
  // staged here under the cheap `mu_out`, and drained into per-conn
  // queues by the io thread. Best-effort semantics: a frame staged for a
  // peer that is gone at drain time is dropped, exactly like the
  // reference's sends to disconnected peers (tcp.rs:559-643).
  struct OutMsg {
    std::shared_ptr<std::vector<uint8_t>> frame;
    bool broadcast = false;
    NodeIdBytes target{};
  };
  rabia::Mutex mu_out{"transport.mu_out"};
  std::deque<OutMsg> outq RABIA_GUARDED_BY(mu_out);
  // outbound frame arena
  std::vector<std::vector<uint8_t>> out_pool RABIA_GUARDED_BY(mu_out);
  uint64_t out_hits RABIA_GUARDED_BY(mu_out) = 0;
  uint64_t out_misses RABIA_GUARDED_BY(mu_out) = 0;

  // Chaos shaping layer (rt_set_shaping): per-peer outbound delay/drop
  // injection, applied by the io thread at drain time so the REAL
  // epoll/TCP path carries the shaped traffic (the chaos plane's
  // adverse-network profiles exercise the production C runtime, not a
  // simulator stand-in). Guarded by `mu` (drain_out_locked holds it).
  // Mux client sessions are never shaped — shaping targets replica
  // peers by node id.
  struct Shape {
    uint32_t delay_us = 0;
    uint32_t jitter_us = 0;
    double drop = 0.0;
  };
  std::map<NodeIdBytes, Shape> shaping RABIA_GUARDED_BY(mu);
  struct Delayed {
    double due;
    std::shared_ptr<std::vector<uint8_t>> frame;
    NodeIdBytes target;
    bool operator>(const Delayed& o) const { return due > o.due; }
  };
  std::priority_queue<Delayed, std::vector<Delayed>, std::greater<Delayed>>
      delayq RABIA_GUARDED_BY(mu);
  uint64_t shape_rng RABIA_GUARDED_BY(mu) = 0x9E3779B97F4A7C15ull;

  static inline uint64_t xs64(uint64_t& s) {
    s ^= s << 13;
    s ^= s >> 7;
    s ^= s << 17;
    return s;
  }
  double shape_rand01() RABIA_REQUIRES(mu) {  // uniform [0,1), 53-bit
    return (double)(xs64(shape_rng) >> 11) * (1.0 / 9007199254740992.0);
  }

  // Apply the peer's shape to one frame destined for `id` (established
  // fd `fd`): returns true when the frame was consumed (dropped or
  // queued for later delivery), false when the caller should enqueue it
  // now. Caller holds `mu`.
  bool shape_outbound(const NodeIdBytes& id, int fd, double now,
                      const std::shared_ptr<std::vector<uint8_t>>& f)
      RABIA_REQUIRES(mu) {
    (void)fd;
    if (shaping.empty()) return false;
    auto it = shaping.find(id);
    if (it == shaping.end()) return false;
    const Shape& sh = it->second;
    if (sh.drop > 0.0 && shape_rand01() < sh.drop) {
      bump(RTC_SHAPE_DROPPED);
      return true;
    }
    if (sh.delay_us == 0 && sh.jitter_us == 0) return false;
    double d_us = (double)sh.delay_us;
    if (sh.jitter_us)
      d_us += (shape_rand01() * 2.0 - 1.0) * (double)sh.jitter_us;
    if (d_us <= 0.0) return false;  // jitter-only draws clamp at "now"
    delayq.push(Delayed{now + d_us * 1e-6, f, id});
    bump(RTC_SHAPE_DELAYED);
    return true;
  }

  // Release delayed frames whose due time passed; returns the epoll
  // timeout (ms) until the next one is due (capped by `base_ms`).
  // Caller holds `mu`.
  int release_delayed(double now, int base_ms) RABIA_REQUIRES(mu) {
    while (!delayq.empty() && delayq.top().due <= now) {
      Delayed d = delayq.top();
      delayq.pop();
      auto est = established.find(d.target);
      if (est != established.end()) {
        enqueue_shared_locked(est->second, d.frame);
      }
      // peer gone at release time: best-effort drop, exactly like an
      // unshaped frame staged for a disconnected peer
    }
    if (delayq.empty()) return base_ms;
    int ms = (int)((delayq.top().due - now) * 1e3) + 1;
    if (ms < 1) ms = 1;
    return ms < base_ms ? ms : base_ms;
  }

  // observability counter block (RTC_*), exposed raw via rt_counters.
  // Relaxed atomics: multi-writer (io thread + caller threads), read
  // lock-free by the Python scrape path; std::atomic<uint64_t> is
  // layout-compatible with uint64_t for that zero-copy read.
  std::atomic<uint64_t> ctrs[RTC_COUNT];
  static_assert(sizeof(std::atomic<uint64_t>) == sizeof(uint64_t),
                "counter block must read as a plain uint64 array");

  void bump(int32_t i, uint64_t n = 1) {
    ctrs[i].fetch_add(n, std::memory_order_relaxed);
  }

  // flight-recorder frame ring; all writers hold `mu` (handle_readable /
  // enqueue_shared_locked), rt_flight_copy reads under `mu` too
  std::vector<TfEvent> tf RABIA_GUARDED_BY(mu) =
      std::vector<TfEvent>(kFlightCap);
  uint64_t tf_head RABIA_GUARDED_BY(mu) = 0;

  void tf_rec(uint8_t dir, const NodeIdBytes& peer_id, uint32_t len,
              uint8_t msg_type) RABIA_REQUIRES(mu) {
    TfEvent& e = tf[tf_head & (kFlightCap - 1)];
    e.t_ns = tf_now_ns();
    memcpy(&e.peer, peer_id.data() + 8, 8);
    e.len = len;
    e.dir = dir;
    e.msg_type = msg_type;
    e.pad = 0;
    tf_head++;
  }

  std::shared_ptr<std::vector<uint8_t>> make_frame(const uint8_t* data,
                                                   uint32_t len) {
    std::vector<uint8_t> v;
    {
      rabia::MutexLock lo(mu_out);
      if (!out_pool.empty()) {
        v = std::move(out_pool.back());
        out_pool.pop_back();
        v.clear();
        out_hits++;
        bump(RTC_OUT_POOL_HITS);
      } else {
        out_misses++;
        bump(RTC_OUT_POOL_MISSES);
      }
    }
    v.reserve(4 + len);
    v.resize(4 + len);
    v[0] = len & 0xFF;
    v[1] = (len >> 8) & 0xFF;
    v[2] = (len >> 16) & 0xFF;
    v[3] = (len >> 24) & 0xFF;
    memcpy(v.data() + 4, data, len);
    return std::make_shared<std::vector<uint8_t>>(std::move(v));
  }

  void recycle_frame(std::shared_ptr<std::vector<uint8_t>>&& sp) {
    if (sp.use_count() != 1) return;  // other conns still sending it
    rabia::MutexLock lo(mu_out);
    if (out_pool.size() < kMaxPooled && sp->capacity() <= kMaxPooledBuf) {
      out_pool.push_back(std::move(*sp));
    }
  }

  void kick() {
    uint64_t one = 1;
    (void)!::write(wake_fd, &one, 8);
  }

  // buffer arena (rabia-core/src/memory_pool.rs analog): frame/message
  // byte vectors are recycled instead of allocated per frame. Guarded by
  // mu like everything else.
  std::vector<std::vector<uint8_t>> buf_pool RABIA_GUARDED_BY(mu);
  uint64_t pool_hits RABIA_GUARDED_BY(mu) = 0;
  uint64_t pool_misses RABIA_GUARDED_BY(mu) = 0;
  static constexpr size_t kMaxPooled = 256;

  std::vector<uint8_t> pool_get_locked(size_t need) RABIA_REQUIRES(mu) {
    if (!buf_pool.empty()) {
      std::vector<uint8_t> v = std::move(buf_pool.back());
      buf_pool.pop_back();
      v.clear();
      v.reserve(need);
      pool_hits++;
      bump(RTC_IN_POOL_HITS);
      return v;
    }
    pool_misses++;
    bump(RTC_IN_POOL_MISSES);
    std::vector<uint8_t> v;
    v.reserve(need);
    return v;
  }

  // retain only small buffers: consensus traffic is KB-scale; parking
  // snapshot-sized (up to 16 MiB) buffers would pin gigabytes for the
  // process lifetime
  static constexpr size_t kMaxPooledBuf = 256 * 1024;

  void pool_put_locked(std::vector<uint8_t>&& v) RABIA_REQUIRES(mu) {
    if (buf_pool.size() < kMaxPooled && v.capacity() <= kMaxPooledBuf) {
      buf_pool.push_back(std::move(v));
    }
  }

  void io_loop() RABIA_EXCLUDES(mu);
  void handle_readable(int fd) RABIA_REQUIRES(mu);
  void deliver_groups_locked(InboundMsg&& m, int32_t ng) RABIA_REQUIRES(mu);
  void handle_writable(int fd) RABIA_REQUIRES(mu);
  void try_dials() RABIA_REQUIRES(mu);
  void drain_shutdown(int fd, Conn& c) RABIA_REQUIRES(mu);
  void sweep_draining() RABIA_REQUIRES(mu);
  void dial(const NodeIdBytes& id, Peer& p) RABIA_REQUIRES(mu);
  void close_conn(int fd) RABIA_REQUIRES(mu);
  // false: conn was dropped (dup loser)
  bool establish(int fd, Conn& c) RABIA_REQUIRES(mu);
  void enqueue_shared_locked(
      int fd, const std::shared_ptr<std::vector<uint8_t>>& f)
      RABIA_REQUIRES(mu);
  void drain_out_locked() RABIA_REQUIRES(mu) RABIA_EXCLUDES(mu_out);
  void arm_write(int fd, bool on) RABIA_REQUIRES(mu);
};

int set_nonblock(int fd) {
  int fl = fcntl(fd, F_GETFL, 0);
  return fcntl(fd, F_SETFL, fl | O_NONBLOCK);
}

void Transport::arm_write(int fd, bool on) RABIA_REQUIRES(mu) {
  epoll_event ev{};
  ev.events = EPOLLIN | (on ? EPOLLOUT : 0);
  ev.data.fd = fd;
  epoll_ctl(epoll_fd, EPOLL_CTL_MOD, fd, &ev);
}

void Transport::close_conn(int fd) RABIA_REQUIRES(mu) {
  auto it = conns.find(fd);
  if (it == conns.end()) return;
  Conn& c = it->second;
  if (c.hs_in_queue && !c.wqueue.empty()) {
    // the raw handshake id is not a frame — never re-route it
    c.wqueue.pop_front();
    c.woff = 0;
    c.hs_in_queue = false;
  }
  if (c.mux) {
    // unbind every session riding this connection; a session that
    // redials (or already rebound to a newer conn) re-binds on its
    // first inbound frame there
    for (auto it = mux_sessions.begin(); it != mux_sessions.end();) {
      if (it->second == fd)
        it = mux_sessions.erase(it);
      else
        ++it;
    }
  }
  if (c.handshaken_in && !c.mux && !c.wqueue.empty()) {
    // undelivered frames must not die with the socket when the peer is
    // still reachable: re-route whole frames to the established winner
    // (a partially written front frame arrives truncated and is
    // discarded by the peer's length-prefix parser, so re-sending the
    // whole frame cannot duplicate), or stash them briefly for the
    // next connection when the winner's handshake hasn't finished yet.
    auto est = established.find(c.peer);
    if (est != established.end() && est->second != fd) {
      auto wit = conns.find(est->second);
      if (wit != conns.end()) {
        for (auto& f : c.wqueue)
          wit->second.wqueue.push_back(std::move(f));
        arm_write(est->second, true);
        c.wqueue.clear();
      }
    }
    if (!c.wqueue.empty()) {
      auto p = peers.find(c.peer);
      if (p != peers.end()) {
        for (auto& f : c.wqueue) p->second.stash.push_back(std::move(f));
        p->second.stash_t = now_s();
        c.wqueue.clear();
      }
    }
  }
  if (c.handshaken_in) {
    auto est = established.find(c.peer);
    if (est != established.end() && est->second == fd) {
      established.erase(est);
      bump(RTC_CONNS_CLOSED);
      auto p = peers.find(c.peer);
      if (p != peers.end()) {
        p->second.connected = false;
        p->second.attempts = 0;
        p->second.next_dial = now_s() + kDialBaseDelayS;
      }
    }
  }
  epoll_ctl(epoll_fd, EPOLL_CTL_DEL, fd, nullptr);
  ::close(fd);
  conns.erase(it);
}

bool Transport::establish(int fd, Conn& c) RABIA_REQUIRES(mu) {
  auto old = established.find(c.peer);
  if (old != established.end() && old->second != fd) {
    // simultaneous-dial duplicate: BOTH sides must deterministically keep
    // the same connection or they flap (each closing the one the other
    // kept). Rule: the connection dialed by the smaller node id wins.
    // The loser is DRAINED, not closed (see Conn::draining): frames a
    // peer sent on it during the race window must still deliver, and
    // our queued writes on it must still flush.
    auto initiator = [&](const Conn& conn) -> const NodeIdBytes& {
      return conn.outbound ? self_id : conn.peer;
    };
    int old_fd = old->second;
    Conn& oldc = conns[old_fd];
    bool new_wins = initiator(c) < initiator(oldc);
    Conn& loser = new_wins ? oldc : c;
    int loser_fd = new_wins ? old_fd : fd;
    loser.draining = true;
    loser.drain_deadline = now_s() + kRedialPeriodS;
    drain_shutdown(loser_fd, loser);
    if (!new_wins) return false;  // c lives on, draining
    established.erase(old);
  }
  established[c.peer] = fd;
  bump(RTC_CONNS_ESTABLISHED);
  auto p = peers.find(c.peer);
  if (p != peers.end()) {
    p->second.connected = true;
    p->second.attempts = 0;
    if (!p->second.stash.empty()) {
      // frames stranded by a dying duplicate connection: deliver on
      // this one unless the redelivery window lapsed (a long-dead peer
      // should not receive stale protocol frames on reconnect —
      // consensus retransmission owns that timescale)
      bool fresh = now_s() - p->second.stash_t <= kStashTtlS;
      for (auto& f : p->second.stash) {
        if (fresh) c.wqueue.push_back(std::move(f));
      }
      p->second.stash.clear();
      if (fresh) arm_write(fd, true);
    }
  }
  return true;
}

void Transport::drain_shutdown(int fd, Conn& c) RABIA_REQUIRES(mu) {
  // half-close a draining loser once its queued writes flushed; the
  // peer (running the same rule) does the same, and each side closes
  // on the other's EOF — no frame in either direction is dropped
  if (c.draining && !c.shut_wr && c.wqueue.empty()) {
    ::shutdown(fd, SHUT_WR);
    c.shut_wr = true;
  }
}

void Transport::sweep_draining() RABIA_REQUIRES(mu) {
  // a draining peer that crashed mid-drain never EOFs us; reap on the
  // deadline (same period as the redial scan)
  double t = now_s();
  std::vector<int> overdue;
  for (auto& [fd, c] : conns) {
    if (c.draining && t >= c.drain_deadline) overdue.push_back(fd);
  }
  for (int fd : overdue) close_conn(fd);
}

void Transport::handle_readable(int fd) RABIA_REQUIRES(mu) {
  auto it = conns.find(fd);
  if (it == conns.end()) return;
  Conn& c = it->second;
  uint8_t buf[64 * 1024];
  for (;;) {
    ssize_t n = ::recv(fd, buf, sizeof(buf), 0);
    if (n > 0) {
      c.rbuf.insert(c.rbuf.end(), buf, buf + n);
    } else if (n == 0) {
      close_conn(fd);
      return;
    } else if (errno == EAGAIN || errno == EWOULDBLOCK) {
      break;
    } else {
      close_conn(fd);
      return;
    }
  }
  size_t off = 0;
  // handshake: first 16 bytes are the peer's node id
  if (!c.handshaken_in) {
    if (c.rbuf.size() < 16) return;
    memcpy(c.peer.data(), c.rbuf.data(), 16);
    c.handshaken_in = true;
    off = 16;
    if (memcmp(c.peer.data(), kMuxMagic, 16) == 0) {
      // session-multiplexed client connection: many sessions, one
      // socket. Never enters `established` (two mux conns would
      // collide on the magic id) and skips the dup tiebreak.
      c.mux = true;
    } else {
      // a dup loser keeps draining: frames already on this socket still
      // parse and deliver below (sender id is known now either way)
      establish(fd, c);
    }
  }
  while (c.rbuf.size() - off >= 4) {
    uint32_t len = static_cast<uint32_t>(c.rbuf[off]) |
                   (static_cast<uint32_t>(c.rbuf[off + 1]) << 8) |
                   (static_cast<uint32_t>(c.rbuf[off + 2]) << 16) |
                   (static_cast<uint32_t>(c.rbuf[off + 3]) << 24);
    if (len > kMaxFrame || (c.mux && len < 16)) {
      // poisoned stream (mux frames must carry a session id prefix)
      close_conn(fd);
      return;
    }
    if (c.rbuf.size() - off - 4 < len) break;
    InboundMsg m;
    if (c.mux) {
      // [16B session id][inner payload]: the embedded id IS the sender
      memcpy(m.sender.data(), c.rbuf.data() + off + 4, 16);
      mux_sessions[m.sender] = fd;  // bind/rebind replies to this conn
      len -= 16;
      m.data = pool_get_locked(len);
      m.data.assign(c.rbuf.begin() + off + 20,
                    c.rbuf.begin() + off + 20 + len);
      off += 16;  // consumed the prefix; the tail advance below adds len
    } else {
      m.sender = c.peer;
      m.data = pool_get_locked(len);
      m.data.assign(c.rbuf.begin() + off + 4,
                    c.rbuf.begin() + off + 4 + len);
    }
    bump(RTC_FRAMES_IN);
    bump(RTC_BYTES_IN, len);
    tf_rec(0, m.sender, len, len >= 2 ? m.data[1] : 0);
    const int32_t ng = ngroups.load(std::memory_order_acquire);
    if (ng > 0) {
      // thread-per-shard-group routing: classify by the shards the
      // frame carries and deliver to each flagged group's own inbox
      deliver_groups_locked(std::move(m), ng);
    } else {
      if (inbox.size() >= kMaxInbox) {
        pool_put_locked(std::move(inbox.front().data));
        inbox.pop_front();
        dropped_frames++;
        bump(RTC_INBOX_DROPPED);
      }
      inbox.push_back(std::move(m));
    }
    off += 4 + len;
  }
  if (off) c.rbuf.erase(c.rbuf.begin(), c.rbuf.begin() + off);
  if (!inbox.empty()) inbox_cv.notify_all();
}

// Route one inbound frame to its shard groups' inboxes. Multi-group
// frames (a workers=1 peer's mixed vote batch) are copied per extra
// group — each worker's rk ctx ingests only its own shard range, so
// every group must see the whole frame. The classifier is pure and
// read-only (runtime.cpp rtm_frame_group_mask); mask 0 or unroutable
// frames (Propose/sync/admin/malformed) land in group 0, whose worker
// owns control-plane escalation.
void Transport::deliver_groups_locked(InboundMsg&& m, int32_t ng)
    RABIA_REQUIRES(mu) {
  uint64_t mask =
      classify ? classify(classify_arg, m.data.data(),
                          (uint32_t)m.data.size())
               : 1;
  const uint64_t all = ng >= 64 ? ~0ull : ((1ull << ng) - 1);
  mask &= all;
  if (!mask) mask = 1;
  const int32_t last = 63 - __builtin_clzll(mask);
  for (int32_t g = 0; g < ng; g++) {
    if (!(mask & (1ull << g))) continue;
    GroupInbox& G = *groups[(size_t)g];
    InboundMsg d;
    d.sender = m.sender;
    if (g == last) {
      d.data = std::move(m.data);
    } else {
      d.data = pool_get_locked(m.data.size());
      d.data.assign(m.data.begin(), m.data.end());
      bump(RTC_GROUP_COPIES);
    }
    bump(RTC_GROUP_FRAMES);
    {
      rabia::MutexLock lg(G.gmu);
      // sweep this group's released borrow buffers back into the arena
      // (the worker recycles lock-cheap; only the io thread, already
      // holding `mu`, touches the shared pool)
      if (!G.recycle.empty()) {
        for (auto& v : G.recycle) pool_put_locked(std::move(v));
        G.recycle.clear();
      }
      if (G.q.size() >= kMaxInbox) {
        pool_put_locked(std::move(G.q.front().data));
        G.q.pop_front();
        dropped_frames++;
        bump(RTC_INBOX_DROPPED);
      }
      G.q.push_back(std::move(d));
    }
    G.cv.notify_all();
  }
}

void Transport::handle_writable(int fd) RABIA_REQUIRES(mu) {
  auto it = conns.find(fd);
  if (it == conns.end()) return;
  Conn& c = it->second;
  while (!c.wqueue.empty()) {
    auto& front = *c.wqueue.front();
    ssize_t n = ::send(fd, front.data() + c.woff, front.size() - c.woff,
                       MSG_NOSIGNAL);
    if (n > 0) {
      c.woff += static_cast<size_t>(n);
      if (c.woff == front.size()) {
        auto sp = std::move(c.wqueue.front());
        c.wqueue.pop_front();
        c.woff = 0;
        c.hs_in_queue = false;  // handshake is strictly first
        recycle_frame(std::move(sp));
      }
    } else if (errno == EAGAIN || errno == EWOULDBLOCK) {
      return;  // stay EPOLLOUT-armed
    } else {
      close_conn(fd);
      return;
    }
  }
  arm_write(fd, false);
  drain_shutdown(fd, c);  // draining loser: flushed — half-close now
}

void Transport::enqueue_shared_locked(
    int fd, const std::shared_ptr<std::vector<uint8_t>>& f)
    RABIA_REQUIRES(mu) {
  auto it = conns.find(fd);
  if (it == conns.end()) return;
  it->second.wqueue.push_back(f);
  bump(RTC_FRAMES_OUT);
  bump(RTC_BYTES_OUT, f->size());
  tf_rec(1, it->second.peer, (uint32_t)(f->size() - 4),
         f->size() >= 6 ? (*f)[5] : 0);
  arm_write(fd, true);
}

void Transport::drain_out_locked() RABIA_REQUIRES(mu) {
  std::deque<OutMsg> local;
  {
    rabia::MutexLock lo(mu_out);
    local.swap(outq);
  }
  const double now = local.empty() ? 0.0 : now_s();
  for (auto& m : local) {
    if (m.broadcast) {
      for (auto& [id, fd] : established) {
        if (!shape_outbound(id, fd, now, m.frame))
          enqueue_shared_locked(fd, m.frame);
      }
    } else {
      auto est = established.find(m.target);
      if (est != established.end()) {
        if (!shape_outbound(m.target, est->second, now, m.frame))
          enqueue_shared_locked(est->second, m.frame);
        continue;
      }
      auto mx = mux_sessions.find(m.target);
      if (mx != mux_sessions.end()) {
        // session on a muxed connection: re-frame with the 16-byte
        // session id prefix so the client side can demultiplex
        const auto& f = *m.frame;  // [4B len][payload]
        uint32_t plen = (uint32_t)(f.size() - 4);
        auto wrapped = std::make_shared<std::vector<uint8_t>>();
        wrapped->resize(4 + 16 + plen);
        uint32_t wl = 16 + plen;
        (*wrapped)[0] = wl & 0xFF;
        (*wrapped)[1] = (wl >> 8) & 0xFF;
        (*wrapped)[2] = (wl >> 16) & 0xFF;
        (*wrapped)[3] = (wl >> 24) & 0xFF;
        memcpy(wrapped->data() + 4, m.target.data(), 16);
        memcpy(wrapped->data() + 20, f.data() + 4, plen);
        enqueue_shared_locked(mx->second, wrapped);
      }
    }
  }
}

void Transport::dial(const NodeIdBytes& id, Peer& p) RABIA_REQUIRES(mu) {
  bump(RTC_DIALS);
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return;
  set_nonblock(fd);
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(p.port);
  if (inet_pton(AF_INET, p.host.c_str(), &addr.sin_addr) != 1) {
    ::close(fd);
    return;
  }
  int rc = ::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr));
  if (rc < 0 && errno != EINPROGRESS) {
    // try_dials already advanced attempts/next_dial for this cycle; an
    // immediate refusal must not double-charge the backoff budget
    ::close(fd);
    return;
  }
  Conn c;
  c.fd = fd;
  c.outbound = true;
  c.dial_target = id;
  // send our id immediately (kernel buffers it through connect completion)
  c.wqueue.push_back(
      std::make_shared<std::vector<uint8_t>>(self_id.begin(), self_id.end()));
  c.handshake_sent = true;
  c.hs_in_queue = true;
  conns[fd] = std::move(c);
  epoll_event ev{};
  ev.events = EPOLLIN | EPOLLOUT;
  ev.data.fd = fd;
  epoll_ctl(epoll_fd, EPOLL_CTL_ADD, fd, &ev);
}

void Transport::try_dials() RABIA_REQUIRES(mu) {
  double t = now_s();
  for (auto& [id, p] : peers) {
    if (p.connected) continue;
    bool mid_dial = false;  // an in-flight outbound conn to this peer?
    for (auto& [fd, c] : conns) {
      if (c.outbound && !c.handshaken_in && c.dial_target == id) {
        mid_dial = true;
        break;
      }
    }
    if (mid_dial) continue;
    // after the initial backoff budget, keep re-dialing slowly forever
    if (p.attempts >= kMaxDialAttempts) {
      if (t >= p.next_dial) {
        p.attempts = 0;
        p.next_dial = t + kRedialPeriodS;
        dial(id, p);
      }
      continue;
    }
    if (t >= p.next_dial) {
      p.attempts++;
      double delay = kDialBaseDelayS;
      for (int i = 1; i < p.attempts; i++) delay *= 2.0;
      if (delay > kDialMaxDelayS) delay = kDialMaxDelayS;
      p.next_dial = t + delay;
      dial(id, p);
    }
  }
}

void Transport::io_loop() RABIA_EXCLUDES(mu) {
  epoll_event evs[64];
  int wait_ms = 50;
  while (!stopping.load()) {
    int n = epoll_wait(epoll_fd, evs, 64, wait_ms);
    rabia::MutexLock lk(mu);
    drain_out_locked();
    // chaos shaping: deliver due delayed frames and tighten the next
    // epoll timeout to the next due time (50ms granularity would smear
    // sub-50ms injected delays)
    wait_ms = delayq.empty() ? 50 : release_delayed(now_s(), 50);
    for (int i = 0; i < n; i++) {
      int fd = evs[i].data.fd;
      uint32_t e = evs[i].events;
      if (fd == wake_fd) {
        uint64_t junk;
        while (::read(wake_fd, &junk, 8) == 8) {
        }
        continue;
      }
      if (fd == listen_fd) {
        for (;;) {
          int cfd = ::accept(listen_fd, nullptr, nullptr);
          if (cfd < 0) break;
          set_nonblock(cfd);
          int one = 1;
          setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
          Conn c;
          c.fd = cfd;
          c.wqueue.push_back(std::make_shared<std::vector<uint8_t>>(
              self_id.begin(), self_id.end()));
          c.handshake_sent = true;
          c.hs_in_queue = true;
          conns[cfd] = std::move(c);
          epoll_event ev{};
          ev.events = EPOLLIN | EPOLLOUT;
          ev.data.fd = cfd;
          epoll_ctl(epoll_fd, EPOLL_CTL_ADD, cfd, &ev);
        }
        continue;
      }
      // read BEFORE acting on HUP: a peer's final frames can land in
      // the same epoll event as its FIN (EPOLLIN|EPOLLHUP — routine on
      // a draining duplicate connection whose both halves are shut).
      // Closing first would discard them unread from the kernel
      // buffer; handle_readable drains to EOF and closes the conn
      // itself, making the HUP branch a no-op for that fd.
      if (e & EPOLLIN) handle_readable(fd);
      if (e & (EPOLLHUP | EPOLLERR)) {
        close_conn(fd);
        continue;
      }
      if (e & EPOLLOUT) handle_writable(fd);
    }
    try_dials();
    sweep_draining();
  }
}

}  // namespace

extern "C" {

// Creates + starts a transport. Writes the actually-bound port into
// *actual_port (useful with port=0). Returns an opaque handle or null.
void* rt_create(const uint8_t node_id[16], const char* bind_host,
                uint16_t port, uint16_t* actual_port) {
  auto* t = new Transport();
  memcpy(t->self_id.data(), node_id, 16);
  for (auto& c : t->ctrs) c.store(0, std::memory_order_relaxed);

  t->listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (t->listen_fd < 0) {
    delete t;
    return nullptr;
  }
  int one = 1;
  setsockopt(t->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  if (inet_pton(AF_INET, bind_host, &addr.sin_addr) != 1) {
    ::close(t->listen_fd);
    delete t;
    return nullptr;
  }
  if (::bind(t->listen_fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) <
          0 ||
      ::listen(t->listen_fd, 128) < 0) {
    ::close(t->listen_fd);
    delete t;
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  getsockname(t->listen_fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  t->port = ntohs(addr.sin_port);
  if (actual_port) *actual_port = t->port;
  set_nonblock(t->listen_fd);

  t->epoll_fd = epoll_create1(0);
  t->wake_fd = eventfd(0, EFD_NONBLOCK);
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.fd = t->listen_fd;
  epoll_ctl(t->epoll_fd, EPOLL_CTL_ADD, t->listen_fd, &ev);
  ev.data.fd = t->wake_fd;
  epoll_ctl(t->epoll_fd, EPOLL_CTL_ADD, t->wake_fd, &ev);

  t->io_thread = std::thread([t] { t->io_loop(); });
  return t;
}

int rt_add_peer(void* h, const uint8_t peer_id[16], const char* host,
                uint16_t port) {
  auto* t = static_cast<Transport*>(h);
  NodeIdBytes id;
  memcpy(id.data(), peer_id, 16);
  {
    rabia::MutexLock lk(t->mu);
    Peer p;
    p.host = host;
    p.port = port;
    p.next_dial = 0.0;
    t->peers[id] = std::move(p);
  }
  uint64_t one = 1;
  (void)!::write(t->wake_fd, &one, 8);
  return 0;
}

int rt_remove_peer(void* h, const uint8_t peer_id[16]) {
  auto* t = static_cast<Transport*>(h);
  NodeIdBytes id;
  memcpy(id.data(), peer_id, 16);
  rabia::MutexLock lk(t->mu);
  t->peers.erase(id);
  auto est = t->established.find(id);
  if (est != t->established.end()) t->close_conn(est->second);
  return 0;
}

// Chaos shaping layer: inject per-peer outbound delay (+/- jitter) and
// drop probability on this transport's link TO peer_id, applied on the
// io thread at drain time (see Transport::shape_outbound). Asymmetric
// by construction — shape one side's transport to impair one direction.
// delay_us=0, drop=0 clears the peer's entry; seed != 0 reseeds the
// deterministic drop RNG. Returns 0.
int rt_set_shaping(void* h, const uint8_t peer_id[16], uint32_t delay_us,
                   uint32_t jitter_us, double drop, uint64_t seed) {
  auto* t = static_cast<Transport*>(h);
  NodeIdBytes id;
  memcpy(id.data(), peer_id, 16);
  {
    rabia::MutexLock lk(t->mu);
    if (seed) t->shape_rng = seed;
    if (delay_us == 0 && jitter_us == 0 && drop <= 0.0) {
      t->shaping.erase(id);
    } else {
      Transport::Shape sh;
      sh.delay_us = delay_us;
      sh.jitter_us = jitter_us;
      sh.drop = drop < 0.0 ? 0.0 : (drop > 1.0 ? 1.0 : drop);
      t->shaping[id] = sh;
    }
  }
  t->kick();
  return 0;
}

// Clear every shaping entry (delayed frames already queued still deliver
// at their due times — clearing stops future impairment, it does not
// reorder traffic already in the delay queue).
int rt_clear_shaping(void* h) {
  auto* t = static_cast<Transport*>(h);
  rabia::MutexLock lk(t->mu);
  t->shaping.clear();
  return 0;
}

// 0 = queued; -1 = peer not connected.
int rt_send(void* h, const uint8_t peer_id[16], const uint8_t* data,
            uint32_t len) {
  auto* t = static_cast<Transport*>(h);
  if (len > kMaxFrame) return -2;
  NodeIdBytes id;
  memcpy(id.data(), peer_id, 16);
  auto frame = t->make_frame(data, len);
  {
    rabia::MutexLock lo(t->mu_out);
    t->outq.push_back({std::move(frame), false, id});
  }
  t->kick();
  return 0;
}

// Returns number of peers the frame was queued to.
int rt_broadcast(void* h, const uint8_t* data, uint32_t len) {
  auto* t = static_cast<Transport*>(h);
  if (len > kMaxFrame) return -2;
  auto frame = t->make_frame(data, len);
  {
    rabia::MutexLock lo(t->mu_out);
    t->outq.push_back({std::move(frame), true, NodeIdBytes{}});
  }
  t->kick();
  return 0;
}

// Broadcast a batch of frames packed as [u32 record_len][frame bytes]...
// (the native tick's outbound buffer, hostkernel.cpp rk_tick). All frames
// are staged under ONE outbound lock acquisition and one io-loop kick, so
// a chained tick's R1+R2+Decision wave costs a single Python->C call and
// a single wakeup. Returns the number of frames staged, or -2 if any
// record is malformed / exceeds the frame cap (staging stops there).
int rt_broadcast_frames(void* h, const uint8_t* buf, int64_t len) {
  auto* t = static_cast<Transport*>(h);
  // frame first (make_frame takes mu_out itself for the pool), then stage
  // the whole batch under one lock acquisition
  std::vector<Transport::OutMsg> staged;
  int64_t pos = 0;
  while (pos + 4 <= len) {
    uint32_t rec;
    memcpy(&rec, buf + pos, 4);
    if (rec > kMaxFrame || pos + 4 + (int64_t)rec > len) return -2;
    staged.push_back({t->make_frame(buf + pos + 4, rec), true,
                      NodeIdBytes{}});
    pos += 4 + (int64_t)rec;
  }
  if (staged.empty()) return 0;
  const int n = (int)staged.size();
  {
    rabia::MutexLock lo(t->mu_out);
    for (auto& m : staged) t->outq.push_back(std::move(m));
  }
  t->kick();
  return n;
}

// Blocks up to timeout_ms for one inbound frame. Returns the frame length
// >= 0 (copied into buf, truncated to buf_cap; 0 is a valid empty frame),
// -3 on timeout with no message, -1 if closed.
int rt_recv(void* h, uint8_t sender_out[16], uint8_t* buf, uint32_t buf_cap,
            int timeout_ms) {
  auto* t = static_cast<Transport*>(h);
  rabia::MutexLock lk(t->mu);
  if (t->inbox.empty() && timeout_ms != 0) {
    const uint64_t k0 = t->kick_gen.load(std::memory_order_relaxed);
    const timespec dl =
        rabia::CondVar::deadline_in((double)timeout_ms * 1e-3);
    while (t->inbox.empty() && !t->stopping.load() &&
           t->kick_gen.load(std::memory_order_relaxed) == k0) {
      if (!t->inbox_cv.wait_until(lk, dl)) break;
    }
  }
  if (t->inbox.empty()) return t->stopping.load() ? -1 : -3;
  InboundMsg m = std::move(t->inbox.front());
  t->inbox.pop_front();
  memcpy(sender_out, m.sender.data(), 16);
  uint32_t n = static_cast<uint32_t>(m.data.size());
  if (n > buf_cap) n = buf_cap;
  memcpy(buf, m.data.data(), n);
  t->pool_put_locked(std::move(m.data));
  return static_cast<int>(n);
}

// Zero-copy variant of rt_recv: pops one inbound frame and hands out a
// BORROWED pointer into its pooled buffer — no memcpy (the SURVEY
// §7.4.7 handoff: the codec and jax.dlpack consume the frame where the
// io thread landed it). The buffer stays alive, parked in a borrow
// table, until rt_recv_release(token); releasing returns it to the
// arena. Returns a token > 0 with *ptr_out/*len_out set, -3 on timeout
// with no message, -1 if closed.
int64_t rt_recv_borrow(void* h, uint8_t sender_out[16],
                       const uint8_t** ptr_out, uint32_t* len_out,
                       int timeout_ms) {
  auto* t = static_cast<Transport*>(h);
  std::vector<int64_t> rel;
  {
    rabia::MutexLock lr(t->mu_rel);
    rel.swap(t->released);
  }
  rabia::MutexLock lk(t->mu);
  for (int64_t tok : rel) {
    auto it = t->borrowed.find(tok);
    if (it != t->borrowed.end()) {
      t->pool_put_locked(std::move(it->second));
      t->borrowed.erase(it);
    }
  }
  if (t->inbox.empty() && timeout_ms != 0) {
    const uint64_t k0 = t->kick_gen.load(std::memory_order_relaxed);
    const timespec dl =
        rabia::CondVar::deadline_in((double)timeout_ms * 1e-3);
    while (t->inbox.empty() && !t->stopping.load() &&
           t->kick_gen.load(std::memory_order_relaxed) == k0) {
      if (!t->inbox_cv.wait_until(lk, dl)) break;
    }
  }
  if (t->inbox.empty()) return t->stopping.load() ? -1 : -3;
  InboundMsg m = std::move(t->inbox.front());
  t->inbox.pop_front();
  memcpy(sender_out, m.sender.data(), 16);
  int64_t tok = t->next_borrow_token++;
  auto& slot = t->borrowed[tok];
  slot = std::move(m.data);
  *ptr_out = slot.data();
  *len_out = static_cast<uint32_t>(slot.size());
  t->bump(RTC_BORROWS);
  return tok;
}

// Return a borrowed frame's buffer to the arena. Unknown/already-released
// tokens are ignored (close() may race a late release harmlessly as long
// as the handle itself is still alive). Deliberately NEVER takes `mu`:
// the caller is the engine's event-loop thread, and `mu` is held by the
// io thread across whole epoll batches — the token is staged and the
// buffer reclaimed by the next rt_recv_borrow. The borrowed frame stays
// valid until then (reclamation only happens under `mu` in borrow).
void rt_recv_release(void* h, int64_t token) {
  auto* t = static_cast<Transport*>(h);
  const int64_t g = (token >> 48) - 1;
  if (g >= 0) {
    // group-encoded token (rt_recv_borrow_group): recycle group-locally;
    // the io thread sweeps the buffers back into the shared arena at its
    // next delivery. Callers release on the borrowing worker's thread.
    if ((size_t)g < t->groups.size()) {
      GroupInbox& G = *t->groups[(size_t)g];
      rabia::MutexLock lg(G.gmu);
      auto it = G.borrowed.find(token & 0xFFFFFFFFFFFFll);
      if (it != G.borrowed.end()) {
        if (G.recycle.size() < 256) G.recycle.push_back(std::move(it->second));
        G.borrowed.erase(it);
      }
    }
    return;
  }
  rabia::MutexLock lr(t->mu_rel);
  t->released.push_back(token);
}

// Zero-copy receive from one shard group's inbox (rt_set_groups routing
// active). Same contract as rt_recv_borrow; the returned token routes
// its release back to the group. Returns -3 timeout, -1 closed/invalid.
int64_t rt_recv_borrow_group(void* h, int32_t group, uint8_t sender_out[16],
                             const uint8_t** ptr_out, uint32_t* len_out,
                             int timeout_ms) {
  auto* t = static_cast<Transport*>(h);
  const int32_t ng = t->ngroups.load(std::memory_order_acquire);
  if (group < 0 || group >= ng) return -1;
  GroupInbox& G = *t->groups[(size_t)group];
  rabia::MutexLock lk(G.gmu);
  if (G.q.empty() && timeout_ms != 0) {
    const uint64_t k0 = G.kick_gen.load(std::memory_order_relaxed);
    const timespec dl =
        rabia::CondVar::deadline_in((double)timeout_ms * 1e-3);
    while (G.q.empty() && !t->stopping.load() &&
           G.kick_gen.load(std::memory_order_relaxed) == k0) {
      if (!G.cv.wait_until(lk, dl)) break;
    }
  }
  if (G.q.empty()) return t->stopping.load() ? -1 : -3;
  InboundMsg m = std::move(G.q.front());
  G.q.pop_front();
  memcpy(sender_out, m.sender.data(), 16);
  int64_t tok = G.next_token++;
  auto& slot = G.borrowed[tok];
  slot = std::move(m.data);
  *ptr_out = slot.data();
  *len_out = static_cast<uint32_t>(slot.size());
  t->bump(RTC_BORROWS);
  return ((int64_t)(group + 1) << 48) | tok;
}

// Install (ngroups >= 1) or clear (ngroups == 0) per-shard-group frame
// routing. classify_fn(arg, data, len) -> group bitmask (0 = group 0).
// MUST be called while no thread is inside a _group entry point — the
// runtime bridge installs routing before rtm_start and clears it after
// rtm_stop. On clear, undelivered group frames merge back into the
// legacy inbox so a re-attached Python reader sees them.
int rt_set_groups(void* h, int32_t ngroups, void* classify_fn, void* arg) {
  auto* t = static_cast<Transport*>(h);
  if (ngroups < 0 || ngroups > 64) return -1;
  rabia::MutexLock lk(t->mu);
  if (ngroups == 0) {
    t->ngroups.store(0, std::memory_order_release);
    t->classify = nullptr;
    t->classify_arg = nullptr;
    for (auto& gp : t->groups) {
      if (!gp) continue;
      rabia::MutexLock lg(gp->gmu);
      while (!gp->q.empty()) {
        if (t->inbox.size() < kMaxInbox) {
          t->inbox.push_back(std::move(gp->q.front()));
        } else {
          // legacy inbox full: drop like every other overflow path —
          // counted, buffer recycled (not silently destroyed)
          t->pool_put_locked(std::move(gp->q.front().data));
          t->dropped_frames++;
          t->bump(RTC_INBOX_DROPPED);
        }
        gp->q.pop_front();
      }
      for (auto& v : gp->recycle) t->pool_put_locked(std::move(v));
      gp->recycle.clear();
    }
    // `groups` entries are retained: a straggling release may still
    // index them (GroupInbox addresses are stable behind unique_ptr)
    if (!t->inbox.empty()) t->inbox_cv.notify_all();
    return 0;
  }
  while ((int32_t)t->groups.size() < ngroups)
    t->groups.push_back(std::make_unique<GroupInbox>());
  t->classify = (rt_classify_t)classify_fn;
  t->classify_arg = arg;
  t->ngroups.store(ngroups, std::memory_order_release);
  return 0;
}

// Buffer-arena counters (memory_pool.rs PoolStats analog).
void rt_pool_stats(void* h, uint64_t* hits, uint64_t* misses) {
  auto* t = static_cast<Transport*>(h);
  rabia::MutexLock lk(t->mu);
  rabia::MutexLock lo(t->mu_out);
  *hits = t->pool_hits + t->out_hits;
  *misses = t->pool_misses + t->out_misses;
}

// Outbound frame-arena counters alone (the out-pool: rt_send/rt_broadcast
// staging buffers), previously folded invisibly into rt_pool_stats.
void rt_out_pool_stats(void* h, uint64_t* hits, uint64_t* misses) {
  auto* t = static_cast<Transport*>(h);
  rabia::MutexLock lo(t->mu_out);
  *hits = t->out_hits;
  *misses = t->out_misses;
}

// --- observability counter block -------------------------------------------

int32_t rt_counters_version(void) { return kCountersVersion; }
int32_t rt_counters_count(void) { return RTC_COUNT; }

// --- flight recorder (frame in/out ring) ------------------------------------

int32_t rt_flight_version(void) { return kFlightVersion; }
int32_t rt_flight_record_size(void) { return (int32_t)sizeof(TfEvent); }
// Copy the most recent min(written, kFlightCap, max_records) records into
// `out` (max_records * rt_flight_record_size() bytes) in chronological
// order; returns the count. Taken under the io mutex — a consistent
// snapshot, unlike the relaxed counter block.
int64_t rt_flight_copy(void* h, uint8_t* out, int64_t max_records) {
  auto* t = static_cast<Transport*>(h);
  rabia::MutexLock lk(t->mu);
  uint64_t n = t->tf_head < kFlightCap ? t->tf_head : kFlightCap;
  if ((int64_t)n > max_records) n = (uint64_t)max_records;
  uint64_t start = t->tf_head - n;
  auto* dst = reinterpret_cast<TfEvent*>(out);
  for (uint64_t i = 0; i < n; i++) {
    dst[i] = t->tf[(start + i) & (kFlightCap - 1)];
  }
  return (int64_t)n;
}
// Borrowed pointer to the transport's counter block (RTC_* order), valid
// until rt_close. Relaxed-atomic cells readable as plain uint64s.
const uint64_t* rt_counters(void* h) {
  auto* t = static_cast<Transport*>(h);
  return reinterpret_cast<const uint64_t*>(t->ctrs);
}

// Writes up to cap peer ids (16 bytes each) of established peers; returns
// the count.
int rt_connected(void* h, uint8_t* ids_out, int cap) {
  auto* t = static_cast<Transport*>(h);
  rabia::MutexLock lk(t->mu);
  int i = 0;
  for (auto& [id, fd] : t->established) {
    if (i >= cap) break;
    memcpy(ids_out + 16 * i, id.data(), 16);
    i++;
  }
  return i;
}

uint16_t rt_port(void* h) { return static_cast<Transport*>(h)->port; }

// Spurious-wake a thread blocked in rt_recv / rt_recv_borrow (returns -3
// there as on timeout). Used by the Python control plane to nudge the
// native runtime thread after staging a command. Deliberately LOCK-FREE:
// taking `mu` here would stall the caller behind whole io-loop epoll
// batches (milliseconds under load — measured on the engine's submit
// path). The cost is a nanoseconds-wide lost-wakeup window (generation
// bumped after the waiter's predicate check but notified before its
// futex wait); the runtime thread bounds that race with a short recv
// timeout, so a lost kick only delays a command by one idle tick.
void rt_inbox_kick(void* h) {
  auto* t = static_cast<Transport*>(h);
  t->kick_gen.fetch_add(1, std::memory_order_relaxed);
  t->inbox_cv.notify_all();
  // wake every shard-group worker too (same lock-free contract)
  const int32_t ng = t->ngroups.load(std::memory_order_acquire);
  for (int32_t g = 0; g < ng; g++) {
    GroupInbox& G = *t->groups[(size_t)g];
    G.kick_gen.fetch_add(1, std::memory_order_relaxed);
    G.cv.notify_all();
  }
}

// Stop the io loop and unblock any rt_recv caller WITHOUT deleting the
// transport. Used when the Python reader thread might still be inside
// rt_recv: stop first, join the reader, then rt_close. Safe to call more
// than once; rt_close after rt_stop is the normal teardown.
void rt_stop(void* h) {
  auto* t = static_cast<Transport*>(h);
  t->stopping.store(true);
  {
    rabia::MutexLock lk(t->mu);
    t->inbox_cv.notify_all();
  }
  const int32_t ng = t->ngroups.load(std::memory_order_acquire);
  for (int32_t g = 0; g < ng; g++) t->groups[(size_t)g]->cv.notify_all();
  uint64_t one = 1;
  (void)!::write(t->wake_fd, &one, 8);
}

// Total inbound frames dropped due to the bounded inbox (oldest-first).
uint64_t rt_dropped(void* h) {
  auto* t = static_cast<Transport*>(h);
  rabia::MutexLock lk(t->mu);
  return t->dropped_frames;
}

void rt_close(void* h) {
  auto* t = static_cast<Transport*>(h);
  t->stopping.store(true);
  {
    rabia::MutexLock lk(t->mu);
    t->inbox_cv.notify_all();
  }
  {
    const int32_t ng = t->ngroups.load(std::memory_order_acquire);
    for (int32_t g = 0; g < ng; g++) t->groups[(size_t)g]->cv.notify_all();
  }
  uint64_t one = 1;
  (void)!::write(t->wake_fd, &one, 8);
  if (t->io_thread.joinable()) t->io_thread.join();
  {
    // the lock_guard must release BEFORE delete: unlocking a destroyed
    // mutex is use-after-free (found by the TSan stress harness)
    rabia::MutexLock lk(t->mu);
    for (auto& [fd, c] : t->conns) ::close(fd);
    t->conns.clear();
    ::close(t->listen_fd);
    ::close(t->epoll_fd);
    ::close(t->wake_fd);
  }
  delete t;
}

}  // extern "C"
