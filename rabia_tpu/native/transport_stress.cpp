// ThreadSanitizer stress harness for the native transport (SURVEY §5.2:
// the reference configures no race detection; we gate the C++ data plane
// with TSan here). Build and run via tests/test_tcp.py::TestTsanStress:
//
//   g++ -O1 -g -std=c++17 -fsanitize=thread -pthread \
//       transport_stress.cpp transport_tsan_glue.cpp -o stress && ./stress
//
// The harness links transport.cpp directly (no dlopen) so TSan sees every
// thread: two transports handshake over loopback, then four threads hammer
// send/broadcast/recv/stats/add-remove-peer concurrently while a fifth
// tears one side down mid-traffic.

#include <atomic>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {
void* rt_create(const unsigned char* self_id, const char* host,
                unsigned short port, unsigned short* actual_port);
int rt_add_peer(void* h, const unsigned char* id, const char* host,
                unsigned short port);
int rt_remove_peer(void* h, const unsigned char* id);
int rt_send(void* h, const unsigned char* id, const char* data,
            unsigned int len);
int rt_broadcast(void* h, const char* data, unsigned int len);
int rt_recv(void* h, unsigned char sender_out[16], unsigned char* buf,
            unsigned int buf_cap, int timeout_ms);
int rt_connected(void* h, unsigned char* ids_out, int cap);
unsigned short rt_port(void* h);
unsigned long long rt_dropped(void* h);
void rt_pool_stats(void* h, unsigned long long* hits,
                   unsigned long long* misses);
void rt_stop(void* h);
void rt_close(void* h);
}

int main() {
  unsigned char id_a[16] = {1};
  unsigned char id_b[16] = {2};
  unsigned short pa = 0, pb = 0;
  void* a = rt_create(id_a, "127.0.0.1", 0, &pa);
  void* b = rt_create(id_b, "127.0.0.1", 0, &pb);
  if (!a || !b) {
    std::fprintf(stderr, "create failed\n");
    return 1;
  }
  rt_add_peer(a, id_b, "127.0.0.1", pb);
  rt_add_peer(b, id_a, "127.0.0.1", pa);

  // wait for the handshake
  for (int i = 0; i < 200; i++) {
    unsigned char ids[16 * 4];
    if (rt_connected(a, ids, 4) >= 1 && rt_connected(b, ids, 4) >= 1) break;
    std::this_thread::sleep_for(std::chrono::milliseconds(10));
  }

  std::atomic<bool> stop{false};
  std::atomic<long> received{0};

  std::thread sender_a([&] {
    char msg[512];
    std::memset(msg, 0x5A, sizeof(msg));
    while (!stop.load()) {
      rt_send(a, id_b, msg, sizeof(msg));
      rt_broadcast(a, msg, 64);
    }
  });
  std::thread sender_b([&] {
    char msg[2048];
    std::memset(msg, 0xA5, sizeof(msg));
    while (!stop.load()) rt_broadcast(b, msg, sizeof(msg));
  });
  std::thread receiver_a([&] {
    unsigned char sender[16];
    std::vector<unsigned char> buf(1 << 16);
    while (!stop.load()) {
      int n = rt_recv(a, sender, buf.data(), buf.size(), 20);
      if (n >= 0) received.fetch_add(1);
    }
  });
  std::thread receiver_b([&] {
    unsigned char sender[16];
    std::vector<unsigned char> buf(1 << 16);
    while (!stop.load()) {
      int n = rt_recv(b, sender, buf.data(), buf.size(), 20);
      if (n >= 0) received.fetch_add(1);
    }
  });
  std::thread meddler([&] {
    unsigned char ids[16 * 8];
    while (!stop.load()) {
      rt_connected(a, ids, 8);
      unsigned long long h = 0, m = 0;
      rt_pool_stats(b, &h, &m);
      rt_dropped(a);
      std::this_thread::sleep_for(std::chrono::milliseconds(5));
    }
  });

  std::this_thread::sleep_for(std::chrono::seconds(2));
  // tear one side down mid-traffic (close-under-load path)
  rt_stop(b);
  std::this_thread::sleep_for(std::chrono::milliseconds(100));
  stop.store(true);
  sender_a.join();
  sender_b.join();
  receiver_a.join();
  receiver_b.join();
  meddler.join();
  rt_close(b);
  rt_stop(a);
  rt_close(a);
  std::printf("stress ok: %ld frames received\n", received.load());
  return received.load() > 0 ? 0 : 2;
}
