// ThreadSanitizer stress harness for the native transport (SURVEY §5.2:
// the reference configures no race detection; we gate the C++ data plane
// with TSan here). Build and run via tests/test_tcp.py::TestTsanStress:
//
//   g++ -O1 -g -std=c++17 -fsanitize=thread -pthread \
//       transport.cpp transport_stress.cpp -o stress && ./stress
//
// The harness links transport.cpp directly (no dlopen) so TSan sees every
// thread: two transports handshake over loopback, then four threads hammer
// send/broadcast/recv/stats concurrently while the main thread tears one
// side down mid-traffic.

#include <atomic>
#include <chrono>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

#include "transport.h"

int main() {
  unsigned char id_a[16] = {1};
  unsigned char id_b[16] = {2};
  unsigned short pa = 0, pb = 0;
  void* a = rt_create(id_a, "127.0.0.1", 0, &pa);
  void* b = rt_create(id_b, "127.0.0.1", 0, &pb);
  if (!a || !b) {
    std::fprintf(stderr, "create failed\n");
    return 1;
  }
  rt_add_peer(a, id_b, "127.0.0.1", pb);
  rt_add_peer(b, id_a, "127.0.0.1", pa);

  // wait for the handshake
  for (int i = 0; i < 200; i++) {
    unsigned char ids[16 * 4];
    if (rt_connected(a, ids, 4) >= 1 && rt_connected(b, ids, 4) >= 1) break;
    std::this_thread::sleep_for(std::chrono::milliseconds(10));
  }

  std::atomic<bool> stop{false};
  std::atomic<long> received{0};

  std::thread sender_a([&] {
    uint8_t msg[512];
    std::memset(msg, 0x5A, sizeof(msg));
    while (!stop.load()) {
      rt_send(a, id_b, msg, sizeof(msg));
      rt_broadcast(a, msg, 64);
    }
  });
  std::thread sender_b([&] {
    uint8_t msg[2048];
    std::memset(msg, 0xA5, sizeof(msg));
    while (!stop.load()) rt_broadcast(b, msg, sizeof(msg));
  });
  std::thread receiver_a([&] {
    uint8_t sender[16];
    std::vector<uint8_t> buf(1 << 16);
    while (!stop.load()) {
      int n = rt_recv(a, sender, buf.data(), buf.size(), 20);
      if (n >= 0) received.fetch_add(1);
    }
  });
  std::thread receiver_b([&] {
    uint8_t sender[16];
    std::vector<uint8_t> buf(1 << 16);
    while (!stop.load()) {
      int n = rt_recv(b, sender, buf.data(), buf.size(), 20);
      if (n >= 0) received.fetch_add(1);
    }
  });
  std::thread meddler([&] {
    uint8_t ids[16 * 8];
    while (!stop.load()) {
      rt_connected(a, ids, 8);
      uint64_t h = 0, m = 0;
      rt_pool_stats(b, &h, &m);
      rt_dropped(a);
      std::this_thread::sleep_for(std::chrono::milliseconds(5));
    }
  });

  std::this_thread::sleep_for(std::chrono::seconds(2));
  // tear one side down mid-traffic (close-under-load path)
  rt_stop(b);
  std::this_thread::sleep_for(std::chrono::milliseconds(100));
  stop.store(true);
  sender_a.join();
  sender_b.join();
  receiver_a.join();
  receiver_b.join();
  meddler.join();
  rt_close(b);
  rt_stop(a);
  rt_close(a);
  std::printf("stress ok: %ld frames received\n", received.load());
  return received.load() > 0 ? 0 : 2;
}
