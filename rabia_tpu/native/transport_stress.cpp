// ThreadSanitizer stress harness for the native transport (SURVEY §5.2:
// the reference configures no race detection; we gate the C++ data plane
// with TSan here). Build and run via tests/test_tcp.py::TestTsanStress:
//
//   g++ -O1 -g -std=c++17 -fsanitize=thread -pthread \
//       transport.cpp transport_stress.cpp -o stress && ./stress
//
// The harness links transport.cpp directly (no dlopen) so TSan sees every
// thread: two transports handshake over loopback, then four threads hammer
// send/broadcast/recv/stats concurrently while the main thread tears one
// side down mid-traffic.

#include <atomic>
#include <chrono>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

#include "transport.h"

int main() {
  unsigned char id_a[16] = {1};
  unsigned char id_b[16] = {2};
  unsigned short pa = 0, pb = 0;
  void* a = rt_create(id_a, "127.0.0.1", 0, &pa);
  void* b = rt_create(id_b, "127.0.0.1", 0, &pb);
  if (!a || !b) {
    std::fprintf(stderr, "create failed\n");
    return 1;
  }
  rt_add_peer(a, id_b, "127.0.0.1", pb);
  rt_add_peer(b, id_a, "127.0.0.1", pa);

  // wait for the handshake
  for (int i = 0; i < 200; i++) {
    unsigned char ids[16 * 4];
    if (rt_connected(a, ids, 4) >= 1 && rt_connected(b, ids, 4) >= 1) break;
    std::this_thread::sleep_for(std::chrono::milliseconds(10));
  }

  std::atomic<bool> stop{false};
  std::atomic<long> received{0};

  std::thread sender_a([&] {
    uint8_t msg[512];
    std::memset(msg, 0x5A, sizeof(msg));
    // a batch of 3 length-prefixed frames, as the native tick's rk_tick
    // emits them (rt_broadcast_frames staging path)
    uint8_t batch[3 * (4 + 96)];
    for (int f = 0; f < 3; f++) {
      uint8_t* rec = batch + f * (4 + 96);
      uint32_t len = 96;
      std::memcpy(rec, &len, 4);
      std::memset(rec + 4, 0x30 + f, 96);
    }
    while (!stop.load()) {
      rt_send(a, id_b, msg, sizeof(msg));
      rt_broadcast(a, msg, 64);
      rt_broadcast_frames(a, batch, sizeof(batch));
    }
  });
  std::thread sender_b([&] {
    uint8_t msg[2048];
    std::memset(msg, 0xA5, sizeof(msg));
    while (!stop.load()) rt_broadcast(b, msg, sizeof(msg));
  });
  std::thread receiver_a([&] {
    // zero-copy drain: borrow straight from the frame arena, touch the
    // bytes (TSan-visible read of io-thread-written memory), release
    uint8_t sender[16];
    const uint8_t* ptr = nullptr;
    uint32_t len = 0;
    volatile uint8_t sink = 0;
    while (!stop.load()) {
      int64_t tok = rt_recv_borrow(a, sender, &ptr, &len, 20);
      if (tok >= 0) {
        if (len > 0) sink ^= ptr[len - 1];
        rt_recv_release(a, tok);
        received.fetch_add(1);
      } else if (tok == -1) {
        break;  // closing
      }
    }
    (void)sink;
  });
  std::thread receiver_b([&] {
    uint8_t sender[16];
    std::vector<uint8_t> buf(1 << 16);
    while (!stop.load()) {
      int n = rt_recv(b, sender, buf.data(), buf.size(), 20);
      if (n >= 0) received.fetch_add(1);
    }
  });
  std::thread meddler([&] {
    uint8_t ids[16 * 8];
    int cycles = 0;
    while (!stop.load()) {
      rt_connected(a, ids, 8);
      uint64_t h = 0, m = 0;
      rt_pool_stats(b, &h, &m);
      rt_dropped(a);
      if (++cycles % 40 == 0) {
        // concurrent redial churn under load: drop and re-add the peer
        // while senders stage into the out pool and the borrow drain
        // holds arena frames (the arena-decode/out_pool interplay the
        // native tick leans on)
        rt_remove_peer(a, id_b);
        std::this_thread::sleep_for(std::chrono::milliseconds(10));
        rt_add_peer(a, id_b, "127.0.0.1", pb);
      }
      std::this_thread::sleep_for(std::chrono::milliseconds(5));
    }
  });

  std::this_thread::sleep_for(std::chrono::seconds(2));
  // tear one side down mid-traffic (close-under-load path)
  rt_stop(b);
  std::this_thread::sleep_for(std::chrono::milliseconds(100));
  stop.store(true);
  sender_a.join();
  sender_b.join();
  receiver_a.join();
  receiver_b.join();
  meddler.join();
  rt_close(b);
  rt_stop(a);
  rt_close(a);
  std::printf("stress ok: %ld frames received\n", received.load());
  return received.load() > 0 ? 0 : 2;
}
