// Native binary codec for ProtocolMessage hot frames.
//
// SURVEY §2 C9 / §7.2 step 5 assign the binary serializer to the C++
// host library (reference: rabia-core/src/serialization.rs:22-63 bincode
// codec, :152-169 pooled zero-alloc path). This extension implements the
// SAME wire format as rabia_tpu/core/serialization.py (version 3,
// hand-rolled little-endian) for the latency-critical frame types —
// VoteRound1/VoteRound2 (packed vote vectors), Decision, Propose and
// NewBatch (command batches), ProposeBlock, HeartBeat, SyncRequest,
// SyncResponse (the recovery/snapshot frame, incl. its zlib-level-1 body
// compression), and the client gateway frames (ClientHello, Submit,
// Result, ReadIndex — rabia_tpu/gateway) — and returns None for
// everything else so the Python codec remains the semantics owner and
// fallback. Byte-for-byte compatibility is pinned by
// tests/test_native_codec.py.
//
// Built as a CPython extension (not ctypes): the cost of the Python
// codec is object construction and bytecode, not byte shuffling, so the
// win comes from building ProtocolMessage/vote-vector objects directly
// against the C API (tp_new + slot writes instead of Python __init__).

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#define NPY_NO_DEPRECATED_API NPY_1_7_API_VERSION
#define PY_ARRAY_UNIQUE_SYMBOL rabia_codec_ARRAY_API
#include <numpy/arrayobject.h>

#include <zlib.h>

#include <cstdint>
#include <cstring>

extern "C" {
// CPython private-but-exported 128-bit int helpers (uuid.py's own
// int.from_bytes path, minus the bytecode). Present throughout 3.x.
PyObject* _PyLong_FromByteArray(const unsigned char* bytes, size_t n,
                                int little_endian, int is_signed);
int _PyLong_AsByteArray(PyLongObject* v, unsigned char* bytes, size_t n,
                        int little_endian, int is_signed);
}

namespace {

constexpr uint8_t WIRE_VERSION = 3;
constexpr uint8_t FLAG_COMPRESSED = 0x01;
constexpr uint8_t FLAG_HAS_RECIPIENT = 0x02;

// MessageType codes (core/messages.py MessageType — order stable)
constexpr uint8_t MT_PROPOSE = 1;
constexpr uint8_t MT_VOTE1 = 2;
constexpr uint8_t MT_VOTE2 = 3;
constexpr uint8_t MT_DECISION = 4;
constexpr uint8_t MT_SYNCREQ = 5;
constexpr uint8_t MT_SYNCRESP = 6;
constexpr uint8_t MT_NEWBATCH = 7;
constexpr uint8_t MT_HEARTBEAT = 8;
constexpr uint8_t MT_PROPOSE_BLOCK = 10;
constexpr uint8_t MT_CLIENT_HELLO = 11;
constexpr uint8_t MT_SUBMIT = 12;
constexpr uint8_t MT_RESULT = 13;
constexpr uint8_t MT_READ_INDEX = 14;

// Python classes / helpers bound once via bind()
PyObject* g_ProtocolMessage = nullptr;
PyObject* g_VoteRound1 = nullptr;
PyObject* g_VoteRound2 = nullptr;
PyObject* g_Decision = nullptr;
PyObject* g_HeartBeat = nullptr;
PyObject* g_SyncRequest = nullptr;
PyObject* g_SyncResponse = nullptr;
PyObject* g_ClientHello = nullptr;
PyObject* g_Submit = nullptr;
PyObject* g_Result = nullptr;
PyObject* g_ReadIndex = nullptr;
PyObject* g_ProposeBlock = nullptr;
PyObject* g_PayloadBlock = nullptr;
PyObject* g_NodeId = nullptr;
PyObject* g_BatchId = nullptr;
PyObject* g_Propose = nullptr;
PyObject* g_NewBatch = nullptr;
PyObject* g_CommandBatch = nullptr;
PyObject* g_Command = nullptr;
PyObject* g_ShardId = nullptr;
PyObject* g_StateValue = nullptr;
PyObject* g_UUID = nullptr;
PyObject* g_safe_unknown = nullptr;  // uuid.SafeUUID.unknown
PyObject* g_SerializationError = nullptr;
PyObject* g_crc32 = nullptr;  // zlib.crc32
PyObject* g_node_intern = nullptr;  // dict: 16-raw-bytes -> NodeId
PyObject* g_empty_tuple = nullptr;

// interned attribute names
PyObject* s_payload; PyObject* s_id; PyObject* s_sender; PyObject* s_recipient;
PyObject* s_timestamp; PyObject* s_value; PyObject* s_int; PyObject* s_is_safe;
PyObject* s_shards; PyObject* s_phases; PyObject* s_vals; PyObject* s_bids;
PyObject* s_current_phase; PyObject* s_committed_phase; PyObject* s_state_version;
PyObject* s_block; PyObject* s_slots; PyObject* s_counts; PyObject* s_cmd_sizes;
PyObject* s_data; PyObject* s_total_commands;
PyObject* s_shard; PyObject* s_phase; PyObject* s_batch_id; PyObject* s_batch;
PyObject* s_commands;
PyObject* s_responder_phase; PyObject* s_snapshot; PyObject* s_per_shard_phase;
PyObject* s_applied_ids; PyObject* s_per_shard_version;
PyObject* s_client_id; PyObject* s_seq; PyObject* s_ack; PyObject* s_last_seq;
PyObject* s_max_inflight; PyObject* s_ack_upto; PyObject* s_status;
PyObject* s_mode; PyObject* s_key; PyObject* s_frontier;

inline void wr_u32(uint8_t* p, uint32_t v) { memcpy(p, &v, 4); }
inline void wr_u64(uint8_t* p, uint64_t v) { memcpy(p, &v, 8); }
inline uint32_t rd_u32(const uint8_t* p) { uint32_t v; memcpy(&v, p, 4); return v; }
inline uint64_t rd_u64(const uint8_t* p) { uint64_t v; memcpy(&v, p, 8); return v; }

struct Buf {
  uint8_t stack[8192];
  uint8_t* p = stack;
  size_t cap = sizeof(stack);
  size_t len = 0;
  ~Buf() { if (p != stack) PyMem_Free(p); }
  uint8_t* reserve(size_t n) {
    if (len + n > cap) {
      size_t ncap = cap * 2;
      while (ncap < len + n) ncap *= 2;
      uint8_t* np = (uint8_t*)PyMem_Malloc(ncap);
      if (!np) return nullptr;
      memcpy(np, p, len);
      if (p != stack) PyMem_Free(p);
      p = np; cap = ncap;
    }
    uint8_t* out = p + len;
    len += n;
    return out;
  }
  bool put_u8(uint8_t v) { uint8_t* q = reserve(1); if (!q) return false; *q = v; return true; }
  bool put_u32(uint32_t v) { uint8_t* q = reserve(4); if (!q) return false; wr_u32(q, v); return true; }
  bool put_u64(uint64_t v) { uint8_t* q = reserve(8); if (!q) return false; wr_u64(q, v); return true; }
  bool put_raw(const void* src, size_t n) {
    uint8_t* q = reserve(n); if (!q) return false; memcpy(q, src, n); return true;
  }
};

struct Rd {
  const uint8_t* p;
  size_t len;
  size_t pos = 0;
  const uint8_t* take(size_t n) {
    if (pos + n > len) {
      PyErr_Format(g_SerializationError,
                   "truncated message: need %zu bytes at offset %zu, have %zu",
                   n, pos, len - pos);
      return nullptr;
    }
    const uint8_t* out = p + pos;
    pos += n;
    return out;
  }
};

// --- object construction helpers -----------------------------------------

// allocate an instance without running __init__ (object.__new__ path)
PyObject* raw_new(PyObject* cls) {
  PyTypeObject* t = (PyTypeObject*)cls;
  return t->tp_new(t, g_empty_tuple, nullptr);
}

// set an attribute bypassing the class's __setattr__ (works for both
// __slots__ descriptors and instance dicts; same mechanism as
// object.__setattr__, which frozen dataclasses / uuid.UUID themselves use)
int raw_set(PyObject* obj, PyObject* name, PyObject* val) {
  return PyObject_GenericSetAttr(obj, name, val);
}

// uuid.UUID from 16 big-endian bytes, skipping UUID.__init__ validation
PyObject* make_uuid(const uint8_t* raw) {
  PyObject* big = _PyLong_FromByteArray(raw, 16, /*little=*/0, /*signed=*/0);
  if (!big) return nullptr;
  PyObject* u = raw_new(g_UUID);
  if (!u) { Py_DECREF(big); return nullptr; }
  if (raw_set(u, s_int, big) < 0 ||
      raw_set(u, s_is_safe, g_safe_unknown) < 0) {
    Py_DECREF(big); Py_DECREF(u); return nullptr;
  }
  Py_DECREF(big);
  return u;
}

// 16 wire bytes of a uuid.UUID (big-endian of its .int)
bool uuid_bytes(PyObject* u, uint8_t* out) {
  PyObject* big = PyObject_GetAttr(u, s_int);
  if (!big) return false;
  // UUID(int=...) stores whatever integer-like it was given (e.g. a
  // numpy int64); coerce to an exact PyLong before the byte export
  PyObject* exact = PyNumber_Index(big);
  Py_DECREF(big);
  if (!exact) return false;
  int rc = _PyLong_AsByteArray((PyLongObject*)exact, out, 16, /*little=*/0,
                               /*signed=*/0);
  Py_DECREF(exact);
  return rc == 0;
}

// interned NodeId from 16 raw bytes
PyObject* intern_node(const uint8_t* raw) {
  PyObject* key = PyBytes_FromStringAndSize((const char*)raw, 16);
  if (!key) return nullptr;
  PyObject* hit = PyDict_GetItemWithError(g_node_intern, key);
  if (hit) {
    Py_INCREF(hit);
    Py_DECREF(key);
    return hit;
  }
  if (PyErr_Occurred()) { Py_DECREF(key); return nullptr; }
  if (PyDict_Size(g_node_intern) > 4096) PyDict_Clear(g_node_intern);
  PyObject* u = make_uuid(raw);
  if (!u) { Py_DECREF(key); return nullptr; }
  PyObject* node = raw_new(g_NodeId);
  if (!node || raw_set(node, s_value, u) < 0) {
    Py_XDECREF(node); Py_DECREF(u); Py_DECREF(key); return nullptr;
  }
  Py_DECREF(u);
  if (PyDict_SetItem(g_node_intern, key, node) < 0) {
    Py_DECREF(node); Py_DECREF(key); return nullptr;
  }
  Py_DECREF(key);
  return node;
}

// contiguous int64 view of a numpy attr (no copy when already i64)
PyArrayObject* as_i64(PyObject* owner, PyObject* name) {
  PyObject* a = PyObject_GetAttr(owner, name);
  if (!a) return nullptr;
  PyArrayObject* arr = (PyArrayObject*)PyArray_FROM_OTF(
      a, NPY_INT64, NPY_ARRAY_C_CONTIGUOUS | NPY_ARRAY_ALIGNED);
  Py_DECREF(a);
  return arr;
}

PyArrayObject* as_i8(PyObject* owner, PyObject* name) {
  PyObject* a = PyObject_GetAttr(owner, name);
  if (!a) return nullptr;
  PyArrayObject* arr = (PyArrayObject*)PyArray_FROM_OTF(
      a, NPY_INT8, NPY_ARRAY_C_CONTIGUOUS | NPY_ARRAY_ALIGNED);
  Py_DECREF(a);
  return arr;
}

// --- payload encoders -----------------------------------------------------

// vote vector body: u32 n + n * (u32 shard, u64 phase, u8 vote)
bool encode_votes(Buf& b, PyObject* payload) {
  PyArrayObject* sh = as_i64(payload, s_shards);
  PyArrayObject* ph = as_i64(payload, s_phases);
  PyArrayObject* vv = as_i8(payload, s_vals);
  if (!sh || !ph || !vv) {
    Py_XDECREF(sh); Py_XDECREF(ph); Py_XDECREF(vv);
    return false;
  }
  npy_intp n = PyArray_DIM(sh, 0);
  const int64_t* ps = (const int64_t*)PyArray_DATA(sh);
  const int64_t* pp = (const int64_t*)PyArray_DATA(ph);
  const int8_t* pv = (const int8_t*)PyArray_DATA(vv);
  bool ok = b.put_u32((uint32_t)n);
  if (ok) {
    uint8_t* q = b.reserve((size_t)n * 13);
    ok = q != nullptr;
    if (ok) {
      for (npy_intp i = 0; i < n; i++) {
        wr_u32(q, (uint32_t)ps[i]);
        wr_u64(q + 4, (uint64_t)pp[i]);
        q[12] = (uint8_t)pv[i];
        q += 13;
      }
    }
  }
  Py_DECREF(sh); Py_DECREF(ph); Py_DECREF(vv);
  return ok;
}

// Decision body: u32 n + n * (u32, u64, u8 decision, u8 has_bid) +
// trailing 16B batch ids for has_bid entries in order
bool encode_decision(Buf& b, PyObject* payload) {
  PyArrayObject* sh = as_i64(payload, s_shards);
  PyArrayObject* ph = as_i64(payload, s_phases);
  PyArrayObject* vv = as_i8(payload, s_vals);
  PyObject* bids = PyObject_GetAttr(payload, s_bids);
  if (!sh || !ph || !vv || !bids) {
    Py_XDECREF(sh); Py_XDECREF(ph); Py_XDECREF(vv); Py_XDECREF(bids);
    return false;
  }
  npy_intp n = PyArray_DIM(sh, 0);
  const int64_t* ps = (const int64_t*)PyArray_DATA(sh);
  const int64_t* pp = (const int64_t*)PyArray_DATA(ph);
  const int8_t* pv = (const int8_t*)PyArray_DATA(vv);
  bool has_bids = bids != Py_None;
  bool ok = b.put_u32((uint32_t)n);
  uint8_t* q = ok ? b.reserve((size_t)n * 14) : nullptr;
  ok = q != nullptr;
  if (ok) {
    for (npy_intp i = 0; i < n; i++) {
      wr_u32(q, (uint32_t)ps[i]);
      wr_u64(q + 4, (uint64_t)pp[i]);
      q[12] = (uint8_t)pv[i];
      uint8_t hb = 0;
      if (has_bids) {
        PyObject* bid = PyList_GET_ITEM(bids, i);  // borrowed
        hb = (bid != Py_None) ? 1 : 0;
      }
      q[13] = hb;
      q += 14;
    }
    if (has_bids) {
      for (npy_intp i = 0; ok && i < n; i++) {
        PyObject* bid = PyList_GET_ITEM(bids, i);
        if (bid == Py_None) continue;
        PyObject* val = PyObject_GetAttr(bid, s_value);
        uint8_t raw[16];
        ok = val && uuid_bytes(val, raw) && b.put_raw(raw, 16);
        Py_XDECREF(val);
      }
    }
  }
  Py_DECREF(sh); Py_DECREF(ph); Py_DECREF(vv); Py_DECREF(bids);
  return ok;
}

bool put_u64_attr(Buf& b, PyObject* payload, PyObject* name) {
  PyObject* v = PyObject_GetAttr(payload, name);
  if (!v) return false;
  uint64_t x = PyLong_AsUnsignedLongLong(v);
  Py_DECREF(v);
  if (x == (uint64_t)-1 && PyErr_Occurred()) return false;
  return b.put_u64(x);
}

// zlib-compatible CRC-32 (IEEE 0xEDB88320), table built on first use —
// CommandBatch.checksum() chains crc32 over (id bytes, data) per command,
// which would cost one Python call per piece via g_crc32
uint32_t crc32_table[256];
bool crc32_ready = false;
uint32_t crc32_run(uint32_t crc, const uint8_t* buf, size_t n) {
  if (!crc32_ready) {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      crc32_table[i] = c;
    }
    crc32_ready = true;
  }
  crc = ~crc;
  for (size_t i = 0; i < n; i++)
    crc = crc32_table[(crc ^ buf[i]) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

uint32_t crc32_of(PyObject* data_bytes, bool* ok) {
  PyObject* r = PyObject_CallFunctionObjArgs(g_crc32, data_bytes, nullptr);
  if (!r) { *ok = false; return 0; }
  uint32_t v = (uint32_t)(PyLong_AsUnsignedLong(r) & 0xFFFFFFFFu);
  Py_DECREF(r);
  *ok = !PyErr_Occurred();
  return v;
}

// ProposeBlock body (serialization.py _encode_payload ProposeBlock branch)
bool encode_block(Buf& b, PyObject* payload) {
  PyObject* blk = PyObject_GetAttr(payload, s_block);
  if (!blk) return false;
  PyObject* bid = PyObject_GetAttr(blk, s_id);
  PyArrayObject* sh = as_i64(blk, s_shards);
  PyArrayObject* sl = as_i64(blk, s_slots);
  PyArrayObject* ct = as_i64(blk, s_counts);
  PyArrayObject* cs = as_i64(blk, s_cmd_sizes);
  PyObject* data = PyObject_GetAttr(blk, s_data);
  PyObject* tot = PyObject_GetAttr(blk, s_total_commands);
  bool ok = bid && sh && sl && ct && cs && data && tot &&
            PyBytes_Check(data);
  if (ok) {
    uint8_t raw[16];
    ok = uuid_bytes(bid, raw) && b.put_raw(raw, 16);
    npy_intp k = PyArray_DIM(sh, 0);
    ok = ok && b.put_u32((uint32_t)k);
    if (ok) {
      const int64_t* p = (const int64_t*)PyArray_DATA(sh);
      uint8_t* q = b.reserve((size_t)k * 4);
      ok = q != nullptr;
      for (npy_intp i = 0; ok && i < k; i++) wr_u32(q + 4 * i, (uint32_t)p[i]);
    }
    if (ok) {
      const int64_t* p = (const int64_t*)PyArray_DATA(sl);
      uint8_t* q = b.reserve((size_t)k * 8);
      ok = q != nullptr;
      for (npy_intp i = 0; ok && i < k; i++) wr_u64(q + 8 * i, (uint64_t)p[i]);
    }
    if (ok) {
      const int64_t* p = (const int64_t*)PyArray_DATA(ct);
      uint8_t* q = b.reserve((size_t)k * 4);
      ok = q != nullptr;
      for (npy_intp i = 0; ok && i < k; i++) wr_u32(q + 4 * i, (uint32_t)p[i]);
    }
    long total = ok ? PyLong_AsLong(tot) : 0;
    ok = ok && !PyErr_Occurred() && b.put_u32((uint32_t)total);
    if (ok) {
      npy_intp nsz = PyArray_DIM(cs, 0);
      const int64_t* p = (const int64_t*)PyArray_DATA(cs);
      uint8_t* q = b.reserve((size_t)nsz * 4);
      ok = q != nullptr;
      for (npy_intp i = 0; ok && i < nsz; i++) wr_u32(q + 4 * i, (uint32_t)p[i]);
    }
    if (ok) {
      Py_ssize_t dn = PyBytes_GET_SIZE(data);
      ok = b.put_u32((uint32_t)dn) &&
           b.put_raw(PyBytes_AS_STRING(data), (size_t)dn);
    }
    if (ok) {
      uint32_t crc = crc32_of(data, &ok);
      ok = ok && b.put_u32(crc);
    }
  }
  Py_XDECREF(bid); Py_XDECREF(sh); Py_XDECREF(sl); Py_XDECREF(ct);
  Py_XDECREF(cs); Py_XDECREF(data); Py_XDECREF(tot); Py_DECREF(blk);
  return ok;
}

bool u64_attr_val(PyObject* obj, PyObject* name, uint64_t* out);

// SyncResponse body (serialization.py _encode_payload SyncResponse
// branch): u64 responder_phase, u64 state_version, u8 has_snapshot
// [+ u32 len + bytes], u32 n + n*u64 per_shard_phase, u32 n + n*(u32
// shard, 16B batch uuid) applied_ids, u32 n + n*u64 per_shard_version.
// The recovery frame of rabia-core/src/serialization.rs:22-63 (uniform
// codec over every message type incl. snapshots). Any shape surprise
// sets *decline (Python codec owns the frame; its error surfaces
// unchanged) rather than raising here.
bool syncresp_u64_seq(Buf& b, PyObject* payload, PyObject* name,
                      bool* decline) {
  PyObject* seq = PyObject_GetAttr(payload, name);
  if (!seq) { PyErr_Clear(); *decline = true; return false; }
  PyObject* fast = PySequence_Fast(seq, "");
  Py_DECREF(seq);
  if (!fast) { PyErr_Clear(); *decline = true; return false; }
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  bool ok = b.put_u32((uint32_t)n);
  for (Py_ssize_t i = 0; ok && i < n; i++) {
    PyObject* v = PySequence_Fast_GET_ITEM(fast, i);
    PyObject* ix = PyNumber_Index(v);
    if (!ix) { PyErr_Clear(); *decline = true; ok = false; break; }
    uint64_t x = PyLong_AsUnsignedLongLong(ix);
    Py_DECREF(ix);
    if (x == (uint64_t)-1 && PyErr_Occurred()) {
      PyErr_Clear(); *decline = true; ok = false; break;
    }
    ok = b.put_u64(x);
  }
  Py_DECREF(fast);
  return ok;
}

bool encode_syncresp(Buf& b, PyObject* payload, bool* decline) {
  uint64_t rp, sv;
  if (!u64_attr_val(payload, s_responder_phase, &rp) ||
      !u64_attr_val(payload, s_state_version, &sv)) {
    PyErr_Clear(); *decline = true; return false;
  }
  if (!b.put_u64(rp) || !b.put_u64(sv)) return false;
  PyObject* snap = PyObject_GetAttr(payload, s_snapshot);
  if (!snap) { PyErr_Clear(); *decline = true; return false; }
  bool ok;
  if (snap == Py_None) {
    ok = b.put_u8(0);
  } else if (PyBytes_Check(snap)) {
    Py_ssize_t n = PyBytes_GET_SIZE(snap);
    if ((uint64_t)n > 0xFFFFFFFFull) {
      // a >4GiB snapshot overflows the u32 length prefix: the Python
      // writer raises there — decline so it does, never truncate
      Py_DECREF(snap);
      *decline = true;
      return false;
    }
    ok = b.put_u8(1) && b.put_u32((uint32_t)n) &&
         b.put_raw(PyBytes_AS_STRING(snap), (size_t)n);
  } else {
    *decline = true; ok = false;  // bytearray/memoryview: Python path
  }
  Py_DECREF(snap);
  if (!ok) return false;
  if (!syncresp_u64_seq(b, payload, s_per_shard_phase, decline))
    return false;
  PyObject* ids = PyObject_GetAttr(payload, s_applied_ids);
  if (!ids) { PyErr_Clear(); *decline = true; return false; }
  PyObject* fast = PySequence_Fast(ids, "");
  Py_DECREF(ids);
  if (!fast) { PyErr_Clear(); *decline = true; return false; }
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  ok = b.put_u32((uint32_t)n);
  for (Py_ssize_t i = 0; ok && i < n; i++) {
    PyObject* pair = PySequence_Fast_GET_ITEM(fast, i);
    PyObject* pf = PySequence_Fast(pair, "");
    if (!pf || PySequence_Fast_GET_SIZE(pf) != 2) {
      Py_XDECREF(pf); PyErr_Clear(); *decline = true; ok = false; break;
    }
    PyObject* ix = PyNumber_Index(PySequence_Fast_GET_ITEM(pf, 0));
    uint64_t shard = ix ? PyLong_AsUnsignedLongLong(ix) : (uint64_t)-1;
    Py_XDECREF(ix);
    if (!ix || (shard == (uint64_t)-1 && PyErr_Occurred()) ||
        shard > 0xFFFFFFFFull) {
      Py_DECREF(pf); PyErr_Clear(); *decline = true; ok = false; break;
    }
    PyObject* bid = PySequence_Fast_GET_ITEM(pf, 1);
    PyObject* val = PyObject_GetAttr(bid, s_value);
    uint8_t raw[16];
    bool got = val && uuid_bytes(val, raw);
    Py_XDECREF(val);
    Py_DECREF(pf);
    if (!got) { PyErr_Clear(); *decline = true; ok = false; break; }
    ok = b.put_u32((uint32_t)shard) && b.put_raw(raw, 16);
  }
  Py_DECREF(fast);
  if (!ok) return false;
  return syncresp_u64_seq(b, payload, s_per_shard_version, decline);
}

// --- client gateway frame encoders (rabia_tpu/gateway) --------------------
// Same decline discipline as encode_syncresp: any shape surprise (non-
// bytes blob, out-of-range u32 field) routes the frame to the Python
// codec so its historical error surfaces unchanged.

// 16 wire bytes of a PLAIN uuid.UUID attribute (gateway client ids are
// bare UUIDs, not NodeId/BatchId wrappers)
bool put_uuid_attr(Buf& b, PyObject* payload, PyObject* name, bool* decline) {
  PyObject* u = PyObject_GetAttr(payload, name);
  if (!u) { PyErr_Clear(); *decline = true; return false; }
  uint8_t raw[16];
  bool got = uuid_bytes(u, raw);
  Py_DECREF(u);
  if (!got) { PyErr_Clear(); *decline = true; return false; }
  return b.put_raw(raw, 16);
}

// u32 count + count * (u32 len + bytes) from a tuple-of-bytes attribute
bool encode_blob_tuple(Buf& b, PyObject* payload, PyObject* name,
                       bool* decline) {
  PyObject* seq = PyObject_GetAttr(payload, name);
  if (!seq) { PyErr_Clear(); *decline = true; return false; }
  PyObject* fast = PySequence_Fast(seq, "");
  Py_DECREF(seq);
  if (!fast) { PyErr_Clear(); *decline = true; return false; }
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  bool ok = b.put_u32((uint32_t)n);
  for (Py_ssize_t i = 0; ok && i < n; i++) {
    PyObject* v = PySequence_Fast_GET_ITEM(fast, i);
    if (!PyBytes_Check(v)) { *decline = true; ok = false; break; }
    ok = b.put_u32((uint32_t)PyBytes_GET_SIZE(v)) &&
         b.put_raw(PyBytes_AS_STRING(v), (size_t)PyBytes_GET_SIZE(v));
  }
  Py_DECREF(fast);
  return ok;
}

// an int attribute bounded to a wire width; out-of-range declines
bool u64_attr_max(PyObject* obj, PyObject* name, uint64_t max, uint64_t* out,
                  bool* decline) {
  if (!u64_attr_val(obj, name, out) || *out > max) {
    PyErr_Clear(); *decline = true; return false;
  }
  return true;
}

// ClientHello body: u8 ack | 16B client uuid | u64 last_seq |
// u32 max_inflight
bool encode_client_hello(Buf& b, PyObject* payload, bool* decline) {
  PyObject* ack = PyObject_GetAttr(payload, s_ack);
  if (!ack) { PyErr_Clear(); *decline = true; return false; }
  int truth = PyObject_IsTrue(ack);
  Py_DECREF(ack);
  if (truth < 0) { PyErr_Clear(); *decline = true; return false; }
  if (!b.put_u8((uint8_t)truth)) return false;
  if (!put_uuid_attr(b, payload, s_client_id, decline)) return false;
  uint64_t ls, mi;
  if (!u64_attr_max(payload, s_last_seq, ~0ull, &ls, decline) ||
      !u64_attr_max(payload, s_max_inflight, 0xFFFFFFFFull, &mi, decline))
    return false;
  return b.put_u64(ls) && b.put_u32((uint32_t)mi);
}

// Submit body: 16B client uuid | u64 seq | u32 shard | u64 ack_upto |
// u32 n | n * blob
bool encode_submit(Buf& b, PyObject* payload, bool* decline) {
  if (!put_uuid_attr(b, payload, s_client_id, decline)) return false;
  uint64_t seq, shard, au;
  if (!u64_attr_max(payload, s_seq, ~0ull, &seq, decline) ||
      !u64_attr_max(payload, s_shard, 0xFFFFFFFFull, &shard, decline) ||
      !u64_attr_max(payload, s_ack_upto, ~0ull, &au, decline))
    return false;
  if (!b.put_u64(seq) || !b.put_u32((uint32_t)shard) || !b.put_u64(au))
    return false;
  return encode_blob_tuple(b, payload, s_commands, decline);
}

// Result body: 16B client uuid | u64 seq | u8 status | u32 n | n * blob
bool encode_result(Buf& b, PyObject* payload, bool* decline) {
  if (!put_uuid_attr(b, payload, s_client_id, decline)) return false;
  uint64_t seq, status;
  if (!u64_attr_max(payload, s_seq, ~0ull, &seq, decline) ||
      !u64_attr_max(payload, s_status, 0xFFull, &status, decline))
    return false;
  if (!b.put_u64(seq) || !b.put_u8((uint8_t)status)) return false;
  return encode_blob_tuple(b, payload, s_payload, decline);
}

// ReadIndex body: u8 mode | 16B client uuid | u64 seq | u32 shard |
// u32 klen + key | u32 k | k * u64 frontier
bool encode_read_index(Buf& b, PyObject* payload, bool* decline) {
  uint64_t mode, seq, shard;
  if (!u64_attr_max(payload, s_mode, 0xFFull, &mode, decline)) return false;
  if (!b.put_u8((uint8_t)mode)) return false;
  if (!put_uuid_attr(b, payload, s_client_id, decline)) return false;
  if (!u64_attr_max(payload, s_seq, ~0ull, &seq, decline) ||
      !u64_attr_max(payload, s_shard, 0xFFFFFFFFull, &shard, decline))
    return false;
  if (!b.put_u64(seq) || !b.put_u32((uint32_t)shard)) return false;
  PyObject* key = PyObject_GetAttr(payload, s_key);
  if (!key) { PyErr_Clear(); *decline = true; return false; }
  bool ok = PyBytes_Check(key);
  if (ok) {
    ok = b.put_u32((uint32_t)PyBytes_GET_SIZE(key)) &&
         b.put_raw(PyBytes_AS_STRING(key), (size_t)PyBytes_GET_SIZE(key));
  } else {
    *decline = true;  // bytearray/memoryview key: Python path
  }
  Py_DECREF(key);
  if (!ok) return false;
  return syncresp_u64_seq(b, payload, s_frontier, decline);
}

// u32/u64 from an int-like attribute (plain int, numpy integer, IntEnum).
// Deliberately NO ShardId-style .value unwrapping: the Python writer
// (struct.pack) rejects wrappers for payload-level fields, and the
// prescan (attr_fits with allow_wrapper=false) routes those frames to it
// so the historical error surfaces unchanged.
bool u64_attr_val(PyObject* obj, PyObject* name, uint64_t* out) {
  PyObject* v = PyObject_GetAttr(obj, name);
  if (!v) return false;
  PyObject* ix = PyNumber_Index(v);
  Py_DECREF(v);
  if (!ix) return false;
  *out = PyLong_AsUnsignedLongLong(ix);
  Py_DECREF(ix);
  return !(*out == (uint64_t)-1 && PyErr_Occurred());
}

// CommandBatch body (serialization.py _write_batch): uuid id, f64 ts,
// u32 shard, u32 checksum, u32 n, then per command uuid id + blob data.
// Caller has pre-validated every Command.data is bytes (see the prescan
// in codec_encode) so checksum and emission are single-pass C.
bool encode_batch(Buf& b, PyObject* batch) {
  PyObject* bid = PyObject_GetAttr(batch, s_id);
  PyObject* bval = bid ? PyObject_GetAttr(bid, s_value) : nullptr;
  Py_XDECREF(bid);
  if (!bval) return false;
  uint8_t raw[16];
  bool ok = uuid_bytes(bval, raw) && b.put_raw(raw, 16);
  Py_DECREF(bval);
  if (!ok) return false;
  PyObject* ts = PyObject_GetAttr(batch, s_timestamp);
  if (!ts) return false;
  double tsv = PyFloat_AsDouble(ts);
  Py_DECREF(ts);
  if (tsv == -1.0 && PyErr_Occurred()) return false;
  uint64_t bits;
  memcpy(&bits, &tsv, 8);
  if (!b.put_u64(bits)) return false;
  // CommandBatch.shard: a ShardId or a plain int — Python writes
  // int(batch.shard), which accepts both
  PyObject* sh = PyObject_GetAttr(batch, s_shard);
  if (!sh) return false;
  PyObject* ix = PyNumber_Index(sh);
  if (!ix) {
    PyErr_Clear();
    PyObject* shv = PyObject_GetAttr(sh, s_value);
    if (shv) {
      ix = PyNumber_Index(shv);
      Py_DECREF(shv);
    }
  }
  Py_DECREF(sh);
  if (!ix) return false;
  uint32_t shard = (uint32_t)PyLong_AsUnsignedLong(ix);
  Py_DECREF(ix);
  if (PyErr_Occurred() || !b.put_u32(shard)) return false;

  PyObject* cmds = PyObject_GetAttr(batch, s_commands);
  if (!cmds) return false;
  Py_ssize_t n = PyTuple_Check(cmds) ? PyTuple_GET_SIZE(cmds) : -1;
  if (n < 0) { Py_DECREF(cmds); return false; }
  // checksum pass (ids big-endian + data, chained)
  uint32_t crc = 0;
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject* c = PyTuple_GET_ITEM(cmds, i);  // borrowed
    PyObject* cid = PyObject_GetAttr(c, s_id);
    uint8_t craw[16];
    ok = cid && uuid_bytes(cid, craw);
    Py_XDECREF(cid);
    if (!ok) { Py_DECREF(cmds); return false; }
    crc = crc32_run(crc, craw, 16);
    PyObject* data = PyObject_GetAttr(c, s_data);
    if (!data || !PyBytes_Check(data)) {
      Py_XDECREF(data); Py_DECREF(cmds);
      if (!PyErr_Occurred())
        PyErr_SetString(g_SerializationError, "command data is not bytes");
      return false;
    }
    crc = crc32_run(crc, (const uint8_t*)PyBytes_AS_STRING(data),
                    (size_t)PyBytes_GET_SIZE(data));
    Py_DECREF(data);
  }
  ok = b.put_u32(crc) && b.put_u32((uint32_t)n);
  for (Py_ssize_t i = 0; ok && i < n; i++) {
    PyObject* c = PyTuple_GET_ITEM(cmds, i);
    PyObject* cid = PyObject_GetAttr(c, s_id);
    uint8_t craw[16];
    ok = cid && uuid_bytes(cid, craw) && b.put_raw(craw, 16);
    Py_XDECREF(cid);
    if (!ok) break;
    PyObject* data = PyObject_GetAttr(c, s_data);
    ok = data && PyBytes_Check(data) &&
         b.put_u32((uint32_t)PyBytes_GET_SIZE(data)) &&
         b.put_raw(PyBytes_AS_STRING(data),
                   (size_t)PyBytes_GET_SIZE(data));
    Py_XDECREF(data);
  }
  Py_DECREF(cmds);
  return ok;
}

// Propose body: u32 shard, u64 phase, uuid batch_id, u8 value,
// u8 has_batch [+ batch]
bool encode_propose(Buf& b, PyObject* payload) {
  uint64_t shard, phase;
  if (!u64_attr_val(payload, s_shard, &shard) ||
      !u64_attr_val(payload, s_phase, &phase))
    return false;
  if (!b.put_u32((uint32_t)shard) || !b.put_u64(phase)) return false;
  PyObject* bid = PyObject_GetAttr(payload, s_batch_id);
  PyObject* bval = bid ? PyObject_GetAttr(bid, s_value) : nullptr;
  Py_XDECREF(bid);
  if (!bval) return false;
  uint8_t raw[16];
  bool ok = uuid_bytes(bval, raw) && b.put_raw(raw, 16);
  Py_DECREF(bval);
  if (!ok) return false;
  PyObject* val = PyObject_GetAttr(payload, s_value);
  if (!val) return false;
  long code = PyLong_AsLong(val);
  Py_DECREF(val);
  if (code == -1 && PyErr_Occurred()) return false;
  if (!b.put_u8((uint8_t)code)) return false;
  PyObject* batch = PyObject_GetAttr(payload, s_batch);
  if (!batch) return false;
  if (batch == Py_None) {
    ok = b.put_u8(0);
  } else {
    ok = b.put_u8(1) && encode_batch(b, batch);
  }
  Py_DECREF(batch);
  return ok;
}

// NewBatch body: u32 shard + batch
bool encode_newbatch(Buf& b, PyObject* payload) {
  uint64_t shard;
  if (!u64_attr_val(payload, s_shard, &shard)) return false;
  if (!b.put_u32((uint32_t)shard)) return false;
  PyObject* batch = PyObject_GetAttr(payload, s_batch);
  if (!batch) return false;
  bool ok = encode_batch(b, batch);
  Py_DECREF(batch);
  return ok;
}

// A Propose/NewBatch payload is fast-pathable only when every command's
// data is exactly bytes (the Python writer accepts any buffer; rather
// than replicate that, odd inputs take the Python path). Returns the
// exact encoded batch body size, 0 for None, or -1 when not
// fast-pathable — the caller compares against the serializer's
// compression threshold, above which the Python codec owns the frame
// (it may compress; this codec never does, and byte parity is pinned).
// an int-like attr that must fit the given wire width; returns false
// (with the error cleared) when it does not — the Python codec then
// owns the frame and raises exactly as it always has. allow_wrapper
// additionally unwraps a .value carrier (ShardId): valid ONLY where the
// Python writer itself coerces via int() (CommandBatch.shard) — the
// struct.pack payload fields must stay strict or the native path would
// succeed where Python raises.
bool attr_fits(PyObject* obj, PyObject* name, uint64_t max,
               bool allow_wrapper) {
  PyObject* v = PyObject_GetAttr(obj, name);
  if (!v) { PyErr_Clear(); return false; }
  PyObject* ix = PyNumber_Index(v);
  if (!ix && allow_wrapper) {
    PyErr_Clear();
    PyObject* val = PyObject_GetAttr(v, s_value);
    Py_DECREF(v);
    if (!val) { PyErr_Clear(); return false; }
    ix = PyNumber_Index(val);
    Py_DECREF(val);
  } else {
    Py_DECREF(v);
  }
  if (!ix) { PyErr_Clear(); return false; }
  uint64_t x = PyLong_AsUnsignedLongLong(ix);
  Py_DECREF(ix);
  if (x == (uint64_t)-1 && PyErr_Occurred()) {
    PyErr_Clear();  // negative or > 2^64
    return false;
  }
  return x <= max;
}

Py_ssize_t batch_body_size(PyObject* batch) {
  if (batch == Py_None) return 0;
  if (Py_TYPE(batch) != (PyTypeObject*)g_CommandBatch) return -1;
  if (!attr_fits(batch, s_shard, 0xFFFFFFFFull, /*allow_wrapper=*/true))
    return -1;
  PyObject* cmds = PyObject_GetAttr(batch, s_commands);
  if (!cmds) { PyErr_Clear(); return -1; }
  Py_ssize_t size = 16 + 8 + 4 + 4 + 4;  // id, ts, shard, crc, count
  bool ok = PyTuple_Check(cmds);
  if (ok) {
    for (Py_ssize_t i = 0; i < PyTuple_GET_SIZE(cmds); i++) {
      PyObject* c = PyTuple_GET_ITEM(cmds, i);
      if (Py_TYPE(c) != (PyTypeObject*)g_Command) { ok = false; break; }
      PyObject* data = PyObject_GetAttr(c, s_data);
      if (!data) { PyErr_Clear(); ok = false; break; }
      bool is_bytes = PyBytes_Check(data);
      if (is_bytes) size += 16 + 4 + PyBytes_GET_SIZE(data);
      Py_DECREF(data);
      if (!is_bytes) { ok = false; break; }
    }
  }
  Py_DECREF(cmds);
  return ok ? size : -1;
}

// --- payload decoders -----------------------------------------------------

PyObject* make_i64_array(npy_intp n) {
  npy_intp dims[1] = {n};
  return PyArray_SimpleNew(1, dims, NPY_INT64);
}

PyObject* decode_votes(Rd& r, PyObject* cls) {
  const uint8_t* q = r.take(4);
  if (!q) return nullptr;
  uint32_t n = rd_u32(q);
  const uint8_t* body = r.take((size_t)n * 13);
  if (!body) return nullptr;
  PyObject* sh = make_i64_array(n);
  PyObject* ph = make_i64_array(n);
  npy_intp dims[1] = {(npy_intp)n};
  PyObject* vv = PyArray_SimpleNew(1, dims, NPY_INT8);
  if (!sh || !ph || !vv) { Py_XDECREF(sh); Py_XDECREF(ph); Py_XDECREF(vv); return nullptr; }
  int64_t* ps = (int64_t*)PyArray_DATA((PyArrayObject*)sh);
  int64_t* pp = (int64_t*)PyArray_DATA((PyArrayObject*)ph);
  int8_t* pv = (int8_t*)PyArray_DATA((PyArrayObject*)vv);
  bool bad = false;
  for (uint32_t i = 0; i < n; i++) {
    const uint8_t* e = body + (size_t)i * 13;
    ps[i] = rd_u32(e);
    pp[i] = (int64_t)rd_u64(e + 4);
    uint8_t code = e[12];
    if (code > 3) bad = true;
    pv[i] = (int8_t)code;
  }
  if (bad) {
    Py_DECREF(sh); Py_DECREF(ph); Py_DECREF(vv);
    PyErr_SetString(g_SerializationError, "vote code out of range");
    return nullptr;
  }
  PyObject* obj = raw_new(cls);
  if (!obj || raw_set(obj, s_shards, sh) < 0 || raw_set(obj, s_phases, ph) < 0 ||
      raw_set(obj, s_vals, vv) < 0) {
    Py_XDECREF(obj); Py_DECREF(sh); Py_DECREF(ph); Py_DECREF(vv);
    return nullptr;
  }
  Py_DECREF(sh); Py_DECREF(ph); Py_DECREF(vv);
  return obj;
}

PyObject* decode_decision(Rd& r) {
  const uint8_t* q = r.take(4);
  if (!q) return nullptr;
  uint32_t n = rd_u32(q);
  const uint8_t* body = r.take((size_t)n * 14);
  if (!body) return nullptr;
  PyObject* sh = make_i64_array(n);
  PyObject* ph = make_i64_array(n);
  npy_intp dims[1] = {(npy_intp)n};
  PyObject* vv = PyArray_SimpleNew(1, dims, NPY_INT8);
  if (!sh || !ph || !vv) { Py_XDECREF(sh); Py_XDECREF(ph); Py_XDECREF(vv); return nullptr; }
  int64_t* ps = (int64_t*)PyArray_DATA((PyArrayObject*)sh);
  int64_t* pp = (int64_t*)PyArray_DATA((PyArrayObject*)ph);
  int8_t* pv = (int8_t*)PyArray_DATA((PyArrayObject*)vv);
  bool bad = false;
  uint32_t n_bids = 0;
  for (uint32_t i = 0; i < n; i++) {
    const uint8_t* e = body + (size_t)i * 14;
    ps[i] = rd_u32(e);
    pp[i] = (int64_t)rd_u64(e + 4);
    uint8_t code = e[12];
    if (code > 3) bad = true;
    pv[i] = (int8_t)code;
    if (e[13]) n_bids++;
  }
  if (bad) {
    Py_DECREF(sh); Py_DECREF(ph); Py_DECREF(vv);
    PyErr_SetString(g_SerializationError, "decision code out of range");
    return nullptr;
  }
  PyObject* bids = Py_None;
  Py_INCREF(Py_None);
  if (n_bids) {
    Py_DECREF(Py_None);
    bids = PyList_New(n);
    if (!bids) { Py_DECREF(sh); Py_DECREF(ph); Py_DECREF(vv); return nullptr; }
    for (uint32_t i = 0; i < n; i++) {
      const uint8_t* e = body + (size_t)i * 14;
      PyObject* item;
      if (e[13]) {
        const uint8_t* raw = r.take(16);
        if (!raw) { Py_DECREF(bids); Py_DECREF(sh); Py_DECREF(ph); Py_DECREF(vv); return nullptr; }
        PyObject* u = make_uuid(raw);
        if (!u) { Py_DECREF(bids); Py_DECREF(sh); Py_DECREF(ph); Py_DECREF(vv); return nullptr; }
        item = raw_new(g_BatchId);
        if (!item || raw_set(item, s_value, u) < 0) {
          Py_XDECREF(item); Py_DECREF(u); Py_DECREF(bids);
          Py_DECREF(sh); Py_DECREF(ph); Py_DECREF(vv);
          return nullptr;
        }
        Py_DECREF(u);
      } else {
        item = Py_None;
        Py_INCREF(Py_None);
      }
      PyList_SET_ITEM(bids, i, item);  // steals
    }
  }
  PyObject* obj = raw_new(g_Decision);
  if (!obj || raw_set(obj, s_shards, sh) < 0 || raw_set(obj, s_phases, ph) < 0 ||
      raw_set(obj, s_vals, vv) < 0 || raw_set(obj, s_bids, bids) < 0) {
    Py_XDECREF(obj); Py_DECREF(sh); Py_DECREF(ph); Py_DECREF(vv); Py_DECREF(bids);
    return nullptr;
  }
  Py_DECREF(sh); Py_DECREF(ph); Py_DECREF(vv); Py_DECREF(bids);
  return obj;
}

// SyncResponse payload from a (decompressed) body reader
PyObject* decode_syncresp(Rd& r) {
  const uint8_t* q = r.take(17);  // u64 + u64 + u8 has_snapshot
  if (!q) return nullptr;
  PyObject* rp = PyLong_FromUnsignedLongLong(rd_u64(q));
  PyObject* sv = PyLong_FromUnsignedLongLong(rd_u64(q + 8));
  PyObject* snap = nullptr;
  PyObject *psp = nullptr, *ids = nullptr, *psv = nullptr;
  PyObject* obj = nullptr;
  do {
    if (!rp || !sv) break;
    if (q[16]) {
      const uint8_t* ln = r.take(4);
      if (!ln) break;
      uint32_t n = rd_u32(ln);
      const uint8_t* raw = r.take(n);
      if (!raw) break;
      snap = PyBytes_FromStringAndSize((const char*)raw, n);
    } else {
      snap = Py_None;
      Py_INCREF(Py_None);
    }
    if (!snap) break;
    // two u64 tuple sections + the (u32, uuid) applied_ids between them
    auto u64_tuple = [&r]() -> PyObject* {
      const uint8_t* ln = r.take(4);
      if (!ln) return nullptr;
      uint32_t n = rd_u32(ln);
      const uint8_t* raw = r.take((size_t)n * 8);
      if (!raw) return nullptr;
      PyObject* t = PyTuple_New(n);
      if (!t) return nullptr;
      for (uint32_t i = 0; i < n; i++) {
        PyObject* v = PyLong_FromUnsignedLongLong(rd_u64(raw + (size_t)i * 8));
        if (!v) { Py_DECREF(t); return nullptr; }
        PyTuple_SET_ITEM(t, i, v);
      }
      return t;
    };
    psp = u64_tuple();
    if (!psp) break;
    const uint8_t* ln = r.take(4);
    if (!ln) break;
    uint32_t n_ids = rd_u32(ln);
    const uint8_t* raw = r.take((size_t)n_ids * 20);
    if (!raw) break;
    ids = PyTuple_New(n_ids);
    if (!ids) break;
    bool bad = false;
    for (uint32_t i = 0; i < n_ids; i++) {
      const uint8_t* e = raw + (size_t)i * 20;
      PyObject* shard = PyLong_FromUnsignedLong(rd_u32(e));
      PyObject* u = shard ? make_uuid(e + 4) : nullptr;
      PyObject* bid = u ? raw_new(g_BatchId) : nullptr;
      if (!bid || raw_set(bid, s_value, u) < 0) {
        Py_XDECREF(bid); Py_XDECREF(u); Py_XDECREF(shard);
        bad = true; break;
      }
      Py_DECREF(u);
      PyObject* pair = PyTuple_New(2);
      if (!pair) {
        Py_DECREF(bid); Py_DECREF(shard);
        bad = true; break;
      }
      PyTuple_SET_ITEM(pair, 0, shard);  // steals
      PyTuple_SET_ITEM(pair, 1, bid);
      PyTuple_SET_ITEM(ids, i, pair);
    }
    if (bad) break;
    psv = u64_tuple();
    if (!psv) break;
    obj = raw_new(g_SyncResponse);
    if (!obj || raw_set(obj, s_responder_phase, rp) < 0 ||
        raw_set(obj, s_state_version, sv) < 0 ||
        raw_set(obj, s_snapshot, snap) < 0 ||
        raw_set(obj, s_per_shard_phase, psp) < 0 ||
        raw_set(obj, s_applied_ids, ids) < 0 ||
        raw_set(obj, s_per_shard_version, psv) < 0) {
      Py_XDECREF(obj);
      obj = nullptr;
      break;
    }
  } while (false);
  Py_XDECREF(rp); Py_XDECREF(sv); Py_XDECREF(snap);
  Py_XDECREF(psp); Py_XDECREF(ids); Py_XDECREF(psv);
  return obj;
}

// zlib-inflate a compressed body into a PyMem buffer (size unknown up
// front — snapshots compress 10x+; grow geometrically like Python's
// zlib.decompress). Returns nullptr with SerializationError set.
uint8_t* inflate_body(const uint8_t* src, size_t n, size_t* out_len) {
  z_stream zs;
  memset(&zs, 0, sizeof(zs));
  if (inflateInit(&zs) != Z_OK) {
    PyErr_SetString(g_SerializationError, "decompression failed: init");
    return nullptr;
  }
  size_t cap = n * 4 + 256;
  uint8_t* out = (uint8_t*)PyMem_Malloc(cap);
  if (!out) { inflateEnd(&zs); PyErr_NoMemory(); return nullptr; }
  zs.next_in = (Bytef*)src;
  zs.avail_in = (uInt)n;
  size_t have = 0;
  int rc;
  do {
    if (have == cap) {
      cap *= 2;
      uint8_t* np = (uint8_t*)PyMem_Realloc(out, cap);
      if (!np) { PyMem_Free(out); inflateEnd(&zs); PyErr_NoMemory(); return nullptr; }
      out = np;
    }
    zs.next_out = out + have;
    zs.avail_out = (uInt)(cap - have);
    rc = inflate(&zs, Z_NO_FLUSH);
    have = zs.total_out;
    if (rc != Z_OK && rc != Z_STREAM_END && rc != Z_BUF_ERROR) {
      PyMem_Free(out);
      inflateEnd(&zs);
      PyErr_Format(g_SerializationError, "decompression failed: %s",
                   zs.msg ? zs.msg : "corrupt stream");
      return nullptr;
    }
    if (rc == Z_BUF_ERROR && zs.avail_in == 0 && zs.avail_out > 0) {
      // truncated compressed data
      PyMem_Free(out);
      inflateEnd(&zs);
      PyErr_SetString(g_SerializationError,
                      "decompression failed: incomplete stream");
      return nullptr;
    }
  } while (rc != Z_STREAM_END);
  inflateEnd(&zs);
  *out_len = have;
  return out;
}

// frozen-dataclass carrier with two u64 fields (HeartBeat / SyncRequest)
PyObject* decode_two_u64(Rd& r, PyObject* cls, PyObject* f1, PyObject* f2) {
  const uint8_t* q = r.take(16);
  if (!q) return nullptr;
  PyObject* a = PyLong_FromUnsignedLongLong(rd_u64(q));
  PyObject* b = PyLong_FromUnsignedLongLong(rd_u64(q + 8));
  PyObject* obj = (a && b) ? raw_new(cls) : nullptr;
  if (!obj || raw_set(obj, f1, a) < 0 || raw_set(obj, f2, b) < 0) {
    Py_XDECREF(obj); Py_XDECREF(a); Py_XDECREF(b);
    return nullptr;
  }
  Py_DECREF(a); Py_DECREF(b);
  return obj;
}

PyObject* decode_block(Rd& r) {
  const uint8_t* braw = r.take(16);
  if (!braw) return nullptr;
  PyObject* bid = make_uuid(braw);
  if (!bid) return nullptr;
  const uint8_t* q = r.take(4);
  if (!q) { Py_DECREF(bid); return nullptr; }
  uint32_t k = rd_u32(q);
  const uint8_t* shr = r.take((size_t)k * 4);
  const uint8_t* slr = shr ? r.take((size_t)k * 8) : nullptr;
  const uint8_t* ctr = slr ? r.take((size_t)k * 4) : nullptr;
  const uint8_t* totr = ctr ? r.take(4) : nullptr;
  if (!totr) { Py_DECREF(bid); return nullptr; }
  uint32_t total = rd_u32(totr);
  const uint8_t* szr = r.take((size_t)total * 4);
  const uint8_t* dlenr = szr ? r.take(4) : nullptr;
  if (!dlenr) { Py_DECREF(bid); return nullptr; }
  uint32_t dlen = rd_u32(dlenr);
  const uint8_t* draw = r.take(dlen);
  const uint8_t* crcr = draw ? r.take(4) : nullptr;
  if (!crcr) { Py_DECREF(bid); return nullptr; }

  PyObject* data = PyBytes_FromStringAndSize((const char*)draw, dlen);
  if (!data) { Py_DECREF(bid); return nullptr; }
  bool ok = true;
  uint32_t crc = crc32_of(data, &ok);
  if (!ok) { Py_DECREF(bid); Py_DECREF(data); return nullptr; }
  if (crc != rd_u32(crcr)) {
    Py_DECREF(bid); Py_DECREF(data);
    PyErr_SetString(g_SerializationError, "block data checksum mismatch");
    return nullptr;
  }
  PyObject* sh = make_i64_array(k);
  PyObject* sl = make_i64_array(k);
  PyObject* ct = make_i64_array(k);
  PyObject* cs = make_i64_array(total);
  if (!sh || !sl || !ct || !cs) {
    Py_XDECREF(sh); Py_XDECREF(sl); Py_XDECREF(ct); Py_XDECREF(cs);
    Py_DECREF(bid); Py_DECREF(data);
    return nullptr;
  }
  int64_t* p;
  p = (int64_t*)PyArray_DATA((PyArrayObject*)sh);
  for (uint32_t i = 0; i < k; i++) p[i] = rd_u32(shr + 4 * i);
  p = (int64_t*)PyArray_DATA((PyArrayObject*)sl);
  for (uint32_t i = 0; i < k; i++) p[i] = (int64_t)rd_u64(slr + 8 * i);
  p = (int64_t*)PyArray_DATA((PyArrayObject*)ct);
  for (uint32_t i = 0; i < k; i++) p[i] = rd_u32(ctr + 4 * i);
  p = (int64_t*)PyArray_DATA((PyArrayObject*)cs);
  for (uint32_t i = 0; i < total; i++) p[i] = rd_u32(szr + 4 * i);

  // PayloadBlock validates shape/ordering invariants in __init__ — call
  // it normally; malformed content must raise SerializationError
  PyObject* blk = PyObject_CallFunctionObjArgs(
      g_PayloadBlock, bid, sh, sl, ct, cs, data, nullptr);
  Py_DECREF(bid); Py_DECREF(sh); Py_DECREF(sl); Py_DECREF(ct);
  Py_DECREF(cs); Py_DECREF(data);
  if (!blk) {
    PyObject *et, *ev, *tb;
    PyErr_Fetch(&et, &ev, &tb);
    PyErr_Format(g_SerializationError, "malformed block: %S",
                 ev ? ev : Py_None);
    Py_XDECREF(et); Py_XDECREF(ev); Py_XDECREF(tb);
    return nullptr;
  }
  PyObject* obj = raw_new(g_ProposeBlock);
  if (!obj || raw_set(obj, s_block, blk) < 0) {
    Py_XDECREF(obj); Py_DECREF(blk);
    return nullptr;
  }
  Py_DECREF(blk);
  return obj;
}

// CommandBatch from the wire (serialization.py _read_batch), checksum
// verified with the C crc32 while commands are built
PyObject* decode_batch(Rd& r) {
  const uint8_t* braw = r.take(16);
  if (!braw) return nullptr;
  PyObject* bid_u = make_uuid(braw);
  PyObject* bid = bid_u ? raw_new(g_BatchId) : nullptr;
  if (!bid || raw_set(bid, s_value, bid_u) < 0) {
    Py_XDECREF(bid); Py_XDECREF(bid_u);
    return nullptr;
  }
  Py_DECREF(bid_u);
  const uint8_t* fixed = r.take(8 + 4 + 4 + 4);
  if (!fixed) { Py_DECREF(bid); return nullptr; }
  double tsv;
  uint64_t bits = rd_u64(fixed);
  memcpy(&tsv, &bits, 8);
  uint32_t shard = rd_u32(fixed + 8);
  uint32_t checksum = rd_u32(fixed + 12);
  uint32_t n = rd_u32(fixed + 16);
  // bound the wire-controlled count by the remaining bytes BEFORE
  // allocating (every command needs >= 20 bytes: 16B id + u32 len) —
  // otherwise a short hostile frame forces a multi-GB tuple allocation
  if ((uint64_t)n * 20 > (uint64_t)(r.len - r.pos)) {
    Py_DECREF(bid);
    PyErr_Format(g_SerializationError,
                 "truncated batch: %u commands in %zu bytes", n,
                 r.len - r.pos);
    return nullptr;
  }
  PyObject* cmds = PyTuple_New((Py_ssize_t)n);
  if (!cmds) { Py_DECREF(bid); return nullptr; }
  uint32_t crc = 0;
  for (uint32_t i = 0; i < n; i++) {
    const uint8_t* idr = r.take(16);
    const uint8_t* lenr = idr ? r.take(4) : nullptr;
    if (!lenr) { Py_DECREF(bid); Py_DECREF(cmds); return nullptr; }
    uint32_t dlen = rd_u32(lenr);
    const uint8_t* draw = r.take(dlen);
    if (!draw) { Py_DECREF(bid); Py_DECREF(cmds); return nullptr; }
    crc = crc32_run(crc, idr, 16);
    crc = crc32_run(crc, draw, dlen);
    PyObject* cid = make_uuid(idr);
    PyObject* data =
        cid ? PyBytes_FromStringAndSize((const char*)draw, dlen) : nullptr;
    PyObject* cmd = data ? raw_new(g_Command) : nullptr;
    if (!cmd || raw_set(cmd, s_id, cid) < 0 ||
        raw_set(cmd, s_data, data) < 0) {
      Py_XDECREF(cmd); Py_XDECREF(data); Py_XDECREF(cid);
      Py_DECREF(bid); Py_DECREF(cmds);
      return nullptr;
    }
    Py_DECREF(cid); Py_DECREF(data);
    PyTuple_SET_ITEM(cmds, i, cmd);  // steals
  }
  if (crc != checksum) {
    Py_DECREF(bid); Py_DECREF(cmds);
    PyErr_SetString(g_SerializationError,
                    "batch checksum mismatch on decode");
    return nullptr;
  }
  PyObject* shard_obj = raw_new(g_ShardId);
  PyObject* shard_val = PyLong_FromUnsignedLong(shard);
  PyObject* ts = PyFloat_FromDouble(tsv);
  PyObject* batch =
      (shard_obj && shard_val && ts) ? raw_new(g_CommandBatch) : nullptr;
  if (!batch || raw_set(shard_obj, s_value, shard_val) < 0 ||
      raw_set(batch, s_id, bid) < 0 ||
      raw_set(batch, s_commands, cmds) < 0 ||
      raw_set(batch, s_timestamp, ts) < 0 ||
      raw_set(batch, s_shard, shard_obj) < 0) {
    Py_XDECREF(batch); Py_XDECREF(shard_obj); Py_XDECREF(shard_val);
    Py_XDECREF(ts); Py_DECREF(bid); Py_DECREF(cmds);
    return nullptr;
  }
  Py_DECREF(shard_obj); Py_DECREF(shard_val); Py_DECREF(ts);
  Py_DECREF(bid); Py_DECREF(cmds);
  return batch;
}

PyObject* decode_propose(Rd& r) {
  const uint8_t* fixed = r.take(4 + 8 + 16 + 1 + 1);
  if (!fixed) return nullptr;
  uint32_t shard = rd_u32(fixed);
  uint64_t phase = rd_u64(fixed + 4);
  const uint8_t* bidr = fixed + 12;
  uint8_t code = fixed[28];
  uint8_t has_batch = fixed[29];
  PyObject* batch;
  if (has_batch) {
    batch = decode_batch(r);
    if (!batch) return nullptr;
  } else {
    batch = Py_None;
    Py_INCREF(Py_None);
  }
  PyObject* bid_u = make_uuid(bidr);
  PyObject* bid = bid_u ? raw_new(g_BatchId) : nullptr;
  if (!bid || raw_set(bid, s_value, bid_u) < 0) {
    Py_XDECREF(bid); Py_XDECREF(bid_u); Py_DECREF(batch);
    return nullptr;
  }
  Py_DECREF(bid_u);
  // StateValue(code) through the enum class: invalid codes raise exactly
  // what the Python decoder would (ValueError), preserving error parity
  PyObject* sval = PyObject_CallFunction(g_StateValue, "i", (int)code);
  PyObject* shard_obj = sval ? PyLong_FromUnsignedLong(shard) : nullptr;
  PyObject* phase_obj = shard_obj ? PyLong_FromUnsignedLongLong(phase) : nullptr;
  PyObject* obj = phase_obj ? raw_new(g_Propose) : nullptr;
  if (!obj || raw_set(obj, s_shard, shard_obj) < 0 ||
      raw_set(obj, s_phase, phase_obj) < 0 ||
      raw_set(obj, s_batch_id, bid) < 0 ||
      raw_set(obj, s_value, sval) < 0 ||
      raw_set(obj, s_batch, batch) < 0) {
    Py_XDECREF(obj); Py_XDECREF(phase_obj); Py_XDECREF(shard_obj);
    Py_XDECREF(sval); Py_DECREF(bid); Py_DECREF(batch);
    return nullptr;
  }
  Py_DECREF(phase_obj); Py_DECREF(shard_obj); Py_DECREF(sval);
  Py_DECREF(bid); Py_DECREF(batch);
  return obj;
}

PyObject* decode_newbatch(Rd& r) {
  const uint8_t* q = r.take(4);
  if (!q) return nullptr;
  uint32_t shard = rd_u32(q);
  PyObject* batch = decode_batch(r);
  if (!batch) return nullptr;
  PyObject* shard_obj = PyLong_FromUnsignedLong(shard);
  PyObject* obj = shard_obj ? raw_new(g_NewBatch) : nullptr;
  if (!obj || raw_set(obj, s_shard, shard_obj) < 0 ||
      raw_set(obj, s_batch, batch) < 0) {
    Py_XDECREF(obj); Py_XDECREF(shard_obj); Py_DECREF(batch);
    return nullptr;
  }
  Py_DECREF(shard_obj); Py_DECREF(batch);
  return obj;
}

// --- client gateway frame decoders ----------------------------------------

// u32 count + count * (u32 len + bytes) -> tuple of bytes
PyObject* decode_blob_tuple(Rd& r) {
  const uint8_t* q = r.take(4);
  if (!q) return nullptr;
  uint32_t n = rd_u32(q);
  // bound the wire-controlled count by the remaining bytes BEFORE
  // allocating (every blob needs >= 4 length bytes)
  if ((uint64_t)n * 4 > (uint64_t)(r.len - r.pos)) {
    PyErr_Format(g_SerializationError,
                 "truncated blob tuple: %u entries in %zu bytes", n,
                 r.len - r.pos);
    return nullptr;
  }
  PyObject* t = PyTuple_New((Py_ssize_t)n);
  if (!t) return nullptr;
  for (uint32_t i = 0; i < n; i++) {
    const uint8_t* ln = r.take(4);
    if (!ln) { Py_DECREF(t); return nullptr; }
    uint32_t dlen = rd_u32(ln);
    const uint8_t* raw = r.take(dlen);
    if (!raw) { Py_DECREF(t); return nullptr; }
    PyObject* blob = PyBytes_FromStringAndSize((const char*)raw, dlen);
    if (!blob) { Py_DECREF(t); return nullptr; }
    PyTuple_SET_ITEM(t, i, blob);  // steals
  }
  return t;
}

// u32 count + count * u64 -> tuple of ints
PyObject* decode_u64_tuple(Rd& r) {
  const uint8_t* ln = r.take(4);
  if (!ln) return nullptr;
  uint32_t n = rd_u32(ln);
  const uint8_t* raw = r.take((size_t)n * 8);
  if (!raw) return nullptr;
  PyObject* t = PyTuple_New((Py_ssize_t)n);
  if (!t) return nullptr;
  for (uint32_t i = 0; i < n; i++) {
    PyObject* v = PyLong_FromUnsignedLongLong(rd_u64(raw + (size_t)i * 8));
    if (!v) { Py_DECREF(t); return nullptr; }
    PyTuple_SET_ITEM(t, i, v);
  }
  return t;
}

PyObject* decode_client_hello(Rd& r) {
  const uint8_t* q = r.take(1 + 16 + 8 + 4);
  if (!q) return nullptr;
  PyObject* ack = PyBool_FromLong(q[0]);
  PyObject* cid = make_uuid(q + 1);
  PyObject* ls = PyLong_FromUnsignedLongLong(rd_u64(q + 17));
  PyObject* mi = PyLong_FromUnsignedLong(rd_u32(q + 25));
  PyObject* obj = (ack && cid && ls && mi) ? raw_new(g_ClientHello) : nullptr;
  if (!obj || raw_set(obj, s_client_id, cid) < 0 ||
      raw_set(obj, s_ack, ack) < 0 || raw_set(obj, s_last_seq, ls) < 0 ||
      raw_set(obj, s_max_inflight, mi) < 0) {
    Py_XDECREF(obj); Py_XDECREF(ack); Py_XDECREF(cid);
    Py_XDECREF(ls); Py_XDECREF(mi);
    return nullptr;
  }
  Py_DECREF(ack); Py_DECREF(cid); Py_DECREF(ls); Py_DECREF(mi);
  return obj;
}

PyObject* decode_submit(Rd& r) {
  const uint8_t* q = r.take(16 + 8 + 4 + 8);
  if (!q) return nullptr;
  PyObject* cid = make_uuid(q);
  PyObject* seq = PyLong_FromUnsignedLongLong(rd_u64(q + 16));
  PyObject* shard = PyLong_FromUnsignedLong(rd_u32(q + 24));
  PyObject* au = PyLong_FromUnsignedLongLong(rd_u64(q + 28));
  PyObject* cmds =
      (cid && seq && shard && au) ? decode_blob_tuple(r) : nullptr;
  PyObject* obj = cmds ? raw_new(g_Submit) : nullptr;
  if (!obj || raw_set(obj, s_client_id, cid) < 0 ||
      raw_set(obj, s_seq, seq) < 0 || raw_set(obj, s_shard, shard) < 0 ||
      raw_set(obj, s_commands, cmds) < 0 ||
      raw_set(obj, s_ack_upto, au) < 0) {
    Py_XDECREF(obj); Py_XDECREF(cid); Py_XDECREF(seq);
    Py_XDECREF(shard); Py_XDECREF(au); Py_XDECREF(cmds);
    return nullptr;
  }
  Py_DECREF(cid); Py_DECREF(seq); Py_DECREF(shard);
  Py_DECREF(au); Py_DECREF(cmds);
  return obj;
}

PyObject* decode_result(Rd& r) {
  const uint8_t* q = r.take(16 + 8 + 1);
  if (!q) return nullptr;
  PyObject* cid = make_uuid(q);
  PyObject* seq = PyLong_FromUnsignedLongLong(rd_u64(q + 16));
  PyObject* status = PyLong_FromLong(q[24]);
  PyObject* pl = (cid && seq && status) ? decode_blob_tuple(r) : nullptr;
  PyObject* obj = pl ? raw_new(g_Result) : nullptr;
  if (!obj || raw_set(obj, s_client_id, cid) < 0 ||
      raw_set(obj, s_seq, seq) < 0 || raw_set(obj, s_status, status) < 0 ||
      raw_set(obj, s_payload, pl) < 0) {
    Py_XDECREF(obj); Py_XDECREF(cid); Py_XDECREF(seq);
    Py_XDECREF(status); Py_XDECREF(pl);
    return nullptr;
  }
  Py_DECREF(cid); Py_DECREF(seq); Py_DECREF(status); Py_DECREF(pl);
  return obj;
}

PyObject* decode_read_index(Rd& r) {
  const uint8_t* q = r.take(1 + 16 + 8 + 4);
  if (!q) return nullptr;
  PyObject* mode = PyLong_FromLong(q[0]);
  PyObject* cid = make_uuid(q + 1);
  PyObject* seq = PyLong_FromUnsignedLongLong(rd_u64(q + 17));
  PyObject* shard = PyLong_FromUnsignedLong(rd_u32(q + 25));
  PyObject* key = nullptr;
  if (mode && cid && seq && shard) {
    const uint8_t* ln = r.take(4);
    const uint8_t* raw = ln ? r.take(rd_u32(ln)) : nullptr;
    if (raw)
      key = PyBytes_FromStringAndSize((const char*)raw,
                                      (Py_ssize_t)rd_u32(ln));
  }
  PyObject* fr = key ? decode_u64_tuple(r) : nullptr;
  PyObject* obj = fr ? raw_new(g_ReadIndex) : nullptr;
  if (!obj || raw_set(obj, s_mode, mode) < 0 ||
      raw_set(obj, s_client_id, cid) < 0 || raw_set(obj, s_seq, seq) < 0 ||
      raw_set(obj, s_shard, shard) < 0 || raw_set(obj, s_key, key) < 0 ||
      raw_set(obj, s_frontier, fr) < 0) {
    Py_XDECREF(obj); Py_XDECREF(mode); Py_XDECREF(cid); Py_XDECREF(seq);
    Py_XDECREF(shard); Py_XDECREF(key); Py_XDECREF(fr);
    return nullptr;
  }
  Py_DECREF(mode); Py_DECREF(cid); Py_DECREF(seq);
  Py_DECREF(shard); Py_DECREF(key); Py_DECREF(fr);
  return obj;
}

// --- entry points ---------------------------------------------------------

PyObject* codec_encode(PyObject*, PyObject* args) {
  PyObject* msg;
  Py_ssize_t compress_threshold = 0;
  if (!PyArg_ParseTuple(args, "O|n", &msg, &compress_threshold))
    return nullptr;
  if (!g_ProtocolMessage) {
    PyErr_SetString(PyExc_RuntimeError, "codec not bound");
    return nullptr;
  }
  PyObject* payload = PyObject_GetAttr(msg, s_payload);
  if (!payload) return nullptr;
  PyTypeObject* pt = Py_TYPE(payload);
  uint8_t mt;
  if (pt == (PyTypeObject*)g_VoteRound1) mt = MT_VOTE1;
  else if (pt == (PyTypeObject*)g_VoteRound2) mt = MT_VOTE2;
  else if (pt == (PyTypeObject*)g_Decision) mt = MT_DECISION;
  else if (pt == (PyTypeObject*)g_HeartBeat) mt = MT_HEARTBEAT;
  else if (pt == (PyTypeObject*)g_SyncRequest) mt = MT_SYNCREQ;
  else if (pt == (PyTypeObject*)g_SyncResponse) mt = MT_SYNCRESP;
  else if (pt == (PyTypeObject*)g_ProposeBlock) mt = MT_PROPOSE_BLOCK;
  else if (pt == (PyTypeObject*)g_Propose) mt = MT_PROPOSE;
  else if (pt == (PyTypeObject*)g_NewBatch) mt = MT_NEWBATCH;
  else if (pt == (PyTypeObject*)g_ClientHello) mt = MT_CLIENT_HELLO;
  else if (pt == (PyTypeObject*)g_Submit) mt = MT_SUBMIT;
  else if (pt == (PyTypeObject*)g_Result) mt = MT_RESULT;
  else if (pt == (PyTypeObject*)g_ReadIndex) mt = MT_READ_INDEX;
  else {
    Py_DECREF(payload);
    Py_RETURN_NONE;  // unsupported: Python codec handles it
  }
  if (mt == MT_PROPOSE || mt == MT_NEWBATCH) {
    PyObject* batch = PyObject_GetAttr(payload, s_batch);
    if (!batch) { Py_DECREF(payload); return nullptr; }
    Py_ssize_t bsize = batch_body_size(batch);
    bool ok_batch =
        bsize >= 0 && (batch != Py_None || mt == MT_PROPOSE) &&
        attr_fits(payload, s_shard, 0xFFFFFFFFull,
                  /*allow_wrapper=*/false) &&
        (mt != MT_PROPOSE ||
         attr_fits(payload, s_phase, ~0ull, /*allow_wrapper=*/false));
    Py_DECREF(batch);
    Py_ssize_t body_size =
        (mt == MT_PROPOSE ? 4 + 8 + 16 + 1 + 1 : 4) + bsize;
    if (!ok_batch ||
        (compress_threshold > 0 && body_size > compress_threshold)) {
      // odd batch content, or large enough that the Python codec may
      // compress it: the Python path owns the frame
      Py_DECREF(payload);
      Py_RETURN_NONE;
    }
  }
  if (mt == MT_DECISION) {
    // encode_decision indexes bids with PyList_GET_ITEM; a non-list
    // sequence (Decision.__init__ accepts any sized iterable) must
    // fall back to the Python codec, not be reinterpreted as a list
    PyObject* bids = PyObject_GetAttr(payload, s_bids);
    if (!bids) { Py_DECREF(payload); return nullptr; }
    bool ok_bids = bids == Py_None || PyList_Check(bids);
    Py_DECREF(bids);
    if (!ok_bids) {
      Py_DECREF(payload);
      Py_RETURN_NONE;
    }
  }

  bool decline = false;  // shape surprise: Python codec owns the frame
  PyObject* mid = PyObject_GetAttr(msg, s_id);
  PyObject* sender = mid ? PyObject_GetAttr(msg, s_sender) : nullptr;
  PyObject* recipient = sender ? PyObject_GetAttr(msg, s_recipient) : nullptr;
  PyObject* ts = recipient ? PyObject_GetAttr(msg, s_timestamp) : nullptr;
  PyObject* out = nullptr;
  if (ts) {
    double tsv = PyFloat_AsDouble(ts);
    if (!(tsv == -1.0 && PyErr_Occurred())) {
      Buf env;
      uint8_t flags = (recipient != Py_None) ? FLAG_HAS_RECIPIENT : 0;
      bool ok = env.put_u8(WIRE_VERSION) && env.put_u8(mt) && env.put_u8(flags);
      uint8_t raw[16];
      ok = ok && uuid_bytes(mid, raw) && env.put_raw(raw, 16);
      if (ok) {
        PyObject* sval = PyObject_GetAttr(sender, s_value);
        ok = sval && uuid_bytes(sval, raw) && env.put_raw(raw, 16);
        Py_XDECREF(sval);
      }
      if (ok && recipient != Py_None) {
        PyObject* rval = PyObject_GetAttr(recipient, s_value);
        ok = rval && uuid_bytes(rval, raw) && env.put_raw(raw, 16);
        Py_XDECREF(rval);
      }
      if (ok) {
        uint64_t bits;
        memcpy(&bits, &tsv, 8);
        ok = env.put_u64(bits);
      }
      if (ok) {
        Buf body;
        switch (mt) {
          case MT_VOTE1:
          case MT_VOTE2: ok = encode_votes(body, payload); break;
          case MT_DECISION: ok = encode_decision(body, payload); break;
          case MT_PROPOSE: ok = encode_propose(body, payload); break;
          case MT_NEWBATCH: ok = encode_newbatch(body, payload); break;
          case MT_HEARTBEAT:
            ok = put_u64_attr(body, payload, s_current_phase) &&
                 put_u64_attr(body, payload, s_committed_phase);
            break;
          case MT_SYNCREQ:
            ok = put_u64_attr(body, payload, s_current_phase) &&
                 put_u64_attr(body, payload, s_state_version);
            break;
          case MT_SYNCRESP:
            ok = encode_syncresp(body, payload, &decline);
            break;
          case MT_PROPOSE_BLOCK: ok = encode_block(body, payload); break;
          case MT_CLIENT_HELLO:
            ok = encode_client_hello(body, payload, &decline);
            break;
          case MT_SUBMIT: ok = encode_submit(body, payload, &decline); break;
          case MT_RESULT: ok = encode_result(body, payload, &decline); break;
          case MT_READ_INDEX:
            ok = encode_read_index(body, payload, &decline);
            break;
        }
        bool body_done = false;
        if (ok && mt == MT_SYNCRESP && compress_threshold > 0 &&
            (Py_ssize_t)body.len > compress_threshold) {
          // same rule as _serialize_py: zlib level 1, keep only if it
          // actually shrinks (byte parity pinned by test_native_codec)
          uLongf clen = compressBound((uLong)body.len);
          uint8_t* cbuf = (uint8_t*)PyMem_Malloc(clen);
          if (!cbuf) {
            ok = false;
            PyErr_NoMemory();
          } else {
            if (compress2(cbuf, &clen, body.p, (uLong)body.len, 1) == Z_OK &&
                (size_t)clen < body.len) {
              env.p[2] |= FLAG_COMPRESSED;  // flags byte of the envelope
              ok = env.put_u32((uint32_t)clen) && env.put_raw(cbuf, clen);
              body_done = true;
            }
            PyMem_Free(cbuf);
          }
        }
        if (ok && !body_done)
          ok = env.put_u32((uint32_t)body.len) &&
               env.put_raw(body.p, body.len);
        if (ok && !decline)
          out = PyBytes_FromStringAndSize((const char*)env.p,
                                          (Py_ssize_t)env.len);
      }
      if (!ok && !decline && !PyErr_Occurred())
        PyErr_SetString(g_SerializationError, "native encode failed");
    }
  }
  Py_XDECREF(ts); Py_XDECREF(recipient); Py_XDECREF(sender);
  Py_XDECREF(mid); Py_DECREF(payload);
  if (decline && !out && !PyErr_Occurred()) {
    // shape surprise: hand the frame to the Python codec untouched
    out = Py_None;
    Py_INCREF(Py_None);
  }
  return out;
}

PyObject* codec_decode(PyObject*, PyObject* arg) {
  if (!g_ProtocolMessage) {
    PyErr_SetString(PyExc_RuntimeError, "codec not bound");
    return nullptr;
  }
  Py_buffer view;
  if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0) return nullptr;
  Rd r{(const uint8_t*)view.buf, (size_t)view.len};
  PyObject* result = nullptr;
  PyObject* payload = nullptr;
  PyObject *mid = nullptr, *sender = nullptr, *recipient = nullptr,
           *tsobj = nullptr;
  uint8_t* inflated = nullptr;
  do {
    const uint8_t* h = r.take(3);
    if (!h) break;
    uint8_t version = h[0], mt = h[1], flags = h[2];
    if (version != WIRE_VERSION) {
      PyErr_Format(g_SerializationError, "unsupported wire version %d",
                   (int)version);
      break;
    }
    bool supported =
        (mt == MT_VOTE1 || mt == MT_VOTE2 || mt == MT_DECISION ||
         mt == MT_HEARTBEAT || mt == MT_SYNCREQ || mt == MT_SYNCRESP ||
         mt == MT_PROPOSE_BLOCK || mt == MT_PROPOSE || mt == MT_NEWBATCH ||
         mt == MT_CLIENT_HELLO || mt == MT_SUBMIT || mt == MT_RESULT ||
         mt == MT_READ_INDEX) &&
        (!(flags & FLAG_COMPRESSED) || mt == MT_SYNCRESP);
    if (!supported) {
      // Python codec owns the remaining types / compressed bodies
      result = Py_None;
      Py_INCREF(Py_None);
      break;
    }
    const uint8_t* idr = r.take(16);
    if (!idr) break;
    mid = make_uuid(idr);
    if (!mid) break;
    const uint8_t* sndr = r.take(16);
    if (!sndr) break;
    sender = intern_node(sndr);
    if (!sender) break;
    if (flags & FLAG_HAS_RECIPIENT) {
      const uint8_t* rcp = r.take(16);
      if (!rcp) break;
      recipient = intern_node(rcp);
      if (!recipient) break;
    } else {
      recipient = Py_None;
      Py_INCREF(Py_None);
    }
    const uint8_t* tsr = r.take(8);
    if (!tsr) break;
    double tsv;
    uint64_t bits = rd_u64(tsr);
    memcpy(&tsv, &bits, 8);
    tsobj = PyFloat_FromDouble(tsv);
    if (!tsobj) break;
    const uint8_t* blr = r.take(4);
    if (!blr) break;
    uint32_t body_len = rd_u32(blr);
    const uint8_t* body = r.take(body_len);
    if (!body) break;
    Rd br{body, body_len};
    if (flags & FLAG_COMPRESSED) {  // only MT_SYNCRESP reaches here
      size_t ilen = 0;
      inflated = inflate_body(body, body_len, &ilen);
      if (!inflated) break;
      br = Rd{inflated, ilen};
    }
    switch (mt) {
      case MT_VOTE1: payload = decode_votes(br, g_VoteRound1); break;
      case MT_VOTE2: payload = decode_votes(br, g_VoteRound2); break;
      case MT_DECISION: payload = decode_decision(br); break;
      case MT_HEARTBEAT:
        payload = decode_two_u64(br, g_HeartBeat, s_current_phase,
                                 s_committed_phase);
        break;
      case MT_SYNCREQ:
        payload = decode_two_u64(br, g_SyncRequest, s_current_phase,
                                 s_state_version);
        break;
      case MT_SYNCRESP: payload = decode_syncresp(br); break;
      case MT_PROPOSE_BLOCK: payload = decode_block(br); break;
      case MT_PROPOSE: payload = decode_propose(br); break;
      case MT_NEWBATCH: payload = decode_newbatch(br); break;
      case MT_CLIENT_HELLO: payload = decode_client_hello(br); break;
      case MT_SUBMIT: payload = decode_submit(br); break;
      case MT_RESULT: payload = decode_result(br); break;
      case MT_READ_INDEX: payload = decode_read_index(br); break;
    }
    if (!payload) break;
    PyObject* msg = raw_new(g_ProtocolMessage);
    if (!msg || raw_set(msg, s_id, mid) < 0 ||
        raw_set(msg, s_sender, sender) < 0 ||
        raw_set(msg, s_recipient, recipient) < 0 ||
        raw_set(msg, s_timestamp, tsobj) < 0 ||
        raw_set(msg, s_payload, payload) < 0) {
      Py_XDECREF(msg);
      break;
    }
    result = msg;
  } while (false);
  Py_XDECREF(payload); Py_XDECREF(mid); Py_XDECREF(sender);
  Py_XDECREF(recipient); Py_XDECREF(tsobj);
  if (inflated) PyMem_Free(inflated);
  PyBuffer_Release(&view);
  return result;
}

PyObject* codec_bind(PyObject*, PyObject* args, PyObject* kwargs) {
  static const char* kwlist[] = {
      "ProtocolMessage", "VoteRound1", "VoteRound2", "Decision",
      "HeartBeat", "SyncRequest", "ProposeBlock", "PayloadBlock",
      "NodeId", "BatchId", "UUID", "safe_unknown", "SerializationError",
      "crc32", "Propose", "NewBatch", "CommandBatch", "Command",
      "ShardId", "StateValue", "SyncResponse", "ClientHello", "Submit",
      "Result", "ReadIndex", nullptr};
  PyObject *pm, *v1, *v2, *dc, *hb, *sr, *pb, *plb, *nid, *bid, *uu, *su,
      *se, *crc, *pr, *nb, *cb, *cm, *si, *sv, *srp, *ch, *sb, *rs, *ri;
  if (!PyArg_ParseTupleAndKeywords(
          args, kwargs, "OOOOOOOOOOOOOOOOOOOOOOOOO", (char**)kwlist, &pm,
          &v1, &v2, &dc, &hb, &sr, &pb, &plb, &nid, &bid, &uu, &su, &se,
          &crc, &pr, &nb, &cb, &cm, &si, &sv, &srp, &ch, &sb, &rs, &ri))
    return nullptr;
#define BIND(slot, val) Py_XDECREF(slot); Py_INCREF(val); slot = val
  BIND(g_ProtocolMessage, pm); BIND(g_VoteRound1, v1); BIND(g_VoteRound2, v2);
  BIND(g_Decision, dc); BIND(g_HeartBeat, hb); BIND(g_SyncRequest, sr);
  BIND(g_ProposeBlock, pb); BIND(g_PayloadBlock, plb); BIND(g_NodeId, nid);
  BIND(g_BatchId, bid); BIND(g_UUID, uu); BIND(g_safe_unknown, su);
  BIND(g_SerializationError, se); BIND(g_crc32, crc);
  BIND(g_Propose, pr); BIND(g_NewBatch, nb); BIND(g_CommandBatch, cb);
  BIND(g_Command, cm); BIND(g_ShardId, si); BIND(g_StateValue, sv);
  BIND(g_SyncResponse, srp); BIND(g_ClientHello, ch); BIND(g_Submit, sb);
  BIND(g_Result, rs); BIND(g_ReadIndex, ri);
#undef BIND
  Py_RETURN_NONE;
}

PyMethodDef methods[] = {
    {"bind", (PyCFunction)codec_bind, METH_VARARGS | METH_KEYWORDS,
     "Bind the Python message classes the codec builds/reads."},
    {"encode", codec_encode, METH_VARARGS,
     "encode(msg, compress_threshold=0): serialize a ProtocolMessage; "
     "None if the type is not fast-pathed (or would compress)."},
    {"decode", codec_decode, METH_O,
     "Deserialize wire bytes; None if the type is not fast-pathed."},
    {nullptr, nullptr, 0, nullptr}};

PyModuleDef moduledef = {PyModuleDef_HEAD_INIT,
                         "rabia_native_codec",
                         "Native binary codec for protocol hot frames",
                         -1,
                         methods,
                         nullptr,
                         nullptr,
                         nullptr,
                         nullptr};

}  // namespace

extern "C" PyMODINIT_FUNC PyInit_rabia_native_codec(void) {
  import_array();
  PyObject* m = PyModule_Create(&moduledef);
  if (!m) return nullptr;
  g_node_intern = PyDict_New();
  g_empty_tuple = PyTuple_New(0);
#define INTERN(var, name) var = PyUnicode_InternFromString(name)
  INTERN(s_payload, "payload"); INTERN(s_id, "id"); INTERN(s_sender, "sender");
  INTERN(s_recipient, "recipient"); INTERN(s_timestamp, "timestamp");
  INTERN(s_value, "value"); INTERN(s_int, "int"); INTERN(s_is_safe, "is_safe");
  INTERN(s_shards, "shards"); INTERN(s_phases, "phases");
  INTERN(s_vals, "vals"); INTERN(s_bids, "bids");
  INTERN(s_current_phase, "current_phase");
  INTERN(s_committed_phase, "committed_phase");
  INTERN(s_state_version, "state_version"); INTERN(s_block, "block");
  INTERN(s_slots, "slots"); INTERN(s_counts, "counts");
  INTERN(s_cmd_sizes, "cmd_sizes"); INTERN(s_data, "data");
  INTERN(s_total_commands, "total_commands");
  INTERN(s_shard, "shard"); INTERN(s_phase, "phase");
  INTERN(s_batch_id, "batch_id"); INTERN(s_batch, "batch");
  INTERN(s_commands, "commands");
  INTERN(s_responder_phase, "responder_phase"); INTERN(s_snapshot, "snapshot");
  INTERN(s_per_shard_phase, "per_shard_phase");
  INTERN(s_applied_ids, "applied_ids");
  INTERN(s_per_shard_version, "per_shard_version");
  INTERN(s_client_id, "client_id"); INTERN(s_seq, "seq");
  INTERN(s_ack, "ack"); INTERN(s_last_seq, "last_seq");
  INTERN(s_max_inflight, "max_inflight"); INTERN(s_ack_upto, "ack_upto");
  INTERN(s_status, "status"); INTERN(s_mode, "mode");
  INTERN(s_key, "key"); INTERN(s_frontier, "frontier");
#undef INTERN
  return m;
}
