// Native durability plane: the C write-ahead log of decided waves.
//
// The engine's apply paths (runtime.cpp's decide->apply stage and the
// asyncio apply plane) stage CRC-framed records into an in-memory buffer
// with one cheap mutex-protected append per record; a DEDICATED flush
// thread drains the buffer to the current segment file and fsyncs — one
// fsync covers every record staged while the previous fsync ran
// (group commit), so neither the GIL-free io/tick thread nor the asyncio
// loop ever blocks on disk. Callers that need a durability barrier
// (vote write-ahead, gateway result frames) compare wal_durable() to the
// LSN their append returned and wait on the eventfd.
//
// The Python twin (rabia_tpu/persistence/native_wal.py `_PyWalWriter`,
// forced by RABIA_PY_WAL=1) is the SEMANTICS OWNER of the byte format:
// given the same record sequence and segment limit, both writers must
// produce byte-identical segment files (testing/conformance.py
// run_waves_on_both_wal_paths pins this; scripts/fuzz_conformance.py
// --wal fuzzes it in CI). Keep every format decision here mirrored
// there, and vice versa.
//
// On-disk format (docs/DURABILITY.md):
//   segment file  wal-XXXXXXXX.seg (XXXXXXXX = zero-padded decimal index)
//   header (24B)  "RTWL" | u32 version=1 | u64 segment_index | u64 base_lsn
//   record frame  [u32 LE payload_len][u32 LE crc32(payload)][payload]
//   payload       u8 kind | kind-specific body (encoded by the callers;
//                 this kernel treats payloads as opaque except for the
//                 leading kind byte it counts, and the BARRIER records it
//                 emits itself from wal_barrier_covered)
//
// LSNs are 1-based record ordinals across the whole log (segments
// included); durability is a watermark: wal_durable() returns the
// highest LSN whose record (and all predecessors) survived an fsync.
// Rotation happens on RECORD boundaries at flush time, decided purely by
// accumulated segment bytes — deterministic for a given record sequence,
// independent of flush timing, which is what makes C/Python byte parity
// possible at all.
//
// Recovery (scan + torn-tail truncation + replay) lives in Python
// (native_wal.py): it is a cold path that runs once per process start,
// and keeping it in one place means both writer backends recover through
// literally the same code.

#include <errno.h>
#include <fcntl.h>
#include <pthread.h>
#include <stdio.h>
#include <string.h>
#include <sys/eventfd.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>
#include <zlib.h>

#include <atomic>
#include <cstdint>
#include <new>
#include <string>
#include <thread>
#include <vector>

#include "annotations.h"

extern "C" {

// ---------------------------------------------------------------------------
// counter block (versioned, append-only — docs/OBSERVABILITY.md WLC_*)
// ---------------------------------------------------------------------------

enum {
  WLC_APPENDS = 0,     // records staged (all kinds)
  WLC_APPEND_BYTES,    // framed bytes staged
  WLC_WAVES,           // kind-1 (decided wave) records
  WLC_BARRIERS,        // kind-2 (vote barrier) records
  WLC_FRONTIERS,       // kind-3 (snapshot frontier) records
  WLC_LEDGERS,         // kind-4 (batch-id ledger) records
  WLC_FLUSHES,         // flush-thread drain passes
  WLC_FLUSH_BYTES,     // bytes written to segment files
  WLC_FSYNCS,          // fsync calls on segment files
  WLC_FSYNC_NS,        // cumulative fsync nanoseconds
  WLC_GROUP_RECORDS,   // records covered by fsyncs (group-commit size sum)
  WLC_ROTATIONS,       // segment rotations
  WLC_BARRIER_WAITS,   // wal_barrier_covered calls that had to append
  WLC_IO_ERRORS,       // write/fsync failures (log wedges read-only)
  WLC_COUNT
};

static const int32_t WAL_COUNTERS_VERSION = 1;

// fsync-latency SLO histogram: same log-bucket geometry as runtime.cpp's
// RTH block (2^sub_bits sub-buckets per octave from 2^min_exp ns) so the
// Python exporter reuses one bound table for every native histogram.
static const int32_t WLH_VERSION = 1;
static const int32_t WLH_SUB_BITS = 2;
static const int32_t WLH_MIN_EXP = 10;   // floor 1.024us
static const int32_t WLH_OCTAVES = 25;   // top ~34.4s
static const int32_t WLH_BUCKETS = WLH_OCTAVES << WLH_SUB_BITS;
static const int32_t WLH_STRIDE = WLH_BUCKETS + 2;  // + count + sum_ns

static const uint32_t WAL_MAGIC = 0x4C575452u;  // "RTWL" little-endian
static const uint32_t WAL_VERSION = 1;
static const int64_t WAL_HEADER = 24;

static inline uint64_t mono_ns() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (uint64_t)ts.tv_sec * 1000000000ull + (uint64_t)ts.tv_nsec;
}

struct WalCtx {
  std::string dir;
  int dir_fd = -1;
  int seg_fd = -1;  // flush-thread-owned after wal_start (create before)
  // open-segment identity/fill, published for wal_segment_index/_bytes
  // (advisory cross-thread reads; the flush thread is the only writer
  // after start)
  std::atomic<uint64_t> seg_index{0};
  std::atomic<int64_t> seg_bytes{0};
  int64_t seg_limit = 0;     // rotation threshold (record boundaries)

  rabia::Mutex mu{"walkernel.mu"};
  rabia::CondVar cv;        // append lane -> flush thread
  rabia::CondVar cv_done;   // flush thread -> wal_sync waiters
  std::vector<uint8_t> stage RABIA_GUARDED_BY(mu);  // records to flush
  uint64_t staged_lsn RABIA_GUARDED_BY(mu) = 0;  // last staged record
  uint64_t flushed_lsn RABIA_GUARDED_BY(mu) = 0;  // last record written
  std::atomic<uint64_t> durable_lsn{0};
  std::atomic<int32_t> io_error{0};
  bool stop_req RABIA_GUARDED_BY(mu) = false;

  // vote-barrier state (native-runtime lane): barrier[s] is the first
  // slot NOT yet covered by a durable barrier record. The vector LENGTH
  // is fixed at create time — bounds checks read the immutable
  // n_shards; the slots are guarded.
  std::vector<int64_t> barrier RABIA_GUARDED_BY(mu);
  int64_t n_shards = 1;
  int64_t stride = 16;

  std::thread th;
  bool started = false;  // control-plane thread only (start/stop/destroy)
  int event_fd = -1;

  // counter block: multi-writer (append lane under mu, flush thread
  // without) — relaxed atomics, read zero-copy as plain u64s by the
  // Python scrape path (the RKC torn-read contract)
  std::atomic<uint64_t> ctrs[WLC_COUNT];
  static_assert(sizeof(std::atomic<uint64_t>) == sizeof(uint64_t),
                "counter block must read as a plain uint64 array");
  void bump(int i, uint64_t n = 1) {
    ctrs[i].fetch_add(n, std::memory_order_relaxed);
  }
  uint64_t hist[WLH_STRIDE];  // fsync latency; flush-thread-owned
};

// identical bucket math to runtime.cpp rth_observe: the Python exporter
// merges every native histogram row over ONE bound table (SLO_BUCKETS)
static void hist_observe(WalCtx* c, uint64_t ns) {
  uint64_t* h = c->hist;
  int32_t idx = 0;
  if (ns >= (1ull << WLH_MIN_EXP)) {
    const int32_t exp = 63 - __builtin_clzll(ns);
    const int32_t sub =
        (int32_t)((ns >> (exp - WLH_SUB_BITS)) & ((1 << WLH_SUB_BITS) - 1));
    idx = ((exp - WLH_MIN_EXP) << WLH_SUB_BITS) + sub;
    if (idx >= WLH_BUCKETS) idx = WLH_BUCKETS - 1;
  }
  h[idx]++;
  h[WLH_BUCKETS]++;        // count
  h[WLH_BUCKETS + 1] += ns;  // sum
}

// ---------------------------------------------------------------------------
// segment management (flush-thread only after start; create-time before)
// ---------------------------------------------------------------------------

static bool seg_open(WalCtx* c, uint64_t index, uint64_t base_lsn) {
  char name[64];
  snprintf(name, sizeof(name), "wal-%08llu.seg", (unsigned long long)index);
  std::string path = c->dir + "/" + name;
  int fd = open(path.c_str(), O_CREAT | O_TRUNC | O_WRONLY | O_CLOEXEC, 0644);
  if (fd < 0) return false;
  uint8_t head[WAL_HEADER];
  memcpy(head, &WAL_MAGIC, 4);
  memcpy(head + 4, &WAL_VERSION, 4);
  memcpy(head + 8, &index, 8);
  memcpy(head + 16, &base_lsn, 8);
  if (write(fd, head, WAL_HEADER) != WAL_HEADER) {
    close(fd);
    return false;
  }
  // header durable before any record can land after it; the directory
  // fsync makes the file's existence durable
  if (fsync(fd) != 0) {
    close(fd);
    return false;
  }
  if (c->dir_fd >= 0) fsync(c->dir_fd);
  if (c->seg_fd >= 0) {
    // records written to the OLD segment earlier in this flush batch
    // must be durable before the watermark can cover them — fsync
    // before the fd goes away (close() does not sync)
    fsync(c->seg_fd);
    close(c->seg_fd);
  }
  c->seg_fd = fd;
  c->seg_index.store(index, std::memory_order_relaxed);
  c->seg_bytes.store(WAL_HEADER, std::memory_order_relaxed);
  return true;
}

// write one span, rotating on record boundaries exactly where the Python
// twin would (deterministic in the record sequence, not the flush timing)
static bool flush_batch(WalCtx* c, const uint8_t* buf, int64_t len,
                        uint64_t first_lsn, uint64_t last_lsn) {
  int64_t at = 0;
  uint64_t lsn = first_lsn;
  while (at < len) {
    // find the largest run of whole records that fits the open segment
    int64_t run = 0;
    uint64_t run_recs = 0;
    const int64_t seg_bytes = c->seg_bytes.load(std::memory_order_relaxed);
    while (at + run < len) {
      uint32_t plen;
      memcpy(&plen, buf + at + run, 4);
      const int64_t frame = 8 + (int64_t)plen;
      if (run > 0 && seg_bytes + run + frame > c->seg_limit) break;
      // a first record never fits? it goes in alone (oversized records
      // own a segment; rotation below handles the boundary)
      if (run == 0 && seg_bytes > WAL_HEADER &&
          seg_bytes + frame > c->seg_limit)
        break;
      run += frame;
      run_recs++;
    }
    if (run == 0) {
      // rotation required before this record
      uint64_t next = c->seg_index.load(std::memory_order_relaxed) + 1;
      if (!seg_open(c, next, lsn)) return false;
      c->bump(WLC_ROTATIONS);
      continue;
    }
    int64_t done = 0;
    while (done < run) {
      ssize_t w = write(c->seg_fd, buf + at + done, (size_t)(run - done));
      if (w < 0) {
        if (errno == EINTR) continue;
        return false;
      }
      done += w;
    }
    c->seg_bytes.fetch_add(run, std::memory_order_relaxed);
    c->bump(WLC_FLUSH_BYTES, (uint64_t)run);
    at += run;
    lsn += run_recs;
  }
  (void)last_lsn;
  return true;
}

static void wal_loop(WalCtx* c) {
  std::vector<uint8_t> local;
  for (;;) {
    uint64_t target;
    uint64_t first;
    {
      rabia::MutexLock lk(c->mu);
      while (c->stage.empty() && !c->stop_req) c->cv.wait(lk);
      if (c->stage.empty() && c->stop_req) break;
      local.clear();
      local.swap(c->stage);
      first = c->flushed_lsn + 1;
      target = c->staged_lsn;
      c->flushed_lsn = target;
    }
    c->bump(WLC_FLUSHES);
    bool ok = c->io_error.load(std::memory_order_relaxed) == 0;
    if (ok)
      ok = flush_batch(c, local.data(), (int64_t)local.size(), first, target);
    if (ok) {
      const uint64_t t0 = mono_ns();
      ok = fsync(c->seg_fd) == 0;
      const uint64_t dt = mono_ns() - t0;
      c->bump(WLC_FSYNCS);
      c->bump(WLC_FSYNC_NS, dt);
      c->bump(WLC_GROUP_RECORDS, target - first + 1);
      hist_observe(c, dt);
    }
    {
      // publish under mu: wal_sync's waiter evaluates its predicate
      // while holding mu, so a store outside the lock could land
      // between the check and the block — a lost wakeup that stalls
      // the waiter until its full timeout
      rabia::MutexLock lk(c->mu);
      if (!ok) {
        // a durability failure must never be reported as durable: the
        // watermark freezes, callers waiting on it see the wedge via
        // wal_io_error and fail loudly instead of acking lost writes
        c->io_error.store(1, std::memory_order_release);
        c->bump(WLC_IO_ERRORS);
      } else {
        c->durable_lsn.store(target, std::memory_order_release);
      }
    }
    if (c->event_fd >= 0) {
      uint64_t one = 1;
      (void)!write(c->event_fd, &one, 8);
    }
    c->cv_done.notify_all();
  }
}

// ---------------------------------------------------------------------------
// lifecycle
// ---------------------------------------------------------------------------

// start_lsn / start_segment come from the Python recovery scan: the new
// writer continues the log in a FRESH segment (start_segment) whose first
// record will be start_lsn + 1. seg_limit is the rotation threshold in
// bytes; n_shards sizes the vote-barrier vector; stride amortizes it.
void* wal_create(const char* dir, int64_t seg_limit, int64_t n_shards,
                 int64_t stride, uint64_t start_lsn,
                 uint64_t start_segment) {
  WalCtx* c = new (std::nothrow) WalCtx();
  if (!c) return nullptr;
  c->dir = dir;
  // clamp identically to the Python twin (max(limit, header+64)) — the
  // rotation threshold is part of the byte-parity contract
  c->seg_limit = seg_limit > WAL_HEADER + 64 ? seg_limit : WAL_HEADER + 64;
  c->stride = stride > 0 ? stride : 16;
  c->n_shards = n_shards > 0 ? n_shards : 1;
  for (auto& ctr : c->ctrs) ctr.store(0, std::memory_order_relaxed);
  memset(c->hist, 0, sizeof(c->hist));
  c->dir_fd = open(dir, O_RDONLY | O_DIRECTORY | O_CLOEXEC);
  if (c->dir_fd < 0) {
    delete c;
    return nullptr;
  }
  {
    // no other thread exists yet; the lock is for the analysis (and
    // free — uncontended)
    rabia::MutexLock lk(c->mu);
    c->barrier.assign((size_t)c->n_shards, 0);
    c->staged_lsn = c->flushed_lsn = start_lsn;
  }
  c->durable_lsn.store(start_lsn, std::memory_order_release);
  if (!seg_open(c, start_segment, start_lsn + 1)) {
    close(c->dir_fd);
    delete c;
    return nullptr;
  }
  c->event_fd = eventfd(0, EFD_NONBLOCK | EFD_CLOEXEC);
  return c;
}

int32_t wal_start(void* h) {
  WalCtx* c = (WalCtx*)h;
  if (c->started) return 0;
  c->started = true;
  c->th = std::thread([c] { wal_loop(c); });
  return 0;
}

// flush everything staged, then stop the thread. Records staged before
// this call are durable when it returns (clean-shutdown contract).
void wal_stop(void* h) {
  WalCtx* c = (WalCtx*)h;
  if (!c->started) return;
  {
    rabia::MutexLock lk(c->mu);
    c->stop_req = true;
  }
  c->cv.notify_all();
  if (c->th.joinable()) c->th.join();
  c->started = false;
}

void wal_destroy(void* h) {
  WalCtx* c = (WalCtx*)h;
  if (!c) return;
  wal_stop(c);
  if (c->seg_fd >= 0) close(c->seg_fd);
  if (c->dir_fd >= 0) close(c->dir_fd);
  if (c->event_fd >= 0) close(c->event_fd);
  delete c;
}

// ---------------------------------------------------------------------------
// the append lane (any thread; one mutex-protected buffer append)
// ---------------------------------------------------------------------------

// Stage one record; returns its LSN (>= 1), or -1 on a wedged log.
// Durability is NOT implied: compare wal_durable() or wait on the
// eventfd. The payload's leading kind byte is counted per-kind.
int64_t wal_append(void* h, const uint8_t* payload, int64_t len) {
  WalCtx* c = (WalCtx*)h;
  if (!c || len <= 0) return -1;
  if (c->io_error.load(std::memory_order_acquire)) return -1;
  const uint32_t plen = (uint32_t)len;
  const uint32_t crc = (uint32_t)crc32(0, payload, (uInt)len);
  uint64_t lsn;
  {
    rabia::MutexLock lk(c->mu);
    size_t w = c->stage.size();
    c->stage.resize(w + 8 + (size_t)len);
    memcpy(c->stage.data() + w, &plen, 4);
    memcpy(c->stage.data() + w + 4, &crc, 4);
    memcpy(c->stage.data() + w + 8, payload, (size_t)len);
    lsn = ++c->staged_lsn;
    c->bump(WLC_APPENDS);
    c->bump(WLC_APPEND_BYTES, (uint64_t)len + 8);
    switch (payload[0]) {
      case 1: c->bump(WLC_WAVES); break;
      case 2: c->bump(WLC_BARRIERS); break;
      case 3: c->bump(WLC_FRONTIERS); break;
      case 4: c->bump(WLC_LEDGERS); break;
      default: break;
    }
  }
  c->cv.notify_one();
  return (int64_t)lsn;
}

uint64_t wal_durable(void* h) {
  return ((WalCtx*)h)->durable_lsn.load(std::memory_order_acquire);
}

uint64_t wal_staged(void* h) {
  WalCtx* c = (WalCtx*)h;
  rabia::MutexLock lk(c->mu);
  return c->staged_lsn;
}

int32_t wal_io_error(void* h) {
  return ((WalCtx*)h)->io_error.load(std::memory_order_acquire);
}

int wal_event_fd(void* h) { return ((WalCtx*)h)->event_fd; }

// Block until everything staged so far is durable (shutdown, tests,
// checkpoint barriers). Returns 0 ok, -1 timeout/wedge.
int32_t wal_sync(void* h, double timeout_s) {
  WalCtx* c = (WalCtx*)h;
  uint64_t target;
  {
    rabia::MutexLock lk(c->mu);
    target = c->staged_lsn;
  }
  c->cv.notify_one();
  const timespec dl = rabia::CondVar::deadline_in(timeout_s);
  rabia::MutexLock lk(c->mu);
  for (;;) {
    if (c->io_error.load(std::memory_order_acquire)) return -1;
    if (c->durable_lsn.load(std::memory_order_acquire) >= target) return 0;
    if (!c->cv_done.wait_until(lk, dl)) {
      // timed out: one last look (the flush may have published while we
      // were timing out)
      if (c->io_error.load(std::memory_order_acquire)) return -1;
      return c->durable_lsn.load(std::memory_order_acquire) >= target
                 ? 0
                 : -1;
    }
  }
}

// ---------------------------------------------------------------------------
// the vote-barrier lane (native-runtime write-ahead)
// ---------------------------------------------------------------------------

// Returns 0 when `slot` on `shard` is already covered by a staged
// barrier record (the common, stride-amortized case). Otherwise advances
// the barrier to slot + stride, stages a kind-2 record carrying the FULL
// barrier vector (byte format identical to the Python twin's
// encode_barrier), and returns the record's LSN — the caller must not
// let a vote for the slot reach the wire until wal_durable() >= that.
int64_t wal_barrier_covered(void* h, int64_t shard, int64_t slot) {
  WalCtx* c = (WalCtx*)h;
  if (!c || shard < 0 || shard >= c->n_shards) return 0;
  {
    rabia::MutexLock lk(c->mu);
    if (slot < c->barrier[(size_t)shard]) return 0;
    c->barrier[(size_t)shard] = slot + c->stride;
  }
  // encode outside the lock; wal_append re-locks (cheap, uncontended)
  const uint32_t n = (uint32_t)c->n_shards;
  std::vector<uint8_t> payload(5 + 8 * (size_t)n);
  payload[0] = 2;  // K_BARRIER
  memcpy(payload.data() + 1, &n, 4);
  {
    rabia::MutexLock lk(c->mu);
    memcpy(payload.data() + 5, c->barrier.data(), 8 * (size_t)n);
  }
  c->bump(WLC_BARRIER_WAITS);
  return wal_append(h, payload.data(), (int64_t)payload.size());
}

void wal_set_barrier(void* h, const int64_t* vec, int64_t n) {
  WalCtx* c = (WalCtx*)h;
  rabia::MutexLock lk(c->mu);
  for (int64_t i = 0; i < n && i < c->n_shards; i++)
    c->barrier[(size_t)i] = vec[i];
}

void wal_get_barrier(void* h, int64_t* out, int64_t n) {
  WalCtx* c = (WalCtx*)h;
  rabia::MutexLock lk(c->mu);
  for (int64_t i = 0; i < n && i < c->n_shards; i++)
    out[i] = c->barrier[(size_t)i];
}

// ---------------------------------------------------------------------------
// observability
// ---------------------------------------------------------------------------

int32_t wal_counters_version() { return WAL_COUNTERS_VERSION; }
int32_t wal_counters_count() { return WLC_COUNT; }
void* wal_counters(void* h) { return ((WalCtx*)h)->ctrs; }  // atomics read
                                                            // as plain u64s

int32_t wal_hist_version() { return WLH_VERSION; }
int32_t wal_hist_buckets() { return WLH_BUCKETS; }
int32_t wal_hist_sub_bits() { return WLH_SUB_BITS; }
int32_t wal_hist_min_exp() { return WLH_MIN_EXP; }
void* wal_hist(void* h) { return ((WalCtx*)h)->hist; }

int64_t wal_segment_index(void* h) {
  return (int64_t)((WalCtx*)h)->seg_index.load(std::memory_order_relaxed);
}
int64_t wal_segment_bytes(void* h) {
  return ((WalCtx*)h)->seg_bytes.load(std::memory_order_relaxed);
}

}  // extern "C"
