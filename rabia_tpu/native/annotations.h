// Thread-safety annotations + annotated lock primitives for the native
// kernels (docs/STATIC_ANALYSIS.md).
//
// Three layers, all in this one header so every kernel shares one
// vocabulary:
//
// 1. RABIA_* macros wrapping clang's -Wthread-safety attributes
//    (CAPABILITY / GUARDED_BY / REQUIRES / ...). No-ops on gcc, so the
//    default g++ build is unchanged; the CI thread-safety cell compiles
//    every kernel with clang++ -Werror=thread-safety, turning the
//    ownership contracts that used to live in comments (single-writer-
//    while-RUNNING, sk_plane_lock brackets, the WAL flush-thread
//    handoff) into compile failures. This is the repo's analog of the
//    reference's compiler-enforced Send/Sync (PAPER.md §1).
//
// 2. rabia::Mutex / rabia::RecursiveMutex / rabia::CondVar /
//    rabia::MutexLock — thin annotated wrappers over std::mutex /
//    std::recursive_mutex / pthread_cond_t. Two deliberate choices:
//      - the capability attribute lives on OUR type (libstdc++'s
//        std::mutex carries no annotations), so GUARDED_BY actually
//        binds;
//      - CondVar waits via pthread_cond_timedwait on a CLOCK_MONOTONIC
//        condattr instead of libstdc++'s wait_for (which compiles to
//        pthread_cond_clockwait — NOT intercepted by gcc-10's libtsan,
//        the root cause of the old TSan gate's false "double lock of a
//        mutex" on this container's glibc and therefore of its
//        environmental SKIP). Every wait here goes through an
//        interceptable primitive, which is what made the TSan gate
//        enforceable again (native/stress/, scripts/sanitize_gate.py).
//
// 3. A debug lock-order checker, compiled in under
//    -DRABIA_NATIVE_DEBUG=1 (build.py's debug flavor, forced by the
//    RABIA_NATIVE_DEBUG=1 env): every Mutex carries a name; acquires
//    record per-thread held-lock stacks and a global name-pair edge set,
//    and an acquisition that inverts a previously seen order (or
//    re-acquires a non-recursive Mutex already held by the thread)
//    aborts with both stacks' names. Running the fuzz/conformance gates
//    against debug-flavor kernels turns the whole test suite into a
//    lock-order prover. Zero cost in regular builds (the hooks compile
//    away).

#ifndef RABIA_NATIVE_ANNOTATIONS_H_
#define RABIA_NATIVE_ANNOTATIONS_H_

#include <errno.h>
#include <pthread.h>
#include <time.h>

#include <mutex>

#if defined(__clang__)
#define RABIA_TSA(x) __attribute__((x))
#else
#define RABIA_TSA(x)  // no-op on gcc: annotations are clang-only
#endif

#define RABIA_CAPABILITY(x) RABIA_TSA(capability(x))
#define RABIA_SCOPED_CAPABILITY RABIA_TSA(scoped_lockable)
#define RABIA_GUARDED_BY(x) RABIA_TSA(guarded_by(x))
#define RABIA_PT_GUARDED_BY(x) RABIA_TSA(pt_guarded_by(x))
#define RABIA_ACQUIRE(...) RABIA_TSA(acquire_capability(__VA_ARGS__))
#define RABIA_RELEASE(...) RABIA_TSA(release_capability(__VA_ARGS__))
#define RABIA_TRY_ACQUIRE(...) RABIA_TSA(try_acquire_capability(__VA_ARGS__))
#define RABIA_REQUIRES(...) RABIA_TSA(requires_capability(__VA_ARGS__))
#define RABIA_EXCLUDES(...) RABIA_TSA(locks_excluded(__VA_ARGS__))
#define RABIA_ACQUIRED_BEFORE(...) RABIA_TSA(acquired_before(__VA_ARGS__))
#define RABIA_ACQUIRED_AFTER(...) RABIA_TSA(acquired_after(__VA_ARGS__))
#define RABIA_RETURN_CAPABILITY(x) RABIA_TSA(lock_returned(x))
#define RABIA_NO_TSA RABIA_TSA(no_thread_safety_analysis)

// --- debug lock-order checker hooks -----------------------------------------

#if defined(RABIA_NATIVE_DEBUG) && RABIA_NATIVE_DEBUG

#include <stdio.h>
#include <stdlib.h>

#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace rabia_lockorder {

// One edge "name_a held while acquiring name_b", keyed by NAME (the
// class of lock, e.g. "transport.mu"), not by instance: the ordering
// discipline is a property of the code paths, and instance addresses
// recycle. Self-edges (same name, DIFFERENT instance nested) are
// reported too — nesting two peers' same-class locks has no defined
// order and is exactly the two-transport deadlock shape.
struct Held {
  const void* m;
  const char* name;
  bool recursive;
};

inline std::vector<Held>& held_stack() {
  thread_local std::vector<Held> stack;
  return stack;
}

inline std::mutex& reg_mu() {
  static std::mutex mu;  // raw std::mutex: the checker must not recurse
  return mu;
}

// Acquisition-order DIGRAPH: adjacency by lock name. Kept as a graph
// (not a pair set) so cycles of any length are caught — a 3-path
// A->B, B->C, C->A deadlock has no reversed PAIR to match, but C->A
// closes a cycle the reachability walk below sees.
inline std::unordered_map<std::string, std::unordered_set<std::string>>&
edges() {
  static std::unordered_map<std::string, std::unordered_set<std::string>>
      e;
  return e;
}

// Is `to` reachable from `from` over recorded edges? (DFS; graphs here
// are a handful of lock classes, cost is irrelevant.)
inline bool reaches(const std::string& from, const std::string& to) {
  if (from == to) return true;
  std::vector<std::string> work{from};
  std::unordered_set<std::string> seen{from};
  auto& e = edges();
  while (!work.empty()) {
    std::string cur = work.back();
    work.pop_back();
    auto it = e.find(cur);
    if (it == e.end()) continue;
    for (const std::string& nxt : it->second) {
      if (nxt == to) return true;
      if (seen.insert(nxt).second) work.push_back(nxt);
    }
  }
  return false;
}

inline void fail(const char* what, const char* held, const char* acq) {
  fprintf(stderr,
          "rabia lockorder: %s: holding \"%s\" while acquiring \"%s\" "
          "(aborting; run with the regular build to ignore)\n",
          what, held, acq);
  fflush(stderr);
  abort();
}

// Runs BEFORE the underlying pthread lock: a same-thread re-acquire of
// a non-recursive mutex must ABORT with a report, not deadlock
// silently inside pthread_mutex_lock; an order inversion is likewise
// best reported before this thread parks on the about-to-deadlock
// acquire.
inline void prelock(const void* m, const char* name, bool recursive) {
  auto& stack = held_stack();
  for (const Held& h : stack) {
    if (h.m == m) {
      if (recursive) return;  // recursive re-acquire: no new edges
      fail("double lock", h.name, name);
    }
  }
  std::lock_guard<std::mutex> lk(reg_mu());
  for (const Held& h : stack) {
    if (h.m == m) continue;
    // adding edge h.name -> name: if name already REACHES h.name the
    // new edge closes a cycle (length 2 = classic pairwise inversion,
    // length >= 3 = the multi-thread deadlock a pair check misses)
    if (reaches(name, h.name)) fail("order inversion", h.name, name);
    edges()[h.name].insert(name);
  }
}

inline void acquired(const void* m, const char* name, bool recursive) {
  held_stack().push_back(Held{m, name, recursive});
}

inline void released(const void* m) {
  auto& stack = held_stack();
  // released in any order: erase the LAST matching entry
  for (size_t i = stack.size(); i-- > 0;) {
    if (stack[i].m == m) {
      stack.erase(stack.begin() + (ptrdiff_t)i);
      return;
    }
  }
}

}  // namespace rabia_lockorder

#define RABIA_LOCKORDER_PRELOCK(m, name, rec) \
  ::rabia_lockorder::prelock((m), (name), (rec))
#define RABIA_LOCKORDER_ACQUIRED(m, name, rec) \
  ::rabia_lockorder::acquired((m), (name), (rec))
#define RABIA_LOCKORDER_RELEASED(m) ::rabia_lockorder::released((m))

#else  // !RABIA_NATIVE_DEBUG

#define RABIA_LOCKORDER_PRELOCK(m, name, rec) ((void)0)
#define RABIA_LOCKORDER_ACQUIRED(m, name, rec) ((void)0)
#define RABIA_LOCKORDER_RELEASED(m) ((void)0)

#endif  // RABIA_NATIVE_DEBUG

namespace rabia {

// Annotated mutex. The name is the lock-order class (debug builds) and
// the human handle in checker reports; keep it "<kernel>.<field>".
class RABIA_CAPABILITY("mutex") Mutex {
 public:
  explicit Mutex(const char* name = "mutex") : name_(name) {}
  Mutex(const Mutex&) = delete;
  Mutex& operator=(const Mutex&) = delete;

  void lock() RABIA_ACQUIRE() {
    RABIA_LOCKORDER_PRELOCK(this, name_, false);
    mu_.lock();
    RABIA_LOCKORDER_ACQUIRED(this, name_, false);
  }
  void unlock() RABIA_RELEASE() {
    RABIA_LOCKORDER_RELEASED(this);
    mu_.unlock();
  }
  bool try_lock() RABIA_TRY_ACQUIRE(true) {
    RABIA_LOCKORDER_PRELOCK(this, name_, false);
    if (!mu_.try_lock()) return false;
    RABIA_LOCKORDER_ACQUIRED(this, name_, false);
    return true;
  }
  pthread_mutex_t* native_handle() { return mu_.native_handle(); }
  const char* name() const { return name_; }

 private:
  std::mutex mu_;
  const char* name_;
};

// Annotated recursive mutex (statekernel's plane lock: a locked reader
// may call entry points that lock internally).
class RABIA_CAPABILITY("mutex") RecursiveMutex {
 public:
  explicit RecursiveMutex(const char* name = "recursive_mutex")
      : name_(name) {}
  RecursiveMutex(const RecursiveMutex&) = delete;
  RecursiveMutex& operator=(const RecursiveMutex&) = delete;

  void lock() RABIA_ACQUIRE() {
    RABIA_LOCKORDER_PRELOCK(this, name_, true);
    mu_.lock();
    RABIA_LOCKORDER_ACQUIRED(this, name_, true);
  }
  void unlock() RABIA_RELEASE() {
    RABIA_LOCKORDER_RELEASED(this);
    mu_.unlock();
  }
  const char* name() const { return name_; }

 private:
  std::recursive_mutex mu_;
  const char* name_;
};

// Scoped guard (std::lock_guard twin the analysis understands).
class RABIA_SCOPED_CAPABILITY MutexLock {
 public:
  explicit MutexLock(Mutex& mu) RABIA_ACQUIRE(mu) : mu_(mu) { mu_.lock(); }
  ~MutexLock() RABIA_RELEASE() { mu_.unlock(); }
  MutexLock(const MutexLock&) = delete;
  MutexLock& operator=(const MutexLock&) = delete;

  Mutex& mutex() { return mu_; }

 private:
  friend class CondVar;
  Mutex& mu_;
};

class RABIA_SCOPED_CAPABILITY RecursiveLock {
 public:
  explicit RecursiveLock(RecursiveMutex& mu) RABIA_ACQUIRE(mu) : mu_(mu) {
    mu_.lock();
  }
  ~RecursiveLock() RABIA_RELEASE() { mu_.unlock(); }
  RecursiveLock(const RecursiveLock&) = delete;
  RecursiveLock& operator=(const RecursiveLock&) = delete;

 private:
  RecursiveMutex& mu_;
};

// Condition variable over rabia::Mutex. Deliberately pthread-level with
// a CLOCK_MONOTONIC condattr: timed waits go through
// pthread_cond_timedwait (intercepted by every libtsan we target),
// never pthread_cond_clockwait (not intercepted by gcc-10's — see the
// header comment). Waits keep the capability held from the analysis'
// point of view, matching clang's std::condition_variable model.
class CondVar {
 public:
  CondVar() {
    pthread_condattr_t attr;
    pthread_condattr_init(&attr);
    pthread_condattr_setclock(&attr, CLOCK_MONOTONIC);
    pthread_cond_init(&cv_, &attr);
    pthread_condattr_destroy(&attr);
  }
  ~CondVar() { pthread_cond_destroy(&cv_); }
  CondVar(const CondVar&) = delete;
  CondVar& operator=(const CondVar&) = delete;

  void notify_one() { pthread_cond_signal(&cv_); }
  void notify_all() { pthread_cond_broadcast(&cv_); }

  void wait(MutexLock& lk) { pthread_cond_wait(&cv_, handle(lk)); }

  // Deadline helpers for explicit wait loops. Predicate lambdas are
  // deliberately NOT offered: clang's thread-safety analysis treats a
  // lambda body as an unannotated function, so guarded-field reads
  // inside one would need NO_TSA escapes — an explicit
  //   timespec dl = CondVar::deadline_in(seconds);
  //   while (<predicate on guarded fields>)
  //     if (!cv.wait_until(lk, dl)) break;
  // loop keeps every guarded access visible to the analysis.
  static timespec deadline_in(double seconds) {
    timespec dl;
    clock_gettime(CLOCK_MONOTONIC, &dl);
    const long long add_ns = seconds > 0 ? (long long)(seconds * 1e9) : 0;
    long long tgt =
        (long long)dl.tv_sec * 1000000000ll + dl.tv_nsec + add_ns;
    dl.tv_sec = (time_t)(tgt / 1000000000ll);
    dl.tv_nsec = (long)(tgt % 1000000000ll);
    return dl;
  }

  // Absolute-deadline wait; returns false on timeout (spurious wakes
  // return true — the caller's loop re-checks its predicate).
  bool wait_until(MutexLock& lk, const timespec& deadline) {
    return pthread_cond_timedwait(&cv_, handle(lk), &deadline) != ETIMEDOUT;
  }

 private:
  // pthread-level wait releases + reacquires the mutex without the
  // wrapper hooks seeing it: the thread's held set is unchanged at
  // return, so the lock-order stack stays accurate without bracketing.
  static pthread_mutex_t* handle(MutexLock& lk) {
    return lk.mu_.native_handle();
  }
  pthread_cond_t cv_;
};

// Capability with no runtime state, modelling a THREAD ROLE (runtime.cpp
// io-thread ownership: "only the io/tick thread touches this while
// RUNNING"). Functions that must run on the role's thread are annotated
// RABIA_REQUIRES(role); the thread entry acquires it via the assert
// helper (a no-op at runtime — the handshake that actually transfers
// ownership is rtm_pause/rtm_resume, stress-checked under TSan).
class RABIA_CAPABILITY("role") ThreadRole {
 public:
  explicit ThreadRole(const char* name = "role") : name_(name) {}
  // assert_held: tells the analysis this thread holds the role without
  // emitting code (clang models it via assert_capability).
  void assert_held() const RABIA_TSA(assert_capability(this)) {}
  const char* name() const { return name_; }

 private:
  const char* name_;
};

}  // namespace rabia

#endif  // RABIA_NATIVE_ANNOTATIONS_H_
