"""Native (C++) components: the TCP transport data plane.

Built lazily with g++ into a shared library cached next to the source; no
pip/pybind dependency — the Python side binds via ctypes
(:mod:`rabia_tpu.net.tcp`).
"""

from rabia_tpu.native.build import lib_path, load_library

__all__ = ["lib_path", "load_library"]
