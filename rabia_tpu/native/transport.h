// C ABI of the native transport data plane — the single source of truth
// for every consumer: transport.cpp includes it so definitions are
// compiler-checked against these declarations, the TSan stress harness
// links against it, and rabia_tpu/native/build.py mirrors it in ctypes.
#pragma once

#include <stdint.h>

extern "C" {

// Returns an opaque Transport handle (nullptr on failure); writes the
// actually-bound port (for port 0 requests).
void* rt_create(const uint8_t self_id[16], const char* host, uint16_t port,
                uint16_t* actual_port);
int rt_add_peer(void* h, const uint8_t id[16], const char* host,
                uint16_t port);
int rt_remove_peer(void* h, const uint8_t id[16]);
// Chaos shaping layer: per-peer outbound delay/jitter (us) + drop
// probability, applied by the io thread at drain time. delay=jitter=0
// and drop<=0 clears the peer; seed != 0 reseeds the drop RNG.
int rt_set_shaping(void* h, const uint8_t id[16], uint32_t delay_us,
                   uint32_t jitter_us, double drop, uint64_t seed);
int rt_clear_shaping(void* h);
// 0 ok, -1 unknown/unconnected peer, -2 frame too large.
int rt_send(void* h, const uint8_t id[16], const uint8_t* data, uint32_t len);
// Returns the number of peers reached.
int rt_broadcast(void* h, const uint8_t* data, uint32_t len);
// Broadcast a batch of [u32 record_len][frame] records (the native tick's
// outbound buffer) under one staging lock + one io kick. Returns the
// number of frames staged, -2 on a malformed/oversized record.
int rt_broadcast_frames(void* h, const uint8_t* buf, int64_t len);
// Blocks up to timeout_ms; >=0 frame length (truncated to buf_cap),
// -3 timeout, -1 closed.
int rt_recv(void* h, uint8_t sender_out[16], uint8_t* buf, uint32_t buf_cap,
            int timeout_ms);
// Zero-copy receive: borrow the next inbound frame straight from the
// arena. Returns a token >= 0 (frame at *data_out/*len_out until
// rt_recv_release), -3 timeout, -1 closed.
int64_t rt_recv_borrow(void* h, uint8_t sender_out[16],
                       const uint8_t** data_out, uint32_t* len_out,
                       int timeout_ms);
void rt_recv_release(void* h, int64_t token);
// Thread-per-shard-group routing: install (ngroups >= 1) or clear
// (ngroups == 0) per-group inbound-frame classification. classify_fn is
// `uint64_t (*)(void* arg, const uint8_t* data, uint32_t len)` returning
// a group bitmask (0 = group 0). Call only while no thread is inside a
// _group entry point (install before rtm_start, clear after rtm_stop).
int rt_set_groups(void* h, int32_t ngroups, void* classify_fn, void* arg);
// Zero-copy receive from one shard group's inbox; the returned token is
// group-encoded and releases through rt_recv_release as usual.
int64_t rt_recv_borrow_group(void* h, int32_t group, uint8_t sender_out[16],
                             const uint8_t** data_out, uint32_t* len_out,
                             int timeout_ms);
// Writes up to cap established peer ids (16B each); returns the count.
int rt_connected(void* h, uint8_t* ids_out, int cap);
uint16_t rt_port(void* h);
uint64_t rt_dropped(void* h);
void rt_pool_stats(void* h, uint64_t* hits, uint64_t* misses);
// Outbound-frame arena counters alone (the out-pool), separate from the
// merged rt_pool_stats view.
void rt_out_pool_stats(void* h, uint64_t* hits, uint64_t* misses);
// Versioned, append-only observability counter block: a borrowed pointer
// to rt_counters_count() uint64 cells, valid until rt_close. Indices are
// ABI (RTC_* in transport.cpp); new counters append and bump the
// version. Cells are relaxed atomics — reads are monotonic, not a
// consistent snapshot.
int32_t rt_counters_version(void);
int32_t rt_counters_count(void);
const uint64_t* rt_counters(void* h);
// Flight recorder: one fixed-size record per frame in/out (layout is the
// versioned TfEvent ABI in transport.cpp; the Python twin is
// rabia_tpu/net/tcp.TF_DTYPE). rt_flight_copy writes the most recent
// records into `out` (max_records * rt_flight_record_size() bytes) in
// chronological order and returns the count — a consistent snapshot
// taken under the io mutex.
int32_t rt_flight_version(void);
int32_t rt_flight_record_size(void);
int64_t rt_flight_copy(void* h, uint8_t* out, int64_t max_records);
// Wake any thread blocked in rt_recv / rt_recv_borrow WITHOUT a frame
// (the wait returns -3 as on timeout). The native runtime thread sleeps
// on the transport inbox; the Python control plane kicks it here after
// staging a command so a submission never waits out the recv timeout.
void rt_inbox_kick(void* h);
// Stop the io loop and unblock rt_recv callers WITHOUT freeing the
// handle; call before rt_close when a reader thread may be inside
// rt_recv.
void rt_stop(void* h);
void rt_close(void* h);

}  // extern "C"
