// Runtime stress: the io/tick thread vs the rtm_pause/resume handshake,
// the SPSC command/event rings, and the control-plane observability
// reads — over a REAL transport pair (the loop parks in rt_recv_borrow
// exactly as in production).
//
// The consensus kernels are STUBBED at the fn-pointer boundary (rk_tick
// reports nothing to do, rk_ingest classifies frames by a type byte):
// this program's target is the runtime's OWN shared state, not the
// consensus math the conformance fuzzer owns. Seams:
//   - single-writer-while-RUNNING: the control thread rtm_pause()s,
//     waits for PAUSED, mutates the shared consensus arrays
//     (next_slot/applied/tainted/last_progress), resumes — while the
//     peer keeps blasting frames. The round-13 release/acquire fix on
//     pause_req is exactly what TSan checks here;
//   - the cmd ring (control producer -> io consumer) under no-op
//     CMD_ADVANCE records, and the ev ring (io producer -> control
//     consumer) under escalated-frame traffic;
//   - rtm_inbox kicks racing the loop's timed recv waits;
//   - advisory counter/stage/flight reads while the loop writes them.

#include <vector>

#include "stress_common.h"
#include "transport.h"

extern "C" {
void* rtm_create(const int64_t* dims, const int64_t* ptrs,
                 const int64_t* fns, const uint8_t* uuids,
                 const double* fparams);
int32_t rtm_start(void* ctx);
void rtm_stop(void* ctx);
void rtm_destroy(void* ctx);
int32_t rtm_state(void* ctx);
void rtm_pause(void* ctx);
void rtm_resume(void* ctx);
int rtm_event_fd(void* ctx);
int32_t rtm_cmd_push(void* ctx, const uint8_t* rec, int64_t len);
int64_t rtm_ev_drain(void* ctx, uint8_t* out, int64_t cap);
int32_t rtm_counters_count(void);
void* rtm_counters(void* ctx);
int32_t rtm_stages_count(void);
void* rtm_stages(void* ctx);
int32_t rtm_hist_stages(void);
int32_t rtm_hist_buckets(void);
void* rtm_hist(void* ctx);
int32_t rtm_flight_cap(void);
int32_t rtm_flight_record_size(void);
void* rtm_flight(void* ctx);
uint64_t rtm_flight_head(void* ctx);
}

// --- consensus-kernel stubs at the FN_* boundary ----------------------------

static const uint8_t kTypeNoop = 0x42;  // stub: natively consumed
// anything else (except MT_PROPOSE_BLOCK=10, unused here): escalated

extern "C" int32_t stub_rk_ingest(void*, const uint8_t* frame, int64_t len,
                                  int32_t, double) {
  if (len >= 2 && frame[1] == kTypeNoop) return 2;  // RK_NOOP
  return 0;                                         // RK_PY: escalate
}

extern "C" void stub_rk_tick(void*, double, uint8_t*, int64_t, int32_t,
                             const uint8_t*, const int32_t*,
                             const int8_t*, int64_t* res) {
  for (int i = 0; i < 8; i++) res[i] = 0;  // nothing staged/decided
}

extern "C" void stub_rk_retransmit(void*, double, double, uint8_t*, int64_t,
                                   int64_t* res) {
  if (res) res[0] = 0;
}

extern "C" int64_t stub_rk_drain_stale(void*, int64_t*, int64_t*, int64_t*,
                                       int64_t) {
  return 0;
}

static const int kS = 4;        // shards
static const int kDecRing = 64;

int main() {
  // transport pair: `a` belongs to the runtime, `b` is the peer blaster
  unsigned char id_a[16] = {0xAA};
  unsigned char id_b[16] = {0xBB};
  unsigned short pa = 0, pb = 0;
  void* a = rt_create(id_a, "127.0.0.1", 0, &pa);
  void* b = rt_create(id_b, "127.0.0.1", 0, &pb);
  if (!a || !b) {
    std::fprintf(stderr, "transport create failed\n");
    return 1;
  }
  rt_add_peer(a, id_b, "127.0.0.1", pb);
  rt_add_peer(b, id_a, "127.0.0.1", pa);
  for (int i = 0; i < 200; i++) {
    unsigned char ids[16 * 4];
    if (rt_connected(a, ids, 4) >= 1 && rt_connected(b, ids, 4) >= 1) break;
    stress::sleep_ms(10);
  }

  // shared consensus arrays (the control plane mutates these while
  // PAUSED — the single-writer handoff under test)
  std::vector<int64_t> next_slot(kS, 0), applied(kS, 0), votes_seen(kS, 0),
      tainted(kS, -1);
  std::vector<uint8_t> in_flight(kS, 0);
  std::vector<double> last_progress(kS, 0.0), opened_at(kS, 0.0);
  std::vector<int64_t> ring_slot((size_t)kS * kDecRing, -1);
  std::vector<int8_t> ring_val((size_t)kS * kDecRing, -1);
  std::vector<int32_t> kslot(kS, 0);
  std::vector<int8_t> kdecided(kS, -1);
  std::vector<uint8_t> kdone(kS, 0), knewly(kS, 0);
  uint8_t uuids[2 * 16];
  memcpy(uuids, id_a, 16);
  memcpy(uuids + 16, id_b, 16);

  const int64_t dims[11] = {kS, kS, /*R=*/2, /*me=*/0, kDecRing,
                            /*native_apply=*/0, 1 << 20, 1 << 20,
                            /*max_cmds=*/64, /*max_cmd_size=*/4096,
                            /*workers=*/1};
  const int64_t ptrs[17] = {
      /*rk_ctx*/ 1,  // opaque to the stubs
      (int64_t)a,
      /*sk_plane*/ 0,
      (int64_t)next_slot.data(), (int64_t)applied.data(),
      (int64_t)in_flight.data(), (int64_t)votes_seen.data(),
      (int64_t)tainted.data(), (int64_t)last_progress.data(),
      (int64_t)opened_at.data(), (int64_t)ring_slot.data(),
      (int64_t)ring_val.data(), (int64_t)kslot.data(),
      (int64_t)kdecided.data(), (int64_t)kdone.data(),
      (int64_t)knewly.data(), /*wal*/ 0};
  const int64_t fns[20] = {
      (int64_t)&rt_recv_borrow, (int64_t)&rt_recv_release,
      (int64_t)&rt_broadcast_frames, (int64_t)&rt_send,
      (int64_t)&stub_rk_ingest, (int64_t)&stub_rk_tick,
      (int64_t)&stub_rk_retransmit, (int64_t)&stub_rk_drain_stale,
      0, 0, 0, 0, 0,  // FN_SK_* (native_apply=0)
      0, 0, 0,        // FN_WAL_*
      0, 0, 0, 0};    // FN_RECV_BORROW_GROUP / FN_SK_*_LANE (workers=1)
  const double fparams[4] = {1.0, 30.0, 0.2, 0.05};

  void* rtm = rtm_create(dims, ptrs, fns, uuids, fparams);
  if (!rtm || rtm_start(rtm) != 0) {
    std::fprintf(stderr, "rtm create/start failed\n");
    return 1;
  }

  std::atomic<bool> stop{false};
  std::atomic<long> pauses{0}, ev_bytes{0};
  std::atomic<int> fail{0};

  // control thread: the runtime_bridge's roles — pause/mutate/resume
  // cycles, no-op command pushes, and the ev-ring drain (it is the ONE
  // ev consumer, as in production)
  std::thread control([&] {
    stress::Rng rng(3);
    std::vector<uint8_t> evbuf(1 << 18);
    uint8_t cmd[5] = {3, 0, 0, 0, 0};  // CMD_ADVANCE, count=0 (no-op)
    while (!stop.load()) {
      rtm_pause(rtm);
      const double t0 = stress::now_s();
      while (rtm_state(rtm) != 2 /*PAUSED*/) {
        if (stress::now_s() - t0 > 5.0) {
          fail.store(1);  // pause never acknowledged
          rtm_resume(rtm);
          return;
        }
      }
      // single-writer handoff: mutate the shared arrays while parked
      for (int s = 0; s < kS; s++) {
        next_slot[s] += 1 + rng.below(3);
        applied[s] = next_slot[s] - 1;
        tainted[s] = applied[s] - 1;
        last_progress[s] = stress::now_s();
      }
      rtm_resume(rtm);
      pauses.fetch_add(1);
      for (int i = 0; i < 4; i++) rtm_cmd_push(rtm, cmd, sizeof(cmd));
      const int64_t n = rtm_ev_drain(rtm, evbuf.data(),
                                     (int64_t)evbuf.size());
      if (n > 0) ev_bytes.fetch_add(n);
      stress::sleep_ms(1);
    }
  });

  // peer blaster: half natively-consumed, half escalated to the ev ring
  std::thread blaster([&] {
    stress::Rng rng(4);
    uint8_t frame[128];
    while (!stop.load()) {
      memset(frame, 0, sizeof(frame));
      frame[1] = rng.below(2) ? kTypeNoop : 0x66;  // noop | escalate
      rt_broadcast(b, frame, sizeof(frame));
      rt_inbox_kick(a);
      if ((rng.next() & 63) == 0) stress::sleep_ms(1);
    }
  });

  // advisory scrape: counters/stages/hist/flight while the loop writes
  std::thread scraper([&] {
    const uint64_t* ctrs = (const uint64_t*)rtm_counters(rtm);
    const uint64_t* stg = (const uint64_t*)rtm_stages(rtm);
    const uint64_t* hist = (const uint64_t*)rtm_hist(rtm);
    const int nc = rtm_counters_count();
    const int ns = rtm_stages_count();
    const int nh = rtm_hist_stages() * (rtm_hist_buckets() + 2);
    volatile uint64_t sink = 0;
    while (!stop.load()) {
      sink ^= rabia_stress_advisory_read(ctrs, nc);
      sink ^= rabia_stress_advisory_read(stg, ns);
      sink ^= rabia_stress_advisory_read(hist, nh);
      rtm_flight_head(rtm);
      rtm_state(rtm);
      stress::sleep_ms(1);
    }
    (void)sink;
  });

  const double t0 = stress::now_s();
  while (stress::now_s() - t0 < 1.5 && !fail.load()) stress::sleep_ms(20);
  stop.store(true);
  control.join();
  blaster.join();
  scraper.join();
  rtm_stop(rtm);

  // io thread joined: plain reads of its counters are safe now
  const uint64_t* ctrs = (const uint64_t*)rtm_counters(rtm);
  const uint64_t native = ctrs[3];     // RTM_FRAMES_NATIVE
  const uint64_t escalated = ctrs[5];  // RTM_FRAMES_ESCALATED
  const uint64_t cmds = ctrs[7];       // RTM_CMDS
  rtm_destroy(rtm);
  rt_stop(b);
  rt_close(b);
  rt_stop(a);
  rt_close(a);
  if (fail.load()) {
    std::fprintf(stderr, "invariant violated: code %d\n", fail.load());
    return 2;
  }
  std::printf(
      "stress ok: %ld pauses, %llu native, %llu escalated, %llu cmds, "
      "%ld ev bytes\n",
      pauses.load(), (unsigned long long)native,
      (unsigned long long)escalated, (unsigned long long)cmds,
      ev_bytes.load());
  return (pauses.load() > 10 && native > 0 && escalated > 0 && cmds > 0 &&
          ev_bytes.load() > 0)
             ? 0
             : 3;
}
