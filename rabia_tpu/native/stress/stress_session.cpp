// Sessionkernel stress: the GC sweep vs concurrent gws_submit seam.
//
// The round-13 plane mutex made the session table safe under concurrent
// callers (gateway fleet / thread-per-shard-group direction); this
// program hammers exactly the interleavings the asyncio loop used to
// serialize: a HOT submit lane (hello/submit/complete/dedup-replay,
// dereferencing cached-reply blobs) vs a CHURN lane (sessions opened
// and abandoned to expire) vs the GC sweep (tombstoning + rehash +
// eviction) vs introspection (len/stats/info/ids/seqs).
//
// Blob-borrow discipline: cached replies are borrowed-until-next-
// mutation BY ANY THREAD, so the hot lane only dereferences blobs of
// sessions GC provably cannot touch — ack_upto stays 0 (no frontier
// eviction), per-session results stay far under the cache cap, every
// call passes a FRESH timestamp (a stale `now` makes last_active lie
// to the sweeper), and the concurrent-phase ttls sit far above any
// plausible scheduler stall (an early harness draft used a 0.15s lease
// and ASan-on-a-saturated-box preemption let GC reap a session between
// a submit's return and its blob read — a real use-after-free of the
// borrow contract, caught by this very cell). The churn lane's
// abandoned sessions are the ones that expire concurrently; the hard
// LEASE path is validated deterministically post-join with a forged
// future clock (gws_gc's `now` is a parameter). That mirrors
// production: the asyncio loop owns its live sessions' replies; GC
// only frees what no caller still reads.

#include <vector>

#include "stress_common.h"

extern "C" {
void* gws_create(int64_t default_window, double session_ttl,
                 int64_t result_cache_cap, double lease_ttl);
void gws_destroy(void* h);
int32_t gws_counters_count(void);
void* gws_counters(void* h);
int64_t gws_len(void* h);
void gws_clear(void* h);
void gws_stats(void* h, uint64_t* out);
int64_t gws_hello(void* h, const uint8_t* cid, int64_t req_window,
                  double now, uint64_t* last_seq_out);
int32_t gws_submit(void* h, const uint8_t* cid, uint64_t seq,
                   uint64_t ack_upto, double now, int32_t* status_out,
                   const uint8_t** blob_out, int64_t* blob_len_out);
int32_t gws_complete(void* h, const uint8_t* cid, uint64_t seq,
                     int32_t status, uint64_t frontier_mark,
                     const uint8_t* blob, int64_t blob_len, double now);
void gws_abort(void* h, const uint8_t* cid, uint64_t seq);
int64_t gws_gc(void* h, uint64_t state_version, double now);
int32_t gws_session_info(void* h, const uint8_t* cid, int64_t* window,
                         uint64_t* ack_upto, uint64_t* highest,
                         int64_t* n_inflight, int64_t* n_results);
int32_t gws_get_result(void* h, const uint8_t* cid, uint64_t seq,
                       int32_t* status_out, uint64_t* frontier_out,
                       const uint8_t** blob_out, int64_t* blob_len_out);
int64_t gws_session_ids(void* h, uint8_t* out, int64_t cap);
int64_t gws_result_seqs(void* h, const uint8_t* cid, uint64_t* out,
                        int64_t cap);
int64_t gws_inflight_seqs(void* h, const uint8_t* cid, uint64_t* out,
                          int64_t cap);
}

static void mk_cid(uint8_t* cid, uint32_t base, uint32_t i) {
  memset(cid, 0, 16);
  memcpy(cid, &base, 4);
  memcpy(cid + 4, &i, 4);
}

int main() {
  // session_ttl low enough that ABANDONED churn sessions expire during
  // the run, but far above any plausible stall of a hot lane; the lease
  // outlives the whole run (its path is checked post-join with a forged
  // clock); generous cache cap so hot blobs are never cap-evicted
  void* h = gws_create(/*window=*/8, /*session_ttl=*/2.0,
                       /*cache_cap=*/64, /*lease_ttl=*/30.0);
  if (!h) {
    std::fprintf(stderr, "gws_create failed\n");
    return 1;
  }
  std::atomic<bool> stop{false};
  std::atomic<long> submits{0}, dedups{0};
  std::atomic<int> fail{0};
  const double t0 = stress::now_s();

  // two hot submit lanes over DISJOINT cid ranges (each lane owns its
  // sessions' borrowed blobs; GC cannot free them — see header)
  auto hot = [&](uint32_t base, uint64_t seed) {
    stress::Rng rng(seed);
    uint8_t cid[16], payload[96];
    while (!stop.load()) {
      mk_cid(cid, base, rng.below(32));
      const uint64_t seq = 1 + rng.below(24);
      if (gws_hello(h, cid, 8, stress::now_s() - t0, nullptr) < 0) {
        fail.store(1);
        return;
      }
      int32_t st = 0;
      const uint8_t* blob = nullptr;
      int64_t blen = 0;
      const int32_t rc = gws_submit(h, cid, seq, /*ack_upto=*/0,
                                    stress::now_s() - t0, &st, &blob,
                                    &blen);
      submits.fetch_add(1);
      if (rc == 0) {  // FRESH: complete with a payload blob
        memset(payload, (int)(seq & 0xFF), sizeof(payload));
        gws_complete(h, cid, seq, 0, 1, payload, sizeof(payload),
                     stress::now_s() - t0);
      } else if (rc == 1) {  // DUP_CACHED: read the borrowed reply
        volatile uint8_t sink = 0;
        for (int64_t i = 0; i < blen; i++) sink ^= blob[i];
        if (blen != sizeof(payload) || blob[0] != (uint8_t)(seq & 0xFF))
          fail.store(2);  // cached reply corrupted
        dedups.fetch_add(1);
        (void)sink;
      } else if (rc == 3) {  // window full: abort one inflight
        uint64_t seqs[16];
        const int64_t n = gws_inflight_seqs(h, cid, seqs, 16);
        if (n > 0) gws_abort(h, cid, seqs[0]);
      }
    }
  };
  std::thread h1(hot, 0x1000, 5), h2(hot, 0x2000, 6);

  std::thread churn([&] {
    stress::Rng rng(7);
    uint8_t cid[16];
    uint32_t i = 0;
    while (!stop.load()) {
      const double now = stress::now_s() - t0;
      mk_cid(cid, 0x9000, i++);
      gws_hello(h, cid, 4, now, nullptr);
      int32_t st;
      const uint8_t* b;
      int64_t bl;
      if (gws_submit(h, cid, 1, 0, now, &st, &b, &bl) == 0) {
        uint8_t pay[8] = {1};
        // half complete (idle expiry path), half stay inflight (the
        // hard-lease path must reap them despite the reservation)
        if (rng.below(2)) gws_complete(h, cid, 1, 0, 1, pay, 8, now);
      }
      stress::sleep_ms(1);
    }
  });

  std::thread gc([&] {
    while (!stop.load()) {
      gws_gc(h, /*state_version=*/1u << 20, stress::now_s() - t0);
      stress::sleep_ms(2);
    }
  });

  std::thread intro([&] {
    uint8_t ids[16 * 512];
    uint64_t seqs[64], stats[6];
    uint8_t cid[16];
    stress::Rng rng(9);
    const uint64_t* ctrs = (const uint64_t*)gws_counters(h);
    const int nctrs = gws_counters_count();
    volatile uint64_t sink = 0;
    while (!stop.load()) {
      gws_len(h);
      gws_stats(h, stats);
      gws_session_ids(h, ids, 512);
      mk_cid(cid, 0x1000, rng.below(32));
      int64_t w, ni, nr;
      uint64_t a, hi;
      if (gws_session_info(h, cid, &w, &a, &hi, &ni, &nr)) {
        gws_result_seqs(h, cid, seqs, 64);
        gws_inflight_seqs(h, cid, seqs, 64);
      }
      sink ^= rabia_stress_advisory_read(ctrs, nctrs);
      stress::sleep_ms(1);
    }
    (void)sink;
  });

  while (stress::now_s() - t0 < 3.0 && !fail.load()) stress::sleep_ms(20);
  stop.store(true);
  h1.join();
  h2.join();
  churn.join();
  gc.join();
  intro.join();

  // deterministic expiry + hard-lease checks, single-threaded (gws_gc's
  // `now` is caller time, so a forged future clock exercises both
  // paths without racing the borrow contract)
  uint8_t cid[16];
  mk_cid(cid, 0x7777, 1);
  const double now = stress::now_s() - t0;
  gws_hello(h, cid, 4, now, nullptr);
  int32_t st;
  const uint8_t* b;
  int64_t bl;
  gws_submit(h, cid, 1, 0, now, &st, &b, &bl);  // stays inflight
  gws_gc(h, 1u << 20, now + 100.0);
  int64_t w_, ni_, nr_;
  uint64_t a_, hi_;
  const bool lease_reaped =
      gws_session_info(h, cid, &w_, &a_, &hi_, &ni_, &nr_) == 0;

  uint64_t stats[6];
  gws_stats(h, stats);
  const bool expired = stats[4] > 0;  // sessions were reaped
  const bool leases = stats[5] > 0;   // incl. the inflight one (lease)
  gws_clear(h);
  gws_destroy(h);
  if (fail.load()) {
    std::fprintf(stderr, "invariant violated: code %d\n", fail.load());
    return 2;
  }
  std::printf("stress ok: %ld submits, %ld dedup replays, %llu expired\n",
              submits.load(), dedups.load(),
              (unsigned long long)stats[4]);
  return (submits.load() > 1000 && dedups.load() > 0 && expired &&
          leases && lease_reaped)
             ? 0
             : 3;
}
