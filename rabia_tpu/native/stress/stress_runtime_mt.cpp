// Multi-worker runtime stress (thread-per-shard-group, round 14): two
// shard-group worker threads vs every cross-worker seam the split
// introduced, over a REAL transport pair, the REAL statekernel plane
// (per-lane applies + group store locking) and the REAL walkernel
// (N staging lanes into the one group-commit flush thread). Seams:
//   - per-group inbox routing: the io loop classifies REAL v3 vote
//     frames spanning both groups (rtm_frame_group_mask) and fans them
//     out; each worker ingests only its range (stubbed rk filters by a
//     harness-side range check);
//   - shared WAL staging vs 2 append lanes: both workers stage decided
//     waves via wal_append while the flush thread fsyncs — LSNs must
//     come back monotone per worker and the durable watermark advance;
//   - cross-worker result staging vs the broadcast/control drain: each
//     worker applies through its OWN statekernel lane (want=1), the
//     control thread drains BOTH ev rings through the one rtm_ev_drain;
//   - the multi-worker pause barrier: rtm_pause must park BOTH workers
//     (rtm_state == PAUSED) before the control thread mutates the
//     shared consensus arrays, under sustained frame + wave traffic.
//
// Consensus math is STUBBED at the fn boundary (the conformance fuzzer
// owns it): the stub tick "decides V1" whatever the runtime armed, so
// every CMD_OPEN_WAVE flows decide -> lane apply -> WAL stage -> result
// staging on the worker that owns its shard.

#include <string>
#include <vector>

#include "stress_common.h"
#include "transport.h"

extern "C" {
void* rtm_create(const int64_t* dims, const int64_t* ptrs,
                 const int64_t* fns, const uint8_t* uuids,
                 const double* fparams);
int32_t rtm_start(void* ctx);
void rtm_stop(void* ctx);
void rtm_destroy(void* ctx);
int32_t rtm_state(void* ctx);
void rtm_pause(void* ctx);
void rtm_resume(void* ctx);
int32_t rtm_workers(void* ctx);
int32_t rtm_cmd_push(void* ctx, const uint8_t* rec, int64_t len);
int64_t rtm_ev_drain(void* ctx, uint8_t* out, int64_t cap);
int32_t rtm_counters_count(void);
void* rtm_counters_w(void* ctx, int32_t g);
void* rtm_stages_w(void* ctx, int32_t g);
int32_t rtm_stages_count(void);
uint64_t rtm_flight_head_w(void* ctx, int32_t g);
uint64_t rtm_frame_group_mask(void* ctx, const uint8_t* data, uint32_t len);

// statekernel (real)
void* sk_plane_create(int64_t n_stores, int64_t max_keys,
                      int64_t max_key_len, int64_t max_value_size);
void sk_plane_destroy(void* h);
int32_t sk_set_groups(void* h, int32_t ngroups);
int64_t sk_apply_wave(void* h, const uint8_t* data,
                      const int64_t* cmd_offsets, const int64_t* shards,
                      const int64_t* starts, const int64_t* idxs,
                      int64_t n_idx, double now, int32_t want);
int64_t sk_apply_wave_lane(void* h, int32_t lane, const uint8_t* data,
                           const int64_t* cmd_offsets, const int64_t* shards,
                           const int64_t* starts, const int64_t* idxs,
                           int64_t n_idx, double now, int32_t want);
void* sk_out_buf(void* h);
void* sk_out_offs(void* h);
void* sk_out_buf_lane(void* h, int32_t lane);
void* sk_out_offs_lane(void* h, int32_t lane);
void sk_plane_lock(void* h);
void sk_plane_unlock(void* h);
int64_t sk_get(void* h, int64_t idx, const uint8_t* key, int64_t klen,
               const uint8_t** val_addr, uint64_t* version_out);
int64_t sk_store_size(void* h, int64_t idx);
void* sk_counters(void* h);
int32_t sk_counters_count(void);

// walkernel (real)
void* wal_create(const char* dir, int64_t seg_limit, int64_t n_shards,
                 int64_t stride, uint64_t start_lsn, uint64_t start_segment);
int32_t wal_start(void* h);
void wal_stop(void* h);
void wal_destroy(void* h);
int64_t wal_append(void* h, const uint8_t* payload, int64_t len);
uint64_t wal_durable(void* h);
int64_t wal_barrier_covered(void* h, int64_t shard, int64_t slot);
int32_t wal_sync(void* h, double timeout_s);
}

static const int kS = 8;  // shards: groups [0,4) and [4,8)
static const int kW = 2;
static const int kDecRing = 64;

// shared kernel-state arrays the stub tick "decides" through (each
// worker's tick touches only its armed shards — disjoint by group)
static std::vector<int32_t> g_kslot;
static std::vector<int8_t> g_kdecided;
static std::vector<uint8_t> g_kdone;

extern "C" int32_t stub_rk_ingest(void*, const uint8_t* frame, int64_t len,
                                  int32_t, double) {
  if (len >= 2 && frame[1] == 2) return 2;  // v3 VOTE1: consumed (noop)
  return 0;                                 // escalate
}

// "decide V1 whatever was just armed": open_mask/open_slots arrive for
// this worker's range only, so the shared-array writes stay disjoint
extern "C" void stub_rk_tick(void*, double, uint8_t*, int64_t, int32_t,
                             const uint8_t* open_mask,
                             const int32_t* open_slots, const int8_t*,
                             int64_t* res) {
  for (int i = 0; i < 8; i++) res[i] = 0;
  if (!open_mask) return;
  for (int s = 0; s < kS; s++) {
    if (!open_mask[s]) continue;
    g_kslot[s] = open_slots[s];
    g_kdecided[s] = 1;  // V1
    g_kdone[s] = 1;
    res[1] = 1;  // done_any: process_decided runs
  }
}

extern "C" void stub_rk_retransmit(void*, double, double, uint8_t*, int64_t,
                                   int64_t* res) {
  if (res) res[0] = 0;
}

extern "C" int64_t stub_rk_drain_stale(void*, int64_t*, int64_t*, int64_t*,
                                       int64_t) {
  return 0;
}

// one-shard CMD_OPEN_WAVE with a single SET op (k<shard> = v)
static std::vector<uint8_t> make_wave_cmd(uint64_t token, uint32_t shard,
                                          uint64_t slot) {
  const uint8_t key = (uint8_t)('a' + (shard & 15));
  const uint8_t op[7] = {1, 2, 0, 'k', key, 'v', (uint8_t)('0' + (slot % 10))};
  std::vector<uint8_t> r;
  auto u32 = [&](uint32_t v) {
    r.insert(r.end(), (uint8_t*)&v, (uint8_t*)&v + 4);
  };
  auto u64 = [&](uint64_t v) {
    r.insert(r.end(), (uint8_t*)&v, (uint8_t*)&v + 8);
  };
  r.push_back(2);  // CMD_OPEN_WAVE
  u64(token);
  r.push_back(1);  // want result frames
  u32(1);          // k entries
  u32(0);          // announce_len
  u32(sizeof(op)); // blob_len
  u32(1);          // total ops
  u32(shard);
  u64(slot);
  u32(0);  // bidx
  u32(1);  // nops
  u32(sizeof(op));  // op len
  r.insert(r.end(), op, op + sizeof(op));
  return r;
}

// a REAL v3 VOTE1 frame with entries on the given shards — what the
// group classifier parses and fans out across group inboxes
static std::vector<uint8_t> make_vote_frame(const uint8_t sender[16],
                                            const int* shards, int n) {
  std::vector<uint8_t> f(47 + 4 + (size_t)n * 13, 0);
  f[0] = 3;
  f[1] = 2;  // MT_VOTE1
  f[2] = 0;
  memcpy(f.data() + 19, sender, 16);
  double ts = stress::now_s();
  memcpy(f.data() + 35, &ts, 8);
  uint32_t body_len = 4 + (uint32_t)n * 13;
  memcpy(f.data() + 43, &body_len, 4);
  uint32_t cnt = (uint32_t)n;
  memcpy(f.data() + 47, &cnt, 4);
  for (int i = 0; i < n; i++) {
    uint8_t* e = f.data() + 51 + (size_t)i * 13;
    uint32_t s = (uint32_t)shards[i];
    memcpy(e, &s, 4);
    uint64_t ph = 1ull << 16;
    memcpy(e + 4, &ph, 8);
    e[12] = 1;
  }
  return f;
}

int main(int argc, char** argv) {
  if (argc < 2) {
    std::fprintf(stderr, "usage: %s <wal-dir>\n", argv[0]);
    return 1;
  }
  unsigned char id_a[16] = {0xAA};
  unsigned char id_b[16] = {0xBB};
  unsigned short pa = 0, pb = 0;
  void* a = rt_create(id_a, "127.0.0.1", 0, &pa);
  void* b = rt_create(id_b, "127.0.0.1", 0, &pb);
  if (!a || !b) {
    std::fprintf(stderr, "transport create failed\n");
    return 1;
  }
  rt_add_peer(a, id_b, "127.0.0.1", pb);
  rt_add_peer(b, id_a, "127.0.0.1", pa);
  for (int i = 0; i < 200; i++) {
    unsigned char ids[16 * 4];
    if (rt_connected(a, ids, 4) >= 1 && rt_connected(b, ids, 4) >= 1) break;
    stress::sleep_ms(10);
  }

  void* sk = sk_plane_create(kS, 1 << 16, 256, 1 << 20);
  if (!sk || sk_set_groups(sk, kW) != 0) {
    std::fprintf(stderr, "sk plane create/groups failed\n");
    return 1;
  }
  void* wal = wal_create(argv[1], 1 << 20, kS, 16, 0, 0);
  if (!wal || wal_start(wal) != 0) {
    std::fprintf(stderr, "wal create/start failed\n");
    return 1;
  }

  std::vector<int64_t> next_slot(kS, 0), applied(kS, 0), votes_seen(kS, 0),
      tainted(kS, -1);
  std::vector<uint8_t> in_flight(kS, 0);
  std::vector<double> last_progress(kS, 0.0), opened_at(kS, 0.0);
  std::vector<int64_t> ring_slot((size_t)kS * kDecRing, -1);
  std::vector<int8_t> ring_val((size_t)kS * kDecRing, -1);
  g_kslot.assign(kS, 0);
  g_kdecided.assign(kS, -1);
  g_kdone.assign(kS, 0);
  std::vector<uint8_t> knewly(kS, 0);
  uint8_t uuids[2 * 16];
  memcpy(uuids, id_a, 16);
  memcpy(uuids + 16, id_b, 16);

  const int64_t dims[11] = {kS, kS, /*R=*/2, /*me=*/0, kDecRing,
                            /*native_apply=*/1, 1 << 20, 1 << 20,
                            /*max_cmds=*/64, /*max_cmd_size=*/4096,
                            /*workers=*/kW};
  const int64_t ptrs[18] = {
      /*rk_ctx worker0*/ 1,  // opaque to the stubs
      (int64_t)a,
      (int64_t)sk,
      (int64_t)next_slot.data(), (int64_t)applied.data(),
      (int64_t)in_flight.data(), (int64_t)votes_seen.data(),
      (int64_t)tainted.data(), (int64_t)last_progress.data(),
      (int64_t)opened_at.data(), (int64_t)ring_slot.data(),
      (int64_t)ring_val.data(), (int64_t)g_kslot.data(),
      (int64_t)g_kdecided.data(), (int64_t)g_kdone.data(),
      (int64_t)knewly.data(), (int64_t)wal,
      /*rk_ctx worker1*/ 2};
  const int64_t fns[20] = {
      (int64_t)&rt_recv_borrow, (int64_t)&rt_recv_release,
      (int64_t)&rt_broadcast_frames, (int64_t)&rt_send,
      (int64_t)&stub_rk_ingest, (int64_t)&stub_rk_tick,
      (int64_t)&stub_rk_retransmit, (int64_t)&stub_rk_drain_stale,
      (int64_t)&sk_apply_wave, (int64_t)&sk_out_buf, (int64_t)&sk_out_offs,
      (int64_t)&sk_plane_lock, (int64_t)&sk_plane_unlock,
      (int64_t)&wal_append, (int64_t)&wal_barrier_covered,
      (int64_t)&wal_durable,
      (int64_t)&rt_recv_borrow_group, (int64_t)&sk_apply_wave_lane,
      (int64_t)&sk_out_buf_lane, (int64_t)&sk_out_offs_lane};
  const double fparams[4] = {1.0, 30.0, 0.2, 0.05};

  void* rtm = rtm_create(dims, ptrs, fns, uuids, fparams);
  if (!rtm || rtm_workers(rtm) != kW) {
    std::fprintf(stderr, "rtm create failed / wrong worker count\n");
    return 1;
  }
  // per-group frame routing through the REAL classifier
  if (rt_set_groups(a, kW, (void*)&rtm_frame_group_mask, rtm) != 0) {
    std::fprintf(stderr, "rt_set_groups failed\n");
    return 1;
  }
  if (rtm_start(rtm) != 0) {
    std::fprintf(stderr, "rtm start failed\n");
    return 1;
  }

  std::atomic<bool> stop{false};
  std::atomic<long> pauses{0}, ev_bytes{0}, waves_pushed{0};
  std::atomic<int> fail{0};

  // control thread: wave submissions to BOTH groups, the pause BARRIER
  // (both workers must park), shared-array mutations while parked, and
  // the one ev drain
  std::thread control([&] {
    stress::Rng rng(7);
    std::vector<uint8_t> evbuf(1 << 18);
    uint64_t token = 1;
    std::vector<uint64_t> slot(kS, 0);
    while (!stop.load()) {
      // a wave on one shard of each group
      for (int g = 0; g < kW; g++) {
        const uint32_t s = (uint32_t)(g * (kS / kW) + rng.below(kS / kW));
        auto cmd = make_wave_cmd(token++, s, slot[s]);
        if (rtm_cmd_push(rtm, cmd.data(), (int64_t)cmd.size()) == 0) {
          slot[s]++;  // rejected re-opens reuse the slot; accepted move on
          waves_pushed.fetch_add(1);
        }
        rt_inbox_kick(a);
      }
      const int64_t n =
          rtm_ev_drain(rtm, evbuf.data(), (int64_t)evbuf.size());
      if (n > 0) ev_bytes.fetch_add(n);
      if ((rng.next() & 7) == 0) {
        // the pause barrier across both workers
        rtm_pause(rtm);
        const double t0 = stress::now_s();
        while (rtm_state(rtm) != 2 /*PAUSED*/) {
          if (stress::now_s() - t0 > 5.0) {
            fail.store(1);  // barrier never completed
            rtm_resume(rtm);
            return;
          }
          rtm_ev_drain(rtm, evbuf.data(), (int64_t)evbuf.size());
        }
        // single-writer handoff: mutate shared arrays while BOTH parked
        for (int s = 0; s < kS; s++) last_progress[s] = stress::now_s();
        rtm_resume(rtm);
        pauses.fetch_add(1);
      }
      stress::sleep_ms(1);
    }
  });

  // peer blaster: v3 vote frames spanning BOTH groups (classifier
  // fan-out with a buffer copy), group-pure frames, and escalate-type
  // frames for group 0's control lane
  std::thread blaster([&] {
    stress::Rng rng(9);
    const int both[4] = {0, 3, 4, 7};
    const int g0[2] = {1, 2};
    const int g1[2] = {5, 6};
    while (!stop.load()) {
      const uint32_t pick = rng.below(4);
      std::vector<uint8_t> f;
      if (pick == 0) {
        f = make_vote_frame(id_b, both, 4);
      } else if (pick == 1) {
        f = make_vote_frame(id_b, g0, 2);
      } else if (pick == 2) {
        f = make_vote_frame(id_b, g1, 2);
      } else {
        f.assign(64, 0);
        f[0] = 3;
        f[1] = 0x66;  // unknown type: group 0, escalated
        memcpy(f.data() + 19, id_b, 16);
      }
      rt_broadcast(b, f.data(), (uint32_t)f.size());
      rt_inbox_kick(a);
      if ((rng.next() & 31) == 0) stress::sleep_ms(1);
    }
  });

  // scraper: per-worker advisory block reads + a plane-locked GET
  // (reader vs both apply lanes — the group store locking under test)
  std::thread scraper([&] {
    const int nc = rtm_counters_count();
    const int ns = rtm_stages_count();
    volatile uint64_t sink = 0;
    while (!stop.load()) {
      for (int g = 0; g < kW; g++) {
        sink ^= rabia_stress_advisory_read(
            (const uint64_t*)rtm_counters_w(rtm, g), nc);
        sink ^= rabia_stress_advisory_read(
            (const uint64_t*)rtm_stages_w(rtm, g), ns);
        rtm_flight_head_w(rtm, g);
      }
      sk_plane_lock(sk);
      const uint8_t key[2] = {'k', 'a'};
      const uint8_t* val = nullptr;
      uint64_t ver = 0;
      (void)sk_get(sk, 0, key, 2, &val, &ver);
      if (val) {
        volatile uint8_t v0 = val[0];  // borrowed read under the bracket
        (void)v0;
      }
      sk_plane_unlock(sk);
      rtm_state(rtm);
      stress::sleep_ms(1);
    }
    (void)sink;
  });

  // durability waiter: the group-commit flush must keep the watermark
  // advancing while both workers stage
  std::thread waiter([&] {
    uint64_t last = 0;
    while (!stop.load()) {
      wal_sync(wal, 0.05);
      const uint64_t d = wal_durable(wal);
      if (d < last) fail.store(2);  // watermark went BACKWARDS
      last = d;
      stress::sleep_ms(2);
    }
  });

  const double t0 = stress::now_s();
  while (stress::now_s() - t0 < 1.5 && !fail.load()) stress::sleep_ms(20);
  stop.store(true);
  control.join();
  blaster.join();
  scraper.join();
  waiter.join();
  rtm_stop(rtm);

  // workers joined: plain reads are safe now
  long applied_per_worker[kW] = {0, 0};
  long native_per_worker[kW] = {0, 0};
  for (int g = 0; g < kW; g++) {
    const uint64_t* ctrs = (const uint64_t*)rtm_counters_w(rtm, g);
    applied_per_worker[g] = (long)ctrs[14];  // RTM_SLOTS_APPLIED
    native_per_worker[g] = (long)ctrs[3];    // RTM_FRAMES_NATIVE
  }
  const uint64_t durable = wal_durable(wal);
  // clear routing BEFORE destroying the ctx: the io thread's classifier
  // holds the ctx pointer (the exact teardown order the bridge uses)
  rt_set_groups(a, 0, nullptr, nullptr);
  rtm_destroy(rtm);
  wal_stop(wal);
  wal_destroy(wal);
  sk_plane_destroy(sk);
  rt_stop(b);
  rt_close(b);
  rt_stop(a);
  rt_close(a);
  if (fail.load()) {
    std::fprintf(stderr, "invariant violated: code %d\n", fail.load());
    return 2;
  }
  std::printf(
      "stress ok: %ld pauses, applied per worker [%ld, %ld], frames per "
      "worker [%ld, %ld], %ld waves pushed, %ld ev bytes, durable=%llu\n",
      pauses.load(), applied_per_worker[0], applied_per_worker[1],
      native_per_worker[0], native_per_worker[1], waves_pushed.load(),
      ev_bytes.load(), (unsigned long long)durable);
  // both workers must have done real end-to-end work: frames ingested,
  // waves applied through their own lanes, WAL records durable, events
  // drained, and the pause barrier exercised
  return (pauses.load() > 0 && applied_per_worker[0] > 0 &&
          applied_per_worker[1] > 0 && native_per_worker[0] > 0 &&
          native_per_worker[1] > 0 && ev_bytes.load() > 0 && durable > 0)
             ? 0
             : 3;
}
