// Transport stress: the epoll io loop vs every concurrent caller lane.
//
// Grown from the round-3 transport_stress.cpp (which gated send/
// broadcast/recv/stats/teardown) with the two seams the chaos and
// scale-out planes added since: the SHAPING delay-heap (rt_set_shaping
// mutating the per-peer delay/drop state while the io thread drains the
// heap at release time) and the flight-ring snapshot (rt_flight_copy
// under the io mutex while both sides record frames).
//
// Threads: two senders (send + broadcast + batched broadcast_frames),
// a zero-copy borrow drain, a copying drain, a shaping meddler
// (set/clear shaping + peer remove/re-add churn), and a stats scraper
// (connected/pool/dropped/flight/counters). Main tears one side down
// mid-traffic. Exit 0 requires real traffic flowed.

#include <vector>

#include "stress_common.h"
#include "transport.h"

int main() {
  unsigned char id_a[16] = {1};
  unsigned char id_b[16] = {2};
  unsigned short pa = 0, pb = 0;
  void* a = rt_create(id_a, "127.0.0.1", 0, &pa);
  void* b = rt_create(id_b, "127.0.0.1", 0, &pb);
  if (!a || !b) {
    std::fprintf(stderr, "create failed\n");
    return 1;
  }
  rt_add_peer(a, id_b, "127.0.0.1", pb);
  rt_add_peer(b, id_a, "127.0.0.1", pa);

  for (int i = 0; i < 200; i++) {
    unsigned char ids[16 * 4];
    if (rt_connected(a, ids, 4) >= 1 && rt_connected(b, ids, 4) >= 1) break;
    stress::sleep_ms(10);
  }

  std::atomic<bool> stop{false};
  std::atomic<long> received{0};

  std::thread sender_a([&] {
    uint8_t msg[512];
    memset(msg, 0x5A, sizeof(msg));
    // a batch of 3 length-prefixed frames, as the native tick's rk_tick
    // emits them (rt_broadcast_frames staging path)
    uint8_t batch[3 * (4 + 96)];
    for (int f = 0; f < 3; f++) {
      uint8_t* rec = batch + f * (4 + 96);
      uint32_t len = 96;
      memcpy(rec, &len, 4);
      memset(rec + 4, 0x30 + f, 96);
    }
    while (!stop.load()) {
      rt_send(a, id_b, msg, sizeof(msg));
      rt_broadcast(a, msg, 64);
      rt_broadcast_frames(a, batch, sizeof(batch));
    }
  });
  std::thread sender_b([&] {
    uint8_t msg[2048];
    memset(msg, 0xA5, sizeof(msg));
    while (!stop.load()) rt_broadcast(b, msg, sizeof(msg));
  });
  std::thread receiver_a([&] {
    // zero-copy drain: borrow straight from the frame arena, touch the
    // bytes (TSan-visible read of io-thread-written memory), release
    uint8_t sender[16];
    const uint8_t* ptr = nullptr;
    uint32_t len = 0;
    volatile uint8_t sink = 0;
    while (!stop.load()) {
      int64_t tok = rt_recv_borrow(a, sender, &ptr, &len, 20);
      if (tok >= 0) {
        if (len > 0) sink ^= ptr[len - 1];
        rt_recv_release(a, tok);
        received.fetch_add(1);
      } else if (tok == -1) {
        break;  // closing
      }
    }
    (void)sink;
  });
  std::thread receiver_b([&] {
    uint8_t sender[16];
    std::vector<uint8_t> buf(1 << 16);
    while (!stop.load()) {
      int n = rt_recv(b, sender, buf.data(), buf.size(), 20);
      if (n >= 0) received.fetch_add(1);
    }
  });
  std::thread shaper([&] {
    // the chaos plane's lane: mutate the per-peer shaping entry (delay +
    // jitter + drop, reseeding the RNG) while the io thread applies it
    // at drain time and releases the delay-heap, then clear — plus
    // redial churn under load
    stress::Rng rng(7);
    int cycles = 0;
    while (!stop.load()) {
      rt_set_shaping(a, id_b, 200 + rng.below(400), rng.below(200),
                     0.05, rng.next() | 1);
      stress::sleep_ms(3);
      rt_set_shaping(a, id_b, 0, 0, 0.0, 0);  // clear this peer
      if (++cycles % 16 == 0) {
        rt_clear_shaping(a);
        rt_remove_peer(a, id_b);
        stress::sleep_ms(10);
        rt_add_peer(a, id_b, "127.0.0.1", pb);
      }
      stress::sleep_ms(2);
    }
  });
  std::thread scraper([&] {
    uint8_t ids[16 * 8];
    const int rec = rt_flight_record_size();
    std::vector<uint8_t> flight((size_t)rec * 256);
    const uint64_t* ctrs_a = rt_counters(a);
    const int nctrs = rt_counters_count();
    volatile uint64_t sink = 0;
    while (!stop.load()) {
      rt_connected(a, ids, 8);
      uint64_t h = 0, m = 0;
      rt_pool_stats(b, &h, &m);
      rt_out_pool_stats(a, &h, &m);
      rt_dropped(a);
      rt_flight_copy(a, flight.data(), 256);
      sink ^= rabia_stress_advisory_read(ctrs_a, nctrs);
      rt_inbox_kick(a);
      stress::sleep_ms(5);
    }
    (void)sink;
  });

  stress::sleep_ms(1500);
  // tear one side down mid-traffic (close-under-load path)
  rt_stop(b);
  stress::sleep_ms(100);
  stop.store(true);
  sender_a.join();
  sender_b.join();
  receiver_a.join();
  receiver_b.join();
  shaper.join();
  scraper.join();
  rt_close(b);
  rt_stop(a);
  rt_close(a);
  std::printf("stress ok: %ld frames received\n", received.load());
  return received.load() > 0 ? 0 : 2;
}
