// TSan compatibility shim for gcc toolchains (linked into every TSan
// stress binary by build.py's sanitizer toolchain).
//
// gcc-10's libtsan predates the pthread_cond_clockwait interceptor, but
// libstdc++ on glibc >= 2.30 compiles std::condition_variable::wait_for
// /wait_until<steady_clock> down to exactly that call — so TSan misses
// the mutex release/reacquire inside every timed wait and reports a
// false "double lock of a mutex" on trivially correct code (the root
// cause of the retired environmental SKIP in the old TSan gate; see
// docs/STATIC_ANALYSIS.md).
//
// The shim interposes a strong pthread_cond_clockwait that converts the
// deadline to CLOCK_REALTIME and delegates to pthread_cond_timedwait,
// which every libtsan intercepts. Semantics: identical modulo a
// nanoseconds-wide clock-conversion window (irrelevant for stress
// timeouts); glibc's default condattr clock is REALTIME, matching the
// delegated wait. The kernels themselves never emit clockwait (they
// wait via rabia::CondVar's monotonic pthread_cond_timedwait) — this
// covers libstdc++ internals and test scaffolding only.

#include <pthread.h>
#include <time.h>

extern "C" int pthread_cond_clockwait(pthread_cond_t* cond,
                                      pthread_mutex_t* mu, clockid_t clock,
                                      const struct timespec* abstime) {
  struct timespec now_c, now_r, abs_r;
  clock_gettime(clock, &now_c);
  clock_gettime(CLOCK_REALTIME, &now_r);
  long long rem = (long long)(abstime->tv_sec - now_c.tv_sec) * 1000000000ll +
                  (abstime->tv_nsec - now_c.tv_nsec);
  if (rem < 0) rem = 0;
  const long long tgt =
      (long long)now_r.tv_sec * 1000000000ll + now_r.tv_nsec + rem;
  abs_r.tv_sec = (time_t)(tgt / 1000000000ll);
  abs_r.tv_nsec = (long)(tgt % 1000000000ll);
  return pthread_cond_timedwait(cond, mu, &abs_r);
}
