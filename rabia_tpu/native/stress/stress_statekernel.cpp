// Statekernel stress: the plane lock vs apply/export/snapshot lanes.
//
// Seams (statekernel.cpp): the recursive plane mutex is the handoff
// between the GIL-free runtime thread's apply path and the Python
// control plane's reads — sk_get's BORROWED value pointer is only valid
// while the caller holds the sk_plane_lock bracket across the copy-out,
// and the round-13 annotations made the previously-unlocked advisory
// reads (store_size/version/stats/export) lock internally. This program
// runs mutators (restore-path insert/delete/clear + version/stat
// writes) against bracketed readers (get + copy, export), the
// incremental-snapshot lane (delta_size/delta/mark), and the advisory
// introspection lane, all concurrently.

#include <vector>

#include "stress_common.h"

extern "C" {
void* sk_plane_create(int64_t n_stores, int64_t max_keys,
                      int64_t max_key_len, int64_t max_value_size);
void sk_plane_destroy(void* h);
void sk_plane_lock(void* h);
void sk_plane_unlock(void* h);
int32_t sk_counters_count(void);
void* sk_counters(void* h);
int64_t sk_store_count(void* h);
int64_t sk_store_size(void* h, int64_t idx);
uint64_t sk_store_version(void* h, int64_t idx);
void sk_set_version(void* h, int64_t idx, uint64_t v);
void sk_store_stats(void* h, int64_t idx, uint64_t* out);
void sk_add_stats(void* h, int64_t idx, uint64_t ops, uint64_t reads,
                  uint64_t writes);
int64_t sk_get(void* h, int64_t idx, const uint8_t* key, int64_t klen,
               const uint8_t** val_addr, uint64_t* version_out);
int64_t sk_export_size(void* h, int64_t idx);
int64_t sk_export(void* h, int64_t idx, uint8_t* out, int64_t cap);
void sk_clear_store(void* h, int64_t idx);
int32_t sk_delete_raw(void* h, int64_t idx, const uint8_t* key,
                      int64_t klen);
int32_t sk_insert_raw(void* h, int64_t idx, const uint8_t* key,
                      int64_t klen, const uint8_t* val, int64_t vlen,
                      uint64_t version, double created, double updated);
int64_t sk_snapshot_delta_size(void* h, int64_t idx);
int64_t sk_snapshot_delta(void* h, int64_t idx, uint8_t* out, int64_t cap);
void sk_snapshot_mark(void* h, int64_t idx);
}

static const int64_t kStores = 4;

static void mk_key(uint8_t* k, uint32_t i) {
  memcpy(k, "key-", 4);
  memcpy(k + 4, &i, 4);
}

int main() {
  void* h = sk_plane_create(kStores, 1 << 16, 128, 4096);
  if (!h) {
    std::fprintf(stderr, "sk_plane_create failed\n");
    return 1;
  }
  std::atomic<bool> stop{false};
  std::atomic<long> mutations{0}, hits{0};
  std::atomic<int> fail{0};

  // mutators: each owns two stores (apply-lane stand-in: the restore
  // APIs lock internally exactly like sk_apply_wave does)
  auto mutator = [&](int64_t s0, uint64_t seed) {
    stress::Rng rng(seed);
    uint8_t key[8], val[64];
    uint64_t version = 1;
    while (!stop.load()) {
      const int64_t idx = s0 + (int64_t)rng.below(2);
      mk_key(key, rng.below(512));
      const uint32_t vlen = 8 + rng.below(48);
      memset(val, (int)(version & 0xFF), sizeof(val));
      switch (rng.below(8)) {
        case 0:
          sk_delete_raw(h, idx, key, 8);
          break;
        case 1:
          sk_set_version(h, idx, version);
          break;
        case 2:
          sk_add_stats(h, idx, 3, 2, 1);
          break;
        case 3:
          if ((version & 1023) == 0) sk_clear_store(h, idx);
          break;
        default:
          sk_insert_raw(h, idx, key, 8, val, vlen, version, 1.0, 2.0);
      }
      version++;
      mutations.fetch_add(1);
    }
  };
  std::thread m1(mutator, 0, 31), m2(mutator, 2, 32);

  // bracketed reader: the gateway read-index GET shape — hold the plane
  // lock across the borrow + copy-out
  std::thread reader([&] {
    stress::Rng rng(33);
    uint8_t key[8];
    std::vector<uint8_t> copy;
    std::vector<uint8_t> exp(1 << 20);
    while (!stop.load()) {
      const int64_t idx = (int64_t)rng.below((uint32_t)kStores);
      mk_key(key, rng.below(512));
      sk_plane_lock(h);
      const uint8_t* vp = nullptr;
      uint64_t ver = 0;
      const int64_t vlen = sk_get(h, idx, key, 8, &vp, &ver);
      if (vlen >= 0) {
        copy.assign(vp, vp + vlen);
        // every byte of a value is one fill byte (mutator contract)
        for (int64_t i = 1; i < vlen; i++) {
          if (copy[(size_t)i] != copy[0]) {
            fail.store(1);  // torn value under the bracket: a real race
            break;
          }
        }
        hits.fetch_add(1);
      }
      const int64_t need = sk_export_size(h, idx);
      if (need >= 0 && need <= (int64_t)exp.size())
        sk_export(h, idx, exp.data(), (int64_t)exp.size());
      sk_plane_unlock(h);
      stress::sleep_ms(0);
    }
  });

  // incremental-snapshot lane (durability plane's capture path)
  std::thread snap([&] {
    std::vector<uint8_t> buf(1 << 20);
    stress::Rng rng(34);
    while (!stop.load()) {
      const int64_t idx = (int64_t)rng.below((uint32_t)kStores);
      sk_plane_lock(h);
      const int64_t need = sk_snapshot_delta_size(h, idx);
      if (need > 0 && need <= (int64_t)buf.size()) {
        if (sk_snapshot_delta(h, idx, buf.data(), (int64_t)buf.size()) >= 0)
          sk_snapshot_mark(h, idx);
      }
      sk_plane_unlock(h);
      stress::sleep_ms(1);
    }
  });

  // advisory introspection: the metrics scrape shape (now internally
  // locked; counters read under the bracket like the registry does)
  std::thread intro([&] {
    uint64_t st[3];
    volatile uint64_t sink = 0;
    const int nctrs = sk_counters_count();
    while (!stop.load()) {
      sk_store_count(h);
      for (int64_t i = 0; i < kStores; i++) {
        sk_store_size(h, i);
        sk_store_version(h, i);
        sk_store_stats(h, i, st);
      }
      sk_plane_lock(h);
      sink ^= rabia_stress_advisory_read(
          (const uint64_t*)sk_counters(h), nctrs);
      sk_plane_unlock(h);
      stress::sleep_ms(1);
    }
    (void)sink;
  });

  const double t0 = stress::now_s();
  while (stress::now_s() - t0 < 1.5 && !fail.load()) stress::sleep_ms(20);
  stop.store(true);
  m1.join();
  m2.join();
  reader.join();
  snap.join();
  intro.join();
  sk_plane_destroy(h);
  if (fail.load()) {
    std::fprintf(stderr, "invariant violated: code %d\n", fail.load());
    return 2;
  }
  std::printf("stress ok: %ld mutations, %ld bracketed reads\n",
              mutations.load(), hits.load());
  return (mutations.load() > 1000 && hits.load() > 0) ? 0 : 3;
}
