// Shared scaffolding for the native stress programs (build.py
// STRESS_PROGRAMS; scripts/sanitize_gate.py is the driver).
//
// Conventions every program follows:
//   - exit 0 only when the hammered seam did real work (frame counts,
//     op counts — a stress that silently did nothing must not pass);
//   - all cross-thread coordination in the HARNESS uses atomics or the
//     primitives under test, so a sanitizer report always points at
//     kernel code, not scaffolding;
//   - counter blocks that the Python scrape path reads as plain u64s
//     are read here through rabia_stress_advisory_read — the one vetted
//     TSan suppression (stress/tsan.supp) scoped to exactly that
//     contract.

#ifndef RABIA_STRESS_COMMON_H_
#define RABIA_STRESS_COMMON_H_

#include <time.h>

#include <cstdint>
#include <cstdio>
#include <cstring>

#include <atomic>
#include <chrono>
#include <thread>

namespace stress {

inline double now_s() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (double)ts.tv_sec + (double)ts.tv_nsec * 1e-9;
}

inline void sleep_ms(int ms) {
  std::this_thread::sleep_for(std::chrono::milliseconds(ms));
}

// Tiny deterministic RNG (no libc rand: thread-safe by construction).
struct Rng {
  uint64_t s;
  explicit Rng(uint64_t seed) : s(seed ? seed : 0x9E3779B97F4A7C15ull) {}
  uint64_t next() {
    s ^= s << 13;
    s ^= s >> 7;
    s ^= s << 17;
    return s;
  }
  uint32_t below(uint32_t n) { return (uint32_t)(next() % n); }
};

}  // namespace stress

// Advisory read of a native counter block through the same plain-u64
// aliasing the Python scrape path uses (np.frombuffer over the borrowed
// pointer). The cells are relaxed atomics on the writer side; this
// deliberate torn-read contract (docs/OBSERVABILITY.md, RKC) is
// suppressed by name in stress/tsan.supp. Marked noinline so the
// suppression's stack match is stable across optimization levels.
__attribute__((noinline)) inline uint64_t rabia_stress_advisory_read(
    const uint64_t* block, int count) {
  uint64_t acc = 0;
  for (int i = 0; i < count; i++) acc ^= block[i];
  // compiler barrier: keep the loads in this frame
  __asm__ volatile("" ::: "memory");
  return acc;
}

#endif  // RABIA_STRESS_COMMON_H_
