// WAL stress: the append lane vs the dedicated flush thread vs waiters.
//
// Seams (walkernel.cpp): multi-thread wal_append staging into the
// mutex-guarded buffer while the flush thread drains/rotates/fsyncs;
// the stride-amortized vote-barrier lane advancing + snapshotting the
// barrier vector; wal_sync waiters racing the durable-watermark publish;
// and the advisory observability reads (staged/durable/segment/
// counters) the telemetry thread performs in production. Main stops the
// writer mid-traffic once (clean-shutdown contract: everything staged
// before wal_stop is durable when it returns) and restarts a fresh ctx
// in the same dir continuing the LSN chain.
//
// Usage: stress_wal <empty-dir>

#include <sys/stat.h>

#include <string>
#include <vector>

#include "stress_common.h"

extern "C" {
void* wal_create(const char* dir, int64_t seg_limit, int64_t n_shards,
                 int64_t stride, uint64_t start_lsn, uint64_t start_segment);
int32_t wal_start(void* h);
void wal_stop(void* h);
void wal_destroy(void* h);
int64_t wal_append(void* h, const uint8_t* payload, int64_t len);
uint64_t wal_durable(void* h);
uint64_t wal_staged(void* h);
int32_t wal_io_error(void* h);
int32_t wal_sync(void* h, double timeout_s);
int64_t wal_barrier_covered(void* h, int64_t shard, int64_t slot);
void wal_set_barrier(void* h, const int64_t* vec, int64_t n);
void wal_get_barrier(void* h, int64_t* out, int64_t n);
int32_t wal_counters_count(void);
void* wal_counters(void* h);
int64_t wal_segment_index(void* h);
int64_t wal_segment_bytes(void* h);
}

static const int kShards = 8;

static long run_phase(void* w, double seconds) {
  std::atomic<bool> stop{false};
  std::atomic<long> appended{0};
  std::atomic<int> fail{0};

  auto appender = [&](uint64_t seed) {
    stress::Rng rng(seed);
    std::vector<uint8_t> pay;
    int burst = 0;
    while (!stop.load()) {
      const uint32_t n = 16 + rng.below(480);
      pay.assign(n, 0);
      pay[0] = (uint8_t)(1 + rng.below(4));  // kind 1..4
      for (uint32_t i = 1; i < n; i++) pay[i] = (uint8_t)rng.next();
      if (wal_append(w, pay.data(), (int64_t)n) > 0)
        appended.fetch_add(1);
      else if (!wal_io_error(w))
        fail.store(1);  // append refused on a healthy log: a bug
      // paced bursts: the append lane has no backpressure by design
      // (group commit absorbs it); unpaced spinners on a small box
      // would grow staged-vs-durable lag without bound and turn the
      // syncer's timeout into noise
      if (++burst % 16 == 0) stress::sleep_ms(1);
    }
  };

  std::thread a1(appender, 11), a2(appender, 22), a3(appender, 33);
  std::thread barrier([&] {
    stress::Rng rng(44);
    int64_t slot = 0;
    int64_t vec[kShards];
    int burst = 0;
    while (!stop.load()) {
      slot += 1 + rng.below(8);
      wal_barrier_covered(w, (int64_t)rng.below(kShards), slot);
      wal_get_barrier(w, vec, kShards);
      if ((slot & 63) == 0) wal_set_barrier(w, vec, kShards);
      if (++burst % 32 == 0) stress::sleep_ms(1);
    }
  });
  std::thread syncer([&] {
    while (!stop.load()) {
      const uint64_t staged = wal_staged(w);
      const uint64_t before = wal_durable(w);
      if (wal_sync(w, 10.0) == 0) {
        if (wal_durable(w) < staged) fail.store(2);  // sync lied
      } else if (!wal_io_error(w) && wal_durable(w) == before) {
        // a timeout with PROGRESS is a loaded box (sanitizer overhead
        // on a saturated CI runner); a frozen watermark on a healthy
        // log is the real lost-wakeup/stuck-flush bug
        fail.store(3);
      }
      stress::sleep_ms(2);
    }
  });
  std::thread scraper([&] {
    const uint64_t* ctrs = (const uint64_t*)wal_counters(w);
    const int n = wal_counters_count();
    volatile uint64_t sink = 0;
    while (!stop.load()) {
      sink ^= rabia_stress_advisory_read(ctrs, n);
      wal_segment_index(w);
      wal_segment_bytes(w);
      wal_durable(w);
      stress::sleep_ms(1);
    }
    (void)sink;
  });

  const double t0 = stress::now_s();
  while (stress::now_s() - t0 < seconds && !fail.load()) stress::sleep_ms(20);
  stop.store(true);
  a1.join();
  a2.join();
  a3.join();
  barrier.join();
  syncer.join();
  scraper.join();
  if (fail.load()) {
    std::fprintf(stderr, "invariant violated: code %d\n", fail.load());
    return -1;
  }
  return appended.load();
}

int main(int argc, char** argv) {
  if (argc < 2) {
    std::fprintf(stderr, "usage: stress_wal <dir>\n");
    return 1;
  }
  // small segment limit: rotation happens constantly under load
  void* w = wal_create(argv[1], 32 * 1024, kShards, 4, 0, 0);
  if (!w) {
    std::fprintf(stderr, "wal_create failed\n");
    return 1;
  }
  wal_start(w);
  long n1 = run_phase(w, 0.8);
  if (n1 < 0) return 2;
  // clean shutdown mid-traffic: everything staged must be durable
  const uint64_t staged = wal_staged(w);
  wal_stop(w);
  if (wal_durable(w) < staged && !wal_io_error(w)) {
    std::fprintf(stderr, "clean-shutdown durability hole: staged=%llu "
                 "durable=%llu\n", (unsigned long long)staged,
                 (unsigned long long)wal_durable(w));
    return 3;
  }
  const int64_t seg = wal_segment_index(w);
  wal_destroy(w);

  // restart continuing the chain (the recovery scan's contract: fresh
  // segment, LSNs continue)
  void* w2 = wal_create(argv[1], 32 * 1024, kShards, 4, staged,
                        (uint64_t)seg + 1);
  if (!w2) {
    std::fprintf(stderr, "wal re-create failed\n");
    return 4;
  }
  wal_start(w2);
  long n2 = run_phase(w2, 0.5);
  wal_stop(w2);
  wal_destroy(w2);
  if (n2 < 0) return 5;

  // wedge phase: a rotation that cannot open its next segment must
  // FREEZE the watermark (io_error set, appends refused, never a false
  // durable ack) and still shut down cleanly. Forced by renaming the
  // log directory away mid-traffic (permission tricks don't work under
  // root); the tiny seg_limit makes rotation imminent.
  std::string dir3 = std::string(argv[1]) + "/wedge";
  std::string dir3_moved = std::string(argv[1]) + "/wedge-moved";
  if (mkdir(dir3.c_str(), 0755) != 0) {
    std::fprintf(stderr, "mkdir wedge dir failed\n");
    return 7;
  }
  void* w3 = wal_create(dir3.c_str(), 1, kShards, 4, 0, 0);  // min limit
  if (!w3) {
    std::fprintf(stderr, "wedge wal_create failed\n");
    return 7;
  }
  wal_start(w3);
  if (rename(dir3.c_str(), dir3_moved.c_str()) != 0) {
    std::fprintf(stderr, "rename failed\n");
    return 7;
  }
  uint8_t pay[64] = {1};
  bool wedged = false;
  for (int i = 0; i < 5000 && !wedged; i++) {
    if (wal_append(w3, pay, sizeof(pay)) < 0 && wal_io_error(w3))
      wedged = true;
    if ((i & 63) == 0) stress::sleep_ms(1);
  }
  const uint64_t frozen = wal_durable(w3);
  if (wedged) {
    // the watermark must never move again, and sync must fail fast
    if (wal_sync(w3, 0.2) == 0 || wal_durable(w3) != frozen) {
      std::fprintf(stderr, "wedged log acked a write\n");
      return 8;
    }
  }
  wal_stop(w3);
  wal_destroy(w3);
  if (!wedged) {
    std::fprintf(stderr, "wedge never engaged (rotation not reached)\n");
    return 9;
  }
  std::printf("stress ok: %ld + %ld records, wedge held at %llu\n", n1, n2,
              (unsigned long long)frozen);
  return (n1 > 0 && n2 > 0) ? 0 : 6;
}
