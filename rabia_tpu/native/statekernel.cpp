// Native apply plane: the C twin of the binary-op KV apply path in
// rabia_tpu/apps/kvstore.py (apply_op_bin / apply_ops_bin), which stays
// the semantics owner (RABIA_PY_APPLY=1 forces it; the conformance gate
// in rabia_tpu/testing/conformance.py pins byte-identical results and
// state hashes between the two).
//
// Why: PR 2 moved the per-tick consensus path into C and the sweep wall
// moved to per-op CPython apply (docs/PERFORMANCE.md, transport tier).
// This kernel consumes a DECIDED WAVE of binary ops — the same records
// the wire already carries (gateway Submit -> ledger -> decide) — in one
// call per wave: route each op to its shard's open-addressing byte-key/
// byte-value table, mutate in place, and emit result frames packed as
// [u32 LE len][payload] records, the exact staging format
// rt_broadcast_frames (transport.cpp) consumes, so results can be handed
// to the transport out-pool without re-framing.
//
// Semantics mirrored element-for-element from kvstore.py:
//   - op encoding: u8 opcode (1=SET 2=GET 3=DEL 4=EXISTS 5=CLEAR 6=CAS)
//     | u16 LE keylen | key utf8 | (SET: value utf8)
//     | (CAS: u64 LE expected_version | value utf8)
//   - result: u8 kind (0 ok, 1 not_found, 2 error) | u32 LE version
//     | u8 has_value | value utf8
//   - validation: UTF-8 strict (overlongs/surrogates rejected, like
//     Python's strict codec), key length in CODE POINTS vs
//     max_key_length, value length in BYTES vs max_value_size; error
//     texts byte-identical to StoreError/str formats.
//   - stats: per-store total_operations/reads/writes increment exactly
//     where KVStore does (e.g. DEL of an absent key still counts a
//     write; a malformed op counts nothing; StoreFull counts before it
//     errors).
//
// Layout contract: one SkPlane owns all shard stores of a replica, one
// versioned append-only SKC_* counter block (observability, read
// zero-copy via ctypes like RKC_*), and one FrEvent flight ring (ABI of
// hostkernel.cpp / obs/flight.FR_DTYPE) written once per apply wave on
// the C path. Single-threaded: the engine loop is the only caller.

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <memory>
#include <mutex>
#include <new>
#include <vector>

#include "annotations.h"

extern "C" {

// ---------------------------------------------------------------------------
// counter block (versioned, append-only — docs/OBSERVABILITY.md)
// ---------------------------------------------------------------------------

enum {
  SKC_WAVES = 0,       // sk_apply_wave calls
  SKC_OPS,             // binary ops consumed
  SKC_SETS,            // successful SETs
  SKC_GETS,            // GET lookups (hit or miss)
  SKC_DELS,            // DEL attempts
  SKC_EXISTS,          // EXISTS probes
  SKC_CLEARS,          // CLEAR ops
  SKC_CAS_HITS,        // CAS applied (create or version match)
  SKC_CAS_MISSES,      // CAS not_found / version_conflict
  SKC_ERRORS,          // error result frames emitted
  SKC_BYTES_IN,        // op bytes consumed
  SKC_BYTES_OUT,       // result bytes emitted (framing included)
  SKC_REHASHES,        // table growth events
  SKC_DELTA_SNAPSHOTS, // sk_snapshot_delta emissions (durability plane)
  SKC_DELTA_ENTRIES,   // dirty entries exported by delta snapshots
  SKC_COUNT
};

static const int32_t SK_COUNTERS_VERSION = 2;

// flight ring: FrEvent ABI shared with hostkernel.cpp / obs/flight.py
static const int32_t SK_FLIGHT_VERSION = 1;
static const int32_t SK_FLIGHT_CAP = 1024;
static const uint8_t FRE_APPLY = 15;  // obs/flight.FRE_APPLY

struct FrEvent {
  uint64_t t_ns;
  uint64_t slot;
  uint64_t batch;
  uint32_t shard;
  uint16_t peer;
  uint8_t kind;
  uint8_t arg;
};
static_assert(sizeof(FrEvent) == 32, "FrEvent ABI is 32 bytes");

static inline uint64_t mono_ns() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (uint64_t)ts.tv_sec * 1000000000ull + (uint64_t)ts.tv_nsec;
}

// ---------------------------------------------------------------------------
// open-addressing store
// ---------------------------------------------------------------------------

enum : uint8_t { SLOT_EMPTY = 0, SLOT_FULL = 1, SLOT_TOMB = 2 };

struct Entry {
  uint8_t* kv;        // key bytes then value bytes (one allocation)
  uint64_t hash;
  uint64_t version;   // entry version (KVStore ValueEntry.version)
  uint64_t epoch;     // store mut_epoch at last mutation (delta tracking)
  double created;
  double updated;
  uint32_t klen;
  uint32_t vlen;
  uint32_t vcap;      // value capacity in kv after the key
  uint8_t state;
};

static inline uint64_t fnv1a(const uint8_t* p, int64_t n) {
  uint64_t h = 1469598103934665603ull;
  for (int64_t i = 0; i < n; i++) {
    h ^= p[i];
    h *= 1099511628211ull;
  }
  return h ? h : 1;  // 0 is reserved so hash comparison can short-cut
}

struct Store {
  std::vector<Entry> table;  // power-of-two capacity
  int64_t live = 0;          // SLOT_FULL count
  int64_t used = 0;          // FULL + TOMB (probe-length bound)
  uint64_t version = 0;      // store version (KVStore._version)
  // stats (KVStore.StoreStats parity)
  uint64_t total_operations = 0;
  uint64_t reads = 0;
  uint64_t writes = 0;
  // incremental-snapshot tracking (durability plane): entries stamped
  // with mut_epoch at mutation; sk_snapshot_mark bumps it, so "dirty" =
  // epoch == mut_epoch. Deletions since the last mark are logged by key
  // ([u16 LE klen][key] concatenated, bounded); CLEAR sets `cleared`
  // (the delta then clears-and-reinserts, which is complete because
  // every survivor postdates the clear).
  uint64_t mut_epoch = 1;
  std::vector<uint8_t> dels;
  uint32_t n_dels = 0;
  bool cleared = false;
  bool dels_overflow = false;  // log bound hit: next delta must be full

  void reset_table(int64_t cap) {
    table.assign((size_t)cap, Entry{});
    live = used = 0;
  }
};

// deletion-log bound: past this the delta degrades to a full snapshot
// (sk_snapshot_delta returns -3) instead of growing without limit
static const size_t SK_DELS_CAP = 1 << 20;

static inline void log_del(Store& st, const uint8_t* key, uint32_t klen) {
  if (st.cleared || st.dels_overflow) return;  // clear supersedes dels
  if (st.dels.size() + 2 + klen > SK_DELS_CAP) {
    st.dels_overflow = true;
    st.dels.clear();
    st.n_dels = 0;
    return;
  }
  const uint16_t kl = (uint16_t)klen;
  size_t w = st.dels.size();
  st.dels.resize(w + 2 + klen);
  memcpy(st.dels.data() + w, &kl, 2);
  memcpy(st.dels.data() + w + 2, key, klen);
  st.n_dels++;
}

// One result-staging lane: a growable [u32 len][payload] record buffer
// plus its offset index. lane0 is the legacy plane-owned staging (the
// asyncio runtime + Python scalar applies); the thread-per-shard-group
// runtime gives each worker its OWN lane (sk_apply_wave_lane), so N
// workers stage results without sharing a buffer.
struct SkLane {
  std::vector<uint8_t> out_buf;
  std::vector<int64_t> out_offs;
  bool staging = true;  // false while want=0: followers skip result frames
};

struct SkPlane {
  std::vector<Store> stores RABIA_GUARDED_BY(mu);
  int64_t max_keys;
  int64_t max_key_len;    // CODE POINTS (KVStoreConfig.max_key_length)
  int64_t max_value_size; // BYTES (KVStoreConfig.max_value_size)
  // relaxed atomics: multi-writer once apply lanes are configured (N
  // worker threads + the Python plane); layout-identical to uint64 for
  // the zero-copy sk_counters scrape
  std::atomic<uint64_t> counters[SKC_COUNT];
  static_assert(sizeof(std::atomic<uint64_t>) == sizeof(uint64_t),
                "counter block must read as a plain uint64 array");
  FrEvent flight[SK_FLIGHT_CAP];
  // relaxed atomic: written on the apply paths (possibly several lanes
  // at once — each writer claims a slot via fetch_add; a torn record is
  // metrics-grade noise), read lock-free via sk_flight_head
  std::atomic<uint64_t> flight_head{0};
  std::atomic<uint64_t> waves{0};
  // Plane lock (native-runtime hook): the GIL-free runtime thread owns
  // the apply path while the Python control plane still serves reads
  // (gateway read-index GETs, snapshot export). Mutating entry points
  // take it internally; read-side critical sections bracket themselves
  // with sk_plane_lock/sk_plane_unlock so borrowed pointers (sk_get's
  // value view) stay valid across the copy-out. Recursive, so a locked
  // reader can call helpers that lock internally (snapshot restore's
  // insert_raw loop). Uncontended cost is nanoseconds — invisible next
  // to a wave apply.
  rabia::RecursiveMutex mu{"statekernel.mu"};
  // wave result staging (plane-owned, reused and grown across waves so
  // a large wave can never overflow mid-apply): [u32 LE len][payload]
  // records in PROCESS order, with out_offs[i] = record i's start and a
  // final total — read zero-copy by the bridge via sk_out_buf/sk_out_offs
  SkLane lane0;
  // thread-per-shard-group apply lanes (sk_set_groups): lanes[g] is
  // worker g's private staging, lane_mus[g] its shard group's store
  // lock. A lane apply takes ONLY its group mutex; every plane-wide
  // entry point takes `mu` plus ALL group mutexes in index order (lock
  // order: mu -> lane_mus[0] -> lane_mus[1] -> …), so readers/snapshots
  // exclude every concurrently-applying worker while workers never
  // serialize against EACH OTHER. Vectors are stable while workers run
  // (sk_set_groups only executes with the runtime quiesced).
  std::vector<std::unique_ptr<SkLane>> lanes;
  std::vector<std::unique_ptr<rabia::RecursiveMutex>> lane_mus;
};

// Plane-wide critical section: `mu` + every configured group mutex.
// With no groups configured this is exactly the historical RecursiveLock
// on `mu` — the workers=1 path stays byte-identical.
struct PlaneGuard {
  SkPlane* p;
  size_t n_lanes;  // lanes locked at construction — sk_set_groups can
                   // GROW lane_mus inside a guard; the destructor must
                   // unlock exactly what the constructor locked
  explicit PlaneGuard(SkPlane* pp) RABIA_NO_TSA : p(pp) {
    p->mu.lock();
    n_lanes = p->lane_mus.size();
    for (size_t i = 0; i < n_lanes; i++) p->lane_mus[i]->lock();
  }
  ~PlaneGuard() RABIA_NO_TSA {
    for (size_t i = n_lanes; i-- > 0;) p->lane_mus[i]->unlock();
    p->mu.unlock();
  }
  PlaneGuard(const PlaneGuard&) = delete;
  PlaneGuard& operator=(const PlaneGuard&) = delete;
};

static void store_free_entries(Store& st) {
  for (auto& e : st.table)
    if (e.state == SLOT_FULL && e.kv) free(e.kv);
}

static bool store_rehash(Store& st, int64_t want_cap) {
  int64_t cap = 64;
  while (cap < want_cap) cap <<= 1;
  std::vector<Entry> old;
  old.swap(st.table);
  st.table.assign((size_t)cap, Entry{});
  st.used = 0;
  const uint64_t mask = (uint64_t)cap - 1;
  for (auto& e : old) {
    if (e.state != SLOT_FULL) continue;
    uint64_t i = e.hash & mask;
    while (st.table[i].state == SLOT_FULL) i = (i + 1) & mask;
    st.table[i] = e;
    st.used++;
  }
  return true;
}

// find the entry for (key, klen); returns index or -1. `free_out` (when
// non-null) receives the first insertable slot (tombstone or empty).
static int64_t store_find(Store& st, uint64_t h, const uint8_t* key,
                          int64_t klen, int64_t* free_out) {
  const uint64_t mask = (uint64_t)st.table.size() - 1;
  uint64_t i = h & mask;
  int64_t free_slot = -1;
  for (;;) {
    Entry& e = st.table[i];
    if (e.state == SLOT_EMPTY) {
      if (free_out) *free_out = free_slot >= 0 ? free_slot : (int64_t)i;
      return -1;
    }
    if (e.state == SLOT_TOMB) {
      if (free_slot < 0) free_slot = (int64_t)i;
    } else if (e.hash == h && e.klen == (uint32_t)klen &&
               memcmp(e.kv, key, (size_t)klen) == 0) {
      if (free_out) *free_out = -1;
      return (int64_t)i;
    }
    i = (i + 1) & mask;
  }
}

// strict UTF-8 validation; returns the code-point count or -1 on any
// invalid sequence (overlong forms and surrogates rejected — Python's
// strict codec parity)
static int64_t utf8_points(const uint8_t* p, int64_t n) {
  int64_t cp = 0, i = 0;
  while (i < n) {
    uint8_t c = p[i];
    if (c < 0x80) {
      i++;
      cp++;
      continue;
    }
    int len;
    uint32_t min, code;
    if ((c & 0xE0) == 0xC0) {
      len = 2;
      min = 0x80;
      code = c & 0x1F;
    } else if ((c & 0xF0) == 0xE0) {
      len = 3;
      min = 0x800;
      code = c & 0x0F;
    } else if ((c & 0xF8) == 0xF0) {
      len = 4;
      min = 0x10000;
      code = c & 0x07;
    } else {
      return -1;
    }
    if (i + len > n) return -1;
    for (int k = 1; k < len; k++) {
      uint8_t cc = p[i + k];
      if ((cc & 0xC0) != 0x80) return -1;
      code = (code << 6) | (cc & 0x3F);
    }
    if (code < min || code > 0x10FFFF ||
        (code >= 0xD800 && code <= 0xDFFF))
      return -1;
    i += len;
    cp++;
  }
  return cp;
}

// ---------------------------------------------------------------------------
// lifecycle
// ---------------------------------------------------------------------------

void* sk_plane_create(int64_t n_stores, int64_t max_keys,
                      int64_t max_key_len, int64_t max_value_size) {
  if (n_stores <= 0) return nullptr;
  SkPlane* p = new (std::nothrow) SkPlane();
  if (!p) return nullptr;
  {
    PlaneGuard lk(p);  // no other thread yet; analysis only
    p->stores.resize((size_t)n_stores);
    for (auto& st : p->stores) st.reset_table(64);
  }
  p->max_keys = max_keys;
  p->max_key_len = max_key_len;
  p->max_value_size = max_value_size;
  for (auto& c : p->counters) c.store(0, std::memory_order_relaxed);
  memset(p->flight, 0, sizeof(p->flight));
  return p;
}

void sk_plane_destroy(void* h) {
  SkPlane* p = (SkPlane*)h;
  if (!p) return;
  {
    PlaneGuard lk(p);  // last reference; analysis only
    for (auto& st : p->stores) store_free_entries(st);
  }
  delete p;
}

int32_t sk_counters_version() { return SK_COUNTERS_VERSION; }
int32_t sk_counters_count() { return SKC_COUNT; }
void* sk_counters(void* h) { return ((SkPlane*)h)->counters; }

int32_t sk_flight_version() { return SK_FLIGHT_VERSION; }
int32_t sk_flight_cap() { return SK_FLIGHT_CAP; }
int32_t sk_flight_record_size() { return (int32_t)sizeof(FrEvent); }
void* sk_flight(void* h) { return ((SkPlane*)h)->flight; }
uint64_t sk_flight_head(void* h) {
  return ((SkPlane*)h)->flight_head.load(std::memory_order_relaxed);
}

// Read-side critical-section brackets (native-runtime hook): hold the
// plane lock across sk_get + the value copy-out (or an export walk) so
// the GIL-free runtime thread's concurrent wave applies cannot free or
// rehash the borrowed bytes mid-read. Recursive with the internal
// mutator locks above.
// NO_TSA: a deliberately unbalanced C-API bracket over an opaque handle
// (the analysis cannot follow the caller's pairing; the debug lock-order
// checker and the TSan stress cell validate it at runtime instead)
void sk_plane_lock(void* h) RABIA_NO_TSA {
  SkPlane* p = (SkPlane*)h;
  p->mu.lock();
  // group lanes configured: the bracket must exclude every lane apply
  // too (a borrowed sk_get pointer must survive a worker's concurrent
  // wave into the same store) — same order as PlaneGuard
  for (auto& m : p->lane_mus) m->lock();
}
void sk_plane_unlock(void* h) RABIA_NO_TSA {
  SkPlane* p = (SkPlane*)h;
  for (auto it = p->lane_mus.rbegin(); it != p->lane_mus.rend(); ++it)
    (*it)->unlock();
  p->mu.unlock();
}

int64_t sk_store_count(void* h) {
  SkPlane* p = (SkPlane*)h;
  PlaneGuard lk(p);
  return (int64_t)p->stores.size();
}

int64_t sk_store_size(void* h, int64_t idx) {
  SkPlane* p = (SkPlane*)h;
  PlaneGuard lk(p);
  if (idx < 0 || (size_t)idx >= p->stores.size()) return -1;
  return p->stores[(size_t)idx].live;
}

uint64_t sk_store_version(void* h, int64_t idx) {
  SkPlane* p = (SkPlane*)h;
  PlaneGuard lk(p);
  if (idx < 0 || (size_t)idx >= p->stores.size()) return 0;
  return p->stores[(size_t)idx].version;
}

void sk_set_version(void* h, int64_t idx, uint64_t v) {
  SkPlane* p = (SkPlane*)h;
  PlaneGuard lk(p);
  if (idx < 0 || (size_t)idx >= p->stores.size()) return;
  p->stores[(size_t)idx].version = v;
}

// out[0..2] = total_operations, reads, writes (StoreStats parity)
void sk_store_stats(void* h, int64_t idx, uint64_t* out) {
  SkPlane* p = (SkPlane*)h;
  PlaneGuard lk(p);
  if (idx < 0 || (size_t)idx >= p->stores.size()) return;
  Store& st = p->stores[(size_t)idx];
  out[0] = st.total_operations;
  out[1] = st.reads;
  out[2] = st.writes;
}

void sk_add_stats(void* h, int64_t idx, uint64_t ops, uint64_t reads,
                  uint64_t writes) {
  SkPlane* p = (SkPlane*)h;
  PlaneGuard lk(p);
  if (idx < 0 || (size_t)idx >= p->stores.size()) return;
  Store& st = p->stores[(size_t)idx];
  st.total_operations += ops;
  st.reads += reads;
  st.writes += writes;
}

// ---------------------------------------------------------------------------
// direct access (reads / snapshot / restore)
// ---------------------------------------------------------------------------

// borrow the value bytes for `key`; returns vlen and fills *val_addr /
// *version_out, or -1 when absent. The pointer is valid until the next
// mutation of this store (single-threaded engine loop contract).
int64_t sk_get(void* h, int64_t idx, const uint8_t* key, int64_t klen,
               const uint8_t** val_addr, uint64_t* version_out) {
  SkPlane* p = (SkPlane*)h;
  PlaneGuard lk(p);
  if (idx < 0 || (size_t)idx >= p->stores.size()) return -1;
  Store& st = p->stores[(size_t)idx];
  int64_t at = store_find(st, fnv1a(key, klen), key, klen, nullptr);
  if (at < 0) return -1;
  Entry& e = st.table[(size_t)at];
  if (val_addr) *val_addr = e.kv + e.klen;
  if (version_out) *version_out = e.version;
  return (int64_t)e.vlen;
}

// bytes needed by sk_export for this store
int64_t sk_export_size(void* h, int64_t idx) {
  SkPlane* p = (SkPlane*)h;
  PlaneGuard lk(p);
  if (idx < 0 || (size_t)idx >= p->stores.size()) return -1;
  Store& st = p->stores[(size_t)idx];
  int64_t total = 0;
  for (auto& e : st.table)
    if (e.state == SLOT_FULL) total += 32 + e.klen + e.vlen;
  return total;
}

// export packed entries (arbitrary order; the Python side sorts):
// [u32 klen][u32 vlen][u64 version][f64 created][f64 updated][key][val]
// returns bytes written, or -(bytes needed) when cap is insufficient.
int64_t sk_export(void* h, int64_t idx, uint8_t* out, int64_t cap) {
  SkPlane* p = (SkPlane*)h;
  PlaneGuard lk(p);
  if (idx < 0 || (size_t)idx >= p->stores.size()) return -1;
  Store& st = p->stores[(size_t)idx];
  int64_t need = sk_export_size(h, idx);
  if (need > cap) return -need;
  uint8_t* w = out;
  for (auto& e : st.table) {
    if (e.state != SLOT_FULL) continue;
    memcpy(w, &e.klen, 4);
    memcpy(w + 4, &e.vlen, 4);
    memcpy(w + 8, &e.version, 8);
    memcpy(w + 16, &e.created, 8);
    memcpy(w + 24, &e.updated, 8);
    memcpy(w + 32, e.kv, e.klen);
    memcpy(w + 32 + e.klen, e.kv + e.klen, e.vlen);
    w += 32 + e.klen + e.vlen;
  }
  return w - out;
}

void sk_clear_store(void* h, int64_t idx) {
  SkPlane* p = (SkPlane*)h;
  PlaneGuard lk(p);
  if (idx < 0 || (size_t)idx >= p->stores.size()) return;
  Store& st = p->stores[(size_t)idx];
  store_free_entries(st);
  st.reset_table(64);
  st.cleared = true;
  st.dels.clear();
  st.n_dels = 0;
  st.dels_overflow = false;
}

// restore-path delete (no stats, no version bump, no deletion-log entry:
// the chain frame being restored already records this deletion, and the
// restored state simply lacks the key — nothing for the next delta to
// re-record). Returns 1 removed, 0 absent, -1 bad index.
int32_t sk_delete_raw(void* h, int64_t idx, const uint8_t* key,
                      int64_t klen) {
  SkPlane* p = (SkPlane*)h;
  PlaneGuard lk(p);
  if (idx < 0 || (size_t)idx >= p->stores.size()) return -1;
  Store& st = p->stores[(size_t)idx];
  int64_t at = store_find(st, fnv1a(key, klen), key, klen, nullptr);
  if (at < 0) return 0;
  Entry& e = st.table[(size_t)at];
  free(e.kv);
  e.kv = nullptr;
  e.state = SLOT_TOMB;
  st.live--;
  return 1;
}

// restore-path insert (no validation, no stats, no version bump — the
// caller sets the store version explicitly after loading)
int32_t sk_insert_raw(void* h, int64_t idx, const uint8_t* key,
                      int64_t klen, const uint8_t* val, int64_t vlen,
                      uint64_t version, double created, double updated) {
  SkPlane* p = (SkPlane*)h;
  PlaneGuard lk(p);
  if (idx < 0 || (size_t)idx >= p->stores.size()) return -1;
  Store& st = p->stores[(size_t)idx];
  if (st.used * 4 >= (int64_t)st.table.size() * 3)
    store_rehash(st, (int64_t)st.table.size() * 2);
  uint64_t hsh = fnv1a(key, klen);
  int64_t free_slot = -1;
  int64_t at = store_find(st, hsh, key, klen, &free_slot);
  uint8_t* kv = (uint8_t*)malloc((size_t)(klen + vlen) + 1);
  if (!kv) return -2;
  memcpy(kv, key, (size_t)klen);
  memcpy(kv + klen, val, (size_t)vlen);
  if (at >= 0) {
    Entry& e = st.table[(size_t)at];
    free(e.kv);
    e.kv = kv;
    e.vlen = e.vcap = (uint32_t)vlen;
    e.version = version;
    e.epoch = st.mut_epoch;
    e.created = created;
    e.updated = updated;
    return 0;
  }
  Entry& e = st.table[(size_t)free_slot];
  if (e.state != SLOT_TOMB) st.used++;
  e.state = SLOT_FULL;
  e.kv = kv;
  e.hash = hsh;
  e.klen = (uint32_t)klen;
  e.vlen = e.vcap = (uint32_t)vlen;
  e.version = version;
  e.epoch = st.mut_epoch;
  e.created = created;
  e.updated = updated;
  st.live++;
  return 0;
}

// ---------------------------------------------------------------------------
// the apply wave
// ---------------------------------------------------------------------------

// result framing: [u32 LE len][payload] records — the rt_broadcast_frames
// staging format — appended to the plane-owned growable buffer (state
// mutations can therefore never be lost to an output-capacity error).

static inline void res_head(SkLane& L, uint8_t kind, uint64_t version,
                            int32_t has_value, int64_t value_len) {
  if (!L.staging) return;
  int64_t payload = 6 + (has_value ? value_len : 0);
  size_t w = L.out_buf.size();
  L.out_buf.resize(w + 4 + (size_t)payload);
  uint8_t* out = L.out_buf.data() + w;
  uint32_t plen = (uint32_t)payload;
  memcpy(out, &plen, 4);
  out[4] = kind;
  uint32_t v32 = (uint32_t)(version & 0xFFFFFFFFull);
  memcpy(out + 5, &v32, 4);
  out[9] = has_value ? 1 : 0;
}

static inline void res_simple(SkLane& L, uint8_t kind, uint64_t version) {
  res_head(L, kind, version, 0, 0);
}

static inline void res_value(SkLane& L, uint8_t kind, uint64_t version,
                             const uint8_t* val, int64_t vlen) {
  if (!L.staging) return;
  res_head(L, kind, version, 1, vlen);
  memcpy(L.out_buf.data() + L.out_buf.size() - vlen, val, (size_t)vlen);
}

static inline void res_text(SkLane& L, uint8_t kind, uint64_t version,
                            const char* text) {
  res_value(L, kind, version, (const uint8_t*)text,
            (int64_t)strlen(text));
}

// Apply ops data[offs[j]..offs[j+1]] for j in [op_lo, op_hi) against
// store st; results + record offsets appended to lane L's buffers.
// Caller holds a lock covering `st` (the plane guard, or the store's
// group mutex on a worker lane).
static void apply_ops_store(SkPlane* p, SkLane& L, Store& st,
                            const uint8_t* data, const int64_t* offs,
                            int64_t op_lo, int64_t op_hi,
                            double now) RABIA_NO_TSA {
  char tmp[128];
  for (int64_t j = op_lo; j < op_hi; j++) {
    const uint8_t* op = data + offs[j];
    const int64_t n = offs[j + 1] - offs[j];
    if (L.staging) L.out_offs.push_back((int64_t)L.out_buf.size());
    p->counters[SKC_OPS]++;
    p->counters[SKC_BYTES_IN] += (uint64_t)n;

    if (n < 1) {
      // Python: data[0] raises IndexError -> "malformed op: index out
      // of range"
      p->counters[SKC_ERRORS]++;
      res_text(L, 2, 0, "malformed op: index out of range");
      continue;
    }
    const uint8_t opcode = op[0];
    // int.from_bytes(data[1:3]) parity on short buffers: missing bytes
    // read as absent (little-endian of the available slice)
    int64_t klen = 0;
    if (n >= 2) klen = op[1];
    if (n >= 3) klen |= ((int64_t)op[2]) << 8;
    if (3 + klen > n) {
      p->counters[SKC_ERRORS]++;
      snprintf(tmp, sizeof(tmp),
               "malformed op: key length %lld exceeds payload",
               (long long)klen);
      res_text(L, 2, 0, tmp);
      continue;
    }
    const uint8_t* key = op + 3;
    const int64_t key_points = utf8_points(key, klen);
    if (key_points < 0) {
      p->counters[SKC_ERRORS]++;
      res_text(L, 2, 0, "malformed op: invalid utf-8");
      continue;
    }

    switch (opcode) {
      case 1: {  // SET
        const uint8_t* val = op + 3 + klen;
        const int64_t vlen = n - 3 - klen;
        if (utf8_points(val, vlen) < 0) {
          p->counters[SKC_ERRORS]++;
          res_text(L, 2, 0, "malformed op: invalid utf-8");
          break;
        }
        // _validate_key / _validate_value run BEFORE stats (KVStore.set)
        if (klen == 0) {
          p->counters[SKC_ERRORS]++;
          res_text(L, 2, 0, "StoreError: key_empty");
          break;
        }
        if (key_points > p->max_key_len) {
          p->counters[SKC_ERRORS]++;
          snprintf(tmp, sizeof(tmp), "StoreError: key_too_long: %lld > %lld",
                   (long long)key_points, (long long)p->max_key_len);
          res_text(L, 2, 0, tmp);
          break;
        }
        if (vlen > p->max_value_size) {
          p->counters[SKC_ERRORS]++;
          res_text(L, 2, 0, "StoreError: value_too_large");
          break;
        }
        st.total_operations++;
        st.writes++;
        uint64_t hsh = fnv1a(key, klen);
        int64_t free_slot = -1;
        int64_t at = store_find(st, hsh, key, klen, &free_slot);
        if (at < 0) {
          if (st.live >= p->max_keys) {
            p->counters[SKC_ERRORS]++;
            res_text(L, 2, 0, "StoreError: store_full");
            break;
          }
          uint8_t* kv = (uint8_t*)malloc((size_t)(klen + vlen) + 1);
          if (!kv) {
            p->counters[SKC_ERRORS]++;
            res_text(L, 2, 0, "internal: oom");
            break;
          }
          memcpy(kv, key, (size_t)klen);
          memcpy(kv + klen, val, (size_t)vlen);
          st.version++;
          Entry& e = st.table[(size_t)free_slot];
          if (e.state != SLOT_TOMB) st.used++;
          e.state = SLOT_FULL;
          e.kv = kv;
          e.hash = hsh;
          e.klen = (uint32_t)klen;
          e.vlen = e.vcap = (uint32_t)vlen;
          e.version = st.version;
          e.epoch = st.mut_epoch;
          e.created = e.updated = now;
          st.live++;
          if (st.used * 4 >= (int64_t)st.table.size() * 3) {
            store_rehash(st, (int64_t)st.table.size() * 2);
            p->counters[SKC_REHASHES]++;
          }
        } else {
          Entry& e = st.table[(size_t)at];
          if ((uint32_t)vlen > e.vcap) {
            uint8_t* kv = (uint8_t*)realloc(e.kv, (size_t)(klen + vlen) + 1);
            if (!kv) {
              p->counters[SKC_ERRORS]++;
              res_text(L, 2, 0, "internal: oom");
              break;
            }
            e.kv = kv;
            e.vcap = (uint32_t)vlen;
          }
          memcpy(e.kv + klen, val, (size_t)vlen);
          e.vlen = (uint32_t)vlen;
          st.version++;
          e.version = st.version;
          e.epoch = st.mut_epoch;
          e.updated = now;
        }
        p->counters[SKC_SETS]++;
        res_simple(L, 0, st.version);
        break;
      }
      case 2: {  // GET
        st.total_operations++;
        st.reads++;
        p->counters[SKC_GETS]++;
        int64_t at = store_find(st, fnv1a(key, klen), key, klen, nullptr);
        if (at < 0) {
          res_simple(L, 1, 0);
        } else {
          Entry& e = st.table[(size_t)at];
          res_value(L, 0, e.version, e.kv + e.klen, e.vlen);
        }
        break;
      }
      case 3: {  // DEL
        st.total_operations++;
        st.writes++;
        p->counters[SKC_DELS]++;
        uint64_t hsh = fnv1a(key, klen);
        int64_t at = store_find(st, hsh, key, klen, nullptr);
        if (at < 0) {
          res_simple(L, 1, 0);
        } else {
          Entry& e = st.table[(size_t)at];
          st.version++;
          // result carries the OLD value and the NEW store version
          res_value(L, 0, st.version, e.kv + e.klen, e.vlen);
          log_del(st, key, (uint32_t)klen);
          free(e.kv);
          e.kv = nullptr;
          e.state = SLOT_TOMB;
          st.live--;
        }
        break;
      }
      case 4: {  // EXISTS
        st.total_operations++;
        st.reads++;
        p->counters[SKC_EXISTS]++;
        int64_t at = store_find(st, fnv1a(key, klen), key, klen, nullptr);
        res_text(L, 0, 0, at >= 0 ? "true" : "false");
        break;
      }
      case 5: {  // CLEAR
        st.total_operations++;
        st.writes++;
        p->counters[SKC_CLEARS]++;
        int64_t count = st.live;
        store_free_entries(st);
        st.reset_table(64);
        st.cleared = true;
        st.dels.clear();
        st.n_dels = 0;
        st.dels_overflow = false;
        st.version++;
        snprintf(tmp, sizeof(tmp), "%lld", (long long)count);
        res_text(L, 0, 0, tmp);
        break;
      }
      case 6: {  // CAS
        if (3 + klen + 8 > n) {
          p->counters[SKC_ERRORS]++;
          res_text(L, 2, 0,
                   "malformed op: cas payload shorter than its "
                   "version field");
          break;
        }
        uint64_t expected;
        memcpy(&expected, op + 3 + klen, 8);
        const uint8_t* val = op + 3 + klen + 8;
        const int64_t vlen = n - 3 - klen - 8;
        if (utf8_points(val, vlen) < 0) {
          p->counters[SKC_ERRORS]++;
          res_text(L, 2, 0, "malformed op: invalid utf-8");
          break;
        }
        if (klen == 0) {
          p->counters[SKC_ERRORS]++;
          res_text(L, 2, 0, "StoreError: key_empty");
          break;
        }
        if (key_points > p->max_key_len) {
          p->counters[SKC_ERRORS]++;
          snprintf(tmp, sizeof(tmp), "StoreError: key_too_long: %lld > %lld",
                   (long long)key_points, (long long)p->max_key_len);
          res_text(L, 2, 0, tmp);
          break;
        }
        if (vlen > p->max_value_size) {
          p->counters[SKC_ERRORS]++;
          res_text(L, 2, 0, "StoreError: value_too_large");
          break;
        }
        st.total_operations++;
        st.writes++;
        uint64_t hsh = fnv1a(key, klen);
        int64_t free_slot = -1;
        int64_t at = store_find(st, hsh, key, klen, &free_slot);
        if (at < 0) {
          if (expected != 0) {
            p->counters[SKC_CAS_MISSES]++;
            res_simple(L, 1, 0);  // not_found
            break;
          }
          if (st.live >= p->max_keys) {
            p->counters[SKC_ERRORS]++;
            res_text(L, 2, 0, "StoreError: store_full");
            break;
          }
          uint8_t* kv = (uint8_t*)malloc((size_t)(klen + vlen) + 1);
          if (!kv) {
            p->counters[SKC_ERRORS]++;
            res_text(L, 2, 0, "internal: oom");
            break;
          }
          memcpy(kv, key, (size_t)klen);
          memcpy(kv + klen, val, (size_t)vlen);
          st.version++;
          Entry& e = st.table[(size_t)free_slot];
          if (e.state != SLOT_TOMB) st.used++;
          e.state = SLOT_FULL;
          e.kv = kv;
          e.hash = hsh;
          e.klen = (uint32_t)klen;
          e.vlen = e.vcap = (uint32_t)vlen;
          e.version = st.version;
          e.epoch = st.mut_epoch;
          e.created = e.updated = now;
          st.live++;
          if (st.used * 4 >= (int64_t)st.table.size() * 3) {
            store_rehash(st, (int64_t)st.table.size() * 2);
            p->counters[SKC_REHASHES]++;
          }
          p->counters[SKC_CAS_HITS]++;
          res_simple(L, 0, st.version);
          break;
        }
        Entry& e = st.table[(size_t)at];
        if (e.version != expected) {
          p->counters[SKC_CAS_MISSES]++;
          p->counters[SKC_ERRORS]++;
          res_text(L, 2, e.version, "version_conflict");
          break;
        }
        if ((uint32_t)vlen > e.vcap) {
          uint8_t* kv = (uint8_t*)realloc(e.kv, (size_t)(klen + vlen) + 1);
          if (!kv) {
            p->counters[SKC_ERRORS]++;
            res_text(L, 2, 0, "internal: oom");
            break;
          }
          e.kv = kv;
          e.vcap = (uint32_t)vlen;
        }
        memcpy(e.kv + klen, val, (size_t)vlen);
        e.vlen = (uint32_t)vlen;
        st.version++;
        e.version = st.version;
        e.epoch = st.mut_epoch;
        e.updated = now;
        p->counters[SKC_CAS_HITS]++;
        res_simple(L, 0, st.version);
        break;
      }
      default: {
        p->counters[SKC_ERRORS]++;
        snprintf(tmp, sizeof(tmp), "unknown opcode %d", (int)opcode);
        res_text(L, 2, 0, tmp);
        break;
      }
    }
  }
}

static void flight_wave(SkPlane* p, int64_t first_shard, int64_t total_ops) {
  // one FRE_APPLY record per wave on the C path (the engine's per-slot
  // Python records stay the lifecycle source on both tick paths)
  // fetch_add slot claim: several apply lanes may record concurrently;
  // each writer owns its claimed slot (a reader racing a write sees one
  // torn record — metrics-grade, documented in OBSERVABILITY.md)
  const uint64_t head = p->flight_head.fetch_add(1, std::memory_order_relaxed);
  FrEvent& ev = p->flight[head % SK_FLIGHT_CAP];
  ev.t_ns = mono_ns();
  ev.slot = p->waves.fetch_add(1, std::memory_order_relaxed);
  ev.batch = (uint64_t)total_ops;
  ev.shard = (uint32_t)(first_shard < 0 ? 0 : first_shard);
  ev.peer = 0xFFFF;
  ev.kind = FRE_APPLY;
  ev.arg = (uint8_t)(total_ops > 255 ? 255 : total_ops);
}

// wave result staging accessors (valid until the next apply call)
void* sk_out_buf(void* h) { return ((SkPlane*)h)->lane0.out_buf.data(); }
void* sk_out_offs(void* h) { return ((SkPlane*)h)->lane0.out_offs.data(); }
int64_t sk_out_count(void* h) {
  return (int64_t)((SkPlane*)h)->lane0.out_offs.size();
}

// Per-worker-lane staging accessors (sk_apply_wave_lane results).
void* sk_out_buf_lane(void* h, int32_t lane) {
  SkPlane* p = (SkPlane*)h;
  if (lane < 0 || (size_t)lane >= p->lanes.size()) return nullptr;
  return p->lanes[(size_t)lane]->out_buf.data();
}
void* sk_out_offs_lane(void* h, int32_t lane) {
  SkPlane* p = (SkPlane*)h;
  if (lane < 0 || (size_t)lane >= p->lanes.size()) return nullptr;
  return p->lanes[(size_t)lane]->out_offs.data();
}

// Configure per-shard-group apply lanes: ngroups worker lanes, each with
// its own staging buffers and group mutex (the runtime's shard→group
// partition is contiguous; group membership only matters to the CALLER —
// the plane just guarantees lane g's applies exclude plane-wide entry
// points and nothing else). ngroups=0 clears. MUST be called while no
// worker is inside a lane apply (the runtime bridge configures before
// rtm_start). Returns 0, or -1 on a bad count.
int32_t sk_set_groups(void* h, int32_t ngroups) {
  SkPlane* p = (SkPlane*)h;
  if (!p || ngroups < 0 || ngroups > 64) return -1;
  PlaneGuard lk(p);
  if (ngroups == 0) {
    // lanes retained (stable addresses for stragglers); mutexes too
    return 0;
  }
  static const struct LaneNames {
    char n[64][24];
    LaneNames() {
      for (int i = 0; i < 64; i++)
        snprintf(n[i], sizeof(n[i]), "statekernel.lane%02d", i);
    }
  } kLaneNames;
  while ((int32_t)p->lanes.size() < ngroups) {
    const size_t i = p->lanes.size();
    p->lanes.push_back(std::make_unique<SkLane>());
    p->lane_mus.push_back(
        std::make_unique<rabia::RecursiveMutex>(kLaneNames.n[i & 63]));
  }
  return 0;
}

// The lane-parameterized wave apply core. Caller holds a lock covering
// every store the wave touches (PlaneGuard, or one group mutex when the
// wave is group-pure).
static int64_t apply_wave_into(SkPlane* p, SkLane& L, const uint8_t* data,
                               const int64_t* cmd_offsets,
                               const int64_t* shards, const int64_t* starts,
                               const int64_t* idxs, int64_t n_idx,
                               double now, int32_t want) RABIA_NO_TSA {
  L.staging = want != 0;
  L.out_buf.clear();
  L.out_offs.clear();
  int64_t first_shard = -1;
  int64_t total_ops = 0;
  const int64_t n_stores = (int64_t)p->stores.size();
  for (int64_t i = 0; i < n_idx; i++) {
    const int64_t idx = idxs[i];
    int64_t s = shards[idx] % n_stores;
    if (s < 0) s += n_stores;
    if (first_shard < 0) first_shard = s;
    Store& st = p->stores[(size_t)s];
    const int64_t lo = starts[idx], hi = starts[idx + 1];
    total_ops += hi - lo;
    apply_ops_store(p, L, st, data, cmd_offsets, lo, hi, now);
  }
  if (L.staging) L.out_offs.push_back((int64_t)L.out_buf.size());
  p->counters[SKC_WAVES]++;
  p->counters[SKC_BYTES_OUT] += (uint64_t)L.out_buf.size();
  flight_wave(p, first_shard, total_ops);
  return (int64_t)L.out_buf.size();
}

// Apply one decided wave: for each selected covered-index `idxs[i]` the
// ops are commands [starts[idx], starts[idx+1]) of the block, each op
// being data[cmd_offsets[j] .. cmd_offsets[j+1]], routed to store
// shards[idx]. Results are staged into the plane's growable out buffer
// as [u32 LE len][payload] records in process order (sk_out_buf /
// sk_out_offs; the final out_offs entry is the total byte count), the
// exact record format rt_broadcast_frames consumes. Returns bytes
// staged, or -2 on a bad handle.
int64_t sk_apply_wave(void* h, const uint8_t* data,
                      const int64_t* cmd_offsets, const int64_t* shards,
                      const int64_t* starts, const int64_t* idxs,
                      int64_t n_idx, double now, int32_t want) {
  SkPlane* p = (SkPlane*)h;
  if (!p || n_idx < 0) return -2;
  PlaneGuard lk(p);
  return apply_wave_into(p, p->lane0, data, cmd_offsets, shards, starts,
                         idxs, n_idx, now, want);
}

// Thread-per-shard-group wave apply: worker `lane`'s GROUP-PURE wave
// (every shard in the wave belongs to the lane's group) applies under
// ONLY that group's mutex, staging results into the lane's private
// buffers (sk_out_buf_lane / sk_out_offs_lane — no further lock needed
// to read them: the lane has a single owner thread). N workers applying
// to different groups no longer serialize on the plane mutex; plane-wide
// readers (sk_get, exports, snapshots) exclude every lane by taking all
// group mutexes through the PlaneGuard.
int64_t sk_apply_wave_lane(void* h, int32_t lane, const uint8_t* data,
                           const int64_t* cmd_offsets, const int64_t* shards,
                           const int64_t* starts, const int64_t* idxs,
                           int64_t n_idx, double now, int32_t want) {
  SkPlane* p = (SkPlane*)h;
  if (!p || n_idx < 0) return -2;
  if (lane < 0 || (size_t)lane >= p->lanes.size()) return -2;
  rabia::RecursiveLock lg(*p->lane_mus[(size_t)lane]);
  return apply_wave_into(p, *p->lanes[(size_t)lane], data, cmd_offsets,
                         shards, starts, idxs, n_idx, now, want);
}

// ---------------------------------------------------------------------------
// incremental snapshots (durability plane — docs/DURABILITY.md)
// ---------------------------------------------------------------------------
//
// Delta frame for one store (emitted by sk_snapshot_delta, decoded by
// persistence/native_wal.py, which is the semantics owner of the
// surrounding file format):
//   u8 flags (bit0: cleared — restore must clear the store first)
//   u32 LE n_del  | n_del * (u16 LE klen | key)
//   u32 LE n_ent  | n_ent * sk_export entry
//                   ([u32 klen][u32 vlen][u64 version][f64 created]
//                    [f64 updated][key][val])
// where n_ent covers exactly the entries mutated since the last
// sk_snapshot_mark. Restore applies dels BEFORE entries (a deleted-then-
// reset key appears in both; the insert must win).

// bytes a delta frame needs, or -3 when the deletion log overflowed and
// only a FULL snapshot is faithful, or -1 on a bad store index.
int64_t sk_snapshot_delta_size(void* h, int64_t idx) {
  SkPlane* p = (SkPlane*)h;
  PlaneGuard lk(p);
  if (idx < 0 || (size_t)idx >= p->stores.size()) return -1;
  Store& st = p->stores[(size_t)idx];
  if (st.dels_overflow) return -3;
  int64_t total = 1 + 4 + (int64_t)st.dels.size() + 4;
  for (auto& e : st.table)
    if (e.state == SLOT_FULL && e.epoch == st.mut_epoch)
      total += 32 + e.klen + e.vlen;
  return total;
}

// emit the delta frame; returns bytes written, -(bytes needed) when cap
// is insufficient, -3 on deletion-log overflow (caller does a full
// snapshot instead), -1 on a bad index. Does NOT advance the mark —
// call sk_snapshot_mark once the frame is durably on disk, so a failed
// checkpoint write never loses dirty state.
int64_t sk_snapshot_delta(void* h, int64_t idx, uint8_t* out, int64_t cap) {
  SkPlane* p = (SkPlane*)h;
  PlaneGuard lk(p);
  if (idx < 0 || (size_t)idx >= p->stores.size()) return -1;
  Store& st = p->stores[(size_t)idx];
  if (st.dels_overflow) return -3;
  const int64_t need = sk_snapshot_delta_size(h, idx);
  if (need > cap) return -need;
  uint8_t* w = out;
  *w++ = st.cleared ? 1 : 0;
  memcpy(w, &st.n_dels, 4);
  w += 4;
  if (!st.dels.empty()) {
    // empty-log guard: memcpy's src is declared nonnull and an empty
    // vector's data() may be null (UBSan stress finding, round 13)
    memcpy(w, st.dels.data(), st.dels.size());
  }
  w += st.dels.size();
  uint32_t n_ent = 0;
  uint8_t* ent_count_at = w;
  w += 4;
  for (auto& e : st.table) {
    if (e.state != SLOT_FULL || e.epoch != st.mut_epoch) continue;
    memcpy(w, &e.klen, 4);
    memcpy(w + 4, &e.vlen, 4);
    memcpy(w + 8, &e.version, 8);
    memcpy(w + 16, &e.created, 8);
    memcpy(w + 24, &e.updated, 8);
    memcpy(w + 32, e.kv, e.klen);
    memcpy(w + 32 + e.klen, e.kv + e.klen, e.vlen);
    w += 32 + e.klen + e.vlen;
    n_ent++;
  }
  memcpy(ent_count_at, &n_ent, 4);
  p->counters[SKC_DELTA_SNAPSHOTS]++;
  p->counters[SKC_DELTA_ENTRIES] += n_ent;
  return w - out;
}

// advance the snapshot mark: everything emitted by the delta just
// written is now "clean"; future mutations stamp the new epoch.
void sk_snapshot_mark(void* h, int64_t idx) {
  SkPlane* p = (SkPlane*)h;
  PlaneGuard lk(p);
  if (idx < 0 || (size_t)idx >= p->stores.size()) return;
  Store& st = p->stores[(size_t)idx];
  st.mut_epoch++;
  st.dels.clear();
  st.n_dels = 0;
  st.cleared = false;
  st.dels_overflow = false;
}

// Scalar-lane convenience: apply `n_ops` ops (offsets over `data`)
// against ONE store. Same staging contract as sk_apply_wave.
int64_t sk_apply_ops(void* h, int64_t store_idx, const uint8_t* data,
                     const int64_t* cmd_offsets, int64_t n_ops, double now,
                     int32_t want) {
  SkPlane* p = (SkPlane*)h;
  if (!p) return -2;
  PlaneGuard lk(p);
  if (store_idx < 0 || (size_t)store_idx >= p->stores.size()) return -2;
  SkLane& L = p->lane0;
  L.staging = want != 0;
  L.out_buf.clear();
  L.out_offs.clear();
  Store& st = p->stores[(size_t)store_idx];
  apply_ops_store(p, L, st, data, cmd_offsets, 0, n_ops, now);
  if (L.staging) L.out_offs.push_back((int64_t)L.out_buf.size());
  p->counters[SKC_WAVES]++;
  p->counters[SKC_BYTES_OUT] += (uint64_t)L.out_buf.size();
  flight_wave(p, store_idx, n_ops);
  return (int64_t)L.out_buf.size();
}

}  // extern "C"
