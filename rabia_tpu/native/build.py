"""Lazy g++ build + loaders for the native libraries.

Four artifacts, all digest-keyed and built on first use:
- ``transport.cpp``   -> ctypes CDLL (the TCP data plane)
- ``codec.cpp``       -> CPython extension module (the binary message
  codec, SURVEY §2 C9's native component)
- ``hostkernel.cpp``  -> ctypes CDLL (the engine's per-activation
  consensus step; numpy twin in kernel/host_driver.py stays the
  semantics owner)
- ``statekernel.cpp`` -> ctypes CDLL (the native apply plane: the
  binary-op KV state machine; the Python apply path in
  apps/kvstore.py stays the semantics owner, RABIA_PY_APPLY=1
  forces it)
- ``runtime.cpp``     -> ctypes CDLL (the native engine runtime: a
  GIL-free io/tick thread gluing transport -> hostkernel ->
  statekernel; the asyncio orchestration stays the semantics owner,
  RABIA_PY_RUNTIME=1 forces it)
- ``sessionkernel.cpp`` -> ctypes CDLL (the native gateway plane: the
  client session/dedup table; the Python SessionTable in
  gateway/session.py stays the semantics owner, RABIA_PY_GATEWAY=1
  forces it)
"""

from __future__ import annotations

import ctypes
import hashlib
import importlib.util
import os
import subprocess
import sysconfig
import threading
from pathlib import Path

from rabia_tpu.core.errors import InternalError

_HERE = Path(__file__).parent
_SRC = _HERE / "transport.cpp"
_CODEC_SRC = _HERE / "codec.cpp"
_HK_SRC = _HERE / "hostkernel.cpp"
_SK_SRC = _HERE / "statekernel.cpp"
_LOCK = threading.Lock()
_CACHED: ctypes.CDLL | None = None
_CODEC_CACHED = None
_CODEC_FAILED: str | None = None
_HK_CACHED: ctypes.CDLL | None = None
_HK_FAILED: str | None = None
_SK_CACHED: ctypes.CDLL | None = None
_SK_FAILED: str | None = None


# Every kernel includes the annotations header; its digest keys rebuilds
# exactly like the kernel's own source (a changed macro or lock wrapper
# must invalidate every cached .so).
_ANNOT = _HERE / "annotations.h"


def _flavor() -> tuple[str, list[str]]:
    """(digest-suffix, extra flags) of the current build FLAVOR.

    ``RABIA_NATIVE_DEBUG=1`` selects the debug flavor: the lock-order
    checker in annotations.h compiles in (acquisition-order inversions
    and non-recursive double locks abort with both lock names), plus
    debug symbols. The suffix keeps flavors side by side in the cache —
    switching the env back and forth never rebuilds."""
    if os.environ.get("RABIA_NATIVE_DEBUG") == "1":
        return "-dbg", ["-DRABIA_NATIVE_DEBUG=1", "-g"]
    return "", []


def _digest_of(*srcs: Path) -> str:
    h = hashlib.blake2s(digest_size=8)
    for s in srcs:
        h.update(s.read_bytes())
    h.update(_flavor()[0].encode())
    return h.hexdigest()


def _src_digest() -> str:
    return _digest_of(_SRC, _ANNOT)


def lib_path() -> Path:
    """Target .so path, keyed by source digest so edits force rebuilds."""
    return _HERE / f"_transport_{_src_digest()}{_flavor()[0]}.so"


def _compile(
    src: Path, target: Path, extra_args: list[str], stale_glob: str,
    what: str, link_args: list[str] | None = None,
) -> None:
    # compile to a private temp path, then atomically rename: an
    # interrupted or concurrent build (the lock is per-process only) must
    # never leave a truncated .so at the digest-keyed path, which would be
    # trusted forever by the exists() fast path
    tmp = target.with_suffix(f".tmp{os.getpid()}")
    cmd = [
        "g++",
        "-O2",
        "-std=c++17",
        "-shared",
        "-fPIC",
        *_flavor()[1],
        *extra_args,
        str(src),
        "-o",
        str(tmp),
        # libraries must follow the objects that use them (GNU ld
        # resolves left to right)
        *(link_args or []),
    ]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        tmp.unlink(missing_ok=True)
        raise InternalError(
            f"native {what} build failed:\n{proc.stderr[-2000:]}"
        )
    os.replace(tmp, target)
    # clean up stale builds of older source versions (same flavor only:
    # regular and -dbg artifacts coexist, keyed by their suffix)
    dbg = _flavor()[0] == "-dbg"
    for old in _HERE.glob(stale_glob):
        if old != target and old.name.endswith("-dbg.so") == dbg:
            try:
                old.unlink()
            except OSError:
                pass


def _build(target: Path) -> None:
    _compile(_SRC, target, ["-pthread"], "_transport_*.so", "transport")


def _codec_path() -> Path:
    return _HERE / f"_codec_{_digest_of(_CODEC_SRC)}{_flavor()[0]}.so"


def _build_codec(target: Path) -> None:
    import numpy as np

    _compile(
        _CODEC_SRC,
        target,
        [
            f"-I{sysconfig.get_paths()['include']}",
            f"-I{np.get_include()}",
        ],
        "_codec_*.so",
        "codec",
        link_args=["-lz"],  # SyncResponse snapshot (de)compression
    )


def load_codec():
    """Build (if needed) and import the codec extension module.

    Returns the module, or None when unavailable (no compiler, build
    failure) — callers fall back to the Python codec. The failure is
    remembered so a broken toolchain costs one build attempt, not one
    per serializer construction. ``RABIA_PY_CODEC=1`` forces the Python
    codec (debug/differential testing)."""
    global _CODEC_CACHED, _CODEC_FAILED
    if os.environ.get("RABIA_PY_CODEC"):
        return None
    with _LOCK:
        if _CODEC_CACHED is not None:
            return _CODEC_CACHED
        if _CODEC_FAILED is not None:
            return None
        try:
            target = _codec_path()
            if not target.exists():
                _build_codec(target)
            spec = importlib.util.spec_from_file_location(
                "rabia_native_codec", target
            )
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
        except Exception as e:  # noqa: BLE001 - any failure means fallback
            _CODEC_FAILED = str(e)
            return None
        _CODEC_CACHED = mod
        return mod


def _hk_path() -> Path:
    return _HERE / f"_hostkernel_{_digest_of(_HK_SRC)}{_flavor()[0]}.so"


def load_hostkernel() -> ctypes.CDLL | None:
    """Build (if needed) and dlopen the host-kernel step library.

    Returns the CDLL with prototypes set, or None when unavailable —
    callers fall back to the numpy step, which stays the semantics
    owner. ``RABIA_PY_HOSTKERNEL=1`` forces the numpy step
    (debug/differential testing)."""
    global _HK_CACHED, _HK_FAILED
    if os.environ.get("RABIA_PY_HOSTKERNEL"):
        return None
    with _LOCK:
        if _HK_CACHED is not None:
            return _HK_CACHED
        if _HK_FAILED is not None:
            return None
        try:
            target = _hk_path()
            if not target.exists():
                _compile(
                    _HK_SRC, target, ["-O3"], "_hostkernel_*.so",
                    "hostkernel",
                )
            lib = ctypes.CDLL(os.fspath(target))
        except Exception as e:  # noqa: BLE001 - any failure means fallback
            _HK_FAILED = str(e)
            return None
        # pointer args are c_void_p: callers pass raw ndarray.ctypes.data
        # ints (cheapest ctypes marshalling on the per-activation path)
        p = ctypes.c_void_p
        lib.rk_node_step.restype = None
        lib.rk_node_step.argtypes = [
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_int32,
            ctypes.c_uint32, ctypes.c_uint32,
            p, p, p, p, p, p, p, p, p, p, p,
            p, p, p, p,
        ]
        if hasattr(lib, "rk_node_step_ex"):
            # rk_node_step + coin-flip accounting (chaos-plane telemetry)
            lib.rk_node_step_ex.restype = None
            lib.rk_node_step_ex.argtypes = [
                ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
                ctypes.c_int32, ctypes.c_int32,
                ctypes.c_uint32, ctypes.c_uint32,
                p, p, p, p, p, p, p, p, p, p, p,
                p, p, p, p, p,
            ]
        lib.rk_start_slots.restype = None
        lib.rk_start_slots.argtypes = [
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            p, p, p,
            p, p, p, p, p, p, p, p, p, p,
        ]
        lib.rk_open_scan.restype = ctypes.c_int32
        lib.rk_open_scan.argtypes = [
            ctypes.c_int32, p, p, p, p, p, p, p, p, p, p,
        ]
        lib.rk_pack_gather.restype = ctypes.c_int32
        lib.rk_pack_gather.argtypes = [
            p, ctypes.c_int64,
            p, p, p,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            p, p,
        ]
        lib.rk_stall_scan.restype = ctypes.c_int32
        lib.rk_stall_scan.argtypes = [
            ctypes.c_int32, p, p, ctypes.c_double, ctypes.c_double,
        ]
        # native per-tick fast path (the rk tick context)
        lib.rk_ctx_create.restype = ctypes.c_void_p
        lib.rk_ctx_create.argtypes = [p, p, p, p]
        lib.rk_ctx_destroy.restype = None
        lib.rk_ctx_destroy.argtypes = [p]
        # shard-group range (thread-per-shard-group runtime)
        lib.rk_set_range.restype = None
        lib.rk_set_range.argtypes = [
            p,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_uint32,
        ]
        lib.rk_rows_seen.restype = ctypes.c_uint64
        lib.rk_rows_seen.argtypes = [p]
        lib.rk_dropped.restype = ctypes.c_uint64
        lib.rk_dropped.argtypes = [p]
        lib.rk_carry_count.restype = ctypes.c_int64
        lib.rk_carry_count.argtypes = [p]
        lib.rk_drain_stale.restype = ctypes.c_int64
        lib.rk_drain_stale.argtypes = [p, p, p, p, ctypes.c_int64]
        lib.rk_ingest.restype = ctypes.c_int32
        lib.rk_ingest.argtypes = [
            p, p, ctypes.c_int64, ctypes.c_int32, ctypes.c_double,
        ]
        lib.rk_tick.restype = None
        lib.rk_tick.argtypes = [
            p, ctypes.c_double, p, ctypes.c_int64, ctypes.c_int32,
            p, p, p, p,
        ]
        lib.rk_retransmit.restype = None
        lib.rk_retransmit.argtypes = [
            p, ctypes.c_double, ctypes.c_double, p, ctypes.c_int64, p,
        ]
        # observability counter block (versioned, append-only)
        lib.rk_counters_version.restype = ctypes.c_int32
        lib.rk_counters_version.argtypes = []
        lib.rk_counters_count.restype = ctypes.c_int32
        lib.rk_counters_count.argtypes = []
        lib.rk_counters.restype = ctypes.c_void_p
        lib.rk_counters.argtypes = [p]
        if hasattr(lib, "rk_phase_hist"):
            # phases-to-decide histogram (chaos-plane telemetry, v2)
            lib.rk_phase_hist_len.restype = ctypes.c_int32
            lib.rk_phase_hist_len.argtypes = []
            lib.rk_phase_hist.restype = ctypes.c_void_p
            lib.rk_phase_hist.argtypes = [p]
        # flight recorder (fixed-size binary event ring, versioned ABI)
        lib.rk_flight_version.restype = ctypes.c_int32
        lib.rk_flight_version.argtypes = []
        lib.rk_flight_cap.restype = ctypes.c_int32
        lib.rk_flight_cap.argtypes = []
        lib.rk_flight_record_size.restype = ctypes.c_int32
        lib.rk_flight_record_size.argtypes = []
        lib.rk_flight.restype = ctypes.c_void_p
        lib.rk_flight.argtypes = [p]
        lib.rk_flight_head.restype = ctypes.c_uint64
        lib.rk_flight_head.argtypes = [p]
        if hasattr(lib, "rk_dwell"):
            # per-phase consensus dwell histograms (RTH-style geometry)
            lib.rk_dwell_version.restype = ctypes.c_int32
            lib.rk_dwell_version.argtypes = []
            lib.rk_dwell_phases.restype = ctypes.c_int32
            lib.rk_dwell_phases.argtypes = []
            lib.rk_dwell_buckets.restype = ctypes.c_int32
            lib.rk_dwell_buckets.argtypes = []
            lib.rk_dwell_sub_bits.restype = ctypes.c_int32
            lib.rk_dwell_sub_bits.argtypes = []
            lib.rk_dwell_min_exp.restype = ctypes.c_int32
            lib.rk_dwell_min_exp.argtypes = []
            lib.rk_dwell.restype = ctypes.c_void_p
            lib.rk_dwell.argtypes = [p]
        _HK_CACHED = lib
        return lib


def _sk_path() -> Path:
    return (
        _HERE / f"_statekernel_{_digest_of(_SK_SRC, _ANNOT)}{_flavor()[0]}.so"
    )


def load_statekernel() -> ctypes.CDLL | None:
    """Build (if needed) and dlopen the native apply-plane library.

    Returns the CDLL with prototypes set, or None when unavailable —
    callers fall back to the Python binary-op apply in apps/kvstore.py,
    which stays the semantics owner. ``RABIA_PY_APPLY=1`` forces the
    Python path (debug/differential testing, the conformance gate's
    second leg)."""
    global _SK_CACHED, _SK_FAILED
    if os.environ.get("RABIA_PY_APPLY") == "1":
        return None
    with _LOCK:
        if _SK_CACHED is not None:
            return _SK_CACHED
        if _SK_FAILED is not None:
            return None
        try:
            target = _sk_path()
            if not target.exists():
                _compile(
                    _SK_SRC, target, ["-O3"], "_statekernel_*.so",
                    "statekernel",
                )
            lib = ctypes.CDLL(os.fspath(target))
        except Exception as e:  # noqa: BLE001 - any failure means fallback
            _SK_FAILED = str(e)
            return None
        p = ctypes.c_void_p
        i64 = ctypes.c_int64
        lib.sk_plane_create.restype = ctypes.c_void_p
        lib.sk_plane_create.argtypes = [i64, i64, i64, i64]
        lib.sk_plane_destroy.restype = None
        lib.sk_plane_destroy.argtypes = [p]
        lib.sk_counters_version.restype = ctypes.c_int32
        lib.sk_counters_version.argtypes = []
        lib.sk_counters_count.restype = ctypes.c_int32
        lib.sk_counters_count.argtypes = []
        lib.sk_counters.restype = ctypes.c_void_p
        lib.sk_counters.argtypes = [p]
        lib.sk_flight_version.restype = ctypes.c_int32
        lib.sk_flight_version.argtypes = []
        lib.sk_flight_cap.restype = ctypes.c_int32
        lib.sk_flight_cap.argtypes = []
        lib.sk_flight_record_size.restype = ctypes.c_int32
        lib.sk_flight_record_size.argtypes = []
        lib.sk_flight.restype = ctypes.c_void_p
        lib.sk_flight.argtypes = [p]
        lib.sk_flight_head.restype = ctypes.c_uint64
        lib.sk_flight_head.argtypes = [p]
        lib.sk_store_count.restype = i64
        lib.sk_store_count.argtypes = [p]
        lib.sk_store_size.restype = i64
        lib.sk_store_size.argtypes = [p, i64]
        lib.sk_store_version.restype = ctypes.c_uint64
        lib.sk_store_version.argtypes = [p, i64]
        lib.sk_set_version.restype = None
        lib.sk_set_version.argtypes = [p, i64, ctypes.c_uint64]
        lib.sk_store_stats.restype = None
        lib.sk_store_stats.argtypes = [p, i64, p]
        lib.sk_add_stats.restype = None
        lib.sk_add_stats.argtypes = [
            p, i64, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64,
        ]
        lib.sk_get.restype = i64
        lib.sk_get.argtypes = [
            p, i64, p, i64,
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.sk_export_size.restype = i64
        lib.sk_export_size.argtypes = [p, i64]
        lib.sk_export.restype = i64
        lib.sk_export.argtypes = [p, i64, p, i64]
        lib.sk_clear_store.restype = None
        lib.sk_clear_store.argtypes = [p, i64]
        lib.sk_delete_raw.restype = ctypes.c_int32
        lib.sk_delete_raw.argtypes = [p, i64, p, i64]
        lib.sk_insert_raw.restype = ctypes.c_int32
        lib.sk_insert_raw.argtypes = [
            p, i64, p, i64, p, i64,
            ctypes.c_uint64, ctypes.c_double, ctypes.c_double,
        ]
        lib.sk_apply_wave.restype = i64
        lib.sk_apply_wave.argtypes = [
            p, p, p, p, p, p, i64, ctypes.c_double, ctypes.c_int32,
        ]
        lib.sk_apply_ops.restype = i64
        lib.sk_apply_ops.argtypes = [
            p, i64, p, p, i64, ctypes.c_double, ctypes.c_int32,
        ]
        lib.sk_out_buf.restype = ctypes.c_void_p
        lib.sk_out_buf.argtypes = [p]
        lib.sk_out_offs.restype = ctypes.c_void_p
        lib.sk_out_offs.argtypes = [p]
        lib.sk_out_count.restype = i64
        lib.sk_out_count.argtypes = [p]
        # thread-per-shard-group apply lanes (runtime workers > 1)
        lib.sk_set_groups.restype = ctypes.c_int32
        lib.sk_set_groups.argtypes = [p, ctypes.c_int32]
        lib.sk_apply_wave_lane.restype = i64
        lib.sk_apply_wave_lane.argtypes = [
            p, ctypes.c_int32, p, p, p, p, p, i64,
            ctypes.c_double, ctypes.c_int32,
        ]
        lib.sk_out_buf_lane.restype = ctypes.c_void_p
        lib.sk_out_buf_lane.argtypes = [p, ctypes.c_int32]
        lib.sk_out_offs_lane.restype = ctypes.c_void_p
        lib.sk_out_offs_lane.argtypes = [p, ctypes.c_int32]
        # incremental snapshots (durability plane)
        lib.sk_snapshot_delta_size.restype = i64
        lib.sk_snapshot_delta_size.argtypes = [p, i64]
        lib.sk_snapshot_delta.restype = i64
        lib.sk_snapshot_delta.argtypes = [p, i64, p, i64]
        lib.sk_snapshot_mark.restype = None
        lib.sk_snapshot_mark.argtypes = [p, i64]
        # read-side critical-section brackets (native-runtime hook)
        lib.sk_plane_lock.restype = None
        lib.sk_plane_lock.argtypes = [p]
        lib.sk_plane_unlock.restype = None
        lib.sk_plane_unlock.argtypes = [p]
        _SK_CACHED = lib
        return lib


def load_library() -> ctypes.CDLL:
    """Build (if needed) and dlopen the transport library; sets prototypes.

    ``RABIA_NATIVE_LIB`` points at a prebuilt .so (container runtime
    images ship one so they need no toolchain)."""
    global _CACHED
    with _LOCK:
        if _CACHED is not None:
            return _CACHED
        prebuilt = os.environ.get("RABIA_NATIVE_LIB")
        if prebuilt:
            target = Path(prebuilt)
            if not target.exists():
                # an explicitly configured path that is missing must fail
                # loudly — falling back to a source build would mask the
                # misconfiguration (and runtime images ship no compiler)
                raise InternalError(
                    f"RABIA_NATIVE_LIB points at a missing file: {prebuilt}"
                )
        else:
            target = lib_path()
            if not target.exists():
                _build(target)
        lib = ctypes.CDLL(os.fspath(target))
        if prebuilt:
            # a prebuilt library bypasses the source-digest keying: probe
            # the newest exported symbol so a stale .so fails fast with a
            # clear message instead of a cryptic AttributeError later
            try:
                lib.rt_counters
                lib.rt_flight_copy
            except AttributeError:
                raise InternalError(
                    f"RABIA_NATIVE_LIB library {prebuilt} is stale "
                    "(missing rt_counters/rt_flight_copy); rebuild it "
                    "from transport.cpp"
                ) from None

        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.rt_create.restype = ctypes.c_void_p
        lib.rt_create.argtypes = [
            u8p,
            ctypes.c_char_p,
            ctypes.c_uint16,
            ctypes.POINTER(ctypes.c_uint16),
        ]
        lib.rt_add_peer.restype = ctypes.c_int
        lib.rt_add_peer.argtypes = [
            ctypes.c_void_p,
            u8p,
            ctypes.c_char_p,
            ctypes.c_uint16,
        ]
        lib.rt_remove_peer.restype = ctypes.c_int
        lib.rt_remove_peer.argtypes = [ctypes.c_void_p, u8p]
        if hasattr(lib, "rt_set_shaping"):
            # chaos shaping layer (a prebuilt RABIA_NATIVE_LIB may
            # predate it; TcpNetwork.set_peer_shaping raises then)
            lib.rt_set_shaping.restype = ctypes.c_int
            lib.rt_set_shaping.argtypes = [
                ctypes.c_void_p, u8p,
                ctypes.c_uint32, ctypes.c_uint32,
                ctypes.c_double, ctypes.c_uint64,
            ]
            lib.rt_clear_shaping.restype = ctypes.c_int
            lib.rt_clear_shaping.argtypes = [ctypes.c_void_p]
        lib.rt_send.restype = ctypes.c_int
        lib.rt_send.argtypes = [
            ctypes.c_void_p,
            u8p,
            ctypes.c_char_p,
            ctypes.c_uint32,
        ]
        lib.rt_broadcast.restype = ctypes.c_int
        lib.rt_broadcast.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_uint32,
        ]
        # batch-staged broadcast of the native tick's outbound buffer
        # ([u32 record_len][frame]... records, one lock + one kick)
        lib.rt_broadcast_frames.restype = ctypes.c_int
        lib.rt_broadcast_frames.argtypes = [
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_int64,
        ]
        lib.rt_recv.restype = ctypes.c_int
        lib.rt_recv.argtypes = [
            ctypes.c_void_p,
            u8p,
            u8p,
            ctypes.c_uint32,
            ctypes.c_int,
        ]
        lib.rt_recv_borrow.restype = ctypes.c_int64
        lib.rt_recv_borrow.argtypes = [
            ctypes.c_void_p,
            u8p,
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_int,
        ]
        lib.rt_recv_release.restype = None
        lib.rt_recv_release.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        # thread-per-shard-group routing (runtime workers > 1)
        lib.rt_set_groups.restype = ctypes.c_int
        lib.rt_set_groups.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int32,
            ctypes.c_void_p,
            ctypes.c_void_p,
        ]
        lib.rt_recv_borrow_group.restype = ctypes.c_int64
        lib.rt_recv_borrow_group.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int32,
            u8p,
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_int,
        ]
        lib.rt_connected.restype = ctypes.c_int
        lib.rt_connected.argtypes = [ctypes.c_void_p, u8p, ctypes.c_int]
        lib.rt_port.restype = ctypes.c_uint16
        lib.rt_port.argtypes = [ctypes.c_void_p]
        lib.rt_dropped.restype = ctypes.c_uint64
        lib.rt_dropped.argtypes = [ctypes.c_void_p]
        lib.rt_pool_stats.restype = None
        lib.rt_pool_stats.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.rt_out_pool_stats.restype = None
        lib.rt_out_pool_stats.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        # observability counter block (versioned, append-only)
        lib.rt_counters_version.restype = ctypes.c_int32
        lib.rt_counters_version.argtypes = []
        lib.rt_counters_count.restype = ctypes.c_int32
        lib.rt_counters_count.argtypes = []
        lib.rt_counters.restype = ctypes.c_void_p
        lib.rt_counters.argtypes = [ctypes.c_void_p]
        # flight recorder (frame in/out ring, consistent copy under mu)
        lib.rt_flight_version.restype = ctypes.c_int32
        lib.rt_flight_version.argtypes = []
        lib.rt_flight_record_size.restype = ctypes.c_int32
        lib.rt_flight_record_size.argtypes = []
        lib.rt_flight_copy.restype = ctypes.c_int64
        lib.rt_flight_copy.argtypes = [
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_int64,
        ]
        lib.rt_inbox_kick.restype = None
        lib.rt_inbox_kick.argtypes = [ctypes.c_void_p]
        lib.rt_stop.restype = None
        lib.rt_stop.argtypes = [ctypes.c_void_p]
        lib.rt_close.restype = None
        lib.rt_close.argtypes = [ctypes.c_void_p]

        _CACHED = lib
        return lib


_GWS_CACHED: ctypes.CDLL | None = None
_GWS_FAILED: str | None = None


def _gws_path() -> Path:
    digest = _digest_of(_HERE / "sessionkernel.cpp", _ANNOT)
    return _HERE / f"_sessionkernel_{digest}{_flavor()[0]}.so"


def load_sessionkernel() -> ctypes.CDLL | None:
    """Build (if needed) and dlopen the native gateway-plane library
    (sessionkernel.cpp: the client session/dedup table). Returns the
    CDLL with prototypes set, or None when unavailable — the gateway
    falls back to the Python :class:`~rabia_tpu.gateway.session.
    SessionTable`, which stays the semantics owner
    (``RABIA_PY_GATEWAY=1`` forces it; the conformance gate's second
    leg)."""
    global _GWS_CACHED, _GWS_FAILED
    if os.environ.get("RABIA_PY_GATEWAY") == "1":
        return None
    with _LOCK:
        if _GWS_CACHED is not None:
            return _GWS_CACHED
        if _GWS_FAILED is not None:
            return None
        try:
            target = _gws_path()
            if not target.exists():
                _compile(
                    (_HERE / "sessionkernel.cpp"), target, ["-O3"],
                    "_sessionkernel_*.so", "sessionkernel",
                )
            lib = ctypes.CDLL(os.fspath(target))
        except Exception as e:  # noqa: BLE001 - any failure means fallback
            _GWS_FAILED = str(e)
            return None
        p = ctypes.c_void_p
        i64 = ctypes.c_int64
        u64 = ctypes.c_uint64
        lib.gws_create.restype = ctypes.c_void_p
        lib.gws_create.argtypes = [i64, ctypes.c_double, i64,
                                   ctypes.c_double]
        lib.gws_destroy.restype = None
        lib.gws_destroy.argtypes = [p]
        lib.gws_counters_version.restype = ctypes.c_int32
        lib.gws_counters_version.argtypes = []
        lib.gws_counters_count.restype = ctypes.c_int32
        lib.gws_counters_count.argtypes = []
        lib.gws_counters.restype = ctypes.c_void_p
        lib.gws_counters.argtypes = [p]
        lib.gws_len.restype = i64
        lib.gws_len.argtypes = [p]
        lib.gws_clear.restype = None
        lib.gws_clear.argtypes = [p]
        lib.gws_stats.restype = None
        lib.gws_stats.argtypes = [p, p]
        lib.gws_hello.restype = i64
        lib.gws_hello.argtypes = [
            p, p, i64, ctypes.c_double, ctypes.POINTER(u64),
        ]
        lib.gws_submit.restype = ctypes.c_int32
        lib.gws_submit.argtypes = [
            p, p, u64, u64, ctypes.c_double,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(i64),
        ]
        lib.gws_complete.restype = ctypes.c_int32
        lib.gws_complete.argtypes = [
            p, p, u64, ctypes.c_int32, u64, p, i64, ctypes.c_double,
        ]
        lib.gws_abort.restype = None
        lib.gws_abort.argtypes = [p, p, u64]
        lib.gws_gc.restype = i64
        lib.gws_gc.argtypes = [p, u64, ctypes.c_double]
        lib.gws_session_info.restype = ctypes.c_int32
        lib.gws_session_info.argtypes = [
            p, p, ctypes.POINTER(i64), ctypes.POINTER(u64),
            ctypes.POINTER(u64), ctypes.POINTER(i64), ctypes.POINTER(i64),
        ]
        lib.gws_get_result.restype = ctypes.c_int32
        lib.gws_get_result.argtypes = [
            p, p, u64, ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(u64),
            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(i64),
        ]
        lib.gws_session_ids.restype = i64
        lib.gws_session_ids.argtypes = [p, p, i64]
        lib.gws_result_seqs.restype = i64
        lib.gws_result_seqs.argtypes = [p, p, p, i64]
        lib.gws_inflight_seqs.restype = i64
        lib.gws_inflight_seqs.argtypes = [p, p, p, i64]
        _GWS_CACHED = lib
        return lib


_WAL_CACHED: ctypes.CDLL | None = None
_WAL_FAILED: str | None = None


def _wal_path() -> Path:
    digest = _digest_of(_HERE / "walkernel.cpp", _ANNOT)
    return _HERE / f"_walkernel_{digest}{_flavor()[0]}.so"


def load_walkernel() -> ctypes.CDLL | None:
    """Build (if needed) and dlopen the native durability-plane library
    (walkernel.cpp: the group-commit write-ahead log). Returns the CDLL
    with prototypes set, or None when unavailable — WalPersistence falls
    back to the pure-Python writer, which stays the semantics owner of
    the byte format (``RABIA_PY_WAL=1`` forces it; the conformance
    gate's second leg)."""
    global _WAL_CACHED, _WAL_FAILED
    if os.environ.get("RABIA_PY_WAL") == "1":
        return None
    with _LOCK:
        if _WAL_CACHED is not None:
            return _WAL_CACHED
        if _WAL_FAILED is not None:
            return None
        try:
            target = _wal_path()
            if not target.exists():
                _compile(
                    (_HERE / "walkernel.cpp"), target, ["-O2", "-pthread"],
                    "_walkernel_*.so", "walkernel", link_args=["-lz"],
                )
            lib = ctypes.CDLL(os.fspath(target))
        except Exception as e:  # noqa: BLE001 - any failure means fallback
            _WAL_FAILED = str(e)
            return None
        p = ctypes.c_void_p
        i64 = ctypes.c_int64
        u64 = ctypes.c_uint64
        lib.wal_create.restype = ctypes.c_void_p
        lib.wal_create.argtypes = [
            ctypes.c_char_p, i64, i64, i64, u64, u64,
        ]
        lib.wal_start.restype = ctypes.c_int32
        lib.wal_start.argtypes = [p]
        lib.wal_stop.restype = None
        lib.wal_stop.argtypes = [p]
        lib.wal_destroy.restype = None
        lib.wal_destroy.argtypes = [p]
        lib.wal_append.restype = i64
        lib.wal_append.argtypes = [p, p, i64]
        lib.wal_durable.restype = u64
        lib.wal_durable.argtypes = [p]
        lib.wal_staged.restype = u64
        lib.wal_staged.argtypes = [p]
        lib.wal_io_error.restype = ctypes.c_int32
        lib.wal_io_error.argtypes = [p]
        lib.wal_event_fd.restype = ctypes.c_int
        lib.wal_event_fd.argtypes = [p]
        lib.wal_sync.restype = ctypes.c_int32
        lib.wal_sync.argtypes = [p, ctypes.c_double]
        lib.wal_barrier_covered.restype = i64
        lib.wal_barrier_covered.argtypes = [p, i64, i64]
        lib.wal_set_barrier.restype = None
        lib.wal_set_barrier.argtypes = [p, p, i64]
        lib.wal_get_barrier.restype = None
        lib.wal_get_barrier.argtypes = [p, p, i64]
        lib.wal_counters_version.restype = ctypes.c_int32
        lib.wal_counters_version.argtypes = []
        lib.wal_counters_count.restype = ctypes.c_int32
        lib.wal_counters_count.argtypes = []
        lib.wal_counters.restype = ctypes.c_void_p
        lib.wal_counters.argtypes = [p]
        lib.wal_hist_version.restype = ctypes.c_int32
        lib.wal_hist_version.argtypes = []
        lib.wal_hist_buckets.restype = ctypes.c_int32
        lib.wal_hist_buckets.argtypes = []
        lib.wal_hist_sub_bits.restype = ctypes.c_int32
        lib.wal_hist_sub_bits.argtypes = []
        lib.wal_hist_min_exp.restype = ctypes.c_int32
        lib.wal_hist_min_exp.argtypes = []
        lib.wal_hist.restype = ctypes.c_void_p
        lib.wal_hist.argtypes = [p]
        lib.wal_segment_index.restype = i64
        lib.wal_segment_index.argtypes = [p]
        lib.wal_segment_bytes.restype = i64
        lib.wal_segment_bytes.argtypes = [p]
        _WAL_CACHED = lib
        return lib


_RTM_CACHED: ctypes.CDLL | None = None
_RTM_FAILED: str | None = None


def _rtm_path() -> Path:
    digest = _digest_of(_HERE / "runtime.cpp", _ANNOT)
    return _HERE / f"_runtime_{digest}{_flavor()[0]}.so"


def load_runtime() -> ctypes.CDLL | None:
    """Build (if needed) and dlopen the native engine runtime library
    (runtime.cpp: the GIL-free io/tick thread). Returns the CDLL with
    prototypes set, or None when unavailable — the engine falls back to
    the asyncio orchestration, which stays the semantics owner
    (``RABIA_PY_RUNTIME=1`` forces it)."""
    global _RTM_CACHED, _RTM_FAILED
    if os.environ.get("RABIA_PY_RUNTIME") == "1":
        return None
    with _LOCK:
        if _RTM_CACHED is not None:
            return _RTM_CACHED
        if _RTM_FAILED is not None:
            return None
        try:
            target = _rtm_path()
            if not target.exists():
                _compile(
                    (_HERE / "runtime.cpp"), target, ["-O2", "-pthread"],
                    "_runtime_*.so", "runtime", link_args=["-lz"],
                )
            lib = ctypes.CDLL(os.fspath(target))
        except Exception as e:  # noqa: BLE001 - any failure means fallback
            _RTM_FAILED = str(e)
            return None
        p = ctypes.c_void_p
        i64 = ctypes.c_int64
        lib.rtm_create.restype = ctypes.c_void_p
        lib.rtm_create.argtypes = [p, p, p, p, p]
        lib.rtm_start.restype = ctypes.c_int32
        lib.rtm_start.argtypes = [p]
        lib.rtm_stop.restype = None
        lib.rtm_stop.argtypes = [p]
        lib.rtm_destroy.restype = None
        lib.rtm_destroy.argtypes = [p]
        lib.rtm_state.restype = ctypes.c_int32
        lib.rtm_state.argtypes = [p]
        lib.rtm_pause.restype = None
        lib.rtm_pause.argtypes = [p]
        lib.rtm_resume.restype = None
        lib.rtm_resume.argtypes = [p]
        lib.rtm_event_fd.restype = ctypes.c_int
        lib.rtm_event_fd.argtypes = [p]
        lib.rtm_cmd_push.restype = ctypes.c_int32
        lib.rtm_cmd_push.argtypes = [p, p, i64]
        lib.rtm_ev_drain.restype = i64
        lib.rtm_ev_drain.argtypes = [p, p, i64]
        lib.rtm_counters_version.restype = ctypes.c_int32
        lib.rtm_counters_version.argtypes = []
        lib.rtm_counters_count.restype = ctypes.c_int32
        lib.rtm_counters_count.argtypes = []
        lib.rtm_counters.restype = ctypes.c_void_p
        lib.rtm_counters.argtypes = [p]
        # stage profiler block (RTS_*: cumulative ns per loop stage)
        lib.rtm_stages_version.restype = ctypes.c_int32
        lib.rtm_stages_version.argtypes = []
        lib.rtm_stages_count.restype = ctypes.c_int32
        lib.rtm_stages_count.argtypes = []
        lib.rtm_stages.restype = ctypes.c_void_p
        lib.rtm_stages.argtypes = [p]
        # SLO latency histogram block (RTH_*: log-bucketed, fixed size)
        lib.rtm_hist_version.restype = ctypes.c_int32
        lib.rtm_hist_version.argtypes = []
        lib.rtm_hist_stages.restype = ctypes.c_int32
        lib.rtm_hist_stages.argtypes = []
        lib.rtm_hist_buckets.restype = ctypes.c_int32
        lib.rtm_hist_buckets.argtypes = []
        lib.rtm_hist_sub_bits.restype = ctypes.c_int32
        lib.rtm_hist_sub_bits.argtypes = []
        lib.rtm_hist_min_exp.restype = ctypes.c_int32
        lib.rtm_hist_min_exp.argtypes = []
        lib.rtm_hist.restype = ctypes.c_void_p
        lib.rtm_hist.argtypes = [p]
        lib.rtm_flight_version.restype = ctypes.c_int32
        lib.rtm_flight_version.argtypes = []
        lib.rtm_flight_cap.restype = ctypes.c_int32
        lib.rtm_flight_cap.argtypes = []
        lib.rtm_flight_record_size.restype = ctypes.c_int32
        lib.rtm_flight_record_size.argtypes = []
        lib.rtm_flight.restype = ctypes.c_void_p
        lib.rtm_flight.argtypes = [p]
        lib.rtm_flight_head.restype = ctypes.c_uint64
        lib.rtm_flight_head.argtypes = [p]
        # thread-per-shard-group workers: geometry + per-worker blocks
        lib.rtm_workers.restype = ctypes.c_int32
        lib.rtm_workers.argtypes = [p]
        lib.rtm_group_chunk.restype = ctypes.c_int64
        lib.rtm_group_chunk.argtypes = [p]
        lib.rtm_frame_group_mask.restype = ctypes.c_uint64
        lib.rtm_frame_group_mask.argtypes = [p, p, ctypes.c_uint32]
        lib.rtm_counters_w.restype = ctypes.c_void_p
        lib.rtm_counters_w.argtypes = [p, ctypes.c_int32]
        lib.rtm_stages_w.restype = ctypes.c_void_p
        lib.rtm_stages_w.argtypes = [p, ctypes.c_int32]
        lib.rtm_hist_w.restype = ctypes.c_void_p
        lib.rtm_hist_w.argtypes = [p, ctypes.c_int32]
        lib.rtm_flight_w.restype = ctypes.c_void_p
        lib.rtm_flight_w.argtypes = [p, ctypes.c_int32]
        lib.rtm_flight_head_w.restype = ctypes.c_uint64
        lib.rtm_flight_head_w.argtypes = [p, ctypes.c_int32]
        _RTM_CACHED = lib
        return lib


# ---------------------------------------------------------------------------
# static-analysis plane: sanitizer toolchains + the native stress suite
# (docs/STATIC_ANALYSIS.md; scripts/sanitize_gate.py is the driver)
# ---------------------------------------------------------------------------

STRESS_DIR = _HERE / "stress"
_STRESS_BUILD = STRESS_DIR / "_build"

# The gcc-10 libtsan on this container does not intercept
# pthread_cond_clockwait (libstdc++'s timed condvar path on glibc >= 2.30),
# so the unlock/relock inside a wait is invisible to TSan — the root cause
# of the retired probe-SKIP's false "double lock of a mutex". The shim
# routes clockwait to the intercepted pthread_cond_timedwait; linking it
# into every TSan stress binary makes gcc a VIABLE TSan toolchain (the
# kernels themselves wait via rabia::CondVar, which never emits
# clockwait — the shim covers libstdc++ internals and test scaffolding).
_TSAN_COMPAT = STRESS_DIR / "tsan_compat.cpp"

SAN_FLAGS: dict[str, list[str]] = {
    "tsan": ["-fsanitize=thread", "-O1", "-g"],
    "asan": [
        "-fsanitize=address", "-fno-omit-frame-pointer", "-O1", "-g",
    ],
    "ubsan": [
        "-fsanitize=undefined", "-fno-sanitize-recover=undefined",
        "-O1", "-g",
    ],
}


def stress_env(flavor: str) -> dict[str, str]:
    """Runtime env for a `flavor` stress binary: halt_on_error so any
    finding is a nonzero exit (an enforced gate, not a log line), plus
    the vetted suppression file for TSan (each entry justified inline)."""
    env = {"PATH": os.environ.get("PATH", "/usr/bin:/bin")}
    if flavor == "tsan":
        env["TSAN_OPTIONS"] = (
            f"halt_on_error=1:suppressions={STRESS_DIR / 'tsan.supp'}"
        )
    elif flavor == "asan":
        env["ASAN_OPTIONS"] = "halt_on_error=1:detect_leaks=1"
        env["LSAN_OPTIONS"] = f"suppressions={STRESS_DIR / 'lsan.supp'}"
    elif flavor == "ubsan":
        env["UBSAN_OPTIONS"] = "halt_on_error=1:print_stacktrace=1"
    return env


# name -> kernel sources linked into stress/stress_<name>.cpp. Each
# program hammers one cross-thread seam the thread-per-shard-group
# runtime (ROADMAP item 1) will multiply.
STRESS_PROGRAMS: dict[str, dict] = {
    "transport": {"srcs": ["transport.cpp"], "libs": []},
    "wal": {"srcs": ["walkernel.cpp"], "libs": ["-lz"]},
    "session": {"srcs": ["sessionkernel.cpp"], "libs": []},
    "statekernel": {"srcs": ["statekernel.cpp"], "libs": []},
    "runtime": {"srcs": ["runtime.cpp", "transport.cpp"], "libs": ["-lz"]},
    # thread-per-shard-group seams: 2 workers vs per-group inbox
    # routing, per-lane statekernel applies, shared WAL staging lanes,
    # cross-worker result staging and the multi-worker pause barrier
    "runtime_mt": {
        "srcs": [
            "runtime.cpp", "transport.cpp", "statekernel.cpp",
            "walkernel.cpp",
        ],
        "libs": ["-lz"],
    },
}

# deliberately-broken probes: the test suite builds these and asserts the
# gate EXITS NONZERO — proof the matrix is red-on-failure, not
# green-by-silence
SELFCHECK_PROGRAMS: dict[str, str] = {
    "tsan": "selfcheck_race",
    "asan": "selfcheck_uaf",
}

_PROBE_CLEAN = r"""
// race-free by construction: mutex churn + TIMED condvar waits (the
// exact primitives the kernels use; a toolchain that flags this is not
// viable and the gate skips with this program's own output)
#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <mutex>
#include <thread>
#include <vector>
int main() {
  std::mutex mu;
  std::condition_variable cv;
  bool done = false;
  long shared = 0;
  std::vector<std::thread> ts;
  for (int t = 0; t < 3; t++) {
    ts.emplace_back([&] {
      for (int i = 0; i < 20000; i++) {
        std::lock_guard<std::mutex> lk(mu);
        shared++;
        if ((shared & 1023) == 0) cv.notify_all();
      }
    });
  }
  {
    std::unique_lock<std::mutex> lk(mu);
    while (!cv.wait_for(lk, std::chrono::milliseconds(2),
                        [&] { return shared >= 60000; })) {
    }
    done = true;
  }
  for (auto& t : ts) t.join();
  std::printf("probe ok %ld %d\n", shared, (int)done);
  return shared == 60000 ? 0 : 3;
}
"""

_PROBE_BROKEN = {
    # a real data race: the sanitizer must catch it or it cannot be
    # trusted to gate anything
    "tsan": r"""
#include <cstdio>
#include <thread>
long shared = 0;
int main() {
  std::thread a([] { for (int i = 0; i < 200000; i++) shared++; });
  std::thread b([] { for (int i = 0; i < 200000; i++) shared++; });
  a.join();
  b.join();
  std::printf("done %ld\n", shared);
  return 0;
}
""",
    "asan": r"""
#include <cstdio>
#include <cstdlib>
int main() {
  volatile int* p = (volatile int*)malloc(32);
  p[0] = 7;
  free((void*)p);
  std::printf("uaf %d\n", p[0]);  // heap-use-after-free
  return 0;
}
""",
    "ubsan": r"""
#include <cstdio>
int main(int argc, char**) {
  volatile int s = 40 + argc;
  volatile int v = 1 << s;  // shift exponent out of range
  std::printf("ub %d\n", v);
  return 0;
}
""",
}

_TOOLCHAIN_CACHE: dict[str, dict | None] = {}


def _compiler_candidates() -> list[str]:
    import shutil as _sh

    out = []
    for name in (
        "clang++", "clang++-20", "clang++-19", "clang++-18", "clang++-17",
        "clang++-16", "clang++-15", "clang++-14", "g++",
    ):
        if _sh.which(name):
            out.append(name)
    return out


def find_sanitizer_toolchain(flavor: str) -> dict | None:
    """Find a compiler whose `flavor` sanitizer is VIABLE here.

    Viable means BOTH halves hold, probed with real binaries:
      - the clean probe (mutex + timed-condvar churn) runs clean three
        times — a toolchain that false-positives on it (gcc-10 libtsan
        without the clockwait shim) would make every stress verdict
        noise;
      - the broken probe (a planted race / use-after-free / UB shift)
        exits NONZERO — a sanitizer that cannot catch the planted bug
        cannot be trusted to gate the real ones.

    clang is preferred; gcc's TSan qualifies via the clockwait shim.
    Returns {"cxx", "flags", "extra_sources", "reason"} or None (the
    last probe failure lands in find_sanitizer_toolchain.reason for the
    one-line SKIP)."""
    import subprocess as sp
    import tempfile

    if flavor in _TOOLCHAIN_CACHE:
        return _TOOLCHAIN_CACHE[flavor]
    reasons = []
    result = None
    for cxx in _compiler_candidates():
        extra = []
        if flavor == "tsan":
            extra = [str(_TSAN_COMPAT)]
        with tempfile.TemporaryDirectory() as td:
            probe = Path(td) / "probe.cpp"
            probe.write_text(_PROBE_CLEAN)
            exe = Path(td) / "probe"
            cmd = [
                cxx, "-std=c++17", *SAN_FLAGS[flavor], "-pthread",
                str(probe), *extra, "-o", str(exe),
            ]
            rc = sp.run(cmd, capture_output=True, text=True, timeout=180)
            if rc.returncode != 0:
                reasons.append(f"{cxx}: probe build failed")
                continue
            env = stress_env(flavor)
            ok = True
            for _ in range(3):
                run = sp.run(
                    [str(exe)], capture_output=True, text=True,
                    timeout=120, env=env,
                )
                if run.returncode != 0 or "probe ok" not in run.stdout:
                    reasons.append(
                        f"{cxx}: clean probe flagged "
                        f"(rc={run.returncode}): "
                        + (run.stderr or run.stdout)[-300:].replace(
                            "\n", " | "
                        )
                    )
                    ok = False
                    break
            if not ok:
                continue
            broken = Path(td) / "broken.cpp"
            broken.write_text(_PROBE_BROKEN[flavor])
            bexe = Path(td) / "broken"
            rc = sp.run(
                [
                    cxx, "-std=c++17", *SAN_FLAGS[flavor], "-pthread",
                    str(broken), *extra, "-o", str(bexe),
                ],
                capture_output=True, text=True, timeout=180,
            )
            if rc.returncode != 0:
                reasons.append(f"{cxx}: broken probe build failed")
                continue
            caught = False
            for _ in range(5):
                run = sp.run(
                    [str(bexe)], capture_output=True, text=True,
                    timeout=120, env=env,
                )
                if run.returncode != 0:
                    caught = True
                    break
            if not caught:
                reasons.append(f"{cxx}: planted bug not detected")
                continue
            result = {
                "cxx": cxx,
                "flags": list(SAN_FLAGS[flavor]),
                "extra_sources": [str(p) for p in extra],
                "reason": "",
            }
            break
    if result is None:
        find_sanitizer_toolchain.reason = (  # type: ignore[attr-defined]
            "; ".join(reasons) or "no C++ compiler found"
        )
    _TOOLCHAIN_CACHE[flavor] = result
    return result


def build_stress(name: str, flavor: str) -> Path:
    """Build stress/stress_<name>.cpp + its kernel sources under
    `flavor`; returns the binary path (digest-cached like the .so
    builds). Raises InternalError on build failure — a kernel edit that
    breaks the sanitizer build must FAIL the gate, never skip it."""
    import subprocess as sp

    spec = STRESS_PROGRAMS[name]
    tc = find_sanitizer_toolchain(flavor)
    if tc is None:
        raise InternalError(
            f"no viable {flavor} toolchain: "
            + getattr(find_sanitizer_toolchain, "reason", "")
        )
    main_src = STRESS_DIR / f"stress_{name}.cpp"
    # every header an included source can pull in participates in the
    # digest — a header-only ABI edit must never reuse a stale cached
    # stress binary (the silent-stale-artifact class this gate exists
    # to kill)
    srcs = [
        main_src, STRESS_DIR / "stress_common.h", _ANNOT,
        _HERE / "transport.h",
    ]
    srcs += [_HERE / s for s in spec["srcs"]]
    h = hashlib.blake2s(digest_size=8)
    for s in srcs:
        h.update(s.read_bytes())
    for p in tc["extra_sources"]:
        h.update(Path(p).read_bytes())
    h.update((tc["cxx"] + flavor).encode())
    _STRESS_BUILD.mkdir(parents=True, exist_ok=True)
    out = _STRESS_BUILD / f"{name}-{flavor}-{h.hexdigest()}"
    if out.exists():
        return out
    # compile to a private temp path, then atomically rename (the
    # _compile pattern): a build killed mid-link must never leave a
    # truncated binary at the digest-keyed path, which the exists()
    # fast path would trust forever
    tmp = out.with_suffix(f".tmp{os.getpid()}")
    cmd = [
        tc["cxx"], "-std=c++17", *tc["flags"], "-pthread",
        f"-I{_HERE}",
        str(main_src),
        *[str(_HERE / s) for s in spec["srcs"]],
        *tc["extra_sources"],
        "-o", str(tmp),
        *spec["libs"],
    ]
    proc = sp.run(cmd, capture_output=True, text=True, timeout=600)
    if proc.returncode != 0:
        tmp.unlink(missing_ok=True)
        raise InternalError(
            f"{flavor} build of stress_{name} failed:\n"
            + proc.stderr[-2000:]
        )
    os.replace(tmp, out)
    for old in _STRESS_BUILD.glob(f"{name}-{flavor}-*"):
        if old != out:
            try:
                old.unlink()
            except OSError:
                pass
    return out


def build_selfcheck(flavor: str) -> Path:
    """Build the deliberately-broken probe for `flavor` (the gate's
    red-on-failure proof)."""
    import subprocess as sp

    tc = find_sanitizer_toolchain(flavor)
    if tc is None:
        raise InternalError(f"no viable {flavor} toolchain")
    _STRESS_BUILD.mkdir(parents=True, exist_ok=True)
    src = _STRESS_BUILD / f"selfcheck_{flavor}.cpp"
    src.write_text(_PROBE_BROKEN[flavor])
    out = _STRESS_BUILD / f"selfcheck_{flavor}"
    cmd = [
        tc["cxx"], "-std=c++17", *tc["flags"], "-pthread", str(src),
        *tc["extra_sources"], "-o", str(out),
    ]
    proc = sp.run(cmd, capture_output=True, text=True, timeout=180)
    if proc.returncode != 0:
        raise InternalError(
            f"selfcheck build failed:\n{proc.stderr[-1000:]}"
        )
    return out
