// Native host-kernel step: the C twin of HostNodeKernel.node_step /
// start_slots (rabia_tpu/kernel/host_driver.py), which is itself the
// numpy twin of the jitted NodeKernel (kernel/phase_driver.py).
//
// Why: the engine's serial-latency floor is per-activation kernel cost.
// The numpy step is ~40 vectorized calls; at small shard counts (the
// reference's single-shard deployment shape, rabia-engine/src/engine.rs
// round loop) the ~2us-per-call dispatch overhead dominates, putting a
// ~76us floor under every activation. This C step is one call that walks
// each shard's ledger column once. Measured vs the numpy step: 4.7x at
// S=16 down to a steady ~1.2-1.4x at S=16384-65536 — the C path wins at
// every size, so the wrapper uses it unconditionally when the library
// builds. Both paths are bit-identical, gated by the differential fuzz
// in tests/test_native_hostkernel.py.
//
// Semantics owner: host_driver.py. Every transition here mirrors it
// element-for-element, including the portable lowbias32 common coin
// (phase_driver._coin_bits) and the exact vote-code tallies of
// rabia-engine/src/engine.rs:424-706 (vote rules / quorum / coin /
// decision), vectorized over shards.
//
// Layout contract (replica-major, matching HostNodeState): led1/led2 are
// i8[R*S] with sender r's votes at led[r*S + s]. All arrays are dense,
// C-contiguous, caller-owned. node_step mutates state in place (the
// Python wrapper passes fresh copies, preserving the functional step
// contract) and writes the outbox extras that do not alias new state.

#include <cstdint>
#include <cstring>
#include <atomic>
#include <ctime>
#include <vector>

extern "C" {

// vote codes (core/types.py) and stages (kernel/phase_driver.py)
enum : int8_t { V0 = 0, V1 = 1, VQ = 2, ABS = 3 };
enum : int8_t { R1_WAIT = 0, R2_WAIT = 1 };

static inline uint32_t mix32(uint32_t h) {
  // lowbias32 avalanche — must match phase_driver._mix32 bit-for-bit
  h ^= h >> 16;
  h *= 0x21F0AAADu;
  h ^= h >> 15;
  h *= 0x735A2D97u;
  h ^= h >> 15;
  return h;
}

static const uint32_t GOLD = 0x9E3779B9u;

static inline int8_t coin_bit(uint32_t seed, uint32_t shard, uint32_t slot,
                              uint32_t phase, uint32_t threshold) {
  uint32_t h = mix32(seed ^ GOLD);
  h = mix32(h ^ (shard + GOLD));
  h = mix32(h ^ (slot + GOLD));
  h = mix32(h ^ (phase + GOLD));
  return h < threshold ? V1 : V0;
}

// One node_step over S shards. State arrays are mutated in place; the
// outbox fields that alias new state (new_r1=my_r1, new_phase=phase,
// decided_vals=decided) are read by the caller from the state arrays.
// coin_out (nullable): 2 uint64 cells accumulating common-coin flip
// outcomes (index 0 = V0, 1 = V1) — the chaos plane's coin-behavior
// telemetry; pure accounting, no protocol effect.
// s_lo/s_hi bound the shard scan: the thread-per-shard-group runtime
// gives each worker its own RkCtx over a contiguous shard range, and a
// worker must never READ another group's state cells (TSan-visible and
// semantically wrong — foreign ledgers live in foreign contexts). The
// full-range wrappers pass (0, S); coin values depend only on
// (seed, shard, slot, phase), so a range split never changes decisions.
static void rk_node_step_impl(
    int32_t S, int32_t R, int32_t me, int32_t quorum, int32_t f1,
    uint32_t seed, uint32_t coin_threshold, int32_t s_lo, int32_t s_hi,
    const int32_t* slot,       // [S]
    int32_t* phase,            // [S] in/out
    int8_t* stage,             // [S] in/out
    int8_t* my_r1,             // [S] in/out
    int8_t* my_r2,             // [S] in/out
    int8_t* led1,              // [R*S] in/out
    int8_t* led2,              // [R*S] in/out
    int8_t* decided,           // [S] in/out
    uint8_t* done,             // [S] in/out
    const uint8_t* active,     // [S]
    const int8_t* decision_in, // [S] or nullptr
    uint8_t* cast_r2,          // [S] out
    int8_t* r2_vals,           // [S] out
    uint8_t* advanced,         // [S] out
    uint8_t* newly_decided,    // [S] out
    uint64_t* coin_out         // [2] or nullptr (accounting only)
) {
  for (int32_t s = s_lo; s < s_hi; s++) {
    const int8_t st0 = stage[s];
    int8_t m2 = my_r2[s];
    uint8_t cast = 0, adv = 0, newdec = 0;
    const bool enabled = active[s] && !done[s];

    if (enabled && st0 == R1_WAIT) {
      // round-1 tally down this shard's ledger column
      int32_t c0 = 0, c1 = 0, cq = 0;
      for (int32_t r = 0; r < R; r++) {
        const int8_t v = led1[(int64_t)r * S + s];
        c0 += (v == V0);
        c1 += (v == V1);
        cq += (v == VQ);
      }
      if (c0 + c1 + cq >= quorum) {
        cast = 1;
        m2 = (c1 >= quorum) ? V1 : ((c0 >= quorum) ? V0 : VQ);
        my_r2[s] = m2;
        stage[s] = R2_WAIT;
        led2[(int64_t)me * S + s] = m2;
      }
    } else if (enabled && st0 == R2_WAIT) {
      int32_t d0 = 0, d1 = 0, dq = 0;
      for (int32_t r = 0; r < R; r++) {
        const int8_t v = led2[(int64_t)r * S + s];
        d0 += (v == V0);
        d1 += (v == V1);
        dq += (v == VQ);
      }
      if (d0 + d1 + dq >= quorum) {
        adv = 1;
        const bool dec1 = d1 >= f1, dec0 = d0 >= f1;
        int8_t next_v;
        if (dec1) next_v = V1;
        else if (dec0) next_v = V0;
        else if (d1 > 0) next_v = V1;
        else if (d0 > 0) next_v = V0;
        else {
          next_v = coin_bit(seed, (uint32_t)s, (uint32_t)slot[s],
                            (uint32_t)phase[s], coin_threshold);
          if (coin_out) coin_out[next_v == V1 ? 1 : 0]++;
        }
        if (dec1 || dec0) {
          newdec = 1;
          decided[s] = dec1 ? V1 : V0;
        }
        // advance to the next weak-MVC phase
        phase[s] += 1;
        my_r1[s] = next_v;
        stage[s] = R1_WAIT;
        my_r2[s] = ABS;
        for (int32_t r = 0; r < R; r++) {
          led1[(int64_t)r * S + s] = ABS;
          led2[(int64_t)r * S + s] = ABS;
        }
        led1[(int64_t)me * S + s] = next_v;
      }
    }

    // adopted decision (Decision frames routed by the engine): only when
    // not decided by this very step
    if (enabled && !newdec && decision_in && decision_in[s] != ABS) {
      decided[s] = decision_in[s];
      done[s] = 1;
    } else if (newdec) {
      done[s] = 1;
    }

    cast_r2[s] = cast;
    // pre-advance-clear value: an advancing shard reports the R2 vote it
    // had cast in the phase it is leaving (numpy copies my_r2 post-cast,
    // pre-clear)
    r2_vals[s] = m2;
    advanced[s] = adv;
    newly_decided[s] = newdec;
  }
}

void rk_node_step(
    int32_t S, int32_t R, int32_t me, int32_t quorum, int32_t f1,
    uint32_t seed, uint32_t coin_threshold,
    const int32_t* slot, int32_t* phase, int8_t* stage, int8_t* my_r1,
    int8_t* my_r2, int8_t* led1, int8_t* led2, int8_t* decided,
    uint8_t* done, const uint8_t* active, const int8_t* decision_in,
    uint8_t* cast_r2, int8_t* r2_vals, uint8_t* advanced,
    uint8_t* newly_decided) {
  rk_node_step_impl(S, R, me, quorum, f1, seed, coin_threshold, 0, S, slot,
                    phase, stage, my_r1, my_r2, led1, led2, decided, done,
                    active, decision_in, cast_r2, r2_vals, advanced,
                    newly_decided, nullptr);
}

// rk_node_step + coin accounting (coin_out: 2 uint64 cells, V0/V1).
void rk_node_step_ex(
    int32_t S, int32_t R, int32_t me, int32_t quorum, int32_t f1,
    uint32_t seed, uint32_t coin_threshold,
    const int32_t* slot, int32_t* phase, int8_t* stage, int8_t* my_r1,
    int8_t* my_r2, int8_t* led1, int8_t* led2, int8_t* decided,
    uint8_t* done, const uint8_t* active, const int8_t* decision_in,
    uint8_t* cast_r2, int8_t* r2_vals, uint8_t* advanced,
    uint8_t* newly_decided, uint64_t* coin_out) {
  rk_node_step_impl(S, R, me, quorum, f1, seed, coin_threshold, 0, S, slot,
                    phase, stage, my_r1, my_r2, led1, led2, decided, done,
                    active, decision_in, cast_r2, r2_vals, advanced,
                    newly_decided, coin_out);
}

// start_slots: (re)arm masked shards for a new decision slot.
void rk_start_slots(
    int32_t S, int32_t R, int32_t me,
    const uint8_t* mask,        // [S]
    const int32_t* slot_index,  // [S]
    const int8_t* initial,      // [S]
    int32_t* slot, int32_t* phase, int8_t* stage, int8_t* my_r1,
    int8_t* my_r2, int8_t* led1, int8_t* led2, int8_t* decided,
    uint8_t* done, uint8_t* active) {
  for (int32_t s = 0; s < S; s++) {
    if (!mask[s]) continue;
    slot[s] = slot_index[s];
    phase[s] = 0;
    stage[s] = R1_WAIT;
    my_r1[s] = initial[s];
    my_r2[s] = ABS;
    decided[s] = ABS;
    done[s] = 0;
    active[s] = 1;
    for (int32_t r = 0; r < R; r++) {
      led1[(int64_t)r * S + s] = ABS;
      led2[(int64_t)r * S + s] = ABS;
    }
    led1[(int64_t)me * S + s] = initial[s];
  }
}

// Columnar open-candidate scan (engine _open_slots prologue): one pass
// instead of ~9 numpy dispatches per tick. Fills head[s] =
// max(next_slot, applied) and cand[s]; returns the candidate count so an
// idle tick exits on a single int.
// Device-KV window pack gather (the GRID fast path: full-width sorted
// blocks, op i covers wave i/n, shard i%n). One pass copies each op's
// key/value bytes into the zeroed padded planes — replacing numpy's
// materialize-gather + where-mask + reshape-scatter chain (~4 full
// passes over the op bytes) with a single read+write. Validation
// stays in Python (the numpy path remains the semantics owner and
// fallback); this function only trusts its own bounds check and
// returns nonzero on any out-of-range op so the caller can fall back.
int32_t rk_pack_gather(
    const uint8_t* dbuf, int64_t dbuf_len,
    const int64_t* off, const int64_t* klen, const int64_t* vlen,
    int64_t n_ops, int64_t n, int64_t S, int64_t hdr,
    int64_t ku, int64_t vu,
    uint8_t* kwin, uint8_t* vwin) {
  for (int64_t i = 0; i < n_ops; i++) {
    const int64_t kl = klen[i];
    const int64_t vl = vlen[i];
    const int64_t o = off[i] + hdr;
    if (kl < 0 || vl < 0 || kl > ku || vl > vu || o < 0 ||
        o + kl + vl > dbuf_len) {
      return 1;  // out of envelope/bounds: caller uses the numpy path
    }
    const int64_t row = (i / n) * S + (i % n);
    std::memcpy(kwin + row * ku, dbuf + o, (size_t)kl);
    std::memcpy(vwin + row * vu, dbuf + o + kl, (size_t)vl);
  }
  return 0;
}

int32_t rk_open_scan(
    int32_t S,
    const int64_t* next_slot, const int64_t* applied,
    const uint8_t* in_flight, const int64_t* queue_len,
    const uint8_t* prop_flag, const uint8_t* dec_flag,
    const int64_t* votes_seen, const int64_t* tainted,
    int64_t* head, uint8_t* cand) {
  int32_t n = 0;
  for (int32_t s = 0; s < S; s++) {
    const int64_t h =
        next_slot[s] > applied[s] ? next_slot[s] : applied[s];
    head[s] = h;
    const uint8_t c =
        !in_flight[s] &&
        (queue_len[s] > 0 || prop_flag[s] || dec_flag[s] ||
         votes_seen[s] >= h || tainted[s] > 0);
    cand[s] = c;
    n += c;
  }
  return n;
}

// Timeout pre-scan: "is any in-flight shard stalled past `timeout`?" in
// one C call — the engine's per-tick retransmit check early-outs on this
// instead of ~5 numpy dispatches (which dominate the serial shape).
int32_t rk_stall_scan(int32_t S, const uint8_t* in_flight,
                      const double* last_progress, double now,
                      double timeout) {
  for (int32_t s = 0; s < S; s++) {
    if (in_flight[s] && now - last_progress[s] >= timeout) return 1;
  }
  return 0;
}

// ===========================================================================
// Native per-tick fast path (the "rk tick context").
//
// The engine's per-round ingest→route→tally→outbox path, with Python
// touched only for EVENTS (decisions ready to record/apply, sync,
// membership, timeouts). Semantics owner: the Python paths in
// engine/engine.py (`_ingest_vote_arrays`/`_route_votes`/`_kernel_round`/
// `_process_outbox`) — every transition here mirrors them element-for-
// element; conformance is pinned by tests/test_native_tick.py and the
// seeded fuzz schedules run under RABIA_PY_TICK=1 vs the default.
//
// What runs here:
//  - rk_ingest: decode VoteRound1/VoteRound2/Decision wire frames
//    (byte layout of core/serialization.py v3) straight out of the
//    transport arena (or any bytes buffer) — no Python objects; perform
//    the stale-drop / taint-mark / votes-seen side effects; scatter
//    (slot, phase)-matched votes into the kernel ledger; carry future
//    votes; buffer stale ones for the Python repair path.
//  - rk_tick: chained route→node_step→outbox rounds (R1→R2→decide with
//    no Python in between when input allows), framing outbound vote /
//    decision messages directly into a caller-provided buffer in the
//    exact wire format peers decode.
//
// Everything the context touches is borrowed, caller-owned numpy memory
// registered once at creation — the engine guarantees those arrays stay
// alive and in place for the context's lifetime.
// ===========================================================================

enum : int32_t {
  RK_HANDLED = 1,       // consumed natively, with ledger/plane effects
  RK_NOOP = 2,          // consumed natively, NO effects (all entries
                        // stale/dropped) — the engine may skip the kernel
                        // round it would otherwise run for this traffic
  RK_PY = 0,            // not a fast-path frame: Python must handle it
  RK_DROP = -1,         // malformed / spoofed / validation-failed: drop
};

// Versioned, append-only counter block (the observability plane's
// zero-copy window into the rk tick context). Indices are ABI: new
// counters append before RKC_COUNT and bump RK_COUNTERS_VERSION; nothing
// is ever renumbered or removed, so a newer Python reader degrades to
// reading the prefix it knows. Read via rk_counters() as a uint64[]
// ndarray — single-writer (the engine's event loop), so plain u64 cells.
enum : int32_t {
  RKC_TICKS = 0,        // rk_tick calls
  RKC_STAGES,           // chained route->step->outbox activations
  RKC_FRAMES_V1,        // VoteRound1 frames consumed natively
  RKC_FRAMES_V2,        // VoteRound2 frames consumed natively
  RKC_FRAMES_DEC,       // Decision frames consumed natively
  RKC_FRAMES_NOOP,      // frames consumed with no effects (RK_NOOP)
  RKC_DROP_SPOOF,       // envelope/transport sender mismatch
  RKC_DROP_SKEW,        // clock-skew rejections
  RKC_DROP_MALFORMED,   // bad vote/decision codes, empty vote vectors
  RKC_STALE,            // stale (below-applied) vote entries observed
  RKC_TAINT_HITS,       // votes landing under a taint horizon
  RKC_CARRY,            // future-(slot,phase) votes carried
  RKC_SCATTER,          // ledger cell writes (ingest + carry replay)
  RKC_OUT_FRAMES,       // outbound frames emitted by rk_tick
  RKC_DECIDED,          // shards newly decided inside rk_tick
  RKC_OPENED,           // shards armed (opened) by rk_tick
  // -- consensus-health telemetry (chaos plane, v2) --------------------
  RKC_COIN_V0,          // common-coin flips landing V0 (MUST stay
  RKC_COIN_V1,          // adjacent to RKC_COIN_V1: rk_tick hands the
                        // pair to the step as one 2-cell block)
  RKC_PHASE_SUM,        // sum of phases-to-decide over local decisions
  RKC_COUNT
};
static const int32_t RK_COUNTERS_VERSION = 2;

// Phases-to-decide histogram: bin p counts local tally decisions whose
// weak-MVC phase count was p (clamped into the top bin). Sized for the
// tail the paper's termination analysis cares about (P[phases > p]
// decays ~2^-p; 32 covers anything a live cluster can produce).
static const int32_t RK_PHASE_HIST = 32;

// ---------------------------------------------------------------------------
// Flight recorder: a fixed-size binary event ring written on the fast path.
//
// One 32-byte record per ingest / route / node_step / outbox decision, so a
// misrouted vote or stale storm inside a native run is reconstructable after
// the fact (the engine auto-dumps the ring on severe anomalies; the trace
// collector slices it per batch). The record layout and kind codes are a
// versioned ABI like the RKC_* counter block: fields/kinds append, nothing is
// renumbered. The Python twin (rabia_tpu/obs/flight.py FR_DTYPE /
// FlightRecorder) mirrors this layout exactly; RABIA_PY_TICK=1 feeds the
// same kinds from the Python tick paths.
//
// batch_hash is always 0 here: vote/decision wire frames carry no batch ids
// (ids derive from (client_id, seq) — PR 1), so batch association happens at
// the Python event layer (propose/decide/apply records) and the trace merger
// joins on (shard, slot).
// ---------------------------------------------------------------------------

enum : uint8_t {
  FRE_FRAME_IN = 1,     // consensus frame consumed (arg = wire msg_type,
                        // peer = sender row, shard/slot of first entry)
  FRE_ROUTE1 = 2,       // R1 vote scattered into the ledger (arg = vote)
  FRE_ROUTE2 = 3,       // R2 vote scattered into the ledger (arg = vote)
  FRE_CARRY = 4,        // future-(slot,phase) vote carried (arg = round)
  FRE_STALE = 5,        // below-applied vote entry (repair path)
  FRE_DROP = 6,         // frame dropped (arg: 1 spoof, 2 skew, 3 malformed)
  FRE_OPEN = 7,         // slot armed (arg = initial vote)
  FRE_CAST_R2 = 8,      // R1 quorum -> R2 cast (arg = cast vote)
  FRE_ADVANCE = 9,      // weak-MVC phase advance (arg = new phase & 0xFF)
  FRE_STEP_DECIDE = 10, // node_step decided (arg = decided value)
  FRE_FRAME_OUT = 11,   // outbound frame emitted (arg = wire msg_type,
                        // shard/slot of first entry)
  // 12..16 are Python-event kinds (submit/propose/decide/apply/result) and
  // 17/18 the transport frame in/out kinds — never written by this ring but
  // reserved here so the numbering space stays single-sourced.
};

struct FrEvent {
  uint64_t t_ns;        // CLOCK_MONOTONIC
  uint64_t slot;        // decision slot (0 when not slot-scoped)
  uint64_t batch_hash;  // always 0 on the native ring (see above)
  uint32_t shard;
  uint16_t peer;        // sender row, or 0xFFFF when not peer-scoped
  uint8_t kind;         // FRE_*
  uint8_t arg;
};
static_assert(sizeof(FrEvent) == 32, "flight record layout is ABI");

static const int32_t RK_FLIGHT_VERSION = 1;
static const uint32_t RK_FLIGHT_CAP = 4096;  // power of two

// ---------------------------------------------------------------------------
// Per-phase consensus dwell: how long each weak-MVC phase actually took,
// measured where the phase runs (slot open -> advance -> ... -> decide),
// not inferred from aggregate phase counts. One histogram row per phase
// ordinal (1..RK_DWELL_PHASES, top row clamps "8+"), RTH-style log-bucket
// geometry (runtime.cpp): 2^SUB_BITS sub-buckets per power-of-two octave
// from 2^MIN_EXP ns; row layout = BUCKETS counts + total count + sum_ns
// (stride BUCKETS + 2). Versioned ABI like the RKC_* block; the Python
// tick twin (engine._py_dwell) mirrors this geometry exactly.
// ---------------------------------------------------------------------------
static const int32_t RK_DWELL_VERSION = 1;
static const int32_t RK_DWELL_SUB_BITS = 2;  // 4 sub-buckets per octave
static const int32_t RK_DWELL_MIN_EXP = 10;  // floor 1.024us
static const int32_t RK_DWELL_OCTAVES = 25;  // top bound 2^35 ns ~ 34.4s
static const int32_t RK_DWELL_BUCKETS = RK_DWELL_OCTAVES << RK_DWELL_SUB_BITS;
static const int32_t RK_DWELL_STRIDE = RK_DWELL_BUCKETS + 2;
static const int32_t RK_DWELL_PHASES = 8;  // rows: phase 1..7 + "8+"

static inline uint64_t fr_now_ns() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (uint64_t)ts.tv_sec * 1000000000ull + (uint64_t)ts.tv_nsec;
}

struct RkCarry {
  int32_t row;
  int32_t shard;
  int64_t slot;
  int32_t mvc;
  int8_t val;
};

struct RkStale {
  int32_t row;
  int32_t shard;
  int64_t slot;
};

struct RkCtx {
  // geometry / protocol constants
  int32_t S, n, R, me, quorum, f1;
  uint32_t seed, coin_threshold;
  int32_t dec_ring;           // ring depth (power of two)
  int32_t decision_broadcast; // emit Decision frames for newly decided
  double max_future_skew, max_age;

  // engine runtime columns (borrowed)
  int64_t* next_slot;
  int64_t* applied;
  uint8_t* in_flight;
  int64_t* votes_seen;
  int64_t* tainted;
  double* taint_traffic;
  double* last_progress;
  int64_t* ring_slot;  // [S, dec_ring]
  int8_t* ring_val;    // [S, dec_ring]

  // kernel state (borrowed, persistent — mutated in place)
  int32_t* slot;
  int32_t* phase;
  int8_t* stage;
  int8_t* my_r1;
  int8_t* my_r2;
  int8_t* led1;  // [R, S]
  int8_t* led2;  // [R, S]
  int8_t* decided;
  uint8_t* done;
  uint8_t* active;
  int8_t* dec_plane;   // adopted-decision inbox [S]
  uint8_t* newly_acc;  // newly-decided accumulator [S] (engine reads+clears)

  // shard-group range [g_lo, g_hi): the thread-per-shard-group runtime
  // partitions the shard space across per-worker contexts — this ctx
  // ingests/ticks ONLY shards in its range and skips foreign entries
  // (another worker's ctx owns them). Default [0, n) = today's single
  // full-range context, byte-for-byte. id_salt keeps message ids unique
  // across sibling contexts sharing (seed, me); it never feeds the coin.
  int32_t g_lo, g_hi;
  uint32_t id_salt;

  // identity: row -> 16B node uuid (spoof check + outbound sender field)
  std::vector<uint8_t> uuids;  // R * 16
  uint64_t rows_seen;

  // carried future-(slot, phase) votes, bounded like the Python carry
  std::vector<RkCarry> carry1, carry2;
  // stale-vote reports for the Python repair path (rate-limited there)
  std::vector<RkStale> stale;
  uint64_t dropped;  // frames rejected with RK_DROP (engine stats)

  // node_step outbox scratch
  std::vector<uint8_t> cast_r2, advanced, newly_step;
  std::vector<int8_t> r2_vals;
  std::vector<int32_t> idx_scratch;

  uint64_t msg_counter;

  // observability counter block (see RKC_* above); zero-initialized
  uint64_t ctrs[RKC_COUNT];

  // phases-to-decide histogram (see RK_PHASE_HIST above); zero-init
  uint64_t phase_hist[RK_PHASE_HIST];

  // per-phase dwell histogram block (see RK_DWELL_* above); zero-init
  uint64_t dwell[RK_DWELL_PHASES * RK_DWELL_STRIDE];
  // per-shard stamp of the in-progress phase's start, plus the slot it
  // was stamped for (-1 = unarmed). Slots armed outside rk_tick's open
  // path (rk_start_slots called directly) carry no stamp; the slot
  // guard skips them instead of mis-attributing a stale interval.
  std::vector<uint64_t> dwell_t0;
  std::vector<int64_t> dwell_t0_slot;

  // flight-recorder event ring (see FrEvent above); fr_head counts every
  // record ever written, the live window is the last RK_FLIGHT_CAP
  std::vector<FrEvent> fr;
  // relaxed atomic: written on the tick path, read by the Python
  // scrape thread via rk_flight_head while the engine runs
  std::atomic<uint64_t> fr_head;
};

static inline void fr_rec(RkCtx* c, uint8_t kind, uint8_t arg, uint16_t peer,
                          uint32_t shard, int64_t slot) {
  const uint64_t head = c->fr_head.load(std::memory_order_relaxed);
  FrEvent& e = c->fr[head & (RK_FLIGHT_CAP - 1)];
  e.t_ns = fr_now_ns();
  e.slot = (uint64_t)slot;
  e.batch_hash = 0;
  e.shard = shard;
  e.peer = peer;
  e.kind = kind;
  e.arg = arg;
  c->fr_head.store(head + 1, std::memory_order_relaxed);
}

// One completed phase -> its dwell row (phase is the 1-based ordinal of
// the phase that just finished: slots open at phase 0 and each advance
// bumps by one, so the post-advance value counts completed phases).
// Bucketing is bit-identical to runtime.cpp rth_observe.
static inline void rk_dwell_obs(RkCtx* c, int32_t phase, uint64_t ns) {
  if (phase < 1) return;
  const int32_t row =
      (phase < RK_DWELL_PHASES ? phase : RK_DWELL_PHASES) - 1;
  uint64_t* h = c->dwell + (size_t)row * RK_DWELL_STRIDE;
  int32_t idx = 0;
  if (ns >= (1ull << RK_DWELL_MIN_EXP)) {
    const int32_t exp = 63 - __builtin_clzll(ns);
    const int32_t sub = (int32_t)((ns >> (exp - RK_DWELL_SUB_BITS)) &
                                  ((1 << RK_DWELL_SUB_BITS) - 1));
    idx = ((exp - RK_DWELL_MIN_EXP) << RK_DWELL_SUB_BITS) + sub;
    if (idx >= RK_DWELL_BUCKETS) idx = RK_DWELL_BUCKETS - 1;
  }
  h[idx]++;
  h[RK_DWELL_BUCKETS]++;
  h[RK_DWELL_BUCKETS + 1] += ns;
}

static const size_t RK_STALE_CAP = 1024;

static inline uint32_t rd_u32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}
static inline uint64_t rd_u64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;
}
static inline double rd_f64(const uint8_t* p) {
  double v;
  std::memcpy(&v, p, 8);
  return v;
}

// --- context lifecycle ------------------------------------------------------

// dims: [S, n, R, me, quorum, f1, seed, coin_threshold, dec_ring,
//        decision_broadcast]
// ptrs: [next_slot, applied, in_flight, votes_seen, tainted, taint_traffic,
//        last_progress, ring_slot, ring_val,
//        slot, phase, stage, my_r1, my_r2, led1, led2, decided, done,
//        active, dec_plane, newly_acc]
// uuids: R * 16 bytes (row-major node ids)
// fparams: [max_future_skew, max_age]
void* rk_ctx_create(const int64_t* dims, const int64_t* ptrs,
                    const uint8_t* uuids, const double* fparams) {
  RkCtx* c = new RkCtx();
  c->S = (int32_t)dims[0];
  c->n = (int32_t)dims[1];
  c->R = (int32_t)dims[2];
  c->me = (int32_t)dims[3];
  c->quorum = (int32_t)dims[4];
  c->f1 = (int32_t)dims[5];
  c->seed = (uint32_t)dims[6];
  c->coin_threshold = (uint32_t)dims[7];
  c->dec_ring = (int32_t)dims[8];
  c->decision_broadcast = (int32_t)dims[9];
  int i = 0;
  c->next_slot = (int64_t*)ptrs[i++];
  c->applied = (int64_t*)ptrs[i++];
  c->in_flight = (uint8_t*)ptrs[i++];
  c->votes_seen = (int64_t*)ptrs[i++];
  c->tainted = (int64_t*)ptrs[i++];
  c->taint_traffic = (double*)ptrs[i++];
  c->last_progress = (double*)ptrs[i++];
  c->ring_slot = (int64_t*)ptrs[i++];
  c->ring_val = (int8_t*)ptrs[i++];
  c->slot = (int32_t*)ptrs[i++];
  c->phase = (int32_t*)ptrs[i++];
  c->stage = (int8_t*)ptrs[i++];
  c->my_r1 = (int8_t*)ptrs[i++];
  c->my_r2 = (int8_t*)ptrs[i++];
  c->led1 = (int8_t*)ptrs[i++];
  c->led2 = (int8_t*)ptrs[i++];
  c->decided = (int8_t*)ptrs[i++];
  c->done = (uint8_t*)ptrs[i++];
  c->active = (uint8_t*)ptrs[i++];
  c->dec_plane = (int8_t*)ptrs[i++];
  c->newly_acc = (uint8_t*)ptrs[i++];
  c->g_lo = 0;
  c->g_hi = c->n;
  c->id_salt = 0;
  c->uuids.assign(uuids, uuids + (size_t)c->R * 16);
  c->rows_seen = 0;
  c->dropped = 0;
  c->msg_counter = 0;
  c->max_future_skew = fparams[0];
  c->max_age = fparams[1];
  c->cast_r2.resize(c->S);
  c->advanced.resize(c->S);
  c->newly_step.resize(c->S);
  c->r2_vals.resize(c->S);
  c->idx_scratch.resize(c->S);
  std::memset(c->ctrs, 0, sizeof(c->ctrs));
  std::memset(c->phase_hist, 0, sizeof(c->phase_hist));
  std::memset(c->dwell, 0, sizeof(c->dwell));
  c->dwell_t0.assign((size_t)c->S, 0);
  c->dwell_t0_slot.assign((size_t)c->S, -1);
  c->fr.resize(RK_FLIGHT_CAP);
  c->fr_head = 0;
  return c;
}

void rk_ctx_destroy(void* ctx) { delete (RkCtx*)ctx; }

// Restrict this context to the shard-group range [lo, hi) (the
// thread-per-shard-group runtime: one ctx per worker, disjoint ranges
// over shared engine arrays). `salt` differentiates sibling contexts'
// outbound message ids; it does NOT perturb the common coin, so a
// range-partitioned cluster decides identically to a full-range one.
// Call only while no thread is inside this ctx (pre-start or paused).
void rk_set_range(void* ctx, int32_t lo, int32_t hi, uint32_t salt) {
  RkCtx* c = (RkCtx*)ctx;
  if (lo < 0) lo = 0;
  if (hi > c->n) hi = c->n;
  if (hi < lo) hi = lo;
  c->g_lo = lo;
  c->g_hi = hi;
  c->id_salt = salt;
}

uint64_t rk_rows_seen(void* ctx) {
  RkCtx* c = (RkCtx*)ctx;
  uint64_t m = c->rows_seen;
  c->rows_seen = 0;
  return m;
}

uint64_t rk_dropped(void* ctx) { return ((RkCtx*)ctx)->dropped; }

// --- counter block (observability plane) ------------------------------------

int32_t rk_counters_version(void) { return RK_COUNTERS_VERSION; }
int32_t rk_counters_count(void) { return RKC_COUNT; }
// Borrowed pointer to the context's uint64 counter block; valid for the
// context's lifetime. The Python side wraps it as a read-only ndarray.
void* rk_counters(void* ctx) { return ((RkCtx*)ctx)->ctrs; }

// Phases-to-decide histogram (uint64[rk_phase_hist_len()], bin p =
// decisions taking p phases, top bin clamps). Borrowed, context-lifetime,
// single-writer — same contract as rk_counters.
int32_t rk_phase_hist_len(void) { return RK_PHASE_HIST; }
void* rk_phase_hist(void* ctx) { return ((RkCtx*)ctx)->phase_hist; }

// --- flight recorder (binary event ring) ------------------------------------

int32_t rk_flight_version(void) { return RK_FLIGHT_VERSION; }
int32_t rk_flight_cap(void) { return (int32_t)RK_FLIGHT_CAP; }
int32_t rk_flight_record_size(void) { return (int32_t)sizeof(FrEvent); }
// Borrowed pointer to the ring base (RK_FLIGHT_CAP records of
// rk_flight_record_size() bytes); valid for the context's lifetime.
// Single-writer (the engine's event loop); foreign-thread snapshot reads
// may see one torn in-flight record — metrics-grade, not ledger-grade.
void* rk_flight(void* ctx) { return ((RkCtx*)ctx)->fr.data(); }
// Total records ever written; the live window is the last
// min(head, RK_FLIGHT_CAP) records ending at head % RK_FLIGHT_CAP.
uint64_t rk_flight_head(void* ctx) {
  return ((RkCtx*)ctx)->fr_head.load(std::memory_order_relaxed);
}

// --- per-phase dwell histogram block ----------------------------------------

int32_t rk_dwell_version(void) { return RK_DWELL_VERSION; }
int32_t rk_dwell_phases(void) { return RK_DWELL_PHASES; }
int32_t rk_dwell_buckets(void) { return RK_DWELL_BUCKETS; }
int32_t rk_dwell_sub_bits(void) { return RK_DWELL_SUB_BITS; }
int32_t rk_dwell_min_exp(void) { return RK_DWELL_MIN_EXP; }
// Borrowed pointer to the context's dwell block (rk_dwell_phases() rows
// of rk_dwell_buckets() bucket counts + total count + sum_ns, stride
// buckets + 2); context-lifetime, single-writer — the rk_counters
// contract. The geometry accessors exist so the Python exporter can
// refuse to decode a block whose shape it does not recognize.
void* rk_dwell(void* ctx) { return ((RkCtx*)ctx)->dwell; }

int64_t rk_carry_count(void* ctx) {
  RkCtx* c = (RkCtx*)ctx;
  return (int64_t)(c->carry1.size() + c->carry2.size());
}

// Pop up to `cap` buffered stale-vote reports (row, shard, slot) for the
// Python repair path. Returns the count written.
int64_t rk_drain_stale(void* ctx, int64_t* rows, int64_t* shards,
                       int64_t* slots, int64_t cap) {
  RkCtx* c = (RkCtx*)ctx;
  int64_t k = 0;
  for (const RkStale& st : c->stale) {
    if (k >= cap) break;
    rows[k] = st.row;
    shards[k] = st.shard;
    slots[k] = st.slot;
    k++;
  }
  c->stale.clear();
  return k;
}

// --- frame ingest -----------------------------------------------------------

// Wire layout (core/serialization.py, version 3):
//   u8 version | u8 msg_type | u8 flags | 16B id | 16B sender |
//   [16B recipient] | f64 timestamp | u32 body_len | body
// Vote body:     u32 count + count * 13B (u32 shard | u64 phase | u8 vote)
// Decision body: u32 count + count * 14B (u32 shard | u64 phase | u8 val |
//                u8 has_bid) + 16B per has_bid entry
enum : uint8_t {
  MT_VOTE1 = 2,
  MT_VOTE2 = 3,
  MT_DECISION = 4,
  FLAG_COMPRESSED = 0x01,
  FLAG_RECIPIENT = 0x02,
};

static inline bool rk_route_one(RkCtx* c, int32_t round_no, int32_t row,
                                int32_t s, int64_t slot, int32_t mvc,
                                int8_t val, std::vector<RkCarry>& carry) {
  if (c->in_flight[s] && slot == (int64_t)c->slot[s] &&
      mvc == c->phase[s]) {
    int8_t* led = (round_no == 1 ? c->led1 : c->led2);
    int8_t& cell = led[(int64_t)row * c->S + s];
    if (cell == ABS) {
      cell = val;
      c->ctrs[RKC_SCATTER]++;
      fr_rec(c, round_no == 1 ? FRE_ROUTE1 : FRE_ROUTE2, (uint8_t)val,
             (uint16_t)row, (uint32_t)s, slot);
      return true;
    }
    return false;  // first-write-wins duplicate: nothing changed
  }
  carry.push_back(RkCarry{row, s, slot, mvc, val});
  c->ctrs[RKC_CARRY]++;
  fr_rec(c, FRE_CARRY, (uint8_t)round_no, (uint16_t)row, (uint32_t)s, slot);
  return true;
}

int32_t rk_ingest(void* ctx, const uint8_t* data, int64_t len, int32_t row,
                  double now) {
  RkCtx* c = (RkCtx*)ctx;
  if (len < 47) return RK_PY;  // not even a recipient-less header
  const uint8_t version = data[0];
  const uint8_t msg_type = data[1];
  const uint8_t flags = data[2];
  if (version != 3) return RK_PY;
  if (msg_type != MT_VOTE1 && msg_type != MT_VOTE2 &&
      msg_type != MT_DECISION)
    return RK_PY;
  if (flags & FLAG_COMPRESSED) return RK_PY;  // votes are never compressed
  if (row < 0 || row >= c->R) return RK_PY;
  // envelope sender must match the transport-authenticated peer row
  // (engine._handle_message spoof guard)
  if (std::memcmp(data + 19, c->uuids.data() + (size_t)row * 16, 16) != 0) {
    c->dropped++;
    c->ctrs[RKC_DROP_SPOOF]++;
    fr_rec(c, FRE_DROP, 1, (uint16_t)row, 0, 0);
    return RK_DROP;
  }
  int64_t base = 35 + ((flags & FLAG_RECIPIENT) ? 16 : 0);
  if (len < base + 12) return RK_PY;
  const double ts = rd_f64(data + base);
  if (ts > now + c->max_future_skew || ts < now - c->max_age) {
    c->dropped++;  // clock-skew rejection (MessageValidator parity)
    c->ctrs[RKC_DROP_SKEW]++;
    fr_rec(c, FRE_DROP, 2, (uint16_t)row, 0, 0);
    return RK_DROP;
  }
  const uint32_t body_len = rd_u32(data + base + 8);
  const uint8_t* body = data + base + 12;
  if ((int64_t)body_len > len - (base + 12) || body_len < 4) return RK_PY;
  const uint32_t count = rd_u32(body);
  const uint8_t* ent = body + 4;

  if (msg_type == MT_DECISION) {
    if (body_len < 4 + (uint64_t)count * 14) return RK_PY;
    // pass 1: classify without side effects — any entry the Python path
    // must see (bid-bearing, out-of-range, live-but-not-current and not
    // in the decided ring) bails the WHOLE frame out untouched
    for (uint32_t k = 0; k < count; k++) {
      const uint8_t* e = ent + (size_t)k * 14;
      const uint32_t s = rd_u32(e);
      const uint64_t ph = rd_u64(e + 4);
      const uint8_t val = e[12];
      if (e[13]) return RK_PY;       // has_bid: recovery path
      if (val == VQ || val > 3) {
        // "decision cannot be V?" (validator) / code out of range
        // (codec parity) — adopting a garbage code would later blow up
        // StateValue() on the Python event path
        c->dropped++;
        c->ctrs[RKC_DROP_MALFORMED]++;
        fr_rec(c, FRE_DROP, 3, (uint16_t)row, s, (int64_t)(ph >> 16));
        return RK_DROP;
      }
      if (s >= (uint32_t)c->n) return RK_PY;
      if ((int32_t)s < c->g_lo || (int32_t)s >= c->g_hi)
        continue;  // another shard group's entry: its worker owns it
      const int64_t slot = (int64_t)(ph >> 16);
      if (slot < c->applied[s]) continue;  // stale: dropped in pass 2
      if (c->in_flight[s] && slot == (int64_t)c->slot[s]) continue;
      const int64_t ring = slot & (c->dec_ring - 1);
      if (c->ring_slot[(int64_t)s * c->dec_ring + ring] == slot)
        continue;  // already decided locally: recording again is a no-op
      return RK_PY;  // gap/future decision: Python ledger logic owns it
    }
    bool dec_effect = false;
    for (uint32_t k = 0; k < count; k++) {
      const uint8_t* e = ent + (size_t)k * 14;
      const uint32_t s = rd_u32(e);
      const uint64_t ph = rd_u64(e + 4);
      const int64_t slot = (int64_t)(ph >> 16);
      if (s >= (uint32_t)c->n || slot < c->applied[s]) continue;
      if ((int32_t)s < c->g_lo || (int32_t)s >= c->g_hi) continue;
      if (c->in_flight[s] && slot == (int64_t)c->slot[s]) {
        c->dec_plane[s] = (int8_t)e[12];
        dec_effect = true;
      }
    }
    c->rows_seen |= 1ull << (row & 63);
    c->ctrs[RKC_FRAMES_DEC]++;
    if (!dec_effect) c->ctrs[RKC_FRAMES_NOOP]++;
    fr_rec(c, FRE_FRAME_IN, MT_DECISION, (uint16_t)row,
           count ? rd_u32(ent) : 0,
           count ? (int64_t)(rd_u64(ent + 4) >> 16) : 0);
    return dec_effect ? RK_HANDLED : RK_NOOP;
  }

  // vote vector (R1/R2)
  if (count == 0) {
    c->dropped++;  // "vote vector must be non-empty" (validator)
    c->ctrs[RKC_DROP_MALFORMED]++;
    fr_rec(c, FRE_DROP, 3, (uint16_t)row, 0, 0);
    return RK_DROP;
  }
  if (body_len < 4 + (uint64_t)count * 13) return RK_PY;
  // codec parity: reject out-of-range vote codes before any side effect
  for (uint32_t k = 0; k < count; k++) {
    if (ent[(size_t)k * 13 + 12] > 3) {
      c->dropped++;
      c->ctrs[RKC_DROP_MALFORMED]++;
      fr_rec(c, FRE_DROP, 3, (uint16_t)row, 0, 0);
      return RK_DROP;
    }
  }
  const int32_t round_no = (msg_type == MT_VOTE1) ? 1 : 2;
  std::vector<RkCarry>& carry = (round_no == 1) ? c->carry1 : c->carry2;
  bool effect = false;
  for (uint32_t k = 0; k < count; k++) {
    const uint8_t* e = ent + (size_t)k * 13;
    const uint32_t s = rd_u32(e);
    if (s >= (uint32_t)c->n) continue;  // bounds filter (ingest parity)
    if ((int32_t)s < c->g_lo || (int32_t)s >= c->g_hi)
      continue;  // another shard group's vote: its worker's ctx owns it
    const uint64_t ph = rd_u64(e + 4);
    const int64_t slot = (int64_t)(ph >> 16);
    const int32_t mvc = (int32_t)(ph & 0xFFFF);
    const int8_t val = (int8_t)e[12];
    if (slot < c->applied[s]) {
      c->ctrs[RKC_STALE]++;
      fr_rec(c, FRE_STALE, (uint8_t)round_no, (uint16_t)row, s, slot);
      if (c->stale.size() < RK_STALE_CAP)
        c->stale.push_back(RkStale{row, (int32_t)s, slot});
      continue;
    }
    if (slot < c->tainted[s]) {
      c->taint_traffic[s] = now;
      c->ctrs[RKC_TAINT_HITS]++;
      effect = true;
    }
    if (slot > c->votes_seen[s]) {
      c->votes_seen[s] = slot;
      effect = true;
    }
    effect |= rk_route_one(c, round_no, row, (int32_t)s, slot, mvc, val,
                           carry);
  }
  // bound the carry exactly like _route_votes: genuinely unreachable
  // future votes must not accumulate without limit
  const size_t cap = (size_t)8 * c->S * c->R;
  if (carry.size() > cap)
    carry.erase(carry.begin(), carry.begin() + (carry.size() - cap));
  c->rows_seen |= 1ull << (row & 63);
  c->ctrs[round_no == 1 ? RKC_FRAMES_V1 : RKC_FRAMES_V2]++;
  if (!effect) c->ctrs[RKC_FRAMES_NOOP]++;
  fr_rec(c, FRE_FRAME_IN, msg_type, (uint16_t)row, rd_u32(ent),
         (int64_t)(rd_u64(ent + 4) >> 16));
  return effect ? RK_HANDLED : RK_NOOP;
}

// --- outbound framing -------------------------------------------------------

static void rk_msg_id(RkCtx* c, uint8_t* out) {
  // deterministic-unique 16 bytes: lowbias32 stream over (seed, me,
  // counter). Receivers treat message ids as opaque.
  const uint64_t ctr = ++c->msg_counter;
  uint32_t h = mix32(c->seed ^ c->id_salt ^ GOLD ^
                     (uint32_t)(c->me * 0x85EBCA6Bu));
  for (int w = 0; w < 4; w++) {
    h = mix32(h ^ (uint32_t)(ctr >> (16 * (w & 1))) ^ GOLD * (w + 1));
    std::memcpy(out + 4 * w, &h, 4);
  }
  out[6] = (out[6] & 0x0F) | 0x40;  // uuid4 version/variant cosmetics
  out[8] = (out[8] & 0x3F) | 0x80;
}

struct RkFrameWriter {
  uint8_t* out;
  int64_t cap;
  int64_t pos;
  int32_t frames;
  int32_t overflow;
};

// One broadcast frame: [u32 record_len][frame bytes] with the frame in the
// exact v3 wire layout. entry_sz is 13 (votes) or 14 (decisions).
static void rk_emit_frame(RkCtx* c, RkFrameWriter* w, uint8_t msg_type,
                          double now, const int32_t* idx, int32_t count,
                          int32_t entry_sz, const int8_t* vals,
                          int32_t phase_mode) {
  const int64_t frame_len = 47 + 4 + (int64_t)count * entry_sz;
  if (w->pos + 4 + frame_len > w->cap) {
    w->overflow = 1;
    return;
  }
  uint8_t* p = w->out + w->pos;
  const uint32_t rec = (uint32_t)frame_len;
  std::memcpy(p, &rec, 4);
  p += 4;
  p[0] = 3;  // version
  p[1] = msg_type;
  p[2] = 0;  // flags: uncompressed broadcast
  rk_msg_id(c, p + 3);
  std::memcpy(p + 19, c->uuids.data() + (size_t)c->me * 16, 16);
  std::memcpy(p + 35, &now, 8);
  const uint32_t body_len = 4 + (uint32_t)count * entry_sz;
  std::memcpy(p + 43, &body_len, 4);
  uint8_t* body = p + 47;
  const uint32_t cnt = (uint32_t)count;
  std::memcpy(body, &cnt, 4);
  uint8_t* e = body + 4;
  for (int32_t k = 0; k < count; k++) {
    const int32_t s = idx[k];
    const uint32_t su = (uint32_t)s;
    // phase_mode 0: (slot<<16) | phase[s]  (vote frames)
    //            1: (slot<<16)             (decision frames)
    uint64_t ph = ((uint64_t)(int64_t)c->slot[s]) << 16;
    if (phase_mode == 0) ph |= (uint64_t)(uint32_t)c->phase[s] & 0xFFFF;
    std::memcpy(e, &su, 4);
    std::memcpy(e + 4, &ph, 8);
    e[12] = (uint8_t)vals[s];
    if (entry_sz == 14) e[13] = 0;  // has_bid=0 (steady-state decisions)
    e += entry_sz;
  }
  w->pos += 4 + frame_len;
  w->frames++;
  fr_rec(c, FRE_FRAME_OUT, msg_type, 0xFFFF, (uint32_t)idx[0],
         (int64_t)c->slot[idx[0]]);
}

// --- the chained tick -------------------------------------------------------

static void rk_route_carry(RkCtx* c, int32_t round_no) {
  std::vector<RkCarry>& carry = (round_no == 1) ? c->carry1 : c->carry2;
  if (carry.empty()) return;
  size_t w = 0;
  for (size_t i = 0; i < carry.size(); i++) {
    const RkCarry& e = carry[i];
    if (e.slot < c->applied[e.shard]) continue;  // stale: decided+applied
    if (c->in_flight[e.shard] && e.slot == (int64_t)c->slot[e.shard] &&
        e.mvc == c->phase[e.shard]) {
      int8_t* led = (round_no == 1 ? c->led1 : c->led2);
      int8_t& cell = led[(int64_t)e.row * c->S + e.shard];
      if (cell == ABS) {
        cell = e.val;
        c->ctrs[RKC_SCATTER]++;
        fr_rec(c, round_no == 1 ? FRE_ROUTE1 : FRE_ROUTE2, (uint8_t)e.val,
               (uint16_t)e.row, (uint32_t)e.shard, e.slot);
      }
    } else {
      carry[w++] = e;  // keep for a later tick
    }
  }
  carry.resize(w);
}

// res: [out_bytes, done_any, restep, frames, overflow]
// open_mask/open_slots/open_init (nullable): shards opening a new decision
// slot this tick — armed in place (rk_start_slots) and announced with one
// VoteRound1 frame BEFORE the chained rounds, exactly like the Python
// path's start_slots + open broadcast.
void rk_tick(void* ctx, double now, uint8_t* out, int64_t out_cap,
             int32_t max_iters, const uint8_t* open_mask,
             const int32_t* open_slots, const int8_t* open_init,
             int64_t* res) {
  RkCtx* c = (RkCtx*)ctx;
  RkFrameWriter w{out, out_cap, 0, 0, 0};
  int32_t restep = 0;
  c->ctrs[RKC_TICKS]++;
  if (open_mask) {
    rk_start_slots(c->S, c->R, c->me, open_mask, open_slots, open_init,
                   c->slot, c->phase, c->stage, c->my_r1, c->my_r2, c->led1,
                   c->led2, c->decided, c->done, c->active);
    int32_t n_open = 0;
    int32_t* idx = c->idx_scratch.data();
    for (int32_t s = c->g_lo; s < c->g_hi; s++) {
      if (open_mask[s]) {
        idx[n_open++] = s;
        c->dwell_t0[s] = fr_now_ns();
        c->dwell_t0_slot[s] = (int64_t)open_slots[s];
        fr_rec(c, FRE_OPEN, (uint8_t)open_init[s], 0xFFFF, (uint32_t)s,
               (int64_t)open_slots[s]);
      }
    }
    if (n_open)
      rk_emit_frame(c, &w, MT_VOTE1, now, idx, n_open, 13, c->my_r1, 0);
    c->ctrs[RKC_OPENED] += (uint64_t)n_open;
  }
  for (int32_t it = 0; it < max_iters; it++) {
    c->ctrs[RKC_STAGES]++;
    rk_route_carry(c, 1);
    rk_route_carry(c, 2);
    rk_node_step_impl(c->S, c->R, c->me, c->quorum, c->f1, c->seed,
                      c->coin_threshold, c->g_lo, c->g_hi, c->slot,
                      c->phase, c->stage, c->my_r1, c->my_r2, c->led1,
                      c->led2, c->decided, c->done, c->active, c->dec_plane,
                      c->cast_r2.data(), c->r2_vals.data(),
                      c->advanced.data(), c->newly_step.data(),
                      &c->ctrs[RKC_COIN_V0]);
    // dec_plane is a SHARED [S] column: clear only this group's cells
    // (a full-plane memset would erase a sibling worker's adopted
    // decisions mid-tick)
    std::memset(c->dec_plane + c->g_lo, ABS, (size_t)(c->g_hi - c->g_lo));
    // outbox: per-iteration frames, masked by the engine's in-flight set
    // (engine._process_outbox parity)
    int32_t n_cast = 0, n_adv = 0, n_new = 0;
    int32_t* idx = c->idx_scratch.data();
    for (int32_t s = c->g_lo; s < c->g_hi; s++) {
      if (!c->in_flight[s]) continue;
      if (c->cast_r2[s]) {
        idx[n_cast++] = s;
        fr_rec(c, FRE_CAST_R2, (uint8_t)c->r2_vals[s], 0xFFFF, (uint32_t)s,
               (int64_t)c->slot[s]);
      }
    }
    if (n_cast) {
      rk_emit_frame(c, &w, MT_VOTE2, now, idx, n_cast, 13,
                    c->r2_vals.data(), 0);
      for (int32_t k = 0; k < n_cast; k++) c->last_progress[idx[k]] = now;
    }
    for (int32_t s = c->g_lo; s < c->g_hi; s++) {
      if (!c->in_flight[s]) continue;
      if (c->advanced[s] && !c->done[s]) {
        idx[n_adv++] = s;
        fr_rec(c, FRE_ADVANCE, (uint8_t)(c->phase[s] & 0xFF), 0xFFFF,
               (uint32_t)s, (int64_t)c->slot[s]);
      }
    }
    if (n_adv) {
      rk_emit_frame(c, &w, MT_VOTE1, now, idx, n_adv, 13, c->my_r1, 0);
      for (int32_t k = 0; k < n_adv; k++) c->last_progress[idx[k]] = now;
    }
    int32_t any_adv = 0;
    for (int32_t s = c->g_lo; s < c->g_hi; s++) {
      if (!c->in_flight[s]) continue;
      if (c->advanced[s]) {
        any_adv = 1;
        // close the phase that just completed (deciding advances mask
        // FRE_ADVANCE via done[] but still finish their final phase,
        // so dwell is observed on ALL advances); restamp for the next
        if (c->dwell_t0_slot[s] == (int64_t)c->slot[s]) {
          const uint64_t t = fr_now_ns();
          rk_dwell_obs(c, c->phase[s], t - c->dwell_t0[s]);
          c->dwell_t0[s] = t;
        }
      }
      if (c->newly_step[s]) {
        c->newly_acc[s] = 1;
        idx[n_new++] = s;
        // post-advance phase == phases-to-decide for this slot (the
        // decide step bumps phase): the termination-analysis curve
        const int32_t p = c->phase[s];
        c->ctrs[RKC_PHASE_SUM] += (uint64_t)p;
        c->phase_hist[p < RK_PHASE_HIST ? p : RK_PHASE_HIST - 1]++;
        fr_rec(c, FRE_STEP_DECIDE, (uint8_t)c->decided[s], 0xFFFF,
               (uint32_t)s, (int64_t)c->slot[s]);
      }
    }
    if (n_new && c->decision_broadcast)
      rk_emit_frame(c, &w, MT_DECISION, now, idx, n_new, 14, c->decided, 1);
    c->ctrs[RKC_DECIDED] += (uint64_t)n_new;
    restep = (n_cast || any_adv) ? 1 : 0;
    if (!restep) break;
  }
  c->ctrs[RKC_OUT_FRAMES] += (uint64_t)w.frames;
  int64_t done_any = 0;
  for (int32_t s = c->g_lo; s < c->g_hi; s++) {
    if (c->done[s] && c->in_flight[s]) {
      done_any = 1;
      break;
    }
  }
  res[0] = w.pos;
  res[1] = done_any;
  res[2] = restep;
  res[3] = w.frames;
  res[4] = w.overflow;
}

// Retransmit current votes for stalled in-flight shards (the native
// runtime's twin of engine._check_timeouts' vote half): frames a
// VoteRound1 for every stalled shard holding an R1 vote and a
// VoteRound2 for every stalled shard waiting in R2, then refreshes
// last_progress — all without the GIL. Propose/block retransmission
// stays an escalation (the payload bytes live on the control plane).
// res: [out_bytes, stalled, frames, overflow]
void rk_retransmit(void* ctx, double now, double timeout, uint8_t* out,
                   int64_t out_cap, int64_t* res) {
  RkCtx* c = (RkCtx*)ctx;
  RkFrameWriter w{out, out_cap, 0, 0, 0};
  int32_t* idx = c->idx_scratch.data();
  int32_t n_stall = 0, n_r1 = 0;
  for (int32_t s = c->g_lo; s < c->g_hi; s++) {
    if (c->in_flight[s] && now - c->last_progress[s] >= timeout) {
      n_stall++;
      if (c->my_r1[s] != ABS) idx[n_r1++] = s;
    }
  }
  if (n_stall == 0) {
    res[0] = res[1] = res[2] = res[3] = 0;
    return;
  }
  if (n_r1) rk_emit_frame(c, &w, MT_VOTE1, now, idx, n_r1, 13, c->my_r1, 0);
  int32_t n_r2 = 0;
  for (int32_t s = c->g_lo; s < c->g_hi; s++) {
    if (c->in_flight[s] && now - c->last_progress[s] >= timeout &&
        c->stage[s] == R2_WAIT && c->my_r2[s] != ABS)
      idx[n_r2++] = s;
  }
  if (n_r2) rk_emit_frame(c, &w, MT_VOTE2, now, idx, n_r2, 13, c->my_r2, 0);
  for (int32_t s = c->g_lo; s < c->g_hi; s++) {
    if (c->in_flight[s] && now - c->last_progress[s] >= timeout)
      c->last_progress[s] = now;
  }
  c->ctrs[RKC_OUT_FRAMES] += (uint64_t)w.frames;
  res[0] = w.pos;
  res[1] = n_stall;
  res[2] = w.frames;
  res[3] = w.overflow;
}

}  // extern "C"
