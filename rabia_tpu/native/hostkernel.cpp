// Native host-kernel step: the C twin of HostNodeKernel.node_step /
// start_slots (rabia_tpu/kernel/host_driver.py), which is itself the
// numpy twin of the jitted NodeKernel (kernel/phase_driver.py).
//
// Why: the engine's serial-latency floor is per-activation kernel cost.
// The numpy step is ~40 vectorized calls; at small shard counts (the
// reference's single-shard deployment shape, rabia-engine/src/engine.rs
// round loop) the ~2us-per-call dispatch overhead dominates, putting a
// ~76us floor under every activation. This C step is one call that walks
// each shard's ledger column once. Measured vs the numpy step: 4.7x at
// S=16 down to a steady ~1.2-1.4x at S=16384-65536 — the C path wins at
// every size, so the wrapper uses it unconditionally when the library
// builds. Both paths are bit-identical, gated by the differential fuzz
// in tests/test_native_hostkernel.py.
//
// Semantics owner: host_driver.py. Every transition here mirrors it
// element-for-element, including the portable lowbias32 common coin
// (phase_driver._coin_bits) and the exact vote-code tallies of
// rabia-engine/src/engine.rs:424-706 (vote rules / quorum / coin /
// decision), vectorized over shards.
//
// Layout contract (replica-major, matching HostNodeState): led1/led2 are
// i8[R*S] with sender r's votes at led[r*S + s]. All arrays are dense,
// C-contiguous, caller-owned. node_step mutates state in place (the
// Python wrapper passes fresh copies, preserving the functional step
// contract) and writes the outbox extras that do not alias new state.

#include <cstdint>
#include <cstring>

extern "C" {

// vote codes (core/types.py) and stages (kernel/phase_driver.py)
enum : int8_t { V0 = 0, V1 = 1, VQ = 2, ABS = 3 };
enum : int8_t { R1_WAIT = 0, R2_WAIT = 1 };

static inline uint32_t mix32(uint32_t h) {
  // lowbias32 avalanche — must match phase_driver._mix32 bit-for-bit
  h ^= h >> 16;
  h *= 0x21F0AAADu;
  h ^= h >> 15;
  h *= 0x735A2D97u;
  h ^= h >> 15;
  return h;
}

static const uint32_t GOLD = 0x9E3779B9u;

static inline int8_t coin_bit(uint32_t seed, uint32_t shard, uint32_t slot,
                              uint32_t phase, uint32_t threshold) {
  uint32_t h = mix32(seed ^ GOLD);
  h = mix32(h ^ (shard + GOLD));
  h = mix32(h ^ (slot + GOLD));
  h = mix32(h ^ (phase + GOLD));
  return h < threshold ? V1 : V0;
}

// One node_step over S shards. State arrays are mutated in place; the
// outbox fields that alias new state (new_r1=my_r1, new_phase=phase,
// decided_vals=decided) are read by the caller from the state arrays.
void rk_node_step(
    int32_t S, int32_t R, int32_t me, int32_t quorum, int32_t f1,
    uint32_t seed, uint32_t coin_threshold,
    const int32_t* slot,       // [S]
    int32_t* phase,            // [S] in/out
    int8_t* stage,             // [S] in/out
    int8_t* my_r1,             // [S] in/out
    int8_t* my_r2,             // [S] in/out
    int8_t* led1,              // [R*S] in/out
    int8_t* led2,              // [R*S] in/out
    int8_t* decided,           // [S] in/out
    uint8_t* done,             // [S] in/out
    const uint8_t* active,     // [S]
    const int8_t* decision_in, // [S] or nullptr
    uint8_t* cast_r2,          // [S] out
    int8_t* r2_vals,           // [S] out
    uint8_t* advanced,         // [S] out
    uint8_t* newly_decided     // [S] out
) {
  for (int32_t s = 0; s < S; s++) {
    const int8_t st0 = stage[s];
    int8_t m2 = my_r2[s];
    uint8_t cast = 0, adv = 0, newdec = 0;
    const bool enabled = active[s] && !done[s];

    if (enabled && st0 == R1_WAIT) {
      // round-1 tally down this shard's ledger column
      int32_t c0 = 0, c1 = 0, cq = 0;
      for (int32_t r = 0; r < R; r++) {
        const int8_t v = led1[(int64_t)r * S + s];
        c0 += (v == V0);
        c1 += (v == V1);
        cq += (v == VQ);
      }
      if (c0 + c1 + cq >= quorum) {
        cast = 1;
        m2 = (c1 >= quorum) ? V1 : ((c0 >= quorum) ? V0 : VQ);
        my_r2[s] = m2;
        stage[s] = R2_WAIT;
        led2[(int64_t)me * S + s] = m2;
      }
    } else if (enabled && st0 == R2_WAIT) {
      int32_t d0 = 0, d1 = 0, dq = 0;
      for (int32_t r = 0; r < R; r++) {
        const int8_t v = led2[(int64_t)r * S + s];
        d0 += (v == V0);
        d1 += (v == V1);
        dq += (v == VQ);
      }
      if (d0 + d1 + dq >= quorum) {
        adv = 1;
        const bool dec1 = d1 >= f1, dec0 = d0 >= f1;
        int8_t next_v;
        if (dec1) next_v = V1;
        else if (dec0) next_v = V0;
        else if (d1 > 0) next_v = V1;
        else if (d0 > 0) next_v = V0;
        else
          next_v = coin_bit(seed, (uint32_t)s, (uint32_t)slot[s],
                            (uint32_t)phase[s], coin_threshold);
        if (dec1 || dec0) {
          newdec = 1;
          decided[s] = dec1 ? V1 : V0;
        }
        // advance to the next weak-MVC phase
        phase[s] += 1;
        my_r1[s] = next_v;
        stage[s] = R1_WAIT;
        my_r2[s] = ABS;
        for (int32_t r = 0; r < R; r++) {
          led1[(int64_t)r * S + s] = ABS;
          led2[(int64_t)r * S + s] = ABS;
        }
        led1[(int64_t)me * S + s] = next_v;
      }
    }

    // adopted decision (Decision frames routed by the engine): only when
    // not decided by this very step
    if (enabled && !newdec && decision_in && decision_in[s] != ABS) {
      decided[s] = decision_in[s];
      done[s] = 1;
    } else if (newdec) {
      done[s] = 1;
    }

    cast_r2[s] = cast;
    // pre-advance-clear value: an advancing shard reports the R2 vote it
    // had cast in the phase it is leaving (numpy copies my_r2 post-cast,
    // pre-clear)
    r2_vals[s] = m2;
    advanced[s] = adv;
    newly_decided[s] = newdec;
  }
}

// start_slots: (re)arm masked shards for a new decision slot.
void rk_start_slots(
    int32_t S, int32_t R, int32_t me,
    const uint8_t* mask,        // [S]
    const int32_t* slot_index,  // [S]
    const int8_t* initial,      // [S]
    int32_t* slot, int32_t* phase, int8_t* stage, int8_t* my_r1,
    int8_t* my_r2, int8_t* led1, int8_t* led2, int8_t* decided,
    uint8_t* done, uint8_t* active) {
  for (int32_t s = 0; s < S; s++) {
    if (!mask[s]) continue;
    slot[s] = slot_index[s];
    phase[s] = 0;
    stage[s] = R1_WAIT;
    my_r1[s] = initial[s];
    my_r2[s] = ABS;
    decided[s] = ABS;
    done[s] = 0;
    active[s] = 1;
    for (int32_t r = 0; r < R; r++) {
      led1[(int64_t)r * S + s] = ABS;
      led2[(int64_t)r * S + s] = ABS;
    }
    led1[(int64_t)me * S + s] = initial[s];
  }
}

// Columnar open-candidate scan (engine _open_slots prologue): one pass
// instead of ~9 numpy dispatches per tick. Fills head[s] =
// max(next_slot, applied) and cand[s]; returns the candidate count so an
// idle tick exits on a single int.
// Device-KV window pack gather (the GRID fast path: full-width sorted
// blocks, op i covers wave i/n, shard i%n). One pass copies each op's
// key/value bytes into the zeroed padded planes — replacing numpy's
// materialize-gather + where-mask + reshape-scatter chain (~4 full
// passes over the op bytes) with a single read+write. Validation
// stays in Python (the numpy path remains the semantics owner and
// fallback); this function only trusts its own bounds check and
// returns nonzero on any out-of-range op so the caller can fall back.
int32_t rk_pack_gather(
    const uint8_t* dbuf, int64_t dbuf_len,
    const int64_t* off, const int64_t* klen, const int64_t* vlen,
    int64_t n_ops, int64_t n, int64_t S, int64_t hdr,
    int64_t ku, int64_t vu,
    uint8_t* kwin, uint8_t* vwin) {
  for (int64_t i = 0; i < n_ops; i++) {
    const int64_t kl = klen[i];
    const int64_t vl = vlen[i];
    const int64_t o = off[i] + hdr;
    if (kl < 0 || vl < 0 || kl > ku || vl > vu || o < 0 ||
        o + kl + vl > dbuf_len) {
      return 1;  // out of envelope/bounds: caller uses the numpy path
    }
    const int64_t row = (i / n) * S + (i % n);
    std::memcpy(kwin + row * ku, dbuf + o, (size_t)kl);
    std::memcpy(vwin + row * vu, dbuf + o + kl, (size_t)vl);
  }
  return 0;
}

int32_t rk_open_scan(
    int32_t S,
    const int64_t* next_slot, const int64_t* applied,
    const uint8_t* in_flight, const int64_t* queue_len,
    const uint8_t* prop_flag, const uint8_t* dec_flag,
    const int64_t* votes_seen, const int64_t* tainted,
    int64_t* head, uint8_t* cand) {
  int32_t n = 0;
  for (int32_t s = 0; s < S; s++) {
    const int64_t h =
        next_slot[s] > applied[s] ? next_slot[s] : applied[s];
    head[s] = h;
    const uint8_t c =
        !in_flight[s] &&
        (queue_len[s] > 0 || prop_flag[s] || dec_flag[s] ||
         votes_seen[s] >= h || tainted[s] > 0);
    cand[s] = c;
    n += c;
  }
  return n;
}

}  // extern "C"
