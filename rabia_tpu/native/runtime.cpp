// Native engine runtime: a GIL-free io/tick thread running the commit
// path end-to-end — transport readable events feed rk_ingest directly,
// chained rk_tick stages decide, decided waves flow into sk_apply_wave,
// and staged vote/decision frames go out via rt_broadcast_frames — all
// without acquiring the GIL or waking the Python asyncio loop.
//
// Python is demoted to control plane (engine/runtime_bridge.py):
// membership, sync/recovery, config, gateway session logic and obs
// scrapes, talking to this thread through two bounded byte rings (the
// command ring Python->C, the event mailbox C->Python) plus an eventfd
// the Python loop selects on. RABIA_PY_RUNTIME=1 forces today's asyncio
// orchestration, which stays the semantics owner behind the
// run_schedule_on_runtime_paths conformance gate
// (rabia_tpu/testing/conformance.py).
//
// Ownership contract (the whole point of the design): while the runtime
// thread is RUNNING, it is the single writer of the engine's consensus
// columns (next_slot, applied_upto, in_flight, votes_seen, taint
// traffic, last_progress, opened_at, the decided-value rings) and of
// the kernel state arrays behind the rk tick context. Python reads
// them advisorily (aligned 8-byte loads; metrics-grade) and mutates
// them ONLY while the runtime is paused (rtm_pause -> state PAUSED).
// Everything Python must act on — decisions for listeners/futures,
// escalated frames, stalls — arrives through the event mailbox, in
// per-shard slot order.
//
// This file links against nothing: every foreign entry point (transport,
// hostkernel, statekernel) arrives as a raw function pointer registered
// at rtm_create, so the four native libraries stay independently built
// and digest-keyed (native/build.py).

#include <errno.h>
#include <string.h>
#include <sys/eventfd.h>
#include <time.h>
#include <unistd.h>
#include <zlib.h>

#include <atomic>
#include <cstdint>
#include <map>
#include <memory>
#include <thread>
#include <vector>

#include "annotations.h"

extern "C" {

// --- foreign entry points (function-pointer table indices) ------------------

typedef int64_t (*fn_recv_borrow_t)(void*, uint8_t*, const uint8_t**,
                                    uint32_t*, int);
typedef void (*fn_recv_release_t)(void*, int64_t);
typedef int (*fn_bcast_frames_t)(void*, const uint8_t*, int64_t);
typedef int (*fn_send_t)(void*, const uint8_t*, const uint8_t*, uint32_t);
typedef int32_t (*fn_rk_ingest_t)(void*, const uint8_t*, int64_t, int32_t,
                                  double);
typedef void (*fn_rk_tick_t)(void*, double, uint8_t*, int64_t, int32_t,
                             const uint8_t*, const int32_t*, const int8_t*,
                             int64_t*);
typedef void (*fn_rk_retransmit_t)(void*, double, double, uint8_t*, int64_t,
                                   int64_t*);
typedef int64_t (*fn_rk_drain_stale_t)(void*, int64_t*, int64_t*, int64_t*,
                                       int64_t);
typedef int64_t (*fn_sk_apply_wave_t)(void*, const uint8_t*, const int64_t*,
                                      const int64_t*, const int64_t*,
                                      const int64_t*, int64_t, double,
                                      int32_t);
typedef void* (*fn_sk_ptr_t)(void*);
typedef void (*fn_sk_plane_lk_t)(void*);
// durability plane (walkernel.cpp): stage a record / advance the vote
// barrier / read the durability watermark — all lock-cheap, never disk
typedef int64_t (*fn_wal_append_t)(void*, const uint8_t*, int64_t);
typedef int64_t (*fn_wal_barrier_t)(void*, int64_t, int64_t);
typedef uint64_t (*fn_wal_durable_t)(void*);
// thread-per-shard-group additions: per-group transport inbox + per-lane
// statekernel apply (worker g stages results into its private lane)
typedef int64_t (*fn_recv_borrow_grp_t)(void*, int32_t, uint8_t*,
                                        const uint8_t**, uint32_t*, int);
typedef int64_t (*fn_sk_apply_lane_t)(void*, int32_t, const uint8_t*,
                                      const int64_t*, const int64_t*,
                                      const int64_t*, const int64_t*,
                                      int64_t, double, int32_t);
typedef void* (*fn_sk_lane_ptr_t)(void*, int32_t);

enum : int32_t {
  FN_RECV_BORROW = 0,
  FN_RECV_RELEASE,
  FN_BCAST_FRAMES,
  FN_SEND,
  FN_RK_INGEST,
  FN_RK_TICK,
  FN_RK_RETRANSMIT,
  FN_RK_DRAIN_STALE,
  FN_SK_APPLY_WAVE,
  FN_SK_OUT_BUF,
  FN_SK_OUT_OFFS,
  FN_SK_PLANE_LOCK,
  FN_SK_PLANE_UNLOCK,
  FN_WAL_APPEND,
  FN_WAL_BARRIER,
  FN_WAL_DURABLE,
  // appended (workers > 1 only; null with a single worker)
  FN_RECV_BORROW_GROUP,
  FN_SK_APPLY_WAVE_LANE,
  FN_SK_OUT_BUF_LANE,
  FN_SK_OUT_OFFS_LANE,
  FN_COUNT
};

// --- observability counter block (versioned, append-only like RKC_*) --------

enum : int32_t {
  RTM_LOOPS = 0,        // runtime loop iterations
  RTM_WAKES_FRAME,      // blocking waits that returned a frame
  RTM_WAKES_IDLE,       // blocking waits that timed out / were kicked
  RTM_FRAMES_NATIVE,    // frames consumed by rk_ingest (handled + noop)
  RTM_FRAMES_BLOCK,     // ProposeBlock frames bound natively
  RTM_FRAMES_ESCALATED, // frames handed to the Python control plane
  RTM_FRAMES_DROPPED,   // frames dropped (spoof/skew/malformed)
  RTM_CMDS,             // command records consumed
  RTM_OPENS_SCALAR,     // scalar slots armed
  RTM_OPENS_BLOCK,      // block-bound slots armed
  RTM_TICKS,            // rk_tick activations
  RTM_DECIDED_SCALAR,   // scalar decides handed to Python
  RTM_WAVES_NATIVE,     // decided block waves applied natively (no GIL)
  RTM_WAVES_PY,         // decided waves that needed a Python handoff
  RTM_SLOTS_APPLIED,    // slots applied through sk_apply_wave
  RTM_RESULT_BYTES,     // staged result bytes copied into the mailbox
  RTM_EV_RECORDS,       // event records appended
  RTM_EV_STALLS,        // times the event mailbox was full (backpressure)
  RTM_RETRANSMITS,      // stalled-shard vote retransmission rounds
  RTM_STALE_REPAIRS,    // native stale-vote repair Decisions sent
  RTM_PAUSES,           // pause/resume round trips
  RTM_GIL_HANDOFFS,     // commit-path transitions that required Python
                        // (scalar decides + py waves): the acceptance
                        // counter — zero growth per steady-state native
                        // wave
  RTM_EV_DROPPED,       // event records larger than the whole mailbox
                        // (dropped instead of livelocking the thread)
  RTM_COUNT
};
static const int32_t RTM_COUNTERS_VERSION = 2;

// --- runtime stage profiler (versioned, append-only like RTM_*) --------------
//
// Cumulative CLOCK_MONOTONIC nanoseconds per loop stage. Every loop
// iteration is fully attributed: each instrumented section adds its
// duration to one stage AND to a per-iteration accumulator, and the
// iteration remainder lands in RTS_OTHER — so the stage sum equals the
// thread's wall time by construction ("where did the wall move" is a
// scrape, not a guess). Exported as rabia_runtime_stage_seconds{stage=…}
// via the engine registry; rendered by `python -m rabia_tpu profile`.

enum : int32_t {
  RTS_RECV_WAIT = 0,   // blocking inbox wait that returned a frame
  RTS_INGEST,          // frame pump: rk_ingest / native bind / escalate
  RTS_TICK,            // open collection + chained rk_tick stages
  RTS_APPLY,           // sk_apply_wave (decided waves applying in C)
  RTS_RESULT_STAGING,  // result copy-out + event record build/push
  RTS_BROADCAST,       // rt_broadcast_frames staging of tick out-frames
  RTS_CMD,             // command-ring drain (control-plane commands)
  RTS_TIMERS,          // retransmit / stale repair / stall escalation
  RTS_IDLE,            // blocking inbox wait that timed out; pause park
  RTS_OTHER,           // loop remainder (bookkeeping between sections)
  RTS_COUNT
};
static const int32_t RTS_VERSION = 1;

// --- SLO latency histogram block (versioned like RKC_*/SKC_*) ----------------
//
// HDR-style log-bucketed fixed-size histograms: per stage, RTH_BUCKETS
// u64 bucket counts + [RTH_BUCKETS] total count + [RTH_BUCKETS+1] sum of
// observed nanoseconds. Bucketing: 2^RTH_SUB_BITS sub-buckets per
// power-of-two octave starting at 2^RTH_MIN_EXP ns — bucket upper bound
// for octave o, sub s is 2^(RTH_MIN_EXP+o) * (2^SUB + s + 1) / 2^SUB
// (worst-case relative error 1/2^SUB per bucket). Values below the
// floor clamp into bucket 0, values past the top into the last bucket.
// observe() is branch-light bit math + three u64 increments: zero
// allocation on the hot path. The Python twin of the bucket bounds is
// rabia_tpu.obs.registry.SLO_BUCKETS; both paths export the merged
// result as rabia_slo_seconds{stage=…}.

enum : int32_t {
  RTH_DECIDE_APPLY = 0,  // kernel decide -> native wave apply complete
  RTH_BROADCAST,         // tick vote/decision frames staged to the wire
  RTH_STAGE_COUNT
};
static const int32_t RTH_VERSION = 1;
static const int32_t RTH_SUB_BITS = 2;  // 4 sub-buckets per octave
static const int32_t RTH_MIN_EXP = 10;  // floor 1.024us
static const int32_t RTH_OCTAVES = 25;  // top bound 2^35 ns ~ 34.4s
static const int32_t RTH_BUCKETS = RTH_OCTAVES << RTH_SUB_BITS;
static const int32_t RTH_STRIDE = RTH_BUCKETS + 2;  // + count + sum_ns

// --- flight recorder (FrEvent ABI of hostkernel.cpp / obs/flight.py) --------

enum : uint8_t {
  FRE_RT_WAKE = 19,     // runtime thread wakeup (arg: 1 frames, 2 idle)
  FRE_RT_HANDOFF = 20,  // event record handed to Python (arg = ev type)
};

struct FrEvent {
  uint64_t t_ns;
  uint64_t slot;
  uint64_t batch;
  uint32_t shard;
  uint16_t peer;
  uint8_t kind;
  uint8_t arg;
};
static_assert(sizeof(FrEvent) == 32, "FrEvent ABI is 32 bytes");
static const int32_t RTM_FLIGHT_VERSION = 1;
static const uint32_t RTM_FLIGHT_CAP = 2048;  // power of two

// --- mailbox record types ---------------------------------------------------

// events (C -> Python); each record is u32 len | u8 type | payload
enum : uint8_t {
  EV_FRAME = 1,    // u16 row | frame bytes (escalated wire frame)
  EV_DECIDE = 2,   // u32 shard | u64 slot | u8 value | f64 opened_at
  EV_WAVE = 3,     // u64 token | u8 applied | u8 has_results | u32 count |
                   // count * (u32 shard | u64 slot | u32 bidx | u8 value)
                   // | if has_results: count * (u32 rlen | bytes)
  EV_REJECT = 4,   // u64 token | u32 bidx | u32 shard | u64 slot | u8 why
  EV_STALL = 5,    // u8 kind | u32 shard | u64 slot_or_token
                   // kind 0: scalar propose retransmit wanted
                   // kind 1: block announce retransmit wanted (token)
                   // kind 2: peer votes waiting, no binding (V0 candidate)
  EV_LEDGER = 6,   // 16B block id | u32 count | count * (u32 shard |
                   // u64 slot): natively applied PEER-block wave entries
                   // (token 0 — no Python owner) whose K_WAVE records
                   // were staged with zero batch ids; the control plane
                   // derives bid = block_batch_id(block_id, shard) and
                   // backfills K_LEDGER so follower recovery repopulates
                   // the applied_ids dedup ledger (ROADMAP 3c)
};

// commands (Python -> C); u32 len | u8 type | payload
enum : uint8_t {
  CMD_OPEN_SCALAR = 1,  // u32 shard | u64 slot | u8 init | u32 flen | frame
  CMD_OPEN_WAVE = 2,    // u64 token | u8 want | u32 k | u32 announce_len |
                        // u32 blob_len | u32 total_ops |
                        // k * (u32 shard | u64 slot | u32 bidx | u32 nops) |
                        // total_ops * u32 op_len | announce | blob
  CMD_ADVANCE = 3,      // u32 count | count * (u32 shard | u64 new_applied)
  CMD_DECIDE = 4,       // u32 shard | u64 slot | u8 value (adopt at head)
  CMD_STOP = 5,
};

enum : int32_t {
  RTM_RUNNING = 0,
  RTM_PAUSE_REQ = 1,
  RTM_PAUSED = 2,
  RTM_STOPPED = 3,
};

// --- wire constants (core/serialization.py v3) ------------------------------

enum : uint8_t {
  MT_VOTE1 = 2,
  MT_VOTE2 = 3,
  MT_DECISION = 4,
  MT_PROPOSE_BLOCK = 10,
  FLAG_COMPRESSED = 0x01,
  FLAG_RECIPIENT = 0x02,
};

enum : int32_t { RK_HANDLED = 1, RK_NOOP = 2, RK_PY = 0, RK_DROP = -1 };
enum : int8_t { V0c = 0, V1c = 1 };

// --- small helpers ----------------------------------------------------------

// The io/tick thread ROLE (annotations.h ThreadRole): every function
// below marked RABIA_REQUIRES(rtm_io_role) touches state the runtime's
// single-writer-while-RUNNING contract reserves for the io thread —
// calling one from a control-plane entry point is a compile error under
// clang -Werror=thread-safety. The runtime handshake that actually
// transfers ownership (rtm_pause -> PAUSED -> mutate -> rtm_resume) is
// stress-checked under TSan in native/stress/stress_runtime.cpp.
static rabia::ThreadRole rtm_io_role{"runtime.io"};

static inline uint64_t mono_ns() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (uint64_t)ts.tv_sec * 1000000000ull + (uint64_t)ts.tv_nsec;
}

static inline double wall_s() {
  timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  return (double)ts.tv_sec + (double)ts.tv_nsec * 1e-9;
}

static inline uint32_t rd_u32(const uint8_t* p) {
  uint32_t v;
  memcpy(&v, p, 4);
  return v;
}
static inline uint64_t rd_u64(const uint8_t* p) {
  uint64_t v;
  memcpy(&v, p, 8);
  return v;
}
static inline double rd_f64(const uint8_t* p) {
  double v;
  memcpy(&v, p, 8);
  return v;
}
static inline void wr_u32(std::vector<uint8_t>& b, uint32_t v) {
  size_t w = b.size();
  b.resize(w + 4);
  memcpy(b.data() + w, &v, 4);
}
static inline void wr_u64(std::vector<uint8_t>& b, uint64_t v) {
  size_t w = b.size();
  b.resize(w + 8);
  memcpy(b.data() + w, &v, 8);
}
static inline void wr_f64(std::vector<uint8_t>& b, double v) {
  size_t w = b.size();
  b.resize(w + 8);
  memcpy(b.data() + w, &v, 8);
}

// --- the SPSC byte rings ----------------------------------------------------

// Records are u32 len | payload at (pos % cap); a record never wraps —
// when the tail of the buffer is too short, a u32 0xFFFFFFFF pad marker
// (when >= 4 bytes remain) skips to offset 0. head/tail are absolute
// monotonic byte counters; both sides run the producer/consumer halves
// in C (rtm_cmd_push / rtm_ev_drain are called from the Python thread),
// so the acquire/release pairing is real on every architecture.
struct ByteRing {
  std::vector<uint8_t> buf;
  std::atomic<uint64_t> head{0};  // producer cursor (bytes ever written)
  std::atomic<uint64_t> tail{0};  // consumer cursor (bytes ever consumed)

  int64_t cap() const { return (int64_t)buf.size(); }
  int64_t free_space() const {
    return cap() - (int64_t)(head.load(std::memory_order_relaxed) -
                             tail.load(std::memory_order_acquire));
  }
  // space a record of `len` payload bytes needs, worst case (pad + hdr)
  static int64_t need(int64_t len) { return len + 8; }

  bool push(const uint8_t* a, int64_t alen, const uint8_t* b, int64_t blen) {
    const int64_t len = alen + blen;
    if (free_space() < need(len)) return false;
    uint64_t h = head.load(std::memory_order_relaxed);
    int64_t at = (int64_t)(h % (uint64_t)cap());
    if (at + 4 + len > cap()) {
      // pad to the wrap point, restart at 0 (space already checked via
      // the conservative need(); re-check against the real layout)
      int64_t pad = cap() - at;
      if ((int64_t)(h + pad + 4 + len -
                    tail.load(std::memory_order_acquire)) > cap())
        return false;
      if (pad >= 4) {
        uint32_t marker = 0xFFFFFFFFu;
        memcpy(buf.data() + at, &marker, 4);
      }
      h += pad;
      at = 0;
    }
    uint32_t l32 = (uint32_t)len;
    memcpy(buf.data() + at, &l32, 4);
    memcpy(buf.data() + at + 4, a, (size_t)alen);
    if (blen) memcpy(buf.data() + at + 4 + alen, b, (size_t)blen);
    head.store(h + 4 + len, std::memory_order_release);
    return true;
  }

  // Pop records into `out` back to back as u32 len | payload; returns
  // bytes written. Stops before a record that would not fit.
  int64_t drain(uint8_t* out, int64_t out_cap) {
    uint64_t t = tail.load(std::memory_order_relaxed);
    const uint64_t h = head.load(std::memory_order_acquire);
    int64_t w = 0;
    while (t < h) {
      int64_t at = (int64_t)(t % (uint64_t)cap());
      if (at + 4 > cap()) {
        t += cap() - at;  // unmarked short tail: skip to 0
        continue;
      }
      uint32_t len = rd_u32(buf.data() + at);
      if (len == 0xFFFFFFFFu) {
        t += cap() - at;  // pad marker
        continue;
      }
      if (w + 4 + (int64_t)len > out_cap) break;
      memcpy(out + w, buf.data() + at, 4 + (size_t)len);
      w += 4 + len;
      t += 4 + len;
    }
    tail.store(t, std::memory_order_release);
    return w;
  }
};

// --- C-side block registry --------------------------------------------------

struct CBlk {
  std::vector<uint8_t> data;         // op blob (empty when !has_data)
  std::vector<int64_t> cmd_offsets;  // total+1 byte offsets into data
  std::vector<int64_t> starts;       // k+1 command-index prefix
  std::vector<int64_t> shards;       // k actual shard ids
  std::vector<int64_t> slots;        // k bound slots
  std::vector<uint32_t> bidx;        // k Python-side block indices
  uint64_t token = 0;                // 0 = peer block (no Python owner)
  int want = 0;                      // stage result frames on apply
  int has_data = 0;
  int64_t remaining = 0;             // live bindings (pending + open)
  double bound_at = 0.0;
  // 16B wire block id of a natively parsed peer block (has_block_id=1):
  // lets the control plane backfill K_LEDGER batch ids for C-staged
  // waves on NON-proposer replicas (EV_LEDGER) — batch ids derive
  // deterministically from (block_id, shard), core/blocks.py
  uint8_t block_id[16] = {0};
  int has_block_id = 0;
};

// One shard-group worker: a dedicated io/tick thread owning the commit
// path for shards [lo, hi) end-to-end — its own rk tick context, frame
// inbox (per-group transport routing), command/event SPSC rings (the
// Python-facing rtm_cmd_push/rtm_ev_drain entry points route/merge so
// the control plane still sees ONE ring pair), result-staging lane into
// the shared statekernel plane, WAL staging lane into the shared
// group-commit flush, and its own observability blocks (counters, stage
// profiler, SLO histograms, flight ring) summed at scrape. With one
// worker this is exactly the round-8 runtime, byte for byte.
struct RtmWorker {
  int32_t gid = 0;
  int64_t lo = 0, hi = 0;  // owned shard range
  void* rk = nullptr;      // this worker's rk tick context

  std::map<int64_t, CBlk> blocks;
  int64_t next_blk = 1;

  // open scratch (S-wide planes handed to rk_tick; only [lo,hi) used)
  std::vector<uint8_t> open_mask;
  std::vector<int32_t> open_slots;
  std::vector<int8_t> open_init;

  // outbound tick buffer
  std::vector<uint8_t> out;

  // mailboxes (SPSC: Python thread <-> this worker)
  ByteRing cmd, ev;
  std::vector<uint8_t> cmd_scratch;

  // stale-vote repair
  std::vector<int64_t> st_rows, st_shards, st_slots;
  std::vector<double> last_repair;  // per row
  uint64_t msg_counter = 0;

  std::atomic<int32_t> state{RTM_RUNNING};
  std::thread th;
  // start at 1: anything the control plane pre-ingested into the rk
  // ledger before rtm_start (frames the detached Python reader had
  // already pulled) gets its tick on the first iteration
  int restep = 1;
  double last_timers = 0.0;

  uint64_t ctrs[RTM_COUNT];
  uint64_t stg[RTS_COUNT];                   // stage profiler (ns)
  uint64_t hist[RTH_STAGE_COUNT * RTH_STRIDE];  // SLO histogram block
  std::vector<FrEvent> fr;
  // relaxed atomic: single-writer (this worker) but read by the Python
  // scrape path via rtm_flight_head while the loop runs (TSan stress
  // finding, round 13)
  std::atomic<uint64_t> fr_head{0};
};

struct RtmCtx {
  // geometry
  int32_t S, n, R, me, dec_ring;
  int32_t native_apply;  // sk plane present: decided waves apply in C
  int32_t W = 1;         // worker (= shard group) count
  int64_t chunk = 0;     // contiguous group width: group = s / chunk
  int64_t max_cmds, max_cmd_size;
  double max_future_skew, max_age, phase_timeout, grace;

  // handles + foreign entry points
  void* tr;
  void* sk;
  void* wal = nullptr;  // durability plane (walkernel.cpp), or null
  void* fns[FN_COUNT];

  // engine columns (borrowed; single-writer of shard s = the worker
  // owning s's group, while RUNNING)
  int64_t* next_slot;
  int64_t* applied;
  uint8_t* in_flight;
  int64_t* votes_seen;
  int64_t* tainted;
  double* last_progress;
  double* opened_at;
  int64_t* ring_slot;  // [S, dec_ring]
  int8_t* ring_val;
  // kernel views (borrowed)
  int32_t* kslot;
  int8_t* kdecided;
  uint8_t* kdone;
  uint8_t* knewly;

  std::vector<uint8_t> uuids;  // R * 16

  // per-shard runtime state (disjoint per-worker access by shard range)
  std::vector<int64_t> blk_pend_ref, blk_pend_pos, blk_pend_slot;
  std::vector<int64_t> blk_cur_ref, blk_cur_pos;
  std::vector<int64_t> sp_slot;          // pending scalar open slot (-1)
  std::vector<int8_t> sp_init;
  std::vector<std::vector<uint8_t>> sp_frame;  // propose frame to emit
  std::vector<double> stall_ev_at;       // EV_STALL rate limit per shard
  std::vector<double> votes_wait_at;     // kind-2 escalation rate limit
  // vote-barrier write-ahead (durability plane): a shard whose next
  // open outran the durable barrier parks here until the group-commit
  // fsync covers the barrier record's LSN
  std::vector<int64_t> bar_wait;

  int event_fd = -1;
  std::atomic<int32_t> stop_req{0};
  std::atomic<int32_t> pause_req{0};  // pause = a barrier across workers

  std::vector<std::unique_ptr<RtmWorker>> workers;

  int32_t group_of(int64_t s) const {
    if (W <= 1 || chunk <= 0) return 0;
    int64_t g = s / chunk;
    return (int32_t)(g >= W ? W - 1 : g);
  }
};

static inline void rth_observe(RtmWorker* w, int32_t stage, uint64_t ns)
    RABIA_REQUIRES(rtm_io_role) {
  uint64_t* h = w->hist + (size_t)stage * RTH_STRIDE;
  int32_t idx = 0;
  if (ns >= (1ull << RTH_MIN_EXP)) {
    const int32_t exp = 63 - __builtin_clzll(ns);
    const int32_t sub =
        (int32_t)((ns >> (exp - RTH_SUB_BITS)) & ((1 << RTH_SUB_BITS) - 1));
    idx = ((exp - RTH_MIN_EXP) << RTH_SUB_BITS) + sub;
    if (idx >= RTH_BUCKETS) idx = RTH_BUCKETS - 1;
  }
  h[idx]++;
  h[RTH_BUCKETS]++;
  h[RTH_BUCKETS + 1] += ns;
}

static inline void fr_rec(RtmWorker* w, uint8_t kind, uint8_t arg,
                          uint32_t shard, int64_t slot)
    RABIA_REQUIRES(rtm_io_role) {
  const uint64_t head = w->fr_head.load(std::memory_order_relaxed);
  FrEvent& e = w->fr[head & (RTM_FLIGHT_CAP - 1)];
  e.t_ns = mono_ns();
  e.slot = (uint64_t)slot;
  e.batch = 0;
  e.shard = shard;
  e.peer = 0xFFFF;
  e.kind = kind;
  e.arg = arg;
  w->fr_head.store(head + 1, std::memory_order_relaxed);
}

// Append one event record; spins (bounded sleeps) when the mailbox is
// full — backpressure on the commit path, exactly like the transport's
// bounded inbox, except nothing is dropped (Python's drain is
// eventfd-driven, so the stall resolves in microseconds).
static void ev_push(RtmCtx* c, RtmWorker* w, const std::vector<uint8_t>& rec)
    RABIA_REQUIRES(rtm_io_role) {
  if (ByteRing::need((int64_t)rec.size()) > w->ev.cap()) {
    // a record larger than the whole mailbox can never be delivered:
    // drop it (counted) instead of spinning the commit path forever.
    // The ring default is sized above the transport's 16 MiB frame cap,
    // so only pathological wave-result sections can land here; the
    // protocol's retransmit/sync machinery owns recovery.
    w->ctrs[RTM_EV_DROPPED]++;
    return;
  }
  while (!w->ev.push(rec.data(), (int64_t)rec.size(), nullptr, 0)) {
    w->ctrs[RTM_EV_STALLS]++;
    uint64_t one = 1;
    (void)!write(c->event_fd, &one, 8);
    usleep(500);
    if (c->stop_req.load(std::memory_order_relaxed)) {
      // shutdown with the mailbox STILL full after the stall loop:
      // nothing will drain it before the thread joins, so this record
      // is lost — count it so the drop is visible in /metrics instead
      // of silently violating the drain-on-shutdown contract (only
      // reachable when shutdown races a full 20 MB mailbox)
      w->ctrs[RTM_EV_DROPPED]++;
      return;
    }
  }
  w->ctrs[RTM_EV_RECORDS]++;
  fr_rec(w, FRE_RT_HANDOFF, rec.empty() ? 0 : rec[0], 0, 0);
  uint64_t one = 1;
  (void)!write(c->event_fd, &one, 8);
}

static int32_t row_of(RtmCtx* c, const uint8_t sender[16]) {
  for (int32_t r = 0; r < c->R; r++) {
    if (memcmp(c->uuids.data() + (size_t)r * 16, sender, 16) == 0) return r;
  }
  return -1;
}

// --- outbound framing (v3 wire header, mirrors hostkernel rk_msg_id) --------

static inline uint32_t mix32(uint32_t h) {
  h ^= h >> 16;
  h *= 0x21F0AAADu;
  h ^= h >> 15;
  h *= 0x735A2D97u;
  h ^= h >> 15;
  return h;
}

static void rtm_msg_id(RtmCtx* c, RtmWorker* w, uint8_t* out)
    RABIA_REQUIRES(rtm_io_role) {
  const uint64_t ctr = ++w->msg_counter;
  // gid-salted stream so sibling workers never collide; gid 0 (and the
  // single-worker path) reproduces the historical ids bit for bit
  uint32_t h = mix32(0x52544D00u ^ (uint32_t)(c->me * 0x85EBCA6Bu) ^
                     (uint32_t)(w->gid * 0x9E3779B1u));
  for (int w = 0; w < 4; w++) {
    h = mix32(h ^ (uint32_t)(ctr >> (16 * (w & 1))) ^ 0x9E3779B9u * (w + 1));
    memcpy(out + 4 * w, &h, 4);
  }
  out[6] = (out[6] & 0x0F) | 0x40;
  out[8] = (out[8] & 0x3F) | 0x80;
}

// Build a bid-free Decision frame for explicit (shard, slot, value)
// entries (the native stale-vote repair; rk_emit_frame only frames the
// kernel's CURRENT slots). Returns frame length.
static int64_t build_decision_frame(RtmCtx* c, RtmWorker* w,
                                    std::vector<uint8_t>& f, double now,
                                    const int64_t* shards,
                                    const int64_t* slots, const int8_t* vals,
                                    int32_t count)
    RABIA_REQUIRES(rtm_io_role) {
  f.clear();
  const uint32_t body_len = 4 + (uint32_t)count * 14;
  f.resize(47 + body_len);
  uint8_t* p = f.data();
  p[0] = 3;
  p[1] = MT_DECISION;
  p[2] = 0;
  rtm_msg_id(c, w, p + 3);
  memcpy(p + 19, c->uuids.data() + (size_t)c->me * 16, 16);
  memcpy(p + 35, &now, 8);
  memcpy(p + 43, &body_len, 4);
  uint8_t* body = p + 47;
  const uint32_t cnt = (uint32_t)count;
  memcpy(body, &cnt, 4);
  uint8_t* e = body + 4;
  for (int32_t k = 0; k < count; k++) {
    const uint32_t su = (uint32_t)shards[k];
    const uint64_t ph = ((uint64_t)slots[k]) << 16;
    memcpy(e, &su, 4);
    memcpy(e + 4, &ph, 8);
    e[12] = (uint8_t)vals[k];
    e[13] = 0;
    e += 14;
  }
  return (int64_t)f.size();
}

// --- ProposeBlock native parse ----------------------------------------------

// Wire: v3 header | body: 16B block id | u32 k | k*u32 shards | k*u64
// slots | k*u32 counts | u32 total | total*u32 cmd_sizes | u32 blob_len
// | blob | u32 crc32(blob). Binding acceptance mirrors
// engine._on_propose_block element-for-element: proposer row must own
// each (shard, slot), slot >= applied, binding slot free, slot >= head.
// Returns 1 bound-something, 0 nothing-bound (still consumed), -1 not a
// parseable block (caller escalates), -2 drop (bad checksum/limits).
static int parse_propose_block(RtmCtx* c, RtmWorker* w, const uint8_t* data,
                               int64_t len, int32_t row, double now)
    RABIA_REQUIRES(rtm_io_role) {
  if (len < 47) return -1;
  if (data[0] != 3 || data[1] != MT_PROPOSE_BLOCK) return -1;
  const uint8_t flags = data[2];
  if (flags & FLAG_COMPRESSED) return -1;
  if (memcmp(data + 19, c->uuids.data() + (size_t)row * 16, 16) != 0) {
    w->ctrs[RTM_FRAMES_DROPPED]++;
    return -2;  // spoofed envelope
  }
  int64_t base = 35 + ((flags & FLAG_RECIPIENT) ? 16 : 0);
  if (len < base + 12) return -1;
  const double ts = rd_f64(data + base);
  if (ts > now + c->max_future_skew || ts < now - c->max_age) {
    w->ctrs[RTM_FRAMES_DROPPED]++;
    return -2;
  }
  const uint32_t body_len = rd_u32(data + base + 8);
  const uint8_t* body = data + base + 12;
  if ((int64_t)body_len > len - (base + 12)) return -1;
  if (body_len < 16 + 4) return -1;
  const uint32_t k = rd_u32(body + 16);
  if (k == 0 || k > (uint32_t)c->n) return -1;
  // fixed-section bounds before any pointer arithmetic (wire fields are
  // attacker-controlled; everything is 64-bit so the sums cannot wrap)
  uint64_t off = 16 + 4 + (uint64_t)k * 16;
  if (off + 4 > body_len) return -1;
  const uint8_t* sh_arr = body + 20;
  const uint8_t* sl_arr = sh_arr + (size_t)k * 4;
  const uint8_t* cnt_arr = sl_arr + (size_t)k * 8;
  const uint32_t total = rd_u32(body + off);
  off += 4;
  if (off + (uint64_t)total * 4 + 4 > body_len) return -1;
  const uint8_t* sz_arr = body + off;
  off += (uint64_t)total * 4;
  const uint32_t blob_len = rd_u32(body + off);
  off += 4;
  if (off + (uint64_t)blob_len + 4 > body_len) return -1;
  const uint8_t* blob = body + off;
  const uint32_t crc_wire = rd_u32(body + off + blob_len);
  if ((uint32_t)crc32(0, blob, blob_len) != crc_wire) {
    w->ctrs[RTM_FRAMES_DROPPED]++;
    return -2;
  }
  // validator-parity limits + structural sums
  uint64_t cnt_sum = 0;
  for (uint32_t i = 0; i < k; i++) {
    const uint32_t cc = rd_u32(cnt_arr + (size_t)i * 4);
    if ((int64_t)cc > c->max_cmds) return -2;
    cnt_sum += cc;
  }
  if (cnt_sum != total) return -1;
  uint64_t sz_sum = 0;
  for (uint32_t i = 0; i < total; i++) {
    const uint32_t sz = rd_u32(sz_arr + (size_t)i * 4);
    if ((int64_t)sz > c->max_cmd_size) return -2;
    sz_sum += sz;
  }
  if (sz_sum != blob_len) return -1;

  // binding pass (first binding wins; in-bounds shards of THIS worker's
  // group only — sibling workers bind their own entries from their copy)
  std::vector<uint32_t> acc;
  acc.reserve(k);
  for (uint32_t i = 0; i < k; i++) {
    const int64_t s = (int64_t)rd_u32(sh_arr + (size_t)i * 4);
    const int64_t slot = (int64_t)rd_u64(sl_arr + (size_t)i * 8);
    if (s < 0 || s >= c->n) continue;
    if (s < w->lo || s >= w->hi) continue;  // another group's entry
    if ((s + slot) % c->R != row) continue;  // slot_proposer parity
    if (slot < c->applied[s]) continue;
    if (c->blk_pend_ref[s] != -1 || c->blk_cur_ref[s] != -1) continue;
    const int64_t head =
        c->next_slot[s] > c->applied[s] ? c->next_slot[s] : c->applied[s];
    if (slot < head) continue;
    acc.push_back(i);
  }
  if (acc.empty()) return 0;
  const int64_t ref = w->next_blk++;
  CBlk& b = w->blocks[ref];
  b.token = 0;
  b.want = 0;
  b.has_data = 1;
  b.bound_at = now;
  memcpy(b.block_id, body, 16);
  b.has_block_id = 1;
  b.data.assign(blob, blob + blob_len);
  b.cmd_offsets.resize((size_t)total + 1);
  b.cmd_offsets[0] = 0;
  for (uint32_t i = 0; i < total; i++)
    b.cmd_offsets[i + 1] =
        b.cmd_offsets[i] + (int64_t)rd_u32(sz_arr + (size_t)i * 4);
  b.starts.resize((size_t)k + 1);
  b.starts[0] = 0;
  for (uint32_t i = 0; i < k; i++)
    b.starts[i + 1] = b.starts[i] + (int64_t)rd_u32(cnt_arr + (size_t)i * 4);
  b.shards.resize(k);
  b.slots.resize(k);
  b.bidx.resize(k);
  for (uint32_t i = 0; i < k; i++) {
    b.shards[i] = (int64_t)rd_u32(sh_arr + (size_t)i * 4);
    b.slots[i] = (int64_t)rd_u64(sl_arr + (size_t)i * 8);
    b.bidx[i] = i;
  }
  b.remaining = (int64_t)acc.size();
  for (uint32_t i : acc) {
    const int64_t s = b.shards[i];
    c->blk_pend_ref[s] = ref;
    c->blk_pend_pos[s] = i;
    c->blk_pend_slot[s] = b.slots[i];
  }
  w->ctrs[RTM_FRAMES_BLOCK]++;
  return 1;
}

static void blk_unref(RtmWorker* w, int64_t ref, int64_t n)
    RABIA_REQUIRES(rtm_io_role) {
  auto it = w->blocks.find(ref);
  if (it == w->blocks.end()) return;
  it->second.remaining -= n;
  if (it->second.remaining <= 0) w->blocks.erase(it);
}

// A decided slot voids any pending binding it overtook (asyncio parity:
// _record_decision -> _void_pending_block); Python demotes/settles the
// owner through the reject event.
static void void_stale_pend(RtmCtx* c, RtmWorker* w, int64_t s, int64_t slot)
    RABIA_REQUIRES(rtm_io_role) {
  if (c->blk_pend_ref[s] != -1 && c->blk_pend_slot[s] <= slot) {
    auto it = w->blocks.find(c->blk_pend_ref[s]);
    if (it != w->blocks.end()) {
      std::vector<uint8_t> rec;
      rec.push_back(EV_REJECT);
      wr_u64(rec, it->second.token);
      wr_u32(rec, it->second.bidx[c->blk_pend_pos[s]]);
      wr_u32(rec, (uint32_t)s);
      wr_u64(rec, (uint64_t)c->blk_pend_slot[s]);
      rec.push_back(2);
      ev_push(c, w, rec);
    }
    blk_unref(w, c->blk_pend_ref[s], 1);
    c->blk_pend_ref[s] = -1;
    c->blk_pend_slot[s] = -1;
  }
  if (c->sp_slot[s] != -1 && c->sp_slot[s] <= slot) {
    c->sp_slot[s] = -1;
    c->sp_frame[s].clear();
  }
}

}  // extern "C" (reopened below; internal linkage helpers end here)

extern "C" {

// --- command processing -----------------------------------------------------

static void handle_cmd(RtmCtx* c, RtmWorker* w, const uint8_t* p,
                       int64_t len, double now)
    RABIA_REQUIRES(rtm_io_role) {
  if (len < 1) return;
  const uint8_t type = p[0];
  const uint8_t* q = p + 1;
  w->ctrs[RTM_CMDS]++;
  if (type == CMD_OPEN_SCALAR) {
    if (len < 1 + 4 + 8 + 1 + 4) return;
    const int64_t s = (int64_t)rd_u32(q);
    const int64_t slot = (int64_t)rd_u64(q + 4);
    const int8_t init = (int8_t)q[12];
    const uint32_t flen = rd_u32(q + 13);
    if (s < w->lo || s >= w->hi) return;
    if (slot < c->applied[s] || c->in_flight[s] ||
        (c->blk_pend_ref[s] != -1 && c->blk_pend_slot[s] <= slot)) {
      std::vector<uint8_t> rec;
      rec.push_back(EV_REJECT);
      wr_u64(rec, 0);
      wr_u32(rec, 0);
      wr_u32(rec, (uint32_t)s);
      wr_u64(rec, (uint64_t)slot);
      rec.push_back(1);
      ev_push(c, w, rec);
      return;
    }
    c->sp_slot[s] = slot;
    c->sp_init[s] = init;
    c->sp_frame[s].assign(q + 17, q + 17 + flen);
  } else if (type == CMD_OPEN_WAVE) {
    if (len < 1 + 8 + 1 + 4 + 4 + 4) return;
    const uint64_t token = rd_u64(q);
    const uint8_t want = q[8];
    const uint32_t k = rd_u32(q + 9);
    const uint32_t announce_len = rd_u32(q + 13);
    const uint32_t blob_len = rd_u32(q + 17);
    const uint32_t total = rd_u32(q + 21);
    const uint8_t* ent = q + 25;
    const uint8_t* ops = ent + (size_t)k * 20;
    const uint8_t* announce = ops + (size_t)total * 4;
    const uint8_t* blob = announce + announce_len;
    const int64_t ref = w->next_blk++;
    CBlk& b = w->blocks[ref];
    b.token = token;
    b.want = want;
    b.has_data = blob_len > 0;
    b.bound_at = now;
    if (blob_len) b.data.assign(blob, blob + blob_len);
    b.shards.resize(k);
    b.slots.resize(k);
    b.bidx.resize(k);
    b.starts.resize((size_t)k + 1);
    b.starts[0] = 0;
    uint64_t op_at = 0;
    for (uint32_t i = 0; i < k; i++) {
      const uint8_t* e = ent + (size_t)i * 20;
      b.shards[i] = (int64_t)rd_u32(e);
      b.slots[i] = (int64_t)rd_u64(e + 4);
      b.bidx[i] = rd_u32(e + 12);
      const uint32_t nops = rd_u32(e + 16);
      op_at += nops;
      b.starts[i + 1] = (int64_t)op_at;
    }
    b.cmd_offsets.resize((size_t)total + 1);
    b.cmd_offsets[0] = 0;
    for (uint32_t i = 0; i < total; i++)
      b.cmd_offsets[i + 1] =
          b.cmd_offsets[i] + (int64_t)rd_u32(ops + (size_t)i * 4);
    b.remaining = 0;
    for (uint32_t i = 0; i < k; i++) {
      const int64_t s = b.shards[i];
      const int64_t slot = b.slots[i];
      bool ok = s >= w->lo && s < w->hi && slot >= c->applied[s] &&
                c->blk_pend_ref[s] == -1 && c->blk_cur_ref[s] == -1;
      if (ok) {
        const int64_t head =
            c->next_slot[s] > c->applied[s] ? c->next_slot[s] : c->applied[s];
        ok = slot >= head && c->tainted[s] <= slot;
      }
      if (!ok) {
        std::vector<uint8_t> rec;
        rec.push_back(EV_REJECT);
        wr_u64(rec, token);
        wr_u32(rec, b.bidx[i]);
        wr_u32(rec, (uint32_t)s);
        wr_u64(rec, (uint64_t)slot);
        rec.push_back(1);
        ev_push(c, w, rec);
        continue;
      }
      c->blk_pend_ref[s] = ref;
      c->blk_pend_pos[s] = i;
      c->blk_pend_slot[s] = slot;
      b.remaining++;
    }
    if (b.remaining == 0) {
      w->blocks.erase(ref);
      return;
    }
    if (announce_len) {
      // broadcast the ProposeBlock announce BEFORE any open/vote frame
      // (asyncio parity: announces flush ahead of the kernel round)
      std::vector<uint8_t> one(4 + announce_len);
      memcpy(one.data(), &announce_len, 4);
      memcpy(one.data() + 4, announce, announce_len);
      ((fn_bcast_frames_t)c->fns[FN_BCAST_FRAMES])(c->tr, one.data(),
                                                   (int64_t)one.size());
    }
  } else if (type == CMD_ADVANCE) {
    if (len < 1 + 4) return;
    const uint32_t count = rd_u32(q);
    const uint8_t* e = q + 4;
    for (uint32_t i = 0; i < count && 1 + 4 + (int64_t)(i + 1) * 12 <= len;
         i++) {
      const int64_t s = (int64_t)rd_u32(e + (size_t)i * 12);
      const int64_t upto = (int64_t)rd_u64(e + (size_t)i * 12 + 4);
      if (s >= w->lo && s < w->hi && upto > c->applied[s])
        c->applied[s] = upto;
    }
  } else if (type == CMD_DECIDE) {
    if (len < 1 + 4 + 8 + 1) return;
    const int64_t s = (int64_t)rd_u32(q);
    const int64_t slot = (int64_t)rd_u64(q + 4);
    const int8_t val = (int8_t)q[12];
    if (s < w->lo || s >= w->hi || c->in_flight[s]) return;
    const int64_t head =
        c->next_slot[s] > c->applied[s] ? c->next_slot[s] : c->applied[s];
    if (slot != head) return;
    if (c->blk_pend_ref[s] != -1 && c->blk_pend_slot[s] == slot) {
      // a block binding holds this slot's payload: let it open and
      // decide through consensus/adoption instead — adopting here
      // would strand a payload-less V1 record on the control plane
      return;
    }
    // adopt: bookkeeping here, record/apply in Python — but ONLY off
    // the confirming event below (a silently-rejected adopt must not
    // leave Python with a record C never made)
    if (slot + 1 > c->next_slot[s]) c->next_slot[s] = slot + 1;
    const int64_t ring = slot & (c->dec_ring - 1);
    c->ring_slot[s * c->dec_ring + ring] = slot;
    c->ring_val[s * c->dec_ring + ring] = val;
    c->sp_slot[s] = -1;
    c->sp_frame[s].clear();
    std::vector<uint8_t> rec;
    rec.push_back(EV_DECIDE);
    wr_u32(rec, (uint32_t)s);
    wr_u64(rec, (uint64_t)slot);
    rec.push_back((uint8_t)val);
    wr_f64(rec, 0.0);
    ev_push(c, w, rec);
  } else if (type == CMD_STOP) {
    c->stop_req.store(1, std::memory_order_relaxed);
  }
}

static void drain_cmds(RtmCtx* c, RtmWorker* w, double now)
    RABIA_REQUIRES(rtm_io_role) {
  for (;;) {
    int64_t got = w->cmd.drain(w->cmd_scratch.data(),
                               (int64_t)w->cmd_scratch.size());
    if (got <= 0) break;
    int64_t at = 0;
    while (at + 4 <= got) {
      const uint32_t len = rd_u32(w->cmd_scratch.data() + at);
      handle_cmd(c, w, w->cmd_scratch.data() + at + 4, (int64_t)len, now);
      at += 4 + len;
    }
  }
}

// --- decided-slot processing ------------------------------------------------

static void process_decided(RtmCtx* c, RtmWorker* w, double now)
    RABIA_REQUIRES(rtm_io_role) {
  // group decided block-bound shards by ref; scalars stream directly
  std::map<int64_t, std::vector<int64_t>> waves;  // ref -> shard list
  for (int64_t s = w->lo; s < w->hi; s++) {
    if (!(c->kdone[s] && c->in_flight[s])) continue;
    const int64_t slot = (int64_t)c->kslot[s];
    const int8_t val = c->kdecided[s];
    c->knewly[s] = 0;
    if (c->blk_cur_ref[s] != -1) {
      // validate the binding still describes THIS slot: a sync adoption
      // (Python, under pause) can overtake an in-flight shard and leave
      // a stale cur binding — routing a later decide through it would
      // apply the wrong entry's ops
      auto bit = w->blocks.find(c->blk_cur_ref[s]);
      if (bit != w->blocks.end() &&
          bit->second.slots[c->blk_cur_pos[s]] == slot) {
        waves[c->blk_cur_ref[s]].push_back(s);
        continue;
      }
      blk_unref(w, c->blk_cur_ref[s], 1);
      c->blk_cur_ref[s] = -1;
    }
    if (c->blk_pend_ref[s] != -1 && c->blk_pend_slot[s] == slot &&
        val == V1c) {
      // a V1 decide adopted into a slot whose block binding never
      // OPENED here (we grace-opened V0, peers decided V1): the bound
      // payload still applies — promote the pending binding and route
      // through the wave path (asyncio parity: _process_decided's
      // blk_pending branch)
      c->blk_cur_ref[s] = c->blk_pend_ref[s];
      c->blk_cur_pos[s] = c->blk_pend_pos[s];
      c->blk_pend_ref[s] = -1;
      c->blk_pend_slot[s] = -1;
      waves[c->blk_cur_ref[s]].push_back(s);
      continue;
    }
    // scalar decide: consensus bookkeeping here, record/apply in Python
    c->in_flight[s] = 0;
    if (slot + 1 > c->next_slot[s]) c->next_slot[s] = slot + 1;
    const int64_t ring = slot & (c->dec_ring - 1);
    c->ring_slot[s * c->dec_ring + ring] = slot;
    c->ring_val[s * c->dec_ring + ring] = val;
    const double opened = c->opened_at[s];
    c->opened_at[s] = 0.0;
    void_stale_pend(c, w, s, slot);
    std::vector<uint8_t> rec;
    rec.push_back(EV_DECIDE);
    wr_u32(rec, (uint32_t)s);
    wr_u64(rec, (uint64_t)slot);
    rec.push_back((uint8_t)val);
    wr_f64(rec, opened);
    ev_push(c, w, rec);
    w->ctrs[RTM_DECIDED_SCALAR]++;
    w->ctrs[RTM_GIL_HANDOFFS]++;
  }

  for (auto& [ref, shards] : waves) {
    auto bit = w->blocks.find(ref);
    if (bit == w->blocks.end()) {
      // registry raced empty (should not happen: refs release at decide)
      for (int64_t s : shards) {
        c->in_flight[s] = 0;
        c->blk_cur_ref[s] = -1;
      }
      continue;
    }
    CBlk& b = bit->second;
    // classify entries; only in-order V1 entries of a data-bearing block
    // apply natively (asyncio parity: _finish_block_slots)
    std::vector<int64_t> idxs;  // block positions to apply (V1, in order)
    std::vector<int64_t> ent_shard, ent_slot, ent_pos;
    std::vector<uint32_t> ent_bidx;
    std::vector<int8_t> ent_val;
    std::vector<uint8_t> ent_in_order;
    const bool native = b.has_data && c->native_apply;
    for (int64_t s : shards) {
      const int64_t pos = c->blk_cur_pos[s];
      const int64_t slot = (int64_t)c->kslot[s];
      const int8_t val = c->kdecided[s];
      const bool in_order = c->applied[s] == slot;
      ent_shard.push_back(s);
      ent_slot.push_back(slot);
      ent_pos.push_back(pos);
      ent_bidx.push_back(b.bidx[pos]);
      ent_val.push_back(val);
      ent_in_order.push_back(in_order ? 1 : 0);
      if (val == V1c && in_order && native) idxs.push_back(pos);
    }
    int64_t staged = -1;
    const int32_t want = (b.token != 0 && b.want) ? 1 : 0;
    // per-entry staged-result slices, captured below while the plane
    // lock is still held (slice i of res_bytes has length res_len[i],
    // concatenated in entry order)
    std::vector<int64_t> res_len(ent_shard.size(), 0);
    std::vector<uint8_t> res_bytes;
    if (native && !idxs.empty()) {
      // Single-worker path: hold the store-plane lock across the apply
      // AND the result read-out — the asyncio thread's scalar applies
      // (sk_apply_ops) clear and regrow the SAME out_buf, so reading it
      // after sk_apply_wave's internal lock is released races a
      // concurrent clear/realloc. The plane mutex is recursive, so
      // bracketing the call is safe — but the bracket must end before
      // any ev_push (a full mailbox blocks until Python drains, and
      // Python's drain paths take this lock: holding it there would
      // deadlock).
      //
      // Multi-worker path: the wave is group-pure, so it applies through
      // this worker's PRIVATE statekernel lane (sk_apply_wave_lane) —
      // the group mutex is taken inside the call and the lane's staging
      // buffers have a single owner thread, so neither the apply nor the
      // read-out needs the plane-wide bracket. N workers' applies stop
      // serializing on the recursive plane mutex.
      const bool lane_apply = c->W > 1 &&
                              c->fns[FN_SK_APPLY_WAVE_LANE] != nullptr;
      const bool plane_held =
          !lane_apply && c->fns[FN_SK_PLANE_LOCK] != nullptr;
      if (plane_held)
        ((fn_sk_plane_lk_t)c->fns[FN_SK_PLANE_LOCK])(c->sk);
      const uint64_t ap0 = mono_ns();
      if (lane_apply) {
        staged = ((fn_sk_apply_lane_t)c->fns[FN_SK_APPLY_WAVE_LANE])(
            c->sk, w->gid, b.data.data(), b.cmd_offsets.data(),
            b.shards.data(), b.starts.data(), idxs.data(),
            (int64_t)idxs.size(), now, want);
      } else {
        staged = ((fn_sk_apply_wave_t)c->fns[FN_SK_APPLY_WAVE])(
            c->sk, b.data.data(), b.cmd_offsets.data(), b.shards.data(),
            b.starts.data(), idxs.data(), (int64_t)idxs.size(), now, want);
      }
      const uint64_t ap_ns = mono_ns() - ap0;
      w->stg[RTS_APPLY] += ap_ns;
      rth_observe(w, RTH_DECIDE_APPLY, ap_ns);
      if (want && staged >= 0) {
        const uint8_t* ob;
        const int64_t* offs;
        if (lane_apply) {
          ob = (const uint8_t*)((fn_sk_lane_ptr_t)
                                    c->fns[FN_SK_OUT_BUF_LANE])(c->sk,
                                                                w->gid);
          offs = (const int64_t*)((fn_sk_lane_ptr_t)
                                      c->fns[FN_SK_OUT_OFFS_LANE])(c->sk,
                                                                   w->gid);
        } else {
          ob = (const uint8_t*)((fn_sk_ptr_t)c->fns[FN_SK_OUT_BUF])(c->sk);
          offs =
              (const int64_t*)((fn_sk_ptr_t)c->fns[FN_SK_OUT_OFFS])(c->sk);
        }
        std::map<int64_t, std::pair<int64_t, int64_t>> ranges;  // pos->ops
        int64_t op_at = 0;
        for (int64_t pos : idxs) {
          const int64_t nops = b.starts[pos + 1] - b.starts[pos];
          ranges.emplace(pos, std::make_pair(op_at, op_at + nops));
          op_at += nops;
        }
        for (size_t i = 0; i < ent_shard.size(); i++) {
          auto rit = ranges.find(ent_pos[i]);
          if (rit == ranges.end()) continue;
          const int64_t lo = offs[rit->second.first];
          const int64_t hi = offs[rit->second.second];
          res_len[i] = hi - lo;
          if (hi > lo) {
            size_t wb = res_bytes.size();
            res_bytes.resize(wb + (size_t)(hi - lo));
            memcpy(res_bytes.data() + wb, ob + lo, (size_t)(hi - lo));
          }
        }
      }
      if (plane_held)
        ((fn_sk_plane_lk_t)c->fns[FN_SK_PLANE_UNLOCK])(c->sk);
      w->ctrs[RTM_SLOTS_APPLIED] += (uint64_t)idxs.size();
    }
    if (c->wal && native) {
      // durability plane: stage each in-order entry of the wave into
      // the WAL's group-commit lane BEFORE its EV_WAVE record reaches
      // Python — the gateway's result barrier then only has to wait on
      // the watermark. Payload layout = native_wal.encode_wave (the
      // Python twin is the semantics owner; keep byte-identical). The
      // batch id field is zeros here — the control plane backfills it
      // with a K_LEDGER record off the commit path (C never derives
      // deterministic batch ids).
      const uint64_t w0 = mono_ns();
      std::vector<uint8_t> pay;
      for (size_t i = 0; i < ent_shard.size(); i++) {
        if (!ent_in_order[i]) continue;  // py lane stages sync-overtaken
        const bool with_ops = ent_val[i] == V1c;
        pay.clear();
        pay.push_back(1);  // K_WAVE
        wr_u32(pay, (uint32_t)ent_shard[i]);
        wr_u64(pay, (uint64_t)ent_slot[i]);
        pay.push_back((uint8_t)ent_val[i]);
        pay.push_back(with_ops ? 1 : 0);
        if (with_ops) {
          pay.resize(pay.size() + 16, 0);  // bid: K_LEDGER backfills
          const int64_t pos = ent_pos[i];
          const int64_t lo = b.starts[pos], hi = b.starts[pos + 1];
          wr_u32(pay, (uint32_t)(hi - lo));
          for (int64_t j = lo; j < hi; j++) {
            const int64_t o0 = b.cmd_offsets[j], o1 = b.cmd_offsets[j + 1];
            wr_u32(pay, (uint32_t)(o1 - o0));
            size_t wb = pay.size();
            pay.resize(wb + (size_t)(o1 - o0));
            memcpy(pay.data() + wb, b.data.data() + o0, (size_t)(o1 - o0));
          }
        }
        ((fn_wal_append_t)c->fns[FN_WAL_APPEND])(c->wal, pay.data(),
                                                 (int64_t)pay.size());
      }
      w->stg[RTS_APPLY] += mono_ns() - w0;  // staging rides the apply stage
      if (b.token == 0 && b.has_block_id) {
        // receiver-side ledger completeness: hand the (block id, shard,
        // slot) tuples of the zero-bid K_WAVE records just staged to
        // Python, which backfills K_LEDGER off the commit path (the
        // proposer path backfills from its block registry in _on_wave)
        std::vector<uint8_t> lrec;
        uint32_t n_led = 0;
        for (size_t i = 0; i < ent_shard.size(); i++)
          if (ent_in_order[i] && ent_val[i] == V1c) n_led++;
        if (n_led) {
          lrec.push_back(EV_LEDGER);
          size_t wb = lrec.size();
          lrec.resize(wb + 16);
          memcpy(lrec.data() + wb, b.block_id, 16);
          wr_u32(lrec, n_led);
          for (size_t i = 0; i < ent_shard.size(); i++) {
            if (!ent_in_order[i] || ent_val[i] != V1c) continue;
            wr_u32(lrec, (uint32_t)ent_shard[i]);
            wr_u64(lrec, (uint64_t)ent_slot[i]);
          }
          ev_push(c, w, lrec);
        }
      }
    }
    // bookkeeping for every decided entry
    for (size_t i = 0; i < ent_shard.size(); i++) {
      const int64_t s = ent_shard[i];
      const int64_t slot = ent_slot[i];
      c->in_flight[s] = 0;
      c->opened_at[s] = 0.0;
      if (slot + 1 > c->next_slot[s]) c->next_slot[s] = slot + 1;
      const int64_t ring = slot & (c->dec_ring - 1);
      c->ring_slot[s * c->dec_ring + ring] = slot;
      c->ring_val[s * c->dec_ring + ring] = ent_val[i];
      if (native && ent_in_order[i]) c->applied[s] = slot + 1;
      c->blk_cur_ref[s] = -1;
      void_stale_pend(c, w, s, slot);
    }

    // one EV_WAVE per (ref, tick-batch)
    std::vector<uint8_t> rec;
    const uint8_t applied_flag = native ? 1 : 0;
    const uint8_t has_results = (native && want && staged >= 0) ? 1 : 0;
    rec.push_back(EV_WAVE);
    wr_u64(rec, b.token);
    rec.push_back(applied_flag);
    rec.push_back(has_results);
    wr_u32(rec, (uint32_t)ent_shard.size());
    for (size_t i = 0; i < ent_shard.size(); i++) {
      wr_u32(rec, (uint32_t)ent_shard[i]);
      wr_u64(rec, (uint64_t)ent_slot[i]);
      wr_u32(rec, ent_bidx[i]);
      // value bits 0-1; bit 2 flags out-of-order (sync-overtaken)
      // entries Python must route through its scalar ledger
      rec.push_back((uint8_t)ent_val[i] | (ent_in_order[i] ? 0 : 4));
    }
    if (has_results) {
      // results section: count * u32 rlen, then ONE concatenated payload
      // blob (entry order) — the Python side slices lazily with numpy
      // instead of a per-entry parse loop. Per-entry [u32 len][payload]
      // result records stay inside each entry's slice (the
      // rt_broadcast_frames staging format the plane emits). The slices
      // themselves were copied out of the plane's out_buf above, under
      // the plane lock.
      for (size_t i = 0; i < ent_shard.size(); i++)
        wr_u32(rec, (uint32_t)res_len[i]);
      if (!res_bytes.empty()) {
        size_t wb = rec.size();
        rec.resize(wb + res_bytes.size());
        memcpy(rec.data() + wb, res_bytes.data(), res_bytes.size());
        w->ctrs[RTM_RESULT_BYTES] += (uint64_t)res_bytes.size();
      }
    }
    blk_unref(w, ref, (int64_t)ent_shard.size());
    ev_push(c, w, rec);
    if (native) {
      // proposer-side future settle is Python bookkeeping but OFF the
      // commit path (peers already progressed) — not a GIL handoff
      w->ctrs[RTM_WAVES_NATIVE]++;
    } else {
      w->ctrs[RTM_WAVES_PY]++;
      w->ctrs[RTM_GIL_HANDOFFS]++;
    }
  }
}

// --- open collection --------------------------------------------------------

static int32_t collect_opens(RtmCtx* c, RtmWorker* w)
    RABIA_REQUIRES(rtm_io_role) {
  int32_t n_open = 0;
  // durability plane: the watermark read once per pass (an atomic load)
  const uint64_t wal_durable =
      c->wal ? ((fn_wal_durable_t)c->fns[FN_WAL_DURABLE])(c->wal) : 0;
  memset(w->open_mask.data() + w->lo, 0, (size_t)(w->hi - w->lo));
  for (int64_t s = w->lo; s < w->hi; s++) {
    if (c->in_flight[s]) continue;
    if (c->blk_cur_ref[s] != -1) {
      // idle shard with a cur binding = a sync adoption overtook the
      // open (Python cleared in_flight under pause): release it before
      // anything re-opens the shard
      blk_unref(w, c->blk_cur_ref[s], 1);
      c->blk_cur_ref[s] = -1;
    }
    if (c->blk_pend_ref[s] == -1 && c->sp_slot[s] == -1) continue;
    const int64_t head =
        c->next_slot[s] > c->applied[s] ? c->next_slot[s] : c->applied[s];
    if (c->wal) {
      // vote-barrier write-ahead: this replica's FIRST vote in any slot
      // >= the persisted barrier must not reach the wire until the
      // barrier record advancing past it is DURABLE — otherwise a
      // restart could re-vote differently in the same (slot, phase)
      // (equivocation). wal_barrier_covered is stride-amortized: the
      // common case returns 0 (covered) without touching the log, and
      // a shard that does advance it parks un-armed for the next loop
      // pass or two while the group-commit fsync lands (other shards
      // and the frame pump keep running — the io/tick thread NEVER
      // blocks on disk).
      if (c->bar_wait[s] > 0) {
        if (wal_durable < (uint64_t)c->bar_wait[s]) {
          w->restep = 1;  // stay hot: the fsync is typically ~100us out
          continue;
        }
        c->bar_wait[s] = 0;
      }
      const int64_t blsn = ((fn_wal_barrier_t)c->fns[FN_WAL_BARRIER])(
          c->wal, s, head);
      if (blsn > 0 && wal_durable < (uint64_t)blsn) {
        c->bar_wait[s] = blsn;
        w->restep = 1;
        continue;
      }
    }
    void_stale_pend(c, w, s, head - 1);  // drop bindings the head overtook
    // block binding at head wins (asyncio parity: bulk open runs first)
    if (c->blk_pend_ref[s] != -1 && c->blk_pend_slot[s] == head &&
        c->tainted[s] <= head) {
      c->blk_cur_ref[s] = c->blk_pend_ref[s];
      c->blk_cur_pos[s] = c->blk_pend_pos[s];
      c->blk_pend_ref[s] = -1;
      c->blk_pend_slot[s] = -1;
      w->open_mask[s] = 1;
      w->open_slots[s] = (int32_t)head;
      w->open_init[s] = V1c;
      n_open++;
      w->ctrs[RTM_OPENS_BLOCK]++;
      continue;
    }
    if (c->sp_slot[s] == head && c->tainted[s] <= head) {
      w->open_mask[s] = 1;
      w->open_slots[s] = (int32_t)head;
      w->open_init[s] = c->sp_init[s];
      n_open++;
      w->ctrs[RTM_OPENS_SCALAR]++;
      if (!c->sp_frame[s].empty()) {
        // Propose rides ahead of the open's R1 frame (asyncio parity)
        std::vector<uint8_t> one;
        const uint32_t flen = (uint32_t)c->sp_frame[s].size();
        wr_u32(one, flen);
        size_t wb = one.size();
        one.resize(wb + flen);
        memcpy(one.data() + wb, c->sp_frame[s].data(), flen);
        ((fn_bcast_frames_t)c->fns[FN_BCAST_FRAMES])(c->tr, one.data(),
                                                     (int64_t)one.size());
        c->sp_frame[s].clear();
      }
      c->sp_slot[s] = -1;
    }
  }
  if (n_open) {
    const double now = wall_s();
    for (int64_t s = w->lo; s < w->hi; s++) {
      if (!w->open_mask[s]) continue;
      c->in_flight[s] = 1;
      // next_slot = max(next_slot, slot) — np.maximum.at parity; the
      // +1 advance happens at decide
      if ((int64_t)w->open_slots[s] > c->next_slot[s])
        c->next_slot[s] = (int64_t)w->open_slots[s];
      c->opened_at[s] = now;
      c->last_progress[s] = now;
    }
  }
  return n_open;
}

// --- timers: retransmit, stale repair, stall escalation ---------------------

static void run_timers(RtmCtx* c, RtmWorker* w, double now)
    RABIA_REQUIRES(rtm_io_role) {
  // vote retransmits for stalled shards (pure C)
  int64_t res[4] = {0, 0, 0, 0};
  ((fn_rk_retransmit_t)c->fns[FN_RK_RETRANSMIT])(
      w->rk, now, c->phase_timeout, w->out.data(), (int64_t)w->out.size(),
      res);
  if (res[0] > 0) {
    ((fn_bcast_frames_t)c->fns[FN_BCAST_FRAMES])(c->tr, w->out.data(), res[0]);
    w->ctrs[RTM_RETRANSMITS]++;
  }
  if (res[1] > 0) {
    // payload retransmission is Python's (it owns the propose bytes):
    // escalate stalled shards' bindings, rate-limited per shard
    for (int64_t s = w->lo; s < w->hi; s++) {
      if (!c->in_flight[s]) continue;
      if (now - c->opened_at[s] < c->phase_timeout) continue;
      if (now - c->stall_ev_at[s] < c->phase_timeout) continue;
      c->stall_ev_at[s] = now;
      std::vector<uint8_t> rec;
      if (c->blk_cur_ref[s] != -1) {
        auto it = w->blocks.find(c->blk_cur_ref[s]);
        const uint64_t token = it != w->blocks.end() ? it->second.token : 0;
        rec.push_back(EV_STALL);
        rec.push_back(1);
        wr_u32(rec, (uint32_t)s);
        wr_u64(rec, token);
      } else {
        rec.push_back(EV_STALL);
        rec.push_back(0);
        wr_u32(rec, (uint32_t)s);
        wr_u64(rec, (uint64_t)c->kslot[s]);
      }
      ev_push(c, w, rec);
    }
  }
  // peer-votes-waiting escalation (the V0 grace path stays in Python).
  // Bounded per pass: at wide shard counts an unthrottled scan would
  // flood the mailbox with stall events faster than the control plane
  // can bind payloads, turning a transient binding lag into a V0-open
  // cascade (measured: ~1M stall events in one config-5 run).
  int32_t stall_budget = 128;
  for (int64_t s = w->lo; s < w->hi && stall_budget > 0; s++) {
    if (c->in_flight[s]) continue;
    const int64_t head =
        c->next_slot[s] > c->applied[s] ? c->next_slot[s] : c->applied[s];
    if (c->votes_seen[s] < head) continue;
    if (c->blk_pend_ref[s] != -1 || c->sp_slot[s] != -1) continue;
    if (now - c->votes_wait_at[s] < c->grace) continue;
    c->votes_wait_at[s] = now;
    stall_budget--;
    std::vector<uint8_t> rec;
    rec.push_back(EV_STALL);
    rec.push_back(2);
    wr_u32(rec, (uint32_t)s);
    wr_u64(rec, (uint64_t)head);
    ev_push(c, w, rec);
  }
  // native stale-vote repair from the decided-value ring (bid-free
  // Decisions, unicast, per-row rate limit — _repair_stale_sender parity)
  const int64_t k = ((fn_rk_drain_stale_t)c->fns[FN_RK_DRAIN_STALE])(
      w->rk, w->st_rows.data(), w->st_shards.data(), w->st_slots.data(),
      (int64_t)w->st_rows.size());
  if (k > 0) {
    const double limit =
        c->phase_timeout / 4 > 0.05 ? c->phase_timeout / 4 : 0.05;
    std::vector<int64_t> shards, slots;
    std::vector<int8_t> vals;
    for (int32_t row = 0; row < c->R; row++) {
      if (row == c->me) continue;
      shards.clear();
      slots.clear();
      vals.clear();
      for (int64_t i = 0; i < k && (int64_t)shards.size() < 256; i++) {
        if (w->st_rows[i] != row) continue;
        const int64_t s = w->st_shards[i];
        const int64_t slot = w->st_slots[i];
        const int64_t ring = slot & (c->dec_ring - 1);
        if (c->ring_slot[s * c->dec_ring + ring] != slot) continue;
        shards.push_back(s);
        slots.push_back(slot);
        vals.push_back(c->ring_val[s * c->dec_ring + ring]);
      }
      if (shards.empty()) continue;
      if (now - w->last_repair[row] < limit) continue;
      w->last_repair[row] = now;
      std::vector<uint8_t> f;
      build_decision_frame(c, w, f, now, shards.data(), slots.data(),
                           vals.data(), (int32_t)shards.size());
      ((fn_send_t)c->fns[FN_SEND])(c->tr,
                                   c->uuids.data() + (size_t)row * 16,
                                   f.data(), (uint32_t)f.size());
      w->ctrs[RTM_STALE_REPAIRS]++;
    }
  }
}

// --- frame classification (per-group transport routing) ---------------------

// Which shard groups must see this frame? Vote/Decision/ProposeBlock
// frames map their entry shards to groups (a workers=1 peer's mixed
// batch fans out — each worker's rk ctx ingests only its own range);
// everything else (Propose, sync, admin, malformed, non-v3) lands in
// group 0, whose worker owns control-plane escalation. Pure + read-only:
// the transport's io thread calls this through rt_set_groups, and
// workers recompute it for escalation dedup — same bytes, same mask.
static uint64_t group_mask_of(const RtmCtx* c, const uint8_t* data,
                              uint32_t len) {
  if (c->W <= 1) return 1;
  if (len < 47 || data[0] != 3) return 1;
  const uint8_t mt = data[1];
  const uint8_t flags = data[2];
  if (flags & FLAG_COMPRESSED) return 1;
  const uint32_t base = 35 + ((flags & FLAG_RECIPIENT) ? 16 : 0);
  if (len < base + 12) return 1;
  const uint32_t body_len = rd_u32(data + base + 8);
  if ((uint64_t)body_len > (uint64_t)len - (base + 12)) return 1;
  const uint8_t* body = data + base + 12;
  uint64_t mask = 0;
  if (mt == MT_VOTE1 || mt == MT_VOTE2 || mt == MT_DECISION) {
    if (body_len < 4) return 1;
    const uint32_t count = rd_u32(body);
    const uint32_t esz = (mt == MT_DECISION) ? 14u : 13u;
    if (4ull + (uint64_t)count * esz > body_len) return 1;
    const uint8_t* e = body + 4;
    for (uint32_t k = 0; k < count; k++, e += esz) {
      const uint32_t s = rd_u32(e);
      if (s < (uint32_t)c->n) mask |= 1ull << c->group_of((int64_t)s);
    }
    return mask ? mask : 1;
  }
  if (mt == MT_PROPOSE_BLOCK) {
    if (body_len < 20) return 1;
    const uint32_t k = rd_u32(body + 16);
    if (k == 0 || k > (uint32_t)c->n) return 1;
    if (20ull + (uint64_t)k * 16 > body_len) return 1;
    const uint8_t* sh = body + 20;
    for (uint32_t i = 0; i < k; i++) {
      const uint32_t s = rd_u32(sh + (size_t)i * 4);
      if (s < (uint32_t)c->n) mask |= 1ull << c->group_of((int64_t)s);
    }
    return mask ? mask : 1;
  }
  return 1;
}

// --- the io/tick loop -------------------------------------------------------

// One inbound frame through the native path: rk_ingest (votes/decisions),
// the native ProposeBlock binder, or escalation to the Python mailbox.
// Returns 1 when the frame had ledger/binding effects (a tick is due).
static int32_t handle_frame(RtmCtx* c, RtmWorker* w, int32_t row,
                            const uint8_t* fp, uint32_t flen, double now)
    RABIA_REQUIRES(rtm_io_role) {
  const int32_t rc =
      ((fn_rk_ingest_t)c->fns[FN_RK_INGEST])(w->rk, fp, (int64_t)flen, row,
                                             now);
  if (rc == RK_HANDLED) {
    w->ctrs[RTM_FRAMES_NATIVE]++;
    return 1;
  }
  if (rc == RK_NOOP) {
    w->ctrs[RTM_FRAMES_NATIVE]++;
    return 0;
  }
  if (rc == RK_DROP) {
    w->ctrs[RTM_FRAMES_DROPPED]++;
    return 0;
  }
  // RK_PY: bind blocks natively when the apply plane is native —
  // otherwise the frame goes up (Python owns binding AND apply there)
  if (flen >= 2 && fp[1] == MT_PROPOSE_BLOCK && c->native_apply) {
    const int brc = parse_propose_block(c, w, fp, (int64_t)flen, row, now);
    if (brc >= 0) return brc;
    if (brc == -2) return 0;  // dropped (spoof/skew/checksum/limits)
  }
  if (c->W > 1 && flen >= 2 && fp[1] == MT_PROPOSE_BLOCK) {
    // escalation dedup: a multi-group ProposeBlock was delivered to
    // every group it binds — exactly ONE worker (the lowest group in
    // the recomputed mask) hands it to Python, or _on_propose_block
    // would register duplicate block entries. Vote/Decision escalations
    // stay per-worker: their Python handlers are idempotent per entry.
    const uint64_t mask = group_mask_of(c, fp, flen);
    if (w->gid != __builtin_ctzll(mask ? mask : 1)) return 0;
  }
  std::vector<uint8_t> rec;
  rec.push_back(EV_FRAME);
  rec.push_back((uint8_t)(row & 0xFF));
  rec.push_back((uint8_t)((row >> 8) & 0xFF));
  size_t wat = rec.size();
  rec.resize(wat + flen);
  memcpy(rec.data() + wat, fp, flen);
  ev_push(c, w, rec);
  w->ctrs[RTM_FRAMES_ESCALATED]++;
  return 0;
}

// Stage bracket: add a measured duration to one RTS_* stage and to the
// iteration accumulator (the RTS_OTHER remainder computation needs every
// attributed nanosecond counted exactly once).
#define RTS_ADD(stage, dur)   \
  do {                        \
    const uint64_t _d = (dur); \
    w->stg[stage] += _d;      \
    acc += _d;                \
  } while (0)

static void rtm_loop(RtmCtx* c, RtmWorker* w) {
  // this thread IS the io role for its shard group: assert_capability
  // informs the analysis without emitting code (rtm_start spawns one
  // such thread per group; shard ranges are disjoint)
  rtm_io_role.assert_held();
  fn_recv_borrow_t recv_borrow = (fn_recv_borrow_t)c->fns[FN_RECV_BORROW];
  fn_recv_borrow_grp_t recv_borrow_grp =
      (fn_recv_borrow_grp_t)c->fns[FN_RECV_BORROW_GROUP];
  fn_recv_release_t recv_release = (fn_recv_release_t)c->fns[FN_RECV_RELEASE];
  fn_rk_tick_t rk_tick = (fn_rk_tick_t)c->fns[FN_RK_TICK];
  fn_bcast_frames_t bcast = (fn_bcast_frames_t)c->fns[FN_BCAST_FRAMES];
  const bool grouped = c->W > 1;
  uint8_t sender[16];
  const uint8_t* fp = nullptr;
  uint32_t flen = 0;
  int64_t res[8];
  const double timer_every =
      c->phase_timeout / 4 < 0.05 ? c->phase_timeout / 4 : 0.05;

  while (!c->stop_req.load(std::memory_order_relaxed)) {
    w->ctrs[RTM_LOOPS]++;
    const uint64_t it0 = mono_ns();
    uint64_t acc = 0, t0 = 0;
    double now = wall_s();
    t0 = mono_ns();
    drain_cmds(c, w, now);
    RTS_ADD(RTS_CMD, mono_ns() - t0);
    if (c->pause_req.load(std::memory_order_acquire)) {
      // the pause is a BARRIER handshake: every worker parks itself and
      // rtm_state reports PAUSED only once all of them have (the
      // round-13 release/acquire handshake, multiplied per worker)
      w->state.store(RTM_PAUSED, std::memory_order_release);
      w->ctrs[RTM_PAUSES]++;
      t0 = mono_ns();
      // acquire pairs with rtm_resume's release store: the control
      // plane's while-PAUSED mutations of the shared arrays must be
      // visible before the loop reads them again
      while (c->pause_req.load(std::memory_order_acquire) &&
             !c->stop_req.load(std::memory_order_relaxed))
        usleep(200);
      RTS_ADD(RTS_IDLE, mono_ns() - t0);
      w->state.store(RTM_RUNNING, std::memory_order_release);
      w->stg[RTS_OTHER] += (mono_ns() - it0) - acc;
      continue;
    }

    // nonblocking frame pump: rk_ingest consumes vote/decision frames in
    // place; ProposeBlock binds natively; everything else escalates
    int32_t got = 0, consumed = 0;
    t0 = mono_ns();
    while (consumed < 512) {
      const int64_t tok =
          grouped ? recv_borrow_grp(c->tr, w->gid, sender, &fp, &flen, 0)
                  : recv_borrow(c->tr, sender, &fp, &flen, 0);
      if (tok < 0) break;
      consumed++;
      const int32_t row = row_of(c, sender);
      if (row >= 0) got += handle_frame(c, w, row, fp, flen, now);
      recv_release(c->tr, tok);
    }
    RTS_ADD(RTS_INGEST, mono_ns() - t0);

    t0 = mono_ns();
    const int32_t n_open = collect_opens(c, w);
    RTS_ADD(RTS_TICK, mono_ns() - t0);
    if (got || n_open || w->restep) {
      w->restep = 0;
      now = wall_s();
      t0 = mono_ns();
      rk_tick(w->rk, now, w->out.data(), (int64_t)w->out.size(), 4,
              n_open ? w->open_mask.data() : nullptr,
              n_open ? w->open_slots.data() : nullptr,
              n_open ? w->open_init.data() : nullptr, res);
      RTS_ADD(RTS_TICK, mono_ns() - t0);
      w->ctrs[RTM_TICKS]++;
      if (res[0] > 0) {
        t0 = mono_ns();
        bcast(c->tr, w->out.data(), res[0]);
        const uint64_t bc_ns = mono_ns() - t0;
        RTS_ADD(RTS_BROADCAST, bc_ns);
        rth_observe(w, RTH_BROADCAST, bc_ns);
      }
      if (res[2]) w->restep = 1;
      if (res[1]) {
        // process_decided brackets its own sk_apply_wave sections into
        // RTS_APPLY; everything else it does (decision bookkeeping,
        // result copy-out, event-record staging) is result staging
        const uint64_t a0 = w->stg[RTS_APPLY];
        t0 = mono_ns();
        process_decided(c, w, now);
        const uint64_t pd = mono_ns() - t0;
        const uint64_t ap = w->stg[RTS_APPLY] - a0;
        w->stg[RTS_RESULT_STAGING] += pd > ap ? pd - ap : 0;
        acc += pd;
      }
    }

    if (now - w->last_timers >= timer_every) {
      w->last_timers = now;
      t0 = mono_ns();
      run_timers(c, w, now);
      RTS_ADD(RTS_TIMERS, mono_ns() - t0);
    }

    if (w->restep) {
      w->stg[RTS_OTHER] += (mono_ns() - it0) - acc;
      continue;
    }
    if (consumed) {
      fr_rec(w, FRE_RT_WAKE, 1, 0, 0);
      w->ctrs[RTM_WAKES_FRAME]++;
      w->stg[RTS_OTHER] += (mono_ns() - it0) - acc;
      continue;  // stay hot while traffic flows
    }
    // idle: block on the transport inbox (frames and rt_inbox_kick both
    // wake it). Capped at 5ms — rt_inbox_kick is lock-free, so a kick
    // can (rarely) lose its wakeup; the cap bounds that race AND keeps
    // timer latency tight without burning idle CPU.
    int timeout_ms = (int)(timer_every * 1000.0);
    if (timeout_ms > 5) timeout_ms = 5;
    if (timeout_ms < 1) timeout_ms = 1;
    t0 = mono_ns();
    const int64_t tok =
        grouped
            ? recv_borrow_grp(c->tr, w->gid, sender, &fp, &flen, timeout_ms)
            : recv_borrow(c->tr, sender, &fp, &flen, timeout_ms);
    if (tok >= 0) {
      RTS_ADD(RTS_RECV_WAIT, mono_ns() - t0);
      t0 = mono_ns();
      const int32_t row = row_of(c, sender);
      if (row >= 0 && handle_frame(c, w, row, fp, flen, wall_s()))
        w->restep = 1;  // force a tick next iteration
      recv_release(c->tr, tok);
      RTS_ADD(RTS_INGEST, mono_ns() - t0);
      fr_rec(w, FRE_RT_WAKE, 1, 0, 0);
      w->ctrs[RTM_WAKES_FRAME]++;
    } else {
      RTS_ADD(RTS_IDLE, mono_ns() - t0);
      fr_rec(w, FRE_RT_WAKE, 2, 0, 0);
      w->ctrs[RTM_WAKES_IDLE]++;
    }
    w->stg[RTS_OTHER] += (mono_ns() - it0) - acc;
  }
  w->state.store(RTM_STOPPED, std::memory_order_release);
  uint64_t one = 1;
  (void)!write(c->event_fd, &one, 8);
}

// --- lifecycle / ABI --------------------------------------------------------

// dims: [S, n, R, me, dec_ring, native_apply, cmd_ring_cap, ev_ring_cap,
//        max_cmds_per_batch, max_cmd_size, workers]
//        (workers: shard-group worker threads; <= 1 or absent = the
//         single-thread runtime, byte-for-byte the round-8 behavior)
// ptrs: [rk_ctx, transport, sk_plane, next_slot, applied, in_flight,
//        votes_seen, tainted, last_progress, opened_at, ring_slot,
//        ring_val, kslot, kdecided, kdone, knewly, wal_ctx,
//        rk_ctx_1 .. rk_ctx_{workers-1}]
//        (wal_ctx: walkernel handle or 0 — the durability plane; the
//         extra rk handles are the per-worker tick contexts, already
//         range-restricted via rk_set_range by the bridge)
// fns:  FN_* order above
// fparams: [max_future_skew, max_age, phase_timeout, grace]
void* rtm_create(const int64_t* dims, const int64_t* ptrs, const int64_t* fns,
                 const uint8_t* uuids, const double* fparams) {
  RtmCtx* c = new RtmCtx();
  c->S = (int32_t)dims[0];
  c->n = (int32_t)dims[1];
  c->R = (int32_t)dims[2];
  c->me = (int32_t)dims[3];
  c->dec_ring = (int32_t)dims[4];
  c->native_apply = (int32_t)dims[5];
  const int64_t cmd_cap = dims[6] > 0 ? dims[6] : (8 << 20);
  const int64_t ev_cap = dims[7] > 0 ? dims[7] : (20 << 20);
  c->max_cmds = dims[8];
  c->max_cmd_size = dims[9];
  int32_t W = (int32_t)dims[10];
  if (W < 1) W = 1;
  if (W > 64) W = 64;
  if (W > c->n) W = c->n > 0 ? c->n : 1;
  c->W = W;
  c->chunk = (c->n + W - 1) / W;
  int i = 0;
  void* rk0 = (void*)ptrs[i++];
  c->tr = (void*)ptrs[i++];
  c->sk = (void*)ptrs[i++];
  c->next_slot = (int64_t*)ptrs[i++];
  c->applied = (int64_t*)ptrs[i++];
  c->in_flight = (uint8_t*)ptrs[i++];
  c->votes_seen = (int64_t*)ptrs[i++];
  c->tainted = (int64_t*)ptrs[i++];
  c->last_progress = (double*)ptrs[i++];
  c->opened_at = (double*)ptrs[i++];
  c->ring_slot = (int64_t*)ptrs[i++];
  c->ring_val = (int8_t*)ptrs[i++];
  c->kslot = (int32_t*)ptrs[i++];
  c->kdecided = (int8_t*)ptrs[i++];
  c->kdone = (uint8_t*)ptrs[i++];
  c->knewly = (uint8_t*)ptrs[i++];
  c->wal = (void*)ptrs[i++];
  for (int j = 0; j < FN_COUNT; j++) c->fns[j] = (void*)fns[j];
  if (!c->fns[FN_WAL_APPEND] || !c->fns[FN_WAL_BARRIER] ||
      !c->fns[FN_WAL_DURABLE])
    c->wal = nullptr;
  c->uuids.assign(uuids, uuids + (size_t)c->R * 16);
  c->max_future_skew = fparams[0];
  c->max_age = fparams[1];
  c->phase_timeout = fparams[2];
  c->grace = fparams[3];
  if (!c->native_apply) c->sk = nullptr;

  c->blk_pend_ref.assign(c->S, -1);
  c->blk_pend_pos.assign(c->S, 0);
  c->blk_pend_slot.assign(c->S, -1);
  c->blk_cur_ref.assign(c->S, -1);
  c->blk_cur_pos.assign(c->S, 0);
  c->sp_slot.assign(c->S, -1);
  c->sp_init.assign(c->S, 0);
  c->sp_frame.resize(c->S);
  c->stall_ev_at.assign(c->S, 0.0);
  c->votes_wait_at.assign(c->S, 0.0);
  c->bar_wait.assign(c->S, 0);
  c->event_fd = eventfd(0, EFD_NONBLOCK);

  for (int32_t g = 0; g < W; g++) {
    auto w = std::make_unique<RtmWorker>();
    w->gid = g;
    w->lo = (int64_t)g * c->chunk;
    w->hi = g == W - 1 ? (int64_t)c->n : (int64_t)(g + 1) * c->chunk;
    if (w->hi > c->n) w->hi = c->n;
    w->rk = g == 0 ? rk0 : (void*)ptrs[17 + (g - 1)];
    w->open_mask.assign(c->S, 0);
    w->open_slots.assign(c->S, 0);
    w->open_init.assign(c->S, 0);
    // outbound buffer: same sizing rule as NativeTick, with headroom
    w->out.resize((size_t)(4096 + 72 + 13 * (int64_t)c->n +
                           4 * (3 * 72 + 40 * (int64_t)c->n)));
    w->cmd.buf.resize((size_t)cmd_cap);
    w->ev.buf.resize((size_t)ev_cap);
    // scratch covers the whole ring: a record the push accepted must
    // always drain (a smaller scratch would wedge the command plane
    // behind the first oversized record)
    w->cmd_scratch.resize((size_t)cmd_cap);
    w->st_rows.assign(1024, 0);
    w->st_shards.assign(1024, 0);
    w->st_slots.assign(1024, 0);
    w->last_repair.assign(c->R, 0.0);
    memset(w->ctrs, 0, sizeof(w->ctrs));
    memset(w->stg, 0, sizeof(w->stg));
    memset(w->hist, 0, sizeof(w->hist));
    w->fr.resize(RTM_FLIGHT_CAP);
    c->workers.push_back(std::move(w));
  }
  return c;
}

// The transport classifier (rt_set_groups): pure, read-only, safe from
// the io thread while workers run.
uint64_t rtm_frame_group_mask(void* ctx, const uint8_t* data, uint32_t len) {
  return group_mask_of((const RtmCtx*)ctx, data, len);
}

int32_t rtm_workers(void* ctx) { return ((RtmCtx*)ctx)->W; }

// Shard-group geometry for the control plane: contiguous chunks of
// rtm_group_chunk(ctx) shards; group of shard s = min(s / chunk, W-1).
int64_t rtm_group_chunk(void* ctx) { return ((RtmCtx*)ctx)->chunk; }

int32_t rtm_start(void* ctx) {
  RtmCtx* c = (RtmCtx*)ctx;
  for (auto& w : c->workers) {
    RtmWorker* wp = w.get();
    wp->th = std::thread([c, wp] { rtm_loop(c, wp); });
  }
  return 0;
}

// Request a stop and join every worker. Each loop finishes its current
// iteration — decided waves already ingested complete their apply +
// event staging before the thread exits (mid-wave shutdown never loses
// staged result frames; the bridge drains the mailbox after this
// returns).
void rtm_stop(void* ctx) {
  RtmCtx* c = (RtmCtx*)ctx;
  c->stop_req.store(1, std::memory_order_relaxed);
  for (auto& w : c->workers)
    if (w->th.joinable()) w->th.join();
}

void rtm_destroy(void* ctx) {
  RtmCtx* c = (RtmCtx*)ctx;
  rtm_stop(c);
  if (c->event_fd >= 0) close(c->event_fd);
  delete c;
}

// Aggregate run state: STOPPED once every worker stopped, PAUSED once
// every worker parked (the pause barrier's completion signal — the
// bridge's pause() polls this), RUNNING otherwise.
int32_t rtm_state(void* ctx) {
  RtmCtx* c = (RtmCtx*)ctx;
  int32_t n_stop = 0, n_parked = 0;
  for (auto& w : c->workers) {
    const int32_t st = w->state.load(std::memory_order_acquire);
    if (st == RTM_STOPPED) {
      n_stop++;
      n_parked++;
    } else if (st == RTM_PAUSED) {
      n_parked++;
    }
  }
  const int32_t W = (int32_t)c->workers.size();
  if (n_stop == W) return RTM_STOPPED;
  if (n_parked == W) return RTM_PAUSED;
  return RTM_RUNNING;
}

void rtm_pause(void* ctx) {
  ((RtmCtx*)ctx)->pause_req.store(1, std::memory_order_release);
}

// release: the control plane mutates the shared consensus arrays
// (next_slot/applied/tainted/...) while every worker is parked in
// PAUSED; each worker's acquire load of pause_req in its park loop is
// the other half of the edge that makes those writes visible before it
// resumes ticking. (Was relaxed/relaxed — a real ordering bug the TSan
// stress cell flags on weakly-ordered machines.)
void rtm_resume(void* ctx) {
  ((RtmCtx*)ctx)->pause_req.store(0, std::memory_order_release);
}

int rtm_event_fd(void* ctx) { return ((RtmCtx*)ctx)->event_fd; }

// Producer half of the command rings, called from the Python control
// plane thread (the only producer). The control plane sees ONE command
// ring: records route to the owning worker's SPSC ring by the shard
// they carry (the bridge splits multi-shard records per group first).
// Returns 0 staged, -1 full.
int32_t rtm_cmd_push(void* ctx, const uint8_t* rec, int64_t len) {
  RtmCtx* c = (RtmCtx*)ctx;
  int32_t g = 0;
  if (c->W > 1 && len >= 1) {
    const uint8_t type = rec[0];
    int64_t s = -1;
    if (type == CMD_OPEN_SCALAR && len >= 5) {
      s = (int64_t)rd_u32(rec + 1);
    } else if (type == CMD_OPEN_WAVE && len >= 30) {
      s = (int64_t)rd_u32(rec + 26);  // first entry's shard
    } else if (type == CMD_ADVANCE && len >= 9) {
      s = (int64_t)rd_u32(rec + 5);  // first entry's shard
    } else if (type == CMD_DECIDE && len >= 5) {
      s = (int64_t)rd_u32(rec + 1);
    } else if (type == CMD_STOP) {
      // fan the stop out so every parked/blocked worker wakes
      c->stop_req.store(1, std::memory_order_relaxed);
      for (auto& w : c->workers)
        (void)w->cmd.push(rec, len, nullptr, 0);
      return 0;
    }
    if (s >= 0 && s < c->n) g = c->group_of(s);
  }
  return c->workers[(size_t)g]->cmd.push(rec, len, nullptr, 0) ? 0 : -1;
}

// Consumer half of the event mailboxes, called from the Python control
// plane thread (the only consumer). Drains every worker's ring into
// `out` ([u32 len][payload]... records back to back) — per-shard event
// order is per-worker order, which each SPSC ring preserves. Returns
// bytes written.
int64_t rtm_ev_drain(void* ctx, uint8_t* out, int64_t cap) {
  RtmCtx* c = (RtmCtx*)ctx;
  int64_t total = 0;
  for (auto& w : c->workers) {
    if (total >= cap) break;
    total += w->ev.drain(out + total, cap - total);
  }
  return total;
}

int32_t rtm_counters_version(void) { return RTM_COUNTERS_VERSION; }
int32_t rtm_counters_count(void) { return RTM_COUNT; }
void* rtm_counters(void* ctx) { return ((RtmCtx*)ctx)->workers[0]->ctrs; }
// per-worker counter blocks (same RTM_* geometry; the bridge sums at
// scrape and labels per-worker series)
void* rtm_counters_w(void* ctx, int32_t g) {
  RtmCtx* c = (RtmCtx*)ctx;
  if (g < 0 || (size_t)g >= c->workers.size()) return nullptr;
  return c->workers[(size_t)g]->ctrs;
}

// stage profiler block: RTS_COUNT u64 cumulative ns, index order RTS_*
int32_t rtm_stages_version(void) { return RTS_VERSION; }
int32_t rtm_stages_count(void) { return RTS_COUNT; }
void* rtm_stages(void* ctx) { return ((RtmCtx*)ctx)->workers[0]->stg; }
void* rtm_stages_w(void* ctx, int32_t g) {
  RtmCtx* c = (RtmCtx*)ctx;
  if (g < 0 || (size_t)g >= c->workers.size()) return nullptr;
  return c->workers[(size_t)g]->stg;
}

// SLO histogram block: RTH_STAGE_COUNT rows of RTH_BUCKETS bucket
// counts + total count + sum_ns (stride RTH_BUCKETS + 2), index order
// RTH_*. Bucket-geometry params are exported so the Python twin
// (obs.registry.SLO_BUCKETS) can be verified against the ABI.
int32_t rtm_hist_version(void) { return RTH_VERSION; }
int32_t rtm_hist_stages(void) { return RTH_STAGE_COUNT; }
int32_t rtm_hist_buckets(void) { return RTH_BUCKETS; }
int32_t rtm_hist_sub_bits(void) { return RTH_SUB_BITS; }
int32_t rtm_hist_min_exp(void) { return RTH_MIN_EXP; }
void* rtm_hist(void* ctx) { return ((RtmCtx*)ctx)->workers[0]->hist; }
void* rtm_hist_w(void* ctx, int32_t g) {
  RtmCtx* c = (RtmCtx*)ctx;
  if (g < 0 || (size_t)g >= c->workers.size()) return nullptr;
  return c->workers[(size_t)g]->hist;
}

int32_t rtm_flight_version(void) { return RTM_FLIGHT_VERSION; }
int32_t rtm_flight_cap(void) { return (int32_t)RTM_FLIGHT_CAP; }
int32_t rtm_flight_record_size(void) { return (int32_t)sizeof(FrEvent); }
void* rtm_flight(void* ctx) {
  return ((RtmCtx*)ctx)->workers[0]->fr.data();
}
uint64_t rtm_flight_head(void* ctx) {
  return ((RtmCtx*)ctx)->workers[0]->fr_head.load(std::memory_order_relaxed);
}
void* rtm_flight_w(void* ctx, int32_t g) {
  RtmCtx* c = (RtmCtx*)ctx;
  if (g < 0 || (size_t)g >= c->workers.size()) return nullptr;
  return c->workers[(size_t)g]->fr.data();
}
uint64_t rtm_flight_head_w(void* ctx, int32_t g) {
  RtmCtx* c = (RtmCtx*)ctx;
  if (g < 0 || (size_t)g >= c->workers.size()) return 0;
  return c->workers[(size_t)g]->fr_head.load(std::memory_order_relaxed);
}

}  // extern "C"
