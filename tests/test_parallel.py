"""Mesh execution tests on the virtual 8-device CPU mesh.

Covers the two device-plane modes of SURVEY.md §5.8 and the §7.4.6
conformance gate: vmap-simulated replicas (ClusterKernel) and mesh-axis
replicas with collectives (MeshPhaseKernel) must be decision-identical.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rabia_tpu.core.types import ABSENT, V0, V1
from rabia_tpu.kernel import ClusterKernel
from rabia_tpu.parallel import (
    MeshPhaseKernel,
    ShardedClusterKernel,
    make_mesh,
)


@pytest.fixture(scope="module")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, "conftest must provide 8 virtual CPU devices"
    return devs


class TestMakeMesh:
    def test_default_all_on_shard_axis(self, devices):
        m = make_mesh()
        assert m.shape == {"shard": 8, "replica": 1}

    def test_two_d(self, devices):
        m = make_mesh(shard_axis_size=2, replica_axis_size=4)
        assert m.shape == {"shard": 2, "replica": 4}

    def test_bad_factorization_rejected(self, devices):
        with pytest.raises(ValueError):
            make_mesh(shard_axis_size=3, replica_axis_size=3)


class TestShardedClusterKernel:
    def test_pipeline_matches_single_device(self, devices):
        S, R, T = 16, 3, 4
        votes = np.random.RandomState(0).choice(
            [V0, V1], size=(T, S, R)
        ).astype(np.int8)
        alive = jnp.ones((S, R), bool)

        plain = ClusterKernel(S, R, seed=11)
        d_plain, p_plain = plain.slot_pipeline(jnp.asarray(votes), alive, T)

        mesh = make_mesh(shard_axis_size=8, replica_axis_size=1)
        sharded = ShardedClusterKernel(S, R, mesh, seed=11)
        d_shard, p_shard = sharded.slot_pipeline(
            sharded.place_votes(jnp.asarray(votes)), alive, T
        )
        np.testing.assert_array_equal(np.asarray(d_plain), np.asarray(d_shard))
        np.testing.assert_array_equal(np.asarray(p_plain), np.asarray(p_shard))

    def test_state_is_actually_sharded(self, devices):
        mesh = make_mesh(shard_axis_size=8, replica_axis_size=1)
        k = ShardedClusterKernel(32, 3, mesh)
        st = k.init_state()
        assert len(st.phase.sharding.device_set) == 8

    def test_indivisible_shards_rejected(self, devices):
        mesh = make_mesh(shard_axis_size=8, replica_axis_size=1)
        with pytest.raises(ValueError):
            ShardedClusterKernel(12, 3, mesh)


class TestMeshPhaseKernel:
    def test_unanimous_v1_decides_first_phase(self, devices):
        S, R = 16, 4
        mesh = make_mesh(shard_axis_size=2, replica_axis_size=4)
        k = MeshPhaseKernel(S, R, mesh, seed=5)
        st = k.init_state(jnp.full((S, R), V1, jnp.int8))
        alive = k.place(jnp.ones((S, R), bool))
        st = k.phase_step(st, alive, k.shard_index_array())
        assert np.all(np.asarray(st.decided) == V1)

    def test_mixed_votes_terminate_and_agree(self, devices):
        S, R = 8, 4
        mesh = make_mesh(shard_axis_size=2, replica_axis_size=4)
        k = MeshPhaseKernel(S, R, mesh, seed=7)
        votes = np.random.RandomState(3).choice([V0, V1], size=(S, R)).astype(np.int8)
        st = k.init_state(jnp.asarray(votes))
        alive = k.place(jnp.ones((S, R), bool))
        idx = k.shard_index_array()
        for _ in range(12):
            st = k.phase_step(st, alive, idx)
        dec = np.asarray(st.decided)
        assert np.all(dec != ABSENT)
        # agreement: every replica of a shard decided the same value
        assert np.all(dec == dec[:, :1])

    def test_conformance_with_cluster_kernel(self, devices):
        """§7.4.6: mesh-collective replicas and vmap-simulated replicas must
        be decision-identical (same seed, fault-free, lockstep)."""
        S, R, T = 8, 4, 3
        seed = 23
        votes = np.random.RandomState(9).choice(
            [V0, V1], size=(T, S, R)
        ).astype(np.int8)

        plain = ClusterKernel(S, R, seed=seed)
        d_plain, _ = plain.slot_pipeline(
            jnp.asarray(votes), jnp.ones((S, R), bool), T, rounds_per_slot=16
        )

        mesh = make_mesh(shard_axis_size=2, replica_axis_size=4)
        k = MeshPhaseKernel(S, R, mesh, seed=seed)
        alive = k.place(jnp.ones((S, R), bool))
        idx = k.shard_index_array()
        mesh_decisions = []
        for t in range(T):
            st = k.init_state(jnp.asarray(votes[t]))
            st = st._replace(
                slot=k.place(jnp.full((S, R), t, jnp.int32))
            )
            for _ in range(16):
                st = k.phase_step(st, alive, idx)
            dec = np.asarray(st.decided)
            assert np.all(dec == dec[:, :1])
            mesh_decisions.append(dec[:, 0])
        np.testing.assert_array_equal(
            np.asarray(d_plain), np.stack(mesh_decisions)
        )

    def test_minority_crash_still_decides(self, devices):
        S, R = 8, 4
        mesh = make_mesh(shard_axis_size=2, replica_axis_size=4)
        k = MeshPhaseKernel(S, R, mesh, seed=1)
        st = k.init_state(jnp.full((S, R), V1, jnp.int8))
        alive_np = np.ones((S, R), bool)
        alive_np[:, 0] = False  # 1 of 4 crashed (f = 1)
        alive = k.place(jnp.asarray(alive_np))
        idx = k.shard_index_array()
        for _ in range(8):
            st = k.phase_step(st, alive, idx)
        dec = np.asarray(st.decided)
        assert np.all(dec[:, 1:] == V1)


class TestMeshSlotPipeline:
    def test_window_matches_cluster_kernel(self, devices):
        """The mesh slot pipeline (collective plane) decides the same
        values as the transport-plane ClusterKernel for mixed votes."""
        import numpy as np

        from rabia_tpu.kernel import ClusterKernel

        S, R, T = 16, 4, 6
        mesh = make_mesh(shard_axis_size=2, replica_axis_size=4)
        mk = MeshPhaseKernel(S, R, mesh, seed=5)
        rng = np.random.default_rng(0)
        votes = rng.choice(np.array([0, 1], np.int8), size=(T, S, R))
        alive = mk.place(jnp.ones((S, R), bool))
        decided = np.asarray(
            mk.slot_pipeline(jnp.asarray(votes), alive, T, max_phases=6)
        )
        assert (decided != 3).all(), "every slot decides within the window"
        ck = ClusterKernel(S, R, seed=5)
        ck_decided, _ = ck.slot_pipeline(
            jnp.asarray(votes), jnp.ones((S, R), bool), T, rounds_per_slot=12
        )
        ck_decided = np.asarray(ck_decided)
        # unanimous slots must agree exactly with the cluster kernel; mixed
        # slots may legitimately differ (different delivery interleavings
        # are both valid weak-MVC outcomes) but must still be concrete
        for t in range(T):
            for s in range(S):
                col = votes[t, :, :][s]
                if (col == col[0]).all():
                    assert decided[t, s] == col[0] == ck_decided[t, s]

    def test_window_with_crashed_minority(self, devices):
        S, R, T = 8, 4, 3
        mesh = make_mesh(shard_axis_size=2, replica_axis_size=4)
        mk = MeshPhaseKernel(S, R, mesh, seed=1)
        votes = jnp.ones((T, S, R), jnp.int8)
        alive_np = jnp.asarray(
            np.broadcast_to(np.array([True, True, True, False]), (S, R))
        )
        decided = np.asarray(
            mk.slot_pipeline(votes, mk.place(alive_np), T, max_phases=4)
        )
        assert (decided == 1).all()

    def test_slot_window_matches_slot_pipeline_uniform_base(self, devices):
        """slot_window with a uniform base is exactly slot_pipeline."""
        S, R, T = 8, 4, 5
        mesh = make_mesh(shard_axis_size=2, replica_axis_size=4)
        mk = MeshPhaseKernel(S, R, mesh, seed=9)
        votes = np.random.RandomState(4).choice(
            [V0, V1], size=(T, S, R)
        ).astype(np.int8)
        alive = mk.place(jnp.ones((S, R), bool))
        d_pipe = np.asarray(
            mk.slot_pipeline(
                jnp.asarray(votes), alive, T, max_phases=8, start_slot_index=7
            )
        )
        base = jnp.full((S,), 7, jnp.int32)
        d_win = np.asarray(
            mk.slot_window(
                jnp.asarray(votes), alive, base, n_slots=T, max_phases=8
            )
        )
        np.testing.assert_array_equal(d_pipe, d_win)

    def test_crash_mask_conformance_with_cluster_kernel(self, devices):
        """§7.4.6 under faults: per-shard crash masks (≤ f crashed) must
        leave the mesh plane decision-identical to the vmap plane on the
        same vote trace — crashed replicas' votes vanish from both
        tallies the same way."""
        S, R, T = 8, 4, 4
        seed = 31
        rng = np.random.RandomState(8)
        votes = rng.choice([V0, V1], size=(T, S, R)).astype(np.int8)
        # one crashed replica (f=1 for R=4), varying BY SHARD
        alive_np = np.ones((S, R), bool)
        for s in range(S):
            alive_np[s, rng.randint(R)] = False

        plain = ClusterKernel(S, R, seed=seed)
        d_plain, _ = plain.slot_pipeline(
            jnp.asarray(votes), jnp.asarray(alive_np), T, rounds_per_slot=16
        )

        mesh = make_mesh(shard_axis_size=2, replica_axis_size=4)
        mk = MeshPhaseKernel(S, R, mesh, seed=seed)
        d_mesh = np.asarray(
            mk.slot_pipeline(
                jnp.asarray(votes),
                mk.place(jnp.asarray(alive_np)),
                T,
                max_phases=16,
            )
        )
        d_plain = np.asarray(d_plain)
        assert (d_mesh != ABSENT).all()
        np.testing.assert_array_equal(d_plain, d_mesh)

    def test_lossy_cluster_agrees_with_mesh_on_unanimous_slots(self, devices):
        """Validity across planes: a slot with unanimous initial votes
        must decide that value on BOTH the lossy transport plane (30%
        loss) and the reliable collective plane. (Split-vote slots may
        legitimately differ between planes — different delivery orders
        are both valid weak-MVC runs — but must stay concrete and
        internally agreed.)"""
        S, R, T = 8, 4, 4
        seed = 13
        rng = np.random.RandomState(5)
        votes = rng.choice([V0, V1], size=(T, S, R)).astype(np.int8)
        votes[:, ::2, :] = V1  # even shards unanimous
        unanimous = np.zeros((T, S), bool)
        unanimous[:, ::2] = True

        mesh = make_mesh(shard_axis_size=2, replica_axis_size=4)
        mk = MeshPhaseKernel(S, R, mesh, seed=seed)
        d_mesh = np.asarray(
            mk.slot_pipeline(
                jnp.asarray(votes),
                mk.place(jnp.ones((S, R), bool)),
                T,
                max_phases=12,
            )
        )

        ck = ClusterKernel(S, R, seed=seed)
        alive = jnp.ones((S, R), bool)
        every = jnp.ones((S,), bool)
        decided = []
        st = ck.init_state()
        for t in range(T):
            st = ck.start_slot(st, every, jnp.asarray(votes[t]))
            st = st._replace(slot=jnp.full((S,), t, jnp.int32))
            st = ck.run_rounds(
                st, alive, 60, jax.random.key(100 + t), p_deliver=0.7
            )
            dec = np.asarray(st.decided)
            assert (dec != ABSENT).all(), "lossy run failed to terminate"
            decided.append(dec)
        d_lossy = np.stack(decided)
        np.testing.assert_array_equal(
            d_lossy[unanimous], d_mesh[unanimous]
        )
        assert (d_lossy[unanimous] == V1).all()

    def test_window_offsets_change_coin_stream(self, devices):
        """Successive windows must not reuse coin sequences: split votes
        decided at start_slot_index=0 vs =16 draw different coins (the
        decision patterns differ for at least one slot over enough
        samples)."""
        S, R, T = 8, 4, 8
        mesh = make_mesh(shard_axis_size=2, replica_axis_size=4)
        mk = MeshPhaseKernel(S, R, mesh, seed=3)
        # 2-2 split votes: every decision goes through the coin
        votes = np.zeros((T, S, R), np.int8)
        votes[:, :, :2] = 1
        alive = mk.place(jnp.ones((S, R), bool))
        d0 = np.asarray(mk.slot_pipeline(jnp.asarray(votes), alive, T, max_phases=8))
        d1 = np.asarray(
            mk.slot_pipeline(
                jnp.asarray(votes), alive, T, max_phases=8, start_slot_index=16
            )
        )
        assert (d0 != 3).all() and (d1 != 3).all()
        assert (d0 != d1).any(), "windows drew identical coin streams"
