"""C host-kernel step <-> numpy step bit-identity.

The C library (rabia_tpu/native/hostkernel.cpp) is the engine's
per-activation fast path; the numpy implementation in
kernel/host_driver.py remains the semantics owner (and itself carries a
bit-identity contract against the jitted NodeKernel, enforced by
tests/test_host_kernel.py — so this file transitively pins C == numpy ==
XLA). Random schedules cross every transition: slot starts, in-place
offer_votes ingest, inbox merges, decision adoption, quorum casts, phase
advances, and the portable lowbias32 common coin.
"""

from __future__ import annotations

import numpy as np
import pytest

from rabia_tpu.kernel.host_driver import HostNodeKernel
from rabia_tpu.native.build import load_hostkernel

pytestmark = pytest.mark.skipif(
    load_hostkernel() is None,
    reason="native hostkernel unavailable (no toolchain)",
)


def _pair(S: int, R: int, me: int, seed: int, p1: float):
    kc = HostNodeKernel(S, R, me=me, seed=seed, coin_p1=p1)
    kn = HostNodeKernel(S, R, me=me, seed=seed, coin_p1=p1)
    kn._native_lib = None  # force the numpy semantics owner
    assert kc._native() is not None
    return kc, kn


def _assert_same(sc, sn, oc, on, ctx) -> None:
    for f in sc._fields:
        assert np.array_equal(getattr(sc, f), getattr(sn, f)), (
            ctx, "state", f,
        )
    for f in oc._fields:
        assert np.array_equal(getattr(oc, f), getattr(on, f)), (
            ctx, "outbox", f,
        )


class TestNativeHostKernelParity:
    def test_differential_fuzz(self):
        rng = np.random.default_rng(11)
        for trial in range(60):
            S = int(rng.integers(1, 33))
            R = int(rng.choice([1, 2, 3, 4, 5, 7]))
            kc, kn = _pair(
                S, R,
                me=int(rng.integers(0, R)),
                seed=int(rng.integers(0, 2**31)),
                p1=float(rng.choice([0.5, 0.3, 1.0, 0.0])),
            )
            sc = kc.init_state()
            sn = kn.init_state()
            for step in range(24):
                if rng.random() < 0.5:
                    m = rng.random(S) < 0.3
                    sl = rng.integers(0, 100, S).astype(np.int32)
                    iv = rng.choice([0, 1], S).astype(np.int8)
                    sc = kc.start_slots(sc, m, sl, iv)
                    sn = kn.start_slots(sn, m, sl, iv)
                if rng.random() < 0.4:  # in-place offer_votes ingest
                    row = int(rng.integers(0, R))
                    rd = int(rng.choice([1, 2]))
                    sh = np.unique(rng.integers(0, S, 4)).astype(np.int64)
                    vo = rng.choice([0, 1, 2], len(sh)).astype(np.int8)
                    kc.offer_votes(sc, rd, row, sh, vo)
                    kn.offer_votes(sn, rd, row, sh, vo)
                ib1 = (
                    rng.choice(
                        [0, 1, 2, 3], (S, R), p=[0.2, 0.2, 0.1, 0.5]
                    ).astype(np.int8)
                    if rng.random() < 0.7
                    else None
                )
                ib2 = (
                    rng.choice(
                        [0, 1, 2, 3], (S, R), p=[0.2, 0.2, 0.1, 0.5]
                    ).astype(np.int8)
                    if rng.random() < 0.7
                    else None
                )
                dec = (
                    rng.choice([0, 1, 3], S, p=[0.05, 0.05, 0.9]).astype(
                        np.int8
                    )
                    if rng.random() < 0.5
                    else None
                )
                sc, oc = kc.node_step(sc, ib1, ib2, dec)
                sn, on = kn.node_step(sn, ib1, ib2, dec)
                _assert_same(sc, sn, oc, on, (trial, step))

    def test_coin_path_exercised(self):
        # all-V? round-2 quorum forces the common-coin branch: both
        # sides must flip identical lowbias32 bits per (shard,slot,phase)
        S, R = 8, 3
        kc, kn = _pair(S, R, me=0, seed=1234, p1=0.5)
        sc = kc.init_state()
        sn = kn.init_state()
        m = np.ones(S, bool)
        sl = np.arange(S, dtype=np.int32)
        iv = np.ones(S, np.int8)
        sc = kc.start_slots(sc, m, sl, iv)
        sn = kn.start_slots(sn, m, sl, iv)
        vq = np.full((S, R), 2, np.int8)
        # R1 quorum of V? -> cast R2=V?; R2 quorum of V? -> coin advance
        for ib1, ib2 in ((vq, None), (None, vq)):
            sc, oc = kc.node_step(sc, ib1, ib2)
            sn, on = kn.node_step(sn, ib1, ib2)
            _assert_same(sc, sn, oc, on, "coin")
        assert (sc.phase == 1).all()  # advanced via the coin
        assert np.isin(sc.my_r1, (0, 1)).all()

    def test_ping_pong_workspace_stability(self):
        # a returned state/outbox must stay intact across ONE further
        # node_step (the documented aliasing window)
        kc, _ = _pair(4, 3, me=1, seed=7, p1=0.5)
        st = kc.start_slots(
            kc.init_state(),
            np.ones(4, bool),
            np.zeros(4, np.int32),
            np.ones(4, np.int8),
        )
        ib = np.ones((4, 3), np.int8)
        st1, ob1 = kc.node_step(st, ib, None)
        snap = {f: getattr(st1, f).copy() for f in st1._fields}
        st2, _ = kc.node_step(st1, None, ib)
        for f in st1._fields:  # st1 untouched by the following step
            assert np.array_equal(getattr(st1, f), snap[f]), f
        assert st2 is not st1

    def test_open_scan_matches_numpy(self):
        lib = load_hostkernel()
        rng = np.random.default_rng(3)
        for _ in range(50):
            n = int(rng.integers(1, 64))
            next_slot = rng.integers(0, 50, n)
            applied = rng.integers(0, 50, n)
            in_flight = rng.random(n) < 0.5
            queue_len = rng.integers(0, 3, n)
            prop = rng.random(n) < 0.2
            dec = rng.random(n) < 0.2
            votes_seen = rng.integers(-1, 50, n)
            tainted = rng.integers(0, 2, n) * rng.integers(0, 20, n)
            head = np.zeros(n, np.int64)
            cand = np.zeros(n, np.uint8)
            cnt = lib.rk_open_scan(
                n,
                next_slot.ctypes.data, applied.ctypes.data,
                in_flight.ctypes.data, queue_len.ctypes.data,
                prop.ctypes.data, dec.ctypes.data,
                votes_seen.ctypes.data, tainted.ctypes.data,
                head.ctypes.data, cand.ctypes.data,
            )
            head_np = np.maximum(next_slot, applied)
            cand_np = ~in_flight & (
                (queue_len > 0)
                | prop
                | dec
                | (votes_seen >= head_np)
                | (tainted > 0)
            )
            assert np.array_equal(head, head_np)
            assert np.array_equal(cand.astype(bool), cand_np)
            assert cnt == int(cand_np.sum())

    def test_forced_python_env(self, monkeypatch):
        # RABIA_PY_HOSTKERNEL=1 must force the numpy step
        import rabia_tpu.native.build as build

        monkeypatch.setenv("RABIA_PY_HOSTKERNEL", "1")
        monkeypatch.setattr(build, "_HK_CACHED", None)
        assert build.load_hostkernel() is None
