"""Property tests of the weak-MVC Ivy invariants against the kernel.

Reference parity: docs/weak_mvc.ivy:190+ — the inductive invariants behind
Rabia's safety argument, checked here as executable properties on kernel
traces (SURVEY.md §4.4, C32):

- **agreement**: no two replicas decide different values for one instance;
- **validity**: a unanimous initial vote v is the only decidable value;
- **decision uniqueness/stability**: a shard's decision, once set, never
  changes in any later round;
- **round-2 coherence**: two non-? round-2 votes cast in the same phase
  carry the same value (weak_mvc.ivy's core lemma — their round-1
  majorities intersect);
- **no progress without quorum**: fewer than a majority of live replicas
  can never decide.

Each property is exercised under adversarial schedules: random initial
votes, Bernoulli delivery masks, crashed replicas, and static partitions.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rabia_tpu.core.types import ABSENT, V0, V1, VQUESTION, quorum_size
from rabia_tpu.kernel import ClusterKernel
from rabia_tpu.kernel.phase_driver import R2_WAIT

S, R = 12, 5  # shards x replicas for the stress grid


def _trace(kernel, state, alive, key, p_deliver, n_rounds):
    """Run round-by-round, yielding the state after each round."""
    states = []
    for i in range(n_rounds):
        k = jax.random.fold_in(key, i)
        base = jnp.ones((kernel.S, kernel.R, kernel.R), bool)
        if p_deliver < 1.0:
            base = base & jax.random.bernoulli(
                k, p_deliver, (kernel.S, kernel.R, kernel.R)
            )
        state = kernel.round_step(state, alive, base)
        states.append(state)
    return states


def _start(kernel, votes, active=None):
    active = (
        jnp.ones((kernel.S,), bool) if active is None else jnp.asarray(active)
    )
    return kernel.start_slot(kernel.init_state(), active, jnp.asarray(votes))


@pytest.mark.parametrize("seed", range(6))
class TestAgreementAndStability:
    def test_decision_stable_and_agreed(self, seed):
        rng = np.random.RandomState(seed)
        kernel = ClusterKernel(S, R, seed=seed)
        votes = rng.choice([V0, V1], size=(S, R)).astype(np.int8)
        alive_np = np.ones((S, R), bool)
        # crash a random minority per shard
        for s in range(S):
            k = rng.randint(0, quorum_size(R) - 1 + 1)  # 0..f
            alive_np[s, rng.choice(R, size=k, replace=False)] = False
        alive = jnp.asarray(alive_np)
        st = _start(kernel, votes)
        key = jax.random.key(seed + 1000)
        first_decided = np.full(S, ABSENT, np.int8)
        for snap in _trace(kernel, st, alive, key, 0.7, 60):
            dec = np.asarray(snap.decided)
            for s in range(S):
                if first_decided[s] == ABSENT and dec[s] != ABSENT:
                    first_decided[s] = dec[s]
                elif first_decided[s] != ABSENT:
                    # decision uniqueness/stability (ivy: decision is a
                    # function, never rewritten)
                    assert dec[s] == first_decided[s], (
                        f"shard {s} decision changed "
                        f"{first_decided[s]} -> {dec[s]}"
                    )
        # liveness under minority crash + 30% loss
        assert np.all(first_decided != ABSENT)
        assert np.all(np.isin(first_decided, (V0, V1)))


@pytest.mark.parametrize("value", [V0, V1])
@pytest.mark.parametrize("seed", range(3))
class TestValidity:
    def test_unanimous_value_is_only_outcome(self, value, seed):
        """weak_mvc.ivy validity: if every live replica starts with v, the
        only reachable decision is v — under loss AND minority crash."""
        rng = np.random.RandomState(seed)
        kernel = ClusterKernel(S, R, seed=seed)
        votes = np.full((S, R), value, np.int8)
        alive_np = np.ones((S, R), bool)
        alive_np[:, rng.choice(R, size=(R - 1) // 2, replace=False)] = False
        st = _start(kernel, votes)
        states = _trace(
            kernel, st, jnp.asarray(alive_np), jax.random.key(seed), 0.6, 80
        )
        dec = np.asarray(states[-1].decided)
        assert np.all(dec == value)


@pytest.mark.parametrize("seed", range(4))
class TestRound2Coherence:
    def test_same_phase_r2_votes_agree(self, seed):
        """Two non-? round-2 votes in one (shard, phase) must carry the same
        value (weak_mvc.ivy's majority-intersection lemma)."""
        rng = np.random.RandomState(seed)
        kernel = ClusterKernel(S, R, seed=seed)
        votes = rng.choice([V0, V1], size=(S, R)).astype(np.int8)
        alive = jnp.ones((S, R), bool)
        st = _start(kernel, votes)
        for snap in _trace(kernel, st, alive, jax.random.key(seed), 0.5, 50):
            phase = np.asarray(snap.phase)
            stage = np.asarray(snap.stage)
            r2 = np.asarray(snap.my_r2)
            for s in range(S):
                cast = (stage[s] == R2_WAIT) & np.isin(r2[s], (V0, V1))
                if cast.sum() < 2:
                    continue
                for ph in np.unique(phase[s][cast]):
                    vals = r2[s][cast & (phase[s] == ph)]
                    assert len(set(vals.tolist())) <= 1, (
                        f"shard {s} phase {ph}: conflicting R2 votes {vals}"
                    )


class TestNoQuorumNoProgress:
    @pytest.mark.parametrize("n_alive", [1, 2])
    def test_sub_quorum_never_decides(self, n_alive):
        """quorum_size(5) = 3: with <=2 live replicas nothing may ever
        decide, no matter how many rounds run."""
        kernel = ClusterKernel(S, R, seed=0)
        votes = np.full((S, R), V1, np.int8)
        alive_np = np.zeros((S, R), bool)
        alive_np[:, :n_alive] = True
        st = _start(kernel, votes)
        states = _trace(
            kernel, st, jnp.asarray(alive_np), jax.random.key(0), 1.0, 40
        )
        assert np.all(np.asarray(states[-1].decided) == ABSENT)

    def test_exact_quorum_decides(self):
        kernel = ClusterKernel(S, R, seed=0)
        votes = np.full((S, R), V1, np.int8)
        alive_np = np.zeros((S, R), bool)
        alive_np[:, : quorum_size(R)] = True
        st = _start(kernel, votes)
        states = _trace(
            kernel, st, jnp.asarray(alive_np), jax.random.key(0), 1.0, 10
        )
        assert np.all(np.asarray(states[-1].decided) == V1)


class TestPartitionSafety:
    @pytest.mark.parametrize("seed", range(3))
    def test_split_brain_impossible(self, seed):
        """A static partition into {0,1} | {2,3,4}: the minority side must
        never decide anything, and the majority's decisions must satisfy
        agreement when the partition heals."""
        rng = np.random.RandomState(seed)
        kernel = ClusterKernel(S, R, seed=seed)
        votes = rng.choice([V0, V1], size=(S, R)).astype(np.int8)
        groups = np.array([0, 0, 1, 1, 1])
        link_np = (groups[:, None] == groups[None, :])
        link = jnp.broadcast_to(jnp.asarray(link_np), (S, R, R))
        alive = jnp.ones((S, R), bool)
        st = _start(kernel, votes)
        # partitioned phase
        for i in range(30):
            st = kernel.round_step(st, alive, link)
        decided_mid = np.asarray(st.decided)
        done_mid = np.asarray(st.done)
        # minority replicas (rows 0,1) can never have decided
        assert not done_mid[:, :2].any()
        # heal; run to completion
        full = jnp.ones((S, R, R), bool)
        for i in range(40):
            st = kernel.round_step(st, alive, full)
        dec = np.asarray(st.decided)
        assert np.all(dec != ABSENT)
        # decisions reached during the partition must survive the heal
        healed_changed = (decided_mid != ABSENT) & (decided_mid != dec)
        assert not healed_changed.any()


class TestVQuestionNeverDecided:
    @pytest.mark.parametrize("seed", range(4))
    def test_question_is_not_a_decision_value(self, seed):
        """V? may be voted but never decided (ivy: decision(v) => v != vq)."""
        rng = np.random.RandomState(seed)
        kernel = ClusterKernel(S, R, seed=seed)
        votes = rng.choice([V0, V1], size=(S, R)).astype(np.int8)
        st = _start(kernel, votes)
        for snap in _trace(
            kernel, st, jnp.ones((S, R), bool), jax.random.key(seed), 0.8, 40
        ):
            dec = np.asarray(snap.decided)
            assert not np.any(dec == VQUESTION)
