"""Flight recorder + cross-replica commit trace gates (rabia_tpu/obs/flight).

- ring mechanics: Python ring bounds, deterministic batch-id/hash
  derivation, native-ring ABI agreement (record size, version);
- trace slicing: batch-hash + (shard, slot) join, transport-window
  inclusion;
- clock alignment: RTT-midpoint offset estimation and its error bound,
  per-replica order preservation through the merge;
- the acceptance end-to-end: `python -m rabia_tpu trace` against a
  3-replica TCP gateway cluster reconstructs one submitted command's
  timeline with every stage (submit, propose, per-peer R1/R2 votes,
  decide, apply, result) present and monotonically ordered after
  alignment — on the native tick path AND under RABIA_PY_TICK=1.
"""

from __future__ import annotations

import asyncio
import json
import os
import uuid

import pytest

from rabia_tpu.obs.flight import (
    FR_DTYPE,
    FRE_DECIDE,
    FlightRecorder,
    align_slice,
    batch_id_for,
    build_trace_slice,
    fr_hash,
    merge_slices,
    render_timeline,
    timeline_stages,
)


class TestRingMechanics:
    def test_python_ring_bounded_and_ordered(self):
        fr = FlightRecorder(cap=8)
        for i in range(20):
            fr.record(FRE_DECIDE, shard=0, slot=i, arg=1)
        assert len(fr) == 8
        assert fr.head == 20
        snap = fr.snapshot()
        assert [e["slot"] for e in snap] == list(range(12, 20))
        ts = [e["t_ns"] for e in snap]
        assert ts == sorted(ts)

    def test_batch_id_derivation_matches_gateway(self):
        """The trace collector names batches from (client_id, seq) with
        the same derivation the gateway uses — byte-identical ids."""
        from rabia_tpu.core.messages import Submit
        from rabia_tpu.gateway.server import GatewayServer

        cid = uuid.UUID(int=0x1234)
        p = Submit(client_id=cid, seq=7, shard=0, commands=(b"x",))
        batch = GatewayServer._deterministic_batch(p)
        assert batch.id.value == batch_id_for(cid, 7)
        # and the hash is stable (the ring join key)
        assert fr_hash(batch.id) == fr_hash(batch_id_for(cid, 7))

    def test_native_ring_abi(self):
        from rabia_tpu.native.build import load_hostkernel

        lib = load_hostkernel()
        if lib is None or not hasattr(lib, "rk_flight_record_size"):
            pytest.skip("native hostkernel unavailable")
        assert int(lib.rk_flight_record_size()) == FR_DTYPE.itemsize
        assert int(lib.rk_flight_version()) >= 1
        cap = int(lib.rk_flight_cap())
        assert cap > 0 and (cap & (cap - 1)) == 0  # power of two ring

    def test_transport_ring_abi(self):
        from rabia_tpu.native import load_library
        from rabia_tpu.obs.flight import TF_DTYPE

        lib = load_library()
        if not hasattr(lib, "rt_flight_record_size"):
            pytest.skip("native transport predates the flight ring")
        assert int(lib.rt_flight_record_size()) == TF_DTYPE.itemsize
        assert int(lib.rt_flight_version()) >= 1


class _FakeEngine:
    """flight_events()-only stand-in for build_trace_slice tests."""

    def __init__(self, events):
        self._events = events
        from rabia_tpu.core.types import NodeId

        self.node_id = NodeId.from_int(1)
        self.me = 0
        self._row_to_node = {0: NodeId.from_int(1)}

    def flight_events(self):
        return self._events


class TestTraceSlice:
    def _ev(self, t, kind, shard=0, slot=0, peer=0xFFFF, arg=0, batch=0):
        return {
            "t_ns": t, "kind": kind, "shard": shard, "slot": slot,
            "peer": peer, "arg": arg, "batch": batch,
        }

    def test_slice_joins_batch_and_slot(self):
        h = fr_hash(batch_id_for(uuid.UUID(int=5), 1))
        other = fr_hash(batch_id_for(uuid.UUID(int=5), 2))
        events = [
            self._ev(100, "submit", shard=0, batch=h),
            self._ev(150, "propose", shard=0, slot=3, batch=h),
            self._ev(160, "frame_in", shard=0, slot=3, peer=1, arg=2),
            self._ev(165, "route1", shard=0, slot=3, peer=1, arg=1),
            self._ev(170, "frame_in", shard=0, slot=4, peer=1, arg=2),
            self._ev(180, "decide", shard=0, slot=3, arg=1, batch=h),
            self._ev(185, "apply", shard=0, slot=3, arg=1, batch=h),
            self._ev(190, "submit", shard=0, batch=other),
            self._ev(200, "tf_in", arg=2),
            self._ev(999_999_999, "tf_out", arg=2),  # far outside window
        ]
        doc = build_trace_slice(_FakeEngine(events), h)
        kinds = [(e["kind"], e["slot"]) for e in doc["events"]]
        assert ("submit", 0) in kinds
        assert ("propose", 3) in kinds
        assert ("frame_in", 3) in kinds  # slot join pulled the vote in
        assert ("route1", 3) in kinds
        assert ("decide", 3) in kinds and ("apply", 3) in kinds
        assert ("tf_in", 0) in kinds  # in-window transport frame
        # excluded: the other batch's submit, the off-slot vote, the
        # out-of-window transport frame
        assert ("frame_in", 4) not in kinds
        assert ("tf_out", 0) not in kinds
        batches = {e["batch"] for e in doc["events"] if e["kind"] == "submit"}
        assert batches == {h}

    def test_align_and_merge_preserve_per_replica_order(self):
        sl_a = {
            "node": "a", "row": 0, "mono_ns": 1_000_000_000,
            "events": [
                self._ev(900_000_000, "submit"),
                self._ev(950_000_000, "decide"),
            ],
        }
        sl_b = {
            "node": "b", "row": 1, "mono_ns": 77_000_000_000,
            "events": [self._ev(76_940_000_000, "frame_in", peer=0)],
        }
        # replica a answered at collector wall 100.0 (rtt 2ms), replica b
        # at 100.5 (rtt 10ms): offsets differ wildly, order must survive
        align_slice(sl_a, 99.999, 100.001)
        align_slice(sl_b, 100.495, 100.505)
        assert abs(sl_a["err_s"] - 0.001) < 1e-9
        assert abs(sl_b["err_s"] - 0.005) < 1e-9
        merged = merge_slices([sl_a, sl_b])
        a_ts = [e["t"] for e in merged if e["node"] == "a"]
        assert a_ts == sorted(a_ts)
        # a's decide was 50ms before its serve time => ~99.95 aligned
        dec = next(e for e in merged if e["kind"] == "decide")
        assert abs(dec["t"] - 99.95) < 0.002
        assert "decide" in render_timeline(merged)

    def test_merge_requires_alignment(self):
        with pytest.raises(ValueError):
            merge_slices([{"node": "a", "row": 0, "events": []}])


@pytest.mark.asyncio
class TestEngineFlight:
    async def _commit_cluster(self, n=3):
        from rabia_tpu.core.config import RabiaConfig
        from rabia_tpu.core.network import ClusterConfig
        from rabia_tpu.core.state_machine import InMemoryStateMachine
        from rabia_tpu.core.types import Command, CommandBatch, NodeId
        from rabia_tpu.engine import RabiaEngine
        from rabia_tpu.net import InMemoryHub

        cfg = RabiaConfig(
            phase_timeout=2.0, heartbeat_interval=0.05, round_interval=0.001
        ).with_kernel(num_shards=1, shard_pad_multiple=1)
        hub = InMemoryHub()
        nodes = [NodeId.from_int(i + 1) for i in range(n)]
        engines = [
            RabiaEngine(
                ClusterConfig.new(nd, nodes), InMemoryStateMachine(),
                hub.register(nd), config=cfg,
            )
            for nd in nodes
        ]
        tasks = [asyncio.ensure_future(e.run()) for e in engines]
        for _ in range(300):
            await asyncio.sleep(0.01)
            if all(
                [(await e.get_statistics()).has_quorum for e in engines]
            ):
                break
        bids = []
        for i in range(3):
            batch = CommandBatch.new([Command.new(f"SET k{i} v".encode())])
            bids.append(batch.id)
            fut = await engines[0].submit_batch(batch)
            assert await asyncio.wait_for(fut, 15.0) == [b"OK"]
        return engines, tasks, bids

    async def _stop(self, engines, tasks):
        for e in engines:
            await e.shutdown()
        for t in tasks:
            t.cancel()
        await asyncio.gather(*tasks, return_exceptions=True)

    async def test_flight_events_merged_and_ordered(self):
        engines, tasks, bids = await self._commit_cluster()
        try:
            e0 = engines[0]
            evs = e0.flight_events()
            assert evs, "no flight events after commits"
            ts = [e["t_ns"] for e in evs]
            assert ts == sorted(ts)
            kinds = {e["kind"] for e in evs}
            assert {"submit", "decide", "apply"} <= kinds
            if e0._rk is not None:
                # the native ring carried the fast-path kinds
                assert e0._rk.flight_head() > 0
                assert {"frame_in", "open", "frame_out"} <= kinds
            # the submitted batches are joinable by hash
            h0 = fr_hash(bids[0])
            assert any(e["batch"] == h0 for e in evs)
            json.dumps(evs)  # dump-ready: plain types only
        finally:
            await self._stop(engines, tasks)

    async def test_dump_flight_env_gated(self, tmp_path, monkeypatch):
        engines, tasks, _ = await self._commit_cluster()
        try:
            e0 = engines[0]
            monkeypatch.delenv("RABIA_FLIGHT_DIR", raising=False)
            assert e0.dump_flight(reason="test") is None  # env unset: no-op
            monkeypatch.setenv("RABIA_FLIGHT_DIR", str(tmp_path))
            p = e0.dump_flight(reason="test")
            assert p is not None and os.path.exists(p)
            doc = json.loads(open(p).read())
            assert doc["reason"] == "test"
            assert doc["events"]
            # severe journal kinds trigger the auto-dump hook
            before = len(list(tmp_path.iterdir()))
            e0._last_flight_dump = 0.0
            e0.journal.record(e0.journal.STALE_STORM, row=1, entries=99)
            assert len(list(tmp_path.iterdir())) == before + 1
        finally:
            await self._stop(engines, tasks)


async def _run_gateway_trace(via_cli: bool) -> None:
    """The acceptance path: one client command through a 3-replica TCP
    gateway cluster, then a full cross-replica trace."""
    from rabia_tpu.apps.kvstore import encode_set_bin
    from rabia_tpu.gateway.client import RabiaClient
    from rabia_tpu.obs.flight import collect_trace
    from rabia_tpu.testing.gateway_cluster import GatewayCluster

    cluster = GatewayCluster(n_replicas=3, n_shards=2)
    await cluster.start()
    client = None
    try:
        client = RabiaClient(cluster.endpoints())
        await client.connect()
        resp = await client.submit(0, [encode_set_bin("tracer", "42")])
        assert resp
        addrs = [("127.0.0.1", g.port) for g in cluster.gateways]
        if via_cli:
            # the real console entry point (`python -m rabia_tpu trace`),
            # run on a worker thread so its asyncio.run gets its own loop
            from rabia_tpu.__main__ import main as cli_main

            rc = await asyncio.to_thread(
                cli_main,
                ["trace", *[f"{h}:{p}" for h, p in addrs],
                 "--client", str(client.client_id), "--seq", "1"],
            )
            assert rc == 0
            return
        merged = await collect_trace(addrs, client.client_id, 1)
        stages = timeline_stages(merged)

        # -- every stage present ----------------------------------------
        for stage in ("submit", "propose", "decide", "apply", "result"):
            assert stage in stages, f"stage {stage!r} missing: {sorted(stages)}"
        # per-peer R1/R2 votes: every vote frame consumed anywhere in the
        # cluster leaves a frame_in record tagged with its sender row —
        # both quorum voters must therefore appear for each round
        r1_rows = {
            e["peer"] for e in stages.get("frame_in", []) if e["arg"] == 2
        }
        r2_rows = {
            e["peer"] for e in stages.get("frame_in", []) if e["arg"] == 3
        }
        assert len(r1_rows) >= 2, f"R1 votes from {r1_rows} only"
        assert len(r2_rows) >= 2, f"R2 votes from {r2_rows} only"

        # -- monotonically ordered after clock alignment ----------------
        # the submitter replica (row 0) carries all five milestones on
        # ONE clock, so their aligned order must be exact
        def first(stage, row=0):
            return min(
                e["t"] for e in stages[stage] if e["row"] == row
            )

        t_submit = first("submit")
        t_propose = first("propose")
        t_decide = first("decide")
        t_apply = first("apply")
        t_result = first("result")
        assert t_submit <= t_propose <= t_decide <= t_apply <= t_result
        # peer vote events land between propose and decide within the
        # alignment error bound
        tol = max(e["err_s"] for e in merged) + 0.001
        for e in stages.get("frame_in", []):
            if e["arg"] in (2, 3) and e["slot"] == stages["decide"][0]["slot"]:
                assert t_submit - tol <= e["t"]
        # the merged list itself is time-sorted
        ts = [e["t"] for e in merged]
        assert ts == sorted(ts)
    finally:
        if client is not None:
            await client.close()
        await cluster.stop()


@pytest.mark.asyncio
class TestGatewayTrace:
    async def test_trace_reconstructs_commit_timeline(self):
        await _run_gateway_trace(via_cli=False)

    async def test_trace_cli_end_to_end(self):
        await _run_gateway_trace(via_cli=True)

    async def test_trace_python_tick_path(self, monkeypatch):
        """The equivalent Python-side ring: the same timeline must
        reconstruct with the native tick forced off."""
        monkeypatch.setenv("RABIA_PY_TICK", "1")
        await _run_gateway_trace(via_cli=False)


@pytest.mark.asyncio
class TestRuntimeFlight:
    async def test_runtime_kinds_complete_the_timeline(self, tmp_path,
                                                       monkeypatch):
        """With the GIL-free engine runtime owning the commit path (a
        persistence-free TCP cluster), the merged flight timeline must
        still carry the full lifecycle PLUS the runtime's own kinds —
        rt_wake (thread wakeups) and rt_handoff (mailbox events) — and
        they must survive into a dump file."""
        import json as _json

        from rabia_tpu.apps import make_sharded_kv
        from rabia_tpu.apps.kvstore import encode_set_bin
        from rabia_tpu.core.config import RabiaConfig, TcpNetworkConfig
        from rabia_tpu.core.network import ClusterConfig
        from rabia_tpu.core.types import Command, CommandBatch, NodeId
        from rabia_tpu.engine import RabiaEngine
        from rabia_tpu.native.build import load_runtime
        from rabia_tpu.net.tcp import TcpNetwork

        if load_runtime() is None:
            pytest.skip("native runtime library unavailable")
        ids = [NodeId.from_int(i + 1) for i in range(3)]
        nets = [TcpNetwork(i, TcpNetworkConfig(bind_port=0)) for i in ids]
        for i in range(3):
            for j in range(3):
                if i != j:
                    nets[i].add_peer(ids[j], "127.0.0.1", nets[j].port)
        cfg = RabiaConfig(
            phase_timeout=2.0, heartbeat_interval=0.05
        ).with_kernel(num_shards=2, shard_pad_multiple=2)
        engines, tasks = [], []
        for i, n in enumerate(ids):
            e = RabiaEngine(
                ClusterConfig.new(n, ids), make_sharded_kv(2)[0], nets[i],
                config=cfg,
            )
            engines.append(e)
            tasks.append(asyncio.ensure_future(e.run()))
        try:
            for _ in range(500):
                await asyncio.sleep(0.01)
                if all(
                    [(await e.get_statistics()).has_quorum for e in engines]
                ):
                    break
            e0 = engines[0]
            assert e0._rtm is not None, "runtime inactive on a TCP cluster"
            fut = await e0.submit_batch(
                CommandBatch.new(
                    [Command.new(encode_set_bin("fk", "fv"))], shard=0
                ),
                shard=0,
            )
            await asyncio.wait_for(fut, 10.0)
            kinds = {ev["kind"] for ev in e0.flight_events()}
            assert "rt_wake" in kinds, sorted(kinds)
            assert "rt_handoff" in kinds, sorted(kinds)
            # the full commit lifecycle is still present alongside
            assert {"submit", "propose", "decide", "apply"} <= kinds
            monkeypatch.setenv("RABIA_FLIGHT_DIR", str(tmp_path))
            p = e0.dump_flight(reason="runtime-test")
            doc = _json.loads(open(p).read())
            dumped = {ev["kind"] for ev in doc["events"]}
            assert "rt_wake" in dumped and "rt_handoff" in dumped
        finally:
            for e in engines:
                await e.shutdown()
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            for n in nets:
                await n.close()
