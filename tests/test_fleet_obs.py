"""Fleet observability plane tests (obs/fleet_obs.py, round 18).

Four tiers: pure derived-metric math against hand-computed counter
deltas; the SLO burn-rate watchdog's fire/quiet/edge-trigger semantics;
the aggregator + cross-tier trace against a live in-process fleet
(ring discovery, scrape alignment, trace-hop ordering across a MOVED
redirect); and the fleet-top CLI / chaos-profile plumbing.
"""

from __future__ import annotations

import asyncio
import json
import shutil

import pytest

from rabia_tpu.obs.fleet_obs import (
    BurnRateWatchdog,
    FleetAggregator,
    SLOPolicy,
    collect_fleet_trace,
    derive_fleet_sample,
    derive_gateway_figures,
    discover_fleet,
    render_fleet_table,
    shard_coalesce_figures,
)
from rabia_tpu.obs.journal import AnomalyJournal


def _shard_metrics(per_shard: dict) -> dict:
    """Hand-build a parsed-metrics dict from {shard: {field: value}}."""
    out = {}
    for shard, fields in per_shard.items():
        for fld, v in fields.items():
            out[
                f'rabia_coalesce_shard_total{{field="{fld}",'
                f'shard="{shard}"}}'
            ] = float(v)
    return out


class TestDerivedFigures:
    def test_shard_figures_sum_only_named_shards(self):
        m = _shard_metrics({
            0: {"waves": 4, "covered": 12, "results_ok": 16},
            1: {"waves": 2, "covered": 2, "results_ok": 2},
            2: {"waves": 100, "covered": 900, "results_ok": 1000},
        })
        fig = shard_coalesce_figures(m, [0, 1])
        assert fig["waves"] == 6.0
        assert fig["covered"] == 14.0
        assert fig["results_ok"] == 18.0
        assert fig["solo"] == 0.0  # absent key reads as zero

    def test_gateway_figures_match_hand_math(self):
        m = _shard_metrics({
            0: {"waves": 10, "covered": 30, "scalar": 2,
                "results_ok": 32},
        })
        fig = derive_gateway_figures([0], [m])
        assert fig["coalesce_density"] == 3.0  # 30 / 10
        assert fig["slots_per_op"] == round(12 / 32, 6)

    def test_gateway_figures_delta_against_prev(self):
        prev = _shard_metrics({0: {"waves": 10, "covered": 30}})
        cur = _shard_metrics({0: {"waves": 15, "covered": 50}})
        fig = derive_gateway_figures([0], [cur], [prev])
        assert fig["waves"] == 5.0
        assert fig["covered"] == 20.0
        assert fig["coalesce_density"] == 4.0

    def test_zero_denominators_derive_none_not_perfection(self):
        fig = derive_gateway_figures([0], [_shard_metrics({})])
        assert fig["coalesce_density"] is None
        assert fig["slots_per_op"] is None

    def test_figures_sum_across_replicas(self):
        a = _shard_metrics({0: {"waves": 3, "covered": 6}})
        b = _shard_metrics({0: {"waves": 1, "covered": 6}})
        fig = derive_gateway_figures([0], [a, b])
        assert fig["waves"] == 4.0
        assert fig["coalesce_density"] == 3.0


def _ring_doc(names):
    from rabia_tpu.core.types import NodeId
    from rabia_tpu.fleet import HashRing, RingMember

    ring = HashRing(vnodes=8)
    for i, name in enumerate(names):
        ring.add(RingMember(
            name=name, host="127.0.0.1", port=50000 + i,
            node=NodeId.from_int(2000 + i),
        ))
    return ring.to_doc(), ring


def _scrape(t, metrics=None, stats=None, sessions=0):
    return {
        "metrics": metrics or {},
        "health": {"sessions": sessions, "stats": stats or {}},
        "t": t,
        "err_s": 0.001,
    }


class TestDeriveFleetSample:
    def test_rates_and_aggregate_from_hand_built_scrapes(self):
        doc, ring = _ring_doc(["gw0", "gw1"])
        rep0 = {
            **_shard_metrics({s: {"waves": 0, "covered": 0,
                                  "results_ok": 0} for s in range(4)}),
            "rabia_wal_fsyncs_total": 10.0,
            "rabia_gateway_reads_total": 100.0,
            "rabia_engine_reads_probe_total": 100.0,
        }
        prev = derive_fleet_sample(
            doc, 4,
            {"gw0": _scrape(100.0, stats={"submits": 0}),
             "gw1": _scrape(100.0, stats={"submits": 0})},
            [_scrape(100.0, metrics=rep0)],
        )
        rep1 = {
            **_shard_metrics({s: {"waves": 2, "covered": 8,
                                  "results_ok": 10} for s in range(4)}),
            "rabia_wal_fsyncs_total": 14.0,
            "rabia_gateway_reads_total": 140.0,
            "rabia_engine_reads_probe_total": 130.0,
        }
        cur = derive_fleet_sample(
            doc, 4,
            {"gw0": _scrape(110.0, stats={"submits": 200}),
             "gw1": _scrape(110.0, stats={"submits": 100})},
            [_scrape(110.0, metrics=rep1)],
            prev=prev,
        )
        assert cur["interval_s"] == pytest.approx(10.0)
        # every shard moved identically, so density is 4.0 regardless
        # of which shards each gateway owns
        for name in ("gw0", "gw1"):
            g = cur["gateways"][name]
            assert g["owned_shards"] == ring.owned_shards(name, 4)
            if g["waves"] > 0:
                assert g["coalesce_density"] == 4.0
        assert cur["gateways"]["gw0"]["submits_per_s"] == 20.0
        agg = cur["aggregate"]
        assert agg["waves"] == 8.0
        assert agg["fsyncs_per_result"] == pytest.approx(4 / 40)
        assert agg["offcons_fraction"] == pytest.approx(30 / 40)

    def test_unreachable_member_marked_stale(self):
        doc, _ = _ring_doc(["gw0", "gw1"])
        cur = derive_fleet_sample(
            doc, 4,
            {"gw0": _scrape(5.0), "gw1": None},
            [_scrape(5.0)],
        )
        assert cur["stale_members"] == ["gw1"]
        assert cur["gateways"]["gw1"] == {"stale": True}
        # and the table renders the corpse instead of hiding it
        table = render_fleet_table(cur)
        assert "UNREACHABLE" in table
        assert "gw0" in table

    def test_first_sample_has_no_rates(self):
        doc, _ = _ring_doc(["gw0"])
        cur = derive_fleet_sample(doc, 4, {"gw0": _scrape(5.0)}, [])
        assert cur["interval_s"] is None
        assert "submits_per_s" not in cur["gateways"]["gw0"]
        assert "first sample" in render_fleet_table(cur)


class TestBurnRateWatchdog:
    POLICY = SLOPolicy(fast_window_s=2.0, slow_window_s=8.0)

    def _feed(self, wd, rows):
        fired = []
        for t, sample in rows:
            fired.extend(wd.observe(t, sample))
        return fired

    def test_quiet_on_healthy_run(self):
        wd = BurnRateWatchdog(self.POLICY)
        fired = self._feed(wd, [
            (float(t), {"ok": 100.0 * t, "errors": 0.0,
                        "members_alive": 3, "members_total": 3})
            for t in range(12)
        ])
        assert fired == []
        v = wd.verdict()
        assert v["quiet"] is True
        assert v["samples"] == 12

    def test_slo_burn_fires_once_per_episode_and_rearms(self):
        wd = BurnRateWatchdog(self.POLICY)
        rows = []
        ok = errors = 0.0
        for t in range(10):  # healthy preamble spans both windows
            ok += 100.0
            rows.append((float(t), {"ok": ok, "errors": errors}))
        for t in range(10, 20):  # 50% error rate >> 1% budget
            ok += 50.0
            errors += 50.0
            rows.append((float(t), {"ok": ok, "errors": errors}))
        fired = self._feed(wd, rows)
        assert fired == [AnomalyJournal.SLO_BURN]  # edge, not level
        # recovery clears the episode...
        for t in range(20, 40):
            ok += 100.0
            rows = [(float(t), {"ok": ok, "errors": errors})]
            assert self._feed(wd, rows) == []
        assert wd.verdict()["active"] == []
        # ...and a second incident is a second episode
        for t in range(40, 50):
            ok += 50.0
            errors += 50.0
            if self._feed(
                wd, [(float(t), {"ok": ok, "errors": errors})]
            ):
                break
        assert wd.verdict()["fired"][AnomalyJournal.SLO_BURN] == 2

    def test_burn_needs_minimum_volume(self):
        wd = BurnRateWatchdog(self.POLICY)
        # 100% errors but only ~2 ops per window: below min_ops
        fired = self._feed(wd, [
            (float(t), {"ok": 0.0, "errors": 0.2 * t})
            for t in range(15)
        ])
        assert fired == []

    def test_coalesce_density_drop(self):
        wd = BurnRateWatchdog(self.POLICY)
        rows = []
        waves = covered = 0.0
        for t in range(10):  # density 4.0
            waves += 5.0
            covered += 20.0
            rows.append((float(t), {"waves": waves, "covered": covered}))
        for t in range(10, 14):  # density collapses to 1.0
            waves += 5.0
            covered += 5.0
            rows.append((float(t), {"waves": waves, "covered": covered}))
        fired = self._feed(wd, rows)
        assert AnomalyJournal.COALESCE_DENSITY_DROP in fired

    def test_read_lane_demoted(self):
        wd = BurnRateWatchdog(self.POLICY)
        rows = []
        reads = offcons = 0.0
        for t in range(8):  # all reads off-consensus
            reads += 50.0
            offcons += 50.0
            rows.append((float(t), {"reads": reads,
                                    "reads_offcons": offcons}))
        for t in range(8, 12):  # lane demoted: probes stop
            reads += 50.0
            rows.append((float(t), {"reads": reads,
                                    "reads_offcons": offcons}))
        fired = self._feed(wd, rows)
        assert AnomalyJournal.READ_LANE_DEMOTED in fired

    def test_ring_stale_gauge_fires_and_journal_records(self):
        wd = BurnRateWatchdog(self.POLICY)
        assert wd.observe(
            0.0, {"members_alive": 3, "members_total": 3}
        ) == []
        fired = wd.observe(
            1.0,
            {"members_alive": 2, "members_total": 3,
             "stale_members": ["gw1"]},
        )
        assert fired == [AnomalyJournal.RING_STALE]
        entries = wd.journal.snapshot(kind=AnomalyJournal.RING_STALE)
        assert entries and entries[-1]["stale"] == ["gw1"]
        # watchdog kinds page via verdict, not the SEVERE dump path
        assert AnomalyJournal.RING_STALE not in AnomalyJournal.SEVERE

    def test_verdict_shape(self):
        wd = BurnRateWatchdog(self.POLICY)
        wd.observe(0.0, {"members_alive": 1, "members_total": 2})
        v = wd.verdict()
        assert v["quiet"] is False
        assert v["fired"] == {AnomalyJournal.RING_STALE: 1}
        assert v["episodes"][0]["kind"] == AnomalyJournal.RING_STALE
        assert v["active"] == [AnomalyJournal.RING_STALE]


class TestChaosPlumbing:
    def test_profiles_declare_and_scale_expect_watchdog(self):
        from rabia_tpu.chaos.profiles import default_profiles

        by_name = default_profiles()
        for name in ("routed_gateway_failover", "coalesce_flap_restart"):
            p = by_name[name]
            assert "ring_stale" in p.expect_watchdog
            assert p.scaled(0.5).expect_watchdog == p.expect_watchdog


@pytest.mark.asyncio
async def test_aggregator_and_trace_against_live_fleet(tmp_path):
    """Integration: ring discovery, two-tier scrape + derived figures,
    and a cross-tier trace whose hops stay ordered across a MOVED
    redirect. Pure-Python engine plane (persistence off) so the full
    submit→propose→decide→apply lifecycle carries the batch hash."""
    from rabia_tpu.apps.kvstore import encode_set_bin
    from rabia_tpu.core.messages import ResultStatus
    from rabia_tpu.fleet.harness import FleetHarness, FleetSession

    h = FleetHarness(n_gateways=2, n_shards=4, persistence=False)
    await h.start()
    try:
        agg = FleetAggregator(("127.0.0.1", h.gateways[0].port))
        inv = await agg.refresh()
        assert [n for n, _h, _p in inv["members"]] == ["gw0", "gw1"]
        assert inv["n_shards"] == 4
        assert len(inv["upstreams"]) == 3  # the replica tier
        await agg.sample()

        # a submit that starts with a poisoned ring view: wrong owner
        # answers MOVED, the re-sent seq lands on the true owner
        shard = 0
        owner, succ = h.gateways[0].ring.successors(shard, 2)
        resolver = h.resolver()
        wrong = next(
            g for g in h.gateways if g.config.name != owner.name
        )
        resolver.note_moved(shard, ("127.0.0.1", wrong.port))
        sess = FleetSession(h.ser, resolver, call_timeout=10.0)
        res = await sess.submit(shard, [encode_set_bin("obs", "1")])
        assert res.status == ResultStatus.OK
        assert sess.redirects >= 1
        # background traffic so every gateway's figures have deltas
        other = FleetSession(h.ser, h.resolver(), call_timeout=10.0)
        for i in range(8):
            await other.submit(
                i % 4, [encode_set_bin(f"bg{i}", "v")]
            )
        await asyncio.sleep(0.3)  # ledger replication is post-Result

        doc = await agg.sample()
        assert doc["stale_members"] == []
        for name in ("gw0", "gw1"):
            g = doc["gateways"][name]
            assert g["owned_shards"]
            assert g["results_ok"] >= 0
        assert doc["aggregate"]["results_ok"] >= 9.0
        table = render_fleet_table(doc)
        assert "gw0" in table and "-- fleet" in table

        merged = await collect_fleet_trace(
            [("127.0.0.1", g.port) for g in h.gateways],
            [("127.0.0.1", g.port) for g in h.cluster.gateways],
            sess.client_id, 1,
        )
        kinds = [e["kind"] for e in merged]
        for stage in ("fleet_recv", "fleet_moved", "fleet_fwd",
                      "submit", "decide", "apply", "result",
                      "fleet_result", "fleet_ledger_send"):
            assert stage in kinds, f"missing {stage} in {sorted(kinds)}"
        ts = [e["t"] for e in merged]
        assert ts == sorted(ts)

        def first(kind):
            return next(e["t"] for e in merged if e["kind"] == kind)

        # the MOVED hop precedes the owner's forward precedes the relay
        assert first("fleet_moved") < first("fleet_fwd")
        assert first("fleet_fwd") < first("fleet_result")
        # both tiers answered: fleet slices carry their tier tag
        tiers = {e.get("tier", "replica") for e in merged}
        assert tiers == {"fleet", "replica"}
    finally:
        await h.stop()
        if h.cluster.wal_dir:
            shutil.rmtree(h.cluster.wal_dir, ignore_errors=True)


@pytest.mark.asyncio
async def test_fleet_top_cli_smoke(tmp_path, capsys):
    """`python -m rabia_tpu fleet-top --json --out` against a live
    fleet: last-sample JSON on stdout, full series in the out file."""
    from rabia_tpu import __main__ as cli
    from rabia_tpu.fleet.harness import FleetHarness

    h = FleetHarness(n_gateways=2, n_shards=4, persistence=False)
    await h.start()
    try:
        out = tmp_path / "fleet_top.json"
        # _fleet_top runs its own sampling loop synchronously via
        # asyncio.run, so drive the coroutine body directly here
        agg = FleetAggregator(
            ("127.0.0.1", h.gateways[0].port), timeout=10.0
        )
        await agg.refresh()
        await agg.sample()
        await asyncio.sleep(0.05)
        doc = await agg.sample()
        series = agg.series()
        out.write_text(json.dumps({"version": 1, "series": series}))
        assert json.loads(out.read_text())["series"][-1]["t"] == doc["t"]
        assert len(series) == 2
        assert series[-1]["interval_s"] > 0
        # the argparse wiring exists and names the knobs
        assert cli._fleet_top is not None
    finally:
        await h.stop()
        if h.cluster.wal_dir:
            shutil.rmtree(h.cluster.wal_dir, ignore_errors=True)
