"""Kernel correctness: the vectorized phase driver vs the scalar oracle.

Three layers (SURVEY.md §4.4's strengthened strategy):
1. step-for-step conformance of ``ClusterKernel.round_step`` against
   ``WeakMVCOracle.step`` under identical delivery masks and the *same*
   device coin — every field, every step;
2. Ivy-invariant property tests (agreement/validity) on the kernel directly
   under random loss/crash masks;
3. ``NodeKernel`` (per-node, inbox/outbox) driven by a host-side router must
   reach the same decisions as the cluster kernel.
"""

import functools
import random

import numpy as np
import pytest

from rabia_tpu.core.oracle import WeakMVCOracle
from rabia_tpu.core.types import ABSENT, V0, V1, VQUESTION

import jax
import jax.numpy as jnp

from rabia_tpu.kernel.phase_driver import (
    ClusterKernel,
    NodeKernel,
    R1_WAIT,
    R2_WAIT,
    device_coin,
    pack_phase,
    unpack_phase,
)


@functools.lru_cache(maxsize=None)
def _coin(seed, shard, slot, phase):
    return device_coin(seed, shard, slot, phase)


def oracle_coin(seed, shard, slot=0):
    return lambda phase: _coin(seed, shard, slot, phase)


def _start(kernel, initial):
    state = kernel.init_state()
    votes = jnp.asarray(initial, jnp.int8)
    return kernel.start_slot(state, jnp.ones((kernel.S,), bool), votes)


class TestFaultFreeKernel:
    @pytest.mark.parametrize("R", [3, 5, 7])
    def test_unanimous_v1_decides_in_two_rounds(self, R):
        S = 16
        k = ClusterKernel(S, R, seed=0)
        state = _start(k, np.full((S, R), V1))
        full = jnp.ones((S, R, R), bool)
        alive = jnp.ones((S, R), bool)
        state = k.round_step(state, alive, full)
        assert not np.any(np.asarray(state.decided) != ABSENT)
        state = k.round_step(state, alive, full)
        assert np.all(np.asarray(state.decided) == V1)
        assert np.all(np.asarray(state.decided_phase) == 0)
        assert np.all(np.asarray(state.done))

    def test_unanimous_v0_decides_v0(self):
        S, R = 8, 5
        k = ClusterKernel(S, R, seed=0)
        state = _start(k, np.full((S, R), V0))
        state = k.run_rounds(state, jnp.ones((S, R), bool), 2, jax.random.key(0))
        assert np.all(np.asarray(state.decided) == V0)

    def test_slot_pipeline_throughput_path(self):
        S, R, T = 32, 5, 4
        k = ClusterKernel(S, R, seed=3)
        votes = jnp.full((T, S, R), V1, jnp.int8)
        decided, dphase = k.slot_pipeline(votes, jnp.ones((S, R), bool), T)
        assert decided.shape == (T, S)
        assert np.all(np.asarray(decided) == V1)
        assert np.all(np.asarray(dphase) == 0)

    def test_slot_pipeline_wide_bit_identical(self):
        # the batched (vmap-over-slots) pipeline must reproduce the
        # sequential scan exactly — random votes, crash masks, odd sizes
        rng = np.random.default_rng(3)
        S, R, T = 17, 5, 8
        k = ClusterKernel(S, R, seed=9)
        votes = jnp.asarray(rng.choice([0, 1], size=(T, S, R)).astype(np.int8))
        alive = jnp.asarray(rng.random((S, R)) > 0.25)
        d1, p1 = k.slot_pipeline(
            votes, alive, T, rounds_per_slot=6, start_slot_index=3
        )
        d2, p2 = k.slot_pipeline_wide(
            votes, alive, T, rounds_per_slot=6, start_slot_index=3, block=4
        )
        assert np.array_equal(np.asarray(d1), np.asarray(d2))
        assert np.array_equal(np.asarray(p1), np.asarray(p2))
        with pytest.raises(ValueError, match="multiple"):
            k.slot_pipeline_wide(votes, alive, T, block=3)

    def test_slot_pipeline_fused_bit_identical(self):
        # the fused (closed-form / Pallas) fault-free window must match the
        # scanned general machinery exactly: random votes over ALL four
        # codes, random crash masks, varied R incl. even clusters and R=1
        rng = np.random.default_rng(11)
        for S, R in [(8, 1), (16, 3), (24, 4), (128, 5), (32, 7)]:
            k = ClusterKernel(S, R, seed=S + R)
            T = 8
            votes = jnp.asarray(
                rng.choice([0, 1, 2, 3], size=(T, S, R),
                           p=[0.3, 0.4, 0.15, 0.15]).astype(np.int8)
            )
            alive = jnp.asarray(rng.random((S, R)) > 0.3)
            d1, p1 = k.slot_pipeline(votes, alive, T)
            # closed-form XLA path
            d2, p2 = k.slot_pipeline_fused(votes, alive, T, use_pallas=False)
            assert np.array_equal(np.asarray(d1), np.asarray(d2)), (S, R)
            assert np.array_equal(np.asarray(p1), np.asarray(p2)), (S, R)
            # Pallas kernel (interpreter mode on CPU)
            d3, p3 = k.slot_pipeline_fused(votes, alive, T, interpret=True)
            assert np.array_equal(np.asarray(d1), np.asarray(d3)), (S, R)
            assert np.array_equal(np.asarray(p1), np.asarray(p3)), (S, R)
            # replica-major entry (the bandwidth-shaped production path):
            # same decisions from [R,T,S] votes, with and without the
            # derivable phase plane, on both the XLA and Pallas paths
            votes_rm = jnp.transpose(votes, (2, 0, 1))
            alive_rm = jnp.transpose(alive, (1, 0))
            for kw in ({"use_pallas": False}, {"interpret": True}):
                d4, p4 = k.slot_pipeline_fused_rmajor(
                    votes_rm, alive_rm, T, **kw
                )
                assert np.array_equal(np.asarray(d1), np.asarray(d4)), (S, R)
                assert np.array_equal(np.asarray(p1), np.asarray(p4)), (S, R)
                d5 = k.slot_pipeline_fused_rmajor(
                    votes_rm, alive_rm, T, want_phase=False, **kw
                )
                assert np.array_equal(np.asarray(d1), np.asarray(d5)), (S, R)

    def test_minority_crash_still_decides(self):
        S, R = 8, 5
        k = ClusterKernel(S, R, seed=1)
        alive = jnp.asarray(
            np.broadcast_to(np.array([False, False, True, True, True]), (S, R))
        )
        state = _start(k, np.full((S, R), V1))
        state = k.run_rounds(state, alive, 4, jax.random.key(0))
        assert np.all(np.asarray(state.decided) == V1)
        done = np.asarray(state.done)
        assert np.all(done[:, 2:])

    def test_majority_crash_no_progress(self):
        S, R = 4, 3
        k = ClusterKernel(S, R, seed=1)
        alive = jnp.asarray(np.broadcast_to(np.array([True, False, False]), (S, R)))
        state = _start(k, np.full((S, R), V1))
        state = k.run_rounds(state, alive, 20, jax.random.key(0))
        assert np.all(np.asarray(state.decided) == ABSENT)

    def test_inactive_shards_untouched(self):
        S, R = 8, 3
        k = ClusterKernel(S, R, seed=0)
        state = k.init_state()
        mask = np.zeros((S,), bool)
        mask[::2] = True
        votes = jnp.full((S, R), V1, jnp.int8)
        state = k.start_slot(state, jnp.asarray(mask), votes)
        state = k.run_rounds(state, jnp.ones((S, R), bool), 2, jax.random.key(0))
        decided = np.asarray(state.decided)
        assert np.all(decided[::2] == V1)
        assert np.all(decided[1::2] == ABSENT)


class TestOracleConformance:
    """round_step must be WeakMVCOracle.step, vectorized — field for field."""

    @pytest.mark.parametrize(
        "R,p,seed",
        [(3, 1.0, 0), (3, 0.6, 1), (5, 0.6, 2), (5, 0.35, 3), (4, 0.5, 4), (7, 0.6, 5)],
    )
    def test_stepwise_conformance(self, R, p, seed):
        S, T = 4, 30
        rng = np.random.default_rng(seed)
        initial = rng.integers(0, 2, size=(S, R))
        alive_np = np.ones((S, R), bool)
        if seed % 2:
            alive_np[:, 0] = False  # one crashed replica

        k = ClusterKernel(S, R, seed=seed)
        state = _start(k, initial)
        oracles = [
            WeakMVCOracle(
                R,
                list(initial[s]),
                oracle_coin(seed, s),
                alive=list(alive_np[s]),
            )
            for s in range(S)
        ]
        alive = jnp.asarray(alive_np)

        masks = rng.random((T, S, R, R)) < p
        for t in range(T):
            state = k.round_step(state, alive, jnp.asarray(masks[t]))
            for s in range(S):
                m = masks[t, s]
                oracles[s].step(lambda i, j, m=m: bool(m[i, j]))
            self._compare(state, oracles, alive_np, t)

    @staticmethod
    def _compare(state, oracles, alive_np, t):
        phase = np.asarray(state.phase)
        stage = np.asarray(state.stage)
        my_r1 = np.asarray(state.my_r1)
        my_r2 = np.asarray(state.my_r2)
        done = np.asarray(state.done)
        decided = np.asarray(state.decided)
        dphase = np.asarray(state.decided_phase)
        for s, o in enumerate(oracles):
            kd = None if decided[s] == ABSENT else int(decided[s])
            assert kd == o.decided_value, f"step {t} shard {s}: decided {kd} vs {o.decided_value}"
            kp = None if dphase[s] < 0 else int(dphase[s])
            assert kp == o.decided_phase, f"step {t} shard {s}: decided_phase {kp} vs {o.decided_phase}"
            for r, node in enumerate(o.nodes):
                if not alive_np[s, r]:
                    continue
                ctx = f"step {t} shard {s} replica {r}"
                assert done[s, r] == (node.decided is not None), ctx
                if node.decided is not None:
                    continue  # frozen replicas may hold stale fields
                assert phase[s, r] == node.phase, ctx
                assert stage[s, r] == node.stage, ctx
                assert my_r1[s, r] == node.my_r1, ctx
                assert my_r2[s, r] == node.my_r2, ctx


class TestKernelProperties:
    @pytest.mark.parametrize("seed", range(6))
    def test_agreement_validity_under_loss(self, seed):
        rng = np.random.default_rng(100 + seed)
        S, R, T = 8, 5, 120
        initial = rng.integers(0, 2, size=(S, R))
        k = ClusterKernel(S, R, seed=seed)
        state = _start(k, initial)
        alive = jnp.ones((S, R), bool)
        key = jax.random.key(seed)
        state = k.run_rounds(state, alive, T, key, p_deliver=0.55)
        decided = np.asarray(state.decided)
        done = np.asarray(state.done)
        # liveness: with 120 lossy rounds everything should be decided
        assert np.all(decided != ABSENT)
        assert np.all(done)
        assert np.all(decided != VQUESTION)
        # validity per-shard
        for s in range(S):
            if np.all(initial[s] == V1):
                assert decided[s] == V1
            if np.all(initial[s] == V0):
                assert decided[s] == V0

    def test_static_partition_blocks_minority_then_heals(self):
        S, R = 4, 5
        k = ClusterKernel(S, R, seed=9)
        initial = np.full((S, R), V1)
        state = _start(k, initial)
        alive = jnp.ones((S, R), bool)
        # partition {0,1} | {2,3,4}
        part = np.ones((R, R), bool)
        for i in range(R):
            for j in range(R):
                if (i < 2) != (j < 2):
                    part[i, j] = False
        state = k.run_rounds(
            state, alive, 6, jax.random.key(0), link_mask=jnp.asarray(part[None])
        )
        decided = np.asarray(state.decided)
        done = np.asarray(state.done)
        assert np.all(decided == V1)  # majority side decides
        assert not np.any(done[:, :2])  # minority side still dark
        # heal
        state = k.run_rounds(state, alive, 2, jax.random.key(1))
        assert np.all(np.asarray(state.done))


class TestNodeKernelRouter:
    """NodeKernel × R with a host router == ClusterKernel decisions."""

    @pytest.mark.parametrize("R,seed", [(3, 0), (5, 1)])
    def test_full_delivery_matches_cluster(self, R, seed):
        S = 4
        rng = np.random.default_rng(seed)
        initial = rng.integers(0, 2, size=(S, R)).astype(np.int8)

        nodes = [NodeKernel(S, R, me=i, seed=seed) for i in range(R)]
        states = [n.init_state() for n in nodes]
        # buffers[(shard, slot, phase)] = {"r1": {sender: v}, "r2": {...}}
        buffers: dict = {}
        decisions_wire: dict[int, int] = {}  # shard -> value

        def buf(s, slot, ph):
            return buffers.setdefault((s, slot, ph), {"r1": {}, "r2": {}})

        mask = jnp.ones((S,), bool)
        slot_idx = jnp.zeros((S,), jnp.int32)
        for i, n in enumerate(nodes):
            states[i] = n.start_slots(states[i], mask, slot_idx, jnp.asarray(initial[:, i]))
            for s in range(S):
                buf(s, 0, 0)["r1"][i] = int(initial[s, i])

        for _ in range(30):
            if all(bool(np.all(np.asarray(st.done))) for st in states):
                break
            new_states = []
            outs = []
            old_phases = []
            for i, n in enumerate(nodes):
                st = states[i]
                # snapshot BEFORE the step: node_step donates its input
                # state, so its buffers are dead afterwards on device
                # backends
                phase = np.asarray(st.phase)
                slot = np.asarray(st.slot)
                old_phases.append(phase)
                in1 = np.full((S, R), ABSENT, np.int8)
                in2 = np.full((S, R), ABSENT, np.int8)
                dec = np.full((S,), ABSENT, np.int8)
                for s in range(S):
                    b = buffers.get((s, int(slot[s]), int(phase[s])))
                    if b:
                        for snd, v in b["r1"].items():
                            in1[s, snd] = v
                        for snd, v in b["r2"].items():
                            in2[s, snd] = v
                    if s in decisions_wire:
                        dec[s] = decisions_wire[s]
                st2, out = n.node_step(
                    st, jnp.asarray(in1), jnp.asarray(in2), jnp.asarray(dec)
                )
                new_states.append(st2)
                outs.append(out)
            # route outboxes (full delivery)
            for i, (st2, out) in enumerate(zip(new_states, outs)):
                slot = np.asarray(st2.slot)
                cast = np.asarray(out.cast_r2)
                r2v = np.asarray(out.r2_vals)
                adv = np.asarray(out.advanced)
                r1v = np.asarray(out.new_r1)
                nph = np.asarray(out.new_phase)
                nd = np.asarray(out.newly_decided)
                dv = np.asarray(out.decided_vals)
                oph = old_phases[i]  # phase before the step
                for s in range(S):
                    if cast[s]:
                        buf(s, int(slot[s]), int(oph[s]))["r2"][i] = int(r2v[s])
                    if adv[s]:
                        buf(s, int(slot[s]), int(nph[s]))["r1"][i] = int(r1v[s])
                    if nd[s]:
                        decisions_wire[s] = int(dv[s])
            states = new_states

        for st in states:
            assert np.all(np.asarray(st.done)), "liveness: all nodes decide"
        vals = np.stack([np.asarray(st.decided) for st in states])
        # agreement across nodes
        assert np.all(vals == vals[0])
        # conformance with the cluster kernel under the same full delivery
        k = ClusterKernel(S, R, seed=seed)
        cs = _start(k, initial)
        cs = k.run_rounds(cs, jnp.ones((S, R), bool), 30, jax.random.key(0))
        assert np.all(np.asarray(cs.decided) == vals[0])


class TestCoin:
    def test_device_coin_common_and_deterministic(self):
        a = device_coin(5, 2, 1, 3)
        b = device_coin(5, 2, 1, 3)
        assert a == b and a in (V0, V1)

    def test_device_coin_spreads(self):
        vals = {device_coin(0, s, 0, p) for s in range(4) for p in range(8)}
        assert vals == {V0, V1}

    def test_phase_packing(self):
        assert unpack_phase(pack_phase(123, 45)) == (123, 45)
        assert pack_phase(1, 0) > pack_phase(0, 65535)


class TestStages:
    def test_stage_constants(self):
        assert R1_WAIT == 0 and R2_WAIT == 1
