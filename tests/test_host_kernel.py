"""HostNodeKernel (numpy) ⟷ NodeKernel (JAX) bit-identity conformance.

The engine may run either implementation (host arrays for CPU round
pacing, device arrays for TPU); decisions must be identical — same
contract as the vmap/mesh conformance gate (SURVEY.md §7.4.6).
"""

from __future__ import annotations

import numpy as np
import pytest

from rabia_tpu.core.types import ABSENT, V0, V1
from rabia_tpu.kernel.host_driver import HostNodeKernel
from rabia_tpu.kernel.phase_driver import NodeKernel, device_coin, _coin_bits


def _assert_state_equal(a, b, where=""):
    """a: JAX NodeState ([S,R] ledgers); b: HostNodeState ([R,S] ledgers)."""
    for f in a._fields:
        av, bv = np.asarray(getattr(a, f)), np.asarray(getattr(b, f))
        if f in ("led1", "led2"):
            bv = bv.T
        assert np.array_equal(av, bv), f"{where}: field {f} diverged"


def _assert_outbox_equal(a, b, where=""):
    for f in a._fields:
        av, bv = np.asarray(getattr(a, f)), np.asarray(getattr(b, f))
        assert np.array_equal(av, bv), f"{where}: outbox {f} diverged"


def _random_round(rng, S, R, p_absent=0.45):
    """Random (garbage-laden) inboxes: valid votes, ABSENT, and an
    out-of-range code (7) that must be ignored identically by both
    kernels."""
    choices = np.array([ABSENT, V0, V1, 7], np.int8)
    probs = [p_absent, (1 - p_absent) / 2.5, (1 - p_absent) / 2.5, (1 - p_absent) / 5]
    in1 = rng.choice(choices, size=(S, R), p=probs)
    in2 = rng.choice(choices, size=(S, R), p=probs)
    dec = rng.choice(
        np.array([ABSENT, ABSENT, ABSENT, V1], np.int8), size=(S,)
    )
    return in1, in2, dec


class TestCoinPortability:
    def test_numpy_and_jax_coins_identical(self):
        import jax.numpy as jnp

        rng = np.random.default_rng(0)
        shard = rng.integers(0, 10000, 256).astype(np.int32)
        slot = rng.integers(0, 100000, 256).astype(np.int32)
        phase = rng.integers(0, 64, 256).astype(np.int32)
        for seed in (0, 7, 123456):
            a = _coin_bits(seed, shard, slot, phase, 0.5, xp=np)
            b = np.asarray(_coin_bits(seed, jnp.asarray(shard), jnp.asarray(slot), jnp.asarray(phase), 0.5))
            assert np.array_equal(a, b)

    def test_coin_is_fair_ish(self):
        vals = [device_coin(3, s, sl, p) for s in range(8) for sl in range(8) for p in range(8)]
        frac = sum(1 for v in vals if v == V1) / len(vals)
        assert 0.4 < frac < 0.6

    def test_coin_bias_parameter(self):
        ones = [device_coin(1, s, 0, p, p1=0.99) for s in range(64) for p in range(4)]
        assert sum(1 for v in ones if v == V1) > 0.9 * len(ones)


class TestHostKernelConformance:
    @pytest.mark.parametrize("R", [3, 5, 7])
    def test_randomized_rounds_bit_identical(self, R):
        S = 32
        seed = 11
        jk = NodeKernel(S, R, me=1, seed=seed)
        hk = HostNodeKernel(S, R, me=1, seed=seed)
        js, hs = jk.init_state(), hk.init_state()
        _assert_state_equal(js, hs, "init")

        rng = np.random.default_rng(42)
        slot_counter = np.zeros(S, np.int64)
        for step in range(30):
            # periodically (re)start slots on a random subset
            if step % 5 == 0:
                mask = rng.random(S) < 0.7
                init = rng.choice(np.array([V0, V1], np.int8), size=S)
                slot_counter[mask] += 1
                slots = slot_counter.astype(np.int32)
                js = jk.start_slots(js, mask, slots, init)
                hs = hk.start_slots(hs, mask, slots, init)
                _assert_state_equal(js, hs, f"start@{step}")
            in1, in2, dec = _random_round(rng, S, R)
            js, job = jk.node_step(js, in1, in2, dec)
            hs, hob = hk.node_step(hs, in1, in2, dec)
            _assert_state_equal(js, hs, f"step@{step}")
            _assert_outbox_equal(job, hob, f"step@{step}")

    def test_clean_two_round_decision(self):
        """All-V1 unanimous inboxes decide V1 in two rounds, both kernels."""
        S, R = 8, 5
        hk = HostNodeKernel(S, R, me=0, seed=0)
        st = hk.init_state()
        st = hk.start_slots(
            st, np.ones(S, bool), np.zeros(S, np.int32), np.full(S, V1, np.int8)
        )
        full1 = np.full((S, R), V1, np.int8)
        absent = np.full((S, R), ABSENT, np.int8)
        no_dec = np.full(S, ABSENT, np.int8)
        st, ob = hk.node_step(st, full1, absent, no_dec)
        assert bool(np.all(ob.cast_r2)) and bool(np.all(ob.r2_vals == V1))
        full2 = np.full((S, R), V1, np.int8)
        st, ob = hk.node_step(st, absent, full2, no_dec)
        assert bool(np.all(ob.newly_decided))
        assert bool(np.all(st.decided == V1))
