"""Critical-path decomposition gates (rabia_tpu/obs/critpath).

- segment math against hand-built flight captures: exact tiling of the
  full fleet->gateway->consensus->durability pipeline, MOVED redirect
  hops, overlapping-ring advance dedup + contiguous-chain cutoff,
  missing-mark honesty (unattributed, never a neighbouring segment),
  cross-node clock reorder clamping;
- slowlog reservoir mechanics: bounded slowest-first windows, floor
  fast path, rotation retention, exemplar age stamps;
- dwell-histogram geometry: the native RK_DWELL block's exported
  geometry must equal the registry's SLO bucket constants (the
  decomposer's consensus segments sit next to those rows);
- the acceptance end-to-end: a live 3-replica TCP gateway cluster's
  slowlog exemplars decompose in-process with bounded unattributed
  time, `python -m rabia_tpu slowlog` serves the same view, and the
  dwell metric family exposes identical label sets on the native and
  ``RABIA_PY_TICK=1`` planes.
"""

from __future__ import annotations

import asyncio
import time

import pytest

from rabia_tpu.obs.critpath import (
    PHASE_CLAMP,
    SEGMENT_ORDER,
    CritpathAggregator,
    decompose,
    decompose_exemplars,
    dominant_segment,
    inprocess_exemplar_timeline,
    render_slowlog,
    render_waterfall,
    segment_names,
)

MS = 1e-3


def ev(kind, t, row=0, shard=0, slot=5, arg=0, truncated=False,
       err_s=0.0):
    """One merged-timeline entry, the shape ``merge_slices`` emits."""
    return {
        "kind": kind, "t": t, "t_ns": int(t * 1e9), "row": row,
        "shard": shard, "slot": slot, "arg": arg,
        "truncated": truncated, "err_s": err_s, "node": f"n{row}",
    }


def full_pipeline_timeline():
    """A hand-built capture of the whole path: fleet tier with one
    MOVED redirect (two forward hops), coalesced gateway drive, a
    3-phase decide, WAL barrier, fleet relay and ledger replication.
    Segment values are chosen so the tiling is exact and distinct."""
    return [
        ev("fleet_recv", 0.000),
        ev("fleet_moved", 0.001),
        ev("fleet_fwd", 0.002),
        ev("fleet_fwd", 0.004),        # last hop ends fleet_routing
        ev("gw_recv", 0.006, arg=1),   # arg=1: coalesced drive
        ev("submit", 0.010),
        ev("propose", 0.0105),         # binds proposer row 0, slot 5
        ev("open", 0.011),
        ev("advance", 0.013, arg=1),
        ev("advance", 0.014, arg=2),
        ev("step_decide", 0.016),
        ev("apply", 0.018),
        ev("barrier", 0.022),
        ev("result", 0.024),
        ev("fleet_result", 0.026),
        ev("fleet_ledger_send", 0.030),
    ]


class TestSegmentMath:
    def test_full_pipeline_tiles_exactly(self):
        d = decompose(full_pipeline_timeline(), wall_s=0.031)
        assert d["ok"] and not d["truncated"]
        s = d["segments"]
        assert s["fleet_routing"] == pytest.approx(4 * MS)
        assert s["gateway_queue"] == pytest.approx(2 * MS)
        assert s["coalesce_park"] == pytest.approx(4 * MS)
        assert s["propose_to_open"] == pytest.approx(1 * MS)
        assert s["consensus_phase_1"] == pytest.approx(2 * MS)
        assert s["consensus_phase_2"] == pytest.approx(1 * MS)
        # step_decide closes the FINAL phase: 2 advances + 1
        assert s["consensus_phase_3"] == pytest.approx(2 * MS)
        assert d["phases_to_decide"] == 3
        assert s["decide_to_apply"] == pytest.approx(2 * MS)
        assert s["fsync_barrier"] == pytest.approx(4 * MS)
        # barrier -> result plus the result -> fleet relay
        assert s["result_fanout"] == pytest.approx(4 * MS)
        assert s["ledger_replication"] == pytest.approx(4 * MS)
        assert d["total_s"] == pytest.approx(30 * MS)
        assert d["unattributed_s"] == pytest.approx(0.0)
        assert d["moved_hops"] == 1
        assert d["coalesced"] is True
        assert d["proposer_row"] == 0 and d["slot"] == [0, 5]
        assert sum(s.values()) == pytest.approx(d["total_s"])

    def test_uncoalesced_no_fleet_no_barrier(self):
        """Single-gateway, WAL off: recv->submit counts as queue (not
        park), fanout anchors on apply, fleet segments absent."""
        tl = [
            ev("gw_recv", 0.000, arg=0),
            ev("submit", 0.003),
            ev("propose", 0.0035),
            ev("open", 0.004),
            ev("step_decide", 0.006),   # 1-phase decide, no advance
            ev("apply", 0.007),
            ev("result", 0.009),
        ]
        d = decompose(tl)
        s = d["segments"]
        assert s["gateway_queue"] == pytest.approx(3 * MS)
        assert "coalesce_park" not in s
        assert s["consensus_phase_1"] == pytest.approx(2 * MS)
        assert d["phases_to_decide"] == 1
        assert "fsync_barrier" not in s
        assert s["result_fanout"] == pytest.approx(2 * MS)
        for absent in ("fleet_routing", "ledger_replication"):
            assert absent not in s
        assert d["unattributed_s"] == pytest.approx(0.0)

    def test_missing_mark_goes_unattributed(self):
        """Dropping the open mark must not fold the spanned time into a
        neighbouring segment — it becomes explicit unattributed."""
        tl = [e for e in full_pipeline_timeline()
              if e["kind"] not in ("open", "advance")]
        d = decompose(tl)
        s = d["segments"]
        assert "propose_to_open" not in s
        assert not any(k.startswith("consensus_phase") for k in s)
        # submit(10ms) -> step_decide(16ms) is now unaccounted
        assert d["unattributed_s"] == pytest.approx(6 * MS)
        assert d["total_s"] == pytest.approx(30 * MS)
        assert sum(s.values()) + d["unattributed_s"] == pytest.approx(
            d["total_s"]
        )

    def test_clock_reorder_clamps_never_negative(self):
        """A cross-node mark aligned EARLIER than its causal
        predecessor collapses that segment to zero; the tiling stays
        exact (no negative time, no double counting)."""
        tl = full_pipeline_timeline()
        for e in tl:
            if e["kind"] == "barrier":
                e["t"] = 0.017  # before apply (0.018): skewed clock
                e["err_s"] = 0.002
        d = decompose(tl)
        s = d["segments"]
        assert s["fsync_barrier"] == pytest.approx(0.0)
        assert all(v >= 0.0 for v in s.values())
        assert d["err_s"] == pytest.approx(0.002)
        assert sum(s.values()) + d["unattributed_s"] == pytest.approx(
            d["total_s"]
        )

    def test_overlapping_rings_dedup_and_contiguity(self):
        """Overlapping rings can retain the same logical advance twice
        (dedup keeps the first) and can DROP a boundary (the chain cuts
        at the gap — an orphaned tail would mis-label dwell)."""
        tl = full_pipeline_timeline()
        tl.insert(9, ev("advance", 0.0135, arg=1))  # duplicate ordinal
        d = decompose(tl)
        assert d["segments"]["consensus_phase_1"] == pytest.approx(
            2 * MS
        )
        assert d["phases_to_decide"] == 3
        # now a gap: advances 1 and 3 observed, 2 lost to a wrap
        tl2 = [e for e in full_pipeline_timeline()
               if not (e["kind"] == "advance" and e["arg"] == 2)]
        tl2.insert(9, ev("advance", 0.015, arg=3))
        d2 = decompose(tl2)
        segs = [k for k in d2["segments"]
                if k.startswith("consensus_phase")]
        # only the contiguous prefix (phase 1) plus the closing phase
        assert "consensus_phase_1" in segs
        assert "consensus_phase_3" not in segs
        assert d2["phases_to_decide"] == 2

    def test_foreign_row_marks_ignored(self):
        """Consensus marks from non-proposer rows (every replica runs
        the slot) must not contaminate the proposer's chain."""
        tl = full_pipeline_timeline()
        tl.append(ev("advance", 0.0132, row=1, arg=1))
        tl.append(ev("step_decide", 0.0155, row=2))
        d = decompose(tl)
        assert d["segments"]["consensus_phase_1"] == pytest.approx(
            2 * MS
        )
        assert d["segments"]["consensus_phase_3"] == pytest.approx(
            2 * MS
        )

    def test_truncated_ring_display_not_aggregate(self):
        tl = full_pipeline_timeline()
        tl[0]["truncated"] = True
        d = decompose(tl)
        assert d["ok"] and d["truncated"]
        agg = CritpathAggregator()
        assert agg.add(d) is False
        assert agg.truncated_total == 1
        assert agg.summary()["segments"] == {}
        d2 = decompose(full_pipeline_timeline())
        assert agg.add(d2) is True
        assert agg.summary()["segments"][
            "consensus_phase_1"
        ] == pytest.approx(2 * MS)
        # the waterfall still renders truncated exemplars, with the
        # warning attached
        assert "ring wrapped" in render_waterfall(d)

    def test_empty_timeline_not_ok(self):
        d = decompose([])
        assert d["ok"] is False
        assert dominant_segment(d) is None
        agg = CritpathAggregator()
        assert agg.add(d) is False
        assert agg.unanchored_total == 1

    def test_segment_name_universe(self):
        names = segment_names()
        assert names[-1] == "unattributed"
        for base in SEGMENT_ORDER:
            if base == "consensus":
                continue
            assert base in names
        for p in range(1, PHASE_CLAMP):
            assert f"consensus_phase_{p}" in names
        assert f"consensus_phase_{PHASE_CLAMP}+" in names
        assert f"consensus_phase_{PHASE_CLAMP}" not in names

    def test_dominant_includes_unattributed(self):
        tl = [e for e in full_pipeline_timeline()
              if e["kind"] not in ("open", "advance", "step_decide",
                                   "apply", "barrier")]
        d = decompose(tl)
        assert dominant_segment(d) == "unattributed"


class TestSlowlogReservoir:
    def _mk(self, cap=4, window=100.0):
        from rabia_tpu.gateway.server import _SlowlogReservoir

        return _SlowlogReservoir(cap, window)

    def test_keeps_slowest_bounded(self):
        r = self._mk(cap=4)
        for i in range(20):
            r.observe((i + 1) * MS, {"batch": f"b{i}"})
        doc = r.document()
        walls = [e["wall_s"] for e in doc["exemplars"]]
        assert walls == [20 * MS, 19 * MS, 18 * MS, 17 * MS]
        assert doc["observed"] == 20
        assert doc["cap"] == 4
        # the floor fast path: a fast completion never evicts
        r.observe(0.5 * MS, {"batch": "fast"})
        assert len(r.document()["exemplars"]) == 4
        assert all(
            e["batch"] != "fast" for e in r.document()["exemplars"]
        )

    def test_rotation_retains_previous_window(self):
        r = self._mk(cap=4, window=0.05)
        r.observe(9 * MS, {"batch": "old"})
        time.sleep(0.06)
        r.observe(3 * MS, {"batch": "new"})
        doc = r.document()
        assert r.rotations >= 1
        batches = {e["batch"] for e in doc["exemplars"]}
        assert batches == {"old", "new"}  # cur + one previous window

    def test_exemplar_age_stamps(self):
        r = self._mk()
        r.observe(5 * MS, {"batch": "a"})
        time.sleep(0.02)
        doc = r.document()
        age = doc["exemplars"][0]["age_s"]
        assert 0.0 <= age < 5.0
        assert age >= 0.02 - 1e-9

    def test_last_limit_and_disable(self):
        r = self._mk(cap=4)
        for i in range(4):
            r.observe((i + 1) * MS, {"batch": f"b{i}"})
        assert len(r.document(2)["exemplars"]) == 2
        off = self._mk(cap=0)
        off.observe(1.0, {"batch": "x"})
        assert off.document()["exemplars"] == []


class TestDwellGeometry:
    def test_native_block_matches_registry_slo_buckets(self):
        """The decomposer's consensus segments are cross-checked against
        consensus_phase_dwell_seconds — which merges the native RK_DWELL
        block 1:1 only if the exported geometry equals the registry's
        SLO constants."""
        from rabia_tpu.native.build import load_hostkernel
        from rabia_tpu.obs.registry import (
            SLO_MIN_EXP,
            SLO_OCTAVES,
            SLO_SUB_BITS,
        )

        lib = load_hostkernel()
        if lib is None or not hasattr(lib, "rk_dwell"):
            pytest.skip("native hostkernel dwell block unavailable")
        assert int(lib.rk_dwell_sub_bits()) == SLO_SUB_BITS
        assert int(lib.rk_dwell_min_exp()) == SLO_MIN_EXP
        assert int(lib.rk_dwell_buckets()) == (
            SLO_OCTAVES << SLO_SUB_BITS
        )
        assert int(lib.rk_dwell_phases()) == PHASE_CLAMP
        assert int(lib.rk_dwell_version()) >= 1


async def _run_slowlog_cluster(via_cli: bool = False):
    """Drive a 3-replica TCP gateway cluster, then decompose its
    slowlog exemplars in-process. Returns (decomps, dwell label keys)
    so plane-parity tests can compare metric universes."""
    from rabia_tpu.apps.kvstore import encode_set_bin
    from rabia_tpu.gateway.client import RabiaClient
    from rabia_tpu.testing.gateway_cluster import GatewayCluster

    cluster = GatewayCluster(n_replicas=3, n_shards=2)
    await cluster.start()
    client = None
    try:
        client = RabiaClient(cluster.endpoints())
        await client.connect()
        for i in range(8):
            resp = await client.submit(
                i % 2, [encode_set_bin(f"cp{i}", "v")]
            )
            assert resp
        exemplars = []
        for g in cluster.gateways:
            exemplars.extend(
                g.slowlog.document().get("exemplars", [])
            )
        assert exemplars, "no slowlog exemplars captured"
        if via_cli:
            from rabia_tpu.__main__ import main as cli_main

            addrs = [f"127.0.0.1:{g.port}" for g in cluster.gateways]
            rc = await asyncio.to_thread(
                cli_main,
                ["slowlog", addrs[0],
                 *[a for ad in addrs for a in ("--replicas", ad)],
                 "--last", "4"],
            )
            assert rc == 0
            return [], set()
        engines = list(cluster.engines)
        agg = CritpathAggregator()
        decomps = decompose_exemplars(
            exemplars,
            lambda ex: inprocess_exemplar_timeline(engines, ex),
            aggregator=agg,
        )
        good = [
            d for d in decomps if d["ok"] and not d["truncated"]
        ]
        assert good, "no exemplar decomposed cleanly"
        worst = max(good, key=lambda d: d["total_s"])
        assert worst["unattributed_frac"] < 0.5
        assert dominant_segment(worst) is not None
        assert agg.summary()["exemplars"] == len(decomps)
        out = render_slowlog(
            {"node": "gw0", "observed": 8, "window_s": 10.0},
            sorted(decomps,
                   key=lambda d: -(d.get("wall_s") or 0.0)),
        )
        assert "worst exemplar" in out
        dwell_keys = set()
        for eng in engines:
            for key in eng.metrics.snapshot():
                if "consensus_phase_dwell_seconds" in key:
                    dwell_keys.add(key.split("_bucket")[0])
        return decomps, dwell_keys
    finally:
        if client is not None:
            await client.close()
        await cluster.stop()


@pytest.mark.asyncio
class TestCritpathLive:
    async def test_exemplars_decompose_in_process(self):
        await _run_slowlog_cluster()

    async def test_slowlog_cli_end_to_end(self):
        await _run_slowlog_cluster(via_cli=True)

    async def test_dwell_names_parity_python_planes(self, monkeypatch):
        """The native tick and the RABIA_PY_TICK=1 / RABIA_PY_GATEWAY=1
        twins must expose the SAME consensus_phase_dwell_seconds label
        universe — segment attribution that only exists on one plane
        would make waterfalls non-comparable across deployments."""
        _, native_keys = await _run_slowlog_cluster()
        monkeypatch.setenv("RABIA_PY_TICK", "1")
        monkeypatch.setenv("RABIA_PY_GATEWAY", "1")
        decomps, py_keys = await _run_slowlog_cluster()
        assert native_keys == py_keys
        assert any(d["ok"] for d in decomps)
