"""App-layer tests: counter, kvstore (+notifications), banking, sharding.

Reference parity: examples/counter_smr/src/lib.rs:209-324 (counter logic),
rabia-kvstore/src/store.rs:488-568 (CRUD/batch/snapshot),
notifications.rs:316-454 (filtering), banking_smr invariants.
"""

import pytest

from rabia_tpu.apps import (
    BankCommand,
    BankingSMR,
    ChangeType,
    CounterCommand,
    CounterSMR,
    KVOperation,
    KVResultKind,
    KVStore,
    NotificationFilter,
    make_sharded_kv,
    shard_for_key,
)
from rabia_tpu.core.config import KVStoreConfig
from rabia_tpu.core.smr import SMRBridge
from rabia_tpu.core.types import Command, CommandBatch, ShardId


class TestCounter:
    def test_increment_decrement_set_reset(self):
        sm = CounterSMR()
        assert sm.apply_command(CounterCommand.increment(5)).value == 5
        assert sm.apply_command(CounterCommand.decrement(2)).value == 3
        assert sm.apply_command(CounterCommand.set(100)).value == 100
        assert sm.apply_command(CounterCommand.reset()).value == 0
        assert sm.operations == 4

    def test_overflow_rejected_deterministically(self):
        sm = CounterSMR()
        sm.apply_command(CounterCommand.set((1 << 63) - 1))
        r = sm.apply_command(CounterCommand.increment(1))
        assert not r.ok and r.error == "overflow"
        assert sm.value == (1 << 63) - 1
        assert sm.operations == 2  # failed ops still count (determinism)

    def test_underflow_rejected(self):
        sm = CounterSMR()
        sm.apply_command(CounterCommand.set(-(1 << 63)))
        r = sm.apply_command(CounterCommand.decrement(1))
        assert not r.ok and r.error == "underflow"

    def test_command_response_roundtrip(self):
        sm = CounterSMR()
        cmd = CounterCommand.increment(7)
        assert sm.decode_command(sm.encode_command(cmd)) == cmd
        resp = sm.apply_command(cmd)
        assert sm.decode_response(sm.encode_response(resp)) == resp

    def test_state_roundtrip_via_bridge(self):
        sm = CounterSMR()
        bridge = SMRBridge(sm)
        bridge.apply_command(Command.new(sm.encode_command(CounterCommand.increment(41))))
        snap = bridge.create_snapshot()
        sm2 = CounterSMR()
        SMRBridge(sm2).restore_snapshot(snap)
        assert sm2.value == 41


class TestKVStore:
    def test_crud(self):
        s = KVStore()
        assert s.set("a", "1").ok
        assert s.get("a").value == "1"
        assert s.exists("a").value == "true"
        assert s.delete("a").value == "1"
        assert s.get("a").kind == KVResultKind.NotFound

    def test_versions_monotone(self):
        s = KVStore()
        v1 = s.set("k", "x").version
        v2 = s.set("k", "y").version
        assert v2 > v1
        meta = s.get_with_metadata("k")
        assert meta.version == v2 and meta.value == "y"

    def test_key_validation(self):
        import pytest as _pytest

        s = KVStore(KVStoreConfig(max_key_length=4))
        with _pytest.raises(Exception):
            s.set("toolongkey", "v")
        with _pytest.raises(Exception):
            s.set("", "v")

    def test_value_size_limit(self):
        s = KVStore(KVStoreConfig(max_value_size=8))
        with pytest.raises(Exception):
            s.set("k", "x" * 100)

    def test_max_keys(self):
        s = KVStore(KVStoreConfig(max_keys=2))
        s.set("a", "1")
        s.set("b", "2")
        with pytest.raises(Exception):
            s.set("c", "3")
        s.set("a", "updated")  # updates never hit the cap

    def test_keys_prefix_listing(self):
        s = KVStore()
        for k in ["user:1", "user:2", "order:1"]:
            s.set(k, "x")
        assert s.keys("user:") == ["user:1", "user:2"]
        assert len(s.keys()) == 3

    def test_snapshot_roundtrip_and_checksum(self):
        s = KVStore()
        s.set("a", "1")
        s.set("b", "2")
        blob = s.snapshot_bytes()
        s2 = KVStore()
        s2.restore_bytes(blob)
        assert s2.get("a").value == "1"
        assert s.checksum() == s2.checksum()

    def test_snapshot_corruption_detected(self):
        s = KVStore()
        s.set("a", "1")
        blob = bytearray(s.snapshot_bytes())
        blob[10] ^= 0xFF
        with pytest.raises(Exception):
            KVStore().restore_bytes(bytes(blob))

    def test_batch_apply(self):
        s = KVStore()
        results = s.apply_operations(
            [
                KVOperation.set("x", "1"),
                KVOperation.get("x"),
                KVOperation.delete("x"),
                KVOperation.get("x"),
            ]
        )
        assert [r.kind for r in results] == [
            KVResultKind.Success,
            KVResultKind.Success,
            KVResultKind.Success,
            KVResultKind.NotFound,
        ]


class TestNotifications:
    def test_filters(self):
        s = KVStore()
        bus = s.notifications
        all_sub = bus.subscribe()
        key_sub = bus.subscribe(NotificationFilter.key("a"))
        prefix_sub = bus.subscribe(NotificationFilter.key_prefix("user:"))
        type_sub = bus.subscribe(NotificationFilter.change_type(ChangeType.Deleted))

        s.set("a", "1")
        s.set("user:7", "x")
        s.delete("a")

        assert all_sub.queue.qsize() == 3
        assert key_sub.queue.qsize() == 2  # created + deleted for "a"
        assert prefix_sub.queue.qsize() == 1
        assert type_sub.queue.qsize() == 1
        n = type_sub.get_nowait()
        assert n.change == ChangeType.Deleted and n.old_value == "1"

    def test_and_or_composition(self):
        s = KVStore()
        bus = s.notifications
        sub = bus.subscribe(
            NotificationFilter.key_prefix("u:").and_(
                NotificationFilter.change_type(ChangeType.Created)
            )
        )
        s.set("u:1", "a")  # match
        s.set("u:1", "b")  # update: no
        s.set("v:1", "c")  # prefix: no
        assert sub.queue.qsize() == 1

    def test_closed_subscriber_gc(self):
        s = KVStore()
        bus = s.notifications
        sub = bus.subscribe()
        sub.close()
        s.set("k", "v")
        assert bus.stats.active_subscribers == 0


class TestBanking:
    def test_deposit_withdraw_transfer(self):
        b = BankingSMR()
        assert b.apply_command(BankCommand.create("alice", 10_00)).ok
        assert b.apply_command(BankCommand.create("bob")).ok
        assert b.apply_command(BankCommand.deposit("bob", 5_00)).ok
        r = b.apply_command(BankCommand.transfer("alice", "bob", 3_00))
        assert r.ok and r.balance_cents == 7_00
        assert b.apply_command(BankCommand.balance("bob")).balance_cents == 8_00

    def test_conservation_invariant(self):
        b = BankingSMR()
        b.apply_command(BankCommand.create("a", 100_00))
        b.apply_command(BankCommand.create("b", 50_00))
        total = b.total_value()
        for _ in range(10):
            b.apply_command(BankCommand.transfer("a", "b", 1_00))
            b.apply_command(BankCommand.transfer("b", "a", 1_00))
        assert b.total_value() == total

    def test_validation(self):
        b = BankingSMR()
        b.apply_command(BankCommand.create("a", 1_00))
        assert not b.apply_command(BankCommand.deposit("a", -5)).ok
        assert not b.apply_command(BankCommand.deposit("a", 10_000_000_01)).ok
        assert not b.apply_command(BankCommand.withdraw("a", 2_00)).ok
        assert not b.apply_command(BankCommand.transfer("a", "a", 1)).ok
        assert not b.apply_command(BankCommand.transfer("a", "ghost", 1)).ok
        assert b.total_value() == 1_00

    def test_state_roundtrip(self):
        b = BankingSMR()
        b.apply_command(BankCommand.create("x", 42_00))
        blob = b.serialize_state()
        b2 = BankingSMR()
        b2.deserialize_state(blob)
        assert b2.apply_command(BankCommand.balance("x")).balance_cents == 42_00
        assert b2.total_value() == b.total_value()


class TestSharding:
    def test_shard_for_key_stable_and_spread(self):
        assert shard_for_key("k", 8) == shard_for_key("k", 8)
        shards = {shard_for_key(f"key{i}", 8) for i in range(200)}
        assert len(shards) == 8  # every shard reached

    def test_sharded_sm_routes_by_batch_shard(self):
        sm, machines = make_sharded_kv(4)
        op = machines[2].encode_command(KVOperation.set("hello", "world"))
        batch = CommandBatch.new([Command.new(op)], shard=ShardId(2))
        sm.apply_batch(batch)
        assert machines[2].store.get("hello").value == "world"
        assert machines[0].store.size() == 0

    def test_sharded_snapshot_roundtrip(self):
        sm, machines = make_sharded_kv(3)
        for i, m in enumerate(machines):
            m.store.set(f"k{i}", str(i))
        snap = sm.create_snapshot()
        sm2, machines2 = make_sharded_kv(3)
        sm2.restore_snapshot(snap)
        for i, m in enumerate(machines2):
            assert m.store.get(f"k{i}").value == str(i)


class TestCounterClusterEndToEnd:
    """BASELINE config #1: counter SMR, 3 replicas, in-memory transport —
    the minimum end-to-end slice (SURVEY.md §7.3)."""

    @pytest.mark.asyncio
    async def test_counter_cluster(self):
        import asyncio

        from rabia_tpu.core.config import RabiaConfig
        from rabia_tpu.core.network import ClusterConfig
        from rabia_tpu.core.types import NodeId
        from rabia_tpu.engine import RabiaEngine
        from rabia_tpu.net import InMemoryHub

        nodes = [NodeId.from_int(i + 1) for i in range(3)]
        hub = InMemoryHub()
        config = RabiaConfig(
            phase_timeout=0.4, heartbeat_interval=0.05, round_interval=0.002
        ).with_kernel(num_shards=1, shard_pad_multiple=1)
        engines, counters, tasks = [], [], []
        for n in nodes:
            counter = CounterSMR()
            engines.append(
                RabiaEngine(
                    ClusterConfig.new(n, nodes),
                    SMRBridge(counter),
                    hub.register(n),
                    config=config,
                )
            )
            counters.append(counter)
            tasks.append(asyncio.ensure_future(engines[-1].run()))
        try:
            for _ in range(200):
                await asyncio.sleep(0.01)
                sts = [await e.get_statistics() for e in engines]
                if all(s.has_quorum for s in sts):
                    break
            codec = counters[0]
            batch = CommandBatch.new(
                [Command.new(codec.encode_command(CounterCommand.increment(5)))]
            )
            fut = await engines[0].submit_batch(batch, shard=0)
            responses = await asyncio.wait_for(fut, 15.0)
            assert codec.decode_response(responses[0]).value == 5

            async def converged():
                while not all(c.value == 5 for c in counters):
                    await asyncio.sleep(0.02)

            await asyncio.wait_for(converged(), 10.0)
        finally:
            for e in engines:
                await e.shutdown()
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)


class TestShardedKVCluster:
    """BASELINE config #2 shape: sharded kvstore over a 3-replica cluster."""

    @pytest.mark.asyncio
    async def test_sharded_kv_cluster(self):
        import asyncio

        from rabia_tpu.apps import ShardedKVService
        from rabia_tpu.core.config import RabiaConfig
        from rabia_tpu.core.network import ClusterConfig
        from rabia_tpu.core.types import NodeId
        from rabia_tpu.engine import RabiaEngine
        from rabia_tpu.net import InMemoryHub

        n_shards = 4
        nodes = [NodeId.from_int(i + 1) for i in range(3)]
        hub = InMemoryHub()
        config = RabiaConfig(
            phase_timeout=0.4, heartbeat_interval=0.05, round_interval=0.002
        ).with_kernel(num_shards=n_shards, shard_pad_multiple=4)
        engines, all_machines, tasks = [], [], []
        for n in nodes:
            sm, machines = make_sharded_kv(n_shards)
            engines.append(
                RabiaEngine(
                    ClusterConfig.new(n, nodes), sm, hub.register(n), config=config
                )
            )
            all_machines.append(machines)
            tasks.append(asyncio.ensure_future(engines[-1].run()))
        try:
            for _ in range(200):
                await asyncio.sleep(0.01)
                sts = [await e.get_statistics() for e in engines]
                if all(s.has_quorum for s in sts):
                    break
            svc = ShardedKVService(
                n_shards, engines[0].submit_batch, all_machines[0]
            )
            keys = [f"key{i}" for i in range(8)]
            results = await asyncio.gather(
                *[
                    asyncio.wait_for(
                        (lambda k: _set_via(svc, k))(k), 20.0
                    )
                    for k in keys
                ]
            )
            assert all(r.ok for r in results)
            # every replica's shard stores converge
            async def converged():
                while True:
                    ok = True
                    for machines in all_machines:
                        for k in keys:
                            s = shard_for_key(k, n_shards)
                            if machines[s].store.get(k).value != f"v-{k}":
                                ok = False
                    if ok:
                        return
                    await asyncio.sleep(0.05)

            await asyncio.wait_for(converged(), 20.0)
        finally:
            for e in engines:
                await e.shutdown()
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)


async def _set_via(svc, key):
    return await svc.set(key, f"v-{key}")


class TestOperationBatch:
    """OperationBatch/BatchResult (operations.rs:169-262 parity)."""

    def test_batch_introspection(self):
        from rabia_tpu.apps import KVOperation, OperationBatch

        b = OperationBatch.new(
            [KVOperation.set("a", "1"), KVOperation.get("b"),
             KVOperation.delete("c")]
        )
        assert b.size() == 3
        assert b.has_write_operations() and not b.is_read_only()
        assert b.affected_keys() == ["a", "b", "c"]
        assert b.batch_id  # unique id assigned
        ro = OperationBatch.new([KVOperation.get("a"), KVOperation.exists("b")])
        assert ro.is_read_only()

    def test_execute_batch_reports_outcomes(self):
        from rabia_tpu.apps import KVOperation, KVStore, OperationBatch

        store = KVStore()
        batch = OperationBatch.new(
            [KVOperation.set("k", "v"), KVOperation.get("k"),
             KVOperation.get("missing")]
        )
        res = store.execute_batch(batch)
        assert res.batch_id == batch.batch_id
        assert (res.success_count, res.failure_count) == (2, 1)
        assert res.has_failures() and not res.all_succeeded()
        assert abs(res.success_rate() - 200 / 3) < 1e-9
        assert res.execution_time_ms >= 0
        assert res.results[1].value == "v"

    def test_empty_batch_success_rate_zero(self):
        from rabia_tpu.apps import KVStore, OperationBatch

        res = KVStore().execute_batch(OperationBatch.new([]))
        assert res.success_rate() == 0.0 and res.all_succeeded()
