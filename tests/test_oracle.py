"""Weak-MVC oracle tests: the Ivy-spec safety/liveness properties
(docs/weak_mvc.ivy:190+ invariants) under synchronous, lossy and faulty
delivery. The oracle is itself the reference for the kernel conformance
tests, so it gets hammered hard here.
"""

import random

import pytest

from rabia_tpu.core.oracle import (
    WeakMVCOracle,
    bernoulli_deliver,
    seeded_coin,
)
from rabia_tpu.core.types import V0, V1


def run_case(n, initial, *, alive=None, deliver=None, seed=0, max_steps=500):
    o = WeakMVCOracle(n, initial, seeded_coin(seed), alive=alive)
    val = o.run(max_steps=max_steps, deliver=deliver or (lambda i, j: True))
    o.check_agreement()
    o.check_validity(initial)
    return o, val


class TestFaultFree:
    @pytest.mark.parametrize("n", [1, 3, 5, 7])
    def test_unanimous_v1_decides_v1_phase0(self, n):
        o, val = run_case(n, [V1] * n)
        assert val == V1
        assert o.decided_phase == 0

    @pytest.mark.parametrize("n", [3, 5, 7])
    def test_unanimous_v0_decides_v0(self, n):
        _, val = run_case(n, [V0] * n)
        assert val == V0

    def test_two_rounds_to_decide(self):
        # fault-free unanimous input decides after exactly 2 synchronous steps
        o = WeakMVCOracle(3, [V1] * 3, seeded_coin(0))
        o.step()
        assert o.decided_value is None
        o.step()
        assert o.decided_value == V1

    @pytest.mark.parametrize("n,seed", [(3, s) for s in range(5)] + [(5, s) for s in range(5)])
    def test_mixed_inputs_decide(self, n, seed):
        rng = random.Random(seed)
        initial = [rng.choice([V0, V1]) for _ in range(n)]
        o, val = run_case(n, initial, seed=seed)
        assert val in (V0, V1)
        # every alive node must eventually learn the decision
        assert all(nd.decided == val for nd in o.nodes)


class TestCrashFaults:
    @pytest.mark.parametrize("n,crashed", [(3, 1), (5, 2), (7, 3)])
    def test_minority_crash_still_decides(self, n, crashed):
        alive = [True] * n
        for i in range(crashed):
            alive[i] = False
        o, val = run_case(n, [V1] * n, alive=alive)
        assert val == V1
        assert all(nd.decided == V1 for nd in o.nodes if nd.alive)

    def test_majority_crash_no_progress(self):
        alive = [False, False, True]  # only 1 of 3 alive — below quorum
        o = WeakMVCOracle(3, [V1] * 3, seeded_coin(0), alive=alive)
        o.run(max_steps=50)
        assert o.decided_value is None


class TestLossyDelivery:
    @pytest.mark.parametrize("seed", range(8))
    def test_heavy_loss_eventually_decides(self, seed):
        rng = random.Random(seed)
        n = 5
        initial = [rng.choice([V0, V1]) for _ in range(n)]
        o, val = run_case(
            n, initial, deliver=bernoulli_deliver(rng, 0.5), seed=seed, max_steps=2000
        )
        assert val in (V0, V1)

    @pytest.mark.parametrize("seed", range(4))
    def test_asymmetric_partition_heals(self, seed):
        # one-sided partition for the first 20 steps, then full delivery
        n = 5
        rng = random.Random(seed)
        initial = [rng.choice([V0, V1]) for _ in range(n)]
        o = WeakMVCOracle(n, initial, seeded_coin(seed))
        cut = {0, 1}  # isolated minority
        for _ in range(20):
            o.step(lambda i, j: not (i in cut) ^ (j in cut))
        for _ in range(100):
            if all(nd.decided is not None for nd in o.nodes):
                break
            o.step()
        o.check_agreement()
        assert o.decided_value in (V0, V1)


class TestCommonCoin:
    def test_coin_is_common(self):
        c1 = seeded_coin(seed=7, shard=3, slot=2)
        c2 = seeded_coin(seed=7, shard=3, slot=2)
        assert [c1(p) for p in range(32)] == [c2(p) for p in range(32)]

    def test_coin_varies_with_phase_and_seed(self):
        c = seeded_coin(seed=7)
        vals = {c(p) for p in range(64)}
        assert vals == {V0, V1}
        other = seeded_coin(seed=8)
        assert [c(p) for p in range(64)] != [other(p) for p in range(64)]

    def test_split_vote_terminates_via_coin(self):
        # adversarial-ish: 2 vs 3 split with full delivery resolves quickly
        for seed in range(6):
            o, val = run_case(5, [V0, V0, V1, V1, V1], seed=seed)
            assert val in (V0, V1)


class TestAgreementStress:
    @pytest.mark.parametrize("seed", range(10))
    def test_random_masks_never_break_agreement(self, seed):
        rng = random.Random(1000 + seed)
        n = rng.choice([3, 4, 5, 7])
        initial = [rng.choice([V0, V1]) for _ in range(n)]
        alive = [rng.random() > 0.2 for _ in range(n)]
        # guarantee a quorum stays alive so the run can terminate
        while sum(alive) < n // 2 + 1:
            alive[rng.randrange(n)] = True
        o = WeakMVCOracle(n, initial, seeded_coin(seed), alive=alive)
        o.run(max_steps=1500, deliver=bernoulli_deliver(rng, 0.6))
        o.check_agreement()
        o.check_validity(initial)
