"""Integration tests: N real engines over in-process transports.

Reference parity: rabia-testing/tests/integration_basic.rs (N engines +
InMemoryNetwork, :19-80) and integration_consensus.rs (loss/latency
scenarios). Unlike the reference CI — which tolerates consensus failure
(integration_consensus.rs:48-53 masks its vote-routing deviation) — these
tests REQUIRE AllCommitted to actually hold (SURVEY.md §4.4).
"""

import asyncio

import pytest

from rabia_tpu.core.config import RabiaConfig
from rabia_tpu.core.errors import QuorumNotAvailableError
from rabia_tpu.core.network import ClusterConfig
from rabia_tpu.core.state_machine import InMemoryStateMachine
from rabia_tpu.core.types import CommandBatch, NodeId
from rabia_tpu.engine import RabiaEngine, slot_proposer
from rabia_tpu.net import (
    InMemoryHub,
    NetworkConditions,
    NetworkSimulator,
)


def _mk_config(n_shards: int = 2) -> RabiaConfig:
    return RabiaConfig(
        phase_timeout=0.4,
        heartbeat_interval=0.05,
        round_interval=0.002,
        cleanup_interval=1.0,
    ).with_kernel(num_shards=n_shards, shard_pad_multiple=2)


async def _spin_cluster(n, config, transport_factory):
    nodes = [NodeId.from_int(i + 1) for i in range(n)]
    engines, sms, tasks = [], [], []
    for node in nodes:
        sm = InMemoryStateMachine()
        transport = transport_factory(node)
        eng = RabiaEngine(
            ClusterConfig.new(node, nodes), sm, transport, config=config
        )
        engines.append(eng)
        sms.append(sm)
        tasks.append(asyncio.ensure_future(eng.run()))
    # let heartbeats establish quorum
    for _ in range(200):
        await asyncio.sleep(0.01)
        stats = [await e.get_statistics() for e in engines]
        if all(s.has_quorum for s in stats):
            break
    return nodes, engines, sms, tasks


async def _teardown(engines, tasks):
    for e in engines:
        await e.shutdown()
    for t in tasks:
        t.cancel()
    await asyncio.gather(*tasks, return_exceptions=True)


async def _converged(sms, key, value, timeout=10.0):
    async def wait():
        while not all(sm.get(key) == value for sm in sms):
            await asyncio.sleep(0.02)

    await asyncio.wait_for(wait(), timeout)


class TestThreeNodeInMemory:
    @pytest.mark.asyncio
    async def test_single_batch_commits_everywhere(self):
        hub = InMemoryHub()
        _, engines, sms, tasks = await _spin_cluster(
            3, _mk_config(), hub.register
        )
        try:
            fut = await engines[0].submit_batch(
                CommandBatch.new(["SET a 1", "SET b 2"]), shard=0
            )
            responses = await asyncio.wait_for(fut, 10.0)
            assert responses == [b"OK", b"OK"]
            await _converged(sms, "a", "1")
            await _converged(sms, "b", "2")
        finally:
            await _teardown(engines, tasks)

    @pytest.mark.asyncio
    async def test_submissions_from_every_node(self):
        hub = InMemoryHub()
        _, engines, sms, tasks = await _spin_cluster(
            3, _mk_config(), hub.register
        )
        try:
            futs = []
            for i, e in enumerate(engines):
                futs.append(
                    await e.submit_batch(
                        CommandBatch.new([f"SET k{i} v{i}"]), shard=i % 2
                    )
                )
            for f in futs:
                await asyncio.wait_for(f, 15.0)
            for i in range(3):
                await _converged(sms, f"k{i}", f"v{i}")
            stats = [await e.get_statistics() for e in engines]
            assert all(s.decided_v1 >= 3 for s in stats)
        finally:
            await _teardown(engines, tasks)

    @pytest.mark.asyncio
    async def test_single_replica_cluster_keeps_committing(self):
        # regression: R==1 gets no peer traffic, so the input-gated kernel
        # step wedged after the R1 cast — the follow-up step (_restep) must
        # carry each slot through R2 and decision on its own
        hub = InMemoryHub()
        _, engines, sms, tasks = await _spin_cluster(
            1, _mk_config(), hub.register
        )
        try:
            for i in range(3):
                fut = await engines[0].submit_batch(
                    CommandBatch.new([f"SET solo{i} v{i}"]), shard=i % 2
                )
                assert await asyncio.wait_for(fut, 10.0) == [b"OK"]
            for i in range(3):
                await _converged(sms, f"solo{i}", f"v{i}")
        finally:
            await _teardown(engines, tasks)

    @pytest.mark.asyncio
    async def test_live_membership_join_and_leave(self):
        """A configured replica joins MID-RUN (quorum + leader recompute,
        joiner catches up via sync) and another leaves (leader recomputes
        again, survivors keep committing). Reference parity:
        rabia-engine/src/engine.rs:142-153 (update_nodes),
        leader.rs:61-87 (recompute), and the dynamic-topology arm of
        examples/tcp_networking.rs:20-43."""
        hub = InMemoryHub()
        config = _mk_config()
        nodes = [NodeId.from_int(i + 1) for i in range(3)]
        engines, sms, tasks = [], [], []

        def start(node):
            sm = InMemoryStateMachine()
            eng = RabiaEngine(
                ClusterConfig.new(node, nodes), sm, hub.register(node),
                config=config,
            )
            engines.append(eng)
            sms.append(sm)
            tasks.append(asyncio.ensure_future(eng.run()))
            return eng

        # phase 1: only 2 of the 3 configured replicas run (quorum = 2)
        for node in nodes[:2]:
            start(node)
        try:
            for _ in range(300):
                await asyncio.sleep(0.01)
                stats = [await e.get_statistics() for e in engines]
                if all(s.has_quorum for s in stats):
                    break
            for i in range(4):
                fut = await engines[0].submit_batch(
                    CommandBatch.new([f"SET pre{i} v{i}"]), shard=i % 2
                )
                await asyncio.wait_for(fut, 10.0)
            assert engines[0].leader.current_leader == nodes[0]

            # phase 2: node 3 JOINS mid-run
            joiner = start(nodes[2])
            for _ in range(500):
                await asyncio.sleep(0.01)
                st = await joiner.get_statistics()
                if st.has_quorum and st.active_nodes == 3:
                    break
            # membership view refreshed on every running engine
            assert (await engines[0].get_statistics()).active_nodes == 3
            # commits continue with the larger membership...
            fut = await engines[1].submit_batch(
                CommandBatch.new(["SET mid x"]), shard=0
            )
            await asyncio.wait_for(fut, 10.0)
            # ...and the joiner catches up on everything it missed (sync)
            await _converged(sms, "pre3", "v3", timeout=15.0)
            await _converged(sms, "mid", "x", timeout=15.0)

            # phase 3: the leader LEAVES mid-run
            await engines[0].shutdown()
            hub.set_connected(nodes[0], False)
            for _ in range(500):
                await asyncio.sleep(0.01)
                if engines[1].leader.current_leader == nodes[1]:
                    break
            assert engines[1].leader.current_leader == nodes[1]
            st = await engines[1].get_statistics()
            assert st.has_quorum  # 2 of 3 configured still up
            fut = await engines[1].submit_batch(
                CommandBatch.new(["SET post y"]), shard=1
            )
            await asyncio.wait_for(fut, 10.0)
            await _converged(sms[1:], "post", "y", timeout=15.0)
        finally:
            await _teardown(engines, tasks)

    @pytest.mark.asyncio
    async def test_no_quorum_rejects_submission(self):
        hub = InMemoryHub()
        nodes = [NodeId.from_int(i + 1) for i in range(3)]
        sm = InMemoryStateMachine()
        eng = RabiaEngine(
            ClusterConfig.new(nodes[0], nodes),
            sm,
            hub.register(nodes[0]),
            config=_mk_config(),
        )
        # never started peers: no quorum
        with pytest.raises(QuorumNotAvailableError):
            await eng.submit_batch(CommandBatch.new(["SET x 1"]))

    @pytest.mark.asyncio
    async def test_shutdown_without_run_returns(self):
        hub = InMemoryHub()
        nodes = [NodeId.from_int(1)]
        eng = RabiaEngine(
            ClusterConfig.new(nodes[0], nodes),
            InMemoryStateMachine(),
            hub.register(nodes[0]),
            config=_mk_config(),
        )
        await asyncio.wait_for(eng.shutdown(), 1.0)


class TestSimulatedConditions:
    @pytest.mark.asyncio
    async def test_commits_under_packet_loss(self):
        sim = NetworkSimulator(NetworkConditions.lossy(0.20), seed=7)
        _, engines, sms, tasks = await _spin_cluster(
            3, _mk_config(), sim.register
        )
        try:
            fut = await engines[1].submit_batch(
                CommandBatch.new(["SET lossy yes"]), shard=0
            )
            # under loss the submitter itself can fall behind and receive
            # its own batch's effects via snapshot sync — then the future
            # fails with the documented "responses unavailable" error while
            # the COMMIT is still real; convergence below is the actual
            # assertion either way
            try:
                await asyncio.wait_for(fut, 20.0)
            except Exception as e:  # noqa: BLE001
                assert "responses unavailable" in str(e)
            await _converged(sms, "lossy", "yes", timeout=20.0)
        finally:
            await _teardown(engines, tasks)
            await sim.close()

    @pytest.mark.asyncio
    async def test_commits_under_latency(self):
        sim = NetworkSimulator(
            NetworkConditions(latency_min=0.005, latency_max=0.02), seed=7
        )
        _, engines, sms, tasks = await _spin_cluster(
            3, _mk_config(), sim.register
        )
        try:
            fut = await engines[0].submit_batch(
                CommandBatch.new(["SET slow ok"]), shard=1
            )
            await asyncio.wait_for(fut, 20.0)
            await _converged(sms, "slow", "ok", timeout=20.0)
            assert sim.stats.average_latency > 0.001
        finally:
            await _teardown(engines, tasks)
            await sim.close()

    @pytest.mark.asyncio
    async def test_minority_crash_still_commits(self):
        sim = NetworkSimulator(seed=3)
        nodes_all, engines, sms, tasks = await _spin_cluster(
            3, _mk_config(), sim.register
        )
        try:
            sim.crash(nodes_all[2])
            await asyncio.sleep(0.2)
            fut = await engines[0].submit_batch(
                CommandBatch.new(["SET crashy fine"]), shard=0
            )
            await asyncio.wait_for(fut, 20.0)
            await _converged(sms[:2], "crashy", "fine", timeout=20.0)
        finally:
            await _teardown(engines, tasks)
            await sim.close()


class TestSlotProposer:
    def test_rotation_covers_all_replicas(self):
        rows = {slot_proposer(0, slot, 5) for slot in range(5)}
        assert rows == set(range(5))

    def test_deterministic(self):
        assert slot_proposer(3, 7, 5) == slot_proposer(3, 7, 5)


def _single_engine(n=3, n_shards=1):
    nodes = [NodeId.from_int(i + 1) for i in range(n)]
    hub = InMemoryHub()
    eng = RabiaEngine(
        ClusterConfig.new(nodes[0], nodes),
        InMemoryStateMachine(),
        hub.register(nodes[0]),
        config=_mk_config(n_shards),
    )
    return eng


class TestProposerValidation:
    """Only the rotation proposer of (shard, slot) may bind a batch to it —
    a non-proposer's Propose must be dropped (ADVICE: divergent batch_id
    bindings on a V1-decided slot cause state divergence)."""

    @pytest.mark.asyncio
    async def test_non_proposer_propose_dropped(self):
        from rabia_tpu.core.messages import Propose
        from rabia_tpu.core.types import StateValue
        from rabia_tpu.kernel.phase_driver import pack_phase

        eng = _single_engine()
        batch = CommandBatch.new(["SET a 1"])
        # slot 0 of shard 0 belongs to row 0; rows 1/2 must be rejected
        for bad_row in (1, 2):
            eng._on_propose(
                bad_row,
                Propose(
                    shard=0,
                    phase=pack_phase(0, 0),
                    batch_id=batch.id,
                    value=StateValue.V1,
                    batch=batch,
                ),
            )
        assert eng.rt.shards[0].buf_propose == {}
        # slot 1 belongs to row 1: accepted
        eng._on_propose(
            1,
            Propose(
                shard=0,
                phase=pack_phase(1, 0),
                batch_id=batch.id,
                value=StateValue.V1,
                batch=batch,
            ),
        )
        assert 1 in eng.rt.shards[0].buf_propose

    @pytest.mark.asyncio
    async def test_open_slots_never_rebinds(self):
        """Once a slot carries a binding, the proposer must not swap in a
        different queued batch."""
        eng = _single_engine()
        eng.rt.has_quorum = True
        sh = eng.rt.shards[0]
        bound = CommandBatch.new(["SET first 1"])
        sh.buf_propose[0] = (bound.id, bound)
        await eng.submit_batch(CommandBatch.new(["SET second 2"]), shard=0)
        opened = eng._open_slots()
        assert [(s, slot) for s, slot, _v in opened] == [(0, 0)]
        assert sh.buf_propose[0][0] == bound.id  # binding unchanged


class TestDedupLedger:
    """applied_ids is the duplicate-commit guard; evicting the bounded
    response cache must not re-enable a duplicate apply (ADVICE low)."""

    @pytest.mark.asyncio
    async def test_dedup_survives_response_cache_eviction(self):
        from rabia_tpu.core.types import BatchId

        eng = _single_engine()
        sh = eng.rt.shards[0]
        ids = [BatchId.new() for _ in range(3 * eng.config.max_pending_batches)]
        for bid in ids:
            sh.applied_ids[bid] = None
            sh.applied_results[bid] = [b"ok"]
        eng._gc()
        # response cache bounded...
        assert len(sh.applied_results) <= 2 * eng.config.max_pending_batches
        # ...but every id still known to the dedup ledger
        assert all(bid in sh.applied_ids for bid in ids)


class TestApplyFailureContainment:
    """A committed batch the state machine rejects must fail the submitter
    deterministically — never kill the consensus loop (a poisoned command
    would otherwise crash every replica identically: cluster outage)."""

    @pytest.mark.asyncio
    async def test_undecodable_command_fails_future_not_engine(self):
        from rabia_tpu.apps import make_sharded_kv
        from rabia_tpu.apps.kvstore import encode_set_bin
        from rabia_tpu.core.errors import RabiaError
        from rabia_tpu.core.types import Command, CommandBatch

        nodes = [NodeId.from_int(i + 1) for i in range(3)]
        hub = InMemoryHub()
        engines, tasks = [], []
        for n in nodes:
            sm, _ = make_sharded_kv(2)
            engines.append(
                RabiaEngine(
                    ClusterConfig.new(n, nodes),
                    sm,
                    hub.register(n),
                    config=_mk_config(2),
                )
            )
            tasks.append(asyncio.ensure_future(engines[-1].run()))
        try:
            for _ in range(300):
                await asyncio.sleep(0.01)
                sts = [await e.get_statistics() for e in engines]
                if all(s.has_quorum for s in sts):
                    break
            # poisoned: neither JSON nor valid binary op
            bad = CommandBatch.new([Command.new(b"NOT A VALID COMMAND")], shard=0)
            fut = await engines[0].submit_batch(bad, shard=0)
            with pytest.raises(RabiaError):
                await asyncio.wait_for(fut, 20.0)
            # the cluster is still alive: a good batch commits after it
            good = CommandBatch.new([Command.new(encode_set_bin("k", "v"))], shard=0)
            fut2 = await engines[0].submit_batch(good, shard=0)
            responses = await asyncio.wait_for(fut2, 20.0)
            assert len(responses) == 1
        finally:
            for e in engines:
                await e.shutdown()
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)


class TestQuorumEventPlumbing:
    """NetworkMonitor events drive engine pause/resume (engine.rs:983-997)
    and QuorumNotification broadcasts (messages.rs:132-136)."""

    @pytest.mark.asyncio
    async def test_partition_pauses_and_heal_resumes(self):
        from rabia_tpu.core.state_machine import InMemoryStateMachine
        from rabia_tpu.core.types import Command, CommandBatch

        nodes = [NodeId.from_int(i + 1) for i in range(3)]
        hub = InMemoryHub()
        cfg = _mk_config(1)
        engines, tasks = [], []
        for n in nodes:
            engines.append(
                RabiaEngine(
                    ClusterConfig.new(n, nodes),
                    InMemoryStateMachine(),
                    hub.register(n),
                    config=cfg,
                )
            )
            tasks.append(asyncio.ensure_future(engines[-1].run()))
        try:
            for _ in range(300):
                await asyncio.sleep(0.01)
                sts = [await e.get_statistics() for e in engines]
                if all(s.has_quorum for s in sts):
                    break
            # commit one batch while healthy
            fut = await engines[0].submit_batch(
                CommandBatch.new([Command.new(b"SET a 1")], shard=0), shard=0
            )
            await asyncio.wait_for(fut, 20.0)

            # partition node 0 away from both peers
            hub.set_connected(nodes[1], False)
            hub.set_connected(nodes[2], False)
            for _ in range(400):
                await asyncio.sleep(0.01)
                if engines[0]._paused:
                    break
            assert engines[0]._paused, "quorum loss must pause consensus"
            st = await engines[0].get_statistics()
            assert not st.is_active and not st.has_quorum
            from rabia_tpu.core.errors import QuorumNotAvailableError

            with pytest.raises(QuorumNotAvailableError):
                await engines[0].submit_batch(
                    CommandBatch.new([Command.new(b"SET b 2")], shard=0), shard=0
                )

            # heal: quorum restored resumes consensus and commits again
            hub.set_connected(nodes[1], True)
            hub.set_connected(nodes[2], True)
            for _ in range(400):
                await asyncio.sleep(0.01)
                sts = [await e.get_statistics() for e in engines]
                if not engines[0]._paused and all(s.has_quorum for s in sts):
                    break
            assert not engines[0]._paused
            fut = await engines[0].submit_batch(
                CommandBatch.new([Command.new(b"SET c 3")], shard=0), shard=0
            )
            await asyncio.wait_for(fut, 20.0)
            # peers observed the lost/restored notifications
            seen = any(
                nodes[0] in e._peer_quorum_views for e in engines[1:]
            )
            assert seen, "QuorumNotification broadcasts were not received"
        finally:
            for e in engines:
                await e.shutdown()
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)


class TestTracing:
    @pytest.mark.asyncio
    async def test_spans_record_engine_phases(self):
        from rabia_tpu.core.state_machine import InMemoryStateMachine
        from rabia_tpu.core.tracing import tracer
        from rabia_tpu.core.types import Command, CommandBatch

        nodes = [NodeId.from_int(i + 1) for i in range(3)]
        hub = InMemoryHub()
        engines, tasks = [], []
        for n in nodes:
            engines.append(
                RabiaEngine(
                    ClusterConfig.new(n, nodes),
                    InMemoryStateMachine(),
                    hub.register(n),
                    config=_mk_config(1),
                )
            )
            tasks.append(asyncio.ensure_future(engines[-1].run()))
        tracer.reset()
        tracer.enabled = True
        try:
            for _ in range(300):
                await asyncio.sleep(0.01)
                sts = [await e.get_statistics() for e in engines]
                if all(s.has_quorum for s in sts):
                    break
            fut = await engines[0].submit_batch(
                CommandBatch.new([Command.new(b"SET t 1")], shard=0), shard=0
            )
            await asyncio.wait_for(fut, 20.0)
        finally:
            tracer.enabled = False
            for e in engines:
                await e.shutdown()
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
        report = tracer.report()
        for name in (
            "engine.tick.drain",
            "engine.tick.kernel",
            "engine.kernel.step",
            "engine.tick.apply",
        ):
            assert name in report and report[name]["count"] > 0, report.keys()
        tracer.reset()


class TestMixedProgressSync:
    """Sync adoption must be PER SHARD: a responder ahead on some shards
    must not regress shards where the syncing replica is ahead (wholesale
    snapshot restore under mixed progress poisons state/counter
    consistency)."""

    def _mk(self, S, sm):
        nodes = [NodeId.from_int(i + 1) for i in range(3)]
        hub = InMemoryHub()
        return RabiaEngine(
            ClusterConfig.new(nodes[0], nodes),
            sm,
            hub.register(nodes[0]),
            config=_mk_config(S),
        ), nodes

    @pytest.mark.asyncio
    async def test_sharded_sm_adopts_only_ahead_shards(self):
        from rabia_tpu.apps import make_sharded_kv
        from rabia_tpu.apps.kvstore import encode_set_bin
        from rabia_tpu.core.messages import SyncResponse
        from rabia_tpu.core.types import Command, CommandBatch, ShardId

        S = 2
        sm_a, stores_a = make_sharded_kv(S)  # the responder's state
        sm_b, stores_b = make_sharded_kv(S)  # the syncing replica's

        def put(sm, shard, key, val):
            sm.apply_batch(
                CommandBatch.new(
                    [Command.new(encode_set_bin(key, val))], shard=ShardId(shard)
                )
            )

        # responder A: ahead on shard 0 (3 slots), empty shard 1
        for i in range(3):
            put(sm_a, 0, f"a{i}", f"A{i}")
        # syncer B: ahead on shard 1 (2 slots), empty shard 0
        put(sm_b, 1, "b0", "B0")
        put(sm_b, 1, "b1", "B1")

        eng, nodes = self._mk(S, sm_b)
        eng.rt.shards[1].applied_upto = 2
        eng.rt.shards[1].next_slot = 2

        snap = sm_a.create_snapshot()
        resp = SyncResponse(
            responder_phase=3,
            state_version=3,
            snapshot=snap.to_bytes(),
            per_shard_phase=(3, 0),
            applied_ids=(),
        )
        eng.rt.sync_started_at = 0.0
        eng._on_sync_response(nodes[1], resp)
        # shard 0 adopted from A...
        assert eng.rt.shards[0].applied_upto == 3
        assert stores_b[0].store.get("a2").value == "A2"
        # ...while shard 1's OWN state and counters survive
        assert eng.rt.shards[1].applied_upto == 2
        assert stores_b[1].store.get("b1").value == "B1"

    @pytest.mark.asyncio
    async def test_monolithic_sm_requires_superset_responder(self):
        from rabia_tpu.core.messages import SyncResponse
        from rabia_tpu.core.state_machine import InMemoryStateMachine

        S = 2
        sm = InMemoryStateMachine()
        eng, nodes = self._mk(S, sm)
        # we are ahead on shard 1
        eng.rt.shards[1].applied_upto = 2
        responder_sm = InMemoryStateMachine()
        snap = responder_sm.create_snapshot()
        resp = SyncResponse(
            responder_phase=3,
            state_version=3,
            snapshot=snap.to_bytes(),
            per_shard_phase=(3, 0),  # ahead on 0, BEHIND on 1
            applied_ids=(),
        )
        eng.rt.sync_started_at = 0.0
        eng._on_sync_response(nodes[1], resp)
        # not a superset + no per-shard restore => nothing adopted
        assert eng.rt.shards[0].applied_upto == 0
        assert eng.rt.shards[1].applied_upto == 2

    def test_vector_store_restore_shards(self):
        from rabia_tpu.apps.vector_kv import VectorShardedKV
        from rabia_tpu.apps.kvstore import encode_set_bin
        from rabia_tpu.core.blocks import build_block
        import numpy as np

        a = VectorShardedKV(3, capacity=64)
        b = VectorShardedKV(3, capacity=64)
        a.apply_block(
            build_block([0, 2], [[encode_set_bin("x", "Ax")], [encode_set_bin("z", "Az")]]),
            np.arange(2),
        )
        b.apply_block(
            build_block([1], [[encode_set_bin("y", "By")]]), np.arange(1)
        )
        snap = a.create_snapshot()
        b.restore_shards(snap, [0])  # adopt only shard 0 from A
        assert b.store.get(0, b"x") == (b"Ax", 1)
        assert b.store.get(1, b"y") == (b"By", 1)  # kept
        assert b.store.get(2, b"z") is None  # NOT adopted


class TestBackendFencing:
    def test_default_engine_is_host_kernel_only(self):
        """The engine hot path is single-backend by default: the native/
        numpy HostNodeKernel. backend='jax' is the fenced directly-
        attached-accelerator path and must be an explicit opt-in."""
        from rabia_tpu.core.config import RabiaConfig
        from rabia_tpu.core.network import ClusterConfig
        from rabia_tpu.core.state_machine import InMemoryStateMachine
        from rabia_tpu.core.types import NodeId
        from rabia_tpu.kernel.host_driver import HostNodeKernel
        from rabia_tpu.net import InMemoryHub

        nodes = [NodeId.from_int(i + 1) for i in range(3)]
        hub = InMemoryHub()
        eng = RabiaEngine(
            ClusterConfig.new(nodes[0], nodes),
            InMemoryStateMachine(),
            hub.register(nodes[0]),
            config=RabiaConfig(),
        )
        assert eng._host_kernel
        assert type(eng.kernel) is HostNodeKernel

    @pytest.mark.jax_backend
    def test_jax_backend_warns_on_selection(self, caplog):
        import logging

        from rabia_tpu.core.config import RabiaConfig
        from rabia_tpu.core.network import ClusterConfig
        from rabia_tpu.core.state_machine import InMemoryStateMachine
        from rabia_tpu.core.types import NodeId
        from rabia_tpu.net import InMemoryHub

        nodes = [NodeId.from_int(i + 1) for i in range(3)]
        hub = InMemoryHub()
        with caplog.at_level(logging.WARNING, logger="rabia_tpu.engine"):
            RabiaEngine(
                ClusterConfig.new(nodes[0], nodes),
                InMemoryStateMachine(),
                hub.register(nodes[0]),
                config=RabiaConfig().with_kernel(
                    num_shards=2, shard_pad_multiple=2, backend="jax"
                ),
            )
        assert any("directly-attached" in r.message for r in caplog.records)
