"""Integration tests: N real engines over in-process transports.

Reference parity: rabia-testing/tests/integration_basic.rs (N engines +
InMemoryNetwork, :19-80) and integration_consensus.rs (loss/latency
scenarios). Unlike the reference CI — which tolerates consensus failure
(integration_consensus.rs:48-53 masks its vote-routing deviation) — these
tests REQUIRE AllCommitted to actually hold (SURVEY.md §4.4).
"""

import asyncio

import pytest

from rabia_tpu.core.config import RabiaConfig
from rabia_tpu.core.errors import QuorumNotAvailableError
from rabia_tpu.core.network import ClusterConfig
from rabia_tpu.core.state_machine import InMemoryStateMachine
from rabia_tpu.core.types import CommandBatch, NodeId
from rabia_tpu.engine import RabiaEngine, slot_proposer
from rabia_tpu.net import (
    InMemoryHub,
    NetworkConditions,
    NetworkSimulator,
)


def _mk_config(n_shards: int = 2) -> RabiaConfig:
    return RabiaConfig(
        phase_timeout=0.4,
        heartbeat_interval=0.05,
        round_interval=0.002,
        cleanup_interval=1.0,
    ).with_kernel(num_shards=n_shards, shard_pad_multiple=2)


async def _spin_cluster(n, config, transport_factory):
    nodes = [NodeId.from_int(i + 1) for i in range(n)]
    engines, sms, tasks = [], [], []
    for node in nodes:
        sm = InMemoryStateMachine()
        transport = transport_factory(node)
        eng = RabiaEngine(
            ClusterConfig.new(node, nodes), sm, transport, config=config
        )
        engines.append(eng)
        sms.append(sm)
        tasks.append(asyncio.ensure_future(eng.run()))
    # let heartbeats establish quorum
    for _ in range(200):
        await asyncio.sleep(0.01)
        stats = [await e.get_statistics() for e in engines]
        if all(s.has_quorum for s in stats):
            break
    return nodes, engines, sms, tasks


async def _teardown(engines, tasks):
    for e in engines:
        await e.shutdown()
    for t in tasks:
        t.cancel()
    await asyncio.gather(*tasks, return_exceptions=True)


async def _converged(sms, key, value, timeout=10.0):
    async def wait():
        while not all(sm.get(key) == value for sm in sms):
            await asyncio.sleep(0.02)

    await asyncio.wait_for(wait(), timeout)


class TestThreeNodeInMemory:
    @pytest.mark.asyncio
    async def test_single_batch_commits_everywhere(self):
        hub = InMemoryHub()
        _, engines, sms, tasks = await _spin_cluster(
            3, _mk_config(), hub.register
        )
        try:
            fut = await engines[0].submit_batch(
                CommandBatch.new(["SET a 1", "SET b 2"]), shard=0
            )
            responses = await asyncio.wait_for(fut, 10.0)
            assert responses == [b"OK", b"OK"]
            await _converged(sms, "a", "1")
            await _converged(sms, "b", "2")
        finally:
            await _teardown(engines, tasks)

    @pytest.mark.asyncio
    async def test_submissions_from_every_node(self):
        hub = InMemoryHub()
        _, engines, sms, tasks = await _spin_cluster(
            3, _mk_config(), hub.register
        )
        try:
            futs = []
            for i, e in enumerate(engines):
                futs.append(
                    await e.submit_batch(
                        CommandBatch.new([f"SET k{i} v{i}"]), shard=i % 2
                    )
                )
            for f in futs:
                await asyncio.wait_for(f, 15.0)
            for i in range(3):
                await _converged(sms, f"k{i}", f"v{i}")
            stats = [await e.get_statistics() for e in engines]
            assert all(s.decided_v1 >= 3 for s in stats)
        finally:
            await _teardown(engines, tasks)

    @pytest.mark.asyncio
    async def test_no_quorum_rejects_submission(self):
        hub = InMemoryHub()
        nodes = [NodeId.from_int(i + 1) for i in range(3)]
        sm = InMemoryStateMachine()
        eng = RabiaEngine(
            ClusterConfig.new(nodes[0], nodes),
            sm,
            hub.register(nodes[0]),
            config=_mk_config(),
        )
        # never started peers: no quorum
        with pytest.raises(QuorumNotAvailableError):
            await eng.submit_batch(CommandBatch.new(["SET x 1"]))

    @pytest.mark.asyncio
    async def test_shutdown_without_run_returns(self):
        hub = InMemoryHub()
        nodes = [NodeId.from_int(1)]
        eng = RabiaEngine(
            ClusterConfig.new(nodes[0], nodes),
            InMemoryStateMachine(),
            hub.register(nodes[0]),
            config=_mk_config(),
        )
        await asyncio.wait_for(eng.shutdown(), 1.0)


class TestSimulatedConditions:
    @pytest.mark.asyncio
    async def test_commits_under_packet_loss(self):
        sim = NetworkSimulator(NetworkConditions.lossy(0.20), seed=7)
        _, engines, sms, tasks = await _spin_cluster(
            3, _mk_config(), sim.register
        )
        try:
            fut = await engines[1].submit_batch(
                CommandBatch.new(["SET lossy yes"]), shard=0
            )
            await asyncio.wait_for(fut, 20.0)
            await _converged(sms, "lossy", "yes", timeout=20.0)
        finally:
            await _teardown(engines, tasks)
            await sim.close()

    @pytest.mark.asyncio
    async def test_commits_under_latency(self):
        sim = NetworkSimulator(
            NetworkConditions(latency_min=0.005, latency_max=0.02), seed=7
        )
        _, engines, sms, tasks = await _spin_cluster(
            3, _mk_config(), sim.register
        )
        try:
            fut = await engines[0].submit_batch(
                CommandBatch.new(["SET slow ok"]), shard=1
            )
            await asyncio.wait_for(fut, 20.0)
            await _converged(sms, "slow", "ok", timeout=20.0)
            assert sim.stats.average_latency > 0.001
        finally:
            await _teardown(engines, tasks)
            await sim.close()

    @pytest.mark.asyncio
    async def test_minority_crash_still_commits(self):
        sim = NetworkSimulator(seed=3)
        nodes_all, engines, sms, tasks = await _spin_cluster(
            3, _mk_config(), sim.register
        )
        try:
            sim.crash(nodes_all[2])
            await asyncio.sleep(0.2)
            fut = await engines[0].submit_batch(
                CommandBatch.new(["SET crashy fine"]), shard=0
            )
            await asyncio.wait_for(fut, 20.0)
            await _converged(sms[:2], "crashy", "fine", timeout=20.0)
        finally:
            await _teardown(engines, tasks)
            await sim.close()


class TestSlotProposer:
    def test_rotation_covers_all_replicas(self):
        rows = {slot_proposer(0, slot, 5) for slot in range(5)}
        assert rows == set(range(5))

    def test_deterministic(self):
        assert slot_proposer(3, 7, 5) == slot_proposer(3, 7, 5)
