"""Persistence backends + engine checkpoint/resume.

Reference parity: rabia-persistence/src/tests.rs:1-86 (round-trips) and the
engine's save-after-commit / restore-on-initialize cycle
(engine.rs:156-182, :238-261).
"""

import asyncio

import pytest

from rabia_tpu.core.persistence import PersistedEngineState
from rabia_tpu.core.state_machine import Snapshot
from rabia_tpu.persistence import FileSystemPersistence, InMemoryPersistence


class TestInMemory:
    @pytest.mark.asyncio
    async def test_roundtrip(self):
        p = InMemoryPersistence()
        assert await p.load_state() is None
        await p.save_state(b"hello")
        assert await p.load_state() == b"hello"

    @pytest.mark.asyncio
    async def test_overwrite(self):
        p = InMemoryPersistence()
        await p.save_state(b"a")
        await p.save_state(b"b")
        assert await p.load_state() == b"b"


class TestFileSystem:
    @pytest.mark.asyncio
    async def test_roundtrip(self, tmp_path):
        p = FileSystemPersistence(tmp_path / "node1")
        assert await p.load_state() is None
        await p.save_state(b"durable")
        assert await p.load_state() == b"durable"
        # fresh instance reads the same file
        p2 = FileSystemPersistence(tmp_path / "node1")
        assert await p2.load_state() == b"durable"

    @pytest.mark.asyncio
    async def test_atomic_no_tmp_left_behind(self, tmp_path):
        p = FileSystemPersistence(tmp_path)
        await p.save_state(b"x" * 100_000)
        leftovers = [f for f in tmp_path.iterdir() if f.suffix == ".tmp"]
        assert leftovers == []

    def test_sync_wrappers(self, tmp_path):
        p = FileSystemPersistence(tmp_path)
        p.save_state_sync(b"sync")
        assert p.load_state_sync() == b"sync"


class TestPersistedEngineState:
    def test_roundtrip_with_snapshot(self):
        snap = Snapshot.create(7, b"app-state")
        st = PersistedEngineState(
            current_phase=10,
            last_committed_phase=9,
            state_version=7,
            snapshot=snap,
            per_shard_phase=[3, 4, 3],
            per_shard_committed=[3, 3, 3],
        )
        back = PersistedEngineState.from_bytes(st.to_bytes())
        assert back.current_phase == 10
        assert back.snapshot.data == b"app-state"
        assert back.per_shard_phase == [3, 4, 3]

    def test_corrupt_rejected(self):
        import pytest as _pytest

        from rabia_tpu.core.errors import PersistenceError

        with _pytest.raises(PersistenceError):
            PersistedEngineState.from_bytes(b"not json")


class TestAuxBlobs:
    @pytest.mark.asyncio
    async def test_in_memory_aux_roundtrip(self):
        p = InMemoryPersistence()
        assert await p.load_aux("vote_barrier") is None
        await p.save_aux("vote_barrier", b"\x01\x02")
        assert await p.load_aux("vote_barrier") == b"\x01\x02"
        assert await p.load_aux("other") is None

    @pytest.mark.asyncio
    async def test_file_aux_roundtrip(self, tmp_path):
        p = FileSystemPersistence(tmp_path)
        assert await p.load_aux("vote_barrier") is None
        await p.save_aux("vote_barrier", b"\x09" * 24)
        assert await p.load_aux("vote_barrier") == b"\x09" * 24
        # separate channel: main blob untouched
        assert await p.load_state() is None
        # fresh instance reads the same aux file
        p2 = FileSystemPersistence(tmp_path)
        assert await p2.load_aux("vote_barrier") == b"\x09" * 24

    @pytest.mark.asyncio
    async def test_base_class_default_is_noop(self):
        from rabia_tpu.core.persistence import PersistenceLayer

        class Minimal(PersistenceLayer):
            async def save_state(self, data):
                pass

            async def load_state(self):
                return None

        m = Minimal()
        await m.save_aux("k", b"v")  # must not raise
        assert await m.load_aux("k") is None


def _mk_restart_engine(nodes, persistence, config):
    from rabia_tpu.core.network import ClusterConfig
    from rabia_tpu.core.state_machine import InMemoryStateMachine
    from rabia_tpu.engine import RabiaEngine
    from rabia_tpu.net import InMemoryHub

    hub = InMemoryHub()
    return RabiaEngine(
        ClusterConfig.new(nodes[0], nodes),
        InMemoryStateMachine(),
        hub.register(nodes[0]),
        persistence=persistence,
        config=config,
    )


class TestRestoreTaint:
    """Restart-equivocation guard: slots the pre-crash process may have
    voted in are not re-voted after restore; they rejoin via adopted peer
    Decisions / sync, or the taint lifts after a quiet release window."""

    @pytest.mark.asyncio
    async def test_vote_barrier_written_ahead_of_votes(self):
        """A node that opens a slot persists the barrier in the same tick,
        before any vote leaves (write-ahead)."""
        import numpy as np

        from rabia_tpu.core.config import RabiaConfig
        from rabia_tpu.core.types import NodeId

        nodes = [NodeId.from_int(i + 1) for i in range(3)]
        config = RabiaConfig(
            phase_timeout=0.4, heartbeat_interval=0.05, round_interval=0.002
        ).with_kernel(num_shards=1, shard_pad_multiple=1)
        p = InMemoryPersistence()
        eng = _mk_restart_engine(nodes, p, config)
        await eng._advance_vote_barrier([(0, 0, 1)])
        raw = await p.load_aux("vote_barrier")
        assert raw is not None
        # write-ahead: the barrier covers the opened slot (it is persisted
        # barrier_stride ahead, amortizing one fsync over K opens)
        assert np.frombuffer(raw, np.int64)[0] > 0
        assert p.aux_saves == 1
        # re-opening any slot under the stride does not re-persist
        await eng._advance_vote_barrier([(0, 0, 1)])
        await eng._advance_vote_barrier([(0, 1, 1)])
        assert p.aux_saves == 1

    @pytest.mark.asyncio
    async def test_tainted_slot_not_reopened(self):
        import time as _time

        import numpy as np

        from rabia_tpu.core.config import RabiaConfig
        from rabia_tpu.core.persistence import PersistedEngineState
        from rabia_tpu.core.types import CommandBatch, NodeId

        nodes = [NodeId.from_int(i + 1) for i in range(3)]
        config = RabiaConfig(
            phase_timeout=0.4, heartbeat_interval=0.05, round_interval=0.002
        ).with_kernel(num_shards=1, shard_pad_multiple=1)
        p = InMemoryPersistence()
        # pre-crash: applied 0 slots, but barrier says "may have voted in
        # slots < 1" (slot 0 was opened)
        await p.save_engine_state(
            PersistedEngineState(per_shard_phase=[0], per_shard_committed=[0])
        )
        await p.save_aux("vote_barrier", np.asarray([1], np.int64).tobytes())
        eng = _mk_restart_engine(nodes, p, config)
        await eng.initialize()
        assert eng.rt.shards[0].tainted_upto == 1
        # we are slot 0's proposer ((0+0)%3 == 0) with a queued batch, yet
        # the tainted slot must not open
        eng.rt.has_quorum = True
        await eng.submit_batch(CommandBatch.new(["SET a 1"]), shard=0)
        assert eng._open_slots() == []
        assert eng.rt.shards[0].in_flight is False
        # a peer's Decision for the slot is adopted without voting
        eng.rt.shards[0].buf_decision[0] = (1, None)  # V1... no batch known
        eng.rt.shards[0].buf_propose[0] = (CommandBatch.new(["SET x 9"]).id, None)
        opened = eng._open_slots()
        assert opened == []  # adopted, not opened
        assert 0 in eng.rt.shards[0].decisions
        _ = _time  # silence linters

    @pytest.mark.asyncio
    async def test_taint_lifts_after_quiet_window(self):
        import time as _time

        import numpy as np

        from rabia_tpu.core.config import RabiaConfig
        from rabia_tpu.core.persistence import PersistedEngineState
        from rabia_tpu.core.types import CommandBatch, NodeId

        nodes = [NodeId.from_int(i + 1) for i in range(3)]
        config = RabiaConfig(
            phase_timeout=0.05, heartbeat_interval=0.05, round_interval=0.002
        ).with_kernel(num_shards=1, shard_pad_multiple=1)
        p = InMemoryPersistence()
        await p.save_engine_state(
            PersistedEngineState(per_shard_phase=[0], per_shard_committed=[0])
        )
        await p.save_aux("vote_barrier", np.asarray([1], np.int64).tobytes())
        eng = _mk_restart_engine(nodes, p, config)
        await eng.initialize()
        assert eng.rt.shards[0].tainted_upto == 1
        eng.rt.has_quorum = True
        await eng.submit_batch(CommandBatch.new(["SET a 1"]), shard=0)
        # nothing observed for the tainted slot AND the full membership in
        # view: after one release window the shard resumes (first call
        # clears the taint, next call opens)
        eng.rt.active_nodes = set(nodes)
        eng._restored_at = _time.time() - (eng._taint_release + 1.0)
        eng._open_slots()
        assert eng.rt.shards[0].tainted_upto == 0
        opened = eng._open_slots()
        assert [(s, slot) for s, slot, _v in opened] == [(0, 0)]

    @pytest.mark.asyncio
    async def test_taint_held_longer_with_absent_peers(self):
        # an absent peer is the one that could still hold pre-crash votes:
        # with a partial view the release window stretches 4x
        import time as _time

        import numpy as np

        from rabia_tpu.core.config import RabiaConfig
        from rabia_tpu.core.persistence import PersistedEngineState
        from rabia_tpu.core.types import CommandBatch, NodeId

        nodes = [NodeId.from_int(i + 1) for i in range(3)]
        config = RabiaConfig(
            phase_timeout=0.05, heartbeat_interval=0.05, round_interval=0.002
        ).with_kernel(num_shards=1, shard_pad_multiple=1)
        p = InMemoryPersistence()
        await p.save_engine_state(
            PersistedEngineState(per_shard_phase=[0], per_shard_committed=[0])
        )
        await p.save_aux("vote_barrier", np.asarray([1], np.int64).tobytes())
        eng = _mk_restart_engine(nodes, p, config)
        await eng.initialize()
        eng.rt.has_quorum = True
        await eng.submit_batch(CommandBatch.new(["SET a 1"]), shard=0)
        eng.rt.active_nodes = set(nodes[:2])  # one member out of view
        eng._restored_at = _time.time() - (eng._taint_release + 1.0)
        eng._open_slots()
        assert eng.rt.shards[0].tainted_upto == 1  # still held
        eng._restored_at = _time.time() - (4 * eng._taint_release + 1.0)
        eng._open_slots()
        assert eng.rt.shards[0].tainted_upto == 0

    @pytest.mark.asyncio
    async def test_taint_held_while_traffic_observed(self):
        import time as _time

        import numpy as np

        from rabia_tpu.core.config import RabiaConfig
        from rabia_tpu.core.messages import VoteEntry
        from rabia_tpu.core.persistence import PersistedEngineState
        from rabia_tpu.core.types import NodeId, StateValue
        from rabia_tpu.kernel.phase_driver import pack_phase

        nodes = [NodeId.from_int(i + 1) for i in range(3)]
        config = RabiaConfig(
            phase_timeout=0.05, heartbeat_interval=0.05, round_interval=0.002
        ).with_kernel(num_shards=1, shard_pad_multiple=1)
        p = InMemoryPersistence()
        await p.save_engine_state(
            PersistedEngineState(per_shard_phase=[0], per_shard_committed=[0])
        )
        await p.save_aux("vote_barrier", np.asarray([1], np.int64).tobytes())
        eng = _mk_restart_engine(nodes, p, config)
        await eng.initialize()
        # a peer's vote for the tainted slot arrives: peers are deciding it
        eng._buffer_votes(
            1, (VoteEntry(0, pack_phase(0, 0), StateValue.V1),), round_no=1
        )
        assert eng.rt.shards[0].taint_traffic is True
        eng._restored_at = _time.time() - (eng._taint_release + 1.0)
        eng._open_slots()
        assert eng.rt.shards[0].tainted_upto == 1  # still held

    @pytest.mark.asyncio
    async def test_single_replica_never_taints(self):
        import numpy as np

        from rabia_tpu.core.config import RabiaConfig
        from rabia_tpu.core.persistence import PersistedEngineState
        from rabia_tpu.core.types import NodeId

        nodes = [NodeId.from_int(1)]
        config = RabiaConfig(
            phase_timeout=0.05, heartbeat_interval=0.05, round_interval=0.002
        ).with_kernel(num_shards=1, shard_pad_multiple=1)
        p = InMemoryPersistence()
        await p.save_engine_state(
            PersistedEngineState(per_shard_phase=[2], per_shard_committed=[2])
        )
        await p.save_aux("vote_barrier", np.asarray([3], np.int64).tobytes())
        eng = _mk_restart_engine(nodes, p, config)
        await eng.initialize()
        assert eng.rt.shards[0].tainted_upto == 0


class TestEngineCheckpointResume:
    @pytest.mark.asyncio
    async def test_restart_restores_state(self, tmp_path):
        """Commit on a 3-node cluster with durable persistence; restart one
        node's engine object and check it resumes from the saved state
        instead of slot 0 (engine.rs:238-261)."""
        from rabia_tpu.core.config import RabiaConfig
        from rabia_tpu.core.network import ClusterConfig
        from rabia_tpu.core.state_machine import InMemoryStateMachine
        from rabia_tpu.core.types import CommandBatch, NodeId
        from rabia_tpu.engine import RabiaEngine
        from rabia_tpu.net import InMemoryHub

        nodes = [NodeId.from_int(i + 1) for i in range(3)]
        hub = InMemoryHub()
        config = RabiaConfig(
            phase_timeout=0.4, heartbeat_interval=0.05, round_interval=0.002
        ).with_kernel(num_shards=1, shard_pad_multiple=1)
        persists = [FileSystemPersistence(tmp_path / str(i)) for i in range(3)]
        engines, sms, tasks = [], [], []
        for i, n in enumerate(nodes):
            sm = InMemoryStateMachine()
            engines.append(
                RabiaEngine(
                    ClusterConfig.new(n, nodes),
                    sm,
                    hub.register(n),
                    persistence=persists[i],
                    config=config,
                )
            )
            sms.append(sm)
            tasks.append(asyncio.ensure_future(engines[-1].run()))
        try:
            for _ in range(200):
                await asyncio.sleep(0.01)
                sts = [await e.get_statistics() for e in engines]
                if all(s.has_quorum for s in sts):
                    break
            fut = await engines[0].submit_batch(CommandBatch.new(["SET k v"]))
            await asyncio.wait_for(fut, 15.0)

            # wait for node 0's post-commit save to land on disk
            async def saved():
                while True:
                    blob = await persists[0].load_state()
                    if blob is not None:
                        st = PersistedEngineState.from_bytes(blob)
                        if st.last_committed_phase >= 1:
                            return
                    await asyncio.sleep(0.02)

            await asyncio.wait_for(saved(), 10.0)
        finally:
            for e in engines:
                await e.shutdown()
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)

        # "restart": fresh engine + SM over the same persistence dir
        sm2 = InMemoryStateMachine()
        hub2 = InMemoryHub()
        eng2 = RabiaEngine(
            ClusterConfig.new(nodes[0], nodes),
            sm2,
            hub2.register(nodes[0]),
            persistence=persists[0],
            config=config,
        )
        await eng2.initialize()
        assert eng2.rt.shards[0].applied_upto >= 1
        assert sm2.get("k") == "v"
