"""Persistence backends + engine checkpoint/resume.

Reference parity: rabia-persistence/src/tests.rs:1-86 (round-trips) and the
engine's save-after-commit / restore-on-initialize cycle
(engine.rs:156-182, :238-261).
"""

import asyncio

import pytest

from rabia_tpu.core.persistence import PersistedEngineState
from rabia_tpu.core.state_machine import Snapshot
from rabia_tpu.persistence import FileSystemPersistence, InMemoryPersistence


class TestInMemory:
    @pytest.mark.asyncio
    async def test_roundtrip(self):
        p = InMemoryPersistence()
        assert await p.load_state() is None
        await p.save_state(b"hello")
        assert await p.load_state() == b"hello"

    @pytest.mark.asyncio
    async def test_overwrite(self):
        p = InMemoryPersistence()
        await p.save_state(b"a")
        await p.save_state(b"b")
        assert await p.load_state() == b"b"


class TestFileSystem:
    @pytest.mark.asyncio
    async def test_roundtrip(self, tmp_path):
        p = FileSystemPersistence(tmp_path / "node1")
        assert await p.load_state() is None
        await p.save_state(b"durable")
        assert await p.load_state() == b"durable"
        # fresh instance reads the same file
        p2 = FileSystemPersistence(tmp_path / "node1")
        assert await p2.load_state() == b"durable"

    @pytest.mark.asyncio
    async def test_atomic_no_tmp_left_behind(self, tmp_path):
        p = FileSystemPersistence(tmp_path)
        await p.save_state(b"x" * 100_000)
        leftovers = [f for f in tmp_path.iterdir() if f.suffix == ".tmp"]
        assert leftovers == []

    def test_sync_wrappers(self, tmp_path):
        p = FileSystemPersistence(tmp_path)
        p.save_state_sync(b"sync")
        assert p.load_state_sync() == b"sync"


class TestPersistedEngineState:
    def test_roundtrip_with_snapshot(self):
        snap = Snapshot.create(7, b"app-state")
        st = PersistedEngineState(
            current_phase=10,
            last_committed_phase=9,
            state_version=7,
            snapshot=snap,
            per_shard_phase=[3, 4, 3],
            per_shard_committed=[3, 3, 3],
        )
        back = PersistedEngineState.from_bytes(st.to_bytes())
        assert back.current_phase == 10
        assert back.snapshot.data == b"app-state"
        assert back.per_shard_phase == [3, 4, 3]

    def test_corrupt_rejected(self):
        import pytest as _pytest

        from rabia_tpu.core.errors import PersistenceError

        with _pytest.raises(PersistenceError):
            PersistedEngineState.from_bytes(b"not json")


class TestEngineCheckpointResume:
    @pytest.mark.asyncio
    async def test_restart_restores_state(self, tmp_path):
        """Commit on a 3-node cluster with durable persistence; restart one
        node's engine object and check it resumes from the saved state
        instead of slot 0 (engine.rs:238-261)."""
        from rabia_tpu.core.config import RabiaConfig
        from rabia_tpu.core.network import ClusterConfig
        from rabia_tpu.core.state_machine import InMemoryStateMachine
        from rabia_tpu.core.types import CommandBatch, NodeId
        from rabia_tpu.engine import RabiaEngine
        from rabia_tpu.net import InMemoryHub

        nodes = [NodeId.from_int(i + 1) for i in range(3)]
        hub = InMemoryHub()
        config = RabiaConfig(
            phase_timeout=0.4, heartbeat_interval=0.05, round_interval=0.002
        ).with_kernel(num_shards=1, shard_pad_multiple=1)
        persists = [FileSystemPersistence(tmp_path / str(i)) for i in range(3)]
        engines, sms, tasks = [], [], []
        for i, n in enumerate(nodes):
            sm = InMemoryStateMachine()
            engines.append(
                RabiaEngine(
                    ClusterConfig.new(n, nodes),
                    sm,
                    hub.register(n),
                    persistence=persists[i],
                    config=config,
                )
            )
            sms.append(sm)
            tasks.append(asyncio.ensure_future(engines[-1].run()))
        try:
            for _ in range(200):
                await asyncio.sleep(0.01)
                sts = [await e.get_statistics() for e in engines]
                if all(s.has_quorum for s in sts):
                    break
            fut = await engines[0].submit_batch(CommandBatch.new(["SET k v"]))
            await asyncio.wait_for(fut, 15.0)

            # wait for node 0's post-commit save to land on disk
            async def saved():
                while True:
                    blob = await persists[0].load_state()
                    if blob is not None:
                        st = PersistedEngineState.from_bytes(blob)
                        if st.last_committed_phase >= 1:
                            return
                    await asyncio.sleep(0.02)

            await asyncio.wait_for(saved(), 10.0)
        finally:
            for e in engines:
                await e.shutdown()
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)

        # "restart": fresh engine + SM over the same persistence dir
        sm2 = InMemoryStateMachine()
        hub2 = InMemoryHub()
        eng2 = RabiaEngine(
            ClusterConfig.new(nodes[0], nodes),
            sm2,
            hub2.register(nodes[0]),
            persistence=persists[0],
            config=config,
        )
        await eng2.initialize()
        assert eng2.rt.shards[0].applied_upto >= 1
        assert sm2.get("k") == "v"
