"""Native engine runtime (native/runtime.cpp + engine/runtime_bridge.py).

Covers: activation preconditions, scalar + block commits through the
GIL-free io/tick thread, the zero-GIL-per-wave acceptance counter (and
its /metrics exposure), runtime-vs-asyncio conformance on fixed
schedules, shutdown ordering (runtime drain -> apply flush -> transport
close) including a mid-wave shutdown that must not lose staged result
frames, and the runtime flight-recorder kinds.

The asyncio orchestration stays the semantics owner: RABIA_PY_RUNTIME=1
forces it; scripts/fuzz_conformance.py --runtime draws fresh schedules.
"""

from __future__ import annotations

import asyncio
import time

import numpy as np
import pytest

from rabia_tpu.apps import make_sharded_kv
from rabia_tpu.apps.kvstore import encode_set_bin
from rabia_tpu.core.blocks import build_block
from rabia_tpu.core.config import RabiaConfig, TcpNetworkConfig
from rabia_tpu.core.network import ClusterConfig
from rabia_tpu.core.types import Command, CommandBatch, NodeId
from rabia_tpu.engine import RabiaEngine
from rabia_tpu.engine.leader import slot_proposer_vec
from rabia_tpu.net.tcp import TcpNetwork


def _runtime_lib():
    from rabia_tpu.native.build import load_runtime

    return load_runtime()


pytestmark = pytest.mark.skipif(
    _runtime_lib() is None, reason="native runtime library unavailable"
)


async def _mk_cluster(S: int, R: int, **cfg_kw):
    ids = [NodeId.from_int(i + 1) for i in range(R)]
    nets = [TcpNetwork(i, TcpNetworkConfig(bind_port=0)) for i in ids]
    for i in range(R):
        for j in range(R):
            if i != j:
                nets[i].add_peer(ids[j], "127.0.0.1", nets[j].port)
    cfg = RabiaConfig(
        phase_timeout=cfg_kw.pop("phase_timeout", 2.0),
        heartbeat_interval=0.05,
        round_interval=0.002,
    ).with_kernel(num_shards=S, shard_pad_multiple=max(1, S))
    engines, machines, tasks = [], [], []
    for i, n in enumerate(ids):
        sm, ms = make_sharded_kv(S)
        machines.append(ms)
        e = RabiaEngine(ClusterConfig.new(n, ids), sm, nets[i], config=cfg)
        engines.append(e)
        tasks.append(asyncio.ensure_future(e.run()))
    for _ in range(600):
        await asyncio.sleep(0.01)
        if all([(await e.get_statistics()).has_quorum for e in engines]):
            break
    else:
        raise AssertionError("cluster never formed quorum")
    return ids, nets, engines, machines, tasks


async def _teardown(engines, tasks, nets):
    for e in engines:
        await e.shutdown()
    for t in tasks:
        t.cancel()
    await asyncio.gather(*tasks, return_exceptions=True)
    for n in nets:
        await n.close()


def _own_shards(e, S: int) -> np.ndarray:
    shard_ids = np.arange(S)
    head = np.maximum(e.rt.next_slot[:S], e.rt.applied_upto[:S])
    return shard_ids[
        (slot_proposer_vec(shard_ids, head, e.R) == e.me)
        & (e.rt.queue_len[:S] == 0)
        & ~e.rt.in_flight[:S]
    ]


class TestRuntimeActivation:
    def test_active_on_tcp_inactive_on_env(self, monkeypatch):
        async def run():
            _, nets, engines, _, tasks = await _mk_cluster(4, 3)
            try:
                assert all(e._rtm is not None for e in engines)
                assert all(e.health()["native_runtime"] for e in engines)
                # the transport's Python reader is detached: the runtime
                # thread owns the inbox
                assert all(n._reader_detached for n in nets)
            finally:
                await _teardown(engines, tasks, nets)

        asyncio.run(run())

        async def run_forced():
            _, nets, engines, _, tasks = await _mk_cluster(4, 3)
            try:
                assert all(e._rtm is None for e in engines)
            finally:
                await _teardown(engines, tasks, nets)

        monkeypatch.setenv("RABIA_PY_RUNTIME", "1")
        asyncio.run(run_forced())

    def test_inactive_on_inmemory_hub(self):
        async def run():
            from rabia_tpu.net import InMemoryHub

            hub = InMemoryHub()
            ids = [NodeId.from_int(i + 1) for i in range(3)]
            engines = [
                RabiaEngine(
                    ClusterConfig.new(n, ids),
                    make_sharded_kv(2)[0],
                    hub.register(n),
                    config=RabiaConfig().with_kernel(
                        num_shards=2, shard_pad_multiple=2
                    ),
                )
                for n in ids
            ]
            assert all(e._rtm is None for e in engines)

        asyncio.run(run())


class TestRuntimeCommit:
    def test_scalar_and_block_commit_and_gil_counter(self):
        async def run():
            S, R = 8, 3
            _, nets, engines, machines, tasks = await _mk_cluster(S, R)
            try:
                e0 = engines[0]
                # scalar commit
                fut = await e0.submit_batch(
                    CommandBatch.new(
                        [Command.new(encode_set_bin("k", "v"))], shard=1
                    ),
                    shard=1,
                )
                res = await asyncio.wait_for(fut, 10.0)
                assert len(res) == 1 and res[0][0] == 0  # ok result frame
                gil_before = e0._rtm.counter("gil_handoffs")
                waves_before = e0._rtm.counter("waves_native")
                # block-only waves on each engine's own shards: the
                # decide->apply->result path must never take the GIL
                for _ in range(5):
                    futs = []
                    for e in engines:
                        mine = _own_shards(e, S)
                        if len(mine) == 0:
                            continue
                        futs.append(
                            await e.submit_block(
                                build_block(
                                    mine,
                                    [
                                        [encode_set_bin(f"k{int(s)}", "v")]
                                        for s in mine
                                    ],
                                )
                            )
                        )
                    results = await asyncio.wait_for(
                        asyncio.gather(*futs), 20.0
                    )
                    for r in results:
                        for entry in r:
                            assert not isinstance(entry, Exception)
                assert e0._rtm.counter("waves_native") > waves_before
                assert e0._rtm.counter("gil_handoffs") == gil_before, (
                    "steady-state native waves took a GIL handoff"
                )
                # /metrics exposure of the acceptance counter
                snap = e0.metrics.snapshot()
                assert snap.get("rabia_engine_native_runtime") == 1
                assert snap.get("rabia_runtime_waves_native_total", 0) > 0
                assert "rabia_runtime_gil_handoffs_total" in snap
                # replica state converges
                await asyncio.sleep(0.3)
                want = [m.store.checksum() for m in machines[0]]
                for _ in range(200):
                    if all(
                        [m.store.checksum() for m in ms] == want
                        for ms in machines
                    ):
                        break
                    await asyncio.sleep(0.01)
                assert all(
                    [m.store.checksum() for m in ms] == want
                    for ms in machines
                )
            finally:
                await _teardown(engines, tasks, nets)

        asyncio.run(run())

    def test_flight_runtime_kinds_present(self):
        async def run():
            S, R = 4, 3
            _, nets, engines, _, tasks = await _mk_cluster(S, R)
            try:
                e0 = engines[0]
                fut = await e0.submit_batch(
                    CommandBatch.new(
                        [Command.new(encode_set_bin("fk", "fv"))], shard=0
                    ),
                    shard=0,
                )
                await asyncio.wait_for(fut, 10.0)
                kinds = {ev["kind"] for ev in e0.flight_events()}
                assert "rt_wake" in kinds, kinds
                assert "rt_handoff" in kinds, kinds
                # lifecycle records still present alongside
                assert {"submit", "propose", "decide", "apply"} <= kinds
            finally:
                await _teardown(engines, tasks, nets)

        asyncio.run(run())


class TestRuntimeConformance:
    def test_fixed_schedules_match_asyncio_owner(self):
        from rabia_tpu.testing.conformance import (
            run_schedule_on_runtime_paths,
        )

        schedule = [
            {0: [("a", "1")], 1: [("b", "2"), ("c", "3")]},
            {0: [("a", "4")], 2: [("d", "5")]},
            {1: [("b", "6")], 2: [("e", "7")], 0: [("f", "8")]},
            {0: [("a", "9")], 1: [("g", "10")]},
        ]
        asyncio.run(
            run_schedule_on_runtime_paths(
                schedule, n_shards=3, n_replicas=3, tag="fixed-runtime"
            )
        )


class TestRuntimeShutdown:
    def test_shutdown_ordering_clean(self):
        """Runtime drain -> apply flush -> transport close: state and
        counters survive shutdown; the transport closes last."""

        async def run():
            S, R = 4, 3
            _, nets, engines, machines, tasks = await _mk_cluster(S, R)
            e0 = engines[0]
            fut = await e0.submit_batch(
                CommandBatch.new(
                    [Command.new(encode_set_bin("sk", "sv"))], shard=0
                ),
                shard=0,
            )
            await asyncio.wait_for(fut, 10.0)
            await _teardown(engines, tasks, nets)
            # post-shutdown: frozen counters and flight stay readable
            assert e0._rtm.counter("frames_native") > 0
            assert len(e0.flight_events()) > 0
            assert machines[0][0].store.get("sk").value == "sv"

        asyncio.run(run())

    def test_mid_wave_shutdown_keeps_staged_results(self):
        """A decided wave whose result frames are staged in the event
        mailbox when shutdown starts must still settle the submitter's
        future: stop() finishes the runtime iteration and drains the
        mailbox BEFORE the transport closes."""

        async def run():
            S, R = 8, 3
            _, nets, engines, machines, tasks = await _mk_cluster(S, R)
            e0 = engines[0]
            mine = _own_shards(e0, S)
            assert len(mine) > 0
            fut = await e0.submit_block(
                build_block(
                    mine,
                    [[encode_set_bin(f"m{int(s)}", "w")] for s in mine],
                )
            )
            # push the wave command down WITHOUT letting the event loop
            # drain the mailbox, then block the loop synchronously while
            # the C threads decide and apply the wave — the staged
            # results sit in the event ring when shutdown begins
            e0._rtm.pump()
            deadline = time.time() + 5.0
            while (
                e0._rtm.counter("slots_applied") < len(mine)
                and time.time() < deadline
            ):
                time.sleep(0.01)  # deliberately sync: no drain can run
            assert e0._rtm.counter("slots_applied") >= len(mine), (
                "wave never applied natively"
            )
            assert not fut.done(), "future settled without a drain?"
            await e0.shutdown()  # runtime drain happens in here
            assert fut.done(), "mid-wave shutdown lost staged results"
            res = fut.result()
            assert len(res) == len(mine)
            for entry in res:
                assert not isinstance(entry, Exception)
                assert len(entry) == 1 and bytes(entry[0])[0] == 0
            for e in engines[1:]:
                await e.shutdown()
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            for n in nets:
                await n.close()

        asyncio.run(run())


class TestShardGroupRuntime:
    """Thread-per-shard-group runtime (round 14): N C worker threads,
    each owning a contiguous shard group end-to-end. workers=1 stays the
    byte-for-byte historical runtime; these tests pin the multi-worker
    geometry, routing, per-worker observability and conformance."""

    def test_multi_worker_activation_and_commits(self, monkeypatch):
        monkeypatch.setenv("RABIA_RT_WORKERS", "2")

        async def run():
            S, R = 8, 3
            _, nets, engines, machines, tasks = await _mk_cluster(S, R)
            try:
                e0 = engines[0]
                rtm = e0._rtm
                assert rtm is not None and rtm.workers == 2
                assert rtm._chunk == 4  # contiguous groups [0,4) [4,8)
                assert rtm._group_of(0) == 0 and rtm._group_of(7) == 1
                # submit on shards of BOTH groups; every commit must land
                for s in (0, 2, 4, 7):
                    fut = await e0.submit_batch(
                        CommandBatch.new(
                            [Command.new(encode_set_bin(f"g{s}", "v"))],
                            shard=s,
                        ),
                        shard=s,
                    )
                    res = await asyncio.wait_for(fut, 10.0)
                    assert len(res) == 1 and res[0][0] == 0
                # both workers ran their loops and committed slots
                pw = [
                    rtm.counters_dict_worker(g) for g in range(rtm.workers)
                ]
                assert all(d["loops"] > 0 for d in pw)
                committed = [
                    d["decided_scalar"] + d["waves_native"] for d in pw
                ]
                assert all(cnt > 0 for cnt in committed), committed
                # aggregate counters = per-worker sums
                assert rtm.counter("decided_scalar") == sum(
                    d["decided_scalar"] for d in pw
                )
                # per-worker stage series carry the worker label on
                # /metrics next to the unlabeled aggregate
                text = e0.metrics.render_prometheus()
                assert 'rabia_runtime_stage_seconds{stage="tick"}' in text
                assert (
                    'worker="0"' in text and 'worker="1"' in text
                ), "per-worker stage series missing"
                # replica state converges across workers
                await asyncio.sleep(0.2)
                want = [m.store.checksum() for m in machines[0]]
                for _ in range(200):
                    if all(
                        [m.store.checksum() for m in ms] == want
                        for ms in machines
                    ):
                        break
                    await asyncio.sleep(0.01)
                assert all(
                    [m.store.checksum() for m in ms] == want
                    for ms in machines
                )
            finally:
                await _teardown(engines, tasks, nets)

        asyncio.run(run())

    def test_block_wave_across_groups_no_gil(self, monkeypatch):
        """A block wave spanning BOTH shard groups commits natively on
        every worker with zero GIL handoffs (the bridge splits it into
        group-pure CMD_OPEN_WAVE records; each worker applies through
        its own statekernel lane)."""
        monkeypatch.setenv("RABIA_RT_WORKERS", "2")

        async def run():
            S, R = 8, 3
            _, nets, engines, machines, tasks = await _mk_cluster(S, R)
            try:
                e0 = engines[0]
                rtm = e0._rtm
                gil_before = rtm.counter("gil_handoffs")
                waves_before = rtm.counter("waves_native")
                for _ in range(4):
                    futs = []
                    for e in engines:
                        mine = _own_shards(e, S)
                        if len(mine) == 0:
                            continue
                        futs.append(
                            await e.submit_block(
                                build_block(
                                    mine,
                                    [
                                        [encode_set_bin(f"x{int(s)}", "y")]
                                        for s in mine
                                    ],
                                )
                            )
                        )
                    results = await asyncio.wait_for(
                        asyncio.gather(*futs), 20.0
                    )
                    for r in results:
                        for entry in r:
                            assert not isinstance(entry, Exception)
                assert rtm.counter("waves_native") > waves_before
                assert rtm.counter("gil_handoffs") == gil_before, (
                    "multi-worker native waves took a GIL handoff"
                )
            finally:
                await _teardown(engines, tasks, nets)

        asyncio.run(run())

    def test_workers_conformance_vs_asyncio_and_single(self):
        """workers=2 and workers=1 each pin identical decision ledgers,
        byte-identical client responses and state checksums against the
        asyncio owner — transitively, workers=2 == workers=1.

        One bounded retry per leg (the round-7 packet_loss_30pct
        precedent): under ambient load a retransmit can race a decide
        into one extra dedup'd slot on EITHER leg, which the strict
        full-ledger compare flags; a real conformance bug is
        deterministic on the fixed schedule and fails both attempts."""
        from rabia_tpu.testing.conformance import (
            run_schedule_on_runtime_paths,
        )

        schedule = [
            {0: [("a", "1")], 3: [("b", "2"), ("c", "3")]},
            {1: [("d", "4")], 2: [("e", "5")]},
            {0: [("f", "6")], 1: [("g", "7")], 3: [("h", "8")]},
            {2: [("e", "9")], 0: [("a", "10")]},
        ]
        for w in (2, 1):
            for attempt in (0, 1):
                try:
                    asyncio.run(
                        run_schedule_on_runtime_paths(
                            schedule, n_shards=4, n_replicas=3,
                            tag=f"fixed-runtime-w{w}", workers=w,
                        )
                    )
                    break
                except AssertionError:
                    if attempt:
                        raise

    def test_workers_clamp_and_single_worker_identity(self, monkeypatch):
        """workers never exceed the shard count, and workers=1 keeps the
        historical single-ring geometry (no sibling rk contexts)."""
        monkeypatch.setenv("RABIA_RT_WORKERS", "8")

        async def run():
            S, R = 2, 3
            _, nets, engines, _, tasks = await _mk_cluster(S, R)
            try:
                rtm = engines[0]._rtm
                assert rtm is not None
                assert rtm.workers == 2  # clamped to n_shards
            finally:
                await _teardown(engines, tasks, nets)

        asyncio.run(run())

        monkeypatch.setenv("RABIA_RT_WORKERS", "1")

        async def run_single():
            S, R = 4, 3
            _, nets, engines, _, tasks = await _mk_cluster(S, R)
            try:
                rtm = engines[0]._rtm
                assert rtm is not None and rtm.workers == 1
                assert rtm._extra_rks == []
                assert engines[0]._rk.siblings == []
            finally:
                await _teardown(engines, tasks, nets)

        asyncio.run(run_single())
