"""Block lane: PayloadBlock, ProposeBlock wire, engine bulk path, bulk
service API, adaptive batching in the client path, binary kv op codec."""

from __future__ import annotations

import asyncio
import uuid

import numpy as np
import pytest

from rabia_tpu.apps import ShardedKVService, make_sharded_kv
from rabia_tpu.apps.kvstore import (
    KVOperation,
    KVStore,
    apply_op_bin,
    apply_ops_bin,
    decode_op_bin,
    decode_result_bin,
    encode_op_bin,
    encode_set_bin,
)
from rabia_tpu.core.blocks import PayloadBlock, block_batch_id, build_block
from rabia_tpu.core.config import BatchConfig, RabiaConfig
from rabia_tpu.core.errors import ValidationError
from rabia_tpu.core.messages import ProposeBlock, ProtocolMessage
from rabia_tpu.core.network import ClusterConfig
from rabia_tpu.core.serialization import Serializer
from rabia_tpu.core.types import NodeId
from rabia_tpu.engine import RabiaEngine
from rabia_tpu.net import InMemoryHub


class TestPayloadBlock:
    def test_build_and_slicing(self):
        blk = build_block(
            [3, 7, 11],
            [[b"a"], [b"bb", b"ccc"], [b"dddd"]],
        )
        assert len(blk) == 3
        assert blk.total_commands == 4
        assert blk.commands_for(0) == [b"a"]
        assert blk.commands_for(1) == [b"bb", b"ccc"]
        assert blk.commands_for(2) == [b"dddd"]
        assert blk.batch_id_for(1) == block_batch_id(blk.id, 7)

    def test_subset_shares_identity(self):
        blk = build_block([1, 2, 3], [[b"x"], [b"yy"], [b"zzz"]])
        sub = blk.subset(np.array([0, 2]))
        assert sub.id == blk.id
        assert sub.commands_for(1) == [b"zzz"]
        assert list(sub.shards) == [1, 3]

    def test_materialize_batch(self):
        blk = build_block([5], [[b"cmd1", b"cmd2"]])
        batch = blk.materialize_batch(0)
        assert int(batch.shard) == 5
        assert [c.data for c in batch.commands] == [b"cmd1", b"cmd2"]

    def test_build_rejects_bad_shapes(self):
        with pytest.raises(ValidationError):
            build_block([1, 1], [[b"a"], [b"b"]])  # duplicate shard
        with pytest.raises(ValidationError):
            build_block([1], [[]])  # empty command list

    def test_wire_roundtrip(self):
        blk = build_block([0, 9], [[b"hello"], [b"wo", b"rld"]])
        blk.slots[:] = [4, 5]
        ser = Serializer()
        msg = ProtocolMessage.new(NodeId.from_int(1), ProposeBlock(block=blk))
        back = ser.deserialize(ser.serialize(msg))
        assert back.payload == ProposeBlock(block=blk)
        assert back.payload.block.commands_for(1) == [b"wo", b"rld"]

    def test_wire_rejects_corrupt_data(self):
        from rabia_tpu.core.errors import SerializationError

        blk = build_block([0], [[b"hello"]])
        blk.slots[:] = [0]
        ser = Serializer()
        raw = bytearray(
            ser.serialize(
                ProtocolMessage.new(NodeId.from_int(1), ProposeBlock(block=blk))
            )
        )
        raw[-8] ^= 0xFF  # flip a data byte under the checksum
        with pytest.raises(SerializationError):
            ser.deserialize(bytes(raw))


class TestBinaryOpCodec:
    def test_roundtrip_all_ops(self):
        for op in (
            KVOperation.set("k", "v"),
            KVOperation.get("k"),
            KVOperation.delete("k"),
            KVOperation.exists("k"),
        ):
            assert decode_op_bin(encode_op_bin(op)) == op

    def test_apply_matches_typed_store(self):
        a, b = KVStore(), KVStore()
        r1 = apply_op_bin(a, encode_set_bin("x", "1"))
        r2 = b.set("x", "1")
        assert decode_result_bin(r1).version == r2.version
        ra = decode_result_bin(apply_op_bin(a, encode_op_bin(KVOperation.get("x"))))
        assert ra.value == "1"

    def test_bulk_apply_equivalent_to_sequential(self):
        bulk, seq = KVStore(), KVStore()
        ops = [encode_set_bin(f"k{i % 5}", f"v{i}") for i in range(40)]
        bulk_out = apply_ops_bin(bulk, ops)
        seq_out = [apply_op_bin(seq, b) for b in ops]
        assert [decode_result_bin(r).version for r in bulk_out] == [
            decode_result_bin(r).version for r in seq_out
        ]
        assert {k: e.value for k, e in bulk._data.items()} == {
            k: e.value for k, e in seq._data.items()
        }

    def test_fast_path_respects_notifications(self):
        st = KVStore()
        sub = st.notifications.subscribe()
        # fast path must decline when subscribers exist (notify semantics)
        import time as _t

        assert st.apply_set_bin_fast(encode_set_bin("k", "v"), _t.time()) is None
        st.set("k", "v")
        assert sub.queue.qsize() == 1


def _mk_cluster(S, R=3, persistence=False):
    nodes = [NodeId.from_int(i + 1) for i in range(R)]
    hub = InMemoryHub()
    cfg = RabiaConfig(
        phase_timeout=1.0, heartbeat_interval=0.2, round_interval=0.0005
    ).with_kernel(num_shards=S, shard_pad_multiple=S)
    engines, tasks, stores = [], [], []
    for n in nodes:
        sm, machines = make_sharded_kv(S)
        stores.append(machines)
        engines.append(
            RabiaEngine(ClusterConfig.new(n, nodes), sm, hub.register(n), config=cfg)
        )
    return engines, stores, hub


async def _start(engines):
    tasks = [asyncio.ensure_future(e.run()) for e in engines]
    for _ in range(300):
        await asyncio.sleep(0.01)
        sts = [await e.get_statistics() for e in engines]
        if all(s.has_quorum for s in sts):
            break
    return tasks


async def _stop(engines, tasks):
    for e in engines:
        await e.shutdown()
    for t in tasks:
        t.cancel()
    await asyncio.gather(*tasks, return_exceptions=True)


class TestBlockLaneEndToEnd:
    @pytest.mark.asyncio
    async def test_submit_block_commits_and_converges(self):
        S = 16
        engines, stores, _ = _mk_cluster(S)
        tasks = await _start(engines)
        try:
            svc = ShardedKVService(
                S,
                engines[0].submit_batch,
                stores[0],
                submit_block=engines[0].submit_block,
            )
            res = await asyncio.wait_for(
                svc.set_many([(f"key{i}", f"val{i}") for i in range(64)]), 30.0
            )
            assert all(r.ok for r in res)
            # every replica applied every write
            for _ in range(300):
                await asyncio.sleep(0.01)
                done = all(
                    stores[r][svc.shard_of("key3")].store.get("key3").value == "val3"
                    for r in range(3)
                )
                if done:
                    break
            assert done
        finally:
            await _stop(engines, tasks)

    @pytest.mark.asyncio
    async def test_block_demotion_on_wrong_proposer(self):
        """A block covering shards this replica does NOT propose demotes
        them to the scalar lane (forwarded), and still commits."""
        S = 6
        engines, stores, _ = _mk_cluster(S)
        tasks = await _start(engines)
        try:
            # engine 2 proposes only shards where (s+0)%3==2 at slot 0;
            # cover ALL shards so 2/3 demote+forward
            svc = ShardedKVService(
                S,
                engines[2].submit_batch,
                stores[2],
                submit_block=engines[2].submit_block,
            )
            pairs = [(f"kk{i}", "z") for i in range(24)]
            res = await asyncio.wait_for(svc.set_many(pairs), 30.0)
            assert all(r.ok for r in res), [str(r) for r in res if not r.ok][:3]
        finally:
            await _stop(engines, tasks)

    @pytest.mark.asyncio
    async def test_adaptive_batching_amortizes_slots(self):
        S = 4
        engines, stores, _ = _mk_cluster(S)
        tasks = await _start(engines)
        try:
            svc = ShardedKVService(
                S,
                engines[0].submit_batch,
                stores[0],
                batching=BatchConfig(max_batch_size=8, max_batch_delay=0.01),
            )
            results = await asyncio.wait_for(
                asyncio.gather(*[svc.set(f"b{i}", "x") for i in range(48)]), 30.0
            )
            assert all(r.ok for r in results)
            batches = sum(s.batches_created for s in svc.batch_stats)
            cmds = sum(s.commands_batched for s in svc.batch_stats)
            assert cmds == 48
            assert batches < 48  # multiple commands rode one consensus slot
            await svc.close()
        finally:
            await _stop(engines, tasks)
