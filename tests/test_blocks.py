"""Block lane: PayloadBlock, ProposeBlock wire, engine bulk path, bulk
service API, adaptive batching in the client path, binary kv op codec."""

from __future__ import annotations

import asyncio
import time
import uuid

import numpy as np
import pytest

from netwait import wait_until

from rabia_tpu.apps import ShardedKVService, make_sharded_kv
from rabia_tpu.apps.kvstore import (
    KVOperation,
    KVStore,
    apply_op_bin,
    apply_ops_bin,
    decode_op_bin,
    decode_result_bin,
    encode_op_bin,
    encode_set_bin,
)
from rabia_tpu.core.blocks import block_batch_id, build_block
from rabia_tpu.core.config import BatchConfig, RabiaConfig
from rabia_tpu.core.errors import ValidationError
from rabia_tpu.core.messages import ProposeBlock, ProtocolMessage
from rabia_tpu.core.network import ClusterConfig
from rabia_tpu.core.serialization import Serializer
from rabia_tpu.core.types import NodeId
from rabia_tpu.engine import RabiaEngine
from rabia_tpu.net import InMemoryHub


class TestPayloadBlock:
    def test_build_and_slicing(self):
        blk = build_block(
            [3, 7, 11],
            [[b"a"], [b"bb", b"ccc"], [b"dddd"]],
        )
        assert len(blk) == 3
        assert blk.total_commands == 4
        assert blk.commands_for(0) == [b"a"]
        assert blk.commands_for(1) == [b"bb", b"ccc"]
        assert blk.commands_for(2) == [b"dddd"]
        assert blk.batch_id_for(1) == block_batch_id(blk.id, 7)

    def test_subset_shares_identity(self):
        blk = build_block([1, 2, 3], [[b"x"], [b"yy"], [b"zzz"]])
        sub = blk.subset(np.array([0, 2]))
        assert sub.id == blk.id
        assert sub.commands_for(1) == [b"zzz"]
        assert list(sub.shards) == [1, 3]

    def test_materialize_batch(self):
        blk = build_block([5], [[b"cmd1", b"cmd2"]])
        batch = blk.materialize_batch(0)
        assert int(batch.shard) == 5
        assert [c.data for c in batch.commands] == [b"cmd1", b"cmd2"]

    def test_build_rejects_bad_shapes(self):
        with pytest.raises(ValidationError):
            build_block([1, 1], [[b"a"], [b"b"]])  # duplicate shard
        with pytest.raises(ValidationError):
            build_block([1], [[]])  # empty command list

    def test_wire_roundtrip(self):
        blk = build_block([0, 9], [[b"hello"], [b"wo", b"rld"]])
        blk.slots[:] = [4, 5]
        ser = Serializer()
        msg = ProtocolMessage.new(NodeId.from_int(1), ProposeBlock(block=blk))
        back = ser.deserialize(ser.serialize(msg))
        assert back.payload == ProposeBlock(block=blk)
        assert back.payload.block.commands_for(1) == [b"wo", b"rld"]

    def test_block_batch_ids_are_wire_representable(self):
        # regression: block-lane ids flow into SyncResponse.applied_ids and
        # Decision.batch_id; as tuples they crashed the codec (and with it
        # the engine run loop on the first SyncRequest from a lagging peer)
        from rabia_tpu.core.messages import Decision, DecisionEntry, SyncResponse
        from rabia_tpu.core.types import BatchId, StateValue

        blk = build_block([3, 7], [[b"a"], [b"b"]])
        bid = blk.batch_id_for(1)
        assert isinstance(bid, BatchId)
        # deterministic across independent derivations, distinct per shard
        assert bid == block_batch_id(blk.id, 7)
        assert bid != block_batch_id(blk.id, 3)
        assert block_batch_id(blk.id, 3) == blk.batch_id_for(0)

        ser = Serializer()
        sync = ProtocolMessage.new(
            NodeId.from_int(1),
            SyncResponse(
                responder_phase=5,
                state_version=5,
                snapshot=b"snap",
                per_shard_phase=(2, 3),
                applied_ids=((0, bid), (1, BatchId.new())),
            ),
        )
        back = ser.deserialize(ser.serialize(sync))
        assert back.payload.applied_ids[0] == (0, bid)

        dec = ProtocolMessage.new(
            NodeId.from_int(1),
            Decision(
                decisions=(
                    DecisionEntry(
                        shard=7,
                        phase=4 << 16,
                        decision=StateValue.V1,
                        batch_id=bid,
                    ),
                )
            ),
        )
        back = ser.deserialize(ser.serialize(dec))
        assert back.payload.bids[0] == bid

    def test_wire_rejects_corrupt_data(self):
        from rabia_tpu.core.errors import SerializationError

        blk = build_block([0], [[b"hello"]])
        blk.slots[:] = [0]
        ser = Serializer()
        raw = bytearray(
            ser.serialize(
                ProtocolMessage.new(NodeId.from_int(1), ProposeBlock(block=blk))
            )
        )
        raw[-8] ^= 0xFF  # flip a data byte under the checksum
        with pytest.raises(SerializationError):
            ser.deserialize(bytes(raw))


class TestBinaryOpCodec:
    def test_roundtrip_all_ops(self):
        for op in (
            KVOperation.set("k", "v"),
            KVOperation.get("k"),
            KVOperation.delete("k"),
            KVOperation.exists("k"),
        ):
            assert decode_op_bin(encode_op_bin(op)) == op

    def test_apply_matches_typed_store(self):
        a, b = KVStore(), KVStore()
        r1 = apply_op_bin(a, encode_set_bin("x", "1"))
        r2 = b.set("x", "1")
        assert decode_result_bin(r1).version == r2.version
        ra = decode_result_bin(apply_op_bin(a, encode_op_bin(KVOperation.get("x"))))
        assert ra.value == "1"

    def test_bulk_apply_equivalent_to_sequential(self):
        bulk, seq = KVStore(), KVStore()
        ops = [encode_set_bin(f"k{i % 5}", f"v{i}") for i in range(40)]
        bulk_out = apply_ops_bin(bulk, ops)
        seq_out = [apply_op_bin(seq, b) for b in ops]
        assert [decode_result_bin(r).version for r in bulk_out] == [
            decode_result_bin(r).version for r in seq_out
        ]
        assert {k: e.value for k, e in bulk._data.items()} == {
            k: e.value for k, e in seq._data.items()
        }

    def test_fast_path_respects_notifications(self):
        st = KVStore()
        sub = st.notifications.subscribe()
        # fast path must decline when subscribers exist (notify semantics)
        import time as _t

        assert st.apply_set_bin_fast(encode_set_bin("k", "v"), _t.time()) is None
        st.set("k", "v")
        assert sub.queue.qsize() == 1


def _mk_cluster(S, R=3, persistence=False):
    nodes = [NodeId.from_int(i + 1) for i in range(R)]
    hub = InMemoryHub()
    cfg = RabiaConfig(
        phase_timeout=1.0, heartbeat_interval=0.2, round_interval=0.0005
    ).with_kernel(num_shards=S, shard_pad_multiple=S)
    engines, tasks, stores = [], [], []
    for n in nodes:
        sm, machines = make_sharded_kv(S)
        stores.append(machines)
        engines.append(
            RabiaEngine(ClusterConfig.new(n, nodes), sm, hub.register(n), config=cfg)
        )
    return engines, stores, hub


async def _start(engines):
    tasks = [asyncio.ensure_future(e.run()) for e in engines]
    for _ in range(300):
        await asyncio.sleep(0.01)
        sts = [await e.get_statistics() for e in engines]
        if all(s.has_quorum for s in sts):
            break
    return tasks


async def _stop(engines, tasks):
    for e in engines:
        await e.shutdown()
    for t in tasks:
        t.cancel()
    await asyncio.gather(*tasks, return_exceptions=True)


class TestBlockLaneEndToEnd:
    @pytest.mark.asyncio
    async def test_submit_block_commits_and_converges(self):
        S = 16
        engines, stores, _ = _mk_cluster(S)
        tasks = await _start(engines)
        try:
            svc = ShardedKVService(
                S,
                engines[0].submit_batch,
                stores[0],
                submit_block=engines[0].submit_block,
            )
            res = await asyncio.wait_for(
                svc.set_many([(f"key{i}", f"val{i}") for i in range(64)]), 30.0
            )
            assert all(r.ok for r in res)

            # every replica applied every write (liveness budget)
            def applied():
                return all(
                    (
                        e := stores[r][svc.shard_of("key3")].store.get("key3")
                    )
                    is not None
                    and e.value == "val3"
                    for r in range(3)
                )

            await wait_until(applied, budget=20.0, desc="replica apply")
        finally:
            await _stop(engines, tasks)

    @pytest.mark.asyncio
    async def test_block_demotion_on_wrong_proposer(self):
        """A block covering shards this replica does NOT propose demotes
        them to the scalar lane (forwarded), and still commits."""
        S = 6
        engines, stores, _ = _mk_cluster(S)
        tasks = await _start(engines)
        try:
            # engine 2 proposes only shards where (s+0)%3==2 at slot 0;
            # cover ALL shards so 2/3 demote+forward
            svc = ShardedKVService(
                S,
                engines[2].submit_batch,
                stores[2],
                submit_block=engines[2].submit_block,
            )
            pairs = [(f"kk{i}", "z") for i in range(24)]
            res = await asyncio.wait_for(svc.set_many(pairs), 30.0)
            assert all(r.ok for r in res), [str(r) for r in res if not r.ok][:3]
        finally:
            await _stop(engines, tasks)

    @pytest.mark.asyncio
    async def test_adaptive_batching_amortizes_slots(self):
        S = 4
        engines, stores, _ = _mk_cluster(S)
        tasks = await _start(engines)
        try:
            svc = ShardedKVService(
                S,
                engines[0].submit_batch,
                stores[0],
                batching=BatchConfig(max_batch_size=8, max_batch_delay=0.01),
            )
            results = await asyncio.wait_for(
                asyncio.gather(*[svc.set(f"b{i}", "x") for i in range(48)]), 30.0
            )
            assert all(r.ok for r in results)
            batches = sum(s.batches_created for s in svc.batch_stats)
            cmds = sum(s.commands_batched for s in svc.batch_stats)
            assert cmds == 48
            assert batches < 48  # multiple commands rode one consensus slot
            await svc.close()
        finally:
            await _stop(engines, tasks)


class TestBlockLaneFaults:
    @pytest.mark.asyncio
    async def test_replica_crash_mid_bulk_load(self):
        """Crash a replica while the block lane is pumping: survivors keep
        committing (dead-proposer shards rotate via null slots) and stay
        convergent."""
        from rabia_tpu.core.config import RabiaConfig
        from rabia_tpu.core.network import ClusterConfig
        from rabia_tpu.core.types import NodeId
        from rabia_tpu.engine import RabiaEngine
        from rabia_tpu.engine.leader import slot_proposer_vec

        S, R = 12, 3
        nodes = [NodeId.from_int(i + 1) for i in range(R)]
        hub = InMemoryHub()
        cfg = RabiaConfig(
            phase_timeout=0.3, heartbeat_interval=0.1, round_interval=0.0005
        ).with_kernel(num_shards=S, shard_pad_multiple=S)
        engines, stores, tasks = [], [], []
        for n in nodes:
            sm, machines = make_sharded_kv(S)
            stores.append(machines)
            engines.append(
                RabiaEngine(ClusterConfig.new(n, nodes), sm, hub.register(n), config=cfg)
            )
            tasks.append(asyncio.ensure_future(engines[-1].run()))
        try:
            for _ in range(300):
                await asyncio.sleep(0.01)
                sts = [await e.get_statistics() for e in engines]
                if all(s.has_quorum for s in sts):
                    break
            import numpy as _np

            from rabia_tpu.apps.kvstore import encode_set_bin
            from rabia_tpu.core.blocks import build_block
            from rabia_tpu.core.types import Command, CommandBatch

            shard_ids = _np.arange(S)

            async def wave(live):
                futs = []
                for e in live:
                    head = _np.maximum(e.rt.next_slot[:S], e.rt.applied_upto[:S])
                    mine = shard_ids[
                        (slot_proposer_vec(shard_ids, head, R) == e.me)
                        & ~e.rt.in_flight[:S]
                        & (e.rt.queue_len[:S] == 0)
                    ]
                    if len(mine):
                        futs.append(
                            await e.submit_block(
                                build_block(
                                    mine,
                                    [[encode_set_bin(f"w{int(s)}", "x")] for s in mine],
                                )
                            )
                        )
                if futs:
                    await asyncio.wait_for(
                        asyncio.gather(*futs, return_exceptions=True), 20.0
                    )

            await wave(engines)  # healthy wave
            # crash replica 0 (tolerated: quorum 2 of 3)
            tasks[0].cancel()
            hub.set_connected(nodes[0], False)
            pre = (await engines[1].get_statistics()).committed_slots
            # post-crash: live proposers pump blocks; shards whose rotation
            # hits the dead row are fed through the scalar lane so the
            # forward-timeout null slot rotates them
            deadline = asyncio.get_event_loop().time() + 20.0
            while asyncio.get_event_loop().time() < deadline:
                await wave(engines[1:])
                e = engines[1]
                head = _np.maximum(e.rt.next_slot[:S], e.rt.applied_upto[:S])
                stuck = shard_ids[
                    (slot_proposer_vec(shard_ids, head, R) == 0)
                    & (e.rt.queue_len[:S] < 1)
                ]
                for s in stuck:
                    try:
                        await e.submit_batch(
                            CommandBatch.new(
                                [Command.new(encode_set_bin(f"w{int(s)}", "x"))],
                                shard=int(s),
                            ),
                            shard=int(s),
                        )
                    except Exception:
                        pass
                await asyncio.sleep(0.05)
                post = (await engines[1].get_statistics()).committed_slots
                if post - pre >= 2 * S:
                    break
            post = (await engines[1].get_statistics()).committed_slots
            assert post - pre >= S, f"survivors stalled: {post - pre} commits"
            # survivors convergent on a sample key (liveness budget)
            def survivors_agree():
                a = stores[1][3].store.get("w3")
                b = stores[2][3].store.get("w3")
                return a is not None and b is not None and a.value == b.value

            await wait_until(
                survivors_agree, budget=20.0, desc="survivor convergence"
            )
        finally:
            for e in engines[1:]:
                await e.shutdown()
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)


class TestJaxBackendEngine:
    """The FENCED device-array engine backend (KernelConfig.backend=
    "jax"): kept for directly-attached accelerators; on tunneled
    hardware the per-tick readback floor makes it ~75x slower than the
    host kernel (docs/PERFORMANCE.md, 'Engine kernel backends'). These
    tests keep the path correct, not fast."""

    @pytest.mark.jax_backend
    @pytest.mark.asyncio
    async def test_jax_kernel_backend_commits(self):
        """KernelConfig.backend='jax' (device-array state + inbox planes)
        commits the same as the host kernel — the device-engine deployment
        path stays exercised."""
        from rabia_tpu.core.config import RabiaConfig
        from rabia_tpu.core.network import ClusterConfig
        from rabia_tpu.core.types import Command, CommandBatch, NodeId
        from rabia_tpu.engine import RabiaEngine
        from rabia_tpu.apps.kvstore import encode_set_bin

        S, R = 4, 3
        nodes = [NodeId.from_int(i + 1) for i in range(R)]
        hub = InMemoryHub()
        cfg = RabiaConfig(
            phase_timeout=0.5, heartbeat_interval=0.1, round_interval=0.001
        ).with_kernel(num_shards=S, shard_pad_multiple=S, backend="jax")
        engines, stores, tasks = [], [], []
        for n in nodes:
            sm, machines = make_sharded_kv(S)
            stores.append(machines)
            engines.append(
                RabiaEngine(ClusterConfig.new(n, nodes), sm, hub.register(n), config=cfg)
            )
            tasks.append(asyncio.ensure_future(engines[-1].run()))
        try:
            for _ in range(300):
                await asyncio.sleep(0.01)
                sts = [await e.get_statistics() for e in engines]
                if all(s.has_quorum for s in sts):
                    break
            fut = await engines[0].submit_batch(
                CommandBatch.new([Command.new(encode_set_bin("jk", "jv"))], shard=1),
                shard=1,
            )
            responses = await asyncio.wait_for(fut, 30.0)
            assert len(responses) == 1

            def converged():
                vals = [ms[1].store.get("jk") for ms in stores]
                return all(v is not None and v.value == "jv" for v in vals)

            # the fenced backend ticks slowly by design; under ambient
            # load the other replicas' applies can trail the committer
            # by several seconds (liveness budget, not a speed assert)
            await wait_until(converged, budget=30.0, desc="replica catch-up")
        finally:
            for e in engines:
                await e.shutdown()
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)


class TestNoDecisionBroadcast:
    @pytest.mark.asyncio
    async def test_straggler_recovers_without_decision_broadcasts(self):
        """decision_broadcast=False: a partitioned replica that missed a
        stretch of commits catches back up through the targeted stale-vote
        repair (decided-value ring) and/or snapshot sync."""
        from rabia_tpu.core.config import RabiaConfig
        from rabia_tpu.core.network import ClusterConfig
        from rabia_tpu.core.types import NodeId
        from rabia_tpu.engine import RabiaEngine
        from rabia_tpu.engine.leader import slot_proposer_vec
        import numpy as _np

        from rabia_tpu.apps.kvstore import encode_set_bin
        from rabia_tpu.core.blocks import build_block

        S, R = 8, 3
        nodes = [NodeId.from_int(i + 1) for i in range(R)]
        hub = InMemoryHub()
        cfg = RabiaConfig(
            phase_timeout=0.2,
            heartbeat_interval=0.05,
            round_interval=0.0005,
            sync_timeout=1.0,
            decision_broadcast=False,
        ).with_kernel(num_shards=S, shard_pad_multiple=S)
        engines, stores, tasks = [], [], []
        for n in nodes:
            sm, machines = make_sharded_kv(S)
            stores.append(machines)
            engines.append(
                RabiaEngine(ClusterConfig.new(n, nodes), sm, hub.register(n), config=cfg)
            )
            tasks.append(asyncio.ensure_future(engines[-1].run()))
        try:
            for _ in range(300):
                await asyncio.sleep(0.01)
                sts = [await e.get_statistics() for e in engines]
                if all(s.has_quorum for s in sts):
                    break
            shard_ids = _np.arange(S)

            async def wave(live, tag):
                futs = []
                for e in live:
                    head = _np.maximum(e.rt.next_slot[:S], e.rt.applied_upto[:S])
                    mine = shard_ids[
                        (slot_proposer_vec(shard_ids, head, R) == e.me)
                        & ~e.rt.in_flight[:S]
                        & (e.rt.queue_len[:S] == 0)
                    ]
                    if len(mine):
                        try:
                            futs.append(
                                await e.submit_block(
                                    build_block(
                                        mine,
                                        [
                                            [encode_set_bin(f"s{int(s)}", tag)]
                                            for s in mine
                                        ],
                                    )
                                )
                            )
                        except Exception:
                            # a just-healed replica may not have refreshed
                            # its quorum view yet — skip it this wave
                            pass
                if futs:
                    await asyncio.wait_for(
                        asyncio.gather(*futs, return_exceptions=True), 20.0
                    )

            await wave(engines, "pre")
            # partition node 2; the remaining quorum keeps committing for
            # the slots it proposes (rotation parks at row-2 slots since
            # nothing feeds the scalar give-up path — that's the crash
            # test's job; here we only need the straggler to MISS commits)
            hub.set_connected(nodes[2], False)
            await asyncio.sleep(0.3)
            for i in range(4):
                await wave(engines[:2], f"gap{i}")
            mid = (await engines[2].get_statistics()).committed_slots
            lead = (await engines[0].get_statistics()).committed_slots
            assert lead > mid, "quorum pair did not outrun the straggler"
            # heal: traffic resumes cluster-wide; the straggler's fresh
            # votes in already-decided slots must be answered by the
            # targeted repair / sync — NO Decision broadcasts exist
            hub.set_connected(nodes[2], True)
            a = c = 0
            for _ in range(600):
                await asyncio.sleep(0.01)
                await wave(engines, "post")
                a = (await engines[0].get_statistics()).committed_slots
                c = (await engines[2].get_statistics()).committed_slots
                if c >= a - S and c > mid:
                    break
            assert c > mid, "straggler made no progress after heal"
            assert c >= a - S, f"straggler stuck at {c} vs leader {a}"
        finally:
            for e in engines:
                await e.shutdown()
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)


class TestGetMany:
    @pytest.mark.asyncio
    async def test_bulk_reads_through_consensus(self):
        S = 8
        engines, stores, _ = _mk_cluster(S)
        tasks = await _start(engines)
        try:
            svc = ShardedKVService(
                S,
                engines[0].submit_batch,
                stores[0],
                submit_block=engines[0].submit_block,
            )
            pairs = [(f"gk{i}", f"gv{i}") for i in range(20)]
            res = await asyncio.wait_for(svc.set_many(pairs), 30.0)
            assert all(r.ok for r in res)
            got = await asyncio.wait_for(
                svc.get_many([k for k, _ in pairs] + ["absent-key"]), 30.0
            )
            for (k, v), r in zip(pairs, got):
                assert r.ok and r.value == v, (k, r)
            assert not got[-1].ok or got[-1].value is None  # NotFound
        finally:
            await _stop(engines, tasks)


class TestBlockLanePersistence:
    @pytest.mark.asyncio
    async def test_restart_rejoins_after_bulk_waves(self, tmp_path):
        """Bulk-lane commits + durable persistence: restart one replica's
        engine object; it restores its counters/snapshot and keeps
        committing with the cluster."""
        import numpy as _np

        from rabia_tpu.apps.kvstore import encode_set_bin
        from rabia_tpu.core.blocks import build_block
        from rabia_tpu.engine.leader import slot_proposer_vec
        from rabia_tpu.persistence import FileSystemPersistence

        S, R = 6, 3
        nodes = [NodeId.from_int(i + 1) for i in range(R)]
        hub = InMemoryHub()
        # barrier_stride=1: taint only truly-opened slots so the restored
        # replica rejoins immediately (the deep-stride default trades
        # restart taint width for fsync amortization)
        cfg = RabiaConfig(
            phase_timeout=0.3,
            heartbeat_interval=0.05,
            round_interval=0.0005,
            barrier_stride=1,
        ).with_kernel(num_shards=S, shard_pad_multiple=S)
        persist = [FileSystemPersistence(str(tmp_path / f"n{i}")) for i in range(R)]
        nets = [hub.register(n) for n in nodes]

        def mk_engine(i, sm_holder):
            sm, machines = make_sharded_kv(S)
            sm_holder.append(machines)
            return RabiaEngine(
                ClusterConfig.new(nodes[i], nodes),
                sm,
                nets[i],
                persistence=persist[i],
                config=cfg,
            )

        stores: list = []
        engines = [mk_engine(i, stores) for i in range(R)]
        tasks = [asyncio.ensure_future(e.run()) for e in engines]
        try:
            for _ in range(1000):
                await asyncio.sleep(0.01)
                sts = [await e.get_statistics() for e in engines]
                if all(s.has_quorum for s in sts):
                    break
            shard_ids = _np.arange(S)

            async def wave(live, tag):
                futs = []
                for e in live:
                    head = _np.maximum(e.rt.next_slot[:S], e.rt.applied_upto[:S])
                    mine = shard_ids[
                        (slot_proposer_vec(shard_ids, head, R) == e.me)
                        & ~e.rt.in_flight[:S]
                        & (e.rt.queue_len[:S] == 0)
                    ]
                    if len(mine):
                        try:
                            futs.append(
                                await e.submit_block(
                                    build_block(
                                        mine,
                                        [
                                            [encode_set_bin(f"p{int(s)}", tag)]
                                            for s in mine
                                        ],
                                    )
                                )
                            )
                        except Exception:
                            pass
                if futs:
                    await asyncio.wait_for(
                        asyncio.gather(*futs, return_exceptions=True), 20.0
                    )

            for i in range(3):
                await wave(engines, f"w{i}")
            # force a checkpoint, then stop replica 0 cleanly
            await engines[0]._save_state()
            await engines[0].shutdown()
            tasks[0].cancel()
            await asyncio.gather(tasks[0], return_exceptions=True)
            committed_before = (await engines[1].get_statistics()).committed_slots

            # rebuild replica 0's engine from its persisted state
            restored_stores: list = []
            e0 = mk_engine(0, restored_stores)
            tasks[0] = asyncio.ensure_future(e0.run())
            engines[0] = e0
            for _ in range(1000):
                await asyncio.sleep(0.01)
                st = await e0.get_statistics()
                if st.has_quorum and st.committed_slots > 0:
                    break
            assert (await e0.get_statistics()).committed_slots > 0, (
                "restored replica lost its applied counters"
            )
            # wait for the restored replica's per-shard heads to catch up
            # with the cluster: until sync repair lands, every live
            # proposer defers to a peer (proposer is computed from each
            # engine's OWN head), so a wave issued in that window no-ops
            # — the pre-round-5 version assumed exactly 3 waves would
            # commit and flaked under ambient load on exactly this
            def heads(e):
                return _np.maximum(e.rt.next_slot[:S], e.rt.applied_upto[:S])

            await wait_until(
                lambda: _np.all(heads(e0) >= heads(engines[1])),
                budget=20.0,
                desc="restored replica head catch-up",
            )
            # the cluster keeps committing with the restored member:
            # retry waves under a deadline (a wave still no-ops per-shard
            # while that shard's previous slot is settling)
            deadline = time.monotonic() + 30.0
            i = 0
            after = committed_before
            got = None
            while time.monotonic() < deadline:
                await wave(engines, f"r{i}")
                i += 1
                await asyncio.sleep(0.05)
                after = (await engines[1].get_statistics()).committed_slots
                got = restored_stores[0][2].store.get("p2")
                if (
                    after > committed_before
                    and got is not None
                    and got.value.startswith("r")
                ):
                    break
            assert after > committed_before
            # restored replica converges on post-restart writes
            assert got is not None and got.value.startswith("r")
        finally:
            for e in engines:
                try:
                    await asyncio.wait_for(e.shutdown(), 5.0)
                except Exception:
                    pass
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
