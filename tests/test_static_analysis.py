"""The concurrency & ABI static-analysis plane's gates (round 13).

Four tiers, mirroring docs/STATIC_ANALYSIS.md:

- **Sanitizer matrix** — the native stress programs under TSan/ASan as
  ENFORCED cells (halt_on_error; exit nonzero = red). The old
  environmental SKIP is retired: build.find_sanitizer_toolchain proves
  the toolchain first (clean timed-condvar probe passes AND a planted
  bug is caught — on gcc via the pthread_cond_clockwait shim), so the
  only remaining skip is "no viable toolchain on this machine", one
  line. The selfcheck tests prove red-on-failure with deliberately
  broken probes.
- **ABI linter** — scripts/abi_lint.py clean on the real tree, plus a
  drift-injection suite: each drift class (added counter, reordered
  names, stale version literal, resized struct, diverged code point)
  is seeded into a COPY of the real sources and must be caught.
- **Thread-safety build** — every annotated kernel compiles under
  clang++ -Werror=thread-safety (skips in one line without clang; the
  CI thread-safety cell installs it).
- **Lock-order checker** — the RABIA_NATIVE_DEBUG=1 flavor aborts on a
  deliberate inversion and passes the real kernels' lock paths.
"""

from __future__ import annotations

import shutil
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "scripts"))

from rabia_tpu.native import build as nb  # noqa: E402

import abi_lint  # noqa: E402


def _toolchain_or_skip(flavor: str):
    if shutil.which("g++") is None and shutil.which("clang++") is None:
        pytest.skip("no C++ compiler")
    tc = nb.find_sanitizer_toolchain(flavor)
    if tc is None:
        reason = getattr(nb.find_sanitizer_toolchain, "reason", "?")
        pytest.skip(f"no viable {flavor} toolchain: {reason}")
    return tc


def _run_cell(name: str, flavor: str, extra_args: list[str] | None = None):
    """Build + run one enforced sanitizer cell; any finding is FATAL
    (no skip past this point — the toolchain is already proven)."""
    exe = nb.build_stress(name, flavor)
    proc = subprocess.run(
        [str(exe), *(extra_args or [])],
        capture_output=True, text=True, timeout=300,
        env=nb.stress_env(flavor),
    )
    assert proc.returncode == 0, (
        f"{flavor}/{name} stress FAILED rc={proc.returncode}\n"
        f"{proc.stdout[-800:]}\n{proc.stderr[-4000:]}"
    )
    assert "stress ok" in proc.stdout  # the seam did real work


class TestSanitizerMatrix:
    """Enforced TSan/ASan cells over the cross-thread seams that the
    thread-per-shard-group runtime (ROADMAP item 1) will multiply."""

    def test_tsan_transport(self):
        _toolchain_or_skip("tsan")
        _run_cell("transport", "tsan")

    def test_tsan_wal(self, tmp_path):
        _toolchain_or_skip("tsan")
        _run_cell("wal", "tsan", [str(tmp_path)])

    def test_tsan_session(self):
        _toolchain_or_skip("tsan")
        _run_cell("session", "tsan")

    def test_tsan_statekernel(self):
        _toolchain_or_skip("tsan")
        _run_cell("statekernel", "tsan")

    @pytest.mark.slow
    def test_tsan_runtime(self):
        _toolchain_or_skip("tsan")
        _run_cell("runtime", "tsan")

    def test_tsan_runtime_mt(self, tmp_path):
        # the thread-per-shard-group seams: 2 workers vs group inbox
        # routing, per-lane applies, shared WAL staging, the pause
        # barrier — the round-14 correctness gate
        _toolchain_or_skip("tsan")
        _run_cell("runtime_mt", "tsan", [str(tmp_path)])

    @pytest.mark.slow
    def test_asan_runtime_mt(self, tmp_path):
        _toolchain_or_skip("asan")
        _run_cell("runtime_mt", "asan", [str(tmp_path)])

    def test_asan_wal(self, tmp_path):
        _toolchain_or_skip("asan")
        _run_cell("wal", "asan", [str(tmp_path)])

    def test_asan_session(self):
        _toolchain_or_skip("asan")
        _run_cell("session", "asan")

    @pytest.mark.slow
    def test_asan_transport(self):
        _toolchain_or_skip("asan")
        _run_cell("transport", "asan")

    @pytest.mark.slow
    def test_asan_statekernel_and_runtime(self):
        _toolchain_or_skip("asan")
        _run_cell("statekernel", "asan")
        _run_cell("runtime", "asan")

    @pytest.mark.slow
    def test_ubsan_all(self, tmp_path):
        _toolchain_or_skip("ubsan")
        for name in sorted(nb.STRESS_PROGRAMS):
            needs_dir = name in ("wal", "runtime_mt")
            args = [str(tmp_path / name)] if needs_dir else []
            if needs_dir:
                (tmp_path / name).mkdir()
            _run_cell(name, "ubsan", args)

    def test_tsan_gate_is_red_on_a_planted_race(self):
        """The gate must FAIL on a real race — proof the matrix is
        enforced, not green-by-silence."""
        _toolchain_or_skip("tsan")
        exe = nb.build_selfcheck("tsan")
        for _ in range(5):
            proc = subprocess.run(
                [str(exe)], capture_output=True, text=True, timeout=120,
                env=nb.stress_env("tsan"),
            )
            if proc.returncode != 0:
                return
        pytest.fail("TSan did not catch the planted data race")

    def test_asan_gate_is_red_on_a_planted_uaf(self):
        _toolchain_or_skip("asan")
        exe = nb.build_selfcheck("asan")
        proc = subprocess.run(
            [str(exe)], capture_output=True, text=True, timeout=120,
            env=nb.stress_env("asan"),
        )
        assert proc.returncode != 0, (
            "ASan did not catch the planted use-after-free"
        )


# --- ABI linter -------------------------------------------------------------

# every file the linter reads, relative to the repo root (the drift
# suite copies exactly these into a scratch tree)
_LINT_FILES = [
    "rabia_tpu/native/hostkernel.cpp",
    "rabia_tpu/native/transport.cpp",
    "rabia_tpu/native/statekernel.cpp",
    "rabia_tpu/native/sessionkernel.cpp",
    "rabia_tpu/native/walkernel.cpp",
    "rabia_tpu/native/runtime.cpp",
    "rabia_tpu/native/build.py",
    "rabia_tpu/engine/native_tick.py",
    "rabia_tpu/engine/runtime_bridge.py",
    "rabia_tpu/apps/native_store.py",
    "rabia_tpu/gateway/native_session.py",
    "rabia_tpu/gateway/session.py",
    "rabia_tpu/persistence/native_wal.py",
    "rabia_tpu/net/tcp.py",
    "rabia_tpu/obs/flight.py",
    "rabia_tpu/obs/registry.py",
]


def _scratch_tree(tmp_path: Path) -> Path:
    root = tmp_path / "tree"
    for rel in _LINT_FILES:
        dst = root / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy(REPO / rel, dst)
    return root


def _mutate(root: Path, rel: str, old: str, new: str) -> None:
    p = root / rel
    text = p.read_text()
    assert old in text, f"fixture anchor missing in {rel}: {old!r}"
    p.write_text(text.replace(old, new, 1))


def _rules(root: Path) -> set[str]:
    return {v.rule for v in abi_lint.run(root)}


class TestAbiLint:
    def test_real_tree_is_clean(self):
        violations = abi_lint.run(REPO)
        assert violations == [], "\n".join(str(v) for v in violations)

    def test_scratch_copy_is_clean(self, tmp_path):
        # the drift fixtures prove detection only if the unmutated copy
        # passes
        assert _rules(_scratch_tree(tmp_path)) == set()

    def test_catches_added_counter(self, tmp_path):
        root = _scratch_tree(tmp_path)
        _mutate(root, "rabia_tpu/native/hostkernel.cpp",
                "  RKC_COUNT", "  RKC_SYNTHETIC_NEW,\n  RKC_COUNT")
        assert "count" in _rules(root)

    def test_catches_reordered_names(self, tmp_path):
        root = _scratch_tree(tmp_path)
        _mutate(root, "rabia_tpu/engine/native_tick.py",
                '    "ticks",\n    "stages",',
                '    "stages",\n    "ticks",')
        assert "order" in _rules(root)

    def test_catches_stale_version_literal(self, tmp_path):
        root = _scratch_tree(tmp_path)
        _mutate(root, "rabia_tpu/native/sessionkernel.cpp",
                "GWS_COUNTERS_VERSION = 1", "GWS_COUNTERS_VERSION = 2")
        assert "version" in _rules(root)

    def test_catches_resized_struct(self, tmp_path):
        root = _scratch_tree(tmp_path)
        _mutate(root, "rabia_tpu/obs/flight.py",
                '("shard", "<u4")', '("shard", "<u8")')
        assert "size" in _rules(root)

    def test_catches_diverged_code_point(self, tmp_path):
        root = _scratch_tree(tmp_path)
        _mutate(root, "rabia_tpu/native/sessionkernel.cpp",
                "SUBMIT_SHED_WINDOW = 3", "SUBMIT_SHED_WINDOW = 4")
        assert "codes" in _rules(root)

    def test_catches_histogram_geometry_drift(self, tmp_path):
        root = _scratch_tree(tmp_path)
        _mutate(root, "rabia_tpu/native/walkernel.cpp",
                "WLH_SUB_BITS = 2", "WLH_SUB_BITS = 3")
        assert "geometry" in _rules(root)

    def test_catches_fn_table_drift(self, tmp_path):
        # the rtm_create function-pointer table: a reordered Python
        # _FN_ORDER would register kernel entry points at wrong indices
        root = _scratch_tree(tmp_path)
        _mutate(root, "rabia_tpu/engine/runtime_bridge.py",
                '    "rk_ingest",\n    "rk_tick",',
                '    "rk_tick",\n    "rk_ingest",')
        assert "order" in _rules(root)

    def test_catches_fn_table_missing_entry(self, tmp_path):
        root = _scratch_tree(tmp_path)
        _mutate(root, "rabia_tpu/engine/runtime_bridge.py",
                '    "sk_out_offs_lane",\n', "")
        assert "count" in _rules(root)

    def test_catches_per_worker_accessor_drift(self, tmp_path):
        # a per-worker observability block declared on one side only
        # (thread-per-shard-group runtime) — here build.py loses its
        # rtm_stages_w prototype while runtime.cpp keeps the export
        root = _scratch_tree(tmp_path)
        _mutate(root, "rabia_tpu/native/build.py",
                "lib.rtm_stages_w.restype",
                "lib.rtm_stages_w_RENAMED.restype")
        assert "geometry" in _rules(root)


# --- clang -Werror=thread-safety --------------------------------------------

_ANNOTATED = [
    "transport.cpp", "statekernel.cpp", "sessionkernel.cpp",
    "walkernel.cpp", "runtime.cpp",
]


def _find_clang() -> str | None:
    for name in ("clang++", "clang++-20", "clang++-19", "clang++-18",
                 "clang++-17", "clang++-16", "clang++-15", "clang++-14"):
        if shutil.which(name):
            return name
    return None


class TestThreadSafetyBuild:
    def test_kernels_clean_under_werror_thread_safety(self):
        clang = _find_clang()
        if clang is None:
            pytest.skip("no clang++ (the CI thread-safety cell has one)")
        native = REPO / "rabia_tpu" / "native"
        for src in _ANNOTATED:
            proc = subprocess.run(
                [clang, "-std=c++17", "-fsyntax-only",
                 "-Werror=thread-safety", "-Wthread-safety",
                 f"-I{native}", str(native / src)],
                capture_output=True, text=True, timeout=300,
            )
            assert proc.returncode == 0, (
                f"{src} fails -Werror=thread-safety:\n"
                f"{proc.stderr[-4000:]}"
            )

    def test_annotation_violation_is_a_compile_error(self, tmp_path):
        """GUARDED_BY without the lock must fail the build — proof the
        macros bind (and that the no-op fallback is clang-only)."""
        clang = _find_clang()
        if clang is None:
            pytest.skip("no clang++ (the CI thread-safety cell has one)")
        src = tmp_path / "violate.cpp"
        src.write_text(
            '#include "annotations.h"\n'
            "struct S {\n"
            "  rabia::Mutex mu{\"s.mu\"};\n"
            "  int guarded RABIA_GUARDED_BY(mu) = 0;\n"
            "};\n"
            "int touch(S& s) { return s.guarded; }  // no lock held\n"
        )
        proc = subprocess.run(
            [clang, "-std=c++17", "-fsyntax-only",
             "-Werror=thread-safety", "-Wthread-safety",
             f"-I{REPO / 'rabia_tpu' / 'native'}", str(src)],
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode != 0, (
            "clang accepted an unguarded access to a GUARDED_BY field"
        )


# --- lock-order checker (the RABIA_NATIVE_DEBUG flavor) ---------------------


class TestLockOrder:
    def _gxx(self):
        if shutil.which("g++") is None:
            pytest.skip("no g++")
        return "g++"

    def test_inversion_aborts_with_both_names(self, tmp_path):
        gxx = self._gxx()
        src = tmp_path / "invert.cpp"
        src.write_text(
            '#include <cstdio>\n#include "annotations.h"\n'
            "int main() {\n"
            "  rabia::Mutex a{\"probe.a\"}, b{\"probe.b\"};\n"
            "  { rabia::MutexLock la(a); rabia::MutexLock lb(b); }\n"
            "  { rabia::MutexLock lb(b); rabia::MutexLock la(a); }\n"
            '  std::printf("not reached\\n");\n'
            "  return 0;\n}\n"
        )
        exe = tmp_path / "invert"
        build = subprocess.run(
            [gxx, "-std=c++17", "-O1", "-pthread",
             "-DRABIA_NATIVE_DEBUG=1",
             f"-I{REPO / 'rabia_tpu' / 'native'}", str(src),
             "-o", str(exe)],
            capture_output=True, text=True, timeout=180,
        )
        assert build.returncode == 0, build.stderr[-1500:]
        proc = subprocess.run(
            [str(exe)], capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode != 0
        assert "order inversion" in proc.stderr
        assert "probe.a" in proc.stderr and "probe.b" in proc.stderr

    def test_three_lock_cycle_aborts(self, tmp_path):
        """A->B, B->C, C->A has no reversed PAIR to match — only the
        digraph reachability walk catches it (the 3-thread deadlock a
        pairwise checker misses)."""
        gxx = self._gxx()
        src = tmp_path / "cycle3.cpp"
        src.write_text(
            '#include <cstdio>\n#include "annotations.h"\n'
            "int main() {\n"
            "  rabia::Mutex a{\"probe.a\"}, b{\"probe.b\"}, c{\"probe.c\"};\n"
            "  { rabia::MutexLock la(a); rabia::MutexLock lb(b); }\n"
            "  { rabia::MutexLock lb(b); rabia::MutexLock lc(c); }\n"
            "  { rabia::MutexLock lc(c); rabia::MutexLock la(a); }\n"
            '  std::printf("not reached\\n");\n'
            "  return 0;\n}\n"
        )
        exe = tmp_path / "cycle3"
        build = subprocess.run(
            [gxx, "-std=c++17", "-O1", "-pthread",
             "-DRABIA_NATIVE_DEBUG=1",
             f"-I{REPO / 'rabia_tpu' / 'native'}", str(src),
             "-o", str(exe)],
            capture_output=True, text=True, timeout=180,
        )
        assert build.returncode == 0, build.stderr[-1500:]
        proc = subprocess.run(
            [str(exe)], capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode != 0
        assert "order inversion" in proc.stderr

    def test_double_lock_aborts(self, tmp_path):
        gxx = self._gxx()
        src = tmp_path / "dbl.cpp"
        src.write_text(
            '#include "annotations.h"\n'
            "int main() {\n"
            "  rabia::Mutex a{\"probe.dbl\"};\n"
            "  a.lock();\n"
            "  a.lock();  // same thread, non-recursive: must abort,\n"
            "             // not deadlock inside pthread_mutex_lock\n"
            "  return 0;\n}\n"
        )
        exe = tmp_path / "dbl"
        build = subprocess.run(
            [gxx, "-std=c++17", "-O1", "-pthread",
             "-DRABIA_NATIVE_DEBUG=1",
             f"-I{REPO / 'rabia_tpu' / 'native'}", str(src),
             "-o", str(exe)],
            capture_output=True, text=True, timeout=180,
        )
        assert build.returncode == 0, build.stderr[-1500:]
        proc = subprocess.run(
            [str(exe)], capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode != 0
        assert "double lock" in proc.stderr

    def test_kernel_lock_paths_clean_under_debug_flavor(self, tmp_path):
        """The WAL stress (the deepest lock nest: append lane + flush
        thread + sync waiters) runs clean under the checker."""
        gxx = self._gxx()
        native = REPO / "rabia_tpu" / "native"
        exe = tmp_path / "dbg_wal"
        build = subprocess.run(
            [gxx, "-std=c++17", "-O1", "-pthread",
             "-DRABIA_NATIVE_DEBUG=1", f"-I{native}",
             str(native / "stress" / "stress_wal.cpp"),
             str(native / "walkernel.cpp"), "-o", str(exe), "-lz"],
            capture_output=True, text=True, timeout=300,
        )
        assert build.returncode == 0, build.stderr[-1500:]
        wal_dir = tmp_path / "wal"
        wal_dir.mkdir()
        proc = subprocess.run(
            [str(exe), str(wal_dir)], capture_output=True, text=True,
            timeout=120,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]

    def test_multi_worker_lock_paths_clean_under_debug_flavor(
        self, tmp_path
    ):
        """The multi-worker stress under the lock-order checker: the
        round-14 nest (transport mu -> group gmu, statekernel mu ->
        lane mutexes, worker lane locks, walkernel mu) must build a
        cycle-free acquisition graph with workers > 1."""
        gxx = self._gxx()
        native = REPO / "rabia_tpu" / "native"
        exe = tmp_path / "dbg_rt_mt"
        build = subprocess.run(
            [gxx, "-std=c++17", "-O1", "-pthread",
             "-DRABIA_NATIVE_DEBUG=1", f"-I{native}",
             str(native / "stress" / "stress_runtime_mt.cpp"),
             str(native / "runtime.cpp"),
             str(native / "transport.cpp"),
             str(native / "statekernel.cpp"),
             str(native / "walkernel.cpp"),
             "-o", str(exe), "-lz"],
            capture_output=True, text=True, timeout=300,
        )
        assert build.returncode == 0, build.stderr[-1500:]
        wal_dir = tmp_path / "wal"
        wal_dir.mkdir()
        proc = subprocess.run(
            [str(exe), str(wal_dir)], capture_output=True, text=True,
            timeout=120,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "stress ok" in proc.stdout
