"""Durability-plane tests: WAL format, torn-write recovery, incremental
snapshots, native-runtime engagement on a durable cluster, and the
kill-9 crash-recovery smoke (docs/DURABILITY.md).
"""

from __future__ import annotations

import asyncio
import os
import struct
import uuid
from pathlib import Path

import pytest

from rabia_tpu.apps.kvstore import decode_kv_response, encode_set_bin
from rabia_tpu.persistence.native_wal import (
    SEG_HEADER,
    WalPersistence,
    decode_record,
    encode_barrier,
    encode_frontier,
    encode_ledger,
    encode_wave,
    scan_wal,
    truncate_torn_tail,
)


def _mk_records(n: int = 12) -> list[bytes]:
    out = []
    for i in range(n):
        out.append(
            encode_wave(
                i % 4, i // 4, 1, bytes([i]) * 16,
                [b"\x01\x02\x00k%d" % i + b"v" * (i % 7)],
            )
        )
    return out


class TestWalFormat:
    def test_record_roundtrip(self):
        ops = [b"\x01\x03\x00abcv1", b"", b"\xffgarbage"]
        bid = uuid.uuid4().bytes
        rec = decode_record(encode_wave(3, 77, 1, bid, ops))
        assert rec["kind"] == 1
        assert (rec["shard"], rec["slot"], rec["value"]) == (3, 77, 1)
        assert rec["bid"] == bid
        assert rec["ops"] == ops
        rec = decode_record(encode_wave(0, 5, 0, None, None))
        assert rec["ops"] is None and rec["bid"] is None
        vec = struct.pack("<4q", 1, 2, 3, 4)
        assert decode_record(encode_barrier(vec))["barrier"] == [1, 2, 3, 4]
        fr = decode_record(encode_frontier(7, 99, [5, 6]))
        assert fr["snap_index"] == 7 and fr["applied"] == [5, 6]
        led = decode_record(encode_ledger(2, 11, bid))
        assert (led["shard"], led["slot"], led["bid"]) == (2, 11, bid)

    def test_writer_scan_roundtrip_and_rotation(self, tmp_path):
        p = WalPersistence(tmp_path, segment_bytes=256, n_shards=4)
        recs = _mk_records(40)
        for r in recs:
            p._writer.append(r)
        p.flush_sync()
        p.close()
        scan = scan_wal(tmp_path)
        assert scan.torn is None
        assert [r[3] for r in scan.records] == recs
        assert len(list(tmp_path.glob("wal-*.seg"))) > 1  # rotated

    def test_lsn_continues_across_restart(self, tmp_path):
        p = WalPersistence(tmp_path, n_shards=4)
        for r in _mk_records(5):
            p._writer.append(r)
        p.flush_sync()
        p.close()
        p2 = WalPersistence(tmp_path, n_shards=4)
        assert p2.staged_lsn() == 5
        lsn = p2.stage_wave(0, 9, 0, None, None)
        assert lsn == 6
        p2.flush_sync()
        p2.close()
        assert scan_wal(tmp_path).last_lsn == 6


class TestTornWriteRecovery:
    def test_truncation_at_every_offset_across_a_record_boundary(
        self, tmp_path
    ):
        """The satellite pin: cut the log at EVERY byte offset across
        the last record's frame; recovery must land exactly on the last
        WHOLE record before the cut — never a torn apply, never a
        crash."""
        base = tmp_path / "base"
        base.mkdir()
        p = WalPersistence(base, n_shards=4)
        recs = _mk_records(6)
        for r in recs:
            p._writer.append(r)
        p.flush_sync()
        p.close()
        seg = next(base.glob("wal-*.seg"))
        blob = seg.read_bytes()
        # frame boundaries: offsets where records START
        bounds = [SEG_HEADER]
        pos = SEG_HEADER
        while pos < len(blob):
            plen = struct.unpack_from("<I", blob, pos)[0]
            pos += 8 + plen
            bounds.append(pos)
        # cut at every offset spanning the LAST record (and the frame
        # header of the one before it)
        for cut in range(bounds[-3], len(blob) + 1):
            d = tmp_path / f"cut{cut}"
            d.mkdir()
            (d / seg.name).write_bytes(blob[:cut])
            scan = scan_wal(d)
            whole = sum(1 for b in bounds[1:] if b <= cut)
            assert len(scan.records) == whole, (
                f"cut at {cut}: expected {whole} whole records, "
                f"scanned {len(scan.records)}"
            )
            assert [r[3] for r in scan.records] == recs[:whole]
            if cut in bounds:
                assert scan.torn is None
            else:
                assert scan.torn is not None
            # truncation leaves a clean log that re-scans identically
            truncate_torn_tail(d, scan)
            rescan = scan_wal(d)
            assert rescan.torn is None
            assert [r[3] for r in rescan.records] == recs[:whole]
            # and a new writer continues from the truncated prefix
            p2 = WalPersistence(d, n_shards=4)
            assert p2.staged_lsn() == whole
            p2.close()

    def test_corrupt_byte_flags_crc(self, tmp_path):
        p = WalPersistence(tmp_path, n_shards=4)
        for r in _mk_records(4):
            p._writer.append(r)
        p.flush_sync()
        p.close()
        seg = next(tmp_path.glob("wal-*.seg"))
        blob = bytearray(seg.read_bytes())
        blob[-3] ^= 0xFF  # flip a byte inside the last payload
        seg.write_bytes(bytes(blob))
        scan = scan_wal(tmp_path)
        assert scan.torn is not None
        assert scan.torn["reason"] == "crc mismatch"
        assert len(scan.records) == 3


class TestWalConformance:
    def test_byte_parity_fixed_seeds(self):
        from rabia_tpu.testing.conformance import (
            random_wal_records,
            run_waves_on_both_wal_paths,
        )

        for seed in (3, 20260803):
            run_waves_on_both_wal_paths(
                random_wal_records(seed, 200), tag=f"fixed seed={seed}"
            )


class TestIncrementalSnapshots:
    def test_delta_tracks_mutations_and_deletions(self):
        from rabia_tpu.apps.native_store import NativeStorePlane
        from rabia_tpu.persistence.native_wal import decode_store_delta

        if not _native_wal_available():
            pytest.skip("statekernel unavailable")
        pl = NativeStorePlane(1)

        def _set(k, v):
            return bytes([1]) + len(k).to_bytes(2, "little") + k + v

        def _del(k):
            return bytes([3]) + len(k).to_bytes(2, "little") + k

        pl.apply_ops(0, [_set(b"a", b"1"), _set(b"b", b"2")], 1.0)
        cleared, dels, ents = decode_store_delta(pl.snapshot_delta(0))
        assert not cleared and not dels and len(ents) == 2
        pl.snapshot_mark(0)
        cleared, dels, ents = decode_store_delta(pl.snapshot_delta(0))
        assert not dels and not ents  # clean after mark
        pl.apply_ops(0, [_del(b"a"), _set(b"c", b"3")], 2.0)
        cleared, dels, ents = decode_store_delta(pl.snapshot_delta(0))
        assert dels == [b"a"]
        assert [e[0] for e in ents] == [b"c"]
        pl.close()

    @pytest.mark.asyncio
    async def test_checkpoint_chain_and_gc(self, tmp_path):
        """Checkpoints write delta frames, GC drops covered segments,
        and restore replays the chain byte-identically."""
        from rabia_tpu.apps.sharded import make_sharded_kv

        sm, machines = make_sharded_kv(2)
        if sm._native_plane is None:
            pytest.skip("native plane unavailable")
        p = WalPersistence(
            tmp_path, segment_bytes=1024, n_shards=2, rebase_every=4
        )
        plane = sm._native_plane

        def _set(k, v):
            return bytes([1]) + len(k).to_bytes(2, "little") + k + v

        meta = {"next_slot": [0, 0], "applied_upto": [0, 0],
                "state_version": 0, "v1_applied": [0, 0]}
        for round_i in range(3):
            for i in range(30):
                plane.apply_ops(
                    i % 2, [_set(b"k%d" % i, b"v%d" % round_i)], 1.0
                )
                p.stage_wave(
                    i % 2, round_i * 15 + i // 2, 1, None,
                    [_set(b"k%d" % i, b"v%d" % round_i)],
                )
            meta = {
                "next_slot": [15 * (round_i + 1)] * 2,
                "applied_upto": [15 * (round_i + 1)] * 2,
                "state_version": 30 * (round_i + 1),
                "v1_applied": [15 * (round_i + 1)] * 2,
            }
            await p.checkpoint(meta, sm)
        assert p.checkpoints == 3
        snaps = sorted(tmp_path.glob("snap-*.dat"))
        assert len(snaps) == 3
        # chain restore into a FRESH plane lands on identical state
        sm2, machines2 = make_sharded_kv(2)
        p2 = WalPersistence(tmp_path, segment_bytes=1024, n_shards=2)
        meta2 = p2.restore_chain_into(sm2)
        assert meta2 is not None
        assert int(meta2["state_version"]) == 90
        for s in range(2):
            assert (
                machines[s].store.checksum()
                == machines2[s].store.checksum()
            )
            assert (
                machines[s].store.version == machines2[s].store.version
            )
        p.close()
        p2.close()


def _native_wal_available() -> bool:
    from rabia_tpu.native.build import load_statekernel

    return load_statekernel() is not None


class TestRecoveryGuards:
    def test_replay_stops_at_slot_gap(self, tmp_path):
        """A crash in the sync-adoption -> checkpoint window leaves a
        slot gap in the WAL; replay must stop the shard AT the gap
        (divergent-state guard), not apply past it."""
        from rabia_tpu.apps.sharded import make_sharded_kv
        from rabia_tpu.core.config import RabiaConfig
        from rabia_tpu.core.network import ClusterConfig
        from rabia_tpu.core.types import NodeId
        from rabia_tpu.engine import RabiaEngine
        from rabia_tpu.net import InMemoryHub

        p = WalPersistence(tmp_path, n_shards=2)
        op = b"\x01\x01\x00kv"
        p.stage_wave(0, 0, 1, bytes(16), [op])
        p.stage_wave(0, 3, 1, bytes(16), [op])  # gap: slots 1-2 missing
        p.flush_sync()
        p.close()
        p2 = WalPersistence(tmp_path, n_shards=2)
        hub = InMemoryHub()
        nid = NodeId.from_int(1)
        sm, _m = make_sharded_kv(2)
        cfg = RabiaConfig().with_kernel(num_shards=2, shard_pad_multiple=2)
        eng = RabiaEngine(
            ClusterConfig.new(nid, [nid]), sm, hub.register(nid),
            persistence=p2, config=cfg,
        )
        rep = p2.recover_engine(eng)
        assert rep["waves_replayed"] == 1  # slot 0 only
        assert int(eng.rt.applied_upto[0]) == 1  # stopped AT the gap
        p2.close()

    def test_barrier_survives_wal_prefix_gc(self, tmp_path):
        """The vote barrier rides the checkpoint chain meta: even after
        every K_BARRIER-bearing segment is GC'd, recovery still
        restores the vector (elementwise max of chain + records)."""
        import numpy as np

        from rabia_tpu.apps.sharded import make_sharded_kv

        sm, _m = make_sharded_kv(2)
        p = WalPersistence(tmp_path, segment_bytes=1024, n_shards=2)
        p._writer.set_barrier(np.asarray([7, 9], np.int64))
        p._writer.append(encode_barrier(struct.pack("<2q", 7, 9)))
        # filler forces rotation so the barrier-bearing segment is not
        # the open one (the case GC can actually unlink)
        for i in range(40):
            p.stage_wave(i % 2, i // 2, 1, bytes(16), [b"\x01\x01\x00kv"])
        asyncio.run(
            p.checkpoint(
                {"next_slot": [20, 20], "applied_upto": [20, 20],
                 "state_version": 40, "v1_applied": [20, 20]}, sm,
            )
        )
        p.flush_sync()
        p.close()
        segs = sorted(tmp_path.glob("wal-*.seg"))
        assert segs, "no segments on disk"
        # the barrier-bearing first segment must be GONE below the
        # checkpoint frontier: either the checkpoint's REAL WAL-prefix
        # GC already unlinked it (the flush thread rotated before the
        # checkpoint — timing-dependent), or we simulate the loss by
        # unlinking everything but the open tail
        assert p.gc_segments > 0 or len(segs) > 1, (
            "filler did not rotate a segment"
        )
        for seg in segs[:-1]:
            seg.unlink()
        p2 = WalPersistence(tmp_path, segment_bytes=1024, n_shards=2)
        assert p2.recovered.barrier is not None
        vec = list(struct.unpack("<2q", p2.recovered.barrier))
        assert vec == [7, 9]
        p2.close()


class TestBackendsOrphanSweep:
    @pytest.mark.asyncio
    async def test_sweep_does_not_race_live_aux_write(self, tmp_path):
        """Regression (satellite): constructing a SECOND
        FileSystemPersistence on a directory must not unlink a sibling
        instance's in-flight tmp file — its os.replace would fail with
        ENOENT and drop the aux write."""
        import threading

        from rabia_tpu.persistence.backends import FileSystemPersistence

        a = FileSystemPersistence(tmp_path)
        # hold a tmp file alive exactly as an executor-thread aux write
        # would, while a second instance runs its constructor sweep
        start = threading.Event()
        stop = threading.Event()
        errors: list[Exception] = []

        def writer() -> None:
            try:
                for i in range(200):
                    if i == 5:
                        start.set()
                    a._atomic_write(
                        a._aux_path("vote_barrier"), b"x" * 64
                    )
                    if stop.is_set():
                        break
            except Exception as e:  # noqa: BLE001
                errors.append(e)
            finally:
                start.set()

        th = threading.Thread(target=writer)
        th.start()
        start.wait(5)
        for _ in range(20):
            FileSystemPersistence(tmp_path)  # constructor sweep
        stop.set()
        th.join(10)
        assert not errors, f"aux write lost to the orphan sweep: {errors}"
        assert (await a.load_aux("vote_barrier")) == b"x" * 64

    def test_sweep_still_removes_foreign_orphans(self, tmp_path):
        from rabia_tpu.persistence.backends import FileSystemPersistence

        orphan = tmp_path / "state.tmp99999.0"  # a dead pid's leftovers
        tmp_path.mkdir(exist_ok=True)
        orphan.write_bytes(b"junk")
        FileSystemPersistence(tmp_path)
        assert not orphan.exists()


class TestDurableNativeRuntime:
    @pytest.mark.asyncio
    async def test_native_runtime_engages_with_wal_persistence(self):
        """The headline unlock: a persistence-ON cluster runs the
        GIL-free commit path when the persistence layer is the native
        WAL (the historical gate forced asyncio for ANY persistence)."""
        from rabia_tpu.native.build import load_runtime, load_walkernel
        from rabia_tpu.testing.gateway_cluster import GatewayCluster

        if load_runtime() is None or load_walkernel() is None:
            pytest.skip("native runtime/walkernel unavailable")
        c = GatewayCluster(3, 2, persistence="wal")
        try:
            await c.start()
            assert all(e._rtm is not None for e in c.engines), (
                "native runtime did not engage on the WAL cluster"
            )
            assert all(e._wal is not None and e._wal.native
                       for e in c.engines)
        finally:
            await c.stop()

    @pytest.mark.asyncio
    async def test_gil_handoffs_flat_and_waves_durable_on_wal_cluster(self):
        """Acceptance: on a DURABLE (WAL) cluster, a wave-lane submit ->
        result round trip grows waves_native with gil_handoffs flat, and
        the decided wave is durable (WLC wave count + durable watermark)
        before the result frame left the replica."""
        from rabia_tpu.gateway.client import RabiaClient
        from rabia_tpu.native.build import (
            load_runtime,
            load_sessionkernel,
            load_walkernel,
        )
        from rabia_tpu.testing.gateway_cluster import GatewayCluster

        if (
            load_runtime() is None
            or load_walkernel() is None
            or load_sessionkernel() is None
        ):
            pytest.skip("native libraries unavailable")
        c = GatewayCluster(3, 2, persistence="wal")
        cli = None
        try:
            await c.start()
            e0 = c.engines[0]
            if e0._rtm is None:
                pytest.skip("native runtime did not engage")
            cli = RabiaClient([c.endpoint(0)], call_timeout=30.0)
            await cli.connect()
            await asyncio.sleep(0.3)
            deadline = asyncio.get_event_loop().time() + 20.0
            hit = False
            k = 0
            while asyncio.get_event_loop().time() < deadline:
                before = e0._rtm.counters_dict()
                wal_before = e0._wal.counters_dict()
                resp = await cli.submit(
                    k % 2, [encode_set_bin(f"gilk{k}", "v")]
                )
                assert decode_kv_response(resp[0]).ok
                after = e0._rtm.counters_dict()
                wal_after = e0._wal.counters_dict()
                k += 1
                if after["waves_native"] > before["waves_native"]:
                    # the wave lane fired: the C thread applied AND
                    # staged the wave; results only left after the
                    # durability barrier
                    assert (
                        after["gil_handoffs"] == before["gil_handoffs"]
                    ), (
                        "durable submit->result round trip required a "
                        f"GIL handoff: {before} -> {after}"
                    )
                    assert wal_after["waves"] > wal_before["waves"], (
                        "wave-lane commit staged no WAL record"
                    )
                    assert e0._wal.durable_lsn() >= 1
                    hit = True
                    break
            assert hit, "no wave-lane submit landed within the deadline"
        finally:
            if cli is not None:
                await cli.close()
            await c.stop()

    @pytest.mark.asyncio
    async def test_restart_recovers_from_chain_plus_replay(self):
        """In-process restart on the WAL plane: the restarted replica
        recovers from snapshot chain + WAL replay and reconverges."""
        from rabia_tpu.gateway.client import RabiaClient
        from rabia_tpu.native.build import load_walkernel
        from rabia_tpu.testing.gateway_cluster import GatewayCluster

        if load_walkernel() is None and os.environ.get("RABIA_PY_WAL") != "1":
            pytest.skip("walkernel unavailable")
        c = GatewayCluster(3, 2, persistence="wal")
        cli = None
        try:
            await c.start()
            cli = RabiaClient(c.endpoints(), call_timeout=30.0)
            await cli.connect()
            for k in range(24):
                resp = await cli.submit(
                    k % 2, [encode_set_bin(f"rk{k}", f"v{k}")]
                )
                assert decode_kv_response(resp[0]).ok
            await cli.close()
            cli = None
            await c.restart_replica(1, settle=0.3)
            await c.wait_converged(20)
            rec = c.persists[1].last_recovery
            assert rec["chain_files"] + rec["waves_replayed"] > 0, (
                f"restart recovered nothing: {rec}"
            )
            r = c.store(1, 0).get("rk0")
            assert getattr(r, "value", None) == "v0" or r == "v0"
        finally:
            if cli is not None:
                await cli.close()
            await c.stop()


class TestCrashRecoverySmoke:
    @pytest.mark.asyncio
    async def test_kill9_restart_rejoins_under_load(self):
        """The CI recovery smoke cell: 3 real replica processes on the
        durability plane, kill -9 one under sustained loadgen traffic,
        restart it, assert rejoin within budget and non-zero
        post-rejoin goodput."""
        from rabia_tpu.testing.recovery import run_crash_recovery_trial

        report = await run_crash_recovery_trial(
            preload_keys=40, rejoin_timeout=90.0
        )
        assert report["rejoined"], f"replica never rejoined: {report}"
        assert report["rejoin_under_load_s"] < 90.0
        assert report["post_rejoin_goodput_ok"] > 0, (
            f"cluster made no progress after rejoin: {report}"
        )
        # the restarted process actually recovered durable state
        assert (report["waves_replayed"] or 0) + (
            report["chain_files"] or 0
        ) > 0, f"nothing recovered: {report}"


class TestWalDumpCli:
    def test_wal_dump_renders_and_flags_torn_tail(self, tmp_path, capsys):
        from rabia_tpu.__main__ import main as cli_main

        p = WalPersistence(tmp_path, n_shards=2)
        for i in range(8):
            p.stage_wave(i % 2, i // 2, 1, bytes(16), [b"\x01\x01\x00kv"])
        p.flush_sync()
        p.close()
        assert cli_main(["wal-dump", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "records: 8" in out and "crc=ok" in out
        # torn tail flags, never crashes
        seg = sorted(tmp_path.glob("wal-*.seg"))[-1]
        with open(seg, "ab") as f:
            f.write(b"\x55" * 9)
        assert cli_main(["wal-dump", str(tmp_path), "--records"]) == 0
        out = capsys.readouterr().out
        assert "torn tail" in out and "TORN" in out


class TestReceiverLedgerCompleteness:
    """Receiver-side batch-id ledger backfill (ROADMAP 3c / chaos-plane
    satellite): NON-proposer replicas must resolve a batch id for every
    V1 wave the C runtime staged with a zero bid field — via the
    EV_LEDGER-driven K_LEDGER records — so a follower's crash replay
    repopulates its ``applied_ids`` dedup ledger in parity with the
    proposer's."""

    @pytest.mark.asyncio
    async def test_follower_wal_resolves_every_v1_bid_and_replay_parity(
        self, tmp_path
    ):
        import shutil

        from rabia_tpu.core.blocks import block_batch_id  # noqa: F401
        from rabia_tpu.gateway.client import RabiaClient
        from rabia_tpu.native.build import (
            load_runtime,
            load_sessionkernel,
            load_walkernel,
        )
        from rabia_tpu.testing.gateway_cluster import GatewayCluster

        if (
            load_runtime() is None
            or load_walkernel() is None
            or load_sessionkernel() is None
        ):
            pytest.skip("native libraries unavailable")
        c = GatewayCluster(
            3, 2, persistence="wal",
            # no periodic checkpoints: the whole run must stay in the
            # replayable WAL suffix (a clean shutdown's final checkpoint
            # would fence it; the mid-run dir copy below simulates the
            # crash shape recovery actually faces)
            wal_kwargs={
                "checkpoint_interval": 3600.0,
                "checkpoint_bytes": 1 << 30,
            },
        )
        cli = None
        crash_copies = {}
        try:
            await c.start()
            if any(e._rtm is None for e in c.engines):
                pytest.skip("native runtime did not engage")
            cli = RabiaClient([c.endpoint(0)], call_timeout=30.0)
            await cli.connect()
            for k in range(24):
                resp = await cli.submit(
                    k % 2, [encode_set_bin(f"led{k}", f"v{k}")]
                )
                assert decode_kv_response(resp[0]).ok
            await cli.close()
            cli = None
            await asyncio.sleep(0.5)
            for e in c.engines:
                e._wal.flush_sync()
            # crash-shaped evidence: copy the durable dirs NOW (no clean
            # shutdown checkpoint in the copy)
            for r in range(3):
                dst = tmp_path / f"crash-{r}"
                shutil.copytree(f"{c.wal_dir}/replica-{r}", dst)
                crash_copies[r] = dst
        finally:
            if cli is not None:
                await cli.close()
            await c.stop()

        # scan-level parity: every replica resolves a bid for every V1
        # wave (zero-bid C-staged ones via K_LEDGER), and the resolved
        # (shard, slot) -> bid maps agree across replicas
        maps = {}
        zero_backfilled = {}
        for r, d in crash_copies.items():
            p = WalPersistence(d, n_shards=2)
            try:
                m = {}
                n_zero = 0
                for _lsn, rec in p.recovered.waves:
                    if rec["value"] != 1:
                        continue
                    bid = rec["bid"]
                    if not bid or bid == bytes(16):
                        n_zero += 1
                        # ledger values are LISTS since round 15 (the
                        # coalescing lane stages alias ids after the
                        # wave's own id); the wave's id is first
                        lst = p.recovered.ledger.get(
                            (rec["shard"], rec["slot"])
                        )
                        bid = lst[0] if lst else None
                    assert bid, (
                        f"replica {r}: V1 wave (shard {rec['shard']} "
                        f"slot {rec['slot']}) has no resolvable batch "
                        "id — receiver-side K_LEDGER backfill missing"
                    )
                    m[(rec["shard"], rec["slot"])] = bytes(bid)
                maps[r] = m
                zero_backfilled[r] = n_zero
            finally:
                p.close()
        assert any(m for m in maps.values()), "no V1 waves recovered"
        # at least one replica exercised the zero-bid (C-staged peer
        # block) lane — otherwise this test proved nothing
        assert sum(zero_backfilled.values()) > 0, (
            f"no zero-bid waves were staged anywhere: {zero_backfilled}"
        )
        for r in (1, 2):
            common = set(maps[0]) & set(maps[r])
            for key in common:
                assert maps[0][key] == maps[r][key], (
                    f"bid mismatch at {key}: proposer "
                    f"{maps[0][key].hex()} vs replica {r} "
                    f"{maps[r][key].hex()}"
                )

        # replay parity: recover a FOLLOWER copy into a fresh engine and
        # check the dedup ledger repopulates with the same ids
        follower = max(
            (r for r in maps if r != 0),
            key=lambda r: zero_backfilled[r],
        )
        from rabia_tpu.apps.sharded import make_sharded_kv
        from rabia_tpu.core.network import ClusterConfig
        from rabia_tpu.engine import RabiaEngine
        from rabia_tpu.net import NetworkSimulator

        p = WalPersistence(crash_copies[follower], n_shards=2)
        try:
            sim = NetworkSimulator()
            sm, _machines = make_sharded_kv(2)
            eng = RabiaEngine(
                ClusterConfig.new(c.ids[follower], c.ids),
                sm,
                sim.register(c.ids[follower]),
                persistence=p,
                config=c.config,
            )
            p.recover_engine(eng)
            replayed_ids = {
                bid.value.bytes
                for s in range(2)
                for bid in eng.rt.shards[s].applied_ids
            }
            missing = [
                key for key, bid in maps[follower].items()
                if bid not in replayed_ids
            ]
            assert not missing, (
                f"follower replay missed {len(missing)} batch ids in "
                f"applied_ids: {missing[:4]}"
            )
        finally:
            p.close()


class TestMultiWorkerWalOrdering:
    """Thread-per-shard-group runtime x durability plane (round 14):
    N workers stage decided waves through per-worker WAL lanes into the
    ONE group-commit flush thread. The staging mutex assigns LSNs, so
    the on-disk record sequence must stay monotone-contiguous, and a
    kill -9 mid-load must recover exactly as the single-worker runtime
    does (state parity across worker counts is pinned separately by
    run_schedule_on_runtime_paths)."""

    @pytest.mark.asyncio
    async def test_kill9_recovery_with_two_workers(self, monkeypatch):
        from rabia_tpu.persistence.native_wal import scan_wal
        from rabia_tpu.testing.recovery import run_crash_recovery_trial

        monkeypatch.setenv("RABIA_RT_WORKERS", "2")
        report = await run_crash_recovery_trial(
            n_shards=4, preload_keys=40, rejoin_timeout=90.0
        )
        assert report["rejoined"], f"replica never rejoined: {report}"
        assert report["post_rejoin_goodput_ok"] > 0, report
        # the restarted process replayed real durable state
        assert (report["waves_replayed"] or 0) + (
            report["chain_files"] or 0
        ) > 0, f"nothing recovered: {report}"
        # multi-lane staging yielded a monotone, contiguous LSN
        # sequence on disk: scan every replica's log — a discontinuity
        # or a mid-log tear is a staging-order violation (a tear in the
        # FINAL segment is an in-flight group commit at shutdown, the
        # normal crash shape)
        from pathlib import Path as _Path

        root = _Path(report["wal_root"])
        scanned = 0
        for sub in sorted(root.iterdir()):
            if not sub.is_dir():
                continue
            segs = sorted(sub.glob("wal-*.seg"))
            if not segs:
                continue
            scan = scan_wal(sub)
            scanned += 1
            assert scan.last_lsn > 0, f"{sub}: empty durable prefix"
            if scan.torn is not None:
                last_idx = max(
                    int(p.stem.split("-", 1)[1]) for p in segs
                )
                assert scan.torn["segment"] == last_idx, (
                    f"{sub}: mid-log tear/discontinuity under "
                    f"multi-worker staging: {scan.torn}"
                )
        assert scanned >= 3, f"expected 3 replica logs under {root}"
        # leave no tempdir behind on success
        import shutil as _shutil

        _shutil.rmtree(root, ignore_errors=True)
