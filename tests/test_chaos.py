"""Chaos-plane tests: NetworkSimulator edge semantics (partition expiry
racing heal, crash with in-flight messages, node delay x partition, the
new per-link asymmetric loss and scheduled flapping), the profile DSL,
and a short end-to-end scenario run with consensus-health evidence.
"""

from __future__ import annotations

import asyncio
import time

import pytest

from rabia_tpu.chaos import default_profiles, smoke_profiles
from rabia_tpu.chaos.profiles import ChaosEvent, ChaosProfile
from rabia_tpu.core.types import NodeId
from rabia_tpu.net import NetworkConditions, NetworkSimulator

A = NodeId.from_int(1)
B = NodeId.from_int(2)
C = NodeId.from_int(3)


def _sim(**kw) -> tuple[NetworkSimulator, dict]:
    sim = NetworkSimulator(**kw)
    nets = {n: sim.register(n) for n in (A, B, C)}
    return sim, nets


async def _drain(net, timeout=0.3):
    out = []
    while True:
        try:
            out.append(await net.receive(timeout=timeout))
        except Exception:
            return out


class TestSimulatorEdgeSemantics:
    @pytest.mark.asyncio
    async def test_partition_duration_expiry_races_heal(self):
        """A timed partition that already expired must leave heal_partition
        a no-op (not resurrect anything), and a heal BEFORE expiry must
        not be undone by the stale expiry deadline when a new untimed
        partition follows."""
        sim, nets = _sim()
        sim.partition({B}, duration=0.05)
        sim.send(A, B, b"during")
        assert sim.stats.messages_dropped == 1
        await asyncio.sleep(0.08)
        # expired: traffic flows again even with no explicit heal
        sim.send(A, B, b"after-expiry")
        assert (await nets[B].receive(timeout=0.5))[1] == b"after-expiry"
        sim.heal_partition()  # racing the (already-fired) expiry: no-op
        sim.send(A, B, b"after-heal")
        assert (await nets[B].receive(timeout=0.5))[1] == b"after-heal"

        # heal BEFORE expiry, then a new UNTIMED partition: the old
        # deadline must not expire the new partition early
        sim.partition({B}, duration=5.0)
        sim.heal_partition()
        sim.send(A, B, b"healed-early")
        assert (await nets[B].receive(timeout=0.5))[1] == b"healed-early"
        sim.partition({B})  # no duration: until healed
        await asyncio.sleep(0.06)
        dropped = sim.stats.messages_dropped
        sim.send(A, B, b"blocked")
        assert sim.stats.messages_dropped == dropped + 1

    @pytest.mark.asyncio
    async def test_crash_with_messages_in_flight_drops_at_delivery(self):
        """Messages already in the delay heap when the target crashes
        must be dropped at DELIVERY time, not handed to a dead node."""
        sim, nets = _sim(
            conditions=NetworkConditions(latency_min=0.05, latency_max=0.06)
        )
        sim.send(A, B, b"doomed")
        sim.crash(B)  # in-flight: due in ~50ms
        await asyncio.sleep(0.12)
        assert nets[B].receive_nowait() is None
        assert sim.stats.messages_dropped == 1
        # recovery does NOT resurrect the dropped message
        sim.recover(B)
        await asyncio.sleep(0.08)
        assert nets[B].receive_nowait() is None
        # but fresh traffic flows
        sim.send(A, B, b"fresh")
        assert (await nets[B].receive(timeout=0.5))[1] == b"fresh"

    @pytest.mark.asyncio
    async def test_node_delay_interacts_with_partition_at_delivery(self):
        """set_node_delay holds a message in flight; a partition that
        activates before the due time blocks it at delivery (one-sided
        membership check runs again at delivery time), and a partition
        that heals before the due time lets it through."""
        sim, nets = _sim()
        sim.set_node_delay(B, 0.08)
        sim.send(A, B, b"blocked-at-delivery")
        sim.partition({B}, duration=0.5)  # activates while in flight
        await asyncio.sleep(0.15)
        assert nets[B].receive_nowait() is None
        assert sim.stats.messages_dropped == 1
        sim.heal_partition()
        # reverse order: partitioned at SEND time drops immediately,
        # regardless of the pending delay
        dropped = sim.stats.messages_dropped
        sim.partition({B}, duration=0.02)
        sim.send(A, B, b"dropped-at-send")
        assert sim.stats.messages_dropped == dropped + 1
        # healed while in flight: delivered
        await asyncio.sleep(0.05)
        sim.send(A, B, b"in-flight-heals")
        assert (
            await nets[B].receive(timeout=1.0)
        )[1] == b"in-flight-heals"
        sim.set_node_delay(B, 0.0)

    @pytest.mark.asyncio
    async def test_asymmetric_link_loss_is_directional(self):
        sim, nets = _sim()
        sim.set_link_loss(A, B, 1.0)
        for _ in range(5):
            sim.send(A, B, b"up")   # all dropped
            sim.send(B, A, b"down")  # all delivered
        assert len(await _drain(nets[A], timeout=0.1)) == 5
        assert nets[B].receive_nowait() is None
        # other links untouched
        sim.send(A, C, b"side")
        assert (await nets[C].receive(timeout=0.5))[1] == b"side"
        sim.clear_link_faults()
        sim.send(A, B, b"cleared")
        assert (await nets[B].receive(timeout=0.5))[1] == b"cleared"

    @pytest.mark.asyncio
    async def test_flap_schedule_blocks_down_windows_then_expires(self):
        sim, nets = _sim()
        sim.set_flap({B}, period=0.2, duty=0.5, duration=0.5)
        t0 = time.monotonic()
        # first half-period: down (blocked, one-sided)
        dropped = sim.stats.messages_dropped
        sim.send(A, B, b"down-window")
        assert sim.stats.messages_dropped == dropped + 1
        sim.send(C, A, b"unaffected")  # neither endpoint in the group
        assert (await nets[A].receive(timeout=0.5))[1] == b"unaffected"
        # wait into the second half-period: up
        await asyncio.sleep(max(0.0, t0 + 0.12 - time.monotonic()))
        sim.send(A, B, b"up-window")
        assert (await nets[B].receive(timeout=0.5))[1] == b"up-window"
        # past the episode: flapping is over regardless of phase
        await asyncio.sleep(max(0.0, t0 + 0.55 - time.monotonic()))
        sim.send(A, B, b"episode-over")
        assert (await nets[B].receive(timeout=0.5))[1] == b"episode-over"
        # get_connected_nodes honors the flap window too
        sim.set_flap({B}, period=10.0, duty=1.0)
        assert B not in await nets[A].get_connected_nodes()
        sim.clear_flap()
        assert B in await nets[A].get_connected_nodes()


class TestProfileDsl:
    def test_default_matrix_shape(self):
        profs = default_profiles()
        assert len(profs) >= 6
        fabrics = {p.fabric for p in profs.values()}
        assert fabrics == {"sim", "tcp", "fleet", "mesh", "groups"}
        # the acceptance shape: >=1 real-TCP shaped, >=1 membership,
        # >=1 routed-fleet gateway failover (round 16), >=1 device-plane
        # mesh with a mid-window demotion (round 17), >=1 partitioned-
        # group proposer kill (round 20)
        assert any(
            p.fabric == "tcp"
            and any(e.action in ("wan", "link_loss") for e in p.events)
            for p in profs.values()
        )
        assert any(
            any(
                e.action in ("stop_replica", "start_replica",
                             "restart_replica")
                for e in p.events
            )
            for p in profs.values()
        )
        assert any(
            p.fabric == "fleet"
            and any(e.action == "kill_gateway" for e in p.events)
            for p in profs.values()
        )
        assert any(
            p.fabric == "mesh"
            and any(e.action == "demote_device" for e in p.events)
            for p in profs.values()
        )
        assert any(
            p.fabric == "groups"
            and any(e.action == "kill_group_proposer" for e in p.events)
            for p in profs.values()
        )
        smoke = smoke_profiles()
        assert 2 <= len(smoke) <= 7
        assert any(p.fabric == "tcp" for p in smoke.values())
        assert "routed_gateway_failover" in smoke
        assert "mesh_device_read_lane" in smoke
        assert "group_proposer_kill" in smoke

    def test_scaling_preserves_structure(self):
        p = ChaosProfile(
            name="x", fabric="sim", description="", duration=10.0,
            events=(
                ChaosEvent(2.0, "flap",
                           {"group": [1], "period": 1.0, "duty": 0.4,
                            "duration": 4.0}),
                ChaosEvent(8.0, "heal", {}),
            ),
        )
        s = p.scaled(0.5)
        assert s.duration == 5.0
        assert s.events[0].at == 1.0
        assert s.events[0].args["period"] == 0.5
        assert s.events[0].args["duration"] == 2.0
        assert s.events[0].args["duty"] == 0.4  # NOT time-scaled
        assert s.events[1].at == 4.0
        assert p.scaled(1.0) is p


class TestScenarioRunSim:
    @pytest.mark.asyncio
    async def test_short_sim_profile_records_evidence_and_timeline(self):
        """End-to-end mini scenario on the simulator fabric: the report
        must carry a continuous availability timeline, the
        phases-to-decide distribution and coin tallies — the evidence
        schema every matrix entry promises (docs/SCENARIOS.md)."""
        from rabia_tpu.chaos.runner import run_profile

        prof = ChaosProfile(
            name="mini",
            fabric="sim",
            description="mini flap",
            duration=2.5,
            warmup=0.5,
            rate=60.0,
            events=(
                ChaosEvent(0.5, "flap",
                           {"group": [2], "period": 0.5, "duty": 0.4,
                            "duration": 1.2}),
            ),
            min_availability=0.2,
        )
        rep = await run_profile(prof, verbose=False)
        assert rep["arrivals"] > 0
        assert rep["outcomes"]["ok"] > 0
        assert len(rep["timeline"]) >= 8
        assert any(
            w["availability"] is not None for w in rep["timeline"]
        )
        ev = rep["phases_to_decide"]
        assert ev["decisions"] > 0
        assert ev["hist"], "empty phase-count distribution"
        assert ev["mean_phases"] >= 1.0
        assert set(ev["coin_flips"]) == {"v0", "v1"}
        assert rep["converged"] is True
        assert rep["pass"], rep["problems"]


class TestScenarioRunFleet:
    @pytest.mark.asyncio
    async def test_routed_gateway_failover_mini(self):
        """End-to-end mini routed-fleet scenario: kill a fleet gateway
        mid-wave — clients follow the ring to the survivor, the run
        scores non-zero goodput through the kill, and the post-run
        exactly-once replay sweep (fabric.verify) passes with zero
        problems."""
        from rabia_tpu.chaos.profiles import default_profiles
        from rabia_tpu.chaos.runner import run_profile

        prof = default_profiles()["routed_gateway_failover"].scaled(0.4)
        rep = await run_profile(prof, verbose=False)
        assert rep["fabric"] == "fleet"
        assert rep["outcomes"]["ok"] > 0
        assert rep["converged"] is True
        assert rep["pass"], rep["problems"]


class TestElasticMembership:
    @pytest.mark.asyncio
    async def test_stop_start_replica_under_client_load(self):
        """GatewayCluster's elastic-membership surface directly: a
        replica decommissions while a client keeps committing against
        the surviving quorum, then rejoins (WAL recovery) and the
        cluster reconverges with the writes that happened while it was
        gone."""
        from rabia_tpu.apps.kvstore import decode_kv_response, encode_set_bin
        from rabia_tpu.gateway.client import RabiaClient
        from rabia_tpu.native.build import load_walkernel
        from rabia_tpu.testing.gateway_cluster import GatewayCluster

        if load_walkernel() is None:
            pytest.skip("walkernel unavailable")
        c = GatewayCluster(3, 2, persistence="wal")
        cli = None
        try:
            await c.start()
            cli = RabiaClient(
                [c.endpoint(0), c.endpoint(1)], call_timeout=30.0
            )
            await cli.connect()
            for k in range(6):
                resp = await cli.submit(
                    k % 2, [encode_set_bin(f"em{k}", f"v{k}")]
                )
                assert decode_kv_response(resp[0]).ok
            await c.stop_replica(2)
            assert c.is_down(2) and c.live_replicas == [0, 1]
            # the surviving quorum keeps serving THROUGH the outage
            for k in range(6, 12):
                resp = await cli.submit(
                    k % 2, [encode_set_bin(f"em{k}", f"v{k}")]
                )
                assert decode_kv_response(resp[0]).ok
            await c.start_replica(2)
            assert not c.is_down(2)
            await c.wait_converged(20)
            # the rejoined replica holds a write it never saw live
            # (em8 was submitted on shard 8 % 2 == 0)
            v = c.store(2, 0).get("em8")
            assert getattr(v, "value", v) == "v8"
        finally:
            if cli is not None:
                await cli.close()
            await c.stop()
