"""Cross-session submit coalescing lane (docs/PERFORMANCE.md
"Coalescing tier"): many clients' Submits packed into one multi-client
PayloadBlock entry, per-client alias ids in the dedup ledger, and one
durability-barrier wait releasing every covered Result.

Exactly-once gates (the round-15 acceptance):
- a replayed Submit whose original rode a coalesced wave answers from
  the dedup cache with ONLY that client's response slice;
- a gateway torn down mid-window (staged but un-proposed ops) sheds the
  parked submits retryable and a client retry applies exactly once;
- alias batch ids survive WAL crash recovery (K_LEDGER lists).

Parametrized over the native sessionkernel table and the Python
semantics owner (``RABIA_PY_GATEWAY=1``).
"""

from __future__ import annotations

import asyncio
import uuid

import pytest

from rabia_tpu.apps.kvstore import (
    decode_kv_response,
    encode_set_bin,
)
from rabia_tpu.core.messages import ResultStatus, Submit
from rabia_tpu.core.types import BatchId
from rabia_tpu.gateway import GatewayConfig, RabiaClient
from rabia_tpu.obs.flight import batch_id_for
from rabia_tpu.testing.gateway_cluster import GatewayCluster

SHARDS = 4

# both gateway session tables (the conformance pair): the native
# sessionkernel plane and the Python semantics owner
TABLES = ["native", "python"]


def _table_env(monkeypatch, table: str) -> None:
    if table == "python":
        monkeypatch.setenv("RABIA_PY_GATEWAY", "1")
    else:
        monkeypatch.delenv("RABIA_PY_GATEWAY", raising=False)


async def _spin_up(**kw) -> GatewayCluster:
    gw_cfg = kw.pop(
        "gateway_config",
        GatewayConfig(coalesce=True, coalesce_window=0.01),
    )
    cluster = GatewayCluster(
        n_replicas=3, n_shards=SHARDS, gateway_config=gw_cfg, **kw
    )
    await cluster.start()
    return cluster


async def _connect_clients(cluster, n: int, gw: int = 0):
    clients = []
    for _ in range(n):
        c = RabiaClient([cluster.endpoint(gw)], call_timeout=30.0)
        await c.connect()
        clients.append(c)
    return clients


def _wipe_sessions(gw) -> None:
    """Total session-state loss at the gateway: the python table clears
    its dict; the native table is rebuilt empty."""
    if hasattr(gw.sessions, "sessions"):
        gw.sessions.sessions.clear()
    else:
        from rabia_tpu.gateway.native_session import make_session_table

        gw.sessions.close()
        gw.sessions = make_session_table(
            default_window=gw.config.max_inflight_per_session,
            session_ttl=gw.config.session_ttl,
            result_cache_cap=gw.config.result_cache_cap,
            lease_ttl=gw.config.session_lease,
        )


class TestCoalescedWave:
    @pytest.mark.asyncio
    @pytest.mark.parametrize("table", TABLES)
    async def test_multi_client_wave_exactly_once(self, table, monkeypatch):
        """8 concurrent clients' submits to one shard ride ONE wave:
        per-client response slices, one coalesce wave proposed, every
        covered client's alias id registered in the dedup ledger, and
        the state identical on every replica."""
        _table_env(monkeypatch, table)
        cluster = await _spin_up(persistence="wal")
        clients = []
        try:
            gw = cluster.gateways[0]
            assert gw.sessions.is_native == (table == "native")
            clients = await _connect_clients(cluster, 8)
            shard = 1
            res = await asyncio.gather(
                *(
                    c.submit(shard, [encode_set_bin(f"mc{i}", f"v{i}")])
                    for i, c in enumerate(clients)
                )
            )
            for i, r in enumerate(res):
                assert len(r) == 1, "per-client slice, not the wave"
                assert decode_kv_response(r[0]).ok
            assert gw.stats.coalesce_waves >= 1
            assert gw.stats.submits_coalesced >= 2
            # ONE durability barrier covered many results
            wal = cluster.engines[0]._wal
            assert wal.barrier_covered >= wal.barrier_waits
            assert wal.barrier_covered >= 8
            # every covered client's deterministic id is in the ledger
            # (the wire-symmetric entry id in applied_ids, proposer-
            # local aliases in alias_ledger). A NON-lead id holds ONLY
            # its response slice; an ENTRY (== lead) id keeps the FULL
            # entry list intact (_settle_from_ledger and entry-level
            # peer repair depend on it — the lead's replay truncates to
            # its own prefix at SERVE time instead, asserted below)
            sh = cluster.engines[0].rt.shards[shard]
            lead = None
            for i, c in enumerate(clients):
                bid = BatchId(batch_id_for(c.client_id, 1))
                assert bid in sh.applied_ids or bid in sh.alias_ledger, (
                    f"client {i} alias missing"
                )
                cached = sh.applied_results.get(bid)
                assert cached is not None, f"client {i} responses missing"
                got = len(list(cached))
                if bid in sh.applied_ids and got > 1:
                    lead = c  # entry id: full entry response list
                else:
                    assert got == 1, f"client {i}: {got} responses"
            # the LEAD's session-loss replay serves ONLY its own prefix
            # (the ledger holds the full entry list under its id; the
            # serve path truncates to the replayed op count)
            if lead is not None:
                _wipe_sessions(gw)
                res = await lead._call(1, Submit(
                    client_id=lead.client_id, seq=1, shard=shard,
                    commands=(encode_set_bin("lead-replay", "X"),),
                ))
                assert res.status in (
                    ResultStatus.OK, ResultStatus.CACHED,
                ), (res.status, res.payload)
                assert len(res.payload) == 1, (
                    "lead replay leaked the full entry response list"
                )
                assert decode_kv_response(res.payload[0]).ok
            # state converged everywhere
            await cluster.wait_converged()
            for r in range(3):
                for i in range(8):
                    got = cluster.store(r, shard).get(f"mc{i}")
                    assert got.value == f"v{i}"
        finally:
            for c in clients:
                await c.close()
            await cluster.stop()

    @pytest.mark.asyncio
    @pytest.mark.parametrize("table", TABLES)
    async def test_replay_of_coalesced_submit_hits_dedup_cache(
        self, table, monkeypatch
    ):
        """A replayed (client_id, seq) whose ORIGINAL rode a coalesced
        wave answers CACHED from the session table — with only that
        client's payload — and proposes nothing new."""
        _table_env(monkeypatch, table)
        cluster = await _spin_up()
        clients = []
        try:
            clients = await _connect_clients(cluster, 4)
            shard = 2
            await asyncio.gather(
                *(
                    c.submit(shard, [encode_set_bin(f"rp{i}", f"v{i}")])
                    for i, c in enumerate(clients)
                )
            )
            gw = cluster.gateways[0]
            assert gw.stats.submits_coalesced >= 2
            v1_before = sum(e.rt.decided_v1 for e in cluster.engines)
            c2 = clients[2]
            dup = Submit(
                client_id=c2.client_id, seq=1, shard=shard,
                commands=(encode_set_bin("rp2", "DIFFERENT"),),
            )
            res = await c2._call(1, dup)
            assert res.status == ResultStatus.CACHED
            assert len(res.payload) == 1
            assert decode_kv_response(res.payload[0]).ok
            await asyncio.sleep(0.2)
            assert (
                sum(e.rt.decided_v1 for e in cluster.engines) == v1_before
            ), "replay re-proposed"
            # the original value survived
            assert cluster.store(0, shard).get("rp2").value == "v2"
        finally:
            for c in clients:
                await c.close()
            await cluster.stop()

    @pytest.mark.asyncio
    @pytest.mark.parametrize("table", TABLES)
    async def test_session_loss_replay_dedups_via_alias_ledger(
        self, table, monkeypatch
    ):
        """Session state wiped AFTER a coalesced commit: a replay of a
        NON-LEAD covered client re-proposes under its deterministic id,
        and the alias ledger blocks the double apply (the scalar lane's
        round-8 guarantee, extended to multi-client waves)."""
        _table_env(monkeypatch, table)
        cluster = await _spin_up()
        clients = []
        try:
            clients = await _connect_clients(cluster, 4)
            shard = 1
            await asyncio.gather(
                *(
                    c.submit(shard, [encode_set_bin(f"sl{i}", f"v{i}")])
                    for i, c in enumerate(clients)
                )
            )
            gw = cluster.gateways[0]
            assert gw.stats.submits_coalesced >= 2
            store = cluster.store(0, shard)
            ver = store.version
            _wipe_sessions(gw)
            # replay client 3 (a non-lead window member, order-agnostic:
            # ANY covered client must dedup)
            c3 = clients[3]
            dup = Submit(
                client_id=c3.client_id, seq=1, shard=shard,
                commands=(encode_set_bin("sl3", "v3"),),
            )
            res = await c3._call(1, dup)
            assert res.status in (ResultStatus.OK, ResultStatus.CACHED), (
                res.status, res.payload,
            )
            await asyncio.sleep(0.2)
            assert store.version == ver, "double apply after session loss"
        finally:
            for c in clients:
                await c.close()
            await cluster.stop()


class TestCrossGatewayReplay:
    @pytest.mark.asyncio
    async def test_failover_replay_of_lead_dedups_on_peer_gateway(
        self, monkeypatch
    ):
        """Durable cluster: a wave's wire-derivable (lead) batch id
        enters EVERY replica's live applied ledger, so a client that
        fails over to a DIFFERENT replica's gateway and replays its seq
        dedups there (the responses repair from the peer that holds
        them) instead of re-proposing. Non-lead coalesced aliases stay
        proposer-local by design (PROTOCOL_GUIDE §4e; dedup-table
        replication is ROADMAP item 2)."""
        monkeypatch.delenv("RABIA_PY_GATEWAY", raising=False)
        cluster = await _spin_up(persistence="wal")
        clients = []
        try:
            clients = await _connect_clients(cluster, 4)
            shard = 1
            await asyncio.gather(
                *(
                    c.submit(shard, [encode_set_bin(f"fo{i}", f"v{i}")])
                    for i, c in enumerate(clients)
                )
            )
            assert cluster.gateways[0].stats.submits_coalesced >= 2
            await cluster.wait_converged()
            await asyncio.sleep(0.3)  # EV_LEDGER drain on followers
            # the lead (first-parked) client: find one whose id is in a
            # FOLLOWER's live ledger (the wire-derivable entry id)
            lead = None
            sh1 = cluster.engines[1].rt.shards[shard]
            for c in clients:
                if BatchId(batch_id_for(c.client_id, 1)) in sh1.applied_ids:
                    lead = c
                    break
            assert lead is not None, (
                "no covered client's id reached the follower ledger"
            )
            store = cluster.store(1, shard)
            ver = store.version
            # fail over: same client identity, DIFFERENT gateway
            fo = RabiaClient(
                [cluster.endpoint(1)], call_timeout=30.0,
                client_id=lead.client_id,
            )
            await fo.connect()
            dup = Submit(
                client_id=lead.client_id, seq=1, shard=shard,
                commands=(encode_set_bin("fo-replay", "X"),),
            )
            res = await fo._call(1, dup)
            assert res.status in (
                ResultStatus.OK, ResultStatus.CACHED, ResultStatus.ERROR,
            )
            await asyncio.sleep(0.3)
            assert store.version == ver, (
                "failover replay re-applied on the peer gateway"
            )
            # the replayed commands were NOT applied either
            assert store.get("fo-replay").value is None
            await fo.close()
        finally:
            for c in clients:
                await c.close()
            await cluster.stop()


class TestWindowTeardown:
    @pytest.mark.asyncio
    @pytest.mark.parametrize("table", TABLES)
    async def test_close_mid_window_sheds_retryable_never_applies(
        self, table, monkeypatch
    ):
        """Gateway torn down with a FULL window parked (staged but
        un-proposed): every parked submit is shed RETRYABLE, nothing
        reaches consensus, and a client retry against a surviving
        gateway applies exactly once."""
        _table_env(monkeypatch, table)
        # a huge window (min pinned too — the adaptive sizing would
        # otherwise shrink it) so parked ops cannot flush on their own
        cluster = await _spin_up(
            gateway_config=GatewayConfig(
                coalesce=True, coalesce_window=30.0,
                coalesce_window_min=30.0,
            ),
        )
        clients = []
        try:
            clients = await _connect_clients(cluster, 4)
            gw = cluster.gateways[0]
            shard = 1
            # fire the submits and give the frames time to land in the
            # window (but not to flush: the window is 30s)
            tasks = [
                asyncio.ensure_future(
                    c.submit(shard, [encode_set_bin(f"tw{i}", f"v{i}")])
                )
                for i, c in enumerate(clients)
            ]
            for _ in range(200):
                await asyncio.sleep(0.01)
                if sum(
                    len(w.entries) for w in gw._coal.values()
                ) >= 2:
                    break
            assert gw._coal, "window never opened"
            parked = sum(len(w.entries) for w in gw._coal.values())
            assert parked >= 2
            # the first arrival may have driven through the sparse gate;
            # only the PARKED ones are the subject here
            parked_keys = {
                (p.client_id, p.seq)
                for w in gw._coal.values()
                for _s, p, _t in w.entries
            }
            # tear the gateway down mid-window: parked ops are shed
            # retryable (and were never proposed). The client library
            # would keep retrying against its (now dead) endpoint, so
            # cancel the in-flight calls rather than riding out their
            # timeouts — the assertion below is about the CLUSTER.
            parked_idx = [
                i for i, c in enumerate(clients)
                if (c.client_id, 1) in parked_keys
            ]
            assert len(parked_idx) >= 2
            await gw.close()
            await asyncio.sleep(0.3)
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            # parked (staged but un-proposed) ops NEVER reached
            # consensus — no replica applied them
            for i in parked_idx:
                for r in range(3):
                    got = cluster.store(r, shard).get(f"tw{i}")
                    assert got.value is None, (
                        f"un-proposed parked op tw{i} applied on {r}"
                    )
            # a fresh retry of a parked op against a surviving gateway
            # applies exactly once
            key = f"tw{parked_idx[0]}"
            retry = RabiaClient([cluster.endpoint(1)], call_timeout=30.0)
            await retry.connect()
            resp = await retry.submit(shard, [encode_set_bin(key, "retry")])
            assert decode_kv_response(resp[0]).ok
            await asyncio.sleep(0.2)
            assert cluster.store(1, shard).get(key).value == "retry"
            await retry.close()
        finally:
            for c in clients:
                await c.close()
            await cluster.stop()


class TestFleetLedgerFailover:
    @pytest.mark.asyncio
    async def test_replay_on_other_fleet_gateway_is_byte_identical(self):
        """The round-16 dedup-replication gate: a coalesced submit's
        completed result replicates to the shard's fleet gateway group,
        so a replay landing on a DIFFERENT fleet gateway (failover,
        re-route) answers byte-identically from the replicated ledger —
        with ZERO store mutation on any replica (the race-free
        double-apply detector) and without waiting out session
        leases."""
        from rabia_tpu.fleet.harness import FleetHarness, FleetSession

        h = FleetHarness(
            n_gateways=2,
            n_shards=SHARDS,
            persistence=False,
            gateway_config=GatewayConfig(
                coalesce=True, coalesce_window=0.01
            ),
        )
        await h.start()
        try:
            shard = 0
            ring = h.gateways[0].ring
            owner, succ = ring.successors(shard, 2)
            owner_i = int(owner.name.removeprefix("gw"))
            succ_i = int(succ.name.removeprefix("gw"))
            # several sessions on the same shard so the upstream
            # coalescing window actually packs multi-client waves
            sessions = [FleetSession(h.ser, h.resolver()) for _ in range(4)]
            results = await asyncio.gather(*(
                s.submit(shard, [encode_set_bin(f"fl{i}", f"v{i}")])
                for i, s in enumerate(sessions)
            ))
            assert all(r.status == ResultStatus.OK for r in results)
            want = [tuple(bytes(p) for p in r.payload) for r in results]
            # wait for every fire-and-forget ledger record to land on
            # the successor
            succ_gw = h.gateways[succ_i]
            for _ in range(200):
                if all(
                    succ_gw.sessions.cached_result(s.client_id, 1)
                    for s in sessions
                ):
                    break
                await asyncio.sleep(0.02)
            await h.cluster.wait_converged()
            vers = [
                [h.cluster.store(r, s).version for s in range(SHARDS)]
                for r in range(3)
            ]
            # re-route every session to the OTHER gateway and replay
            m = succ_gw.member()
            for s in sessions:
                s.resolver.note_moved(shard, (m.host, m.port))
            for i, s in enumerate(sessions):
                replay = await s.submit_seq(
                    1, shard, [encode_set_bin(f"fl{i}", "X")]
                )
                assert replay.status == ResultStatus.CACHED, (
                    f"session {i}: {replay.status}"
                )
                assert tuple(bytes(p) for p in replay.payload) == want[i]
            await asyncio.sleep(0.3)
            assert [
                [h.cluster.store(r, s).version for s in range(SHARDS)]
                for r in range(3)
            ] == vers, "cross-gateway replay mutated state (double apply)"
            assert h.gateways[owner_i].stats.ledger_sent >= 4
            assert succ_gw.stats.ledger_applied >= 4
            for s in sessions:
                await s.close()
        finally:
            await h.stop()


class TestAliasRecovery:
    def test_alias_ledger_records_survive_recovery(self, tmp_path):
        """K_LEDGER lists: a wave staged with several per-client alias
        records recovers the wave's own id into applied_ids and every
        alias into the proposer-local alias_ledger (the coalescing
        lane's crash-recovery dedup — aliases stay OUT of applied_ids
        so the apply-path dedup-skip stays symmetric across replicas)."""
        import numpy as np

        from rabia_tpu.persistence.native_wal import WalPersistence

        wal = WalPersistence(tmp_path / "w", n_shards=SHARDS)
        ops = [encode_set_bin("k", "v"), encode_set_bin("k2", "v2")]
        wal.stage_wave(0, 0, 1, bid=b"\x11" * 16, ops=ops)
        alias_a, alias_b = b"\xaa" * 16, b"\xbb" * 16
        wal.stage_ledger(0, 0, alias_a)
        wal.stage_ledger(0, 0, alias_b)
        wal.close()

        class _Shard:
            def __init__(self):
                self.applied_ids = {}
                self.applied_results = {}
                self.alias_ledger = {}

        class _RT:
            pass

        class _Eng:
            pass

        wal2 = WalPersistence(tmp_path / "w", n_shards=SHARDS)
        ledger = wal2.recovered.ledger
        assert ledger[(0, 0)] == [alias_a, alias_b]
        eng = _Eng()
        eng.n_shards = SHARDS
        rt = _RT()
        rt.applied_upto = np.zeros(SHARDS, np.int64)
        rt.next_slot = np.zeros(SHARDS, np.int64)
        rt.state_version = 0
        rt.v1_applied = np.zeros(SHARDS, np.int64)
        rt.shards = [_Shard() for _ in range(SHARDS)]
        eng.rt = rt

        class _SM:
            def apply_batch(self, batch):
                return [b"" for _ in batch.commands]

        eng.sm = _SM()
        replayed = wal2.replay_waves(eng)
        assert replayed == 1
        ids = {b.value.bytes for b in rt.shards[0].applied_ids}
        assert b"\x11" * 16 in ids, "wave's own id missing from applied_ids"
        aliases = {b.value.bytes for b in rt.shards[0].alias_ledger}
        assert {alias_a, alias_b} <= aliases, (
            "alias ids missing from alias_ledger"
        )
        assert not ({alias_a, alias_b} & ids), (
            "proposer-local aliases leaked into applied_ids — the "
            "apply-path dedup-skip would diverge replica state"
        )
        wal2.close()
