"""Native TCP transport tests: framing, handshake, cluster over real sockets.

Reference parity: tcp.rs:829-891 (create/frame/2-node-connect unit tests)
plus a 3-node consensus run over real localhost TCP (the tcp_networking
example's core assertion).
"""

import asyncio

import pytest

from rabia_tpu.core.config import RabiaConfig, TcpNetworkConfig
from rabia_tpu.core.network import ClusterConfig
from rabia_tpu.core.state_machine import InMemoryStateMachine
from rabia_tpu.core.types import CommandBatch, NodeId
from rabia_tpu.engine import RabiaEngine
from rabia_tpu.net.tcp import TcpNetwork

from netwait import wait_connected, wait_full_mesh


def _cfg(n: int = 1) -> RabiaConfig:
    return RabiaConfig(
        phase_timeout=0.4, heartbeat_interval=0.05, round_interval=0.002
    ).with_kernel(num_shards=n, shard_pad_multiple=max(1, n))


class TestTransportBasics:
    @pytest.mark.asyncio
    async def test_bind_ephemeral_port(self):
        t = TcpNetwork(NodeId.from_int(1), TcpNetworkConfig(bind_port=0))
        try:
            assert t.port > 0
        finally:
            await t.close()

    @pytest.mark.asyncio
    async def test_two_node_handshake_and_send(self):
        a, b = NodeId.from_int(1), NodeId.from_int(2)
        ta = TcpNetwork(a, TcpNetworkConfig(bind_port=0))
        tb = TcpNetwork(b, TcpNetworkConfig(bind_port=0))
        try:
            ta.add_peer(b, "127.0.0.1", tb.port)
            tb.add_peer(a, "127.0.0.1", ta.port)
            await wait_connected((ta, b), (tb, a))
            await ta.send_to(b, b"hello over tcp")
            sender, data = await tb.receive(timeout=5.0)
            assert sender == a
            assert data == b"hello over tcp"
        finally:
            await ta.close()
            await tb.close()

    @pytest.mark.asyncio
    async def test_large_frame_roundtrip(self):
        a, b = NodeId.from_int(1), NodeId.from_int(2)
        ta = TcpNetwork(a, TcpNetworkConfig(bind_port=0))
        tb = TcpNetwork(b, TcpNetworkConfig(bind_port=0))
        try:
            ta.add_peer(b, "127.0.0.1", tb.port)
            await wait_connected((ta, b))
            payload = bytes(range(256)) * 4096  # 1 MiB
            await ta.send_to(b, payload)
            _, data = await tb.receive(timeout=15.0)
            assert data == payload
        finally:
            await ta.close()
            await tb.close()

    @pytest.mark.asyncio
    async def test_broadcast_reaches_all(self):
        ids = [NodeId.from_int(i + 1) for i in range(3)]
        nets = [TcpNetwork(i, TcpNetworkConfig(bind_port=0)) for i in ids]
        try:
            for i, a in enumerate(ids):
                for j, b in enumerate(ids):
                    if i != j:
                        nets[i].add_peer(b, "127.0.0.1", nets[j].port)
            await wait_full_mesh(nets, 2)
            await nets[0].broadcast(b"to everyone")
            for k in (1, 2):
                sender, data = await nets[k].receive(timeout=15.0)
                assert sender == ids[0]
                assert data == b"to everyone"
        finally:
            for n in nets:
                await n.close()


class TestPoolStats:
    @pytest.mark.asyncio
    async def test_out_pool_stats_readable_and_counted(self):
        """The outbound frame arena's hit/miss counters (kept natively in
        transport.cpp since the out-pool landed) must be readable from
        Python: misses on cold sends, hits once recycled frames get
        reused, and the merged pool_stats view stays a superset."""
        a, b = NodeId.from_int(1), NodeId.from_int(2)
        ta = TcpNetwork(a, TcpNetworkConfig(bind_port=0))
        tb = TcpNetwork(b, TcpNetworkConfig(bind_port=0))
        try:
            ta.add_peer(b, "127.0.0.1", tb.port)
            tb.add_peer(a, "127.0.0.1", ta.port)
            await wait_connected((ta, b), (tb, a))
            assert ta.out_pool_stats == (0, 0)  # nothing sent yet
            # sequential send/receive round-trips: each completed write
            # recycles its frame buffer, so later sends HIT the arena
            for i in range(32):
                await ta.send_to(b, b"x" * 64)
                await tb.receive(timeout=10.0)
            hits, misses = ta.out_pool_stats
            # recycled-buffer reuse must actually happen (even send #1
            # can hit: the flushed 16B handshake buffer is recycled into
            # the arena before the first data frame)
            assert hits >= 1
            assert hits + misses == 32
            # the merged view includes the out-pool numbers
            mh, mm = ta.pool_stats
            assert mh >= hits and mm >= misses
            # and the counter block agrees with the dedicated accessor
            ctrs = ta.transport_counters()
            assert ctrs["out_pool_hits"] == hits
            assert ctrs["out_pool_misses"] == misses
        finally:
            await ta.close()
            await tb.close()
        # closed: late scrapes read the state frozen at teardown
        assert ta.out_pool_stats == (hits, misses)
        assert ta.transport_counters()["out_pool_hits"] == hits


class TestSimultaneousDialDrain:
    @pytest.mark.asyncio
    async def test_send_in_dup_race_window_not_lost(self):
        """Both sides dial at once, and the sender fires the moment ITS
        side reports connected — possibly on the duplicate connection
        that the deterministic smaller-id-wins tiebreak is about to
        cull. Pre-round-5 the loser was ::close()d immediately, so a
        frame in flight on it was silently dropped (a rare receive
        timeout under CPU load, a different test each run); the drain
        path (native/transport.cpp Conn::draining) must deliver it.
        Probabilistic pin: each iteration reopens the race window."""
        for i in range(25):
            a = NodeId.from_int(1000 + 2 * i)
            b = NodeId.from_int(1001 + 2 * i)
            ta = TcpNetwork(a, TcpNetworkConfig(bind_port=0))
            tb = TcpNetwork(b, TcpNetworkConfig(bind_port=0))
            try:
                # both add_peer -> both dial -> duplicate resolution
                ta.add_peer(b, "127.0.0.1", tb.port)
                tb.add_peer(a, "127.0.0.1", ta.port)
                await wait_connected((ta, b))  # ONE side only, on purpose
                await ta.send_to(b, b"race window frame")
                sender, data = await tb.receive(timeout=15.0)
                assert sender == a, i
                assert data == b"race window frame", i
            finally:
                await ta.close()
                await tb.close()


class TestConsensusOverTcp:
    @pytest.mark.asyncio
    async def test_three_node_cluster_commits(self):
        """Full consensus over real localhost sockets (BASELINE config #5's
        transport)."""
        ids = [NodeId.from_int(i + 1) for i in range(3)]
        nets = [TcpNetwork(i, TcpNetworkConfig(bind_port=0)) for i in ids]
        for i in range(3):
            for j in range(3):
                if i != j:
                    nets[i].add_peer(ids[j], "127.0.0.1", nets[j].port)
        sms = [InMemoryStateMachine() for _ in ids]
        engines = [
            RabiaEngine(
                ClusterConfig.new(ids[i], ids), sms[i], nets[i], config=_cfg()
            )
            for i in range(3)
        ]
        tasks = [asyncio.ensure_future(e.run()) for e in engines]
        try:
            for _ in range(200):
                await asyncio.sleep(0.01)
                sts = [await e.get_statistics() for e in engines]
                if all(s.has_quorum for s in sts):
                    break
            fut = await engines[0].submit_batch(
                CommandBatch.new(["SET tcp works"])
            )
            responses = await asyncio.wait_for(fut, 15.0)
            assert responses == [b"OK"]

            async def converged():
                while not all(sm.get("tcp") == "works" for sm in sms):
                    await asyncio.sleep(0.02)

            await asyncio.wait_for(converged(), 10.0)
        finally:
            for e in engines:
                await e.shutdown()
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            for n in nets:
                await n.close()


class TestTsanStress:
    def test_transport_under_thread_sanitizer(self, tmp_path):
        """Compile the C++ data plane with -fsanitize=thread and hammer it
        from five threads (send/broadcast/recv/stats/teardown-under-load).
        Any data race fails the run (TSAN_OPTIONS halt_on_error)."""
        import shutil
        import subprocess
        from pathlib import Path

        if shutil.which("g++") is None:
            pytest.skip("no g++")
        # probe TSan VIABILITY, not just compilability: the probe is a
        # race-free-by-construction mutex+condvar program (the exact
        # primitives transport.cpp uses). Some container toolchains
        # (gcc-10 libtsan here) flag it with a false-positive "double
        # lock of a mutex" — in that environment every report from the
        # real stress run is noise, so the gate reports
        # SKIP (environment) with the probe's own output instead of a
        # red gate. A compile failure of OUR sources still FAILS below
        # (a regression must not silently disable the race gate).
        probe_src = tmp_path / "probe.cpp"
        probe_src.write_text(
            "#include <atomic>\n"
            "#include <chrono>\n"
            "#include <condition_variable>\n"
            "#include <cstdio>\n"
            "#include <mutex>\n"
            "#include <thread>\n"
            "#include <vector>\n"
            "int main() {\n"
            "  std::mutex mu;\n"
            "  std::condition_variable cv;\n"
            "  std::atomic<bool> stop{false};\n"
            "  int shared = 0;\n"
            "  std::vector<std::thread> ts;\n"
            "  for (int t = 0; t < 3; t++) {\n"
            "    ts.emplace_back([&] {\n"
            "      for (int i = 0; i < 20000 && !stop.load(); i++) {\n"
            "        std::lock_guard<std::mutex> lk(mu);\n"
            "        shared++;\n"
            "        if ((shared & 1023) == 0) cv.notify_all();\n"
            "      }\n"
            "    });\n"
            "  }\n"
            "  for (int i = 0; i < 50; i++) {\n"
            "    std::unique_lock<std::mutex> lk(mu);\n"
            "    cv.wait_for(lk, std::chrono::milliseconds(2),\n"
            "                [&] { return shared > 50000; });\n"
            "  }\n"
            "  stop.store(true);\n"
            "  for (auto& t : ts) t.join();\n"
            "  std::printf(\"probe ok %d\\n\", shared);\n"
            "  return 0;\n"
            "}\n"
        )
        probe = subprocess.run(
            [
                "g++", "-O1", "-g", "-fsanitize=thread", "-pthread",
                str(probe_src), "-o", str(tmp_path / "probe"),
            ],
            capture_output=True,
            text=True,
            timeout=120,
        )
        if probe.returncode != 0:
            pytest.skip(f"toolchain lacks TSan: {probe.stderr[-200:]}")
        # the false positive is timing-dependent: give it five chances
        # to surface before trusting the stress run's verdict
        for _ in range(5):
            probe_run = subprocess.run(
                [str(tmp_path / "probe")],
                capture_output=True,
                text=True,
                timeout=120,
                env={
                    "TSAN_OPTIONS": "halt_on_error=1",
                    "PATH": "/usr/bin:/bin",
                },
            )
            if (
                probe_run.returncode != 0
                or "probe ok" not in probe_run.stdout
            ):
                pytest.skip(
                    "SKIP (environment): TSan flags a race-free "
                    "mutex/condvar probe — reports in this container are "
                    "toolchain noise, not transport races. Probe output:\n"
                    f"{(probe_run.stdout + probe_run.stderr)[-1500:]}"
                )
        src_dir = Path(__file__).parent.parent / "rabia_tpu" / "native"
        out = tmp_path / "stress"
        build = subprocess.run(
            [
                "g++", "-O1", "-g", "-std=c++17", "-fsanitize=thread",
                "-pthread",
                str(src_dir / "transport.cpp"),
                str(src_dir / "transport_stress.cpp"),
                "-o", str(out),
            ],
            capture_output=True,
            text=True,
            timeout=180,
        )
        assert build.returncode == 0, (
            f"TSan build of transport sources failed:\n{build.stderr[-2000:]}"
        )
        run = subprocess.run(
            [str(out)],
            capture_output=True,
            text=True,
            timeout=120,
            env={"TSAN_OPTIONS": "halt_on_error=1", "PATH": "/usr/bin:/bin"},
        )
        assert run.returncode == 0, (
            f"tsan stress failed rc={run.returncode}\n"
            f"stdout: {run.stdout[-500:]}\nstderr: {run.stderr[-2000:]}"
        )
        assert "stress ok" in run.stdout
