"""Native TCP transport tests: framing, handshake, cluster over real sockets.

Reference parity: tcp.rs:829-891 (create/frame/2-node-connect unit tests)
plus a 3-node consensus run over real localhost TCP (the tcp_networking
example's core assertion).
"""

import asyncio
import os

import pytest

from rabia_tpu.core.config import RabiaConfig, TcpNetworkConfig
from rabia_tpu.core.network import ClusterConfig
from rabia_tpu.core.state_machine import InMemoryStateMachine
from rabia_tpu.core.types import CommandBatch, NodeId
from rabia_tpu.engine import RabiaEngine
from rabia_tpu.net.tcp import TcpNetwork

from netwait import wait_connected, wait_full_mesh


def _cfg(n: int = 1) -> RabiaConfig:
    return RabiaConfig(
        phase_timeout=0.4, heartbeat_interval=0.05, round_interval=0.002
    ).with_kernel(num_shards=n, shard_pad_multiple=max(1, n))


class TestTransportBasics:
    @pytest.mark.asyncio
    async def test_bind_ephemeral_port(self):
        t = TcpNetwork(NodeId.from_int(1), TcpNetworkConfig(bind_port=0))
        try:
            assert t.port > 0
        finally:
            await t.close()

    @pytest.mark.asyncio
    async def test_two_node_handshake_and_send(self):
        a, b = NodeId.from_int(1), NodeId.from_int(2)
        ta = TcpNetwork(a, TcpNetworkConfig(bind_port=0))
        tb = TcpNetwork(b, TcpNetworkConfig(bind_port=0))
        try:
            ta.add_peer(b, "127.0.0.1", tb.port)
            tb.add_peer(a, "127.0.0.1", ta.port)
            await wait_connected((ta, b), (tb, a))
            await ta.send_to(b, b"hello over tcp")
            sender, data = await tb.receive(timeout=5.0)
            assert sender == a
            assert data == b"hello over tcp"
        finally:
            await ta.close()
            await tb.close()

    @pytest.mark.asyncio
    async def test_large_frame_roundtrip(self):
        a, b = NodeId.from_int(1), NodeId.from_int(2)
        ta = TcpNetwork(a, TcpNetworkConfig(bind_port=0))
        tb = TcpNetwork(b, TcpNetworkConfig(bind_port=0))
        try:
            ta.add_peer(b, "127.0.0.1", tb.port)
            await wait_connected((ta, b))
            payload = bytes(range(256)) * 4096  # 1 MiB
            await ta.send_to(b, payload)
            _, data = await tb.receive(timeout=15.0)
            assert data == payload
        finally:
            await ta.close()
            await tb.close()

    @pytest.mark.asyncio
    async def test_broadcast_reaches_all(self):
        ids = [NodeId.from_int(i + 1) for i in range(3)]
        nets = [TcpNetwork(i, TcpNetworkConfig(bind_port=0)) for i in ids]
        try:
            for i, a in enumerate(ids):
                for j, b in enumerate(ids):
                    if i != j:
                        nets[i].add_peer(b, "127.0.0.1", nets[j].port)
            await wait_full_mesh(nets, 2)
            await nets[0].broadcast(b"to everyone")
            for k in (1, 2):
                sender, data = await nets[k].receive(timeout=15.0)
                assert sender == ids[0]
                assert data == b"to everyone"
        finally:
            for n in nets:
                await n.close()


class TestPoolStats:
    @pytest.mark.asyncio
    async def test_out_pool_stats_readable_and_counted(self):
        """The outbound frame arena's hit/miss counters (kept natively in
        transport.cpp since the out-pool landed) must be readable from
        Python: misses on cold sends, hits once recycled frames get
        reused, and the merged pool_stats view stays a superset."""
        a, b = NodeId.from_int(1), NodeId.from_int(2)
        ta = TcpNetwork(a, TcpNetworkConfig(bind_port=0))
        tb = TcpNetwork(b, TcpNetworkConfig(bind_port=0))
        try:
            ta.add_peer(b, "127.0.0.1", tb.port)
            tb.add_peer(a, "127.0.0.1", ta.port)
            await wait_connected((ta, b), (tb, a))
            assert ta.out_pool_stats == (0, 0)  # nothing sent yet
            # sequential send/receive round-trips: each completed write
            # recycles its frame buffer, so later sends HIT the arena
            for i in range(32):
                await ta.send_to(b, b"x" * 64)
                await tb.receive(timeout=10.0)
            hits, misses = ta.out_pool_stats
            # recycled-buffer reuse must actually happen (even send #1
            # can hit: the flushed 16B handshake buffer is recycled into
            # the arena before the first data frame)
            assert hits >= 1
            assert hits + misses == 32
            # the merged view includes the out-pool numbers
            mh, mm = ta.pool_stats
            assert mh >= hits and mm >= misses
            # and the counter block agrees with the dedicated accessor
            ctrs = ta.transport_counters()
            assert ctrs["out_pool_hits"] == hits
            assert ctrs["out_pool_misses"] == misses
        finally:
            await ta.close()
            await tb.close()
        # closed: late scrapes read the state frozen at teardown
        assert ta.out_pool_stats == (hits, misses)
        assert ta.transport_counters()["out_pool_hits"] == hits


class TestSimultaneousDialDrain:
    @pytest.mark.asyncio
    async def test_send_in_dup_race_window_not_lost(self):
        """Both sides dial at once, and the sender fires the moment ITS
        side reports connected — possibly on the duplicate connection
        that the deterministic smaller-id-wins tiebreak is about to
        cull. Pre-round-5 the loser was ::close()d immediately, so a
        frame in flight on it was silently dropped (a rare receive
        timeout under CPU load, a different test each run); the drain
        path (native/transport.cpp Conn::draining) must deliver it.
        Probabilistic pin: each iteration reopens the race window."""
        # load-aware receive budget: the 15s default is generous on an
        # idle host but this file shares CI boxes with the chaos/fleet
        # suites; when the 1-minute load average exceeds the core count
        # scale the budget up (capped at 2x) instead of flaking
        load = os.getloadavg()[0] / max(1, os.cpu_count() or 1)
        budget = 15.0 * max(1.0, min(2.0, load))
        for i in range(25):
            a = NodeId.from_int(1000 + 2 * i)
            b = NodeId.from_int(1001 + 2 * i)
            ta = TcpNetwork(a, TcpNetworkConfig(bind_port=0))
            tb = TcpNetwork(b, TcpNetworkConfig(bind_port=0))
            try:
                # both add_peer -> both dial -> duplicate resolution
                ta.add_peer(b, "127.0.0.1", tb.port)
                tb.add_peer(a, "127.0.0.1", ta.port)
                await wait_connected((ta, b))  # ONE side only, on purpose
                await ta.send_to(b, b"race window frame")
                sender, data = await tb.receive(timeout=budget)
                assert sender == a, i
                assert data == b"race window frame", i
            finally:
                await ta.close()
                await tb.close()


class TestConsensusOverTcp:
    @pytest.mark.asyncio
    async def test_three_node_cluster_commits(self):
        """Full consensus over real localhost sockets (BASELINE config #5's
        transport)."""
        ids = [NodeId.from_int(i + 1) for i in range(3)]
        nets = [TcpNetwork(i, TcpNetworkConfig(bind_port=0)) for i in ids]
        for i in range(3):
            for j in range(3):
                if i != j:
                    nets[i].add_peer(ids[j], "127.0.0.1", nets[j].port)
        sms = [InMemoryStateMachine() for _ in ids]
        engines = [
            RabiaEngine(
                ClusterConfig.new(ids[i], ids), sms[i], nets[i], config=_cfg()
            )
            for i in range(3)
        ]
        tasks = [asyncio.ensure_future(e.run()) for e in engines]
        try:
            for _ in range(200):
                await asyncio.sleep(0.01)
                sts = [await e.get_statistics() for e in engines]
                if all(s.has_quorum for s in sts):
                    break
            fut = await engines[0].submit_batch(
                CommandBatch.new(["SET tcp works"])
            )
            responses = await asyncio.wait_for(fut, 15.0)
            assert responses == [b"OK"]

            async def converged():
                while not all(sm.get("tcp") == "works" for sm in sms):
                    await asyncio.sleep(0.02)

            await asyncio.wait_for(converged(), 10.0)
        finally:
            for e in engines:
                await e.shutdown()
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            for n in nets:
                await n.close()


# The transport race gate lives in tests/test_static_analysis.py::
# TestSanitizerMatrix::test_tsan_transport (the round-13 sanitizer matrix;
# scripts/sanitize_gate.py is the standalone driver). The TestTsanStress
# class that lived here — and its gcc-10 environmental probe-SKIP — is
# retired: the matrix runs ENFORCED, with the toolchain proven per-machine
# (clean timed-condvar probe + planted-race detection, clockwait shim on
# gcc). See docs/STATIC_ANALYSIS.md.
