"""Core types, messages, serialization, validation, batching unit tests.

Mirrors the reference's co-located unit tier (SURVEY.md §4.1):
rabia-core/src/lib.rs:112-194 (types/messages), serialization.rs:211-320,
batching.rs:328-454, validation.rs:228-257.
"""

import time
import uuid

import pytest

from rabia_tpu.core.batching import CommandBatcher, ShardedBatcher
from rabia_tpu.core.config import BatchConfig, RabiaConfig, SerializationConfig
from rabia_tpu.core.errors import (
    NetworkError,
    QuorumNotAvailableError,
    SerializationError,
    StateMachineError,
    TimeoutError_,
    ValidationError,
)
from rabia_tpu.core.messages import (
    Decision,
    DecisionEntry,
    HeartBeat,
    PhaseData,
    ProtocolMessage,
    Propose,
    QuorumNotification,
    SyncRequest,
    SyncResponse,
    VoteEntry,
    VoteRound1,
    VoteRound2,
)
from rabia_tpu.core.serialization import (
    BinarySerializer,
    JsonSerializer,
    Serializer,
    estimate_serialized_size,
)
from rabia_tpu.core.state_machine import InMemoryStateMachine, Snapshot
from rabia_tpu.core.types import (
    BatchId,
    Command,
    CommandBatch,
    NodeId,
    PhaseId,
    ShardId,
    StateValue,
    f_plus_1,
    node_index_map,
    quorum_size,
)
from rabia_tpu.core.validation import MessageValidator


class TestIds:
    def test_node_id_deterministic_from_int(self):
        assert NodeId.from_int(7) == NodeId.from_int(7)
        assert NodeId.from_int(7) != NodeId.from_int(8)

    def test_node_id_ordering_stable(self):
        ids = [NodeId.from_int(i) for i in (3, 1, 2)]
        assert sorted(ids) == [NodeId.from_int(i) for i in (1, 2, 3)]

    def test_node_id_random_unique(self):
        assert NodeId.new() != NodeId.new()

    def test_replica_index_map(self):
        nodes = [NodeId.from_int(i) for i in (5, 1, 9)]
        m = node_index_map(nodes)
        assert m[NodeId.from_int(1)] == 0
        assert m[NodeId.from_int(9)] == 2

    def test_phase_id_monotonic(self):
        p = PhaseId(0)
        assert p.is_initial()
        assert p.next().value == 1
        assert PhaseId(3) > PhaseId(2)

    def test_quorum_sizes(self):
        assert quorum_size(3) == 2
        assert quorum_size(5) == 3
        assert quorum_size(7) == 4
        assert quorum_size(4) == 3
        assert f_plus_1(3) == 2
        assert f_plus_1(5) == 3
        assert f_plus_1(7) == 4

    def test_quorum_fp1_intersection(self):
        # any majority and any f+1 set must intersect (weak_mvc.ivy:24-31)
        for n in range(1, 12):
            assert quorum_size(n) + f_plus_1(n) > n


class TestStateValue:
    def test_codes_stable(self):
        assert int(StateValue.V0) == 0
        assert int(StateValue.V1) == 1
        assert int(StateValue.VQuestion) == 2
        assert int(StateValue.Absent) == 3

    def test_is_decided_value(self):
        assert StateValue.V1.is_decided_value()
        assert StateValue.V0.is_decided_value()
        assert not StateValue.VQuestion.is_decided_value()


class TestBatches:
    def test_batch_checksum_roundtrip(self):
        b = CommandBatch.new(["SET a 1", "SET b 2"])
        assert b.verify(b.checksum())
        assert not b.verify(b.checksum() ^ 1)

    def test_batch_basics(self):
        b = CommandBatch.new([b"x"], shard=ShardId(3))
        assert len(b) == 1
        assert not b.is_empty()
        assert int(b.shard) == 3
        assert b.total_size() == 1


class TestPhaseData:
    def test_majority_tally(self):
        pd = PhaseData(phase=PhaseId(1))
        nodes = [NodeId.from_int(i) for i in range(5)]
        for n in nodes[:3]:
            pd.add_round1_vote(n, StateValue.V1)
        pd.add_round1_vote(nodes[3], StateValue.V0)
        assert pd.round1_majority(5) == StateValue.V1
        assert pd.has_round1_quorum(5)
        v0, v1, vq = PhaseData.count_votes(pd.round1_votes)
        assert (v0, v1, vq) == (1, 3, 0)

    def test_duplicate_votes_ignored(self):
        pd = PhaseData(phase=PhaseId(1))
        n = NodeId.from_int(1)
        pd.add_round1_vote(n, StateValue.V1)
        pd.add_round1_vote(n, StateValue.V0)  # second vote ignored
        assert pd.round1_votes[n] == StateValue.V1

    def test_decision_rejects_question(self):
        pd = PhaseData(phase=PhaseId(1))
        pd.set_decision(StateValue.VQuestion)
        assert not pd.is_decided()
        pd.set_decision(StateValue.V1)
        assert pd.decision == StateValue.V1
        pd.set_decision(StateValue.V0)  # first decision wins
        assert pd.decision == StateValue.V1


def _all_payloads():
    batch = CommandBatch.new(["SET k v", "GET k"])
    nodes = tuple(NodeId.from_int(i) for i in range(3))
    votes = (
        VoteEntry(0, 5, StateValue.V1),
        VoteEntry(1, 5, StateValue.VQuestion),
    )
    return [
        Propose(shard=0, phase=5, batch_id=batch.id, value=StateValue.V1, batch=batch),
        Propose(shard=1, phase=6, batch_id=BatchId.new(), value=StateValue.V0, batch=None),
        VoteRound1(votes=votes),
        VoteRound2(votes=votes),
        Decision(
            decisions=(
                DecisionEntry(0, 5, StateValue.V1, batch.id),
                DecisionEntry(1, 5, StateValue.V0, None),
            )
        ),
        SyncRequest(current_phase=9, state_version=4),
        SyncResponse(responder_phase=12, state_version=7, snapshot=b"\x01\x02", per_shard_phase=(1, 2, 3)),
        SyncResponse(responder_phase=1, state_version=0, snapshot=None),
        HeartBeat(current_phase=3, committed_phase=2),
        QuorumNotification(has_quorum=True, active_nodes=nodes),
    ]


class TestSerialization:
    @pytest.mark.parametrize("payload", _all_payloads(), ids=lambda p: type(p).__name__)
    def test_binary_roundtrip(self, payload):
        ser = BinarySerializer()
        msg = ProtocolMessage.new(NodeId.from_int(1), payload, NodeId.from_int(2))
        out = ser.deserialize(ser.serialize(msg))
        assert out == msg

    @pytest.mark.parametrize("payload", _all_payloads(), ids=lambda p: type(p).__name__)
    def test_json_roundtrip(self, payload):
        ser = JsonSerializer()
        msg = ProtocolMessage.new(NodeId.from_int(1), payload)
        out = ser.deserialize(ser.serialize(msg))
        assert out == msg

    def test_broadcast_flag(self):
        ser = BinarySerializer()
        msg = ProtocolMessage.new(NodeId.from_int(1), HeartBeat(1, 0))
        assert msg.is_broadcast()
        assert ser.deserialize(ser.serialize(msg)).recipient is None

    def test_binary_smaller_than_json(self):
        # binary strictly smaller (serialization.rs:259-276 asserts this)
        batch = CommandBatch.new([f"SET key{i} value{i}" for i in range(50)])
        msg = ProtocolMessage.new(
            NodeId.from_int(1),
            Propose(0, 1, batch.id, StateValue.V1, batch),
        )
        b = BinarySerializer().serialize(msg)
        j = JsonSerializer().serialize(msg)
        assert len(b) < len(j)

    def test_compression_kicks_in(self):
        cfg = SerializationConfig(compression_threshold=128)
        batch = CommandBatch.new(["SET k " + "a" * 4096])
        msg = ProtocolMessage.new(
            NodeId.from_int(1), Propose(0, 1, batch.id, StateValue.V1, batch)
        )
        small = BinarySerializer(cfg).serialize(msg)
        big = BinarySerializer(SerializationConfig(compression_threshold=0)).serialize(msg)
        assert len(small) < len(big)
        assert BinarySerializer(cfg).deserialize(small) == msg

    def test_corrupt_payload_rejected(self):
        ser = BinarySerializer()
        batch = CommandBatch.new(["SET a b"])
        msg = ProtocolMessage.new(
            NodeId.from_int(1), Propose(0, 1, batch.id, StateValue.V1, batch)
        )
        raw = bytearray(ser.serialize(msg))
        raw[-3] ^= 0xFF  # flip a byte inside the batch payload
        with pytest.raises(SerializationError):
            ser.deserialize(bytes(raw))

    def test_truncated_rejected(self):
        ser = BinarySerializer()
        msg = ProtocolMessage.new(NodeId.from_int(1), HeartBeat(1, 0))
        raw = ser.serialize(msg)
        with pytest.raises(SerializationError):
            ser.deserialize(raw[: len(raw) // 2])

    def test_dispatcher_autodetect(self):
        s = Serializer()
        msg = ProtocolMessage.new(NodeId.from_int(1), HeartBeat(2, 1))
        assert s.deserialize(BinarySerializer().serialize(msg)) == msg
        assert s.deserialize(JsonSerializer().serialize(msg)) == msg

    def test_size_estimate_order_of_magnitude(self):
        msg = ProtocolMessage.new(
            NodeId.from_int(1), VoteRound1(votes=tuple(VoteEntry(i, 1, StateValue.V1) for i in range(100)))
        )
        actual = len(BinarySerializer().serialize(msg))
        est = estimate_serialized_size(msg)
        assert 0.5 * actual <= est <= 2 * actual


class TestValidation:
    def test_future_message_rejected(self):
        v = MessageValidator()
        msg = ProtocolMessage.new(NodeId.from_int(1), HeartBeat(1, 0))
        msg = ProtocolMessage(
            id=msg.id,
            sender=msg.sender,
            recipient=None,
            timestamp=time.time() + 120,
            payload=msg.payload,
        )
        with pytest.raises(ValidationError):
            v.validate_message(msg)

    def test_stale_message_rejected(self):
        v = MessageValidator()
        msg = ProtocolMessage(
            id=ProtocolMessage.new(NodeId.from_int(1), HeartBeat(1, 0)).id,
            sender=NodeId.from_int(1),
            recipient=None,
            timestamp=time.time() - 700,
            payload=HeartBeat(1, 0),
        )
        with pytest.raises(ValidationError):
            v.validate_message(msg)

    def test_oversized_batch_rejected(self):
        v = MessageValidator()
        batch = CommandBatch.new([f"c{i}" for i in range(1001)])
        with pytest.raises(ValidationError):
            v.validate_batch(batch)

    def test_empty_batch_rejected(self):
        v = MessageValidator()
        with pytest.raises(ValidationError):
            v.validate_batch(CommandBatch.new([]))

    def test_vq_decision_rejected(self):
        v = MessageValidator()
        msg = ProtocolMessage.new(
            NodeId.from_int(1),
            Decision(decisions=(DecisionEntry(0, 1, StateValue.VQuestion),)),
        )
        with pytest.raises(ValidationError):
            v.validate_message(msg)

    def test_phase_progression(self):
        v = MessageValidator()
        assert v.check_phase_progression("n1", 5)
        assert v.check_phase_progression("n1", 6)
        assert not v.check_phase_progression("n1", 6 + 1001)


class TestErrors:
    def test_retryable_taxonomy(self):
        # Network | Timeout | QuorumNotAvailable are retryable (error.rs:249-255)
        assert NetworkError("x").is_retryable()
        assert TimeoutError_("x").is_retryable()
        assert QuorumNotAvailableError("x").is_retryable()
        assert not StateMachineError("x").is_retryable()


class TestBatcher:
    def test_size_flush(self):
        b = CommandBatcher(BatchConfig(max_batch_size=3, adaptive=False))
        assert b.add(Command.new("a")) is None
        assert b.add(Command.new("b")) is None
        batch = b.add(Command.new("c"))
        assert batch is not None and len(batch) == 3
        assert b.pending_count() == 0

    def test_timeout_flush(self):
        b = CommandBatcher(BatchConfig(max_batch_size=100, max_batch_delay=0.01, adaptive=False))
        b.add(Command.new("a"), now=0.0)
        assert b.poll(now=0.005) is None
        batch = b.poll(now=0.02)
        assert batch is not None and len(batch) == 1

    def test_adaptive_grows_under_load(self):
        cfg = BatchConfig(max_batch_size=10, adaptive=True)
        b = CommandBatcher(cfg)
        for _ in range(10):  # 10 size-triggered flushes
            for i in range(10):
                b.add(Command.new(f"c{i}"), now=0.0)
        assert b.target_size > 10

    def test_adaptive_shrinks_when_idle(self):
        cfg = BatchConfig(max_batch_size=100, max_batch_delay=0.01, adaptive=True)
        b = CommandBatcher(cfg)
        for k in range(10):  # 10 timeout-triggered flushes
            b.add(Command.new("x"), now=float(k))
            assert b.poll(now=float(k) + 0.5) is not None
        assert b.target_size < 100

    def test_sharded_batcher_routes(self):
        sb = ShardedBatcher(4, BatchConfig(max_batch_size=1, adaptive=False))
        batch = sb.add(2, Command.new("x"))
        assert batch is not None and int(batch.shard) == 2

    def test_stats(self):
        b = CommandBatcher(BatchConfig(max_batch_size=2, adaptive=False))
        b.add(Command.new("a"))
        b.add(Command.new("b"))
        assert b.stats.batches_created == 1
        assert b.stats.commands_batched == 2
        assert b.stats.avg_batch_size == 2.0


class TestStateMachine:
    def test_set_get_del(self):
        sm = InMemoryStateMachine()
        assert sm.apply_command(Command.new("SET k hello")) == b"OK"
        assert sm.apply_command(Command.new("GET k")) == b"hello"
        assert sm.apply_command(Command.new("DEL k")) == b"DELETED"
        assert sm.apply_command(Command.new("GET k")) == b"NOT_FOUND"

    def test_unknown_command_deterministic_error(self):
        sm = InMemoryStateMachine()
        r1 = sm.apply_command(Command(id=NodeId.from_int(1).value, data=b"BLORP"))
        sm2 = InMemoryStateMachine()
        r2 = sm2.apply_command(Command(id=NodeId.from_int(1).value, data=b"BLORP"))
        assert r1 == r2 and r1.startswith(b"ERROR")

    def test_snapshot_roundtrip(self):
        sm = InMemoryStateMachine()
        sm.apply_command(Command.new("SET a 1"))
        sm.apply_command(Command.new("SET b 2"))
        snap = sm.create_snapshot()
        snap.verify()
        sm2 = InMemoryStateMachine()
        sm2.restore_snapshot(snap)
        assert sm2.get("a") == "1" and sm2.get("b") == "2"
        assert sm2.version == sm.version

    def test_snapshot_corruption_detected(self):
        sm = InMemoryStateMachine()
        sm.apply_command(Command.new("SET a 1"))
        snap = sm.create_snapshot()
        bad = Snapshot(version=snap.version, data=snap.data + b"x", checksum=snap.checksum)
        with pytest.raises(Exception):
            bad.verify()

    def test_snapshot_bytes_roundtrip(self):
        sm = InMemoryStateMachine()
        sm.apply_command(Command.new("SET a 1"))
        snap = sm.create_snapshot()
        assert Snapshot.from_bytes(snap.to_bytes()) == snap


class TestConfig:
    def test_builders(self):
        cfg = RabiaConfig().with_seed(42).with_shards(64)
        assert cfg.randomization_seed == 42
        assert cfg.kernel.num_shards == 64

    def test_padded_shards(self):
        cfg = RabiaConfig().with_shards(65)
        assert cfg.kernel.padded_shards == 72
        assert RabiaConfig().with_shards(64).kernel.padded_shards == 64


class TestFastIds:
    """Random ids come from a process-local PRNG (os.urandom once, not per
    id); they must stay uuid4-shaped, unique, and fork-safe."""

    def test_uuid4_shape_and_uniqueness(self):
        ids = {BatchId.new().value for _ in range(5000)}
        ids |= {NodeId.new().value for _ in range(5000)}
        assert len(ids) == 10000
        sample = next(iter(ids))
        assert sample.version == 4
        assert sample.variant == uuid.RFC_4122

    @staticmethod
    def _first_draw(extra: str = "") -> str:
        """First id drawn by a FRESH interpreter (stream position 1 —
        comparing equal positions catches deterministic seeding, which a
        positional offset would mask)."""
        import pathlib
        import subprocess
        import sys as _sys

        out = subprocess.run(
            [_sys.executable, "-c",
             "from rabia_tpu.core.types import BatchId\n" + extra
             + "print(BatchId.new())"],
            capture_output=True, text=True, timeout=60,
            cwd=str(pathlib.Path(__file__).parent.parent),
        )
        assert out.returncode == 0, out.stderr
        return out.stdout.strip().splitlines()[-1]

    def test_processes_draw_distinct_streams(self):
        assert self._first_draw() != self._first_draw()

    def test_fork_reseeds_child_stream(self):
        # the register_at_fork reseed, exercised in a JAX-free child
        # interpreter (forking the JAX-laden pytest process risks
        # deadlock): parent and forked child at the SAME stream position
        # must draw different ids
        import pathlib
        import subprocess
        import sys as _sys

        script = (
            "import os, sys\n"
            "from rabia_tpu.core.types import BatchId\n"
            "if not hasattr(os, 'fork'):\n"
            "    print('SKIP'); sys.exit(0)\n"
            "r, w = os.pipe()\n"
            "pid = os.fork()\n"
            "if pid == 0:\n"
            "    os.close(r); os.write(w, str(BatchId.new()).encode())\n"
            "    os._exit(0)\n"
            "os.close(w)\n"
            "child = os.read(r, 64).decode(); os.close(r)\n"
            "os.waitpid(pid, 0)\n"
            "print('DIFFER' if str(BatchId.new()) != child else 'SAME')\n"
        )
        out = subprocess.run(
            [_sys.executable, "-c", script],
            capture_output=True, text=True, timeout=60,
            cwd=str(pathlib.Path(__file__).parent.parent),
        )
        assert out.returncode == 0, out.stderr
        verdict = out.stdout.strip().splitlines()[-1]
        if verdict == "SKIP":
            pytest.skip("no fork on this platform")
        assert verdict == "DIFFER"


def test_main_module_environment_report(capsys):
    # `python -m rabia_tpu` doctor: the report path runs on any backend
    # and exits 0 with the version + native-component lines present
    from rabia_tpu.__main__ import main

    assert main([]) == 0
    out = capsys.readouterr().out
    assert "rabia-tpu" in out
    assert "native codec" in out
    assert "native TCP transport" in out
