"""Client gateway subsystem tests: exactly-once sessions, linearizable
read-index reads, admission control, reconnect replay, and a chaos run
with a replica restart — all over real TCP sockets via the native
transport (acceptance gate of the gateway subsystem)."""

from __future__ import annotations

import asyncio
import uuid

import pytest

from rabia_tpu.apps.kvstore import (
    KVResultKind,
    decode_kv_response,
    encode_set_bin,
    shard_for_key,
)
from rabia_tpu.core.messages import (
    ClientHello,
    ReadIndex,
    ReadIndexMode,
    Result,
    ResultStatus,
    Submit,
)
from rabia_tpu.gateway import (
    BackpressureError,
    GatewayConfig,
    RabiaClient,
    SessionTable,
)
from rabia_tpu.gateway.session import CachedResult
from rabia_tpu.testing.gateway_cluster import GatewayCluster

SHARDS = 4


def _shard(key: str) -> int:
    return shard_for_key(key, SHARDS)


def _decided_total(cluster) -> int:
    return sum(
        e.rt.decided_v0 + e.rt.decided_v1 for e in cluster.engines
    )


def _decided_v1_total(cluster) -> int:
    """Committed (V1) slots only — the signal for "did anything new get
    proposed and applied": background forwarding-timeout noise can open
    null (V0) slots at any time, which carry no writes."""
    return sum(e.rt.decided_v1 for e in cluster.engines)


async def _spin_up(**kw) -> GatewayCluster:
    cluster = GatewayCluster(n_replicas=3, n_shards=SHARDS, **kw)
    await cluster.start()
    return cluster


class TestSessionTable:
    def test_gc_requires_ack_and_frontier_advance(self):
        t = SessionTable(session_ttl=1e9)
        cid = uuid.uuid4()
        sess = t.ensure(cid)
        sess.complete(1, CachedResult(0, (b"r",), frontier_mark=10))
        sess.complete(2, CachedResult(0, (b"r2",), frontier_mark=11))
        # unacked: nothing evicts however far the frontier moves
        assert t.gc(state_version=100) == 0
        sess.ack_upto = 1
        # acked but frontier NOT past the mark: stays
        assert t.gc(state_version=10) == 0
        # acked AND frontier advanced: evicted
        assert t.gc(state_version=11) == 1
        assert 1 not in sess.results and 2 in sess.results

    def test_window_grant_capped_by_gateway(self):
        t = SessionTable(default_window=8)
        assert t.ensure(uuid.uuid4(), 0).window == 8
        assert t.ensure(uuid.uuid4(), 4).window == 4
        assert t.ensure(uuid.uuid4(), 99).window == 8
        # renegotiable on resume (downward only)
        cid = uuid.uuid4()
        assert t.ensure(cid, 0).window == 8
        assert t.ensure(cid, 2).window == 2
        assert t.ensure(cid, 99).window == 8

    def test_deterministic_batch_ids(self):
        """A replayed Submit yields a byte-identical batch with the SAME
        id — the engine's dedup ledger then blocks double-applies even
        when the gateway's session state was lost."""
        from rabia_tpu.gateway.server import GatewayServer

        cid = uuid.uuid4()
        mk = lambda seq: Submit(  # noqa: E731
            client_id=cid, seq=seq, shard=1, commands=(b"a", b"bb")
        )
        b1 = GatewayServer._deterministic_batch(mk(3))
        b2 = GatewayServer._deterministic_batch(mk(3))
        b3 = GatewayServer._deterministic_batch(mk(4))
        assert b1.id == b2.id
        assert b1.checksum() == b2.checksum()  # command ids match too
        assert b3.id != b1.id

    def test_idle_session_expiry_spares_inflight_until_lease(self):
        t = SessionTable(session_ttl=10.0, lease_ttl=100.0)
        busy = t.ensure(uuid.uuid4())
        busy.inflight[1] = object()
        idle = t.ensure(uuid.uuid4())
        idle.last_active = busy.last_active = 0.0
        # past the idle ttl but inside the lease: inflight spares it
        t.gc(state_version=0, now=50.0)
        assert busy.client_id in t.sessions
        assert idle.client_id not in t.sessions
        # past the HARD lease: dropped even with inflight seqs — a
        # stalled frontier / wedged engine cannot pin dead sessions
        t.gc(state_version=0, now=200.0)
        assert busy.client_id not in t.sessions
        assert t.stats.leases_expired == 1
        assert t.stats.sessions_expired == 2


class TestGatewayEndToEnd:
    @pytest.mark.asyncio
    async def test_concurrent_clients_exactly_once_and_linearizable(self):
        """The acceptance run: 8 concurrent clients over real TCP against
        a 3-replica cluster — every write exactly-once in the applied
        state machines, reads linearizable against the host-store oracle,
        and the read phase consuming zero consensus slots."""
        cluster = await _spin_up()
        clients = []
        try:
            clients = [
                RabiaClient(
                    [cluster.endpoint(i % 3)], call_timeout=30.0
                )
                for i in range(8)
            ]
            for c in clients:
                await c.connect()

            async def writer(ci: int, c: RabiaClient):
                for k in range(6):
                    key = f"c{ci}-k{k}"
                    resp = await c.submit(
                        _shard(key), [encode_set_bin(key, f"v{ci}.{k}")]
                    )
                    r = decode_kv_response(resp[0])
                    assert r.ok, r

            await asyncio.gather(
                *(writer(i, c) for i, c in enumerate(clients))
            )

            # exactly-once: every key present exactly as written, on
            # every replica, and replicas converge
            await cluster.wait_converged()
            for ci in range(8):
                for k in range(6):
                    key = f"c{ci}-k{k}"
                    for r in range(3):
                        res = cluster.store(r, _shard(key)).get(key)
                        assert res.value == f"v{ci}.{k}"

            # read phase: linearizable reads, zero consensus slots (let
            # the write phase's in-flight slots fully settle first)
            await asyncio.sleep(0.3)
            decided_before = _decided_total(cluster)
            for ci, c in enumerate(clients):
                key = f"c{ci}-k0"
                raw = await c.get(_shard(key), key)
                r = decode_kv_response(raw)
                assert r.ok and r.value == f"v{ci}.0"
            # oracle: the read value matches the host store directly
            assert _decided_total(cluster) == decided_before, (
                "reads consumed consensus slots"
            )
        finally:
            for c in clients:
                await c.close()
            await cluster.stop()

    @pytest.mark.asyncio
    async def test_linearizable_reads_see_acked_writes(self):
        """A reader on gateway B must observe every write a writer on
        gateway A has already been acked for (quorum-probed read index)."""
        cluster = await _spin_up()
        writer = reader = None
        try:
            writer = RabiaClient([cluster.endpoint(0)], call_timeout=30.0)
            reader = RabiaClient([cluster.endpoint(1)], call_timeout=30.0)
            await writer.connect()
            await reader.connect()
            key = "lin-key"
            shard = _shard(key)
            acked = 0
            for v in range(1, 16):
                await writer.submit(
                    shard, [encode_set_bin(key, str(v))]
                )
                acked = v  # write v is acked BEFORE the read below issues
                floor = acked
                raw = await reader.get(shard, key)
                r = decode_kv_response(raw)
                assert r.ok
                assert int(r.value) >= floor, (
                    f"read saw {r.value}, but {floor} was already acked"
                )
        finally:
            for c in (writer, reader):
                if c is not None:
                    await c.close()
            await cluster.stop()


class TestGatewayFailurePaths:
    @pytest.mark.asyncio
    async def test_duplicate_submit_returns_cached_no_second_proposal(self):
        cluster = await _spin_up()
        cli = None
        try:
            cli = RabiaClient([cluster.endpoint(0)], call_timeout=30.0)
            await cli.connect()
            key = "dup-key"
            shard = _shard(key)
            resp = await cli.submit(shard, [encode_set_bin(key, "once")])
            assert decode_kv_response(resp[0]).ok
            store = cluster.store(0, shard)
            version_after_first = store.version
            gw = cluster.gateways[0]
            decided_before = _decided_v1_total(cluster)

            # replay the SAME (client_id, seq) — a client retry after a
            # lost Result
            dup = Submit(
                client_id=cli.client_id,
                seq=cli._seq,
                shard=shard,
                commands=(encode_set_bin(key, "once"),),
                ack_upto=0,
            )
            res = await cli._call(cli._seq, dup)
            assert res.status == ResultStatus.CACHED
            assert res.payload == tuple(resp)
            assert gw.stats.submits_deduped == 1
            # no second apply, no new committed slot for the dup
            assert store.version == version_after_first
            await asyncio.sleep(0.2)
            assert _decided_v1_total(cluster) == decided_before
        finally:
            if cli is not None:
                await cli.close()
            await cluster.stop()

    @pytest.mark.asyncio
    async def test_reconnect_mid_command_replays_seq_without_double_apply(
        self,
    ):
        """Drop the first Result on the floor (lost on the wire) and kill
        the client's link: the client reconnects, replays the seq, and
        the session cache answers — one apply, one version bump."""
        cluster = await _spin_up()
        cli = None
        try:
            cli = RabiaClient(
                [cluster.endpoint(0)],
                call_timeout=30.0,
                retry_interval=0.3,
            )
            await cli.connect()
            gw = cluster.gateways[0]
            key = "replay-key"
            shard = _shard(key)

            # swallow the FIRST result the gateway sends for seq 1, and
            # sever the client's link at the same moment
            orig = gw._send_result
            dropped = []

            def dropping(recipient, client_id, seq, status, payload):
                if seq == 1 and not dropped:
                    dropped.append(seq)
                    return  # lost on the wire
                orig(recipient, client_id, seq, status, payload)

            gw._send_result = dropping
            submit_task = asyncio.ensure_future(
                cli.submit(shard, [encode_set_bin(key, "exactly-once")])
            )
            # wait until the command actually committed gateway-side
            sess = None
            for _ in range(400):
                await asyncio.sleep(0.01)
                sess = gw.sessions.get(cli.client_id)
                if sess is not None and 1 in sess.results:
                    break
            assert sess is not None and 1 in sess.results
            store = cluster.store(0, shard)
            version_after_commit = store.version

            # sever the link mid-command (the Result was "lost"): the
            # client's retry cycle reconnects and replays seq 1
            await cli._net.close()
            resp = await asyncio.wait_for(submit_task, 30.0)
            assert decode_kv_response(resp[0]).ok
            assert cli.reconnects >= 1
            assert cli.cached_replies >= 1  # answered from session cache
            assert gw.stats.submits_deduped >= 1
            assert store.version == version_after_commit  # single apply
        finally:
            if cli is not None:
                await cli.close()
            await cluster.stop()

    @pytest.mark.asyncio
    async def test_replay_after_session_loss_does_not_double_apply(self):
        """Even when the gateway's session state is wiped (restart /
        cache eviction), a replayed (client_id, seq) re-proposes under
        the SAME deterministic batch id and the ENGINE's dedup ledger
        blocks the second apply."""
        cluster = await _spin_up()
        cli = None
        try:
            cli = RabiaClient([cluster.endpoint(0)], call_timeout=30.0)
            await cli.connect()
            key = "wipe-key"
            shard = _shard(key)
            resp = await cli.submit(shard, [encode_set_bin(key, "once")])
            assert decode_kv_response(resp[0]).ok
            store = cluster.store(0, shard)
            version_after_first = store.version

            # simulate total session-state loss at the gateway
            cluster.gateways[0].sessions.sessions.clear()
            dup = Submit(
                client_id=cli.client_id,
                seq=cli._seq,
                shard=shard,
                commands=(encode_set_bin(key, "once"),),
            )
            res = await cli._call(cli._seq, dup)
            # the replay re-proposes (no cache) but the engine dedups the
            # apply and answers from its response cache
            assert res.status in (ResultStatus.OK, ResultStatus.CACHED)
            assert store.version == version_after_first  # single apply
        finally:
            if cli is not None:
                await cli.close()
            await cluster.stop()

    @pytest.mark.asyncio
    async def test_backpressure_rejection_is_retryable(self):
        cluster = await _spin_up(
            gateway_config=GatewayConfig(max_queue_depth=0)
        )
        cli = None
        try:
            cli = RabiaClient(
                [cluster.endpoint(0)],
                call_timeout=10.0,
                retry_backpressure=False,
            )
            await cli.connect()
            with pytest.raises(BackpressureError) as ei:
                await cli.submit(0, [encode_set_bin("k", "v")])
            # the contract: a retryable StoreError, shed BEFORE consensus
            assert ei.value.is_retryable()
            assert ei.value.kind.recoverable
            assert cluster.gateways[0].stats.submits_shed >= 1
            # nothing was proposed
            assert cluster.store(0, 0).version == 0
        finally:
            if cli is not None:
                await cli.close()
            await cluster.stop()

    @pytest.mark.asyncio
    async def test_session_window_sheds_excess_inflight(self):
        cluster = await _spin_up(
            gateway_config=GatewayConfig(max_inflight_per_session=1)
        )
        cli = None
        try:
            cli = RabiaClient(
                [cluster.endpoint(0)],
                call_timeout=10.0,
                retry_backpressure=True,
            )
            await cli.connect()
            assert cli.server_window == 1
            # a burst over the window: all eventually commit via client
            # backoff, and at least one got shed on the way
            keys = [f"w{i}" for i in range(6)]
            await asyncio.gather(
                *(
                    cli.submit(_shard(k), [encode_set_bin(k, "x")])
                    for k in keys
                )
            )
            assert cluster.gateways[0].stats.submits_shed >= 1
            for k in keys:
                assert cluster.store(0, _shard(k)).get(k).value == "x"
        finally:
            if cli is not None:
                await cli.close()
            await cluster.stop()


class TestGatewayChaos:
    @pytest.mark.asyncio
    async def test_replica_restart_with_live_clients(self):
        """One replica restarts (recovering from its persistence layer)
        while clients stay connected to the other two gateways and keep
        writing; every write lands exactly once and the restarted replica
        converges back to full agreement."""
        cluster = await _spin_up()
        clients = []
        try:
            clients = [
                RabiaClient([cluster.endpoint(1 + (i % 2))],
                            call_timeout=45.0)
                for i in range(4)
            ]
            for c in clients:
                await c.connect()
            written: list[str] = []
            stop = asyncio.Event()

            async def writer(ci: int, c: RabiaClient):
                k = 0
                while not stop.is_set():
                    key = f"chaos-c{ci}-{k}"
                    resp = await c.submit(
                        _shard(key), [encode_set_bin(key, f"v{k}")]
                    )
                    r = decode_kv_response(resp[0])
                    assert r.ok, r
                    written.append((key, r.version))
                    k += 1
                    await asyncio.sleep(0.01)

            writers = [
                asyncio.ensure_future(writer(i, c))
                for i, c in enumerate(clients)
            ]
            await asyncio.sleep(0.5)
            await cluster.restart_replica(0)
            await asyncio.sleep(1.0)
            stop.set()
            await asyncio.gather(*writers)
            assert len(written) > 0
            # the restarted replica syncs back to full agreement...
            await cluster.wait_converged(timeout=60.0)
            # ...and every acked write is present (on every replica, by
            # convergence — spot-check a survivor)
            for key, ver in written:
                res = cluster.store(1, _shard(key)).get(key)
                assert res.kind == KVResultKind.Success, (
                    key,
                    ver,
                    [
                        (r, cluster.store(r, _shard(key)).get(key))
                        for r in range(3)
                    ],
                    [
                        (g.stats.results_repaired, g.stats.submits_deduped)
                        for g in cluster.gateways
                    ],
                )
        finally:
            for c in clients:
                await c.close()
            await cluster.stop()


class TestGatewayObservability:
    @pytest.mark.asyncio
    async def test_metrics_and_healthz_from_live_tcp_cluster(self):
        """Acceptance gate of the observability plane: a 3-replica TCP
        cluster serves Prometheus-text /metrics (with nonzero native-tick
        counters when the native path is live) and /healthz reflecting
        decided/applied frontiers — over BOTH surfaces: framed admin
        requests on the gateway's native transport and the stdlib HTTP
        shim."""
        import json
        import urllib.request

        from rabia_tpu.core.messages import AdminKind
        from rabia_tpu.gateway import admin_fetch

        cluster = await _spin_up(
            gateway_config=GatewayConfig(http_port=0)
        )
        try:
            client = RabiaClient(cluster.endpoints())
            await client.connect()
            writes = 6
            for i in range(writes):
                key = f"obs{i}"
                await client.submit(
                    _shard(key), [encode_set_bin(key, f"v{i}")]
                )
            # read once so the read-index counters move too
            await client.get(_shard("obs0"), "obs0")
            await client.close()

            # -- framed admin surface (native transport) ----------------
            ep = cluster.endpoint(0)
            text = (
                await admin_fetch(ep.host, ep.port, int(AdminKind.METRICS))
            ).decode()
            assert text.endswith("\n")
            lines = [
                ln for ln in text.splitlines()
                if ln and not ln.startswith("#")
            ]
            # well-formed exposition: every sample line is "name value"
            for ln in lines:
                name, _, value = ln.rpartition(" ")
                assert name and float(value) is not None, ln
            sample = {
                ln.rpartition(" ")[0]: float(ln.rpartition(" ")[2])
                for ln in lines
            }
            assert sample['rabia_engine_decided_total{value="v1"}'] >= writes
            assert sample["rabia_gateway_submits_total"] >= writes
            assert sample["rabia_gateway_reads_total"] >= 1
            assert sample["rabia_engine_has_quorum"] == 1
            if cluster.engines[0]._rk is not None:
                # native tick live: the rk counter block must be nonzero
                # through the shared tick metric names
                frames = sum(
                    sample[f'rabia_tick_frames_total{{kind="{k}"}}']
                    for k in ("vote1", "vote2", "decision")
                )
                assert frames > 0
                assert sample["rabia_tick_native_out_frames_total"] > 0
            health = json.loads(
                await admin_fetch(ep.host, ep.port, int(AdminKind.HEALTH))
            )
            assert health["status"] == "ok" and health["has_quorum"]
            assert sum(health["applied_frontier"]) >= writes
            assert (
                sum(health["decided_frontier"])
                >= sum(health["applied_frontier"])
            )
            journal = json.loads(
                await admin_fetch(ep.host, ep.port, int(AdminKind.JOURNAL))
            )
            assert isinstance(journal["anomalies"], list)

            # -- HTTP shim ----------------------------------------------
            port = cluster.gateways[0].http_port
            assert port > 0
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5
            ) as r:
                http_text = r.read().decode()
            assert r.headers["Content-Type"].startswith("text/plain")
            assert 'rabia_engine_decided_total{value="v1"}' in http_text
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=5
            ) as r:
                assert json.loads(r.read())["status"] == "ok"
        finally:
            await cluster.stop()


class TestGatewayProtocolFrames:
    def test_frame_roundtrips(self):
        """Envelope round-trip of all four client frame kinds through the
        default serializer (native codec when available, Python always)."""
        from rabia_tpu.core.serialization import BinarySerializer
        from rabia_tpu.core.messages import ProtocolMessage
        from rabia_tpu.core.types import NodeId

        from rabia_tpu.core.messages import AdminRequest, AdminResponse

        cid = uuid.uuid4()
        frames = [
            ClientHello(client_id=cid, ack=True, last_seq=7,
                        max_inflight=32),
            Submit(client_id=cid, seq=9, shard=2,
                   commands=(b"\x01\x01\x00kv", b""), ack_upto=4),
            Result(client_id=cid, seq=9, status=int(ResultStatus.CACHED),
                   payload=(b"resp",)),
            ReadIndex(mode=int(ReadIndexMode.REPLY), client_id=cid,
                      seq=3, frontier=(5, 0, 12)),
            AdminRequest(kind=1, nonce=42),
            AdminRequest(kind=3, nonce=43,
                         query=b'{"client": "00ff", "seq": 2}'),
            AdminResponse(nonce=42, status=0, body=b"# TYPE x counter\n"),
        ]
        s = BinarySerializer()
        for p in frames:
            msg = ProtocolMessage.new(NodeId.from_int(1), p)
            wire = s.serialize(msg)
            # python and native agree byte-for-byte and on decode
            assert wire == s._serialize_py(msg)
            assert s._deserialize_py(wire).payload == p
            assert s.deserialize(wire).payload == p

        # pre-trace AdminRequest bodies (no trailing query blob) still
        # decode — the query field is a wire-compatible append
        import struct

        from rabia_tpu.core.messages import MessageType
        from rabia_tpu.core.serialization import _decode_payload, _Reader

        legacy_body = bytes([1]) + struct.pack("<Q", 42)
        decoded = _decode_payload(MessageType.AdminRequest, _Reader(legacy_body))
        assert decoded == AdminRequest(kind=1, nonce=42, query=b"")


# ---------------------------------------------------------------------------
# native gateway session plane (sessionkernel.cpp)
# ---------------------------------------------------------------------------


def _session_tables():
    """Both tables under test, same knobs; [] when the kernel is
    unavailable (the native id then simply doesn't parametrize)."""
    from rabia_tpu.gateway.native_session import NativeSessionTable
    from rabia_tpu.gateway.session import SessionTable
    from rabia_tpu.native.build import load_sessionkernel

    kw = dict(
        default_window=4, session_ttl=10.0, lease_ttl=60.0,
        result_cache_cap=3,
    )
    out = [("python", lambda: SessionTable(**kw))]
    lib = load_sessionkernel()
    if lib is not None:
        out.append(("native", lambda: NativeSessionTable(lib, **kw)))
    return out


class TestNativeSessionPlane:
    def test_kernel_available(self):
        """The container bakes a toolchain: a silent sessionkernel build
        failure must fail HERE, not demote every gateway to Python."""
        import os

        from rabia_tpu.native.build import load_sessionkernel

        if os.environ.get("RABIA_PY_GATEWAY") == "1":
            pytest.skip("RABIA_PY_GATEWAY=1 forces the Python table")
        lib = load_sessionkernel()
        assert lib is not None
        assert lib.gws_counters_version() == 1
        from rabia_tpu.gateway.native_session import GWC_COUNTER_NAMES

        assert lib.gws_counters_count() == len(GWC_COUNTER_NAMES)

    @pytest.mark.parametrize(
        "name,mk", _session_tables(), ids=[n for n, _ in _session_tables()]
    )
    def test_gc_under_frontier_stall(self, name, mk):
        """Regression (the lease satellite): a STALLED frontier — no
        quorum, state_version pinned — must not pin dead sessions. The
        idle ttl reaps sessions without inflight; the hard lease reaps
        even sessions wedged with inflight seqs; the cached results go
        with them."""
        t = mk()
        wedged, idle = uuid.uuid4(), uuid.uuid4()
        assert t.submit_check(wedged, 1, 0, now=0.0)[0] == 0
        t.complete_op(wedged, 1, 0, (b"r1",), 5, now=0.0)
        assert t.submit_check(wedged, 2, 0, now=0.0)[0] == 0  # stays wedged
        t.hello(idle, 0, now=0.0)
        # frontier NEVER advances (state_version 0 throughout)
        assert t.gc(0, now=1.0) == 0
        assert len(t) == 2
        # past the idle ttl: the inflight-free session goes, the wedged
        # one survives (its seq may still complete)
        t.gc(0, now=11.0)
        assert t.get(idle) is None and t.get(wedged) is not None
        # past the hard lease: the wedged session goes too, cached
        # results and all — nothing is pinned by the stalled frontier
        evicted = t.gc(0, now=61.1)
        assert evicted >= 1
        assert len(t) == 0
        assert t.stats.leases_expired == 1
        assert t.stats.sessions_expired == 2

    @pytest.mark.parametrize(
        "name,mk", _session_tables(), ids=[n for n, _ in _session_tables()]
    )
    def test_cache_cap_evicts_lowest_seqs(self, name, mk):
        t = mk()
        cid = uuid.uuid4()
        for seq in range(1, 6):
            assert t.submit_check(cid, seq, 0, now=0.0)[0] == 0
            t.complete_op(cid, seq, 0, (b"p%d" % seq,), 1, now=0.0)
        assert t.gc(0, now=0.1) == 2  # cap 3: seqs 1-2 evicted
        assert t.cached_result(cid, 1) is None
        assert t.cached_result(cid, 3).payload == (b"p3",)
        assert t.cached_result(cid, 5).payload == (b"p5",)

    def test_fixed_conformance_schedule(self):
        """Deterministic branch-cover schedule through the shared gate
        (same code path as fuzz --gateway, so the checks cannot
        drift)."""
        from rabia_tpu.testing.conformance import (
            run_gateway_ops_on_both_tables,
        )

        cid1, cid2 = uuid.UUID(int=1), uuid.UUID(int=2)
        ops = [
            {"op": "hello", "t": 0.0, "cid": cid1, "window": 99},
            {"op": "hello", "t": 0.0, "cid": cid2, "window": 2},
            {"op": "submit", "t": 0.1, "cid": cid1, "seq": 1},
            {"op": "submit", "t": 0.1, "cid": cid1, "seq": 1},  # inflight dup
            {"op": "complete", "t": 0.2, "cid": cid1, "seq": 1,
             "status": 0, "payload": (b"ok", b""), "frontier": 1},
            {"op": "submit", "t": 0.3, "cid": cid1, "seq": 1},  # cached dup
            {"op": "submit", "t": 0.3, "cid": cid2, "seq": 1},
            {"op": "submit", "t": 0.3, "cid": cid2, "seq": 2},
            {"op": "submit", "t": 0.3, "cid": cid2, "seq": 3},  # window shed
            {"op": "abort", "t": 0.4, "cid": cid2, "seq": 2},
            {"op": "complete", "t": 0.5, "cid": cid2, "seq": 9,
             "status": 2, "payload": (), "frontier": 2},  # error, empty
            {"op": "submit", "t": 0.6, "cid": cid1, "seq": 2, "ack": 1},
            # fleet ledger records (reserve+complete in one step): a
            # fresh landing, a landing onto the existing reservation
            # (cid1 seq 2 is inflight), and a no-op onto a cached seq
            {"op": "ledger", "t": 0.65, "cid": cid2, "seq": 4,
             "status": 0, "payload": (b"led",), "frontier": 3},
            {"op": "ledger", "t": 0.65, "cid": cid1, "seq": 2,
             "status": 0, "payload": (b"r2",), "frontier": 3},
            {"op": "ledger", "t": 0.66, "cid": cid1, "seq": 2,
             "status": 1, "payload": (b"loser",), "frontier": 4},
            {"op": "submit", "t": 0.67, "cid": cid1, "seq": 2},  # cached
            {"op": "gc", "t": 0.7, "sv": 5},   # evicts cid1 seq 1
            {"op": "gc", "t": 20.0, "sv": 5},  # idle expiry (ttl 30 no)
            {"op": "gc", "t": 200.0, "sv": 5},  # lease: everything goes
        ]
        run_gateway_ops_on_both_tables(ops, tag="fixed-schedule")

    def test_random_conformance_schedules(self):
        from rabia_tpu.testing.conformance import (
            random_gateway_ops,
            run_gateway_ops_on_both_tables,
        )

        for seed in range(6):
            run_gateway_ops_on_both_tables(
                random_gateway_ops(seed), tag=f"seed={seed}"
            )

    def test_payload_blob_roundtrip(self):
        from rabia_tpu.gateway.native_session import (
            pack_payload,
            unpack_payload,
        )

        for payload in ((), (b"",), (b"a", b"", b"\x00" * 300), (b"x",) * 9):
            assert unpack_payload(pack_payload(payload)) == payload
        # memoryviews pack like bytes (the apply plane's lazy views)
        assert unpack_payload(
            pack_payload((memoryview(b"abc"), b"d"))
        ) == (b"abc", b"d")


class TestGatewayMux:
    @pytest.mark.asyncio
    async def test_sessions_multiplexed_over_one_connection(self):
        """The C transport's session-mux lane end-to-end: several
        protocol-faithful sessions over ONE socket against a live
        gateway — submits commit, replies demultiplex to the right
        session, and the dedup cache answers a replay with CACHED."""
        import importlib
        import sys
        from pathlib import Path

        sys.path.insert(
            0, str(Path(__file__).resolve().parent.parent / "benchmarks")
        )
        lg = importlib.import_module("loadgen")
        from rabia_tpu.core.serialization import Serializer

        cluster = await _spin_up()
        conn = None
        try:
            ser = Serializer()
            conn = await lg.MuxConn(ser).connect(
                "127.0.0.1", cluster.gateways[0].port
            )
            sessions = [
                await lg.LoadSession(ser).connect_mux(conn)
                for _ in range(5)
            ]
            assert len(conn.sessions) == 5
            for i, s in enumerate(sessions):
                key = f"mux-{i}"
                res = await s.submit(
                    _shard(key), [encode_set_bin(key, f"v{i}")], 10.0
                )
                assert res.status == ResultStatus.OK
                assert res.client_id == s.client_id
            # replay the last seq on session 0: answered from the
            # session cache, routed back over the same muxed socket
            s0 = sessions[0]
            s0._seq -= 1
            res = await s0.submit(
                _shard("mux-0"), [encode_set_bin("mux-0", "v0")], 10.0
            )
            assert res.status == ResultStatus.CACHED
            for i in range(5):
                assert (
                    cluster.store(0, _shard(f"mux-{i}")).get(f"mux-{i}").value
                    == f"v{i}"
                )
            for s in sessions:
                await s.close()
        finally:
            if conn is not None:
                await conn.close()
            await cluster.stop()

    @pytest.mark.asyncio
    async def test_rabia_client_mux_lane_with_redial_rebinding(self):
        """RabiaClient's opt-in mux lane (``mux=True``): the full client
        library — exactly-once seqs, retry machinery, reconnect replay —
        over one multiplexed socket instead of a private native
        transport. A killed connection must redial transparently, the
        session REBINDS to the new socket (transport latest-binding-wins)
        and a replayed seq answers from the dedup cache without a second
        apply."""
        cluster = await _spin_up()
        cli = None
        try:
            cli = RabiaClient(
                [cluster.endpoint(0)], mux=True, call_timeout=20.0
            )
            await cli.connect()
            assert isinstance(cli._net.writer, asyncio.StreamWriter)
            for k in range(6):
                key = f"cmux-{k}"
                resp = await cli.submit(
                    _shard(key), [encode_set_bin(key, f"v{k}")]
                )
                assert decode_kv_response(resp[0]).ok
            v1_before = _decided_v1_total(cluster)
            # kill the muxed socket under the client: the next call must
            # redial, rebind the session, and still be exactly-once
            cli._net.writer.close()
            resp = await cli.submit(
                _shard("cmux-re"), [encode_set_bin("cmux-re", "after")]
            )
            assert decode_kv_response(resp[0]).ok
            assert cli.reconnects >= 1
            # duplicate of an already-committed seq: served CACHED over
            # the REBOUND connection, no new proposal
            await asyncio.sleep(0.2)
            v1_mid = _decided_v1_total(cluster)
            seq_replay = cli._seq
            fut = asyncio.get_event_loop().create_future()
            frame = Submit(
                client_id=cli.client_id,
                seq=seq_replay,
                shard=_shard("cmux-re"),
                commands=(encode_set_bin("cmux-re", "after"),),
                ack_upto=0,
            )
            cli._pending[seq_replay] = (fut, frame)
            cli._send_pending(seq_replay)
            res = await asyncio.wait_for(fut, 10.0)
            cli._pending.pop(seq_replay, None)
            assert res.status == ResultStatus.CACHED
            await asyncio.sleep(0.2)
            assert _decided_v1_total(cluster) == v1_mid, (
                "replayed seq over the rebound mux connection proposed "
                "a second time"
            )
            assert v1_mid >= v1_before
            assert (
                cluster.store(0, _shard("cmux-re")).get("cmux-re").value
                == "after"
            )
        finally:
            if cli is not None:
                await cli.close()
            await cluster.stop()


class TestRuntimeGatewayPlane:
    @pytest.mark.asyncio
    async def test_gil_handoffs_flat_across_submit_result(self):
        """Acceptance: on the native runtime + native gateway plane, a
        client submit -> committed result round trip leaves the
        runtime's gil_handoffs counter FLAT while waves_native grows —
        the commit path never re-enters Python, and the gateway's
        session bookkeeping rides the C table."""
        import os
        import sys

        sys.path.insert(0, os.path.dirname(__file__))
        from rabia_tpu.native.build import load_runtime, load_sessionkernel

        if load_runtime() is None:
            pytest.skip("native runtime library unavailable")
        if load_sessionkernel() is None:
            pytest.skip("native sessionkernel library unavailable")
        from test_runtime import _mk_cluster, _teardown  # noqa: E402

        from rabia_tpu.gateway.server import GatewayServer

        ids, nets, engines, machines, tasks = await _mk_cluster(2, 3)
        gw = None
        cli = None
        try:
            e0 = engines[0]
            if e0._rtm is None:
                pytest.skip("native runtime did not engage")
            gw = GatewayServer(e0, config=GatewayConfig())
            await gw.start()
            assert gw.sessions.is_native
            assert gw.health()["planes"]["gateway"] == "native"
            cli = RabiaClient([gw.endpoint], call_timeout=30.0)
            await cli.connect()
            # settle, then bracket ONE submit->result round trip
            await asyncio.sleep(0.3)
            before = e0._rtm.counters_dict()
            resp = await cli.submit(0, [encode_set_bin("gilk", "v")])
            assert decode_kv_response(resp[0]).ok
            after = e0._rtm.counters_dict()
            assert after["waves_native"] > before["waves_native"]
            assert after["gil_handoffs"] == before["gil_handoffs"], (
                "submit->result round trip required a GIL handoff: "
                f"{before} -> {after}"
            )
        finally:
            if cli is not None:
                await cli.close()
            if gw is not None:
                await gw.close()
            await _teardown(engines, tasks, nets)
