"""Partitioned consensus groups (rabia_tpu.fleet.groups): GroupMap
determinism and bounded-movement rebalance, group-routed Submits across
real OS-process groups, the group-locality fence (admission shed +
coalesce assertion), replay-after-reroute exactly-once, and the
groups=2 vs groups=1 conformance leg.

The invariants under test are docs/FLEET.md's group-map section:
routing is a pure function of the versioned GroupMap doc (every router
computes the same bootstrap map), a ``move_range`` moves ONLY the
shards in the moved range, nothing ever crosses a group boundary (a
coalesced PayloadBlock spanning groups is an assertion failure, an
out-of-range Submit a retryable shed), and partitioning the shard
space changes WHERE an op commits but never WHAT any client observes.
"""

from __future__ import annotations

import asyncio
import uuid

import pytest

from rabia_tpu.apps.kvstore import (
    KVOperation,
    decode_result_bin,
    encode_op_bin,
    encode_set_bin,
)
from rabia_tpu.core.messages import AdminKind, ResultStatus
from rabia_tpu.core.serialization import Serializer
from rabia_tpu.fleet.groups import (
    GroupMap,
    GroupProcHarness,
    GroupRouter,
    GroupedFleetHarness,
    moved_group_shards,
)
from rabia_tpu.gateway.client import admin_fetch
from rabia_tpu.obs.registry import parse_prometheus_text
from rabia_tpu.testing.loadsession import LoadSession

N_SHARDS = 4


# ---------------------------------------------------------------------------
# GroupMap: determinism, bounded movement, doc roundtrip
# ---------------------------------------------------------------------------


class TestGroupMap:
    def test_initial_partition_is_deterministic(self):
        """Every router must compute the SAME bootstrap map from
        (n_shards, n_groups) alone — no coordination round."""
        a = GroupMap.initial(8, 2)
        b = GroupMap.initial(8, 2)
        assert a == b
        assert a.to_doc() == b.to_doc()
        assert a.ranges() == [(0, 4, 0), (4, 8, 1)]
        # remainder spreads over the LOW groups, still contiguous
        assert GroupMap.initial(7, 2).ranges() == [(0, 4, 0), (4, 7, 1)]
        assert GroupMap.initial(5, 3).ranges() == [
            (0, 2, 0), (2, 4, 1), (4, 5, 2),
        ]
        gm = GroupMap.initial(8, 3)
        for s in range(8):
            lo, hi = {0: (0, 3), 1: (3, 6), 2: (6, 8)}[gm.group_of(s)]
            assert lo <= s < hi

    def test_initial_bounds(self):
        with pytest.raises(ValueError):
            GroupMap.initial(4, 0)
        with pytest.raises(ValueError):
            GroupMap.initial(4, 5)

    def test_move_range_bounded_movement(self):
        """move_range(lo, hi, g) must move EXACTLY the shards in
        [lo, hi) — the contiguous-range twin of the hash ring's
        bounded-movement guarantee."""
        gm = GroupMap.initial(8, 2)
        old = gm.copy()
        gm.move_range(4, 6, 0)
        assert moved_group_shards(old, gm) == {4: 0, 5: 0}
        assert gm.version == old.version + 1
        # canonical merge: the widened owner reads as ONE range
        assert gm.ranges() == [(0, 6, 0), (6, 8, 1)]
        # moving back restores the partition (and keeps bumping)
        gm.move_range(4, 6, 1)
        assert gm.ranges() == old.ranges()
        assert gm.version == old.version + 2

    def test_doc_roundtrip_and_validation(self):
        gm = GroupMap.initial(8, 3)
        gm.move_range(2, 4, 2)
        rt = GroupMap.from_doc(gm.to_doc())
        assert rt == gm and rt.version == gm.version
        # gap / overlap / short cover all rejected
        with pytest.raises(ValueError):
            GroupMap(4, [(0, 2, 0), (3, 4, 1)])
        with pytest.raises(ValueError):
            GroupMap(4, [(0, 3, 0), (2, 4, 1)])
        with pytest.raises(ValueError):
            GroupMap(4, [(0, 3, 0)])


class TestGroupRouter:
    def test_routing_spread_and_failover_order(self):
        gm = GroupMap.initial(4, 2)
        router = GroupRouter(gm, {
            0: [("h", 1), ("h", 2)],
            1: [("h", 3)],
        })
        # within a group: the deterministic shard % len spread
        assert router.upstream_for(0) == ("h", 1)
        assert router.upstream_for(1) == ("h", 2)
        assert router.upstream_for(2) == ("h", 3)
        assert router.candidates(1) == [("h", 2), ("h", 1)]
        with pytest.raises(ValueError):
            GroupRouter(gm, {0: [("h", 1)]})  # group 1 unaddressable

    def test_adopt_is_version_gated(self):
        gm = GroupMap.initial(4, 2)
        router = GroupRouter(gm.copy(), {
            0: [("h", 1)], 1: [("h", 2)],
        })
        newer = gm.copy()
        newer.move_range(1, 2, 1)
        stale = gm.copy()  # version 0, same as installed
        assert router.adopt(stale) is False
        assert router.adopt(newer) is True
        assert router.group_of(1) == 1
        # a replayed older push can never roll routing back
        assert router.adopt(stale) is False
        assert router.group_of(1) == 1


# ---------------------------------------------------------------------------
# Group-locality fence: admission shed + the coalesce assertion
# ---------------------------------------------------------------------------


class TestGroupFence:
    @pytest.mark.asyncio
    async def test_out_of_range_submit_sheds_retryable(self):
        """A grouped replica gateway sheds Submits outside its owned
        ranges as RETRY (reason ``group_range``) — retryable because a
        mid-rebalance stale router can land one in-flight submit here —
        and serves in-range traffic normally."""
        from rabia_tpu.gateway import GatewayConfig
        from rabia_tpu.testing.gateway_cluster import GatewayCluster

        cluster = GatewayCluster(
            n_replicas=3,
            n_shards=N_SHARDS,
            gateway_config=GatewayConfig(
                group_id=0, group_shards=((0, 2),)
            ),
        )
        await cluster.start()
        ser = Serializer()
        s = LoadSession(ser)
        try:
            g0 = cluster.gateways[0]
            await s.connect("127.0.0.1", g0.port)
            ok = await s.submit(
                1, [encode_set_bin("in-range", "v")], 10.0
            )
            assert ok.status == ResultStatus.OK
            shed = await s.submit(
                3, [encode_set_bin("out-of-range", "v")], 10.0
            )
            assert shed.status == ResultStatus.RETRY
            assert g0.shed_reasons["group_range"] >= 1
            # the fence runs at admission: the fenced shard never even
            # opened a coalesce window on this gateway
            assert 3 not in g0._coal
        finally:
            await s.close()
            await cluster.stop()

    @pytest.mark.asyncio
    async def test_coalesce_flush_asserts_group_locality(self):
        """A coalesced PayloadBlock must never span groups: windows key
        per shard (structural — one window, one shard, one group) and
        the flush path ASSERTS the flushed shard is group-owned, so a
        routing bug surfaces as a crash, not silent cross-group bytes."""
        from rabia_tpu.gateway import GatewayConfig
        from rabia_tpu.testing.gateway_cluster import GatewayCluster

        cluster = GatewayCluster(
            n_replicas=3,
            n_shards=N_SHARDS,
            gateway_config=GatewayConfig(
                group_id=0, group_shards=((0, 2),), coalesce=True
            ),
        )
        await cluster.start()
        try:
            from rabia_tpu.gateway.server import _CoalesceWindow

            g0 = cluster.gateways[0]
            # an owned shard flushes fine (vacuously, no window open)
            g0._coal_flush(1)
            # inject a window for an UNOWNED shard: the flush must trip
            # the group-locality assertion instead of packing it
            g0._coal.setdefault(3, _CoalesceWindow())
            with pytest.raises(AssertionError, match="outside group"):
                g0._coal_flush(3)
            g0._coal.pop(3, None)
        finally:
            await cluster.stop()


# ---------------------------------------------------------------------------
# Process groups end to end
# ---------------------------------------------------------------------------


def _cid(i: int) -> uuid.UUID:
    return uuid.UUID(int=0xC0FFEE00 + i)


class TestProcessGroups:
    @pytest.mark.slow
    @pytest.mark.asyncio
    async def test_group_routed_submit_e2e_two_process_groups(self):
        """2 groups x 3 durable replica processes: Submits routed by the
        GroupRouter land OK on every shard, the wrong group's gateway
        fences them retryable, and a replayed (client_id, seq) answers
        byte-identical from the proposing gateway (session dedup) and
        never consumes a slot at ANY replica of the owning group."""
        gm = GroupMap.initial(N_SHARDS, 2)
        harness = GroupProcHarness(gm, n_replicas=3)
        ser = Serializer()
        loop = asyncio.get_event_loop()
        try:
            await loop.run_in_executor(None, harness.start)
            router = harness.router()
            acked: dict[int, tuple] = {}
            for shard in range(N_SHARDS):
                s = LoadSession(ser, client_id=_cid(shard))
                try:
                    await s.connect(*router.upstream_for(shard))
                    res = await s.submit(
                        shard,
                        [encode_set_bin(f"e2e-{shard}", "v")],
                        15.0,
                    )
                    assert res.status == ResultStatus.OK, (shard, res)
                    acked[shard] = (
                        s._seq, tuple(bytes(p) for p in res.payload)
                    )
                finally:
                    await s.close()

            # cross-group isolation: group 1's replicas fence shard 0
            s = LoadSession(ser)
            try:
                wrong = harness.upstream_addrs()[1][0]
                await s.connect(*wrong)
                res = await s.submit(
                    0, [encode_set_bin("cross", "v")], 15.0
                )
                assert res.status == ResultStatus.RETRY
            finally:
                await s.close()

            # replay on the SAME gateway over a FRESH connection: the
            # session table keys by client_id, so the dedup answers
            # CACHED byte-identical without re-driving the engine
            for shard in (0, 3):
                seq, want = acked[shard]
                g = gm.group_of(shard)
                same = harness.upstream_addrs()[g][shard % 3]
                s = LoadSession(ser, client_id=_cid(shard))
                try:
                    await s.connect(*same)
                    res = await s.submit_seq(
                        seq, shard,
                        [encode_set_bin(f"e2e-{shard}", "v")],
                        15.0,
                    )
                    assert res.status in (
                        ResultStatus.OK, ResultStatus.CACHED
                    )
                    assert tuple(bytes(p) for p in res.payload) == want
                finally:
                    await s.close()

            # replay at a DIFFERENT replica of the owning group: the
            # engine-ledger dedup must either answer byte-identical or
            # return the HONEST responses-unavailable terminal (native
            # block-lane entries record dedup ids on every replica but
            # responses only at the proposer) — and must NEVER consume
            # a new consensus slot (the double-apply gate below)
            async def applied(g: int) -> list[int]:
                out = []
                for port in harness.harnesses[g].gw_ports:
                    body = await admin_fetch(
                        "127.0.0.1", port,
                        kind=int(AdminKind.METRICS), timeout=10.0,
                    )
                    m = parse_prometheus_text(body.decode())
                    out.append(
                        int(m.get("rabia_engine_applied_slots_total", 0))
                    )
                return out

            await asyncio.sleep(0.5)  # let in-flight applies settle
            for shard in (0, 3):
                seq, want = acked[shard]
                g = gm.group_of(shard)
                other = harness.upstream_addrs()[g][(shard + 1) % 3]
                before = await applied(g)
                s = LoadSession(ser, client_id=_cid(shard))
                try:
                    await s.connect(*other)
                    res = await s.submit_seq(
                        seq, shard,
                        [encode_set_bin(f"e2e-{shard}", "v")],
                        15.0,
                    )
                    got = tuple(bytes(p) for p in res.payload)
                    if res.status in (
                        ResultStatus.OK, ResultStatus.CACHED
                    ):
                        assert got == want
                    else:
                        assert res.status == ResultStatus.ERROR
                        assert (
                            b"committed but responses unavailable"
                            in got[0]
                        ), got
                finally:
                    await s.close()
                await asyncio.sleep(0.3)
                assert await applied(g) == before, (
                    "cross-replica replay consumed consensus slots"
                )
        finally:
            harness.stop()

    @pytest.mark.slow
    @pytest.mark.asyncio
    async def test_rebalance_and_replay_after_reroute(self):
        """Mid-run rebalance through the routed-fleet front door: after
        ``[1, 2)`` moves group 0 -> 1, new Submits for shard 1 commit in
        the NEW owner, and a REPLAY of a pre-move seq still answers
        byte-identical (the routing tier's session dedup) — the
        exactly-once story across the flip."""
        gm = GroupMap.initial(N_SHARDS, 2)
        harness = GroupProcHarness(gm, n_replicas=3)
        fleet = None
        ser = Serializer()
        loop = asyncio.get_event_loop()
        try:
            await loop.run_in_executor(None, harness.start)
            fleet = GroupedFleetHarness(
                gm.copy(), harness.upstream_addrs(), n_gateways=1
            )
            await fleet.start()
            port = fleet.gateways[0].port
            s = LoadSession(ser, client_id=_cid(77))
            try:
                await s.connect("127.0.0.1", port)
                pre = await s.submit(
                    1, [encode_set_bin("pre-move", "a")], 20.0
                )
                assert pre.status == ResultStatus.OK
                pre_seq = s._seq
                want = tuple(bytes(p) for p in pre.payload)

                # the safe order: widen replicas first, then flip routing
                new_map = await harness.rebalance(1, 2, 1)
                assert moved_group_shards(gm, new_map) == {1: 1}
                fleet.adopt_groups(new_map)

                post = await s.submit(
                    1, [encode_set_bin("post-move", "b")], 20.0
                )
                assert post.status == ResultStatus.OK
            finally:
                await s.close()

            # replay across the flip on a FRESH connection (the
            # transport keys by client_id, so the dropped client
            # reconnects first — the realistic replay story): the
            # routing tier's session dedup answers byte-identical
            s2 = LoadSession(ser, client_id=_cid(77))
            try:
                await s2.connect("127.0.0.1", port)
                res = await s2.submit_seq(
                    pre_seq, 1,
                    [encode_set_bin("pre-move", "a")], 20.0,
                )
                assert res.status in (
                    ResultStatus.OK, ResultStatus.CACHED
                )
                assert tuple(bytes(p) for p in res.payload) == want
            finally:
                await s2.close()
        finally:
            if fleet is not None:
                await fleet.stop()
            harness.stop()

    @pytest.mark.slow
    @pytest.mark.asyncio
    async def test_conformance_groups2_matches_groups1(self):
        """Partitioning must change WHERE ops commit, never WHAT clients
        observe: the same deterministic workload against groups=1 and
        groups=2 yields byte-identical per-client responses (SET
        responses carry per-key versions, so this pins apply counts and
        order per key) and identical per-shard mutation counts."""

        async def drive(n_groups: int):
            gm = GroupMap.initial(N_SHARDS, n_groups)
            harness = GroupProcHarness(gm, n_replicas=3)
            ser = Serializer()
            loop = asyncio.get_event_loop()
            responses: dict[int, list[tuple]] = {}
            mutations: dict[int, int] = {}
            try:
                await loop.run_in_executor(None, harness.start)
                router = harness.router()
                for ci in range(4):
                    shard = ci % N_SHARDS
                    s = LoadSession(ser, client_id=_cid(100 + ci))
                    rows = []
                    try:
                        await s.connect(*router.upstream_for(shard))
                        for j in range(4):
                            res = await s.submit(
                                shard,
                                [
                                    encode_set_bin(
                                        f"cf-{ci}-{j}-{k}", f"v{j}.{k}"
                                    )
                                    for k in range(2)
                                ],
                                20.0,
                            )
                            assert res.status == ResultStatus.OK
                            rows.append(
                                tuple(bytes(p) for p in res.payload)
                            )
                        # per-shard mutation counts: sum of per-key
                        # versions read back through the owning group
                        # (consensus-slot GETs: the recovery children
                        # have no peer-gateway wiring, so the zero-slot
                        # read-index quorum probe is unavailable here)
                        total = 0
                        for j in range(4):
                            for k in range(2):
                                r = await s.submit(
                                    shard,
                                    [encode_op_bin(KVOperation.get(
                                        f"cf-{ci}-{j}-{k}"
                                    ))],
                                    20.0,
                                )
                                assert r.status == ResultStatus.OK
                                kv = decode_result_bin(
                                    bytes(r.payload[0])
                                )
                                total += int(kv.version or 0)
                        mutations[shard] = total
                    finally:
                        await s.close()
                    responses[ci] = rows
            finally:
                harness.stop()
            return responses, mutations

        r1, m1 = await drive(1)
        r2, m2 = await drive(2)
        assert r1 == r2, "per-client responses diverge across grouping"
        assert m1 == m2, "per-shard mutation counts diverge"
        # versions are the store's per-shard mutation counter: the 8
        # SETs on a shard stamp versions 1..8, so the sum (36) pins the
        # exact mutation COUNT per shard in both groupings
        assert all(v == 36 for v in m1.values()), m1
