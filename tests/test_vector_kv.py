"""VectorKVStore: columnar correctness vs the classic store, collisions,
growth, deletion chains, snapshots, and the VectorShardedKV block path."""

from __future__ import annotations

import numpy as np
import pytest

from rabia_tpu.apps.kvstore import decode_result_bin, encode_set_bin
from rabia_tpu.apps.vector_kv import VectorKVStore, VectorShardedKV
from rabia_tpu.core.blocks import build_block


def _bulk_args(store, shards, keys):
    lanes, klens = store._lanes_from_keys(keys)
    return np.asarray(shards, np.int64), lanes, klens


class TestVectorKVStore:
    def test_set_get_roundtrip(self):
        st = VectorKVStore(4, capacity=64)
        v1 = st.set(0, b"alpha", b"1")
        v2 = st.set(1, b"alpha", b"2")  # same key, different shard
        assert v1 == 1 and v2 == 1  # per-shard version counters
        assert st.get(0, b"alpha") == (b"1", 1)
        assert st.get(1, b"alpha") == (b"2", 1)
        assert st.get(2, b"alpha") is None
        assert len(st) == 2

    def test_update_bumps_version(self):
        st = VectorKVStore(2, capacity=64)
        st.set(0, b"k", b"a")
        v = st.set(0, b"k", b"b")
        assert v == 2
        assert st.get(0, b"k") == (b"b", 2)
        assert len(st) == 1

    def test_bulk_wave_order_for_duplicate_keys(self):
        st = VectorKVStore(1, capacity=64)
        shards, lanes, klens = _bulk_args(st, [0, 0, 0], [b"k", b"x", b"k"])
        vers = st.bulk_set(shards, lanes, klens, [b"v1", b"vx", b"v2"])
        assert list(vers) == [1, 2, 3]
        assert st.get(0, b"k") == (b"v2", 3)  # later op won
        assert st.get(0, b"x") == (b"vx", 2)

    def test_growth_preserves_contents(self):
        st = VectorKVStore(8, capacity=16)  # tiny: forces several grows
        for i in range(500):
            st.set(i % 8, f"key{i}".encode(), f"v{i}".encode())
        assert st.C >= 1024
        for i in range(500):
            got = st.get(i % 8, f"key{i}".encode())
            assert got is not None and got[0] == f"v{i}".encode()

    def test_collisions_resolve(self):
        # tiny table with many keys ⇒ heavy probing
        st = VectorKVStore(1, capacity=16)
        keys = [f"c{i}".encode() for i in range(200)]
        shards, lanes, klens = _bulk_args(st, [0] * 200, keys)
        st.bulk_set(shards, lanes, klens, [b"x%d" % i for i in range(200)])
        for i, k in enumerate(keys):
            assert st.get(0, k) == (b"x%d" % i, i + 1)

    def test_delete_backward_shift_keeps_chains(self):
        st = VectorKVStore(1, capacity=16)
        keys = [f"d{i}".encode() for i in range(10)]
        for k in keys:
            st.set(0, k, b"v")
        assert st.delete(0, keys[3])
        assert st.get(0, keys[3]) is None
        for i, k in enumerate(keys):
            if i != 3:
                assert st.get(0, k) is not None, f"lost {k} after delete"
        assert not st.delete(0, b"absent")

    def test_long_keys_overflow(self):
        st = VectorKVStore(2, capacity=16)
        long_key = b"L" * 100
        v = st.set(1, long_key, b"big")
        assert v == 1
        assert st.get(1, long_key) == (b"big", 1)
        assert st.delete(1, long_key)
        assert st.get(1, long_key) is None

    def test_snapshot_roundtrip(self):
        st = VectorKVStore(4, capacity=64)
        for i in range(50):
            st.set(i % 4, f"s{i}".encode(), f"v{i}".encode())
        st.set(0, b"X" * 64, b"overflowed")
        raw = st.snapshot_bytes()
        st2 = VectorKVStore(4, capacity=64)
        st2.restore_bytes(raw)
        for i in range(50):
            assert st2.get(i % 4, f"s{i}".encode()) == st.get(
                i % 4, f"s{i}".encode()
            )
        assert st2.get(0, b"X" * 64) == (b"overflowed", st.get(0, b"X" * 64)[1])
        assert list(st2.shard_version) == list(st.shard_version)

    def test_matches_classic_store_semantics(self):
        """Random op sequence: versions per shard match the classic
        KVStore's per-store counters."""
        from rabia_tpu.apps.kvstore import KVStore

        rng = np.random.default_rng(3)
        vec = VectorKVStore(4, capacity=64)
        classic = [KVStore() for _ in range(4)]
        for _ in range(300):
            s = int(rng.integers(0, 4))
            k = f"k{int(rng.integers(0, 20))}"
            v = f"v{int(rng.integers(0, 100))}"
            ver_v = vec.set(s, k.encode(), v.encode())
            ver_c = classic[s].set(k, v).version
            assert ver_v == ver_c
        for s in range(4):
            for k in classic[s].keys():
                got = vec.get(s, k.encode())
                assert got is not None
                assert got[0].decode() == classic[s].get(k).value


class TestVectorShardedKV:
    def test_apply_block_sets(self):
        sm = VectorShardedKV(8, capacity=64)
        shards = [1, 3, 5]
        blk = build_block(
            shards, [[encode_set_bin(f"key{s}", f"val{s}")] for s in shards]
        )
        resp = sm.apply_block(blk, np.arange(3))
        assert [len(r) for r in resp] == [1, 1, 1]
        for r in resp:
            assert decode_result_bin(r[0]).ok
        assert sm.store.get(3, b"key3") == (b"val3", 1)

    def test_apply_block_multi_command_shards(self):
        sm = VectorShardedKV(4, capacity=64)
        blk = build_block(
            [0, 2],
            [
                [encode_set_bin("a", "1"), encode_set_bin("b", "2")],
                [encode_set_bin("c", "3")],
            ],
        )
        resp = sm.apply_block(blk, np.arange(2))
        assert [len(r) for r in resp] == [2, 1]
        assert sm.store.get(0, b"a") == (b"1", 1)
        assert sm.store.get(0, b"b") == (b"2", 2)
        assert sm.store.get(2, b"c") == (b"3", 1)

    def test_apply_block_mixed_ops(self):
        from rabia_tpu.apps.kvstore import KVOperation, encode_op_bin

        sm = VectorShardedKV(4, capacity=64)
        sm.store.set(1, b"x", b"old")
        blk = build_block(
            [0, 1],
            [
                [encode_set_bin("fresh", "v")],
                [encode_op_bin(KVOperation.get("x"))],
            ],
        )
        resp = sm.apply_block(blk, np.arange(2))
        assert decode_result_bin(resp[0][0]).ok
        got = decode_result_bin(resp[1][0])
        assert got.ok and got.value == "old"

    def test_scalar_batch_path(self):
        from rabia_tpu.core.types import Command, CommandBatch, ShardId

        sm = VectorShardedKV(4, capacity=64)
        batch = CommandBatch.new(
            [Command.new(encode_set_bin("sk", "sv"))], shard=ShardId(2)
        )
        resp = sm.apply_batch(batch)
        assert decode_result_bin(resp[0]).ok
        assert sm.store.get(2, b"sk") == (b"sv", 1)

    def test_snapshot_roundtrip(self):
        sm = VectorShardedKV(4, capacity=64)
        blk = build_block([0, 1], [[encode_set_bin("a", "1")], [encode_set_bin("b", "2")]])
        sm.apply_block(blk, np.arange(2))
        snap = sm.create_snapshot()
        sm2 = VectorShardedKV(4, capacity=64)
        sm2.restore_snapshot(snap)
        assert sm2.store.get(0, b"a") == (b"1", 1)
        assert sm2.store.get(1, b"b") == (b"2", 1)

    def test_malformed_op_reports_error(self):
        sm = VectorShardedKV(2, capacity=64)
        blk = build_block([0], [[b"\xff\x00\x00garbage"]])
        resp = sm.apply_block(blk, np.arange(1))
        assert not decode_result_bin(resp[0][0]).ok


class TestReviewRegressions:
    def test_single_wave_larger_than_capacity(self):
        """One wave with more new keys than 2x capacity must grow to
        demand, not exhaust the probe loop mid-insert."""
        st = VectorKVStore(1, capacity=16)
        n = 500
        keys = [f"w{i}".encode() for i in range(n)]
        shards, lanes, klens = _bulk_args(st, [0] * n, keys)
        st.bulk_set(shards, lanes, klens, [b"v"] * n)
        for i in (0, 123, n - 1):
            assert st.get(0, keys[i]) == (b"v", i + 1)

    def test_malformed_set_rejected_not_truncated(self):
        sm = VectorShardedKV(2, capacity=64)
        bad = b"\x01" + (100).to_bytes(2, "little") + b"abc"
        blk = build_block([0], [[bad]])
        resp = sm.apply_block(blk, np.arange(1))
        assert not decode_result_bin(resp[0][0]).ok
        assert sm.store.get(0, b"abc") is None  # nothing stored

    def test_overflow_delete_bumps_version(self):
        st = VectorKVStore(2, capacity=16)
        st.set(0, b"L" * 100, b"x")  # version 1
        assert st.delete(0, b"L" * 100)
        assert st.set(0, b"s", b"y") == 3  # delete consumed version 2

    def test_value_size_limit_enforced(self):
        import pytest as _pytest

        from rabia_tpu.core.errors import StateMachineError

        st = VectorKVStore(1, capacity=64, max_value_size=8)
        with _pytest.raises(StateMachineError):
            st.set(0, b"k", b"x" * 100)
        sm = VectorShardedKV(1, capacity=64)
        sm.store.max_value_size = 8
        blk = build_block([0], [[encode_set_bin("k", "y" * 100)]])
        resp = sm.apply_block(blk, np.arange(1))
        assert not decode_result_bin(resp[0][0]).ok

    def test_response_frames_are_fixed_width(self):
        sm = VectorShardedKV(2, capacity=64)
        blk = build_block([0], [[encode_set_bin("k", "v")]])
        resp = sm.apply_block(blk, np.arange(1))
        assert len(resp[0][0]) == 6  # kind u8 | version u32 | has_value u8

    def test_non_utf8_value_get_errors_not_mangles(self):
        sm = VectorShardedKV(1, capacity=64)
        from rabia_tpu.apps.kvstore import KVOperation, encode_op_bin

        raw_set = b"\x01" + (1).to_bytes(2, "little") + b"k" + b"\xff\xfe"
        blk = build_block([0], [[raw_set]])
        assert decode_result_bin(sm.apply_block(blk, np.arange(1))[0][0]).ok
        blk2 = build_block([0], [[encode_op_bin(KVOperation.get("k"))]])
        res = decode_result_bin(sm.apply_block(blk2, np.arange(1))[0][0])
        assert not res.ok  # explicit error, not replacement characters
        assert sm.store.get(0, b"k") == (b"\xff\xfe", 1)  # bytes API intact


class TestMixedOpEquivalence:
    def test_random_mixed_ops_match_classic(self):
        """Interleaved set/get/delete/exists sequences: the vector store's
        visible behavior (values, versions, found-ness) must match the
        classic store op for op."""
        from rabia_tpu.apps.kvstore import KVStore

        rng = np.random.default_rng(17)
        vec = VectorKVStore(4, capacity=32)  # tiny: forces growth + probes
        classic = [KVStore() for _ in range(4)]
        for step in range(800):
            s = int(rng.integers(0, 4))
            k = f"k{int(rng.integers(0, 12))}"
            op = rng.random()
            if op < 0.55:
                v = f"v{step}"
                assert vec.set(s, k.encode(), v.encode()) == classic[s].set(k, v).version
            elif op < 0.75:
                got = vec.get(s, k.encode())
                cres = classic[s].get(k)
                if cres.value is None:
                    assert got is None
                else:
                    assert got is not None
                    assert got[0].decode() == cres.value
                    assert got[1] == cres.version
            elif op < 0.9:
                deleted = vec.delete(s, k.encode())
                cres = classic[s].delete(k)
                assert deleted == cres.ok
            else:
                found = vec.get(s, k.encode()) is not None
                assert found == (classic[s].exists(k).value == "true")
        # final state equality per shard — BOTH directions: every classic
        # key readable in vec, and no ghost entries beyond the total count
        assert len(vec) == sum(len(c.keys()) for c in classic)
        for s in range(4):
            for k in classic[s].keys():
                got = vec.get(s, k.encode())
                assert got is not None and got[0].decode() == classic[s].get(k).value


class TestApplyBlockMulti:
    """apply_block_multi (the full-width lane's one-call-per-replica
    apply) must be observationally identical to sequential apply_block."""

    @staticmethod
    def _rand_blocks(rng, n_shards, n_waves, mixed=False):
        from rabia_tpu.apps.kvstore import KVOperation, encode_op_bin

        blocks = []
        for _w in range(n_waves):
            shards = sorted(
                rng.choice(n_shards, rng.integers(1, n_shards + 1),
                           replace=False).tolist()
            )
            cmds = []
            for s in shards:
                ops = []
                for _ in range(int(rng.integers(1, 4))):
                    # duplicate keys across waves AND within a wave
                    key = f"k{int(rng.integers(0, 6))}"
                    if mixed and rng.random() < 0.3:
                        op = (
                            KVOperation.get(key)
                            if rng.random() < 0.5
                            else KVOperation.delete(key)
                        )
                        ops.append(encode_op_bin(op))
                    else:
                        ops.append(
                            encode_set_bin(key, f"v{int(rng.integers(0, 100))}")
                        )
                cmds.append(ops)
            blocks.append(build_block(shards, cmds))
        return blocks

    def _assert_equal(self, a: VectorShardedKV, b: VectorShardedKV):
        # timestamps (created/updated) are wall-clock metadata — exclude
        # them, as two equivalent applies never share a clock
        pa = VectorKVStore._parse_snapshot(a.store.snapshot_bytes())
        pb = VectorKVStore._parse_snapshot(b.store.snapshot_bytes())
        assert pa[0].tolist() == pb[0].tolist()  # per-shard versions
        assert pa[1][:4] == pb[1][:4]  # shards, keys, vals, versions
        ov_a = [{k: v for k, v in d.items() if k not in ("created", "updated")}
                for d in pa[2]]
        ov_b = [{k: v for k, v in d.items() if k not in ("created", "updated")}
                for d in pb[2]]
        assert ov_a == ov_b

    @pytest.mark.parametrize("mixed", [False, True])
    def test_matches_sequential_apply(self, mixed):
        rng = np.random.default_rng(7 if mixed else 5)
        n_shards = 6
        for trial in range(8):
            blocks = self._rand_blocks(rng, n_shards, int(rng.integers(2, 6)),
                                       mixed=mixed)
            idxs = [np.arange(len(blk)) for blk in blocks]
            one = VectorShardedKV(n_shards, capacity=256)
            two = VectorShardedKV(n_shards, capacity=256)
            seq = [one.apply_block(blk, i) for blk, i in zip(blocks, idxs)]
            multi = two.apply_block_multi(blocks, idxs)
            assert multi == seq, f"trial {trial}: responses diverge"
            self._assert_equal(one, two)

    def test_want_responses_false_still_applies(self):
        rng = np.random.default_rng(11)
        blocks = self._rand_blocks(rng, 4, 3)
        idxs = [np.arange(len(blk)) for blk in blocks]
        leader = VectorShardedKV(4, capacity=128)
        follower = VectorShardedKV(4, capacity=128)
        assert leader.apply_block_multi(blocks, idxs) is not None
        assert follower.apply_block_multi(blocks, idxs,
                                          want_responses=False) is None
        self._assert_equal(leader, follower)

    def test_single_block_delegates(self):
        blk = build_block([0, 1], [[encode_set_bin("a", "1")],
                                   [encode_set_bin("b", "2")]])
        sm = VectorShardedKV(2, capacity=64)
        out = sm.apply_block_multi([blk], [np.arange(2)])
        assert len(out) == 1 and [len(r) for r in out[0]] == [1, 1]
