"""Native per-tick fast path (hostkernel.cpp rk_tick) gates.

The Python paths in engine/engine.py stay the semantics owner; this suite
pins the native fast path to them:

- fixed-schedule conformance through the shared gate
  (testing.conformance.run_schedule_on_both_tick_paths): identical
  decision ledgers + byte-identical replica state, native vs
  ``RABIA_PY_TICK=1``;
- a MIXED cluster (native + Python replicas interleaved) — every frame
  the C emitter writes must be consumed by the Python ingest and vice
  versa, on the same wire;
- C-emitted frames decode through the Python BinarySerializer (wire
  conformance of the native outbound framing);
- ingest edge cases: spoofed envelopes dropped, future votes carried,
  stale votes reported to the repair path;
- the config-1 serial-latency budget regression test (VERDICT r05 weak
  #1): proposer-direct commit p50 under budget with the fast path on.

The randomized twin of the conformance gate lives in
``scripts/fuzz_conformance.py --tick`` (fresh schedules every run).
"""

from __future__ import annotations

import asyncio
import os
import time

import numpy as np
import pytest

from rabia_tpu.native.build import load_hostkernel

_lib = load_hostkernel()
pytestmark = pytest.mark.skipif(
    _lib is None or not hasattr(_lib, "rk_ctx_create"),
    reason="native hostkernel unavailable",
)


def _mk_cluster(n_shards=1, n_replicas=3, py_rows=(), sm_factory=None, **cfg_kw):
    """In-memory cluster; replicas whose row is in `py_rows` are forced
    onto the Python tick path (mixed-cluster wire conformance).
    ``sm_factory`` overrides the per-replica state machine (default
    InMemoryStateMachine)."""
    from rabia_tpu.core.config import RabiaConfig
    from rabia_tpu.core.network import ClusterConfig
    from rabia_tpu.core.state_machine import InMemoryStateMachine
    from rabia_tpu.core.types import NodeId
    from rabia_tpu.engine import RabiaEngine
    from rabia_tpu.net import InMemoryHub

    kw = dict(
        phase_timeout=2.0, heartbeat_interval=0.05, round_interval=0.001
    )
    kw.update(cfg_kw)
    cfg = RabiaConfig(**kw).with_kernel(
        num_shards=n_shards, shard_pad_multiple=max(1, n_shards)
    )
    hub = InMemoryHub()
    nodes = [NodeId.from_int(i + 1) for i in range(n_replicas)]
    engines, sms = [], []
    prev = os.environ.pop("RABIA_PY_TICK", None)
    try:
        for i, node in enumerate(nodes):
            if i in py_rows:
                os.environ["RABIA_PY_TICK"] = "1"
            else:
                os.environ.pop("RABIA_PY_TICK", None)
            sm = (
                sm_factory() if sm_factory is not None
                else InMemoryStateMachine()
            )
            sms.append(sm)
            engines.append(
                RabiaEngine(
                    ClusterConfig.new(node, nodes), sm,
                    hub.register(node), config=cfg,
                )
            )
    finally:
        if prev is None:
            os.environ.pop("RABIA_PY_TICK", None)
        else:
            os.environ["RABIA_PY_TICK"] = prev
    return hub, nodes, engines, sms


async def _start(engines):
    tasks = [asyncio.ensure_future(e.run()) for e in engines]
    for _ in range(300):
        await asyncio.sleep(0.01)
        sts = [await e.get_statistics() for e in engines]
        if all(s.has_quorum for s in sts):
            return tasks
    raise AssertionError("cluster never formed quorum")


async def _stop(engines, tasks):
    for e in engines:
        await asyncio.wait_for(e.shutdown(), 10.0)
    for t in tasks:
        t.cancel()
    await asyncio.gather(*tasks, return_exceptions=True)


class TestTickPathConformance:
    @pytest.mark.asyncio
    async def test_fixed_schedules_identical(self):
        from rabia_tpu.testing.conformance import (
            run_schedule_on_both_tick_paths,
        )

        schedule = [
            {0: ["SET a 1", "SET b 2"], 1: ["SET c 3"]},
            {1: ["SET c 4"]},
            {0: ["SET a 7"], 1: ["SET d 5", "SET e 6"]},
        ]
        await run_schedule_on_both_tick_paths(
            schedule, n_shards=2, n_replicas=3, tag="fixed-2s3r"
        )

    @pytest.mark.asyncio
    async def test_fixed_schedule_five_replicas(self):
        from rabia_tpu.testing.conformance import (
            run_schedule_on_both_tick_paths,
        )

        schedule = [{0: ["SET x 1"]}, {0: ["SET x 2"]}, {0: ["SET y 9"]}]
        await run_schedule_on_both_tick_paths(
            schedule, n_shards=1, n_replicas=5, tag="fixed-1s5r"
        )

    @pytest.mark.asyncio
    async def test_mixed_cluster_converges(self):
        """Native and Python replicas on the SAME wire: C-emitted frames
        feed the Python ingest and Python-emitted frames feed the C
        ingest; commits and state must still converge."""
        from rabia_tpu.core.types import Command, CommandBatch

        hub, nodes, engines, sms = _mk_cluster(py_rows=(1,))
        assert engines[0]._rk is not None
        assert engines[1]._rk is None  # forced Python path
        assert engines[2]._rk is not None
        tasks = await _start(engines)
        try:
            for i in range(12):
                fut = await engines[i % 3].submit_batch(
                    CommandBatch.new([Command.new(f"SET k{i} v{i}".encode())])
                )
                got = await asyncio.wait_for(fut, 15.0)
                assert got == [b"OK"]
            snap = sms[0].create_snapshot().data
            for _ in range(500):
                if all(s.create_snapshot().data == snap for s in sms):
                    break
                await asyncio.sleep(0.01)
            assert all(s.create_snapshot().data == snap for s in sms)
        finally:
            await _stop(engines, tasks)


class TestNativeWire:
    @pytest.mark.asyncio
    async def test_emitted_frames_decode_via_python_codec(self):
        """Every frame the native tick writes must decode through the
        Python BinarySerializer (wire-format ownership stays with the
        Python codec)."""
        from rabia_tpu.core.messages import (
            Decision,
            VoteRound1,
            VoteRound2,
        )
        from rabia_tpu.core.serialization import BinarySerializer
        from rabia_tpu.core.types import Command, CommandBatch, NodeId

        hub, nodes, engines, sms = _mk_cluster()
        observer = NodeId.from_int(99)
        obs_net = hub.register(observer)
        tasks = await _start(engines)
        try:
            for i in range(4):
                fut = await engines[0].submit_batch(
                    CommandBatch.new([Command.new(b"SET k v")])
                )
                await asyncio.wait_for(fut, 15.0)
        finally:
            await _stop(engines, tasks)
        ser = BinarySerializer()
        kinds = set()
        n_frames = 0
        while True:
            item = obs_net.receive_nowait()
            if item is None:
                break
            sender, data = item
            msg = ser.deserialize(data)  # raises on any malformed frame
            assert msg.sender == sender
            kinds.add(type(msg.payload).__name__)
            if isinstance(msg.payload, (VoteRound1, VoteRound2)):
                assert len(msg.payload) >= 1
                assert int(msg.payload.vals.max()) <= 3
            if isinstance(msg.payload, Decision):
                assert msg.payload.bids is None
            n_frames += 1
        assert n_frames > 0
        # the consensus wave kinds, all native-framed
        assert {"VoteRound1", "VoteRound2", "Decision"} <= kinds

    @pytest.mark.asyncio
    async def test_spoofed_envelope_dropped(self):
        """A frame whose envelope sender differs from the transport-
        authenticated peer must be dropped by the native ingest (same
        guard as engine._handle_message)."""
        from rabia_tpu.core.messages import ProtocolMessage, VoteRound1
        from rabia_tpu.core.serialization import BinarySerializer

        hub, nodes, engines, sms = _mk_cluster()
        e0 = engines[0]
        rk = e0._rk
        assert rk is not None
        ser = BinarySerializer()
        # envelope claims node 2 (row 2); we present it as from row 1
        spoofed = ser.serialize(
            ProtocolMessage.new(
                nodes[2],
                VoteRound1(
                    shards=np.asarray([0]),
                    phases=np.asarray([0]),
                    vals=np.asarray([1], np.int8),
                ),
            )
        )
        before = rk.dropped_frames
        assert rk.ingest(spoofed, 1, time.time()) == -1
        assert rk.dropped_frames == before + 1

    @pytest.mark.asyncio
    async def test_future_votes_carried_and_stale_reported(self):
        from rabia_tpu.core.messages import ProtocolMessage, VoteRound1
        from rabia_tpu.core.serialization import BinarySerializer

        hub, nodes, engines, sms = _mk_cluster()
        e0 = engines[0]
        rk = e0._rk
        ser = BinarySerializer()
        # a vote for a far-future slot: carried, not scattered
        fut_vote = ser.serialize(
            ProtocolMessage.new(
                nodes[1],
                VoteRound1(
                    shards=np.asarray([0]),
                    phases=np.asarray([5 << 16]),
                    vals=np.asarray([1], np.int8),
                ),
            )
        )
        assert rk.ingest(fut_vote, 1, time.time()) == 1
        assert rk.carry_count == 1
        assert int(e0.rt.votes_seen_slot[0]) == 5
        # a stale vote (slot below applied): reported for repair, rc=2
        e0.rt.applied_upto[0] = 3
        stale_vote = ser.serialize(
            ProtocolMessage.new(
                nodes[1],
                VoteRound1(
                    shards=np.asarray([0]),
                    phases=np.asarray([1 << 16]),
                    vals=np.asarray([0], np.int8),
                ),
            )
        )
        assert rk.ingest(stale_vote, 1, time.time()) == 2


class TestSerialLatencyBudget:
    @pytest.mark.asyncio
    @pytest.mark.parametrize(
        "mode", ["plain", "traced", "flight", "apply"]
    )
    async def test_config1_serial_latency_budget(self, mode):
        """Pin the config-1 regression (VERDICT r05 weak #1, p50 1.6 →
        2.49 ms): proposer-direct serial commits through the native tick
        path must hold a p50 budget. The budget is sized for a loaded
        2-core CI host — the Python tick path measures ~4.2-4.7 ms here,
        the native path ~2.3 ms, so the gate catches a regression to the
        Python-path cost class while tolerating host noise. Best-of-two
        rounds to shrug off one noisy measurement window.

        The ``traced`` variant is the observability overhead guard: the
        SAME budget must hold with span tracing enabled (RABIA_TRACE=1
        semantics) and the metrics registry live — instrumentation on
        the hot path is bounded to span bookkeeping plus event-path
        histogram observes, and the disabled path stays one branch.

        The ``flight`` variant is the recorder-on overhead guard: the
        native flight ring is always written on the C fast path (a
        clock_gettime + one 32-byte store per record), and the same
        budget must hold with it verifiably populated — the variant
        additionally asserts the ring carried the run's lifecycle, so a
        silently-disabled recorder can't make the guard vacuous.

        The ``apply`` variant runs the same budget through the NATIVE
        APPLY PLANE (kvstore shard stores on the statekernel, binary
        SET commands): serial commits must not regress when the apply
        side of the commit path is the C plane, and the variant asserts
        the plane actually applied (SKC op counter + its flight ring),
        so a silent fallback to the Python store can't make it vacuous."""
        trace = mode == "traced"
        from rabia_tpu.core.tracing import tracer
        from rabia_tpu.core.types import Command, CommandBatch
        from rabia_tpu.engine.leader import slot_proposer

        # sized against this PR's recorded spread on a 2-core host
        # (engine_sweep_r06: native p50 median 2.15 ms with slow repeats
        # near 3.6 ms under scheduler noise; the Python path measures
        # 4.2-4.7 ms) — best-of-3 rounds under 4.5 ms separates the two
        # cost classes without going red on one noisy window. The budget
        # is additionally LOAD-AWARE (the documented ~1-in-4 ambient-load
        # flake class): a saturating co-tenant scales it, capped at 2x —
        # a regression to the Python-path cost class still trips it.
        budget_ms = 4.5
        try:
            load = os.getloadavg()[0] / max(1, os.cpu_count() or 1)
        except OSError:  # pragma: no cover - platform without loadavg
            load = 0.0
        budget_ms *= max(1.0, min(2.0, load))
        sm_factory = None
        if mode == "apply":
            from rabia_tpu.apps.native_store import native_apply_available
            from rabia_tpu.apps.sharded import make_sharded_kv

            if not native_apply_available():
                pytest.skip("statekernel library unavailable")
            sm_factory = lambda: make_sharded_kv(1, native=True)[0]  # noqa: E731
        hub, nodes, engines, sms = _mk_cluster(
            phase_timeout=0.4, sm_factory=sm_factory,
        )
        assert all(e._rk is not None for e in engines)
        prev_enabled = tracer.enabled
        if trace:
            tracer.enabled = True
        if mode == "apply":
            from rabia_tpu.apps.kvstore import encode_set_bin

            cmd_bytes = encode_set_bin("k", "v")  # the binary wire op
        else:
            cmd_bytes = b"SET k v"
        tasks = await _start(engines)
        try:
            best = float("inf")
            for _round in range(3):
                lat = []
                for i in range(60):
                    e = engines[0]
                    slot = max(
                        int(e.rt.next_slot[0]), int(e.rt.applied_upto[0])
                    )
                    p = slot_proposer(0, slot, 3)
                    t0 = time.perf_counter()
                    fut = await engines[p].submit_batch(
                        CommandBatch.new([Command.new(cmd_bytes)])
                    )
                    await asyncio.wait_for(fut, 10.0)
                    lat.append(time.perf_counter() - t0)
                lat.sort()
                best = min(best, lat[len(lat) // 2] * 1000)
                if best <= budget_ms:
                    break
            # on failure, carry the runtime stage profiler's breakdown
            # (rabia_runtime_stage_seconds): the documented ambient-load
            # flake class becomes a diagnosable report — a co-tenant
            # starving the loop shows up as idle/other dominating, a
            # real regression shows up in ingest/tick/apply
            stages = engines[0].stage_seconds()
            total_s = sum(stages.values()) or 1.0
            breakdown = ", ".join(
                f"{k}={v:.3f}s ({v / total_s * 100:.0f}%)"
                for k, v in sorted(
                    stages.items(), key=lambda kv: -kv[1]
                )
                if v > 0
            )
            assert best <= budget_ms, (
                f"serial commit p50 {best:.2f} ms exceeds the "
                f"{budget_ms} ms budget (config-1 latency regression"
                f"{', tracing ON' if trace else ''}); "
                f"stage breakdown: {breakdown}"
            )
            if trace:
                # the spans must actually have been aggregated (the guard
                # is vacuous if tracing silently stayed off) and fold
                # into the replica metrics exposition
                assert tracer.spans, "tracing enabled but no spans recorded"
                assert "rabia_span_seconds" in (
                    engines[0].metrics.render_prometheus()
                )
            if mode == "flight":
                # the native ring must have recorded the run it just
                # timed (otherwise this variant guards nothing)
                e0 = engines[0]
                assert e0._rk.flight_head() > 0
                kinds = {e["kind"] for e in e0.flight_events()}
                assert {"frame_in", "open", "decide", "apply"} <= kinds
            if mode == "apply":
                # the native apply plane must actually have applied the
                # run (otherwise this variant guards nothing)
                e0 = engines[0]
                plane = e0.sm._native_plane
                assert plane is not None
                assert plane.counter("ops") >= 60
                assert plane.flight_head() > 0
            # the commit pipeline histograms observed every commit
            h = engines[0].metrics.histogram(
                "commit_stage_seconds", labels={"stage": "propose_decide"}
            )
            assert h.count > 0
        finally:
            if trace:
                tracer.enabled = prev_enabled
                tracer.reset()
            await _stop(engines, tasks)
