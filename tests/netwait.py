"""Deadline-based liveness waits for transport tests.

The round-4 field notes recorded ~1-in-4 full-suite runs dropping a
timing-sensitive test under ambient load on the shared 1-core host. The
common shape was a count-based connect wait (``for _ in range(100):
sleep(0.05)``) sized for a quiet box: the native dialer makes 5 backoff
attempts over ~3s and then falls back to a 10s redial period
(native/transport.cpp kRedialPeriodS), so one loaded startup window
pushes the handshake past a 5s budget and the test fails later, at the
receive, with a misleading timeout.

These helpers replace those loops with explicit wall-clock deadlines
that are generous (liveness budgets cost nothing when things are
healthy) and assert AT the wait with diagnostics, so a genuinely broken
transport fails fast and attributably instead of as a downstream
timeout.
"""

from __future__ import annotations

import asyncio
import time

# One redial period past the dialer's 5-attempt burst, with margin for a
# loaded host: generous on purpose. A healthy localhost handshake takes
# ~1ms; the budget only matters when the host is starved, where failing
# the suite over slowness is exactly the flake being removed.
CONNECT_BUDGET_S = 25.0


async def wait_connected(*pairs, budget: float = CONNECT_BUDGET_S) -> None:
    """Wait until every ``(net, peer_id)`` pair reports connected.

    Asserts with a per-pair connectivity dump on timeout.
    """
    deadline = time.monotonic() + budget
    while time.monotonic() < deadline:
        states = [await net.is_connected(peer) for net, peer in pairs]
        if all(states):
            return
        await asyncio.sleep(0.05)
    states = [
        (str(peer), await net.is_connected(peer)) for net, peer in pairs
    ]
    raise AssertionError(
        f"transport handshake incomplete after {budget}s: {states}"
    )


async def wait_until(pred, budget: float = 15.0, interval: float = 0.01,
                     desc: str = "condition") -> None:
    """Wait until a synchronous predicate holds; assert on deadline."""
    deadline = time.monotonic() + budget
    while time.monotonic() < deadline:
        if pred():
            return
        await asyncio.sleep(interval)
    raise AssertionError(f"{desc} not reached within {budget}s")


async def wait_full_mesh(nets, n_peers: int, budget: float = CONNECT_BUDGET_S):
    """Wait until every net in ``nets`` sees ``n_peers`` connected nodes."""
    deadline = time.monotonic() + budget
    while time.monotonic() < deadline:
        conn = [await n.get_connected_nodes() for n in nets]
        if all(len(c) == n_peers for c in conn):
            return
        await asyncio.sleep(0.05)
    conn = [len(await n.get_connected_nodes()) for n in nets]
    raise AssertionError(
        f"mesh incomplete after {budget}s: per-net connected counts {conn}"
        f" (want {n_peers})"
    )
