"""Device-resident KV lane conformance vs the host vector store.

The device table (apps/device_kv.py) is a bounded fast lane; the host
VectorShardedKV is the semantics owner. Every test drives the SAME
full-width SET workload through a device-store MeshEngine and a host
MeshEngine and compares the observables: per-op version responses, and
the final key -> (value, version) content after demotion/sync-down.
Runs on the virtual CPU mesh (conftest pins JAX to CPU).
"""

from __future__ import annotations

import numpy as np
import pytest

from rabia_tpu.apps.kvstore import encode_set_bin
from rabia_tpu.apps.vector_kv import VectorShardedKV
from rabia_tpu.core.blocks import build_block
from rabia_tpu.parallel import MeshEngine, make_mesh


def _mk(n_shards, device: bool, **kw):
    return MeshEngine(
        lambda: VectorShardedKV(n_shards, capacity=1 << 12),
        n_shards=n_shards,
        n_replicas=3,
        mesh=make_mesh(),
        window=kw.pop("window", 4),
        device_store=device,
        **kw,
    )


def _frames(fut):
    """Flatten a block future's responses to a list of frame bytes."""
    return [bytes(g[0]) for g in fut.result_groups()] if hasattr(
        fut, "result_groups"
    ) else [bytes(r[0]) for r in fut._results]


def _set_blocks(n_shards, waves, rng, keyspace=3):
    """Random full-width SET blocks: repeated keys across waves, varied
    value lengths (collision + update coverage)."""
    out = []
    for w in range(waves):
        cmds = []
        for s in range(n_shards):
            k = f"k{s}_{int(rng.integers(0, keyspace))}"
            v = "v" * int(rng.integers(0, 24)) + f"{w}"
            cmds.append([encode_set_bin(k, v)])
        out.append(build_block(list(range(n_shards)), cmds))
    return out


def _store_content(sm: VectorShardedKV, n_shards):
    st = sm.store
    out = {}
    used = np.nonzero(st.state == 1)[0]
    for slot in used.tolist():
        s = int(st.shard_col[slot])
        key = (
            st.key_lanes[slot]
            .view(np.uint8)[: int(st.key_len[slot])]
            .tobytes()
        )
        out[(s, key)] = (sm.store._value_at(slot), int(st.version[slot]))
    return out


class TestDeviceKVConformance:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_versions_and_state_match_host(self, seed):
        n = 8
        rng = np.random.default_rng(seed)
        blocks = _set_blocks(n, waves=6, rng=rng)
        dev = _mk(n, device=True)
        host = _mk(n, device=False)
        dev_futs = [dev.submit_block(b) for b in blocks]
        # identical blocks, fresh identity, through the host engine
        host_blocks = _set_blocks(n, waves=6, rng=np.random.default_rng(seed))
        host_futs = [host.submit_block(b) for b in host_blocks]
        assert dev.flush() == host.flush() == 6 * n
        assert dev._dev_active  # clean SET windows: no demotion
        for df, hf in zip(dev_futs, host_futs):
            d = [list(map(bytes, g)) for g in df._results] if isinstance(
                df._results, list
            ) else None
            h = [list(map(bytes, g)) for g in hf._results] if isinstance(
                hf._results, list
            ) else None
            assert d == h
        # demote and compare the final store content on every replica
        dev._demote_device_store()
        want = _store_content(host.sms[0], n)
        for sm in dev.sms:
            assert _store_content(sm, n) == want
        # slot accounting marched identically
        assert np.array_equal(dev.next_slot, host.next_slot)
        assert dev.decided_v1 == host.decided_v1

    def test_mixed_block_demotes_and_stays_correct(self):
        n = 4
        rng = np.random.default_rng(7)
        dev = _mk(n, device=True)
        host = _mk(n, device=False)
        sets = _set_blocks(n, waves=2, rng=rng)
        for b in sets:
            dev.submit_block(b)
        for b in _set_blocks(n, waves=2, rng=np.random.default_rng(7)):
            host.submit_block(b)
        dev.flush()
        host.flush()
        assert dev._dev_active
        # a value wider than the device table's value lanes is outside
        # the envelope (DEL/EXISTS now run in-lane) -> demotion, and
        # the write must act on the device-written state through the
        # host store
        wide = "y" * 100
        getb = build_block(
            list(range(n)),
            [[encode_set_bin(f"k{s}_0", wide)] for s in range(n)],
        )
        getb_h = build_block(
            list(range(n)),
            [[encode_set_bin(f"k{s}_0", wide)] for s in range(n)],
        )
        df, hf = dev.submit_block(getb), host.submit_block(getb_h)
        dev.flush()
        host.flush()
        assert not dev._dev_active  # demoted
        d = [list(map(bytes, g)) for g in df._results]
        h = [list(map(bytes, g)) for g in hf._results]
        assert d == h
        want = _store_content(host.sms[0], n)
        for sm in dev.sms:
            assert _store_content(sm, n) == want

    def test_fault_demotes_without_corruption(self):
        n = 4
        rng = np.random.default_rng(3)
        dev = _mk(n, device=True)
        host = _mk(n, device=False)
        for b in _set_blocks(n, waves=2, rng=rng):
            dev.submit_block(b)
        for b in _set_blocks(n, waves=2, rng=np.random.default_rng(3)):
            host.submit_block(b)
        dev.flush()
        host.flush()
        # crash a MINORITY replica: quorum holds, every slot still
        # decides V1, and the device lane keeps going — fault tolerance
        # without demotion (only a quorum-losing window demotes)
        dev.crash_replica(2)
        host.crash_replica(2)
        for b in _set_blocks(n, waves=2, rng=np.random.default_rng(4)):
            dev.submit_block(b)
        for b in _set_blocks(n, waves=2, rng=np.random.default_rng(4)):
            host.submit_block(b)
        assert dev.flush() == host.flush()
        assert dev._dev_active  # minority crash rides the device lane
        dev._demote_device_store()
        want = _store_content(host.sms[0], n)
        for sm in dev.sms:
            assert _store_content(sm, n) == want
        assert np.array_equal(dev.next_slot, host.next_slot)

    def test_overflow_demotes(self):
        n = 2
        dev = _mk(n, device=True, device_store_kw={"per_shard_capacity": 4})
        # 6 distinct keys per shard exceeds the 4-slot device table
        for w in range(6):
            dev.submit_block(
                build_block(
                    list(range(n)),
                    [[encode_set_bin(f"key{w}", "x")] for _ in range(n)],
                )
            )
        assert dev.flush() == 6 * n
        assert not dev._dev_active  # overflowed -> demoted mid-stream
        # every key present with version == its wave's shard version
        ref = _mk(n, device=False)
        for w in range(6):
            ref.submit_block(
                build_block(
                    list(range(n)),
                    [[encode_set_bin(f"key{w}", "x")] for _ in range(n)],
                )
            )
        ref.flush()
        assert _store_content(dev.sms[0], n) == _store_content(ref.sms[0], n)

    def test_rollback_respects_submission_order_vs_queued_batches(self):
        # regression (round-5 review): per-batch submissions that arrive
        # while a pipelined device window is IN FLIGHT land directly on
        # the per-shard queues (submit() finds _full_blocks empty). If
        # that window then reads back dirty, the rollback must put its
        # blocks IN FRONT of the queued batches — appending them behind
        # (the old behavior) made the host path apply a newer write
        # before an older one on the same key.
        n = 2
        dev = _mk(
            n,
            device=True,
            device_store_kw={"per_shard_capacity": 4},
            window=8,
        )
        host = _mk(n, device=False, window=8)

        def blocks():
            # 6 distinct keys per shard overflow the 4-slot device
            # table (dirty flags); the last block writes k := A
            out = [
                build_block(
                    list(range(n)),
                    [[encode_set_bin(f"key{w}", "x")] for _ in range(n)],
                )
                for w in range(6)
            ]
            out.append(
                build_block(
                    list(range(n)),
                    [[encode_set_bin("k", "A")] for _ in range(n)],
                )
            )
            return out

        for b in blocks():
            dev.submit_block(b)
        dev.run_cycle()  # dispatches the window; flags resolve later
        assert dev._dev_pipe, "window must be in flight (pipelined)"
        # newer per-batch submission for the same key while in flight
        dev.submit([encode_set_bin("k", "B")], 0)
        dev.flush()
        assert not dev._dev_active  # dirty window -> demoted

        for b in blocks():
            host.submit_block(b)
        host.flush()
        host.submit([encode_set_bin("k", "B")], 0)
        host.flush()

        # submission order holds: k ended as B everywhere, and the full
        # content (incl. versions) matches the host-only reference
        want = _store_content(host.sms[0], n)
        assert want[(0, b"k")][0] == b"B"
        for sm in dev.sms:
            assert _store_content(sm, n) == want

    def test_idle_run_cycle_does_not_demote(self):
        n = 4
        dev = _mk(n, device=True)
        assert dev.run_cycle() == 0  # nothing queued: a no-op, not work
        assert dev._dev_active
        for b in _set_blocks(n, waves=2, rng=np.random.default_rng(5)):
            dev.submit_block(b)
        assert dev.flush() == 2 * n
        assert dev._dev_active

    def test_checkpoint_reflects_device_state(self):
        n = 4
        dev = _mk(n, device=True)
        for b in _set_blocks(n, waves=3, rng=np.random.default_rng(9)):
            dev.submit_block(b)
        dev.flush()
        assert dev._dev_active
        cp = dev.checkpoint()
        assert dev._dev_active  # checkpoint does not leave device mode
        fresh = _mk(n, device=False)
        fresh.restore(cp)
        want = _store_content(fresh.sms[0], n)
        dev._demote_device_store()
        assert _store_content(dev.sms[0], n) == want


class TestRePromotion:
    """After a demotion the engine climbs back onto the device lane:
    upload_from rebuilds the device table from the (authoritative) host
    stores, and subsequent windows run fused again — with version
    continuity and content identical to a pure-host engine."""

    def test_demote_then_repromote_conformance(self):
        n = 4
        rng = np.random.default_rng(11)
        dev = _mk(n, device=True, device_store_repromote=4)
        host = _mk(n, device=False)
        rng_h = np.random.default_rng(11)

        def both(blocks_fn):
            for b in blocks_fn(rng):
                dev.submit_block(b)
            for b in blocks_fn(rng_h):
                host.submit_block(b)
            dev.flush()
            host.flush()

        both(lambda r: _set_blocks(n, waves=3, rng=r))
        assert dev._dev_active
        # demote via an over-width value (DEL/EXISTS now run in-lane)
        g = lambda r: [
            build_block(
                list(range(n)),
                [[encode_set_bin(f"k{s}_0", "y" * 100)] for s in range(n)],
            )
        ]
        both(g)
        assert not dev._dev_active
        # overwrite the wide value with an in-envelope one, or the
        # re-promotion upload keeps declining
        both(lambda r: [
            build_block(
                list(range(n)),
                [[encode_set_bin(f"k{s}_0", "ok")] for s in range(n)],
            )
        ])
        # host-lane SETs while demoted (content the upload must carry)
        both(lambda r: _set_blocks(n, waves=2, rng=r))
        assert not dev._dev_active  # cooldown (4 cycles) not yet served
        # more full-width cycles serve the cooldown and re-promote
        both(lambda r: _set_blocks(n, waves=3, rng=r))
        both(lambda r: _set_blocks(n, waves=3, rng=r))
        assert dev._dev_active, "device lane did not re-promote"
        # device-lane windows after re-promotion stay conformant
        both(lambda r: _set_blocks(n, waves=4, rng=r))
        assert dev._dev_active
        dev._demote_device_store()  # final sync-down for comparison
        want = _store_content(host.sms[0], n)
        for sm in dev.sms:
            assert _store_content(sm, n) == want

    def test_upload_declines_outside_envelope(self):
        n = 2
        dev = _mk(n, device=True, device_store_repromote=1)
        # value wider than the device table's VW: host-lane only content
        wide = "x" * 300
        dev.submit_block(
            build_block(
                list(range(n)),
                [[encode_set_bin(f"k{s}", wide)] for s in range(n)],
            )
        )
        dev.flush()
        assert not dev._dev_active  # wide value demoted the lane
        # re-promotion attempts must DECLINE while the wide value lives
        for _ in range(4):
            dev.submit_block(
                build_block(
                    list(range(n)),
                    [[encode_set_bin(f"s{s}", "v")] for s in range(n)],
                )
            )
            dev.flush()
        assert not dev._dev_active
        # content still correct on the host path
        for sm in dev.sms:
            got = sm.store.get(0, b"k0")
            assert got is not None and got[0] == wide.encode()


class TestGovernedDeviceLane:
    def test_governor_resizes_with_device_store_conformant(self):
        """latency_target_ms + device_store compose: the governor walks
        W (each size recompiles the fused program) while the device lane
        stays active and content matches a fixed-window host engine."""
        n = 8
        eng = MeshEngine(
            lambda: VectorShardedKV(n, capacity=1 << 12),
            n_shards=n,
            n_replicas=3,
            mesh=make_mesh(),
            window=2,
            device_store=True,
            latency_target_ms=60_000.0,
            max_window=8,
        )
        host = _mk(n, device=False)
        rng = np.random.default_rng(2)
        rng_h = np.random.default_rng(2)
        for r in range(25):
            for b in _set_blocks(n, waves=8, rng=rng):  # deep: saturates W
                eng.submit_block(b)
            for b in _set_blocks(n, waves=8, rng=rng_h):
                host.submit_block(b)
            eng.flush()
            host.flush()
        assert eng.window_resizes > 0, "governor never resized"
        assert eng._dev_active
        eng._demote_device_store()
        want = _store_content(host.sms[0], n)
        for sm in eng.sms:
            assert _store_content(sm, n) == want


class TestDeviceGetWindows:
    """GET-only full-width windows run IN the device lane (read-only
    lookup program): responses are byte-for-byte the host store's GET
    framing, kind boundaries split the FIFO into windows instead of
    demoting, and out-of-envelope reads demote exactly like writes."""

    @staticmethod
    def _enc_get(k: str) -> bytes:
        import struct

        return bytes([2]) + struct.pack("<H", len(k)) + k.encode()

    def _mixed_fifo(self, n, rng):
        out = []
        for w in range(3):
            out.append(
                build_block(
                    list(range(n)),
                    [
                        [encode_set_bin(f"k{s}_{int(rng.integers(0, 3))}", f"v{w}")]
                        for s in range(n)
                    ],
                )
            )
        for w in range(2):  # GET run, including never-set keys
            out.append(
                build_block(
                    list(range(n)),
                    [[self._enc_get(f"k{s}_{w}")] for s in range(n)],
                )
            )
        out.append(
            build_block(
                list(range(n)),
                [[encode_set_bin(f"k{s}_0", "after")] for s in range(n)],
            )
        )
        out.append(
            build_block(
                list(range(n)), [[self._enc_get(f"k{s}_0")] for s in range(n)]
            )
        )
        out.append(
            build_block(
                list(range(n)), [[self._enc_get("missing")] for s in range(n)]
            )
        )
        return out

    def test_mixed_set_get_fifo_byte_identical_no_demotion(self):
        n = 8
        dev = _mk(n, device=True)
        host = _mk(n, device=False)
        fd = [dev.submit_block(b) for b in self._mixed_fifo(n, np.random.default_rng(5))]
        fh = [host.submit_block(b) for b in self._mixed_fifo(n, np.random.default_rng(5))]
        dev.flush()
        host.flush()
        assert dev._dev_active, "GET windows demoted the lane"
        for i, (a, b) in enumerate(zip(fd, fh)):
            ra = [list(map(bytes, g)) for g in a.result()]
            rb = [list(map(bytes, g)) for g in b.result()]
            assert ra == rb, i
        # reads left versions/content untouched: sync down and compare
        dev._demote_device_store()
        want = _store_content(host.sms[0], n)
        for sm in dev.sms:
            assert _store_content(sm, n) == want

    def test_intra_block_mixed_ops_run_in_lane(self):
        # a single block interleaving SET and GET across shards used to
        # demote (kind=None); the kind-masked mixed program runs it in
        # the lane, byte-identical to the host path
        n = 8
        dev = _mk(n, device=True)
        host = _mk(n, device=False)

        def fifo():
            out = []
            out.append(
                build_block(
                    list(range(n)),
                    [[encode_set_bin(f"k{s}", f"v{s}")] for s in range(n)],
                )
            )
            for w in range(3):
                cmds = [
                    [encode_set_bin(f"k{s}", f"w{w}")]
                    if s % 2 == w % 2
                    else [self._enc_get(f"k{s}")]
                    for s in range(n)
                ]
                out.append(build_block(list(range(n)), cmds))
            return out

        fd = [dev.submit_block(b) for b in fifo()]
        fh = [host.submit_block(b) for b in fifo()]
        dev.flush()
        host.flush()
        assert dev._dev_active, "intra-block mixed ops demoted the lane"
        for i, (a, b) in enumerate(zip(fd, fh)):
            ra = [list(map(bytes, g)) for g in a.result()]
            rb = [list(map(bytes, g)) for g in b.result()]
            assert ra == rb, i
        dev._demote_device_store()
        want = _store_content(host.sms[0], n)
        for sm in dev.sms:
            assert _store_content(sm, n) == want

    @pytest.mark.parametrize("seed", [11, 12, 13])
    def test_random_kind_fuzz_byte_identical(self, seed):
        # random SET/GET/DEL/EXISTS kind per (wave, shard) over deep
        # FIFOs: reads must observe exactly the applies of earlier waves
        # (host FIFO semantics), DEL's data-dependent version bumps must
        # track the host store's counters, responses byte-identical,
        # versions conformant
        from rabia_tpu.apps.kvstore import (
            KVOperation,
            KVOpType,
            encode_op_bin,
        )

        n = 8
        rng = np.random.default_rng(seed)

        def fifo(r):
            out = []
            for w in range(9):
                cmds = []
                for s in range(n):
                    k = f"k{s}_{int(r.integers(0, 2))}"
                    x = r.random()
                    if x < 0.45:
                        cmds.append([encode_set_bin(k, f"v{w}_{s}")])
                    elif x < 0.75:
                        cmds.append([self._enc_get(k)])
                    elif x < 0.9:
                        cmds.append(
                            [encode_op_bin(KVOperation(KVOpType.Delete, k))]
                        )
                    else:
                        cmds.append(
                            [encode_op_bin(KVOperation(KVOpType.Exists, k))]
                        )
                out.append(build_block(list(range(n)), cmds))
            return out

        dev = _mk(n, device=True)
        host = _mk(n, device=False)
        fd = [dev.submit_block(b) for b in fifo(np.random.default_rng(seed))]
        fh = [host.submit_block(b) for b in fifo(np.random.default_rng(seed))]
        dev.flush()
        host.flush()
        assert dev._dev_active
        for i, (a, b) in enumerate(zip(fd, fh)):
            ra = [list(map(bytes, g)) for g in a.result()]
            rb = [list(map(bytes, g)) for g in b.result()]
            assert ra == rb, (seed, i)
        dev._demote_device_store()
        want = _store_content(host.sms[0], n)
        for sm in dev.sms:
            assert _store_content(sm, n) == want
        del rng

    def test_get_values_resolve_host_side(self):
        # steady state: GET frames come from the host-retained SET
        # segments via a SNAPSHOT resolver (meta-only readback), never
        # the value planes — and the snapshot survives later evictions
        from rabia_tpu.apps.device_kv import ResolvedGetFrameGroups

        n = 4
        dev = _mk(n, device=True)
        dev.submit_block(
            build_block(
                list(range(n)),
                [[encode_set_bin(f"k{s}", f"val{s}")] for s in range(n)],
            )
        )
        f = dev.submit_block(
            build_block(
                list(range(n)), [[self._enc_get(f"k{s}")] for s in range(n)]
            )
        )
        dev.flush()
        assert isinstance(f._results, ResolvedGetFrameGroups)
        # evict every retained segment AFTER settlement: the settled
        # view's snapshot must still resolve (round-5 review finding)
        dev._dev_vseg.clear()
        dev._dev_vseg_bytes = 0
        frames = [list(map(bytes, g)) for g in f.result()]
        # version 1, found, value text round-trips
        for s, fr in enumerate(frames):
            assert f"val{s}".encode() in fr[0]

    def test_evicted_segment_falls_back_to_value_download(self):
        n = 4
        dev = _mk(n, device=True)
        host = _mk(n, device=False)
        dev._dev_vseg_cap = 1  # evict every segment immediately
        for e in (dev, host):
            for w in range(3):
                e.submit_block(
                    build_block(
                        list(range(n)),
                        [
                            [encode_set_bin(f"k{s}", f"w{w}")]
                            for s in range(n)
                        ],
                    )
                )
                e.flush()  # one window (= one segment) per block
        fd = dev.submit_block(
            build_block(
                list(range(n)), [[self._enc_get(f"k{s}")] for s in range(n)]
            )
        )
        fh = host.submit_block(
            build_block(
                list(range(n)), [[self._enc_get(f"k{s}")] for s in range(n)]
            )
        )
        dev.flush()
        host.flush()
        assert dev._dev_active
        assert bool((dev._dev_floor[:n] > 0).any())  # evictions happened
        assert [list(map(bytes, g)) for g in fd.result()] == [
            list(map(bytes, g)) for g in fh.result()
        ]

    def test_native_pack_gather_matches_numpy(self, monkeypatch):
        # the C one-pass gather (native/hostkernel.cpp rk_pack_gather)
        # must produce byte-identical planes to the numpy gather — the
        # semantics owner — across SET and mixed windows with varied
        # value widths; RABIA_PY_DEVPACK=1 forces the numpy path. The
        # native run is ASSERTED to have engaged (a silent fallback
        # would compare numpy against numpy, passing vacuously).
        from rabia_tpu.apps.kvstore import (
            KVOperation,
            KVOpType,
            encode_op_bin,
        )
        from rabia_tpu.native.build import load_hostkernel

        if load_hostkernel() is None:
            pytest.skip("native host kernel unavailable")
        monkeypatch.delenv("RABIA_PY_DEVPACK", raising=False)
        n, W = 8, 6
        dev = _mk(n, device=True, window=W)
        engaged = []
        orig = type(dev._dev)._native_pack_gather

        def spy(self_, *a, **kw):
            r = orig(self_, *a, **kw)
            engaged.append(r)
            return r

        monkeypatch.setattr(type(dev._dev), "_native_pack_gather", spy)
        rng = np.random.default_rng(9)

        def window(mixed):
            out = []
            for w in range(W):
                cmds = []
                for s in range(n):
                    if mixed and s % 3 == 1:
                        cmds.append(
                            [encode_op_bin(
                                KVOperation(KVOpType.Get, f"k{s % 3}")
                            )]
                        )
                    elif mixed and s % 5 == 2:
                        cmds.append(
                            [encode_op_bin(
                                KVOperation(KVOpType.Delete, f"k{s % 3}")
                            )]
                        )
                    else:
                        v = "v" * int(rng.integers(0, 9)) + str(w)
                        cmds.append([encode_set_bin(f"k{s % 3}", v)])
                out.append(build_block(list(range(n)), cmds))
            return out

        for mixed in (False, True):
            bs = window(mixed)
            allow = "mixed" if mixed else "set"
            engaged.clear()
            g_native = dev._dev._gather_window(bs, allow)
            assert engaged == [True], "native gather did not engage"
            monkeypatch.setenv("RABIA_PY_DEVPACK", "1")
            g_numpy = dev._dev._gather_window(bs, allow)
            monkeypatch.delenv("RABIA_PY_DEVPACK")
            for a, b in zip(g_native, g_numpy):
                assert np.array_equal(a, b), f"divergence (mixed={mixed})"

    def test_eviction_pressure_during_deferred_del_windows(self):
        # segment-cap pressure while DEL-bearing (deferred) windows are
        # in flight: eviction stops at PROVISIONAL segments (their
        # exact version range is unknown until settlement patches
        # them), settlement re-runs the eviction loop, and GETs of
        # evicted versions fall back to the value-plane download —
        # all byte-identical to the host path under a 1-byte cap
        from rabia_tpu.apps.kvstore import (
            KVOperation,
            KVOpType,
            encode_op_bin,
        )

        enc = lambda t, k: encode_op_bin(KVOperation(t, k))
        n = 4
        dev = _mk(n, device=True, window=2)
        host = _mk(n, device=False, window=2)
        dev._dev_vseg_cap = 1  # evict every settled segment immediately

        def stream():
            shards = list(range(n))
            blk = lambda op: build_block(shards, [[op] for _ in shards])
            out = []
            for w in range(3):
                out.append(blk(encode_set_bin(f"k{w}", f"v{w}" * 5)))
            out.append(blk(enc(KVOpType.Delete, "k0")))      # deferred
            out.append(blk(encode_set_bin("k0", "back")))    # deferred
            out.append(blk(enc(KVOpType.Get, "k0")))         # same-pipe read
            out.append(blk(enc(KVOpType.Get, "k1")))         # evicted read
            out.append(blk(enc(KVOpType.Delete, "k2")))      # deferred
            out.append(blk(enc(KVOpType.Get, "k2")))         # deleted read
            out.append(blk(encode_set_bin("k3", "tail")))
            return out

        fd = [dev.submit_block(b) for b in stream()]
        fh = [host.submit_block(b) for b in stream()]
        dev.flush()
        host.flush()
        assert dev._dev_active
        assert dev._dev_defer == 0 and not dev._dev_pipe
        assert bool((dev._dev_floor[:n] > 0).any())  # evictions happened
        for i, (a, b) in enumerate(zip(fd, fh)):
            assert _frames(a) == _frames(b), i
        dev._demote_device_store()
        want = _store_content(host.sms[0], n)
        for sm in dev.sms:
            assert _store_content(sm, n) == want

    def test_repromotion_seed_resolves_old_versions(self):
        n = 4
        dev = _mk(n, device=True, device_store_repromote=1)
        host = _mk(n, device=False)
        for e in (dev, host):
            e.submit_block(
                build_block(
                    list(range(n)),
                    [[encode_set_bin(f"k{s}", f"old{s}")] for s in range(n)],
                )
            )
            e.flush()
        # force a demotion (an over-width value is outside the lane
        # envelope; DEL/EXISTS now run in-lane)
        for e in (dev, host):
            e.submit_block(
                build_block(
                    list(range(n)),
                    [[encode_set_bin("other", "x" * 100)] for s in range(n)],
                )
            )
            e.flush()
        assert not dev._dev_active
        # overwrite the wide value so the upload accepts (the attempt
        # at this cycle's START still sees the wide value and declines,
        # re-arming the cooldown), then one more full-width cycle whose
        # start-of-cycle attempt succeeds; then GET the PRE-promotion
        # version: it must resolve from the seed, byte-identical to the
        # host path
        for tag in ("x", "warm"):
            for e in (dev, host):
                e.submit_block(
                    build_block(
                        list(range(n)),
                        [[encode_set_bin("other", tag)] for s in range(n)],
                    )
                )
                e.flush()
        # the re-promotion attempt fires at the start of the NEXT
        # full-width cycle with a served cooldown — that's the GET
        # cycle below, which then runs in-lane (asserted after it)
        fd = dev.submit_block(
            build_block(
                list(range(n)), [[self._enc_get(f"k{s}")] for s in range(n)]
            )
        )
        fh = host.submit_block(
            build_block(
                list(range(n)), [[self._enc_get(f"k{s}")] for s in range(n)]
            )
        )
        dev.flush()
        host.flush()
        assert dev._dev_active
        assert [list(map(bytes, g)) for g in fd.result()] == [
            list(map(bytes, g)) for g in fh.result()
        ]

    def test_dict_upload_engages_and_conforms(self):
        # repetitive SET streams take the dictionary-compressed upload
        # (a _DictSeg lands in the value segments); responses and final
        # content stay identical to the host path
        from rabia_tpu.parallel.mesh_engine import _DictSeg

        n = 4
        dev = _mk(n, device=True)
        host = _mk(n, device=False)
        for e in (dev, host):
            for w in range(3):
                e.submit_block(
                    build_block(
                        list(range(n)),
                        [
                            [encode_set_bin(f"k{s % 2}", f"v{w % 2}")]
                            for s in range(n)
                        ],
                    )
                )
            e.flush()  # pure-SET window: the dict upload path
            fd = e.submit_block(
                build_block(
                    list(range(n)),
                    [[self._enc_get("k0")] for s in range(n)],
                )
            )
            e.flush()
            if e is dev:
                dev_get = fd
            else:
                host_get = fd
        assert dev._dev_active
        assert any(isinstance(sg, _DictSeg) for sg in dev._dev_vseg)
        assert [list(map(bytes, g)) for g in dev_get.result()] == [
            list(map(bytes, g)) for g in host_get.result()
        ]
        dev._demote_device_store()
        want = _store_content(host.sms[0], n)
        for sm in dev.sms:
            assert _store_content(sm, n) == want

    def test_high_cardinality_window_falls_back_to_rows(self):
        # >32 distinct (key, value) rows per shard in one window: the
        # dictionary declines (max_dict) and the row-packed path runs
        from rabia_tpu.parallel.mesh_engine import _RowSeg

        n = 2
        dev = _mk(n, device=True, window=40)
        host = _mk(n, device=False, window=40)
        for e in (dev, host):
            for w in range(40):
                e.submit_block(
                    build_block(
                        list(range(n)),
                        [
                            [encode_set_bin(f"k{w}", f"v{w}")]
                            for s in range(n)
                        ],
                    )
                )
            e.flush()
        assert dev._dev_active
        assert any(isinstance(sg, _RowSeg) for sg in dev._dev_vseg)
        dev._demote_device_store()
        want = _store_content(host.sms[0], n)
        for sm in dev.sms:
            assert _store_content(sm, n) == want

    def test_del_exists_run_in_lane_byte_identical(self):
        # DEL and EXISTS join the device lane's mixed envelope instead
        # of demoting: deterministic sequence covering found DEL,
        # not-found DEL, SET-after-DEL (fresh version continues from
        # the bumped counter), GET-after-DEL (not-found), and EXISTS
        # both ways — responses and final content byte-identical to the
        # host path, no demotion
        from rabia_tpu.apps.kvstore import (
            KVOperation,
            KVOpType,
            encode_op_bin,
        )

        enc = lambda t, k: encode_op_bin(KVOperation(t, k))
        n = 4
        dev = _mk(n, device=True, window=4)
        host = _mk(n, device=False, window=4)

        def stream():
            shards = list(range(n))
            blk = lambda op: build_block(shards, [[op] for _ in shards])
            return [
                blk(encode_set_bin("a", "v1")),
                blk(enc(KVOpType.Delete, "a")),       # found DEL
                blk(enc(KVOpType.Delete, "a")),       # not-found DEL
                blk(enc(KVOpType.Get, "a")),          # not-found GET
                blk(encode_set_bin("a", "v2")),       # SET after DEL
                blk(enc(KVOpType.Exists, "a")),       # true
                blk(enc(KVOpType.Exists, "missing")),  # false
                blk(enc(KVOpType.Get, "a")),          # found GET
                blk(encode_set_bin("b", "v3")),
                blk(enc(KVOpType.Delete, "missing")),  # not-found DEL
            ]

        fd = [dev.submit_block(b) for b in stream()]
        fh = [host.submit_block(b) for b in stream()]
        dev.flush()
        host.flush()
        assert dev._dev_active, "DEL/EXISTS demoted the lane"
        for i, (a, b) in enumerate(zip(fd, fh)):
            assert _frames(a) == _frames(b), i
        dev._demote_device_store()
        want = _store_content(host.sms[0], n)
        for sm in dev.sms:
            assert _store_content(sm, n) == want

    def test_del_windows_pipeline_with_deferred_versions(self):
        # DEL-bearing windows PIPELINE (no synchronous drain): version
        # derivation defers to settlement, and every window dispatched
        # while one is in flight inherits the deferral — a later SET's
        # response version must count the earlier DEL's found-dependent
        # bump even though that bump is unknown at its dispatch. This
        # stream is sized so window k+1 (pure SET) dispatches while the
        # DEL window k is still unsettled: wrong-base derivation would
        # shift every subsequent version by the found-DEL count.
        from rabia_tpu.apps.kvstore import (
            KVOperation,
            KVOpType,
            encode_op_bin,
        )

        enc = lambda t, k: encode_op_bin(KVOperation(t, k))
        n = 4
        dev = _mk(n, device=True, window=2)
        host = _mk(n, device=False, window=2)

        def stream():
            shards = list(range(n))
            blk = lambda op: build_block(shards, [[op] for _ in shards])
            out = []
            # wave pairs = windows of 2: [SET, SET] [DEL, DEL] [SET, SET]
            # [GET, EXISTS] [SET, DEL] [GET, GET]
            out.append(blk(encode_set_bin("a", "v0")))
            out.append(blk(encode_set_bin("b", "v1")))
            out.append(blk(enc(KVOpType.Delete, "a")))      # found
            out.append(blk(enc(KVOpType.Delete, "missing")))  # not found
            out.append(blk(encode_set_bin("a", "v2")))  # ver counts the bump
            out.append(blk(encode_set_bin("c", "v3")))
            out.append(blk(enc(KVOpType.Get, "a")))
            out.append(blk(enc(KVOpType.Exists, "b")))
            out.append(blk(encode_set_bin("b", "v4")))
            out.append(blk(enc(KVOpType.Delete, "c")))      # found
            out.append(blk(enc(KVOpType.Get, "b")))
            out.append(blk(enc(KVOpType.Get, "c")))         # not found
            return out

        fd = [dev.submit_block(b) for b in stream()]
        fh = [host.submit_block(b) for b in stream()]
        dev.flush()
        host.flush()
        assert dev._dev_active, "DEL windows demoted the lane"
        assert dev._dev_defer == 0, "deferral bookkeeping leaked"
        assert not dev._dev_pipe
        for i, (a, b) in enumerate(zip(fd, fh)):
            assert _frames(a) == _frames(b), i
        dev._demote_device_store()
        want = _store_content(host.sms[0], n)
        for sm in dev.sms:
            assert _store_content(sm, n) == want

    def test_deeper_inflight_pipe_byte_identical(self):
        # device_store_inflight=3 keeps three dispatched-but-unresolved
        # windows in the pipe (the throughput-mode default: with one
        # fetch worker per window it measured 1.05-2.4x depth 1 —
        # inflight_depth_ab in benchmarks/results.json); responses and
        # final content must be byte-identical to the host path
        n = 8
        dev = _mk(n, device=True, device_store_inflight=3, window=2)
        host = _mk(n, device=False, window=2)
        rng = np.random.default_rng(21)
        fd = [dev.submit_block(b) for b in self._mixed_fifo(n, rng)]
        fh = [
            host.submit_block(b)
            for b in self._mixed_fifo(n, np.random.default_rng(21))
        ]
        dev.flush()
        host.flush()
        assert dev._dev_active
        assert dev._dev_defer == 0 and not dev._dev_pipe
        for i, (a, b) in enumerate(zip(fd, fh)):
            ra = [list(map(bytes, g)) for g in a.result()]
            rb = [list(map(bytes, g)) for g in b.result()]
            assert ra == rb, i
        dev._demote_device_store()
        want = _store_content(host.sms[0], n)
        for sm in dev.sms:
            assert _store_content(sm, n) == want

    def test_deferred_del_window_dirty_rollback(self):
        # a DEL-bearing (deferred) window that reads back DIRTY: the
        # rollback must unwind the deferral bookkeeping (_dev_defer
        # back to 0, provisional segments popped) for BOTH the dirty
        # window and the deferred window pipelined behind it, then the
        # host path must replay everything in submission order —
        # exercises the rollback branch the clean-path test can't
        from rabia_tpu.apps.kvstore import (
            KVOperation,
            KVOpType,
            encode_op_bin,
        )

        n = 2
        mk = lambda device: _mk(
            n,
            device=device,
            device_store_kw={"per_shard_capacity": 4},
            window=4,
        )
        dev, host = mk(True), mk(False)
        shards = list(range(n))
        blk = lambda op: build_block(shards, [[op] for _ in shards])

        warm = [blk(encode_set_bin(f"k{w}", "x")) for w in range(3)]
        # window 1 (DEL-bearing -> deferred): the DEL frees one slot
        # but three new keys need 5 total -> table overflow -> dirty
        w1 = [blk(enc) for enc in (
            encode_op_bin(KVOperation(KVOpType.Delete, "k0")),
            encode_set_bin("k3", "x"),
            encode_set_bin("k4", "x"),
            encode_set_bin("k5", "x"),
        )]
        # window 2 dispatched while window 1 is in flight: inherits
        # the deferral (pure SET behind a DEL window)
        w2 = [blk(encode_set_bin(f"m{w}", "y")) for w in range(4)]

        for b in warm:
            dev.submit_block(b)
        dev.flush()
        assert dev._dev_active
        for b in w1 + w2:
            dev.submit_block(b)
        dev.run_cycle()  # dispatches window 1, flags resolve later
        assert dev._dev_pipe and dev._dev_defer == 1
        dev.flush()
        assert not dev._dev_active, "dirty DEL window must demote"
        assert dev._dev_defer == 0, "rollback leaked deferral count"
        assert not dev._dev_pipe

        for b in warm + w1 + w2:
            host.submit_block(b)
        host.flush()
        want = _store_content(host.sms[0], n)
        for sm in dev.sms:
            assert _store_content(sm, n) == want
        assert np.array_equal(dev.next_slot, host.next_slot)

    def test_get_window_dict_upload_engages_and_conforms(self):
        # a repetitive GET stream takes the dictionary-compressed key
        # upload (keys repeat like SET rows repeat); responses stay
        # byte-identical to the host path. Pins that
        # pack_get_window_auto actually chooses the dict form.
        from rabia_tpu.apps.device_kv import DeviceDictOps
        from rabia_tpu.apps.kvstore import encode_set_bin

        n = 4
        dev = _mk(n, device=True, window=4)
        host = _mk(n, device=False, window=4)
        for e in (dev, host):
            e.submit_block(
                build_block(
                    list(range(n)),
                    [[encode_set_bin(f"k{s % 2}", "v")] for s in range(n)],
                )
            )
            e.flush()

        def gets():
            return [
                build_block(
                    list(range(n)),
                    [[self._enc_get(f"k{s % 2}")] for s in range(n)],
                )
                for _ in range(8)
            ]

        packed = dev._dev.pack_get_window_auto(gets()[:4])
        assert isinstance(packed, DeviceDictOps)
        fd = [dev.submit_block(b) for b in gets()]
        fh = [host.submit_block(b) for b in gets()]
        dev.flush()
        host.flush()
        assert dev._dev_active, "dict-GET window demoted the lane"
        for a, b in zip(fd, fh):
            assert _frames(a) == _frames(b)

    def test_mixed_window_dict_upload_engages_and_conforms(self):
        # a repetitive INTERLEAVED stream takes the dictionary upload
        # through the MIXED program (GET ops become (key, empty value)
        # dictionary rows); responses stay byte-identical to the host
        # path. Pins that pack_mixed_window_auto actually chooses the
        # dict form — a silent permanent row fallback would pass every
        # conformance test while giving up the 10x upload compression.
        from rabia_tpu.apps.device_kv import DeviceDictOps
        from rabia_tpu.apps.kvstore import encode_set_bin

        n = 4
        dev = _mk(n, device=True, window=6)
        host = _mk(n, device=False, window=6)

        def stream():
            out = []
            for w in range(4):
                out.append(
                    build_block(
                        list(range(n)),
                        [
                            [encode_set_bin(f"k{s % 2}", "v")]
                            for s in range(n)
                        ],
                    )
                )
                out.append(
                    build_block(
                        list(range(n)),
                        [[self._enc_get(f"k{s % 2}")] for s in range(n)],
                    )
                )
            return out

        # the packer must choose the dictionary form for this window
        blocks = stream()[:6]
        packed = dev._dev.pack_mixed_window_auto(blocks)
        assert packed is not None
        assert isinstance(packed[1], DeviceDictOps)

        fd = [dev.submit_block(b) for b in stream()]
        fh = [host.submit_block(b) for b in stream()]
        dev.flush()
        host.flush()
        assert dev._dev_active, "dict-mixed window demoted the lane"
        for a, b in zip(fd, fh):
            assert _frames(a) == _frames(b)
        dev._demote_device_store()
        want = _store_content(host.sms[0], n)
        for sm in dev.sms:
            assert _store_content(sm, n) == want

    def test_long_key_get_demotes_byte_identical(self):
        n = 4
        dev = _mk(n, device=True)
        host = _mk(n, device=False)
        for e in (dev, host):
            e.submit_block(
                build_block(
                    list(range(n)),
                    [[encode_set_bin(f"k{s}", "v")] for s in range(n)],
                )
            )
            e.flush()
        gd = dev.submit_block(
            build_block(
                list(range(n)), [[self._enc_get("K" * 100)] for s in range(n)]
            )
        )
        gh = host.submit_block(
            build_block(
                list(range(n)), [[self._enc_get("K" * 100)] for s in range(n)]
            )
        )
        dev.flush()
        host.flush()
        assert not dev._dev_active  # key over the table width: host path
        assert [list(map(bytes, g)) for g in gd.result()] == [
            list(map(bytes, g)) for g in gh.result()
        ]
